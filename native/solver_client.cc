// Native solver-service client: the cgo-shim equivalent of the reference's
// planned Go -> sidecar boundary (SURVEY.md §7 M5 / §2.8 item 4).
//
// Speaks the KTPU frame protocol v2 of karpenter_tpu/solver/service.py over
// a unix-domain socket:
//   frame := "KTPU" | u32le kind | u32le req_id | u32le len | payload[len]
//   kinds: 1=SOLVE 2=RESULT 3=ERROR 4=PING 5=PONG
// A response echoes the request's req_id; a mismatch means the stream is
// poisoned (a previous caller abandoned a read mid-frame) and the only safe
// recovery is to close the connection — never resynchronize mid-stream.
//
// Usage:
//   solver_client <socket-path> ping
//   solver_client <socket-path> solve < problem.json   (prints the RESULT
//                                                       payload to stdout)
//
// A control plane embedding this as a library would link solve_request();
// the main() is the conformance harness the Python test drives.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include <sys/socket.h>
#include <sys/un.h>
#include <unistd.h>

namespace {

constexpr char kMagic[4] = {'K', 'T', 'P', 'U'};
constexpr uint32_t kSolve = 1;
constexpr uint32_t kResult = 2;
constexpr uint32_t kError = 3;
constexpr uint32_t kPing = 4;
constexpr uint32_t kPong = 5;

bool send_all(int fd, const void* data, size_t n) {
  const char* p = static_cast<const char*>(data);
  while (n > 0) {
    ssize_t w = ::write(fd, p, n);
    if (w <= 0) return false;
    p += w;
    n -= static_cast<size_t>(w);
  }
  return true;
}

bool recv_all(int fd, void* data, size_t n) {
  char* p = static_cast<char*>(data);
  while (n > 0) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

// Refuse absurd frame lengths (mirrors service.py MAX_FRAME_LEN): a
// corrupted header must not make the client buffer gigabytes.
constexpr uint32_t kMaxFrameLen = 64u * 1024u * 1024u;

bool send_frame(int fd, uint32_t kind, uint32_t req_id,
                const std::string& payload) {
  char head[16];
  std::memcpy(head, kMagic, 4);
  uint32_t k = kind, r = req_id, len = static_cast<uint32_t>(payload.size());
  std::memcpy(head + 4, &k, 4);   // little-endian hosts only (x86/arm LE)
  std::memcpy(head + 8, &r, 4);
  std::memcpy(head + 12, &len, 4);
  if (!send_all(fd, head, sizeof head)) return false;
  return payload.empty() || send_all(fd, payload.data(), payload.size());
}

bool recv_frame(int fd, uint32_t* kind, uint32_t* req_id,
                std::string* payload) {
  char head[16];
  if (!recv_all(fd, head, sizeof head)) return false;
  if (std::memcmp(head, kMagic, 4) != 0) return false;
  uint32_t len;
  std::memcpy(kind, head + 4, 4);
  std::memcpy(req_id, head + 8, 4);
  std::memcpy(&len, head + 12, 4);
  if (len > kMaxFrameLen) return false;
  payload->resize(len);
  return len == 0 || recv_all(fd, payload->data(), len);
}

int connect_unix(const char* path) {
  int fd = ::socket(AF_UNIX, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_un addr{};
  addr.sun_family = AF_UNIX;
  std::snprintf(addr.sun_path, sizeof addr.sun_path, "%s", path);
  if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof addr) != 0) {
    ::close(fd);
    return -1;
  }
  return fd;
}

// The embeddable API: returns 0 and fills *result on success; 1 on a
// solver-side ERROR frame (message in *result); negative on transport or
// protocol error (including a correlation mismatch — caller must close
// the fd, the stream is poisoned).
int solve_request(int fd, const std::string& problem_json, std::string* result) {
  static uint32_t next_id = 0;
  uint32_t req_id = ++next_id;
  if (!send_frame(fd, kSolve, req_id, problem_json)) return -2;
  uint32_t kind = 0, rid = 0;
  if (!recv_frame(fd, &kind, &rid, result)) return -3;
  if (rid != req_id) return -5;  // poisoned stream: close, reconnect
  if (kind == kError) return 1;
  if (kind != kResult) return -4;
  return 0;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 3) {
    std::fprintf(stderr, "usage: %s <socket> ping|solve\n", argv[0]);
    return 64;
  }
  int fd = connect_unix(argv[1]);
  if (fd < 0) {
    std::fprintf(stderr, "connect failed: %s\n", argv[1]);
    return 1;
  }
  const std::string cmd = argv[2];
  int rc = 0;
  if (cmd == "ping") {
    std::string payload;
    uint32_t kind = 0, rid = 0;
    if (!send_frame(fd, kPing, 1, "") ||
        !recv_frame(fd, &kind, &rid, &payload) || kind != kPong || rid != 1) {
      std::fprintf(stderr, "ping failed\n");
      rc = 1;
    } else {
      std::printf("pong\n");
    }
  } else if (cmd == "solve") {
    std::string problem, chunk(1 << 16, '\0');
    size_t r;
    while ((r = std::fread(chunk.data(), 1, chunk.size(), stdin)) > 0)
      problem.append(chunk, 0, r);
    std::string result;
    int got = solve_request(fd, problem, &result);
    if (got == 0) {
      std::fwrite(result.data(), 1, result.size(), stdout);
      std::printf("\n");
    } else {
      std::fprintf(stderr, "solve failed (%d): %s\n", got, result.c_str());
      rc = 1;
    }
  } else {
    std::fprintf(stderr, "unknown command %s\n", cmd.c_str());
    rc = 64;
  }
  ::close(fd);
  return rc;
}
