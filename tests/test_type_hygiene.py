"""Optional type-hygiene gate: mypy over `karpenter_tpu/analysis/` and
`karpenter_tpu/utils/` (the [tool.mypy] config in pyproject.toml).

These two packages are pure host-side python with stable, fully
annotatable surfaces — the analyzer must stay import-light and the
milli-unit helpers are the arithmetic the whole codebase trusts. The
gate SKIPS cleanly when mypy isn't installed (the container doesn't bake
it in; `pip install mypy` locally to activate it) — it must never turn
tier-1 red on a missing dev tool.
"""

from __future__ import annotations

import importlib.util
import os
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_HAS_MYPY = importlib.util.find_spec("mypy") is not None


@pytest.mark.skipif(not _HAS_MYPY, reason="mypy not installed")
def test_mypy_clean_on_analysis_and_utils():
    res = subprocess.run(
        [
            sys.executable,
            "-m",
            "mypy",
            "--config-file",
            "pyproject.toml",
            "karpenter_tpu/analysis",
            "karpenter_tpu/utils",
        ],
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert res.returncode == 0, (
        "mypy found type errors:\n" + res.stdout + res.stderr
    )


def test_mypy_config_present_for_when_it_lands():
    """The config the gate runs under must exist even where mypy doesn't
    — otherwise installing mypy later silently checks nothing."""
    with open(
        os.path.join(REPO_ROOT, "pyproject.toml"), encoding="utf-8"
    ) as f:
        text = f.read()
    assert "[tool.mypy]" in text
    assert "karpenter_tpu/analysis" in text
    assert "karpenter_tpu/utils" in text
