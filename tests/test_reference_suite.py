"""Reference scheduling-suite scenario matrices, round 5 (TESTMAP.md).

Ports of /root/reference/pkg/controllers/provisioning/scheduling/
suite_test.go families that had no repo coverage: Custom Constraints,
Well Known Labels, Constraints Validation, Scheduling Logic, Instance
Type Compatibility, and Binpacking. Each test cites the reference It()
block (file:line) it reproduces; the expectations are re-derived from the
reference semantics, the harness mirrors tests/test_scheduling_families.py.

The instance-type universe is the reference fake provider's DEFAULT set
(fake/cloudprovider.go:234-271 — fake.default_instance_types()), because
these scenarios are written against exactly those six types.
"""

from __future__ import annotations

import pytest

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import (
    NodeSelectorRequirement,
    Operator,
)
from karpenter_tpu.cloudprovider import fake
from karpenter_tpu.solver import HybridScheduler, Scheduler, Topology
from karpenter_tpu.solver.oracle import SchedulerOptions
from karpenter_tpu.testing import fixtures
from karpenter_tpu.utils import resources as res

ZONE = well_known.TOPOLOGY_ZONE_LABEL_KEY
ITYPE = well_known.INSTANCE_TYPE_LABEL_KEY
ARCH = well_known.ARCH_LABEL_KEY
OS = well_known.OS_LABEL_KEY
INT_KEY = fake.INTEGER_INSTANCE_LABEL_KEY


def solve(pods, pools=None, its=None, options=None, kernel=False, views=None):
    its = its if its is not None else fake.default_instance_types()
    pools = pools or [fixtures.node_pool(name="default")]
    ibp = {np.name: its for np in pools}
    topo = Topology(pools, ibp, pods, state_node_views=views)
    cls = HybridScheduler if kernel else Scheduler
    kw = {}
    if kernel:
        kw["force_oracle"] = False
        options = options or SchedulerOptions()
        options.tpu_min_pods = 0
    s = cls(pools, ibp, topo, views, None, options, **kw)
    return s.solve(pods)


def claim_of(r, pod_name):
    for c in r.new_node_claims:
        if any(p.name == pod_name for p in c.pods):
            return c
    return None


def scheduled(r, pod_name) -> bool:
    if claim_of(r, pod_name) is not None:
        return True
    return any(
        p.name == pod_name for n in r.existing_nodes for p in n.pods
    )


def claim_value(claim, key):
    """The single requirement value a created node would carry as `key`'s
    label, or None when the claim leaves it open."""
    if not claim.requirements.has(key):
        return None
    req = claim.requirements.get(key)
    if req.complement or len(req.values) != 1:
        return None
    return next(iter(req.values))


def type_names(claim):
    return {it.name for it in claim.instance_type_options}


def allowed_zones(claim):
    """Zones a Create could place this claim in: available offerings of
    surviving types, filtered by the claim's zone requirement — the node
    label the reference asserts on materializes from exactly this set."""
    req = (
        claim.requirements.get(ZONE) if claim.requirements.has(ZONE) else None
    )
    zones = set()
    for it in claim.instance_type_options:
        for o in it.offerings:
            if not o.available:
                continue
            z = o.zone()
            if z and (req is None or req.has(z)):
                zones.add(z)
    return zones


# ---------------------------------------------------------------------------
# Custom Constraints > NodePool with Labels (suite_test.go:151-199)


def test_nodepool_labels_schedule_unconstrained():
    """suite_test.go:152 — unconstrained pod lands on the labeled pool."""
    pool = fixtures.node_pool(name="default", labels={"test-key": "test-value"})
    r = solve([fixtures.pod(name="p")], pools=[pool])
    c = claim_of(r, "p")
    assert c is not None
    assert claim_value(c, "test-key") == "test-value"


def test_nodepool_labels_conflicting_selector_fails():
    """suite_test.go:160 — selector conflicting with the pool label."""
    pool = fixtures.node_pool(name="default", labels={"test-key": "test-value"})
    r = solve(
        [fixtures.pod(name="p", node_selector={"test-key": "different-value"})],
        pools=[pool],
    )
    assert not scheduled(r, "p")


def test_nodepool_labels_undefined_key_fails():
    """suite_test.go:169 — selector on a key no pool defines."""
    r = solve([fixtures.pod(name="p", node_selector={"test-key": "test-value"})])
    assert not scheduled(r, "p")


def test_nodepool_labels_matching_requirement_schedules():
    """suite_test.go:177 — In requirement containing the pool's value."""
    pool = fixtures.node_pool(name="default", labels={"test-key": "test-value"})
    r = solve(
        [
            fixtures.pod(
                name="p",
                node_requirements=[
                    NodeSelectorRequirement(
                        "test-key", Operator.IN, ["test-value", "another-value"]
                    )
                ],
            )
        ],
        pools=[pool],
    )
    c = claim_of(r, "p")
    assert c is not None and claim_value(c, "test-key") == "test-value"


def test_nodepool_labels_conflicting_requirement_fails():
    """suite_test.go:189 — In requirement excluding the pool's value."""
    pool = fixtures.node_pool(name="default", labels={"test-key": "test-value"})
    r = solve(
        [
            fixtures.pod(
                name="p",
                node_requirements=[
                    NodeSelectorRequirement(
                        "test-key", Operator.IN, ["another-value"]
                    )
                ],
            )
        ],
        pools=[pool],
    )
    assert not scheduled(r, "p")


# ---------------------------------------------------------------------------
# Custom Constraints > Well Known Labels (suite_test.go:201-402; the
# duplicate block at :657-1090 runs the same scenarios and is covered by
# these same matrices — see TESTMAP.md)


def test_wkl_nodepool_constraints():
    """suite_test.go:202 — pool zone constraint pins the claim's zone."""
    pool = fixtures.node_pool(
        name="default",
        requirements=[NodeSelectorRequirement(ZONE, Operator.IN, ["test-zone-2"])],
    )
    r = solve([fixtures.pod(name="p")], pools=[pool])
    c = claim_of(r, "p")
    assert c is not None and claim_value(c, ZONE) == "test-zone-2"


def test_wkl_node_selector_narrows_pool():
    """suite_test.go:211 — selector picks one zone of the pool's two."""
    pool = fixtures.node_pool(
        name="default",
        requirements=[
            NodeSelectorRequirement(ZONE, Operator.IN, ["test-zone-1", "test-zone-2"])
        ],
    )
    r = solve(
        [fixtures.pod(name="p", node_selector={ZONE: "test-zone-2"})],
        pools=[pool],
    )
    c = claim_of(r, "p")
    assert c is not None and claim_value(c, ZONE) == "test-zone-2"


def test_wkl_unknown_selector_value_fails():
    """suite_test.go:230 — zone selector outside the universe."""
    pool = fixtures.node_pool(
        name="default",
        requirements=[NodeSelectorRequirement(ZONE, Operator.IN, ["test-zone-1"])],
    )
    r = solve(
        [fixtures.pod(name="p", node_selector={ZONE: "unknown"})], pools=[pool]
    )
    assert not scheduled(r, "p")


def test_wkl_selector_outside_pool_constraints_fails():
    """suite_test.go:240 — selector zone disjoint from the pool's."""
    pool = fixtures.node_pool(
        name="default",
        requirements=[NodeSelectorRequirement(ZONE, Operator.IN, ["test-zone-1"])],
    )
    r = solve(
        [fixtures.pod(name="p", node_selector={ZONE: "test-zone-2"})],
        pools=[pool],
    )
    assert not scheduled(r, "p")


def test_wkl_operator_in():
    """suite_test.go:250 — In[test-zone-3] schedules into zone 3."""
    r = solve(
        [
            fixtures.pod(
                name="p",
                node_requirements=[
                    NodeSelectorRequirement(ZONE, Operator.IN, ["test-zone-3"])
                ],
            )
        ]
    )
    c = claim_of(r, "p")
    assert c is not None and claim_value(c, ZONE) == "test-zone-3"


def test_wkl_operator_gt():
    """suite_test.go:261 — pool integer Gt 8 leaves only the 16-cpu type."""
    pool = fixtures.node_pool(
        name="default",
        requirements=[NodeSelectorRequirement(INT_KEY, Operator.GT, ["8"])],
    )
    r = solve([fixtures.pod(name="p")], pools=[pool])
    c = claim_of(r, "p")
    assert c is not None
    assert type_names(c) == {"arm-instance-type"}  # the only 16-cpu type


def test_wkl_operator_lt():
    """suite_test.go:270 — pool integer Lt 8 keeps small types; the
    cheapest (2-cpu) schedules first (reference expects integer=2)."""
    pool = fixtures.node_pool(
        name="default",
        requirements=[NodeSelectorRequirement(INT_KEY, Operator.LT, ["8"])],
    )
    r = solve([fixtures.pod(name="p")], pools=[pool])
    c = claim_of(r, "p")
    assert c is not None
    assert "arm-instance-type" not in type_names(c)
    assert "small-instance-type" in type_names(c)


def test_wkl_incompatible_requirement_in_fails():
    """suite_test.go:279 — required In[unknown]."""
    r = solve(
        [
            fixtures.pod(
                name="p",
                node_requirements=[
                    NodeSelectorRequirement(ZONE, Operator.IN, ["unknown"])
                ],
            )
        ]
    )
    assert not scheduled(r, "p")


def test_wkl_operator_notin():
    """suite_test.go:289 — NotIn[z1,z2,unknown] leaves zone 3."""
    r = solve(
        [
            fixtures.pod(
                name="p",
                node_requirements=[
                    NodeSelectorRequirement(
                        ZONE,
                        Operator.NOT_IN,
                        ["test-zone-1", "test-zone-2", "unknown"],
                    )
                ],
            )
        ]
    )
    c = claim_of(r, "p")
    assert c is not None and allowed_zones(c) == {"test-zone-3"}


def test_wkl_notin_everything_fails():
    """suite_test.go:300 — NotIn over the whole universe."""
    r = solve(
        [
            fixtures.pod(
                name="p",
                node_requirements=[
                    NodeSelectorRequirement(
                        ZONE,
                        Operator.NOT_IN,
                        ["test-zone-1", "test-zone-2", "test-zone-3", "unknown"],
                    )
                ],
            )
        ]
    )
    assert not scheduled(r, "p")


def test_wkl_compatible_preference_narrows_in():
    """suite_test.go:311 — preference In[z2,unknown] inside required
    In[z1..z3,unknown] lands in zone 2."""
    r = solve(
        [
            fixtures.pod(
                name="p",
                node_requirements=[
                    NodeSelectorRequirement(
                        ZONE,
                        Operator.IN,
                        ["test-zone-1", "test-zone-2", "test-zone-3", "unknown"],
                    )
                ],
                node_preferences=[
                    NodeSelectorRequirement(
                        ZONE, Operator.IN, ["test-zone-2", "unknown"]
                    )
                ],
            )
        ]
    )
    c = claim_of(r, "p")
    assert c is not None and allowed_zones(c) == {"test-zone-2"}


def test_wkl_incompatible_preference_in_still_schedules():
    """suite_test.go:325 — preference In[unknown] relaxes away."""
    r = solve(
        [
            fixtures.pod(
                name="p",
                node_requirements=[
                    NodeSelectorRequirement(
                        ZONE,
                        Operator.IN,
                        ["test-zone-1", "test-zone-2", "test-zone-3", "unknown"],
                    )
                ],
                node_preferences=[
                    NodeSelectorRequirement(ZONE, Operator.IN, ["unknown"])
                ],
            )
        ]
    )
    assert scheduled(r, "p")


def test_wkl_compatible_preference_notin():
    """suite_test.go:338 — preference NotIn[z1,z3] picks zone 2."""
    r = solve(
        [
            fixtures.pod(
                name="p",
                node_requirements=[
                    NodeSelectorRequirement(
                        ZONE,
                        Operator.IN,
                        ["test-zone-1", "test-zone-2", "test-zone-3", "unknown"],
                    )
                ],
                node_preferences=[
                    NodeSelectorRequirement(
                        ZONE, Operator.NOT_IN, ["test-zone-1", "test-zone-3"]
                    )
                ],
            )
        ]
    )
    c = claim_of(r, "p")
    assert c is not None and allowed_zones(c) == {"test-zone-2"}


def test_wkl_incompatible_preference_notin_still_schedules():
    """suite_test.go:352 — preference NotIn[everything] relaxes away."""
    r = solve(
        [
            fixtures.pod(
                name="p",
                node_requirements=[
                    NodeSelectorRequirement(
                        ZONE,
                        Operator.IN,
                        ["test-zone-1", "test-zone-2", "test-zone-3", "unknown"],
                    )
                ],
                node_preferences=[
                    NodeSelectorRequirement(
                        ZONE,
                        Operator.NOT_IN,
                        ["test-zone-1", "test-zone-2", "test-zone-3"],
                    )
                ],
            )
        ]
    )
    assert scheduled(r, "p")


def test_wkl_selector_preference_requirement_combine():
    """suite_test.go:365 — all three dimensions agree on zone 3."""
    r = solve(
        [
            fixtures.pod(
                name="p",
                node_selector={ZONE: "test-zone-3"},
                node_requirements=[
                    NodeSelectorRequirement(
                        ZONE,
                        Operator.IN,
                        ["test-zone-1", "test-zone-2", "test-zone-3"],
                    )
                ],
                node_preferences=[
                    NodeSelectorRequirement(
                        ZONE,
                        Operator.IN,
                        ["test-zone-1", "test-zone-2", "test-zone-3"],
                    )
                ],
            )
        ]
    )
    c = claim_of(r, "p")
    assert c is not None and claim_value(c, ZONE) == "test-zone-3"


def test_wkl_multidimensional_combination():
    """suite_test.go:380 — zone + instance-type selectors, requirements,
    and preferences combined."""
    r = solve(
        [
            fixtures.pod(
                name="p",
                node_selector={
                    ZONE: "test-zone-3",
                    ITYPE: "arm-instance-type",
                },
                node_requirements=[
                    NodeSelectorRequirement(
                        ZONE, Operator.IN, ["test-zone-1", "test-zone-3"]
                    ),
                    NodeSelectorRequirement(
                        ITYPE,
                        Operator.IN,
                        ["default-instance-type", "arm-instance-type"],
                    ),
                ],
                node_preferences=[
                    NodeSelectorRequirement(ZONE, Operator.NOT_IN, ["unknown"]),
                    NodeSelectorRequirement(ITYPE, Operator.NOT_IN, ["unknown"]),
                ],
            )
        ]
    )
    c = claim_of(r, "p")
    assert c is not None
    assert claim_value(c, ZONE) == "test-zone-3"
    assert type_names(c) == {"arm-instance-type"}


# ---------------------------------------------------------------------------
# Custom Constraints > Constraints Validation (suite_test.go:404-478):
# restricted labels/domains on POD selectors are rejected by the
# provisioner's validation (provisioner.go:504 Validate), not the solver.


def _operator_validate(selector: dict) -> str | None:
    from karpenter_tpu.controllers.kube import FakeClock
    from karpenter_tpu.controllers.operator import Operator as Op

    op = Op(clock=FakeClock(), force_oracle=True)
    pod = fixtures.pod(name="p", node_selector=selector)
    return op.provisioner._validate(pod)


def test_validation_restricted_labels_rejected():
    """suite_test.go:405 — kubernetes.io/hostname is a restricted label."""
    assert _operator_validate({well_known.HOSTNAME_LABEL_KEY: "red-node"})


@pytest.mark.parametrize(
    "key",
    [
        "kubernetes.io/custom",
        "k8s.io/custom",
        "karpenter.sh/custom",
        "sub.kubernetes.io/custom",
    ],
)
def test_validation_restricted_domains_rejected(key):
    """suite_test.go:421 — selectors in restricted domains."""
    assert _operator_validate({key: "v"})


@pytest.mark.parametrize(
    "key",
    [
        "kops.k8s.io/custom",
        "sub.kops.k8s.io/custom",
        "node-restriction.kubernetes.io/custom",
        "sub.node-restriction.kubernetes.io/custom",
    ],
)
def test_validation_domain_exceptions_allowed(key):
    """suite_test.go:432-459 — exception (sub)domains pass validation."""
    assert _operator_validate({key: "v"}) is None


def test_validation_well_known_labels_allowed():
    """suite_test.go:460 — well-known keys pass validation."""
    assert _operator_validate({ZONE: "test-zone-1"}) is None
    assert _operator_validate({well_known.NODEPOOL_LABEL_KEY: "default"}) is None


# ---------------------------------------------------------------------------
# Custom Constraints > Scheduling Logic (suite_test.go:480-655)


def test_logic_in_undefined_key_fails():
    """suite_test.go:488."""
    r = solve(
        [
            fixtures.pod(
                name="p",
                node_requirements=[
                    NodeSelectorRequirement("undefined-key", Operator.IN, ["v"])
                ],
            )
        ]
    )
    assert not scheduled(r, "p")


def test_logic_notin_undefined_key_schedules():
    """suite_test.go:497."""
    r = solve(
        [
            fixtures.pod(
                name="p",
                node_requirements=[
                    NodeSelectorRequirement("undefined-key", Operator.NOT_IN, ["v"])
                ],
            )
        ]
    )
    assert scheduled(r, "p")


def test_logic_exists_undefined_key_fails():
    """suite_test.go:507."""
    r = solve(
        [
            fixtures.pod(
                name="p",
                node_requirements=[
                    NodeSelectorRequirement("undefined-key", Operator.EXISTS)
                ],
            )
        ]
    )
    assert not scheduled(r, "p")


def test_logic_doesnotexist_undefined_key_schedules():
    """suite_test.go:516."""
    r = solve(
        [
            fixtures.pod(
                name="p",
                node_requirements=[
                    NodeSelectorRequirement("undefined-key", Operator.DOES_NOT_EXIST)
                ],
            )
        ]
    )
    assert scheduled(r, "p")


def test_logic_in_matching_pool_label():
    """suite_test.go:535."""
    pool = fixtures.node_pool(name="default", labels={"test-key": "test-value"})
    r = solve(
        [
            fixtures.pod(
                name="p",
                node_requirements=[
                    NodeSelectorRequirement("test-key", Operator.IN, ["test-value"])
                ],
            )
        ],
        pools=[pool],
    )
    assert scheduled(r, "p")


def test_logic_notin_matching_pool_label_fails():
    """suite_test.go:547."""
    pool = fixtures.node_pool(name="default", labels={"test-key": "test-value"})
    r = solve(
        [
            fixtures.pod(
                name="p",
                node_requirements=[
                    NodeSelectorRequirement(
                        "test-key", Operator.NOT_IN, ["test-value"]
                    )
                ],
            )
        ],
        pools=[pool],
    )
    assert not scheduled(r, "p")


def test_logic_exists_defined_key_schedules():
    """suite_test.go:558."""
    pool = fixtures.node_pool(name="default", labels={"test-key": "test-value"})
    r = solve(
        [
            fixtures.pod(
                name="p",
                node_requirements=[
                    NodeSelectorRequirement("test-key", Operator.EXISTS)
                ],
            )
        ],
        pools=[pool],
    )
    assert scheduled(r, "p")


def test_logic_doesnotexist_defined_key_fails():
    """suite_test.go:570."""
    pool = fixtures.node_pool(name="default", labels={"test-key": "test-value"})
    r = solve(
        [
            fixtures.pod(
                name="p",
                node_requirements=[
                    NodeSelectorRequirement("test-key", Operator.DOES_NOT_EXIST)
                ],
            )
        ],
        pools=[pool],
    )
    assert not scheduled(r, "p")


def test_logic_in_different_value_fails():
    """suite_test.go:582."""
    pool = fixtures.node_pool(name="default", labels={"test-key": "test-value"})
    r = solve(
        [
            fixtures.pod(
                name="p",
                node_requirements=[
                    NodeSelectorRequirement("test-key", Operator.IN, ["different"])
                ],
            )
        ],
        pools=[pool],
    )
    assert not scheduled(r, "p")


def test_logic_notin_different_value_schedules():
    """suite_test.go:593."""
    pool = fixtures.node_pool(name="default", labels={"test-key": "test-value"})
    r = solve(
        [
            fixtures.pod(
                name="p",
                node_requirements=[
                    NodeSelectorRequirement(
                        "test-key", Operator.NOT_IN, ["different"]
                    )
                ],
            )
        ],
        pools=[pool],
    )
    assert scheduled(r, "p")


def test_logic_compatible_pods_share_node():
    """suite_test.go:605 — zone-3 requirement and NotIn[z1,z2] coexist."""
    pods = [
        fixtures.pod(
            name="a",
            requests={"cpu": "100m"},
            node_requirements=[
                NodeSelectorRequirement(ZONE, Operator.IN, ["test-zone-3"])
            ],
        ),
        fixtures.pod(
            name="b",
            requests={"cpu": "100m"},
            node_requirements=[
                NodeSelectorRequirement(
                    ZONE, Operator.NOT_IN, ["test-zone-1", "test-zone-2"]
                )
            ],
        ),
    ]
    r = solve(pods)
    ca, cb = claim_of(r, "a"), claim_of(r, "b")
    assert ca is not None and ca is cb


def test_logic_incompatible_pods_separate_nodes():
    """suite_test.go:625 — In[z1] and NotIn[z1] split."""
    pods = [
        fixtures.pod(
            name="a",
            requests={"cpu": "100m"},
            node_requirements=[
                NodeSelectorRequirement(ZONE, Operator.IN, ["test-zone-1"])
            ],
        ),
        fixtures.pod(
            name="b",
            requests={"cpu": "100m"},
            node_requirements=[
                NodeSelectorRequirement(ZONE, Operator.NOT_IN, ["test-zone-1"])
            ],
        ),
    ]
    r = solve(pods)
    ca, cb = claim_of(r, "a"), claim_of(r, "b")
    assert ca is not None and cb is not None and ca is not cb


def test_logic_exists_does_not_overwrite():
    """suite_test.go:645 — an Exists pod joins an In[z2] claim and the
    claim keeps the concrete zone."""
    pods = [
        fixtures.pod(
            name="a",
            requests={"cpu": "100m"},
            node_requirements=[
                NodeSelectorRequirement(ZONE, Operator.IN, ["test-zone-2"])
            ],
        ),
        fixtures.pod(
            name="b",
            requests={"cpu": "100m"},
            node_requirements=[NodeSelectorRequirement(ZONE, Operator.EXISTS)],
        ),
    ]
    r = solve(pods)
    ca, cb = claim_of(r, "a"), claim_of(r, "b")
    assert ca is not None and ca is cb
    assert claim_value(ca, ZONE) == "test-zone-2"


# ---------------------------------------------------------------------------
# Instance Type Compatibility (suite_test.go:1226-1512)


def test_itc_oversized_request_fails():
    """suite_test.go:1227 — more cpu than any type has."""
    r = solve([fixtures.pod(name="p", requests={"cpu": "512"})])
    assert not scheduled(r, "p")


def test_itc_different_archs_split_nodes():
    """suite_test.go:1238 — amd64 + arm64 pods need two nodes."""
    pods = [
        fixtures.pod(
            name="amd",
            node_requirements=[
                NodeSelectorRequirement(ARCH, Operator.IN, ["amd64"])
            ],
        ),
        fixtures.pod(
            name="arm",
            node_requirements=[
                NodeSelectorRequirement(ARCH, Operator.IN, ["arm64"])
            ],
        ),
    ]
    r = solve(pods)
    ca, cb = claim_of(r, "amd"), claim_of(r, "arm")
    assert ca is not None and cb is not None and ca is not cb
    assert "arm-instance-type" not in type_names(ca)
    assert type_names(cb) == {"arm-instance-type"}


def test_itc_pod_constraints_exclude_types_instance_type():
    """suite_test.go:1265 — affinity In[small-instance-type] with an
    8-cpu request fails (small has 2 cpu)."""
    r = solve(
        [
            fixtures.pod(
                name="p",
                requests={"cpu": "8"},
                node_requirements=[
                    NodeSelectorRequirement(
                        ITYPE, Operator.IN, ["small-instance-type"]
                    )
                ],
            )
        ]
    )
    assert not scheduled(r, "p")


def test_itc_pod_constraints_exclude_types_os():
    """suite_test.go:1288 — os In[ios] only exists on the arm type; an
    amd64 requirement then fails."""
    r = solve(
        [
            fixtures.pod(
                name="p",
                node_requirements=[
                    NodeSelectorRequirement(OS, Operator.IN, ["ios"]),
                    NodeSelectorRequirement(ARCH, Operator.IN, ["amd64"]),
                ],
            )
        ]
    )
    assert not scheduled(r, "p")
    r = solve(
        [
            fixtures.pod(
                name="q",
                node_requirements=[
                    NodeSelectorRequirement(OS, Operator.IN, ["ios"]),
                ],
            )
        ]
    )
    c = claim_of(r, "q")
    assert c is not None and type_names(c) == {"arm-instance-type"}


def test_itc_different_os_split_nodes():
    """suite_test.go:1329 — an ios pod (arm type only) and an amd64/linux
    pod land on different instances."""
    pods = [
        fixtures.pod(
            name="ios",
            node_requirements=[
                NodeSelectorRequirement(OS, Operator.IN, ["ios"])
            ],
        ),
        fixtures.pod(
            name="linux",
            node_requirements=[
                NodeSelectorRequirement(OS, Operator.IN, ["linux"]),
                NodeSelectorRequirement(ARCH, Operator.IN, ["amd64"]),
            ],
        ),
    ]
    r = solve(pods)
    ca, cb = claim_of(r, "ios"), claim_of(r, "linux")
    assert ca is not None and cb is not None and ca is not cb
    assert type_names(ca) == {"arm-instance-type"}
    assert "arm-instance-type" not in type_names(cb)


def test_itc_different_instance_type_selectors_split_nodes():
    """suite_test.go:1356."""
    pods = [
        fixtures.pod(
            name="a", node_selector={ITYPE: "small-instance-type"}
        ),
        fixtures.pod(
            name="b", node_selector={ITYPE: "default-instance-type"}
        ),
    ]
    r = solve(pods)
    ca, cb = claim_of(r, "a"), claim_of(r, "b")
    assert ca is not None and cb is not None and ca is not cb
    assert type_names(ca) == {"small-instance-type"}
    assert type_names(cb) == {"default-instance-type"}


def test_itc_different_zone_selectors_split_nodes():
    """suite_test.go:1383."""
    pods = [
        fixtures.pod(name="a", node_selector={ZONE: "test-zone-1"}),
        fixtures.pod(name="b", node_selector={ZONE: "test-zone-2"}),
    ]
    r = solve(pods)
    ca, cb = claim_of(r, "a"), claim_of(r, "b")
    assert ca is not None and cb is not None and ca is not cb


def test_itc_disjoint_resources_split_nodes():
    """suite_test.go:1410 — vendor-a and vendor-b gpus live on different
    types, so the two pods fork claims."""
    pods = [
        fixtures.pod(name="a", requests={fake.RESOURCE_GPU_VENDOR_A: "1"}),
        fixtures.pod(name="b", requests={fake.RESOURCE_GPU_VENDOR_B: "1"}),
    ]
    r = solve(pods)
    ca, cb = claim_of(r, "a"), claim_of(r, "b")
    assert ca is not None and cb is not None and ca is not cb
    assert type_names(ca) == {"gpu-vendor-instance-type"}
    assert type_names(cb) == {"gpu-vendor-b-instance-type"}


def test_itc_combined_resources_unsatisfiable():
    """suite_test.go:1439 — one pod asking both vendors' gpus fails."""
    r = solve(
        [
            fixtures.pod(
                name="p",
                requests={
                    fake.RESOURCE_GPU_VENDOR_A: "1",
                    fake.RESOURCE_GPU_VENDOR_B: "1",
                },
            )
        ]
    )
    assert not scheduled(r, "p")


# Provider Specific Labels (suite_test.go:1457-1512)


def test_psl_filter_types_matching_labels():
    """suite_test.go:1458 — size=small/large selectors pick type sets."""
    r = solve([fixtures.pod(name="small", node_selector={fake.LABEL_INSTANCE_SIZE: "small"})])
    c = claim_of(r, "small")
    assert c is not None
    assert all(
        "small" in claim_value_of_type(it) for it in c.instance_type_options
    )


def claim_value_of_type(it):
    req = it.requirements.get(fake.LABEL_INSTANCE_SIZE)
    return next(iter(req.values)) if req is not None and req.values else ""


def test_psl_incompatible_labels_fail():
    """suite_test.go:1471 — size=large + the small types' exotic key."""
    r = solve(
        [
            fixtures.pod(
                name="p",
                node_selector={
                    fake.LABEL_INSTANCE_SIZE: "small",
                    fake.EXOTIC_INSTANCE_LABEL_KEY: "optional",
                },
            )
        ],
        its=fake.instance_types(8),
    )
    assert not scheduled(r, "p")


def test_psl_optional_label_schedules():
    """suite_test.go:1488 — the exotic optional label exists on large."""
    r = solve(
        [
            fixtures.pod(
                name="p",
                node_selector={fake.EXOTIC_INSTANCE_LABEL_KEY: "optional"},
            )
        ],
        its=fake.instance_types(8),
    )
    assert scheduled(r, "p")


def test_psl_doesnotexist_excludes_optional_label():
    """suite_test.go:1500 — DoesNotExist on the exotic key forbids the
    large types that define it."""
    r = solve(
        [
            fixtures.pod(
                name="p",
                node_requirements=[
                    NodeSelectorRequirement(
                        fake.EXOTIC_INSTANCE_LABEL_KEY, Operator.DOES_NOT_EXIST
                    )
                ],
            )
        ],
        its=fake.instance_types(8),
    )
    c = claim_of(r, "p")
    assert c is not None
    assert all(
        not it.requirements.has(fake.EXOTIC_INSTANCE_LABEL_KEY)
        or not it.requirements.get(fake.EXOTIC_INSTANCE_LABEL_KEY).values
        for it in c.instance_type_options
    )


# ---------------------------------------------------------------------------
# Binpacking (suite_test.go:1514-1829)


def test_bp_small_pod_smallest_instance():
    """suite_test.go:1515 — a 100m pod picks the cheapest (smallest)."""
    r = solve([fixtures.pod(name="p", requests={"cpu": "100m"})])
    c = claim_of(r, "p")
    assert c is not None
    # cheapest compatible type must survive; creation picks it
    assert "small-instance-type" in type_names(c)


def test_bp_smallest_possible_when_small_is_full():
    """suite_test.go:1527 — 1950m doesn't fit small (2cpu minus 100m
    kube-reserved overhead = 1900m allocatable); the next-cheapest default
    type hosts it."""
    r = solve([fixtures.pod(name="p", requests={"cpu": "1950m"})])
    c = claim_of(r, "p")
    assert c is not None
    assert "small-instance-type" not in type_names(c)
    assert "default-instance-type" in type_names(c)


def test_bp_multiple_small_pods_pack_one_node():
    """suite_test.go:1567 — five 10m pods share one claim."""
    pods = [
        fixtures.pod(name=f"p{i}", requests={"cpu": "10m"}) for i in range(5)
    ]
    r = solve(pods)
    claims = [claim_of(r, f"p{i}") for i in range(5)]
    assert all(c is not None for c in claims)
    assert len({id(c) for c in claims}) == 1


def test_bp_new_node_at_capacity():
    """suite_test.go:1586 — pods overflow to a second node when the first
    fills."""
    pods = [
        fixtures.pod(name=f"p{i}", requests={"cpu": "1"}) for i in range(40)
    ]
    r = solve(pods, its=fake.instance_types(8))
    assert all(scheduled(r, f"p{i}") for i in range(40))
    assert len(r.new_node_claims) >= 2


def test_bp_small_and_large_pack_together():
    """suite_test.go:1606 — mixed sizes fill large instances."""
    pods = [fixtures.pod(name=f"s{i}", requests={"cpu": "100m"}) for i in range(10)]
    pods += [fixtures.pod(name=f"l{i}", requests={"cpu": "4"}) for i in range(2)]
    r = solve(pods, its=fake.instance_types(8))
    assert all(scheduled(r, p.name) for p in pods)


def test_bp_zero_quantity_requests():
    """suite_test.go:1664 — zero-valued requests schedule fine."""
    r = solve([fixtures.pod(name="p", requests={"cpu": "0"})])
    assert scheduled(r, "p")


def test_bp_exceeding_every_type_fails():
    """suite_test.go:1676 — request larger than every type's capacity."""
    r = solve(
        [fixtures.pod(name="p", requests={"cpu": "1000"})],
        its=fake.instance_types(8),
    )
    assert not scheduled(r, "p")


def test_bp_pods_per_node_limit_forces_new_nodes():
    """suite_test.go:1687 — the single-pod type takes one pod each."""
    pods = [
        fixtures.pod(
            name=f"p{i}",
            node_selector={ITYPE: "single-pod-instance-type"},
        )
        for i in range(3)
    ]
    r = solve(pods)
    claims = {id(claim_of(r, f"p{i}")) for i in range(3)}
    assert None not in claims and len(claims) == 3


# ---------------------------------------------------------------------------
# NodePool requirements instance filtering (suite_test.go:4612-4752)


def test_filtering_no_instance_types_pod_error():
    """suite_test.go:4613 — pool requirements eliminate every type; the
    pod error must say so."""
    pool = fixtures.node_pool(
        name="default",
        requirements=[
            NodeSelectorRequirement(ITYPE, Operator.IN, ["nonexistent-type"])
        ],
    )
    r = solve([fixtures.pod(name="p")], pools=[pool])
    assert not scheduled(r, "p")
    assert r.pod_errors


def test_filtering_conflicting_requirements_all_pods_fail():
    """suite_test.go:4660/4693 — several pods, same empty universe."""
    pool = fixtures.node_pool(
        name="default",
        requirements=[
            NodeSelectorRequirement(ARCH, Operator.IN, ["amd64"]),
            NodeSelectorRequirement(ARCH, Operator.NOT_IN, ["amd64"]),
        ],
    )
    r = solve([fixtures.pod(name=f"p{i}") for i in range(3)], pools=[pool])
    assert all(not scheduled(r, f"p{i}") for i in range(3))


def test_filtering_zone_requirements_empty_universe():
    """suite_test.go:4726 — a zone no offering covers filters all types."""
    pool = fixtures.node_pool(
        name="default",
        requirements=[NodeSelectorRequirement(ZONE, Operator.IN, ["test-zone-9"])],
    )
    r = solve([fixtures.pod(name="p")], pools=[pool])
    assert not scheduled(r, "p")


# ---------------------------------------------------------------------------
# kernel-parity tail: the same families through the TPU path


def test_reference_families_kernel_parity():
    """A mixed batch drawn from the families above, solved oracle AND
    kernel — placements must agree (the repo's standing parity bar)."""
    pods = [
        fixtures.pod(name="u1"),
        fixtures.pod(
            name="z3",
            node_requirements=[
                NodeSelectorRequirement(ZONE, Operator.IN, ["test-zone-3"])
            ],
        ),
        fixtures.pod(
            name="ni",
            node_requirements=[
                NodeSelectorRequirement(
                    ZONE, Operator.NOT_IN, ["test-zone-1", "unknown"]
                )
            ],
        ),
        fixtures.pod(
            name="pref",
            node_requirements=[
                NodeSelectorRequirement(
                    ZONE, Operator.IN, ["test-zone-1", "test-zone-2"]
                )
            ],
            node_preferences=[
                NodeSelectorRequirement(ZONE, Operator.IN, ["test-zone-2"])
            ],
        ),
        fixtures.pod(name="exists", node_requirements=[
            NodeSelectorRequirement(ZONE, Operator.EXISTS)
        ]),
        fixtures.pod(name="fail", node_selector={"undefined-key": "v"}),
    ]

    def snapshot(r):
        out = {}
        for pod in pods:
            c = claim_of(r, pod.name)
            out[pod.name] = (
                None if c is None else (claim_value(c, ZONE), tuple(sorted(type_names(c))))
            )
        return out

    import copy

    r_oracle = solve(copy.deepcopy(pods))
    r_kernel = solve(copy.deepcopy(pods), kernel=True)
    assert snapshot(r_oracle) == snapshot(r_kernel)


# ---------------------------------------------------------------------------
# Topology corner cases ported round 5 (topology_test.go)


def test_topology_anti_affinity_schroedinger():
    """topology_test.go:2527 — a pod with zone anti-affinity lands first
    but its zone is UNDETERMINED within the batch (the claim keeps a
    multi-zone set); a pod matching the anti selector cannot schedule in
    the same batch, because the anti pod could be in any zone. Once the
    first solve commits, a second batch places it in a different zone."""
    from karpenter_tpu.api.objects import LabelSelector, PodAffinityTerm
    from karpenter_tpu.solver import Scheduler, Topology

    its = fake.default_instance_types()
    pool = fixtures.node_pool(name="default")
    anti = [
        PodAffinityTerm(
            topology_key=ZONE,
            label_selector=LabelSelector(match_labels={"security": "s2"}),
        )
    ]
    zone_anywhere = fixtures.pod(
        name="anywhere", requests={"cpu": "2"}, pod_anti_requirements=anti
    )
    aff = fixtures.pod(name="affpod", labels={"security": "s2"})
    pods = [zone_anywhere, aff]
    topo = Topology([pool], {"default": its}, pods)
    r = Scheduler([pool], {"default": its}, topo).solve(pods)
    c_any = claim_of(r, "anywhere")
    assert c_any is not None
    # the anti pod's claim keeps a MULTI-zone set (its zone is genuinely
    # undetermined within the batch) ...
    assert len(allowed_zones(c_any)) > 1
    # ... so the matching pod must NOT schedule (it could collide in any
    # zone) — the Schrödinger essence of topology_test.go:2527. Once the
    # node materializes with a concrete zone, the second-batch behavior
    # (schedule into a DIFFERENT zone) is inverse anti-affinity, covered
    # by test_topology_matrix.py::test_inverse_anti_affinity.
    assert not scheduled(r, "affpod")


def test_topology_interdependent_selectors_pack_one_node():
    """topology_test.go:459 — a hostname spread whose selector matches NO
    pods (the spread-owning pods carry different labels): domain counts
    never move, skew stays 0, and all five pods pack onto one claim."""
    from karpenter_tpu.api.objects import (
        LabelSelector,
        TopologySpreadConstraint,
        WhenUnsatisfiable,
    )

    tsc = [
        TopologySpreadConstraint(
            max_skew=1,
            topology_key=well_known.HOSTNAME_LABEL_KEY,
            when_unsatisfiable=WhenUnsatisfiable.DO_NOT_SCHEDULE,
            label_selector=LabelSelector(match_labels={"app": "nomatch"}),
        )
    ]
    pods = [
        fixtures.pod(
            name=f"p{i}",
            labels={"other": "label"},
            requests={"cpu": "100m"},
            topology_spread_constraints=[t for t in tsc],
        )
        for i in range(5)
    ]
    r = solve(pods)
    claims = [claim_of(r, f"p{i}") for i in range(5)]
    assert all(c is not None for c in claims)
    assert len({id(c) for c in claims}) == 1


def test_topology_interdependent_selectors_kernel_parity():
    """The same scenario through the kernel — identical packing."""
    from karpenter_tpu.api.objects import (
        LabelSelector,
        TopologySpreadConstraint,
        WhenUnsatisfiable,
    )

    def make():
        return [
            fixtures.pod(
                name=f"p{i}",
                labels={"other": "label"},
                requests={"cpu": "100m"},
                topology_spread_constraints=[
                    TopologySpreadConstraint(
                        max_skew=1,
                        topology_key=well_known.HOSTNAME_LABEL_KEY,
                        when_unsatisfiable=WhenUnsatisfiable.DO_NOT_SCHEDULE,
                        label_selector=LabelSelector(
                            match_labels={"app": "nomatch"}
                        ),
                    )
                ],
            )
            for i in range(5)
        ]

    ro = solve(make())
    rt = solve(make(), kernel=True)
    count = lambda r: sorted(
        len(c.pods) for c in r.new_node_claims if c.pods
    )
    assert count(ro) == count(rt) == [5]


def test_self_affinity_first_empty_domain_only_hostname():
    """topology_test.go:2065 — 10 pods with self pod-affinity on hostname:
    they must all co-locate, the fake types hold 5 pods per node, so ONE
    claim takes 5 and the other 5 are unschedulable (opening a second
    hostname would break the affinity to the first)."""
    from karpenter_tpu.api.objects import LabelSelector, PodAffinityTerm

    aff = {"security": "s2"}

    def make():
        return [
            fixtures.pod(
                name=f"sa-{i}",
                labels=dict(aff),
                pod_requirements=[
                    PodAffinityTerm(
                        topology_key=well_known.HOSTNAME_LABEL_KEY,
                        label_selector=LabelSelector(match_labels=dict(aff)),
                    )
                ],
            )
            for i in range(10)
        ]

    r = solve(make())
    claims = [c for c in r.new_node_claims if c.pods]
    assert len(claims) == 1
    assert len(claims[0].pods) == 5  # fake types: 5 pods per node
    assert len(r.pod_errors) == 5


def _ct_spread_pods(when, n=5):
    from karpenter_tpu.api.objects import (
        LabelSelector,
        TopologySpreadConstraint,
    )

    return [
        fixtures.pod(
            name=f"ct-{i}",
            labels={"app": "ct"},
            requests={"cpu": "100m"},
            topology_spread_constraints=[
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=well_known.CAPACITY_TYPE_LABEL_KEY,
                    when_unsatisfiable=when,
                    label_selector=LabelSelector(match_labels={"app": "ct"}),
                )
            ],
        )
        for i in range(n)
    ]


def _spot_seeded_problem(pods):
    """An existing SPOT node holding one matching pod (the spot domain has
    count 1) + an on-demand-only pool — the reference's setup for the
    unsatisfiable capacity-type skew (topology_test.go:683-748)."""
    from karpenter_tpu.solver.topology import ClusterSource

    its = fake.default_instance_types()
    pool = fixtures.node_pool(
        name="default",
        requirements=[
            NodeSelectorRequirement(
                well_known.CAPACITY_TYPE_LABEL_KEY, Operator.IN, ["on-demand"]
            )
        ],
    )
    from karpenter_tpu.api.objects import Node, ObjectMeta

    spot_labels = {
        well_known.CAPACITY_TYPE_LABEL_KEY: "spot",
        ZONE: "test-zone-1",
        well_known.HOSTNAME_LABEL_KEY: "spot-node",
        well_known.INSTANCE_TYPE_LABEL_KEY: "default-instance-type",
        well_known.OS_LABEL_KEY: "linux",
        well_known.ARCH_LABEL_KEY: "amd64",
    }
    seeded = fixtures.pod(name="seed", labels={"app": "ct"})
    seeded.node_name = "spot-node"
    spot_node = Node(
        metadata=ObjectMeta(name="spot-node", labels=dict(spot_labels)),
        ready=True,
    )
    cluster = ClusterSource(
        pods_by_namespace={"default": [seeded]},
        nodes_by_name={"spot-node": spot_node},
    )
    topo = Topology(
        [pool], {"default": its}, pods, cluster=cluster
    )
    return Scheduler([pool], {"default": its}, topo)


def test_capacity_type_spread_schedule_anyway_violates():
    """topology_test.go:718 — a SPOT domain already holds one matching pod
    but the pool is on-demand-only: the (1, 5) skew is unavoidable.
    ScheduleAnyway relaxes and everything schedules on-demand."""
    from karpenter_tpu.api.objects import WhenUnsatisfiable

    pods = _ct_spread_pods(WhenUnsatisfiable.SCHEDULE_ANYWAY)
    r = _spot_seeded_problem(pods).solve(pods)
    assert all(scheduled(r, f"ct-{i}") for i in range(5))
    for i in range(5):
        c = claim_of(r, f"ct-{i}")
        assert claim_value(c, well_known.CAPACITY_TYPE_LABEL_KEY) == "on-demand"


def test_capacity_type_spread_do_not_schedule_blocks():
    """topology_test.go:683 — the same setup with DoNotSchedule: only ONE
    more pod may join the on-demand domain (skew 1 vs the spot domain's
    1); the rest are unschedulable."""
    from karpenter_tpu.api.objects import WhenUnsatisfiable

    pods = _ct_spread_pods(WhenUnsatisfiable.DO_NOT_SCHEDULE)
    r = _spot_seeded_problem(pods).solve(pods)
    placed = [i for i in range(5) if scheduled(r, f"ct-{i}")]
    assert len(placed) == 2, (placed, r.pod_errors)


# ---------------------------------------------------------------------------
# NodeOverlay pricing/capacity overlays (round 13, TESTMAP §4:
# pkg/controllers/nodeoverlay/suite_test.go). The overlay controller
# evaluates overlays weight-ordered into a swap-on-write store
# (nodeoverlay/controller.go:69); these scenarios pin the SCHEDULING
# consequences — launch-price reordering and injected extended capacity —
# not just the patched numbers.


def _overlay_op():
    from karpenter_tpu.cloudprovider.decorators import (
        InstanceTypeStore,
        OverlayCloudProvider,
    )
    from karpenter_tpu.cloudprovider.kwok import construct_instance_types
    from karpenter_tpu.controllers.kube import FakeClock
    from karpenter_tpu.controllers.nodeoverlay import NodeOverlayController
    from karpenter_tpu.controllers.operator import Operator as Op

    op = Op(clock=FakeClock(), force_oracle=True)
    op.raw_cloud.types = construct_instance_types(sizes=[2, 8])
    op.raw_cloud._by_name = {it.name: it for it in op.raw_cloud.types}
    op.kube.create("NodePool", fixtures.node_pool(name="default"))
    store = InstanceTypeStore()
    ctrl = NodeOverlayController(op.kube, op.cloud, store)
    return op, store, ctrl, OverlayCloudProvider(op.cloud, store)


def test_overlay_absolute_price_reorders_launch_choice():
    """nodeoverlay/suite_test.go:132 ("should update the price ...") +
    to_node_claim's price ordering (solver/nodes.py:260): an absolute
    price of ~0 on the 8-cpu family makes it the cheapest LAUNCH choice
    where the 2-cpu type won before."""
    from karpenter_tpu.api.objects import ObjectMeta
    from karpenter_tpu.controllers.nodeoverlay import NodeOverlay

    op, store, ctrl, overlay_cloud = _overlay_op()
    np_ = op.kube.list("NodePool")[0]

    from karpenter_tpu.cloudprovider.types import InstanceTypes

    def cheapest_name(its):
        pods = [fixtures.pod(name="p0", requests={"cpu": "100m"})]
        r = solve(pods, pools=[np_], its=InstanceTypes(its))
        claim = claim_of(r, "p0")
        # the launch choice: to_node_claim injects the price-ordered
        # option list (nodeclaimtemplate.go:79); order the claim's
        # surviving options the same way and take the head
        ordered = InstanceTypes(claim.instance_type_options).order_by_price(
            claim.requirements
        )
        return ordered[0].name if ordered else None

    before = cheapest_name(op.cloud.get_instance_types(np_))
    assert before is not None and "-2x-" in before

    # select the 8x types only, by type name (the overlay requirement
    # matches instance-type labels, suite_test.go:132)
    op.kube.create(
        "NodeOverlay",
        NodeOverlay(
            metadata=ObjectMeta(name="big-discount"),
            requirements=[
                NodeSelectorRequirement(
                    ITYPE,
                    Operator.IN,
                    [
                        it.name
                        for it in op.cloud.get_instance_types(np_)
                        if "-8x-" in it.name
                    ],
                )
            ],
            price=0.0001,
        ),
    )
    assert ctrl.reconcile_all() == {}
    after = cheapest_name(overlay_cloud.get_instance_types(np_))
    assert after is not None and "-8x-" in after, after


def test_overlay_weight_order_highest_wins_per_field():
    """nodeoverlay/suite_test.go:212 (ordered evaluation + conflict
    rules, controller.go:69): two price overlays hit the same types —
    the higher-weight one applies, the lower never stacks on top."""
    from karpenter_tpu.api.objects import ObjectMeta
    from karpenter_tpu.controllers.nodeoverlay import NodeOverlay

    op, store, ctrl, overlay_cloud = _overlay_op()
    np_ = op.kube.list("NodePool")[0]
    base = {it.name: it.offerings[0].price for it in op.cloud.get_instance_types(np_)}
    op.kube.create(
        "NodeOverlay",
        NodeOverlay(
            metadata=ObjectMeta(name="strong"), weight=10, price_adjustment="-50%"
        ),
    )
    op.kube.create(
        "NodeOverlay",
        NodeOverlay(
            metadata=ObjectMeta(name="weak"), weight=1, price_adjustment="-90%"
        ),
    )
    assert ctrl.reconcile_all() == {}
    for it in overlay_cloud.get_instance_types(np_):
        assert it.offerings[0].price == pytest.approx(base[it.name] * 0.5), it.name


def test_overlay_injected_capacity_makes_extended_resource_schedulable():
    """nodeoverlay/suite_test.go:303 ("Capacity"): a pod requesting an
    extended resource no instance type carries is unschedulable until an
    overlay injects the capacity — then it schedules, and the claim's
    accumulated requests count the resource in integer milli-units."""
    from karpenter_tpu.api.objects import ObjectMeta
    from karpenter_tpu.controllers.nodeoverlay import NodeOverlay
    from karpenter_tpu.cloudprovider.types import InstanceTypes

    op, store, ctrl, overlay_cloud = _overlay_op()
    np_ = op.kube.list("NodePool")[0]

    def try_solve(its):
        pods = [
            fixtures.pod(
                name="gpu-pod",
                requests={"cpu": "100m", "smarter.sh/renewable": 2},
            )
        ]
        return solve(pods, pools=[np_], its=InstanceTypes(its))

    r = try_solve(op.cloud.get_instance_types(np_))
    assert not scheduled(r, "gpu-pod")

    op.kube.create(
        "NodeOverlay",
        NodeOverlay(
            metadata=ObjectMeta(name="renewable"),
            capacity={"smarter.sh/renewable": 4000},
        ),
    )
    assert ctrl.reconcile_all() == {}
    r = try_solve(overlay_cloud.get_instance_types(np_))
    assert scheduled(r, "gpu-pod")
    claim = claim_of(r, "gpu-pod")
    assert claim.requests.get("smarter.sh/renewable") == 2000  # milli-units


# ---------------------------------------------------------------------------
# Static capacity (round 13, TESTMAP §4: pkg/controllers/static/
# provisioning/suite_test.go + deprovisioning/suite_test.go). The aux
# suite covers the replica loop mechanics; these pin the reference's
# limit and ordering scenarios.


def _static_op():
    from karpenter_tpu.cloudprovider.kwok import construct_instance_types
    from karpenter_tpu.controllers.kube import FakeClock
    from karpenter_tpu.controllers.operator import Operator as Op
    from karpenter_tpu.options import FeatureGates, Options

    op = Op(
        clock=FakeClock(),
        force_oracle=True,
        options=Options(feature_gates=FeatureGates(static_capacity=True)),
    )
    op.raw_cloud.types = construct_instance_types(sizes=[2])
    op.raw_cloud._by_name = {it.name: it for it in op.raw_cloud.types}
    return op


def test_static_replicas_capped_by_nodes_limit():
    """static/provisioning/suite_test.go:118 ("should not provision past
    the nodes limit", controller.go:93 reserve-against-limit): replicas=5
    under limits.nodes=3 creates exactly 3 claims, and repeat reconciles
    never burst past the reservation."""
    from karpenter_tpu.controllers.static import StaticProvisioning

    op = _static_op()
    op.kube.create(
        "NodePool",
        fixtures.node_pool(name="warm", replicas=5, limits={"nodes": "3"}),
    )
    prov = StaticProvisioning(op.kube, op.cluster, op.recorder)
    assert prov.reconcile_all() == 3
    assert prov.reconcile_all() == 0
    assert len(op.kube.list("NodeClaim")) == 3


def test_static_scale_down_removes_emptiest_first():
    """static/deprovisioning/suite_test.go:146 ("should delete the
    emptiest nodes first", controller.go:84): three static nodes, pods
    bound to two — scaling replicas to 2 deletes exactly the empty one."""
    from karpenter_tpu.api.objects import PodPhase
    from karpenter_tpu.controllers.static import (
        StaticDeprovisioning,
        StaticProvisioning,
    )

    op = _static_op()
    op.kube.create("NodePool", fixtures.node_pool(name="warm", replicas=3))
    StaticProvisioning(op.kube, op.cluster, op.recorder).reconcile_all()
    op.run_until_settled(max_ticks=30)
    nodes = sorted(n.name for n in op.kube.list("Node"))
    assert len(nodes) == 3
    for i, node_name in enumerate(nodes[:2]):
        rider = fixtures.pod(name=f"rider-{i}", requests={"cpu": "100m"})
        rider.node_name = node_name
        rider.phase = PodPhase.RUNNING
        op.kube.create("Pod", rider)
    np_ = op.kube.list("NodePool")[0]
    np_.replicas = 2
    op.kube.update("NodePool", np_)
    assert StaticDeprovisioning(op.kube, op.cluster, op.recorder).reconcile_all() == 1
    deleting = [
        c.name
        for c in op.kube.list("NodeClaim")
        if c.metadata.deletion_timestamp is not None
    ]
    # the one deleted claim is the node with zero riders
    empty = nodes[2]
    claims_by_node = {
        c.status.node_name: c.name for c in op.kube.list("NodeClaim")
    }
    assert deleting == [claims_by_node[empty]]


def test_static_pool_invisible_to_dynamic_provisioning():
    """static/provisioning/suite_test.go:89 + provisioning.py:356: a
    static pool never CREATES claims for pending pods. Its existing
    nodes still serve them (they are ordinary cluster nodes), so the pin
    is two-phase: a filler pod lands on the static node, then an
    overflow pod that would fit a FRESH node stays pending — a dynamic
    pool would have provisioned one, the static pool must not."""
    from karpenter_tpu.controllers.static import StaticProvisioning

    op = _static_op()
    op.kube.create("NodePool", fixtures.node_pool(name="warm", replicas=1))
    StaticProvisioning(op.kube, op.cluster, op.recorder).reconcile_all()
    op.run_until_settled(max_ticks=30)
    assert len(op.kube.list("NodeClaim")) == 1
    filler = fixtures.pod(name="filler", requests={"cpu": "1500m"})
    op.kube.create("Pod", filler)
    op.run_until_settled(max_ticks=20)
    assert op.kube.get("Pod", "filler").node_name is not None
    overflow = fixtures.pod(name="overflow", requests={"cpu": "1000m"})
    op.kube.create("Pod", overflow)
    op.run_until_settled(max_ticks=20)
    # the overflow pod no longer fits the (filled) static node; it WOULD
    # fit a fresh 2-cpu node, but no dynamic claim may be created from
    # the static pool
    assert len(op.kube.list("NodeClaim")) == 1
    assert not op.kube.get("Pod", "overflow").node_name
