"""Property tests: the bitmask tensor encoding vs the Python Requirement
algebra (which is itself tested against the reference semantics in
test_requirements.py). Random requirement pairs must agree on
HasIntersection, Compatible, and the full intersection's allowed-value set.

All trials share one vocab (the fixed VALUE_POOL) and are batched into a
single kernel invocation per test, so the jax dispatch overhead is paid once.
"""

from __future__ import annotations

import random

import numpy as np
import pytest

from karpenter_tpu.api.objects import Operator
from karpenter_tpu.ops import encode_requirements, decode_row, ResourceTable, Vocab
from karpenter_tpu.ops.encode import Reqs
from karpenter_tpu.ops.kernels import (
    VocabArrays,
    compat,
    distinct_value_counts,
    intersect,
    intersect_nonempty,
    intersects_only,
)
from karpenter_tpu.scheduling import ALLOW_UNDEFINED_WELL_KNOWN_LABELS, Requirement, Requirements

KEYS = [
    "topology.kubernetes.io/zone",  # well-known
    "kubernetes.io/arch",  # well-known
    "example.com/custom-a",
    "example.com/custom-b",
    "example.com/int-key",
]
VALUE_POOL = {
    "topology.kubernetes.io/zone": [f"zone-{i}" for i in range(5)],
    "kubernetes.io/arch": ["amd64", "arm64"],
    "example.com/custom-a": list("abcdefg"),
    "example.com/custom-b": list("xyz"),
    "example.com/int-key": [str(n) for n in (1, 3, 5, 8, 13, 21, 40)],
}


def shared_vocab() -> tuple[Vocab, VocabArrays]:
    vocab = Vocab()
    for key, pool in VALUE_POOL.items():
        for v in pool:
            vocab.observe_labels({key: v})
    vocab.finalize()
    return vocab, VocabArrays.from_vocab(vocab)


VOCAB, VA = shared_vocab()


def random_requirement(rng: random.Random, key: str) -> Requirement:
    pool = VALUE_POOL[key]
    op = rng.choice(
        [Operator.IN, Operator.NOT_IN, Operator.EXISTS, Operator.DOES_NOT_EXIST]
        + ([Operator.GT, Operator.LT] if key == "example.com/int-key" else [])
    )
    if op in (Operator.IN, Operator.NOT_IN):
        values = rng.sample(pool, rng.randint(1, min(4, len(pool))))
    elif op in (Operator.GT, Operator.LT):
        values = [str(rng.randint(0, 45))]
    else:
        values = []
    return Requirement(key, op, values)


def random_requirements(rng: random.Random, max_keys: int = 4) -> Requirements:
    keys = rng.sample(KEYS, rng.randint(0, max_keys))
    return Requirements(random_requirement(rng, k) for k in keys)


def np_rows(e: Reqs) -> Reqs:
    return Reqs(*(np.asarray(a) for a in e))


def test_has_intersection_pairs():
    rng = random.Random(7)
    pairs = []
    for _ in range(400):
        key = rng.choice(KEYS)
        pairs.append((key, random_requirement(rng, key), random_requirement(rng, key)))
    left = encode_requirements(VOCAB, [Requirements([a.copy()]) for _, a, _ in pairs])
    right = encode_requirements(VOCAB, [Requirements([b.copy()]) for _, _, b in pairs])
    got = np.asarray(intersect_nonempty(left, right, VA))
    for i, (key, a, b) in enumerate(pairs):
        kid = VOCAB.key_index[key]
        want = a.has_intersection(b)
        assert bool(got[i, kid]) == want, f"trial {i}: {a!r} vs {b!r}"


def test_compatible_and_intersects_sets():
    rng = random.Random(11)
    pairs = [(random_requirements(rng), random_requirements(rng)) for _ in range(400)]
    left = encode_requirements(VOCAB, [a for a, _ in pairs])
    right = encode_requirements(VOCAB, [b for _, b in pairs])
    got_strict = np.asarray(compat(left, right, VA, False))
    got_allow = np.asarray(compat(left, right, VA, True))
    got_inter = np.asarray(intersects_only(left, right, VA))
    for i, (a, b) in enumerate(pairs):
        assert bool(got_strict[i]) == (a.compatible(b) is None), f"{i}: {a!r} || {b!r}"
        assert bool(got_allow[i]) == (
            a.compatible(b, ALLOW_UNDEFINED_WELL_KNOWN_LABELS) is None
        ), f"{i} allow: {a!r} || {b!r}"
        assert bool(got_inter[i]) == (a.intersects(b) is None), f"{i} ∩: {a!r} {b!r}"


def test_intersection_allowed_values_roundtrip():
    rng = random.Random(17)
    pairs = []
    for _ in range(400):
        key = rng.choice(KEYS)
        pairs.append((key, random_requirement(rng, key), random_requirement(rng, key)))
    left = encode_requirements(VOCAB, [Requirements([a.copy()]) for _, a, _ in pairs])
    right = encode_requirements(VOCAB, [Requirements([b.copy()]) for _, _, b in pairs])
    merged = np_rows(intersect(left, right, VA))
    for i, (key, r1, r2) in enumerate(pairs):
        decoded = decode_row(VOCAB, merged.row(i))
        want_req = r1.intersection(r2)
        got_req = decoded.get(key)
        for v in VALUE_POOL[key] + ["unseen-value", "7", "100"]:
            if v not in VALUE_POOL[key] and not want_req.complement:
                # concrete results are exact only over the vocab; concrete
                # requirement values are always vocab members by construction
                continue
            assert got_req.has(v) == want_req.has(v), (
                f"trial {i}: ({r1!r}) ∩ ({r2!r}) disagree on {v!r}: "
                f"decoded {got_req!r} want {want_req!r}"
            )
        assert got_req.operator() == want_req.operator(), (
            f"trial {i}: ({r1!r}) ∩ ({r2!r}) operator drift: "
            f"{got_req.operator()} want {want_req.operator()}"
        )


def test_intersect_notin_collapses_under_bounds():
    """Regression: NotIn{"1"} ∩ Gt(5) must collapse to Exists (the excluded
    value fails the combined bounds), so a subsequent DoesNotExist is NOT
    tolerated — mirroring Requirements.compatible exactly."""
    key = "example.com/int-key"
    a = Requirements([Requirement(key, Operator.NOT_IN, ["1"])])
    b = Requirements([Requirement(key, Operator.GT, ["5"])])
    c = Requirements([Requirement(key, Operator.DOES_NOT_EXIST)])
    enc = encode_requirements(VOCAB, [a, b, c])
    merged = intersect(enc.row(0), enc.row(1), VA)
    decoded = decode_row(VOCAB, np_rows(merged))
    want = a.get(key).intersection(b.get(key))
    assert decoded.get(key).operator() == want.operator() == Operator.EXISTS
    py = Requirements([want])
    want_ok = py.compatible(c) is None
    got_ok = bool(np.asarray(compat(merged, enc.row(2), VA, False)))
    assert got_ok == want_ok == False  # noqa: E712


def test_chained_intersect_then_compat():
    """Property: compat() on an intersect() result must equal the Python
    chain Requirements.add + compatible (catches operator-drift bugs)."""
    rng = random.Random(29)
    triples = []
    for _ in range(300):
        key = rng.choice(KEYS)
        triples.append((key, *(random_requirement(rng, key) for _ in range(3))))
    e1 = encode_requirements(VOCAB, [Requirements([a.copy()]) for _, a, _, _ in triples])
    e2 = encode_requirements(VOCAB, [Requirements([b.copy()]) for _, _, b, _ in triples])
    e3 = encode_requirements(VOCAB, [Requirements([c.copy()]) for _, _, _, c in triples])
    merged = intersect(e1, e2, VA)
    got_strict = np.asarray(compat(merged, e3, VA, False))
    got_allow = np.asarray(compat(merged, e3, VA, True))
    for i, (key, r1, r2, r3) in enumerate(triples):
        py = Requirements([r1.copy()])
        py.add(r2.copy())
        s3 = Requirements([r3.copy()])
        assert bool(got_strict[i]) == (py.compatible(s3) is None), (
            f"trial {i}: ({r1!r} ∩ {r2!r}) || {r3!r}"
        )
        assert bool(got_allow[i]) == (
            py.compatible(s3, ALLOW_UNDEFINED_WELL_KNOWN_LABELS) is None
        ), f"trial {i} allow: ({r1!r} ∩ {r2!r}) || {r3!r}"


def test_decode_roundtrip():
    rng = random.Random(23)
    sets = [random_requirements(rng, max_keys=5) for _ in range(150)]
    enc = np_rows(encode_requirements(VOCAB, sets))
    for i, s in enumerate(sets):
        decoded = decode_row(VOCAB, enc.row(i))
        for key in s:
            for v in VALUE_POOL[key] + ["unseen", "12"]:
                assert decoded.get(key).has(v) == s.get(key).has(v), (key, v, s.get(key))


def test_distinct_value_counts():
    sets = [
        Requirements([Requirement("example.com/custom-a", Operator.IN, ["a", "b"])]),
        Requirements([Requirement("example.com/custom-a", Operator.IN, ["b", "c"])]),
        Requirements([Requirement("example.com/custom-a", Operator.IN, ["d"])]),
    ]
    enc = encode_requirements(VOCAB, sets)
    kid = VOCAB.key_index["example.com/custom-a"]
    alive = np.array([True, True, False])
    counts = np.asarray(distinct_value_counts(np.asarray(enc.mask), alive, VA))
    assert counts[kid] == 3  # {a, b, c}
    alive_all = np.array([True, True, True])
    counts = np.asarray(distinct_value_counts(np.asarray(enc.mask), alive_all, VA))
    assert counts[kid] == 4


def test_resource_table_exact():
    table = ResourceTable()
    mi = 1024 * 1024 * 1000  # 1Mi in milli-bytes
    table.observe({"cpu": 100, "memory": 100 * mi})
    table.observe({"cpu": 250, "memory": 2048 * mi})
    table.observe({"cpu": 128_000, "memory": 262_144 * mi})  # a big node
    table.finalize()
    row = table.encode({"cpu": 250, "memory": 2048 * mi})
    assert table.decode(row) == {"cpu": 250, "memory": 2048 * mi}
    # scales divide all observed values
    ci = table.index["cpu"]
    assert 250 % table.scale[ci] == 0


def test_resource_table_rejects_unobserved():
    from karpenter_tpu.ops import UnsupportedProblem

    table = ResourceTable()
    table.observe({"cpu": 100})
    table.finalize()
    with pytest.raises(UnsupportedProblem):
        table.encode({"nvidia.com/gpu": 1000})
