"""Requirement/Requirements algebra tests.

Scenario coverage modeled on the reference's requirement/requirements suites
(/root/reference/pkg/scheduling/requirements_test.go): pairwise operator
intersection truth tables, bounds interplay, Compatible()'s asymmetric
undefined-key rule, and pod-requirement construction.
"""

import itertools

import pytest

from karpenter_tpu.api.objects import (
    NodeAffinity,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    Operator,
    Pod,
    PreferredSchedulingTerm,
)
from karpenter_tpu.scheduling import Requirement, Requirements

IN = lambda key, *vals: Requirement(key, Operator.IN, vals)
NOT_IN = lambda key, *vals: Requirement(key, Operator.NOT_IN, vals)
EXISTS = lambda key: Requirement(key, Operator.EXISTS)
DOES_NOT_EXIST = lambda key: Requirement(key, Operator.DOES_NOT_EXIST)
GT = lambda key, v: Requirement(key, Operator.GT, [str(v)])
LT = lambda key, v: Requirement(key, Operator.LT, [str(v)])


# -- Requirement.has ---------------------------------------------------------


def test_has():
    assert IN("k", "a", "b").has("a")
    assert not IN("k", "a", "b").has("c")
    assert NOT_IN("k", "a").has("b")
    assert not NOT_IN("k", "a").has("a")
    assert EXISTS("k").has("anything")
    assert not DOES_NOT_EXIST("k").has("anything")
    assert GT("k", 5).has("6")
    assert not GT("k", 5).has("5")
    assert not GT("k", 5).has("abc")  # non-integers invalid under bounds
    assert LT("k", 5).has("4")
    assert not LT("k", 5).has("5")


# -- intersection ------------------------------------------------------------


def test_intersection_in_in():
    r = IN("k", "a", "b").intersection(IN("k", "b", "c"))
    assert r.values == {"b"} and not r.complement


def test_intersection_in_notin():
    r = IN("k", "a", "b").intersection(NOT_IN("k", "b"))
    assert r.values == {"a"} and not r.complement


def test_intersection_notin_notin():
    r = NOT_IN("k", "a").intersection(NOT_IN("k", "b"))
    assert r.complement and r.values == {"a", "b"}


def test_intersection_exists_in():
    r = EXISTS("k").intersection(IN("k", "a"))
    assert not r.complement and r.values == {"a"}


def test_intersection_doesnotexist():
    r = DOES_NOT_EXIST("k").intersection(IN("k", "a"))
    assert not r.complement and r.values == set()


def test_intersection_bounds():
    r = GT("k", 1).intersection(LT("k", 5))
    assert r.complement and r.greater_than == 1 and r.less_than == 5
    assert r.has("3") and not r.has("1") and not r.has("5")
    # contradictory bounds collapse to DoesNotExist
    r2 = GT("k", 5).intersection(LT("k", 3))
    assert r2.operator() == Operator.DOES_NOT_EXIST
    # bounds filter concrete values and are then dropped
    r3 = IN("k", "1", "3", "9").intersection(GT("k", 2))
    assert r3.values == {"3", "9"} and not r3.complement
    assert r3.greater_than is None  # dropped for concrete sets


def test_intersection_min_values_max_wins():
    a = Requirement("k", Operator.IN, ["a", "b"], min_values=1)
    b = Requirement("k", Operator.IN, ["a", "b"], min_values=2)
    assert a.intersection(b).min_values == 2


# -- has_intersection agrees with intersection non-emptiness -----------------


def _nonempty(r: Requirement) -> bool:
    if r.complement:
        # a complement is non-empty iff its integer bounds window is non-empty
        if r.greater_than is not None and r.less_than is not None:
            return r.greater_than < r.less_than
        return True
    return len(r.values) > 0


@pytest.mark.parametrize(
    "a,b",
    list(
        itertools.product(
            [
                IN("k", "a"),
                IN("k", "a", "b"),
                IN("k", "1", "7"),
                NOT_IN("k", "a"),
                NOT_IN("k", "1"),
                EXISTS("k"),
                DOES_NOT_EXIST("k"),
                GT("k", 3),
                LT("k", 5),
                GT("k", 8),
            ],
            repeat=2,
        )
    ),
)
def test_has_intersection_matches_intersection(a, b):
    # Mirrors the reference's property: HasIntersection is the allocation-free
    # equivalent of Intersection + emptiness check (requirement.go:194-197),
    # EXCEPT both-complement cases where the reference returns true without
    # value checks — replicate exactly.
    got = a.has_intersection(b)
    if a.complement and b.complement:
        gt = max((v for v in [a.greater_than, b.greater_than] if v is not None), default=None)
        lt = min((v for v in [a.less_than, b.less_than] if v is not None), default=None)
        expected = not (gt is not None and lt is not None and gt >= lt)
    else:
        expected = _nonempty(a.intersection(b))
    assert got == expected, f"{a!r} ∩ {b!r}"


# -- Requirements map --------------------------------------------------------


def test_add_auto_intersects():
    reqs = Requirements([IN("k", "a", "b")])
    reqs.add(IN("k", "b", "c"))
    assert reqs.get("k").values == {"b"}


def test_get_default_exists():
    reqs = Requirements()
    assert reqs.get("missing").operator() == Operator.EXISTS


def test_label_normalization():
    r = Requirement("beta.kubernetes.io/arch", Operator.IN, ["amd64"])
    assert r.key == "kubernetes.io/arch"


def test_intersects_overlap():
    a = Requirements([IN("k", "a", "b")])
    b = Requirements([IN("k", "b", "c")])
    assert a.intersects(b) is None
    c = Requirements([IN("k", "x")])
    assert a.intersects(c) is not None


def test_intersects_undefined_keys_allowed():
    a = Requirements([IN("k1", "a")])
    b = Requirements([IN("k2", "b")])
    assert a.intersects(b) is None


def test_intersects_notin_vs_notin_tolerated():
    # DoesNotExist incoming vs NotIn existing with no overlap is tolerated
    # (requirements.go:253-259)
    a = Requirements([DOES_NOT_EXIST("k")])
    b = Requirements([NOT_IN("k", "a")])
    assert b.intersects(a) is None


def test_compatible_custom_label_must_be_defined():
    node = Requirements([IN("known", "x")])
    pod = Requirements([IN("custom-key", "x")])
    # custom label undefined on node -> error
    assert node.compatible(pod) is not None
    # but allowed when listed in allow_undefined
    assert node.compatible(pod, allow_undefined={"custom-key"}) is None
    # NotIn/DoesNotExist incoming ops don't require definition
    assert node.compatible(Requirements([NOT_IN("custom-key", "v")])) is None
    assert node.compatible(Requirements([DOES_NOT_EXIST("custom-key")])) is None


def test_compatible_well_known_may_be_undefined():
    from karpenter_tpu.api import labels as wk

    node = Requirements()
    pod = Requirements([IN(wk.TOPOLOGY_ZONE_LABEL_KEY, "zone-1")])
    assert node.compatible(pod, allow_undefined=wk.WELL_KNOWN_LABELS) is None


def test_pod_requirements_construction():
    pod = Pod(
        node_selector={"disk": "ssd"},
        node_affinity=NodeAffinity(
            required_terms=[
                NodeSelectorTerm([NodeSelectorRequirement("zone", Operator.IN, ["a", "b"])]),
                NodeSelectorTerm([NodeSelectorRequirement("zone", Operator.IN, ["c"])]),
            ],
            preferred=[
                PreferredSchedulingTerm(
                    weight=10,
                    preference=NodeSelectorTerm(
                        [NodeSelectorRequirement("arch", Operator.IN, ["amd64"])]
                    ),
                ),
                PreferredSchedulingTerm(
                    weight=1,
                    preference=NodeSelectorTerm(
                        [NodeSelectorRequirement("os", Operator.IN, ["linux"])]
                    ),
                ),
            ],
        ),
    )
    reqs = Requirements.from_pod(pod)
    assert reqs.get("disk").values == {"ssd"}
    # only the first required term
    assert reqs.get("zone").values == {"a", "b"}
    # only the heaviest preference
    assert reqs.get("arch").values == {"amd64"}
    assert not reqs.has("os")
    # strict ignores preferences
    strict = Requirements.strict_from_pod(pod)
    assert not strict.has("arch")
    assert strict.get("zone").values == {"a", "b"}


def test_requirement_len():
    import sys

    assert len(IN("k", "a", "b")) == 2
    assert len(NOT_IN("k", "a")) == sys.maxsize - 1
    assert len(DOES_NOT_EXIST("k")) == 0


def test_ffd_order_equals_ffd_sort_key():
    """ordering.ffd_order (vectorized lexsort) MUST stay identical to
    sorting by ffd_sort_key — the oracle and the TPU path sort with the
    same key or parity breaks (CLAUDE.md invariant). Includes long
    caller-set uids sharing a prefix (the truncation trap) and exact
    request ties."""
    from karpenter_tpu.solver.ordering import ffd_order, ffd_sort_key
    from karpenter_tpu.testing import fixtures
    from karpenter_tpu.utils import resources as res

    for seed in (3, 31):
        fixtures.reset_rng(seed)
        pods = fixtures.make_diverse_pods(300) + fixtures.make_preference_pods(30)
        # adversarial uids: longer than any fixed dtype guess, shared prefix
        for i, p in enumerate(pods[:40]):
            p.metadata.uid = "x" * 44 + f"{(97 - i):04d}"
        reqs = {p.uid: res.requests_for_pods([p]) for p in pods}
        want = sorted(
            range(len(pods)), key=lambda i: ffd_sort_key(pods[i], reqs[pods[i].uid])
        )
        got = ffd_order(pods, lambda p: reqs[p.uid])
        assert got == want
