"""Fleet-axis serving conformance (solver/fleet.py).

The acceptance pins for the coalescing subsystem:

- the PARITY MATRIX: N in {2, 5, 8} concurrent sidecar solves with
  distinct request profiles coalesce into ONE vmapped dispatch (asserted
  via the per-dispatch accounting and the traces' fleet_dispatch spans)
  and EVERY lane's NodeClaims are identical to its solo in-process
  solve — decisions, instance-type survivor sets, and request vectors;
- the shared lane-stack/dispatch core is bit-identical to per-lane
  solo `solve_scan` runs (the in-tree twin of dryrun_multichip phase 4,
  which now drives the same fleet.py code);
- a window that closes with one lane falls back to the solo path with
  identical decisions (mode=solo_window — the coalescer never taxes a
  lone control plane with a compiled vmapped shape);
- runs-path solves never enter the coalescer (mid-solve claim regrow is
  host-driven per lane) and still answer identically;
- the grouping key (epochs.table_fingerprint) admits distinct request
  profiles while refusing different clusters.
"""

from __future__ import annotations

import tempfile
import threading
import time

import numpy as np
import pytest

from karpenter_tpu import tracing
from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.solver import epochs, fleet
from karpenter_tpu.solver.service import SolverClient, SolverServer
from karpenter_tpu.solver.topology import Topology
from karpenter_tpu.solver.tpu import TpuScheduler
from karpenter_tpu.solver.tpu_problem import encode_problem
from karpenter_tpu.testing import fixtures

# the client-side wire budget: the FIRST coalesced window compiles the
# vmapped kernel cold on this CPU backend, and every sibling lane waits
# behind that compile inside its own solve call
WIRE_TIMEOUT = 600.0


def _spread_pods(n: int, cpu: str) -> list:
    """The shared scan-path fixture (fixtures.make_self_spread_pods):
    `cpu` varies the request profile per lane WITHOUT touching the
    requirement classes, so distinct profiles still share one table
    fingerprint (the phase-4 shape)."""
    return fixtures.make_self_spread_pods(n, cpu)


def _problem(cpu: str, n: int = 6):
    fixtures.reset_rng(5)
    its = construct_instance_types(sizes=[2, 8])
    pools = [fixtures.node_pool(name="default")]
    pods = _spread_pods(n, cpu)
    return pools, {"default": its}, pods


def _solo_parts(cpu: str, n: int = 6):
    """The solo in-process referee: the same problem through a fresh
    TpuScheduler (no fleet, no cache)."""
    pools, ibp, pods = _problem(cpu, n)
    topo = Topology(pools, ibp, pods)
    sched = TpuScheduler(pools, ibp, topo)
    r = sched.solve(pods)
    assert not sched.last_used_runs, "referee must ride the scan path"
    assert not r.pod_errors, r.pod_errors
    return sorted(
        (
            tuple(sorted(p.name for p in c.pods)),
            c.template.nodepool_name,
            tuple(sorted(it.name for it in c.instance_type_options)),
            tuple(sorted(c.requests.items())),
        )
        for c in r.new_node_claims
        if c.pods
    )


def _remote_parts(got: dict, pods) -> list:
    name_of = {p.uid: p.name for p in pods}
    return sorted(
        (
            tuple(sorted(name_of[u] for u in cl["pod_uids"])),
            cl["nodepool"],
            tuple(sorted(cl["instance_types"])),
            tuple(sorted((k, int(v)) for k, v in cl["requests"].items())),
        )
        for cl in got["new_node_claims"]
        if cl["pod_uids"]
    )


# all multiples of 100m: request granularity feeds the resource-table
# scale, and a profile that changes the scale (e.g. 150m) changes the
# integer ialloc/icap encodings — a REAL tb difference the table
# fingerprint correctly refuses to stack
# (test_table_fingerprint_groups_profiles_not_clusters pins the refusal
# side on a cluster change)
_PROFILES = [f"{k}00m" for k in range(1, 9)]


@pytest.mark.parametrize("lanes", [2, 5, 8])
def test_fleet_parity_matrix(lanes):
    """The acceptance matrix: `lanes` concurrent sidecar solves with
    distinct request profiles coalesce into ONE vmapped dispatch and
    every lane's claims equal its solo in-process solve."""
    profiles = _PROFILES[:lanes]
    refs = {cpu: _solo_parts(cpu) for cpu in profiles}

    path = tempfile.mktemp(suffix=".fleet.sock")
    srv = SolverServer(
        path,
        # generous: per-lane server-side encode is GIL-serialized on this
        # 1-core box, so the last of 8 lanes can trail the first by
        # seconds — a FULL window still wakes the leader immediately, so
        # the happy path never waits this long
        fleet_window_seconds=10.0,
        fleet_max_lanes=lanes,
        admission=epochs.AdmissionGate(max_inflight=32),
    )
    srv.start()
    d0 = tracing.SOLVE_DISPATCHES.value({"path": "fleet"})
    c0 = fleet.FLEET_SOLVES.value({"mode": "coalesced"})
    seq0 = tracing.Trace("probe").seq  # ring watermark for new traces
    out: dict[str, tuple] = {}
    errors: dict[str, BaseException] = {}
    barrier = threading.Barrier(lanes)

    def client(cpu: str) -> None:
        try:
            c = SolverClient(path, request_timeout=WIRE_TIMEOUT)
            pools, ibp, pods = _problem(cpu)
            barrier.wait()
            got = c.solve(pools, ibp, pods)
            out[cpu] = (got, _remote_parts(got, pods))
            c.close()
        except BaseException as e:
            errors[cpu] = e

    try:
        threads = [
            threading.Thread(target=client, args=(cpu,), daemon=True)
            for cpu in profiles
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=WIRE_TIMEOUT)
    finally:
        srv.stop()
    assert not errors, errors
    for cpu in profiles:
        got, parts = out[cpu]
        assert got["used_tpu"], cpu
        assert not got["pod_errors"], (cpu, got["pod_errors"])
        assert parts == refs[cpu], cpu
    # every lane counted as coalesced, and no lane fell back to a solo
    # scan dispatch
    assert fleet.FLEET_SOLVES.value({"mode": "coalesced"}) - c0 == lanes
    # the per-dispatch span accounting (PR 8): every server-side trace of
    # the window carries the shared fleet_dispatch span + window event
    # reporting ALL `lanes` lanes in ONE window; the global fleet dispatch
    # count equals the window's (shared) requeue-round count — one
    # vmapped dispatch per round for the WHOLE window, never per lane
    new_server_traces = [
        t
        for t in tracing.RING.snapshot()
        if t.seq > seq0 and t.side == "server"
    ]
    assert len(new_server_traces) == lanes
    rounds = set()
    for t in new_server_traces:
        names = {s.name for s in t.spans}
        assert "fleet_dispatch" in names and "fleet_window" in names
        win = next(s for s in t.spans if s.name == "fleet_window")
        assert win.attrs.get("mode") == "coalesced"
        assert win.attrs.get("lanes") == lanes
        rounds.add(t.counts.get("dispatches"))
    assert tracing.SOLVE_DISPATCHES.value({"path": "fleet"}) - d0 == max(
        rounds
    )


def test_fleet_core_matches_solo_solve_scan():
    """The shared lane-stack/dispatch core (the code dryrun_multichip
    phase 4 now drives) is bit-identical per lane to solo solve_scan —
    the in-tree twin of the driver's fleet phase."""
    import jax

    import __graft_entry__ as ge
    from karpenter_tpu.solver import tpu_kernel as K

    tb, st, xs, _, _ = ge._small_problem(n_pods=16)
    B = 4
    scale = (1 + (np.arange(B) % 3)).astype(np.int32)
    xs_lanes = [
        xs._replace(prequests=xs.prequests * int(scale[k])) for k in range(B)
    ]
    refs = []
    for k in range(B):
        st_k, kinds_k, slots_k, _, _ = jax.jit(K.solve_scan)(tb, st, xs_lanes[k])
        refs.append(
            (
                int(st_k.n_claims),
                np.asarray(kinds_k).copy(),
                np.asarray(slots_k).copy(),
            )
        )
    st_b, xs_b = fleet.stack_lanes([st] * B, xs_lanes)
    st_b, xs_b = fleet.shard_lanes(st_b, xs_b)
    st_f, kinds_f, slots_f, _, _ = fleet.fleet_dispatch(tb, st_b, xs_b)
    kinds_f = np.asarray(kinds_f)
    slots_f = np.asarray(slots_f)
    n_claims_f = np.asarray(st_f.n_claims)
    for k, (n_ref, kinds_ref, slots_ref) in enumerate(refs):
        assert int(n_claims_f[k]) == n_ref, k
        assert np.array_equal(kinds_f[k], kinds_ref), k
        assert np.array_equal(slots_f[k], slots_ref), k


def test_single_lane_window_falls_back_solo():
    """A window that closes with one lane must charge only the wait:
    the lane runs the existing solo path (no vmapped compile for B=1)
    with identical decisions, counted as mode=solo_window."""
    ref = _solo_parts("100m")
    s0 = fleet.FLEET_SOLVES.value({"mode": "solo_window"})
    coalescer = fleet.FleetCoalescer(window_seconds=0.05, max_lanes=8)
    pools, ibp, pods = _problem("100m")
    topo = Topology(pools, ibp, pods)
    sched = TpuScheduler(pools, ibp, topo, fleet=coalescer)
    r = sched.solve(pods)
    assert not sched.last_used_fleet
    got = sorted(
        (
            tuple(sorted(p.name for p in c.pods)),
            c.template.nodepool_name,
            tuple(sorted(it.name for it in c.instance_type_options)),
            tuple(sorted(c.requests.items())),
        )
        for c in r.new_node_claims
        if c.pods
    )
    assert got == ref
    assert fleet.FLEET_SOLVES.value({"mode": "solo_window"}) - s0 == 1


def test_runs_path_never_enters_the_coalescer():
    """Bulkable (runs-path) solves are ineligible — mid-solve claim
    regrow is host-driven per lane — and must solve identically with a
    coalescer configured, without touching the window."""
    fixtures.reset_rng(9)
    its = construct_instance_types(sizes=[2, 8])
    pools = [fixtures.node_pool(name="default")]
    pods = fixtures.make_generic_pods(8)

    def solve(coalescer):
        fixtures.reset_rng(9)
        its2 = construct_instance_types(sizes=[2, 8])
        pools2 = [fixtures.node_pool(name="default")]
        pods2 = fixtures.make_generic_pods(8)
        topo = Topology(pools2, {"default": its2}, pods2)
        sched = TpuScheduler(pools2, {"default": its2}, topo, fleet=coalescer)
        r = sched.solve(pods2)
        return sched, sorted(
            tuple(sorted(p.name for p in c.pods))
            for c in r.new_node_claims
            if c.pods
        )

    _, ref = solve(None)
    before = {
        m: fleet.FLEET_SOLVES.value({"mode": m})
        for m in ("coalesced", "solo_window", "fallback")
    }
    sched, got = solve(fleet.FleetCoalescer(window_seconds=5.0))
    assert sched.last_used_runs and not sched.last_used_fleet
    assert got == ref
    for m, v in before.items():
        assert fleet.FLEET_SOLVES.value({"mode": m}) == v, m


def test_table_fingerprint_groups_profiles_not_clusters():
    """The grouping key: distinct request profiles (different request
    vectors, same requirement classes) share a table fingerprint — they
    can stack — while a different cluster (an extra instance-type size)
    never does."""

    def fp(cpu: str, sizes=(2, 8)):
        fixtures.reset_rng(5)
        its = construct_instance_types(sizes=list(sizes))
        pools = [fixtures.node_pool(name="default")]
        pods = _spread_pods(6, cpu)
        topo = Topology(pools, {"default": its}, pods)
        sched = TpuScheduler(pools, {"default": its}, topo)
        problem = encode_problem(sched.oracle, pods)
        return (
            epochs.table_fingerprint(problem),
            epochs.problem_fingerprint(problem),
        )

    t1, p1 = fp("100m")
    t2, p2 = fp("300m")
    t3, _ = fp("100m", sizes=(2, 8, 32))
    assert t1 == t2, "distinct request profiles must share a table key"
    assert p1 != p2, "the full problem fingerprint must still differ"
    assert t1 != t3, "a different cluster must never share a table key"


# ---------------------------------------------------------------------------
# epoch-keyed window sharing (ROADMAP item 3 leftover, round 13): one
# DeviceTableCache materialization serves a whole coalesced window


def _fleet_lane_sched(cpu: str, cache, coalescer):
    pools, ibp, pods = _problem(cpu)
    topo = Topology(pools, ibp, pods)
    return (
        TpuScheduler(pools, ibp, topo, table_cache=cache, fleet=coalescer),
        pods,
    )


def _drive_window(profiles, cache, coalescer):
    """Run one coalesced window (len(profiles) concurrent lanes over a
    shared cache) and return the per-window `_tables` materialization
    count."""
    from karpenter_tpu.analysis.ir import count_method_calls

    lanes = [_fleet_lane_sched(cpu, cache, coalescer) for cpu in profiles]
    errors: list[BaseException] = []

    def run(sched, pods) -> None:
        try:
            sched.solve(pods)
        except BaseException as e:  # surfaced below
            errors.append(e)

    with count_method_calls(TpuScheduler, ("_tables",)) as calls:
        threads = [
            threading.Thread(target=run, args=lane, daemon=True)
            for lane in lanes
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=WIRE_TIMEOUT)
    assert not errors, errors
    assert all(s.last_used_fleet for s, _ in lanes), "lanes did not coalesce"
    return calls["_tables"]


def test_coalesced_window_materializes_tables_once():
    """The regression pin on the PR-11 leftover: a coalesced window whose
    lanes carry DISTINCT request profiles (different problem
    fingerprints — the full-entry cache can't serve them) materializes
    the shared `Tables` pytree exactly ONCE. The first window used to
    race both lanes into `_tables` (the old ceiling-2 budget); the
    table-level single-flight (epochs.DeviceTableCache.begin_tables)
    elects one builder, so first window == 1 and a repeat window == 0
    (resident), matching the `fleet[runtime]` budget in
    kernel_budgets.json."""
    cache = epochs.DeviceTableCache()
    coalescer = fleet.FleetCoalescer(window_seconds=10.0, max_lanes=2)
    first = _drive_window(_PROFILES[:2], cache, coalescer)
    assert first == 1, f"first window materialized {first}x (want 1)"
    repeat = _drive_window(_PROFILES[2:4], cache, coalescer)
    assert repeat == 0, f"repeat window materialized {repeat}x (want 0)"


def test_table_cache_single_flight_election():
    """epochs.DeviceTableCache.begin_tables/end_tables mechanics: one
    builder per key, waiters take the published pytree, a failed publish
    re-elects the waiter, and the shared-tables LRU stays bounded."""
    cache = epochs.DeviceTableCache(capacity=2)

    # election: first caller builds, publish makes it resident
    tb, token = cache.begin_tables("k1")
    assert tb is None and token == "k1"
    done: list = []

    def waiter() -> None:
        done.append(cache.begin_tables("k1"))

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    cache.end_tables(token, "TB1")
    t.join(timeout=10)
    assert done == [("TB1", None)], done
    assert cache.get_tables("k1") == "TB1"

    # failed publish (builder died building): the waiter is re-elected
    _tb, token2 = cache.begin_tables("k2")
    relay: list = []

    def failed_waiter() -> None:
        relay.append(cache.begin_tables("k2"))

    t2 = threading.Thread(target=failed_waiter, daemon=True)
    t2.start()
    cache.end_tables(token2, None)  # publish failure: no pytree
    t2.join(timeout=10)
    assert relay == [(None, "k2")], relay  # waiter must now build
    cache.end_tables("k2", "TB2")

    # LRU: capacity 2 evicts the oldest shared-tables entry
    cache.put_tables("k3", "TB3")
    assert cache.get_tables("k1") is None, "k1 should have aged out"
    assert cache.get_tables("k2") == "TB2"
    assert cache.get_tables("k3") == "TB3"


def test_table_cache_dead_builder_key_recovers():
    """A builder that dies WITHOUT reaching end_tables (hard thread
    death) must not wedge its key: the timed-out waiter evicts the stale
    election, so the NEXT caller is elected immediately instead of every
    future solve on that fingerprint stalling the full BUILD_WAIT."""
    cache = epochs.DeviceTableCache()
    cache.BUILD_WAIT_SECONDS = 0.05
    _tb, token = cache.begin_tables("kd")
    assert token == "kd"  # we are the builder — and we never publish
    got = cache.begin_tables("kd")  # waiter: times out on the dead build
    assert got == (None, None), got  # degraded: build our own copy
    # the key has RECOVERED: a fresh caller is elected builder at once
    t0 = time.monotonic()
    _tb2, token2 = cache.begin_tables("kd")
    assert token2 == "kd" and time.monotonic() - t0 < 1.0
    cache.end_tables(token2, "TBD")
    assert cache.get_tables("kd") == "TBD"
