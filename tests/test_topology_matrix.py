"""The topology/scheduler scenario matrix: the port of the reference's
topology_test.go + suite_test.go scenario families as oracle-vs-hybrid
parity tests (VERDICT round-1 item 6).

Every scenario solves twice — sequential oracle and HybridScheduler (TPU
path with oracle fallback) — and asserts the full placement partition is
identical. Scenarios outside the tensor encoding exercise the fallback
path, which must be byte-equal to a pure oracle run by construction; the
matrix asserts that too, so the dispatch is covered, not assumed.

Families (reference file:line in each scenario builder):
- topology spread: maxSkew, minDomains, zone/hostname/capacity-type keys
  (topology_test.go "TopologySpreadConstraints")
- nodeTaintsPolicy / nodeAffinityPolicy matrices (topologynodefilter.go:31)
- multiple TSCs per pod (topology_test.go "combined constraints")
- pod affinity incl. namespaces selectors (topologygroup.go:313)
- pod anti-affinity + inverse anti-affinity (topology.go:54-66, :528)
- interactions: taints, weights, limits, existing nodes, minValues
"""

from __future__ import annotations

import pytest

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import (
    LabelSelector,
    NodeInclusionPolicy,
    NodeSelectorRequirement,
    Operator,
    PodAffinityTerm,
    Taint,
    TaintEffect,
    Toleration,
    TopologySpreadConstraint,
    WhenUnsatisfiable,
)
from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.solver import HybridScheduler, Scheduler, Topology
from karpenter_tpu.solver.nodes import StateNodeView
from karpenter_tpu.testing import fixtures

ZONE = well_known.TOPOLOGY_ZONE_LABEL_KEY
HOSTNAME = well_known.HOSTNAME_LABEL_KEY
CAPACITY = well_known.CAPACITY_TYPE_LABEL_KEY


def run_parity(make, expect_errors=False):
    """Solve via oracle and hybrid; assert identical partitions. Every
    scenario also runs the kernel-odometer consistency catalog (ISSUE 15
    — and since the counters ride every dispatch judged here, the
    partition assertions below double as the odometer-inertness gate
    across the whole matrix)."""
    from karpenter_tpu.testing.fuzz import odometer_violations

    outs = []
    hyb_sched = None
    for cls in (Scheduler, HybridScheduler):
        node_pools, its_by_pool, pods, views, daemons = make()
        topo = Topology(node_pools, its_by_pool, pods, state_node_views=views)
        s = cls(node_pools, its_by_pool, topo, views, daemons)
        outs.append((s.solve(pods), pods))
        if cls is HybridScheduler:
            hyb_sched = s
    assert odometer_violations(hyb_sched) == []
    (orc, orc_pods), (hyb, hyb_pods) = outs
    orc_names = {p.uid: p.name for p in orc_pods}
    hyb_names = {p.uid: p.name for p in hyb_pods}
    assert {orc_names[u] for u in orc.pod_errors} == {
        hyb_names[u] for u in hyb.pod_errors
    }
    if not expect_errors:
        assert not orc.pod_errors, orc.pod_errors

    def parts(r):
        out = [
            ("new", tuple(sorted(p.name for p in c.pods)))
            for c in r.new_node_claims
            if c.pods
        ]
        out += [
            (n.name, tuple(sorted(p.name for p in n.pods)))
            for n in r.existing_nodes
            if n.pods
        ]
        return sorted(out)

    assert parts(orc) == parts(hyb)
    return orc


def problem(pods_fn, pools_fn=None, views_fn=None, seed=42):
    def make():
        fixtures.reset_rng(seed)
        its = construct_instance_types(sizes=[2, 8])
        pools = pools_fn() if pools_fn else [fixtures.node_pool(name="default")]
        return (
            pools,
            {np.name: its for np in pools},
            pods_fn(),
            views_fn() if views_fn else None,
            None,
        )

    return make


def spread_pods(
    n,
    key=ZONE,
    max_skew=1,
    min_domains=None,
    when=WhenUnsatisfiable.DO_NOT_SCHEDULE,
    taints_policy=NodeInclusionPolicy.IGNORE,
    affinity_policy=NodeInclusionPolicy.HONOR,
    labels=None,
    extra_tsc=None,
    **pod_kw,
):
    labels = labels or {"app": "web"}
    tscs = [
        TopologySpreadConstraint(
            max_skew=max_skew,
            topology_key=key,
            when_unsatisfiable=when,
            label_selector=LabelSelector(match_labels=dict(labels)),
            min_domains=min_domains,
            node_taints_policy=taints_policy,
            node_affinity_policy=affinity_policy,
        )
    ] + (extra_tsc or [])
    return [
        fixtures.pod(
            name=f"sp-{i}",
            labels=dict(labels),
            requests={"cpu": "100m", "memory": "128Mi"},
            topology_spread_constraints=[t for t in tscs],
            **pod_kw,
        )
        for i in range(n)
    ]


# ---------------------------------------------------------------------------
# 1. spread matrix: key x maxSkew x pod count


@pytest.mark.parametrize("key", [ZONE, HOSTNAME, CAPACITY])
@pytest.mark.parametrize("max_skew", [1, 2, 3, 4])
@pytest.mark.parametrize("n", [7, 18])
def test_spread_matrix(key, max_skew, n):
    run_parity(problem(lambda: spread_pods(n, key=key, max_skew=max_skew)))


# ---------------------------------------------------------------------------
# 2. minDomains


@pytest.mark.parametrize("min_domains", [1, 2, 3, 4, 5, 6])
@pytest.mark.parametrize("max_skew", [1, 3])
def test_min_domains(min_domains, max_skew):
    # The KWOK universe spans 4 zones (cloudprovider/kwok.py). minDomains
    # above that forces the global minimum to stay 0, capping every zone at
    # maxSkew (topology.go minDomains semantics) — with maxSkew=1 only 4 of
    # the 10 pods can land; the rest must error identically on both paths.
    zones = 4
    expect_errors = min_domains > zones and max_skew * zones < 10
    r = run_parity(
        problem(
            lambda: spread_pods(
                10, key=ZONE, max_skew=max_skew, min_domains=min_domains
            )
        ),
        expect_errors=expect_errors,
    )
    if expect_errors:
        assert r.pod_errors


def test_min_domains_unsatisfiable_zone_subset():
    """minDomains above the available domain count forces the global
    minimum to zero, capping per-domain occupancy at maxSkew."""

    def pools():
        return [
            fixtures.node_pool(
                name="onezone",
                requirements=[
                    NodeSelectorRequirement(ZONE, Operator.IN, ["test-zone-a"])
                ],
            )
        ]

    r = run_parity(
        problem(
            lambda: spread_pods(4, key=ZONE, max_skew=1, min_domains=3),
            pools_fn=pools,
        ),
        expect_errors=True,
    )
    assert r.pod_errors, "maxSkew=1 with minDomains=3 in 1 zone strands pods"


# ---------------------------------------------------------------------------
# 3. node inclusion policies


@pytest.mark.parametrize(
    "taints_policy", [NodeInclusionPolicy.IGNORE, NodeInclusionPolicy.HONOR]
)
@pytest.mark.parametrize(
    "affinity_policy", [NodeInclusionPolicy.HONOR, NodeInclusionPolicy.IGNORE]
)
def test_node_inclusion_policy_matrix(taints_policy, affinity_policy):
    """Honor-taints goes through the oracle (encode gate); parity must hold
    either way."""

    def pools():
        return [
            fixtures.node_pool(name="plain"),
            fixtures.node_pool(
                name="tainted",
                taints=[Taint(key="team", value="infra", effect=TaintEffect.NO_SCHEDULE)],
                weight=10,
            ),
        ]

    def pods():
        out = spread_pods(
            8,
            key=ZONE,
            taints_policy=taints_policy,
            affinity_policy=affinity_policy,
            tolerations=[Toleration(key="team", operator="Exists")],
        )
        return out

    run_parity(problem(pods, pools_fn=pools))


@pytest.mark.parametrize("affinity_policy", [NodeInclusionPolicy.HONOR, NodeInclusionPolicy.IGNORE])
def test_affinity_policy_with_zonal_affinity(affinity_policy):
    def pods():
        return spread_pods(
            6,
            key=ZONE,
            affinity_policy=affinity_policy,
            node_requirements=[
                NodeSelectorRequirement(
                    ZONE, Operator.IN, ["test-zone-a", "test-zone-b"]
                )
            ],
        )

    run_parity(problem(pods))


# ---------------------------------------------------------------------------
# 4. multiple TSCs per pod


@pytest.mark.parametrize("second_key", [HOSTNAME, CAPACITY])
@pytest.mark.parametrize("n", [6, 10, 14])
def test_multi_tsc_pod(second_key, n):
    def pods():
        extra = [
            TopologySpreadConstraint(
                max_skew=2,
                topology_key=second_key,
                when_unsatisfiable=WhenUnsatisfiable.DO_NOT_SCHEDULE,
                label_selector=LabelSelector(match_labels={"app": "web"}),
            )
        ]
        return spread_pods(n, key=ZONE, extra_tsc=extra)

    run_parity(problem(pods))


def test_three_tscs_per_pod():
    def pods():
        extra = [
            TopologySpreadConstraint(
                max_skew=2,
                topology_key=HOSTNAME,
                when_unsatisfiable=WhenUnsatisfiable.DO_NOT_SCHEDULE,
                label_selector=LabelSelector(match_labels={"app": "web"}),
            ),
            TopologySpreadConstraint(
                max_skew=1,
                topology_key=CAPACITY,
                when_unsatisfiable=WhenUnsatisfiable.DO_NOT_SCHEDULE,
                label_selector=LabelSelector(match_labels={"app": "web"}),
            ),
        ]
        return spread_pods(9, key=ZONE, extra_tsc=extra)

    run_parity(problem(pods))


# ---------------------------------------------------------------------------
# 5. pod affinity


def affinity_pods(n, key=ZONE, target_labels=None, self_affinity=True, namespaces=None):
    target_labels = target_labels or {"db": "primary"}
    out = []
    if not self_affinity:
        out += [
            fixtures.pod(
                name=f"target-{i}",
                labels=dict(target_labels),
                requests={"cpu": "100m"},
            )
            for i in range(2)
        ]
    for i in range(n):
        labels = dict(target_labels) if self_affinity else {"app": "web"}
        out.append(
            fixtures.pod(
                name=f"aff-{i}",
                labels=labels,
                requests={"cpu": "100m", "memory": "128Mi"},
                pod_requirements=[
                    PodAffinityTerm(
                        topology_key=key,
                        label_selector=LabelSelector(match_labels=dict(target_labels)),
                        namespaces=list(namespaces or []),
                    )
                ],
            )
        )
    return out


@pytest.mark.parametrize("key", [ZONE, HOSTNAME])
@pytest.mark.parametrize("n", [5, 12, 21])
def test_self_affinity(key, n):
    run_parity(problem(lambda: affinity_pods(n, key=key)))


@pytest.mark.parametrize("key", [ZONE, HOSTNAME])
def test_affinity_to_other_pods(key):
    # zone affinity to non-self targets: a fresh multi-zone claim is not a
    # countable domain (only single-domain nodes count, topologygroup.go),
    # so zone-affine pods strand in a one-shot solve — parity is the
    # contract; hostname domains are always concrete, so those schedule
    r = run_parity(
        problem(lambda: affinity_pods(6, key=key, self_affinity=False)),
        expect_errors=key == ZONE,
    )
    if key == HOSTNAME:
        assert not r.pod_errors


def test_affinity_same_namespace_explicit():
    run_parity(problem(lambda: affinity_pods(5, namespaces=["default"])))


def test_affinity_other_namespace_never_matches():
    """Affinity scoped to a namespace with no pods: the first pod can still
    bootstrap its own domain only under self-affinity; here the targets are
    elsewhere, so the pods are unschedulable."""

    def pods():
        out = affinity_pods(3, self_affinity=True, namespaces=["production"])
        for p in out:
            p.metadata.namespace = "staging"  # selector targets production
        return out

    r = run_parity(problem(pods), expect_errors=True)
    assert r.pod_errors


# ---------------------------------------------------------------------------
# 6. anti-affinity + inverse anti-affinity


@pytest.mark.parametrize("key", [ZONE, HOSTNAME])
@pytest.mark.parametrize("n", [3, 8])
def test_self_anti_affinity(key, n):
    def pods():
        labels = {"app": "nginx"}
        return [
            fixtures.pod(
                name=f"anti-{i}",
                labels=dict(labels),
                requests={"cpu": "100m"},
                pod_anti_requirements=[
                    PodAffinityTerm(
                        topology_key=key,
                        label_selector=LabelSelector(match_labels=dict(labels)),
                    )
                ],
            )
            for i in range(n)
        ]

    # zone anti-affinity records the claim's full allowed-zone set
    # pessimistically (a new claim may land in any of its zones), so pods
    # can strand before all 4 zones hold a pod — exactly the reference's
    # behavior; hostname anti always fits (fresh hostnames are unlimited)
    expect_errors = key == ZONE
    r = run_parity(problem(pods), expect_errors=expect_errors)
    if key == HOSTNAME:
        assert not r.pod_errors


def test_inverse_anti_affinity():
    """A pod with anti-affinity against label L forbids LATER pods with
    label L from its domain (topology.go:528 inverse groups)."""

    def pods():
        guard = fixtures.pod(
            name="guard",
            labels={"role": "guard"},
            requests={"cpu": "100m"},
            pod_anti_requirements=[
                PodAffinityTerm(
                    topology_key=ZONE,
                    label_selector=LabelSelector(match_labels={"role": "worker"}),
                )
            ],
        )
        workers = [
            fixtures.pod(
                name=f"worker-{i}",
                labels={"role": "worker"},
                requests={"cpu": "2500m"},  # won't share the guard's node anyway
            )
            for i in range(3)
        ]
        return [guard] + workers

    # the guard's claim may span every zone, so its inverse group can fence
    # workers out of all domains (pessimistic multi-zone recording) — parity
    # with the oracle is the contract here
    run_parity(problem(pods), expect_errors=True)


def test_anti_affinity_against_existing_pods():
    def pods():
        blockers = [
            fixtures.pod(name=f"blk-{i}", labels={"app": "redis"}, requests={"cpu": "100m"})
            for i in range(2)
        ]
        anti = [
            fixtures.pod(
                name=f"a-{i}",
                labels={"app": "web"},
                requests={"cpu": "100m"},
                pod_anti_requirements=[
                    PodAffinityTerm(
                        topology_key=HOSTNAME,
                        label_selector=LabelSelector(match_labels={"app": "redis"}),
                    )
                ],
            )
            for i in range(3)
        ]
        return blockers + anti

    run_parity(problem(pods))


# ---------------------------------------------------------------------------
# 7. namespace selectors on spread


def test_spread_selector_ignores_other_namespace_pods():
    def pods():
        mine = spread_pods(6, key=ZONE)
        other = [
            fixtures.pod(
                name=f"other-{i}",
                namespace="other",
                labels={"app": "web"},
                requests={"cpu": "100m"},
            )
            for i in range(3)
        ]
        return mine + other

    run_parity(problem(pods))


# ---------------------------------------------------------------------------
# 8. interactions


@pytest.mark.parametrize("max_skew", [1, 2])
def test_spread_with_existing_nodes(max_skew):
    def views():
        return [
            StateNodeView(
                name=f"existing-{z}",
                labels={
                    ZONE: z,
                    HOSTNAME: f"existing-{z}",
                    well_known.INSTANCE_TYPE_LABEL_KEY: "c-2x-amd64-linux",
                    CAPACITY: "on-demand",
                    well_known.OS_LABEL_KEY: "linux",
                    well_known.ARCH_LABEL_KEY: "amd64",
                    well_known.NODEPOOL_LABEL_KEY: "default",
                },
                available={"cpu": 1500, "memory": 3 * 1024**3 * 1000, "pods": 20_000},
                capacity={"cpu": 2000, "memory": 4 * 1024**3 * 1000},
                initialized=True,
            )
            for z in ("test-zone-a", "test-zone-b")
        ]

    run_parity(
        problem(lambda: spread_pods(9, key=ZONE, max_skew=max_skew), views_fn=views)
    )


@pytest.mark.parametrize("weight_order", [(10, 0), (0, 10)])
def test_spread_with_weighted_pools(weight_order):
    def pools():
        w1, w2 = weight_order
        return [
            fixtures.node_pool(
                name="pool-a",
                weight=w1,
                requirements=[
                    NodeSelectorRequirement(
                        ZONE, Operator.IN, ["test-zone-a", "test-zone-b"]
                    )
                ],
            ),
            fixtures.node_pool(
                name="pool-b",
                weight=w2,
                requirements=[
                    NodeSelectorRequirement(
                        ZONE, Operator.IN, ["test-zone-c", "test-zone-d"]
                    )
                ],
            ),
        ]

    run_parity(problem(lambda: spread_pods(8, key=ZONE), pools_fn=pools))


def test_spread_with_limits():
    """Pool limits + spread: subtractMax's pessimistic accounting strands
    pods once the limit can't cover another max-capacity claim — identical
    on both paths."""

    def pools():
        return [fixtures.node_pool(name="default", limits={"cpu": "6"})]

    r = run_parity(
        problem(lambda: spread_pods(10, key=ZONE), pools_fn=pools),
        expect_errors=True,
    )
    assert any("exceed limits" in e for e in r.pod_errors.values())


@pytest.mark.parametrize("op", [Operator.NOT_IN, Operator.DOES_NOT_EXIST])
def test_spread_with_negative_selectors(op):
    def pods():
        vals = ["test-zone-d"] if op == Operator.NOT_IN else []
        return spread_pods(
            6,
            key=ZONE,
            node_requirements=[NodeSelectorRequirement(ZONE, op, vals)]
            if op == Operator.NOT_IN
            else [
                NodeSelectorRequirement(
                    "karpenter.kwok.sh/instance-family", Operator.NOT_IN, ["m"]
                )
            ],
        )

    run_parity(problem(pods))


@pytest.mark.parametrize("gt,lt", [("1", None), (None, "8"), ("1", "8")])
def test_spread_with_integer_bounds(gt, lt):
    def pods():
        reqs = []
        if gt is not None:
            reqs.append(
                NodeSelectorRequirement(
                    "karpenter.kwok.sh/instance-cpu", Operator.GT, [gt]
                )
            )
        if lt is not None:
            reqs.append(
                NodeSelectorRequirement(
                    "karpenter.kwok.sh/instance-cpu", Operator.LT, [lt]
                )
            )
        return spread_pods(6, key=ZONE, node_requirements=reqs)

    run_parity(problem(pods))


def test_spread_min_values_interaction():
    def pools():
        return [
            fixtures.node_pool(
                name="default",
                requirements=[
                    NodeSelectorRequirement(
                        well_known.INSTANCE_TYPE_LABEL_KEY,
                        Operator.EXISTS,
                        [],
                        min_values=2,
                    )
                ],
            )
        ]

    run_parity(problem(lambda: spread_pods(8, key=ZONE), pools_fn=pools))


@pytest.mark.parametrize("n", [4, 10])
def test_spread_and_affinity_combined(n):
    """Zonal spread + zonal self-affinity pulls opposite directions; the
    progress loop resolves it (scheduler.go:380)."""

    def pods():
        out = spread_pods(n, key=HOSTNAME, labels={"app": "combo"})
        for p in out:
            p.pod_affinity = [
                PodAffinityTerm(
                    topology_key=ZONE,
                    label_selector=LabelSelector(match_labels={"app": "combo"}),
                )
            ]
        return out

    run_parity(problem(pods))


@pytest.mark.parametrize("n", [12, 20])
def test_schedule_anyway_relaxes(n):
    """ScheduleAnyway TSC is droppable -> oracle fallback path; parity must
    hold and every pod lands."""
    run_parity(
        problem(
            lambda: spread_pods(
                n, key=ZONE, when=WhenUnsatisfiable.SCHEDULE_ANYWAY
            )
        )
    )


@pytest.mark.parametrize("seed", [1, 7, 13, 29, 71, 97, 113, 131, 151, 173, 191, 211, 229, 251, 271, 283])
def test_randomized_diverse_mix(seed):
    def pods():
        return fixtures.make_diverse_pods(40)

    run_parity(problem(pods, seed=seed))


# ---------------------------------------------------------------------------
# 9. capacity-type spread with a capacity-type selector


@pytest.mark.parametrize("ct", [["spot"], ["on-demand"], ["spot", "on-demand"]])
def test_capacity_type_spread_with_ct_requirement(ct):
    """Spread over capacity-type while the pod itself constrains the same
    key — the tighten and the constraint share a vocab segment."""

    def pods():
        return spread_pods(
            6,
            key=CAPACITY,
            node_requirements=[NodeSelectorRequirement(CAPACITY, Operator.IN, ct)],
        )

    expect_errors = len(ct) == 1  # a 1-value universe strands pods at skew 1
    r = run_parity(problem(pods), expect_errors=expect_errors)
    if not expect_errors:
        assert not r.pod_errors


# ---------------------------------------------------------------------------
# 10. matchLabelKeys (topology.go:434)


def test_match_label_keys_isolates_groups():
    """Two 'deployments' sharing a selector label but differing in the
    matchLabelKeys label spread INDEPENDENTLY: each group gets its own
    counts, so 8 pods (4+4) land 1-per-zone per group, not 2-per-zone
    combined."""

    def pods():
        out = []
        for rev in ("a", "b"):
            for i in range(4):
                out.append(
                    fixtures.pod(
                        name=f"mlk-{rev}-{i}",
                        labels={"app": "web", "rev": rev},
                        requests={"cpu": "100m"},
                        topology_spread_constraints=[
                            TopologySpreadConstraint(
                                max_skew=1,
                                topology_key=ZONE,
                                when_unsatisfiable=WhenUnsatisfiable.DO_NOT_SCHEDULE,
                                label_selector=LabelSelector(
                                    match_labels={"app": "web"}
                                ),
                                match_label_keys=["rev"],
                            )
                        ],
                    )
                )
        return out

    r = run_parity(problem(pods))
    assert not r.pod_errors
    # pin the isolation mechanism: the two revisions must form TWO distinct
    # topology groups whose folded selectors differ by the rev value
    fixtures.reset_rng(42)
    its = construct_instance_types(sizes=[2, 8])
    pool = fixtures.node_pool(name="default")
    pod_list = pods()
    topo = Topology([pool], {"default": its}, pod_list)
    assert len(topo.topology_groups) == 2, (
        "matchLabelKeys must split the spread into per-revision groups"
    )
    selectors = sorted(
        str(
            next(
                e.values
                for e in tg.selector.match_expressions
                if e.key == "rev"
            )
        )
        for tg in topo.topology_groups.values()
    )
    assert selectors == ["['a']", "['b']"]


def test_match_label_keys_missing_label_ignored():
    """A matchLabelKeys entry absent from the pod's labels folds nothing in
    (reference: the `if value, ok` guard) — such pods share ONE group with
    plain spread pods of the same selector."""

    def pods():
        return spread_pods(6, key=ZONE) + [
            fixtures.pod(
                name=f"nolabel-{i}",
                labels={"app": "web"},
                requests={"cpu": "100m"},
                topology_spread_constraints=[
                    TopologySpreadConstraint(
                        max_skew=1,
                        topology_key=ZONE,
                        when_unsatisfiable=WhenUnsatisfiable.DO_NOT_SCHEDULE,
                        label_selector=LabelSelector(match_labels={"app": "web"}),
                        match_label_keys=["no-such-label"],
                    )
                ],
            )
            for i in range(6)
        ]

    r = run_parity(problem(pods))
    assert not r.pod_errors
    # nothing folded -> structurally identical constraint -> ONE group
    fixtures.reset_rng(42)
    its = construct_instance_types(sizes=[2, 8])
    pool = fixtures.node_pool(name="default")
    topo = Topology([pool], {"default": its}, pods())
    assert len(topo.topology_groups) == 1


# ---------------------------------------------------------------------------
# 11. namespaceSelector on affinity terms (topology.go:503)


def test_affinity_namespace_selector_unions_namespaces():
    """An affinity term's namespaceSelector matches namespaces by LABEL and
    unions with the explicit list; pods in selected namespaces count as
    affinity targets across namespaces."""
    from karpenter_tpu.solver.topology import ClusterSource

    def make():
        fixtures.reset_rng(42)
        its = construct_instance_types(sizes=[2, 8])
        pools = [fixtures.node_pool(name="default")]
        target_labels = {"db": "primary"}
        pods = []
        # anchors in two labeled namespaces
        for ns in ("team-a", "team-b"):
            p = fixtures.pod(
                name=f"anchor-{ns}", labels=dict(target_labels),
                requests={"cpu": "100m"},
            )
            p.metadata.namespace = ns
            pods.append(p)
        # followers in a third namespace select tier=backend namespaces
        for i in range(4):
            p = fixtures.pod(
                name=f"follow-{i}", labels={"app": "web"},
                requests={"cpu": "100m"},
                pod_requirements=[
                    PodAffinityTerm(
                        topology_key=HOSTNAME,
                        label_selector=LabelSelector(match_labels=dict(target_labels)),
                        namespace_selector=LabelSelector(
                            match_labels={"tier": "backend"}
                        ),
                    )
                ],
            )
            p.metadata.namespace = "frontend"
            pods.append(p)
        cluster = ClusterSource(
            namespace_labels={
                "team-a": {"tier": "backend"},
                "team-b": {"tier": "backend"},
                "frontend": {"tier": "frontend"},
                "default": {},
            }
        )
        from karpenter_tpu.solver import Topology

        topo = Topology(pools, {"default": its}, pods, cluster=cluster)
        return pools, {"default": its}, pods, topo

    # group structure: the followers' affinity group spans BOTH backend
    # namespaces (selector-resolved), so the anchors are countable targets
    pools, ibp, pods, topo = make()
    aff_groups = [
        tg
        for tg in topo.topology_groups.values()
        if str(tg.type) == "pod affinity"
    ]
    assert len(aff_groups) == 1
    assert aff_groups[0].namespaces == frozenset({"team-a", "team-b"})

    # and both solver paths agree end-to-end
    outs = []
    for cls in (Scheduler, HybridScheduler):
        pools, ibp, pods, topo = make()
        s = cls(pools, ibp, topo)
        outs.append((s.solve(pods), {p.uid: p.name for p in pods}))
    (orc, orc_names), (hyb, hyb_names) = outs
    assert {orc_names[u] for u in orc.pod_errors} == {
        hyb_names[u] for u in hyb.pod_errors
    }


def test_affinity_empty_namespace_selector_matches_implicit_namespaces():
    """An empty namespaceSelector (LabelSelector()) matches ALL namespaces —
    including ones that exist only implicitly because a pod lives there (in
    real Kubernetes the Namespace object always exists; the sim need not
    create one). The anchor below lives in 'team-x' with no Namespace
    object anywhere; the follower's match-all selector must still resolve
    it (topology.go:503 buildNamespaceList)."""
    from karpenter_tpu.api.objects import LabelSelector, PodAffinityTerm

    def pods():
        anchor = fixtures.pod(
            name="anchor", labels={"db": "primary"}, requests={"cpu": "100m"}
        )
        anchor.metadata.namespace = "team-x"
        out = [anchor]
        for i in range(3):
            p = fixtures.pod(
                name=f"follow-{i}",
                labels={"app": "web"},
                requests={"cpu": "100m"},
                pod_requirements=[
                    PodAffinityTerm(
                        topology_key=HOSTNAME,
                        label_selector=LabelSelector(match_labels={"db": "primary"}),
                        namespace_selector=LabelSelector(),  # match-all
                    )
                ],
            )
            p.metadata.namespace = "frontend"
            out.append(p)
        return out

    r = run_parity(problem(pods))
    assert not r.pod_errors
    # group resolution: the followers' group must span the anchor's
    # implicit namespace
    fixtures.reset_rng(42)
    its = construct_instance_types(sizes=[2, 8])
    pool = fixtures.node_pool(name="default")
    topo = Topology([pool], {"default": its}, pods())
    aff = [
        tg for tg in topo.topology_groups.values() if str(tg.type) == "pod affinity"
    ]
    assert len(aff) == 1
    assert "team-x" in aff[0].namespaces


# ---------------------------------------------------------------------------
# 12. restricted domain universes (topology_test.go zone-subset scenarios)


@pytest.mark.parametrize("nzones", [1, 2, 3])
def test_spread_with_zone_subset_pools(nzones):
    """The domain universe is NodePool ∩ instance-type requirements
    (topology.go:105 buildDomainGroups): restricting the pool to a zone
    subset caps the spread's denominator."""
    zones = ["test-zone-a", "test-zone-b", "test-zone-c"][:nzones]

    def pools():
        return [
            fixtures.node_pool(
                name="subset",
                requirements=[NodeSelectorRequirement(ZONE, Operator.IN, zones)],
            )
        ]

    run_parity(problem(lambda: spread_pods(3 * nzones, key=ZONE), pools_fn=pools))


def test_spread_across_disjoint_pools_unions_domains():
    """Two pools covering disjoint zone sets: the group's universe is the
    union, so pods spread across all four zones via different pools."""

    def pools():
        return [
            fixtures.node_pool(
                name="ab",
                requirements=[
                    NodeSelectorRequirement(
                        ZONE, Operator.IN, ["test-zone-a", "test-zone-b"]
                    )
                ],
            ),
            fixtures.node_pool(
                name="cd",
                requirements=[
                    NodeSelectorRequirement(
                        ZONE, Operator.IN, ["test-zone-c", "test-zone-d"]
                    )
                ],
            ),
        ]

    r = run_parity(problem(lambda: spread_pods(8, key=ZONE), pools_fn=pools))
    assert not r.pod_errors


# ---------------------------------------------------------------------------
# 13. selector shapes


def test_spread_selector_matches_no_pods():
    """A spread whose selector matches nobody (including its own pods)
    keeps every domain count at zero — pods land unconstrained."""

    def pods():
        return spread_pods(6, key=ZONE, labels={"app": "web"}) + [
            fixtures.pod(
                name=f"free-{i}",
                labels={"app": "web"},
                requests={"cpu": "100m"},
                topology_spread_constraints=[
                    TopologySpreadConstraint(
                        max_skew=1,
                        topology_key=ZONE,
                        when_unsatisfiable=WhenUnsatisfiable.DO_NOT_SCHEDULE,
                        label_selector=LabelSelector(
                            match_labels={"app": "nobody-has-this"}
                        ),
                    )
                ],
            )
            for i in range(6)
        ]

    r = run_parity(problem(pods))
    assert not r.pod_errors


def test_spread_selector_with_not_in_expression():
    from karpenter_tpu.api.objects import LabelSelectorRequirement

    def pods():
        out = []
        for i in range(8):
            rev = "canary" if i % 4 == 0 else "stable"
            out.append(
                fixtures.pod(
                    name=f"ni-{i}",
                    labels={"app": "web", "rev": rev},
                    requests={"cpu": "100m"},
                    topology_spread_constraints=[
                        TopologySpreadConstraint(
                            max_skew=1,
                            topology_key=ZONE,
                            when_unsatisfiable=WhenUnsatisfiable.DO_NOT_SCHEDULE,
                            label_selector=LabelSelector(
                                match_labels={"app": "web"},
                                match_expressions=[
                                    LabelSelectorRequirement(
                                        key="rev",
                                        operator=Operator.NOT_IN,
                                        values=["canary"],
                                    )
                                ],
                            ),
                        )
                    ],
                )
            )
        return out

    run_parity(problem(pods))


def test_affinity_with_exists_expression():
    from karpenter_tpu.api.objects import LabelSelectorRequirement

    def pods():
        anchor = fixtures.pod(
            name="anchor", labels={"db": "primary"}, requests={"cpu": "100m"}
        )
        followers = [
            fixtures.pod(
                name=f"f-{i}",
                labels={"app": "web"},
                requests={"cpu": "100m"},
                pod_requirements=[
                    PodAffinityTerm(
                        topology_key=HOSTNAME,
                        label_selector=LabelSelector(
                            match_expressions=[
                                LabelSelectorRequirement(
                                    key="db", operator=Operator.EXISTS, values=[]
                                )
                            ]
                        ),
                    )
                ],
            )
            for i in range(4)
        ]
        return [anchor] + followers

    r = run_parity(problem(pods))
    assert not r.pod_errors


# ---------------------------------------------------------------------------
# 14. namespaces on anti-affinity


def test_anti_affinity_scoped_to_namespace_list():
    """Anti-affinity with an explicit namespaces list only fences pods in
    those namespaces; same-labeled pods elsewhere co-locate freely."""

    def pods():
        fenced = []
        for i in range(2):
            p = fixtures.pod(
                name=f"prod-{i}", labels={"app": "redis"}, requests={"cpu": "100m"}
            )
            p.metadata.namespace = "production"
            fenced.append(p)
        guard = fixtures.pod(
            name="guard",
            labels={"app": "web"},
            requests={"cpu": "100m"},
            pod_anti_requirements=[
                PodAffinityTerm(
                    topology_key=HOSTNAME,
                    label_selector=LabelSelector(match_labels={"app": "redis"}),
                    namespaces=["production"],
                )
            ],
        )
        guard.metadata.namespace = "production"
        # same labels in default ns: invisible to the guard's term
        free = [
            fixtures.pod(
                name=f"dev-{i}", labels={"app": "redis"}, requests={"cpu": "100m"}
            )
            for i in range(2)
        ]
        return fenced + [guard] + free

    r = run_parity(problem(pods))
    assert not r.pod_errors


# ---------------------------------------------------------------------------
# 15. combined zonal spread + hostname anti-affinity on one pod


@pytest.mark.parametrize("n", [4, 9])
def test_zonal_spread_plus_hostname_anti(n):
    def pods():
        labels = {"app": "combo2"}
        return [
            fixtures.pod(
                name=f"za-{i}",
                labels=dict(labels),
                requests={"cpu": "100m"},
                topology_spread_constraints=[
                    TopologySpreadConstraint(
                        max_skew=1,
                        topology_key=ZONE,
                        when_unsatisfiable=WhenUnsatisfiable.DO_NOT_SCHEDULE,
                        label_selector=LabelSelector(match_labels=dict(labels)),
                    )
                ],
                pod_anti_requirements=[
                    PodAffinityTerm(
                        topology_key=HOSTNAME,
                        label_selector=LabelSelector(match_labels=dict(labels)),
                    )
                ],
            )
            for i in range(n)
        ]

    run_parity(problem(pods))


# ---------------------------------------------------------------------------
# 16. hostname spread at higher skews


@pytest.mark.parametrize("max_skew,n", [(2, 8), (3, 12)])
def test_hostname_spread_packs_to_skew(max_skew, n):
    r = run_parity(
        problem(lambda: spread_pods(n, key=HOSTNAME, max_skew=max_skew))
    )
    assert not r.pod_errors


# ---------------------------------------------------------------------------
# 17. existing nodes seed domain counts


def test_min_domains_with_existing_zone_nodes():
    """Existing nodes register their zones as live domains; minDomains
    within reach schedules cleanly."""

    def views():
        return [
            StateNodeView(
                name=f"seed-{z}",
                labels={
                    ZONE: z,
                    HOSTNAME: f"seed-{z}",
                    well_known.INSTANCE_TYPE_LABEL_KEY: "c-2x-amd64-linux",
                    CAPACITY: "on-demand",
                    well_known.OS_LABEL_KEY: "linux",
                    well_known.ARCH_LABEL_KEY: "amd64",
                    well_known.NODEPOOL_LABEL_KEY: "default",
                },
                available={"cpu": 1800, "memory": 3 * 1024**3 * 1000, "pods": 20_000},
                capacity={"cpu": 2000, "memory": 4 * 1024**3 * 1000},
                initialized=True,
            )
            for z in ("test-zone-a", "test-zone-b", "test-zone-c")
        ]

    r = run_parity(
        problem(
            lambda: spread_pods(9, key=ZONE, max_skew=1, min_domains=3),
            views_fn=views,
        )
    )
    assert not r.pod_errors
