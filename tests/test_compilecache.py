"""Persistent XLA compilation cache (karpenter_tpu/jaxsetup.py).

The reference's Solve budget is 1 minute (provisioner.go:366); the batched
kernel's cold compile alone can exceed it. These tests drive REAL separate
processes: the first populates the on-disk cache, the second must serve
every program from it (no new cache entries) and finish its Solve inside
the budget — the operational property VERDICT r4 item #2 demands.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SOLVE_SCRIPT = r"""
import json, os, sys, time

t0 = time.monotonic()
from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.solver.topology import Topology
from karpenter_tpu.solver.tpu import TpuScheduler
from karpenter_tpu.testing import fixtures

fixtures.reset_rng(7)
its = construct_instance_types(sizes=[2, 8])
pool = fixtures.node_pool(name="default")
pods = fixtures.make_diverse_pods(48)
topo = Topology([pool], {"default": its}, pods)
sched = TpuScheduler([pool], {"default": its}, topo)
t1 = time.monotonic()
results = sched.solve(pods)
t2 = time.monotonic()
n_sched = sum(len(c.pods) for c in results.new_node_claims)
print(json.dumps({
    "solve_seconds": t2 - t1,
    "scheduled": n_sched,
    "errors": len(results.pod_errors),
}))
"""


def _run_solve(cache_dir: str) -> dict:
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        KARPENTER_COMPILATION_CACHE_DIR=cache_dir,
        PYTHONPATH=REPO,
    )
    out = subprocess.run(
        [sys.executable, "-c", _SOLVE_SCRIPT],
        env=env,
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert out.returncode == 0, out.stderr[-4000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def _cache_files(cache_dir: str) -> set[str]:
    found = set()
    for root, _, files in os.walk(cache_dir):
        for f in files:
            found.add(os.path.join(root, f))
    return found


def test_cold_process_solve_rides_warm_cache(tmp_path):
    cache_dir = str(tmp_path / "xla-cache")
    r1 = _run_solve(cache_dir)
    files1 = _cache_files(cache_dir)
    assert files1, "first process should populate the persistent cache"
    assert r1["scheduled"] > 0

    r2 = _run_solve(cache_dir)
    files2 = _cache_files(cache_dir)
    # every program the solve needs must come FROM the cache: a second
    # process adds no new entries
    assert files2 == files1, (
        f"second process recompiled {len(files2 - files1)} programs"
    )
    assert r2["scheduled"] == r1["scheduled"]
    # the operational contract: a cold process with a warm cache completes
    # its Solve inside the reference's 1-minute budget (provisioner.go:366)
    assert r2["solve_seconds"] < 60.0, r2
    # and far faster than a cold compile — the cache must actually be used
    assert r2["solve_seconds"] < max(10.0, 0.5 * r1["solve_seconds"]), (r1, r2)


def test_second_solve_same_shape_zero_retraces_in_process():
    """The in-process half of the compile-budget story (the subprocess
    test above covers the cross-process persistent cache): a second solve
    of an identical-shape problem must reuse every compiled program — no
    new jaxpr traces, no backend compiles. Counted with the same
    jax.monitoring event counter the graftlint IR tier's retrace rule
    uses (analysis/ir.py trace_events), so the pytest gate and
    `graftlint --ir` cannot drift apart on what "a retrace" means."""
    from karpenter_tpu.analysis.ir import trace_events
    from karpenter_tpu.cloudprovider.kwok import construct_instance_types
    from karpenter_tpu.solver.topology import Topology
    from karpenter_tpu.solver.tpu import TpuScheduler
    from karpenter_tpu.testing import fixtures

    def solve():
        fixtures.reset_rng(11)
        its = construct_instance_types(sizes=[2])
        pool = fixtures.node_pool(name="default")
        pods = fixtures.make_generic_pods(8)
        topo = Topology([pool], {"default": its}, pods)
        sched = TpuScheduler([pool], {"default": its}, topo)
        return sched.solve(pods), pods

    r1, pods1 = solve()
    with trace_events() as ev:
        r2, pods2 = solve()
    assert ev.traces == 0, (
        f"second same-shape solve traced {ev.traces} new programs"
    )
    assert ev.compiles == 0
    # and it is the same solve: identical pod partition

    def parts(r, pods):
        names = {p.uid: p.name for p in pods}
        return sorted(
            tuple(sorted(names[p.uid] for p in c.pods))
            for c in r.new_node_claims
        )

    assert parts(r2, pods2) == parts(r1, pods1)


def test_cache_disabled_by_empty_env(tmp_path, monkeypatch):
    import importlib

    from karpenter_tpu import jaxsetup

    importlib.reload(jaxsetup)
    monkeypatch.setenv("KARPENTER_COMPILATION_CACHE_DIR", "")
    assert jaxsetup.ensure_compilation_cache() is None
    importlib.reload(jaxsetup)  # leave a clean module for other tests
