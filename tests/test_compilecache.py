"""Persistent XLA compilation cache (karpenter_tpu/jaxsetup.py).

The reference's Solve budget is 1 minute (provisioner.go:366); the batched
kernel's cold compile alone can exceed it. These tests drive REAL separate
processes: the first populates the on-disk cache, the second must serve
every program from it (no new cache entries) and finish its Solve inside
the budget — the operational property VERDICT r4 item #2 demands.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SOLVE_SCRIPT = r"""
import json, os, sys, time

t0 = time.monotonic()
# real-backend-compile accounting lives in ONE place — analysis/ir.py
# trace_events (backend_compile_duration events fire on persistent-cache
# hits too; real builds = events minus cache_hits)
from karpenter_tpu.analysis.ir import trace_events
from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.solver.topology import Topology
from karpenter_tpu.solver.tpu import TpuScheduler
from karpenter_tpu.testing import fixtures

fixtures.reset_rng(7)
its = construct_instance_types(sizes=[2, 8])
pool = fixtures.node_pool(name="default")
pods = fixtures.make_diverse_pods(48)
topo = Topology([pool], {"default": its}, pods)
sched = TpuScheduler([pool], {"default": its}, topo)
t1 = time.monotonic()
with trace_events() as ev:
    results = sched.solve(pods)
t2 = time.monotonic()
n_sched = sum(len(c.pods) for c in results.new_node_claims)
print(json.dumps({
    "solve_seconds": t2 - t1,
    "first_solve_from_start_seconds": t2 - t0,
    "scheduled": n_sched,
    "errors": len(results.pod_errors),
    "backend_compiles": ev.backend_compiles,
    "cache_hits": ev.cache_hits,
}))
"""


def _run_solve(cache_dir: str) -> dict:
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        KARPENTER_COMPILATION_CACHE_DIR=cache_dir,
        PYTHONPATH=REPO,
    )
    out = subprocess.run(
        [sys.executable, "-c", _SOLVE_SCRIPT],
        env=env,
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert out.returncode == 0, out.stderr[-4000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


@pytest.fixture(scope="module")
def warm_cache(tmp_path_factory):
    """(cache_dir, cold result, warm result): one cold + one warm
    subprocess run per module; every warm-path assertion rides the same
    pair (subprocess solves are the expensive unit of this module)."""
    cache_dir = str(tmp_path_factory.mktemp("xla-cache"))
    r1 = _run_solve(cache_dir)
    files1 = _cache_files(cache_dir)
    r2 = _run_solve(cache_dir)
    return cache_dir, r1, files1, r2


def _cache_files(cache_dir: str) -> set[str]:
    found = set()
    for root, _, files in os.walk(cache_dir):
        for f in files:
            found.add(os.path.join(root, f))
    return found


@pytest.mark.coldstart
def test_cold_process_solve_rides_warm_cache(warm_cache):
    cache_dir, r1, files1, r2 = warm_cache
    assert files1, "first process should populate the persistent cache"
    assert r1["scheduled"] > 0

    files2 = _cache_files(cache_dir)
    # every program the solve needs must come FROM the cache: a second
    # process adds no new entries (the manifest the AOT prewarm writes is
    # not a cache entry; it lives beside them)
    assert files2 == files1, (
        f"second process recompiled {len(files2 - files1)} programs"
    )
    assert r2["scheduled"] == r1["scheduled"]
    # the operational contract: a cold process with a warm cache completes
    # its Solve inside the reference's 1-minute budget (provisioner.go:366)
    assert r2["solve_seconds"] < 60.0, r2


@pytest.mark.coldstart
def test_fresh_process_warm_cache_zero_backend_compiles(warm_cache):
    """The ISSUE 8 acceptance pin: a fresh process with a warm disk cache
    reaches its first steady-shape solve with ZERO XLA compiles — every
    compile_or_get_cached call is served by deserializing a persisted
    executable (cache_hits == calls). This is the exact property the
    former `0.5 * cold_seconds` timing heuristic was a proxy for — the
    proxy went flaky once tracing (per-process, cache-proof) became the
    dominant warm-path term. The in-process same-bucket half of the
    contract is the `same_bucket_solve_*` ir-retrace budget
    (kernel_budgets.json)."""
    _, r1, _, r2 = warm_cache
    assert r1["backend_compiles"] > 0, (
        "first (cold) process should have actually built programs"
    )
    assert r2["backend_compiles"] == 0, (r1, r2)
    assert r2["cache_hits"] > 0, r2


def test_second_solve_same_shape_zero_retraces_in_process():
    """The in-process half of the compile-budget story (the subprocess
    test above covers the cross-process persistent cache): a second solve
    of an identical-shape problem must reuse every compiled program — no
    new jaxpr traces, no backend compiles. Counted with the same
    jax.monitoring event counter the graftlint IR tier's retrace rule
    uses (analysis/ir.py trace_events), so the pytest gate and
    `graftlint --ir` cannot drift apart on what "a retrace" means."""
    from karpenter_tpu.analysis.ir import trace_events
    from karpenter_tpu.cloudprovider.kwok import construct_instance_types
    from karpenter_tpu.solver.topology import Topology
    from karpenter_tpu.solver.tpu import TpuScheduler
    from karpenter_tpu.testing import fixtures

    def solve():
        fixtures.reset_rng(11)
        its = construct_instance_types(sizes=[2])
        pool = fixtures.node_pool(name="default")
        pods = fixtures.make_generic_pods(8)
        topo = Topology([pool], {"default": its}, pods)
        sched = TpuScheduler([pool], {"default": its}, topo)
        return sched.solve(pods), pods

    r1, pods1 = solve()
    with trace_events() as ev:
        r2, pods2 = solve()
    assert ev.traces == 0, (
        f"second same-shape solve traced {ev.traces} new programs"
    )
    assert ev.compiles == 0
    # and it is the same solve: identical pod partition

    def parts(r, pods):
        names = {p.uid: p.name for p in pods}
        return sorted(
            tuple(sorted(names[p.uid] for p in c.pods))
            for c in r.new_node_claims
        )

    assert parts(r2, pods2) == parts(r1, pods1)


def test_cache_disabled_by_empty_env(tmp_path, monkeypatch):
    import importlib

    from karpenter_tpu import jaxsetup

    importlib.reload(jaxsetup)
    monkeypatch.setenv("KARPENTER_COMPILATION_CACHE_DIR", "")
    assert jaxsetup.ensure_compilation_cache() is None
    importlib.reload(jaxsetup)  # leave a clean module for other tests
