"""Direct round-trip tests for the api/codec wire form (the header side of
the solver service boundary; service.py exercises it end-to-end, these pin
the codec itself — VERDICT r2 flagged it as indirectly-tested only)."""

from __future__ import annotations

import json

from karpenter_tpu.api import codec
from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import (
    LabelSelector,
    LabelSelectorRequirement,
    NodeSelectorRequirement,
    Operator,
    PodAffinityTerm,
    Taint,
    TaintEffect,
    Toleration,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
    WhenUnsatisfiable,
)
from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.testing import fixtures


def roundtrip(obj):
    # through real JSON text, not just dicts — the wire is bytes
    return codec.from_jsonable(json.loads(json.dumps(codec.to_jsonable(obj))))


def test_pod_roundtrip_full_surface():
    p = fixtures.pod(
        name="rt",
        labels={"app": "web", "rev": "a"},
        requests={"cpu": "1500m", "memory": "2Gi"},
        node_selector={well_known.TOPOLOGY_ZONE_LABEL_KEY: "test-zone-a"},
        node_requirements=[
            NodeSelectorRequirement(
                "karpenter.kwok.sh/instance-cpu", Operator.GT, ["2"]
            )
        ],
        node_preferences=[
            NodeSelectorRequirement(well_known.ARCH_LABEL_KEY, Operator.IN, ["amd64"])
        ],
        pod_requirements=[
            PodAffinityTerm(
                topology_key=well_known.HOSTNAME_LABEL_KEY,
                label_selector=LabelSelector(
                    match_labels={"db": "primary"},
                    match_expressions=[
                        LabelSelectorRequirement(
                            key="tier", operator=Operator.NOT_IN, values=["debug"]
                        )
                    ],
                ),
                namespaces=["prod"],
                namespace_selector=LabelSelector(match_labels={"team": "a"}),
            )
        ],
        pod_anti_preferences=[
            WeightedPodAffinityTerm(
                weight=50,
                term=PodAffinityTerm(
                    topology_key=well_known.TOPOLOGY_ZONE_LABEL_KEY,
                    label_selector=LabelSelector(match_labels={"app": "web"}),
                ),
            )
        ],
        topology_spread_constraints=[
            TopologySpreadConstraint(
                max_skew=2,
                topology_key=well_known.TOPOLOGY_ZONE_LABEL_KEY,
                when_unsatisfiable=WhenUnsatisfiable.SCHEDULE_ANYWAY,
                label_selector=LabelSelector(match_labels={"app": "web"}),
                min_domains=2,
                match_label_keys=["rev"],
            )
        ],
        tolerations=[Toleration(key="team", operator="Exists")],
    )
    p.host_ports = [("", "TCP", 8080)]
    p.priority = 1000
    back = roundtrip(p)
    assert back.metadata.name == "rt"
    assert back.requests == p.requests
    assert back.node_selector == p.node_selector
    assert back.node_affinity.required_terms[0].match_expressions[0].operator == Operator.GT
    term = back.pod_affinity[0]
    assert term.namespace_selector.match_labels == {"team": "a"}
    assert term.label_selector.match_expressions[0].operator == Operator.NOT_IN
    tsc = back.topology_spread_constraints[0]
    assert tsc.when_unsatisfiable == WhenUnsatisfiable.SCHEDULE_ANYWAY
    assert tsc.match_label_keys == ["rev"]
    assert back.pod_anti_affinity_preferred[0].weight == 50
    assert back.host_ports == [("", "TCP", 8080)] or back.host_ports == [["", "TCP", 8080]]
    assert back.priority == 1000


def test_nodepool_roundtrip_preserves_disruption_and_limits():
    np_ = fixtures.node_pool(
        name="pool",
        requirements=[
            NodeSelectorRequirement(
                well_known.INSTANCE_TYPE_LABEL_KEY, Operator.EXISTS, [], min_values=3
            )
        ],
        taints=[Taint(key="team", value="infra", effect=TaintEffect.NO_SCHEDULE)],
        startup_taints=[
            Taint(key="not-ready", value="true", effect=TaintEffect.NO_SCHEDULE)
        ],
        limits={"cpu": "100", "memory": "100Gi"},
        weight=7,
        consolidate_after_seconds=30.0,
    )
    back = roundtrip(np_)
    assert back.name == "pool"
    assert back.weight == 7
    assert back.limits == np_.limits
    assert back.template.taints[0].key == "team"
    assert back.template.startup_taints[0].key == "not-ready"
    assert back.template.requirements[0].min_values == 3
    assert back.disruption.consolidate_after_seconds == 30.0
    assert back.disruption.budgets[0].nodes == "10%"


def test_instance_type_roundtrip_preserves_offerings_and_requirements():
    its = construct_instance_types(sizes=[2])
    it = its[0]
    back = roundtrip(it)
    assert back.name == it.name
    assert dict(back.capacity) == dict(it.capacity)
    assert len(back.offerings) == len(it.offerings)
    assert back.offerings[0].price == it.offerings[0].price
    # requirements survive as a Requirements set with identical values
    for key in it.requirements:
        assert back.requirements.get(key).values == it.requirements.get(key).values


def test_unknown_type_rejected():
    import pytest

    with pytest.raises(KeyError):
        codec.from_jsonable({"__type__": "NotRegistered", "fields": {}})


def test_str_enum_fields_decode_to_typed_members():
    """Differential-fuzzer regression (corpus pin seed8505): every api
    enum subclasses str, so the wire carries bare values — which decode
    as plain `str` unless coerced. A bare-str effect COMPARES equal to
    its member (str-enum equality), so scheduling decisions were never
    wrong, but `taint.effect.value` in the oracle's did-not-tolerate
    error path crashed a sidecar solve. The codec must hand back typed
    members; the wire bytes stay byte-identical (pre-fix senders, the
    C++ client)."""
    from karpenter_tpu.api.objects import NodeInclusionPolicy, Pod, PodPhase

    taint = roundtrip(Taint(key="team", value="a", effect=TaintEffect.NO_EXECUTE))
    assert isinstance(taint.effect, TaintEffect)
    assert taint.effect.value == "NoExecute"  # the crash site

    tol = roundtrip(
        Toleration(key="team", operator="Equal", value="a",
                   effect=TaintEffect.NO_SCHEDULE)
    )
    assert isinstance(tol.effect, TaintEffect)
    assert roundtrip(Toleration(key="any")).effect is None  # None survives

    tsc = roundtrip(
        TopologySpreadConstraint(
            max_skew=1,
            topology_key="zone",
            when_unsatisfiable=WhenUnsatisfiable.SCHEDULE_ANYWAY,
        )
    )
    assert isinstance(tsc.when_unsatisfiable, WhenUnsatisfiable)
    assert isinstance(tsc.node_affinity_policy, NodeInclusionPolicy)

    pod = roundtrip(fixtures.pod(name="p"))
    assert isinstance(pod.phase, PodPhase)

    nsr = roundtrip(NodeSelectorRequirement("k", Operator.GT, ["4"]))
    assert isinstance(nsr.operator, Operator)

    # the wire form is unchanged: bare enum VALUES, no __enum__ envelope
    encoded = codec.to_jsonable(taint)
    assert encoded["effect"] == "NoExecute"
