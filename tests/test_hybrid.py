"""HybridScheduler dispatch: TPU path for supported problems, transparent
oracle fallback on UnsupportedBySolver — callers never see the exception
(reference contract: Scheduler.Solve never fails on feature grounds,
scheduler.go:377)."""

from __future__ import annotations

import pytest

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.solver import HybridScheduler, Scheduler, Topology
from karpenter_tpu.solver.tpu_problem import UnsupportedBySolver
from karpenter_tpu.testing import fixtures


def _universe():
    return construct_instance_types(sizes=[2, 8, 32])


def _problem(pods):
    its = _universe()
    np_ = fixtures.node_pool(name="default")
    topo = Topology([np_], {"default": its}, pods)
    return [np_], {"default": its}, topo


def test_supported_problem_uses_tpu_and_matches_oracle():
    fixtures.reset_rng(7)
    pods = fixtures.make_diverse_pods(20)
    h = HybridScheduler(*_problem(pods))
    results = h.solve(pods)
    assert h.used_tpu is True
    assert h.fallback_reason is None

    fixtures.reset_rng(7)
    pods2 = fixtures.make_diverse_pods(20)
    oracle = Scheduler(*_problem(pods2))
    want = oracle.solve(pods2)
    # claim lists differ in order (the oracle re-sorts by pod count during
    # solve); the packing itself must match as a multiset
    assert sorted(results.node_pod_counts()) == sorted(want.node_pod_counts())
    assert set(results.pod_errors) == set(want.pod_errors)


def test_unsupported_problem_falls_back_without_raising():
    fixtures.reset_rng(7)
    # volume-claim pods stay outside the tensor encoding
    # (tpu_problem._check_pod_supported — host ports ride the kernel since
    # round 5); a batch of ONLY unsupported pods falls back wholesale
    # without raising
    from karpenter_tpu.solver.oracle import SchedulerOptions

    pods = fixtures.make_generic_pods(8)
    for i, p in enumerate(pods):
        p.volume_claims = [f"pvc-{i}"]
    # tpu_min_pods=0 so the UNSUPPORTED fallback (not size routing) is
    # what this test exercises
    h = HybridScheduler(*_problem(pods), options=SchedulerOptions(tpu_min_pods=0))
    results = h.solve(pods)  # must not raise
    assert h.used_tpu is False
    assert h.fallback_reason is not None
    assert "volume claims" in h.fallback_reason
    assert not results.pod_errors

    # and the fallback result equals a pure-oracle run of the same problem
    fixtures.reset_rng(7)
    pods2 = fixtures.make_generic_pods(8)
    for i, p in enumerate(pods2):
        p.volume_claims = [f"pvc-{i}"]
    want = Scheduler(*_problem(pods2)).solve(pods2)
    assert results.node_pod_counts() == want.node_pod_counts()


def test_preference_pods_ride_the_kernel():
    """Round 4: the relaxation ladder lives in the kernel step
    (tpu_kernel._step_relax); preference pods no longer fall back, and the
    outcome matches the oracle's relax-until-schedulable semantics."""
    fixtures.reset_rng(7)
    pods = fixtures.make_preference_pods(8)
    h = HybridScheduler(*_problem(pods))
    results = h.solve(pods)
    assert h.used_tpu is True, h.fallback_reason
    assert h.fallback_reason is None
    assert not results.pod_errors

    fixtures.reset_rng(7)
    pods2 = fixtures.make_preference_pods(8)
    want = Scheduler(*_problem(pods2)).solve(pods2)
    assert sorted(results.node_pod_counts()) == sorted(want.node_pod_counts())


def test_tpu_path_raises_only_inside_dispatch():
    """Direct TpuScheduler use still raises (bench harness relies on it);
    the hybrid wrapper is what absorbs it."""
    from karpenter_tpu.solver.tpu import TpuScheduler

    fixtures.reset_rng(7)
    pods = fixtures.make_generic_pods(4)
    pods[1].node_selector = {well_known.HOSTNAME_LABEL_KEY: "some-node"}
    t = TpuScheduler(*_problem(pods))
    with pytest.raises(UnsupportedBySolver):
        t.solve(pods)


def test_force_oracle():
    fixtures.reset_rng(7)
    pods = fixtures.make_diverse_pods(10)
    h = HybridScheduler(*_problem(pods), force_oracle=True)
    results = h.solve(pods)
    assert h.used_tpu is False
    assert h.tpu is None
    assert sum(results.node_pod_counts()) + len(results.pod_errors) == len(pods)


def test_host_ports_ride_kernel():
    """Round 5 (VERDICT #6): host-port pods ride the kernel — the distinct
    (ip, proto, port) triples are bit positions, conflict is a precomputed
    relation mask, per-slot usage is a State bitmask (hostportusage.go:35).
    Conflicting pods fork claims exactly as the oracle forks them,
    including the wildcard-IP rule."""
    from karpenter_tpu.solver.oracle import SchedulerOptions

    def build(force):
        fixtures.reset_rng(7)
        pods = fixtures.make_generic_pods(6)
        # three pods on the same (proto, port): concrete ip, wildcard,
        # and a DIFFERENT concrete ip (wildcard conflicts both; the two
        # concrete ips do not conflict each other)
        pods[0].host_ports = [("10.0.0.1", "TCP", 8080)]
        pods[1].host_ports = [("0.0.0.0", "TCP", 8080)]
        pods[2].host_ports = [("10.0.0.2", "TCP", 8080)]
        # same port, different protocol: no conflict with any of the above
        pods[3].host_ports = [("0.0.0.0", "UDP", 8080)]
        cls = HybridScheduler if not force else Scheduler
        kw = {"force_oracle": False} if not force else {}
        opts = SchedulerOptions(tpu_min_pods=0)
        s = cls(*_problem(pods), options=opts, **kw)
        return s, s.solve(pods), pods

    h, rt, pods = build(False)
    assert h.used_tpu is True, h.fallback_reason
    assert not rt.pod_errors
    _, ro, _ = build(True)

    def snap(r):
        return sorted(
            tuple(sorted(p.name for p in c.pods)) for c in r.new_node_claims
        )

    assert snap(rt) == snap(ro)
    # the wildcard pod shares a claim with NO other 8080/TCP pod
    for c in rt.new_node_claims:
        names = {p.name for p in c.pods}
        if "generic-1" in names:
            assert not ({"generic-0", "generic-2"} & names)


def test_mixed_batch_partitions_per_pod():
    """The round-2 fallback cliff: ONE relaxable pod in a supported batch
    must not drag everything to the oracle. The kernel packs the bulk; the
    oracle continues on the decoded state for the leftovers."""
    from karpenter_tpu.api.objects import (
        LabelSelector,
        TopologySpreadConstraint,
        WhenUnsatisfiable,
    )
    from karpenter_tpu.api import labels as well_known
    from karpenter_tpu.cloudprovider.kwok import construct_instance_types
    from karpenter_tpu.solver import HybridScheduler, Topology
    from karpenter_tpu.testing import fixtures

    fixtures.reset_rng(3)
    its = construct_instance_types(sizes=[2, 8])
    pool = fixtures.node_pool(name="default")
    pods = fixtures.make_diverse_pods(40)
    relaxable = fixtures.pod(
        name="anyway",
        labels={"app": "web"},
        requests={"cpu": "100m"},
        topology_spread_constraints=[
            TopologySpreadConstraint(
                max_skew=1,
                topology_key=well_known.TOPOLOGY_ZONE_LABEL_KEY,
                when_unsatisfiable=WhenUnsatisfiable.SCHEDULE_ANYWAY,
                label_selector=LabelSelector(match_labels={"app": "web"}),
            )
        ],
    )
    # a volume-claims pod still partitions; the former relaxable and
    # host-port partition cases now ride the kernel
    ported = fixtures.pod(name="ported", requests={"cpu": "100m"})
    ported.volume_claims = ["pvc-ported"]
    pods.append(relaxable)
    pods.append(ported)
    topo = Topology([pool], {"default": its}, pods)
    s = HybridScheduler([pool], {"default": its}, topo)
    r = s.solve(pods)
    assert s.used_tpu is True, s.fallback_reason
    assert s.fallback_reason and "continued on the oracle" in s.fallback_reason
    assert "volume claims" in s.fallback_reason
    assert not r.pod_errors, r.pod_errors
    placed = {p.name for c in r.new_node_claims for p in c.pods}
    assert "anyway" in placed and "ported" in placed
    assert len(placed) == len(pods)


def test_continuation_sees_claim_hostname_counts_with_padded_existing_slots():
    """Regression: existing-node slots are pow2-padded (tpu_problem.py), so
    claim slots live at offset num_existing (the PADDED count). The decode
    sync must read each claim's hostname counts from the padded offset —
    reading from len(existing_nodes) lands on inert padded columns and
    silently drops every claim's counts, letting an oracle-continuation pod
    violate hostname anti-affinity the kernel already recorded. (The hybrid
    partition may legally differ from a pure-oracle run — unsupported pods
    interleave differently in FFD order — so the contract asserted here is
    VALIDITY of the combined placement, not partition equality.)"""
    from karpenter_tpu.api.objects import LabelSelector, PodAffinityTerm
    from karpenter_tpu.solver.nodes import StateNodeView

    HOSTNAME = well_known.HOSTNAME_LABEL_KEY

    fixtures.reset_rng(11)
    its = _universe()
    pool = fixtures.node_pool(name="default")
    views = [
        StateNodeView(
            name=f"existing-{i}",
            labels={
                well_known.TOPOLOGY_ZONE_LABEL_KEY: "test-zone-a",
                HOSTNAME: f"existing-{i}",
                well_known.INSTANCE_TYPE_LABEL_KEY: "c-2x-amd64-linux",
                well_known.CAPACITY_TYPE_LABEL_KEY: "on-demand",
                well_known.OS_LABEL_KEY: "linux",
                well_known.ARCH_LABEL_KEY: "amd64",
                well_known.NODEPOOL_LABEL_KEY: "default",
            },
            available={"cpu": 1500, "memory": 3 * 1024**3 * 1000, "pods": 20_000},
            capacity={"cpu": 2000, "memory": 4 * 1024**3 * 1000},
            initialized=True,
        )
        for i in range(2)  # 2 real nodes -> padded to 8 slots
    ]
    anti = [
        PodAffinityTerm(
            topology_key=HOSTNAME,
            label_selector=LabelSelector(match_labels={"app": "redis"}),
        )
    ]
    pods = [
        fixtures.pod(
            name=f"redis-{i}",
            labels={"app": "redis"},
            requests={"cpu": "100m"},
            pod_anti_requirements=[t for t in anti],
        )
        for i in range(3)  # 2 land on existing nodes, 1 opens a claim
    ]
    # the continuation pod: host ports force the oracle path; its anti
    # term must SEE the kernel-recorded redis pod on the new claim
    chaser = fixtures.pod(
        name="chaser",
        labels={"app": "web"},
        requests={"cpu": "100m"},
        pod_anti_requirements=[t for t in anti],
    )
    chaser.host_ports = [("", "TCP", 9090)]
    pods.append(chaser)
    topo = Topology([pool], {"default": its}, pods, state_node_views=views)
    h = HybridScheduler([pool], {"default": its}, topo, views)
    r = h.solve(pods)
    assert h.used_tpu is True, h.fallback_reason
    assert not r.pod_errors, r.pod_errors

    # validity: no hostname holds both the chaser and a redis pod, and the
    # redis pods are all on distinct hostnames
    groups = [
        {p.name for p in c.pods} for c in r.new_node_claims if c.pods
    ] + [{p.name for p in n.pods} for n in r.existing_nodes if n.pods]
    for g in groups:
        redis = {n for n in g if n.startswith("redis")}
        assert len(redis) <= 1, groups
        if redis:
            assert "chaser" not in g, groups

    # and the synced Topology must carry every claim's hostname counts:
    # the anti group (inverse, counting app=redis pods per hostname) must
    # show exactly 1 for each hostname holding a redis pod — including the
    # new claims, whose slots sit beyond the pow2 padding
    redis_hosts = {}
    for c in r.new_node_claims:
        if any(p.name.startswith("redis") for p in c.pods):
            redis_hosts[c.hostname] = sum(
                1 for p in c.pods if p.name.startswith("redis")
            )
    assert redis_hosts, "expected at least one redis pod on a new claim"
    hostname_groups = [
        tg
        for tg in list(topo.topology_groups.values())
        + list(topo.inverse_topology_groups.values())
        if tg.key == HOSTNAME
    ]
    assert hostname_groups
    for hn, want_count in redis_hosts.items():
        assert any(
            tg.domains.get(hn) == want_count for tg in hostname_groups
        ), (hn, want_count, [dict(tg.domains) for tg in hostname_groups])


def test_small_topology_free_batch_routes_to_oracle():
    """Size-based routing: below the measured crossover a topology-free
    batch runs on the oracle (a 500-pod production tick must never be
    slowed down by the device launch floor). Topology-bearing batches of
    the same size still ride the kernel — the oracle's domain tracking is
    the slow side there."""
    fixtures.reset_rng(7)
    pods = fixtures.make_generic_pods(12)  # no topology constraints
    h = HybridScheduler(*_problem(pods))
    r = h.solve(pods)
    assert h.used_tpu is False
    assert "crossover" in (h.fallback_reason or "")
    assert not r.pod_errors

    # same size, but with a topology spread -> kernel path
    fixtures.reset_rng(7)
    pods = fixtures.make_topology_spread_pods(12, well_known.TOPOLOGY_ZONE_LABEL_KEY)
    h = HybridScheduler(*_problem(pods))
    r = h.solve(pods)
    assert h.used_tpu is True, h.fallback_reason
    assert not r.pod_errors

    # tpu_min_pods=0 disables routing entirely
    from karpenter_tpu.solver.oracle import SchedulerOptions

    fixtures.reset_rng(7)
    pods = fixtures.make_generic_pods(12)
    pools, ibp, topo = _problem(pods)
    h = HybridScheduler(pools, ibp, topo, options=SchedulerOptions(tpu_min_pods=0))
    r = h.solve(pods)
    assert h.used_tpu is True, h.fallback_reason
    assert not r.pod_errors


def test_partition_with_nodepool_limits_matches_oracle():
    """Round-4: nodepool-limit spend syncs back from the device after
    decode (tpu.py _decode -> oracle.remaining_resources), so the hybrid
    can partition a mixed batch even with limits set — the continuation
    must not double-spend the pool's budget. Result must equal the pure
    oracle solve of the same problem."""
    from karpenter_tpu.solver.oracle import SchedulerOptions

    def build():
        fixtures.reset_rng(13)
        its = _universe()
        pool = fixtures.node_pool(name="default", limits={"cpu": "24"})
        pods = fixtures.make_generic_pods(12)
        # one volume-claims pod forces the partitioned continuation
        hp = fixtures.pod(name="hp", requests={"cpu": "100m"})
        hp.volume_claims = ["pvc-hp"]
        pods.append(hp)
        topo = Topology([pool], {"default": its}, pods)
        return pool, its, topo, pods

    outs = []
    for force in (True, False):
        pool, its, topo, pods = build()
        h = HybridScheduler(
            [pool], {"default": its}, topo,
            options=SchedulerOptions(tpu_min_pods=0),
            force_oracle=force,
        )
        outs.append((h.solve(pods), pods, h))
    (orc, orc_pods, _), (hyb, hyb_pods, hs) = outs
    assert hs.used_tpu is True, hs.fallback_reason
    assert "continued on the oracle" in (hs.fallback_reason or "")
    orc_names = {p.uid: p.name for p in orc_pods}
    hyb_names = {p.uid: p.name for p in hyb_pods}
    assert {orc_names[u] for u in orc.pod_errors} == {
        hyb_names[u] for u in hyb.pod_errors
    }
    parts = lambda r: sorted(
        tuple(sorted(p.name for p in c.pods)) for c in r.new_node_claims if c.pods
    )
    assert parts(orc) == parts(hyb)


def test_reserved_capacity_gate_only_fires_with_reservations():
    """The ReservedCapacity feature gate alone doesn't change semantics —
    only actual reservation-id offerings do (reservationmanager.go:28).
    Flag on + no reservations rides the kernel and matches the oracle;
    reservation offerings present still falls back."""
    from karpenter_tpu.api import labels as wk
    from karpenter_tpu.cloudprovider.types import Offering
    from karpenter_tpu.scheduling import Requirement, Requirements
    from karpenter_tpu.api.objects import Operator as Op
    from karpenter_tpu.solver.oracle import SchedulerOptions

    fixtures.reset_rng(7)
    pods = fixtures.make_diverse_pods(12)
    opts = SchedulerOptions(reserved_capacity_enabled=True, tpu_min_pods=0)
    h = HybridScheduler(*_problem(pods), options=opts)
    r = h.solve(pods)
    assert h.used_tpu is True, h.fallback_reason
    assert not r.pod_errors

    fixtures.reset_rng(7)
    pods2 = fixtures.make_diverse_pods(12)
    want = Scheduler(*_problem(pods2)).solve(pods2)
    assert sorted(r.node_pod_counts()) == sorted(want.node_pod_counts())

    # round 5: reservation-id offerings RIDE the kernel in non-strict mode
    # (the whole-problem gate at tpu_problem.py:295 is gone); only strict
    # mode still falls back — see test_reserved_offerings_ride_kernel


def _reserved_universe(capacity=4):
    from karpenter_tpu.api import labels as wk
    from karpenter_tpu.api.objects import Operator as Op
    from karpenter_tpu.cloudprovider.types import Offering
    from karpenter_tpu.scheduling import Requirement, Requirements

    its = _universe()
    it0 = its[0]
    it0.offerings.append(
        Offering(
            requirements=Requirements(
                [
                    Requirement(wk.TOPOLOGY_ZONE_LABEL_KEY, Op.IN, ["test-zone-a"]),
                    Requirement(wk.CAPACITY_TYPE_LABEL_KEY, Op.IN, ["reserved"]),
                    Requirement(wk.RESERVATION_ID_LABEL_KEY, Op.IN, ["res-1"]),
                ]
            ),
            price=0.01,
            available=True,
            reservation_capacity=capacity,
        )
    )
    return its


def test_reserved_offerings_ride_kernel():
    """Round 5 (VERDICT #5): non-strict reserved capacity runs ON the
    kernel — used_tpu=True — with the held-reservation sets, the manager's
    consumed capacity, and finalize()'s reservation-id requirements all
    bit-identical to the oracle (reservationmanager.go:57-98)."""
    from karpenter_tpu.solver.oracle import SchedulerOptions

    def solve(cls, force=None):
        fixtures.reset_rng(7)
        pods = fixtures.make_diverse_pods(12)
        its = _reserved_universe()
        np_ = fixtures.node_pool(name="default")
        topo = Topology([np_], {"default": its}, pods)
        opts = SchedulerOptions(reserved_capacity_enabled=True, tpu_min_pods=0)
        kw = {} if force is None else {"force_oracle": force}
        s = cls([np_], {"default": its}, topo, options=opts, **kw)
        return s, s.solve(pods)

    h, r = solve(HybridScheduler, force=False)
    assert h.used_tpu is True, h.fallback_reason
    o, want = solve(Scheduler)

    def snap(res, sched):
        out = []
        for c in sorted(
            res.new_node_claims, key=lambda c: sorted(p.name for p in c.pods)
        ):
            c.finalize()
            from karpenter_tpu.api import labels as wk

            rid_req = (
                tuple(sorted(c.requirements.get(wk.RESERVATION_ID_LABEL_KEY).values))
                if c.requirements.has(wk.RESERVATION_ID_LABEL_KEY)
                else ()
            )
            out.append(
                (
                    tuple(sorted(p.name for p in c.pods)),
                    tuple(sorted(o.reservation_id() for o in c.reserved_offerings)),
                    rid_req,
                )
            )
        return out, dict(sched.oracle.reservation_manager.capacity) if hasattr(
            sched, "oracle"
        ) else dict(sched.reservation_manager.capacity)

    got = snap(r, h)
    exp = snap(want, o)
    assert got == exp


def test_reserved_capacity_exhaustion_matches_oracle():
    """More claims than reservation capacity: the device capacity vector
    must run out at exactly the same commit the oracle's does."""
    from karpenter_tpu.solver.oracle import SchedulerOptions

    def solve(cls, force=None):
        fixtures.reset_rng(3)
        # every pod too big to share: one claim per pod, 6 claims vs cap 2
        pods = [
            fixtures.pod(name=f"big-{i}", requests={"cpu": "28"})
            for i in range(6)
        ]
        its = _reserved_universe(capacity=2)
        np_ = fixtures.node_pool(name="default")
        topo = Topology([np_], {"default": its}, pods)
        opts = SchedulerOptions(reserved_capacity_enabled=True, tpu_min_pods=0)
        kw = {} if force is None else {"force_oracle": force}
        s = cls([np_], {"default": its}, topo, options=opts, **kw)
        return s, s.solve(pods)

    h, r = solve(HybridScheduler, force=False)
    assert h.used_tpu is True, h.fallback_reason
    o, want = solve(Scheduler)
    got_held = sorted(
        tuple(sorted(x.reservation_id() for x in c.reserved_offerings))
        for c in r.new_node_claims
    )
    exp_held = sorted(
        tuple(sorted(x.reservation_id() for x in c.reserved_offerings))
        for c in want.new_node_claims
    )
    assert got_held == exp_held
    assert (
        h.oracle.reservation_manager.capacity
        == o.reservation_manager.capacity
    )


def test_strict_reserved_mode_still_falls_back():
    """Strict mode's per-candidate reservation errors stay on the oracle."""
    from karpenter_tpu.solver.oracle import SchedulerOptions

    fixtures.reset_rng(7)
    pods = fixtures.make_diverse_pods(6)
    its = _reserved_universe()
    np_ = fixtures.node_pool(name="default")
    topo = Topology([np_], {"default": its}, pods)
    opts = SchedulerOptions(
        reserved_capacity_enabled=True,
        reserved_offering_strict=True,
        tpu_min_pods=0,
    )
    h = HybridScheduler([np_], {"default": its}, topo, options=opts)
    h.solve(pods)
    assert h.used_tpu is False
    assert "strict" in (h.fallback_reason or "")
