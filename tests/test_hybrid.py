"""HybridScheduler dispatch: TPU path for supported problems, transparent
oracle fallback on UnsupportedBySolver — callers never see the exception
(reference contract: Scheduler.Solve never fails on feature grounds,
scheduler.go:377)."""

from __future__ import annotations

import pytest

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.solver import HybridScheduler, Scheduler, Topology
from karpenter_tpu.solver.tpu_problem import UnsupportedBySolver
from karpenter_tpu.testing import fixtures


def _universe():
    return construct_instance_types(sizes=[2, 8, 32])


def _problem(pods):
    its = _universe()
    np_ = fixtures.node_pool(name="default")
    topo = Topology([np_], {"default": its}, pods)
    return [np_], {"default": its}, topo


def test_supported_problem_uses_tpu_and_matches_oracle():
    fixtures.reset_rng(7)
    pods = fixtures.make_diverse_pods(20)
    h = HybridScheduler(*_problem(pods))
    results = h.solve(pods)
    assert h.used_tpu is True
    assert h.fallback_reason is None

    fixtures.reset_rng(7)
    pods2 = fixtures.make_diverse_pods(20)
    oracle = Scheduler(*_problem(pods2))
    want = oracle.solve(pods2)
    # claim lists differ in order (the oracle re-sorts by pod count during
    # solve); the packing itself must match as a multiset
    assert sorted(results.node_pod_counts()) == sorted(want.node_pod_counts())
    assert set(results.pod_errors) == set(want.pod_errors)


def test_unsupported_problem_falls_back_without_raising():
    fixtures.reset_rng(7)
    # preferred node affinity is on the relaxation ladder -> unsupported by
    # the tensor encoding (tpu_problem._check_pod_supported)
    pods = fixtures.make_preference_pods(8)
    h = HybridScheduler(*_problem(pods))
    results = h.solve(pods)  # must not raise
    assert h.used_tpu is False
    assert h.fallback_reason is not None
    assert "relaxable" in h.fallback_reason
    assert not results.pod_errors

    # and the fallback result equals a pure-oracle run of the same problem
    fixtures.reset_rng(7)
    pods2 = fixtures.make_preference_pods(8)
    want = Scheduler(*_problem(pods2)).solve(pods2)
    assert results.node_pod_counts() == want.node_pod_counts()


def test_tpu_path_raises_only_inside_dispatch():
    """Direct TpuScheduler use still raises (bench harness relies on it);
    the hybrid wrapper is what absorbs it."""
    from karpenter_tpu.solver.tpu import TpuScheduler

    fixtures.reset_rng(7)
    pods = fixtures.make_preference_pods(4)
    t = TpuScheduler(*_problem(pods))
    with pytest.raises(UnsupportedBySolver):
        t.solve(pods)


def test_force_oracle():
    fixtures.reset_rng(7)
    pods = fixtures.make_diverse_pods(10)
    h = HybridScheduler(*_problem(pods), force_oracle=True)
    results = h.solve(pods)
    assert h.used_tpu is False
    assert h.tpu is None
    assert sum(results.node_pod_counts()) + len(results.pod_errors) == len(pods)


def test_host_ports_partition_to_oracle():
    """A host-ports pod rides the oracle continuation while the rest of the
    batch stays on the kernel (per-pod partitioning; whole-batch fallback
    was the round-2 cliff)."""
    fixtures.reset_rng(7)
    pods = fixtures.make_generic_pods(4)
    pods[2].host_ports = [("", "TCP", 8080)]
    h = HybridScheduler(*_problem(pods))
    results = h.solve(pods)
    assert h.used_tpu is True
    assert "host ports" in h.fallback_reason
    assert "continued on the oracle" in h.fallback_reason
    assert not results.pod_errors
    placed = {p.name for c in results.new_node_claims for p in c.pods}
    assert len(placed) == len(pods)


def test_mixed_batch_partitions_per_pod():
    """The round-2 fallback cliff: ONE relaxable pod in a supported batch
    must not drag everything to the oracle. The kernel packs the bulk; the
    oracle continues on the decoded state for the leftovers."""
    from karpenter_tpu.api.objects import (
        LabelSelector,
        TopologySpreadConstraint,
        WhenUnsatisfiable,
    )
    from karpenter_tpu.api import labels as well_known
    from karpenter_tpu.cloudprovider.kwok import construct_instance_types
    from karpenter_tpu.solver import HybridScheduler, Topology
    from karpenter_tpu.testing import fixtures

    fixtures.reset_rng(3)
    its = construct_instance_types(sizes=[2, 8])
    pool = fixtures.node_pool(name="default")
    pods = fixtures.make_diverse_pods(40)
    relaxable = fixtures.pod(
        name="anyway",
        labels={"app": "web"},
        requests={"cpu": "100m"},
        topology_spread_constraints=[
            TopologySpreadConstraint(
                max_skew=1,
                topology_key=well_known.TOPOLOGY_ZONE_LABEL_KEY,
                when_unsatisfiable=WhenUnsatisfiable.SCHEDULE_ANYWAY,
                label_selector=LabelSelector(match_labels={"app": "web"}),
            )
        ],
    )
    pods.append(relaxable)
    topo = Topology([pool], {"default": its}, pods)
    s = HybridScheduler([pool], {"default": its}, topo)
    r = s.solve(pods)
    assert s.used_tpu is True, s.fallback_reason
    assert s.fallback_reason and "continued on the oracle" in s.fallback_reason
    assert not r.pod_errors, r.pod_errors
    placed = {p.name for c in r.new_node_claims for p in c.pods}
    assert "anyway" in placed
    assert len(placed) == len(pods)
