from karpenter_tpu.api import labels as wk
from karpenter_tpu.api.objects import NodeClaim, NodeSelectorRequirement, ObjectMeta, Operator
from karpenter_tpu.cloudprovider import fake, kwok
from karpenter_tpu.cloudprovider.types import InstanceTypes, NodeClaimNotFoundError
from karpenter_tpu.scheduling import Requirement, Requirements
from karpenter_tpu.utils import resources as res
from karpenter_tpu.utils.quantity import parse as q

import pytest


def test_fake_instance_types_shape():
    its = fake.instance_types(400)
    assert len(its) == 400
    it0 = its[0]
    assert it0.capacity[res.CPU] == q("1")
    assert it0.capacity[res.MEMORY] == q("2Gi")
    assert it0.capacity[res.PODS] == q("10")
    assert len(it0.offerings) == 5
    # allocatable subtracts kube-reserved overhead
    assert it0.allocatable()[res.CPU] == q("1") - q("100m")
    assert it0.allocatable()[res.MEMORY] == q("2Gi") - q("10Mi")
    # requirements carry zone/capacity-type/integer labels
    assert it0.requirements.get(wk.TOPOLOGY_ZONE_LABEL_KEY).values == {
        "test-zone-1",
        "test-zone-2",
        "test-zone-3",
    }
    assert it0.requirements.get(fake.INTEGER_INSTANCE_LABEL_KEY).values == {"1"}
    assert it0.requirements.get(fake.LABEL_INSTANCE_SIZE).values == {"small"}
    # a big one is large/exotic
    big = its[10]
    assert big.requirements.get(fake.LABEL_INSTANCE_SIZE).values == {"large"}
    assert big.requirements.get(fake.EXOTIC_INSTANCE_LABEL_KEY).values == {"optional"}


def test_fake_labels_registered_well_known():
    assert fake.LABEL_INSTANCE_SIZE in wk.WELL_KNOWN_LABELS
    assert fake.INTEGER_INSTANCE_LABEL_KEY in wk.WELL_KNOWN_LABELS


def test_kwok_universe():
    its = kwok.construct_instance_types()
    assert len(its) == 12 * 3 * 2 * 2  # sizes x families x os x arch
    by_name = {it.name: it for it in its}
    c1 = by_name["c-1x-amd64-linux"]
    assert c1.capacity[res.MEMORY] == q("2Gi")
    s4 = by_name["s-4x-arm64-windows"]
    assert s4.capacity[res.MEMORY] == q("16Gi")
    m256 = by_name["m-256x-amd64-linux"]
    assert m256.capacity[res.MEMORY] == q("2048Gi")
    assert m256.capacity[res.PODS] == q("1024")  # clamped
    # 4 zones x 2 capacity types offerings
    assert len(c1.offerings) == 8
    # spot is 0.7x on-demand
    spot = [o for o in c1.offerings if o.capacity_type() == "spot"][0]
    od = [o for o in c1.offerings if o.capacity_type() == "on-demand"][0]
    assert abs(spot.price - 0.7 * od.price) < 1e-9
    # price formula: 1 vCPU * 0.025 + 2 GiB * 0.001 * (1024^3/1e9)
    assert abs(od.price - (0.025 + 0.001 * 2 * 1024**3 / 1e9)) < 1e-9


def test_order_by_price():
    its = fake.instance_types(10)
    reqs = Requirements()
    its_sorted = InstanceTypes(list(its)).order_by_price(reqs)
    prices = [
        min(o.price for o in it.offerings) for it in its_sorted
    ]
    assert prices == sorted(prices)
    # restricting to an offering-less zone pushes everything to +inf, order stable
    reqs_zone = Requirements([Requirement(wk.TOPOLOGY_ZONE_LABEL_KEY, Operator.IN, ["nope"])])
    InstanceTypes(list(its)).order_by_price(reqs_zone)


def test_satisfies_min_values():
    its = InstanceTypes(fake.instance_types(5))
    reqs = Requirements(
        [
            Requirement(
                wk.INSTANCE_TYPE_LABEL_KEY,
                Operator.IN,
                [f"fake-it-{i}" for i in range(5)],
                min_values=3,
            )
        ]
    )
    needed, unsat, err = its.satisfies_min_values(reqs)
    assert err is None and needed == 3 and not unsat
    reqs_too_many = Requirements(
        [
            Requirement(
                wk.INSTANCE_TYPE_LABEL_KEY,
                Operator.IN,
                [f"fake-it-{i}" for i in range(5)],
                min_values=9,
            )
        ]
    )
    needed, unsat, err = its.satisfies_min_values(reqs_too_many)
    assert err is not None and unsat == {wk.INSTANCE_TYPE_LABEL_KEY: 5}


def test_truncate_respects_min_values():
    its = InstanceTypes(fake.instance_types(10))
    reqs = Requirements(
        [
            Requirement(
                wk.INSTANCE_TYPE_LABEL_KEY,
                Operator.IN,
                [f"fake-it-{i}" for i in range(10)],
                min_values=5,
            )
        ]
    )
    truncated, err = its.truncate(reqs, max_items=6)
    assert err is None and len(truncated) == 6
    _, err2 = its.truncate(reqs, max_items=3)
    assert err2 is not None  # 3 < minValues 5
    # best-effort policy allows the violation
    truncated3, err3 = its.truncate(reqs, max_items=3, best_effort_min_values=True)
    assert err3 is None and len(truncated3) == 3


def _claim(requirements=None, pool="default"):
    nc = NodeClaim(
        metadata=ObjectMeta(name="test-claim", labels={wk.NODEPOOL_LABEL_KEY: pool}),
        requirements=requirements or [],
    )
    return nc


def test_fake_provider_create_picks_cheapest_compatible():
    cp = fake.FakeCloudProvider(fake.instance_types(10))
    created = cp.create(
        _claim(
            requirements=[
                NodeSelectorRequirement(fake.INTEGER_INSTANCE_LABEL_KEY, Operator.IN, ["4"])
            ]
        )
    )
    assert created.metadata.labels[wk.INSTANCE_TYPE_LABEL_KEY] == "fake-it-3"
    assert created.status.provider_id.startswith("fake:///fake-it-3/")
    assert cp.get(created.status.provider_id) is created
    assert len(cp.list()) == 1
    cp.delete(created)
    with pytest.raises(NodeClaimNotFoundError):
        cp.get(created.status.provider_id)


def test_fake_provider_injected_error():
    cp = fake.FakeCloudProvider()
    cp.next_create_err = RuntimeError("boom")
    with pytest.raises(RuntimeError):
        cp.create(_claim())
    # error is one-shot
    cp.create(_claim())


def test_benchmark_pod_mixes():
    from karpenter_tpu import testing as fixtures

    fixtures.reset_rng()
    pods = fixtures.make_diverse_pods(100)
    assert len(pods) == 100
    tsc = [p for p in pods if p.topology_spread_constraints]
    aff = [p for p in pods if p.pod_affinity]
    anti = [p for p in pods if p.pod_anti_affinity]
    assert len(tsc) == 40 and len(aff) == 20 and len(anti) == 20
    for p in pods:
        assert p.requests[res.CPU] in {100, 250, 500, 1000, 1500}
        assert p.requests[res.MEMORY] % q("1Mi") == 0
    prefs = fixtures.make_preference_pods(10)
    assert all(p.node_affinity.preferred for p in prefs)
    assert all(len(p.pod_anti_affinity_preferred) == 2 for p in prefs)
