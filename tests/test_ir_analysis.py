"""graftlint IR tier gate (analysis/ir.py): per-rule positive/negative
fixtures, the budget-manifest mechanics against live measurements, and
the full-tree run — every solver entry point traces clean and matches
kernel_budgets.json.

The module-scoped `report` fixture does the expensive work once: traces
the eight kernel entry points and runs the two runtime-accounting solves
on JAX_PLATFORMS=cpu. Everything else is doctored-input unit tests on
the walkers and the manifest comparison.
"""

from __future__ import annotations

import copy
import json
import os

import jax
import jax.numpy as jnp
import pytest

from karpenter_tpu.analysis import budgets as budgets_mod
from karpenter_tpu.analysis import ir
from karpenter_tpu.analysis.__main__ import main as graftlint_main

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def report():
    return ir.run_ir_analysis(REPO_ROOT)


@pytest.fixture(scope="module")
def manifest_entries(report):
    """Deep-copyable real manifest entries for doctoring."""
    return {
        name: copy.deepcopy(e) for name, e in report["manifest"].entries.items()
    }


# ---------------------------------------------------------------------------
# full-tree cleanliness (the gate)


def test_full_tree_clean(report):
    assert report["errors"] == []
    assert [f.render() for f in report["findings"]] == []
    assert report["stale"] == []
    assert report["unjustified"] == []
    assert report["budget_unjustified"] == []


def test_manifest_covers_every_entry_point(report):
    # _entry_paths is the registry of everything measure() produces:
    # the traced kernels plus the runtime-contract pseudo-entries
    names = set(ir._entry_paths())
    assert {"solve[runtime]", "setsweep[runtime]"} <= names
    assert set(report["measured"]) == names
    assert set(report["manifest"].entries) == names


def test_runtime_contracts_hold(report):
    rt = report["measured"]["solve[runtime]"]
    # the absolute contracts, independent of what the manifest says:
    # per-class tables ship once per solve, and a repeated same-shape
    # solve retraces and recompiles nothing
    assert rt["table_uploads"] == 1
    assert rt["pod_table_uploads"] == 1
    assert rt["second_solve_traces"] == 0
    assert rt["second_solve_compiles"] == 0


# ---------------------------------------------------------------------------
# ir-callbacks


def test_callbacks_flags_debug_callback():
    def f(x):
        jax.debug.print("x={x}", x=x)
        return x + 1

    found = ir.forbidden_primitives(jax.make_jaxpr(f)(jnp.ones(3)))
    assert found and all("callback" in p for p in found)


def test_callbacks_flags_pure_callback():
    import numpy as np

    def f(x):
        return jax.pure_callback(
            lambda a: np.asarray(a),
            jax.ShapeDtypeStruct((3,), jnp.float32),
            x,
        )

    assert ir.forbidden_primitives(jax.make_jaxpr(f)(jnp.ones(3))) == [
        "pure_callback"
    ]


def test_callbacks_clean_program_negative():
    j = jax.make_jaxpr(lambda x: (x * 2).sum())(jnp.ones(3))
    assert ir.forbidden_primitives(j) == []


def test_callbacks_seen_through_jit_and_scan():
    """The walker must recurse into pjit/scan sub-jaxprs — a callback
    hidden inside nested control flow still surfaces."""

    @jax.jit
    def inner(c, x):
        jax.debug.print("c={c}", c=c)
        return c + x, x

    def f(xs):
        return jax.lax.scan(inner, jnp.float32(0), xs)

    assert ir.forbidden_primitives(jax.make_jaxpr(f)(jnp.ones(4)))


# ---------------------------------------------------------------------------
# ir-dtype


def test_dtype_flags_64bit_avals():
    from jax.experimental import enable_x64

    with enable_x64():
        j = jax.make_jaxpr(lambda x: x.astype("int64") + 1)(
            jnp.arange(3, dtype=jnp.int32)
        )
    assert "int64" in ir.wide_dtypes(j)


def test_dtype_negative_int32_program():
    j = jax.make_jaxpr(lambda x: x * jnp.int32(2))(
        jnp.arange(3, dtype=jnp.int32)
    )
    assert ir.wide_dtypes(j) == []


def test_dtype_flags_weak_carry():
    def f(x):
        return jax.lax.while_loop(
            lambda c: c[0] < 3, lambda c: (c[0] + 1, c[1] * 2.0), (0, x)
        )

    stats = ir.loop_stats(jax.make_jaxpr(f)(jnp.float32(1.0)))
    assert sum(s.weak_carries for s in stats) > 0


def test_dtype_negative_pinned_carry():
    def f(x):
        return jax.lax.while_loop(
            lambda c: c[0] < jnp.int32(3),
            lambda c: (c[0] + jnp.int32(1), c[1] * jnp.float32(2)),
            (jnp.int32(0), x),
        )

    stats = ir.loop_stats(jax.make_jaxpr(f)(jnp.float32(1.0)))
    assert stats and sum(s.weak_carries for s in stats) == 0


# ---------------------------------------------------------------------------
# loop-carry measurement


def test_loop_stats_scan_carry_bytes():
    def f(xs):
        def body(c, x):
            return (c[0] + x, c[1] + jnp.int32(1)), x

        return jax.lax.scan(
            body, (jnp.zeros(4, jnp.float32), jnp.int32(0)), xs
        )

    stats = ir.loop_stats(jax.make_jaxpr(f)(jnp.ones(5)))
    scans = [s for s in stats if s.kind == "scan"]
    assert len(scans) == 1
    assert scans[0].length == 5
    assert scans[0].carry_bytes == 4 * 4 + 4  # f32[4] + i32 scalar


def test_kernel_metrics_shape():
    def f(xs):
        return jax.lax.scan(lambda c, x: (c + x, x), jnp.float32(0), xs)

    m = ir.kernel_metrics(jax.make_jaxpr(f)(jnp.ones(3)))
    assert m == {
        "while_loops": 0,
        "scans": 1,
        "max_carry_bytes": 4,
        "total_carry_bytes": 4,
        "scan_total_length": 3,
    }
    assert set(m) <= set(budgets_mod.METRIC_POLICY)


# ---------------------------------------------------------------------------
# ir-carry-budget (doctored manifests against live measurements)


def _findings_for(measured, entries, rule_ids=None):
    manifest = budgets_mod.BudgetManifest(entries)
    findings, notes = ir.budget_findings(measured, manifest, rule_ids)
    return findings, notes


def test_budget_regression_detected(report, manifest_entries):
    entries = copy.deepcopy(manifest_entries)
    got = report["measured"]["solve_scan[relax=False]"]["max_carry_bytes"]
    entries["solve_scan[relax=False]"]["metrics"]["max_carry_bytes"] = got - 1
    findings, _ = _findings_for(report["measured"], entries)
    assert any(
        f.rule == "ir-carry-budget" and "regressed" in f.message
        and f.text == "solve_scan[relax=False]"
        for f in findings
    )


def test_budget_structure_mismatch_detected(report, manifest_entries):
    entries = copy.deepcopy(manifest_entries)
    entries["solve_scan[relax=True]"]["metrics"]["while_loops"] += 1
    findings, _ = _findings_for(report["measured"], entries)
    assert any(
        f.rule == "ir-carry-budget" and "exact-match" in f.message
        for f in findings
    )


def test_budget_ceiling_slack_is_not_a_finding(report, manifest_entries):
    entries = copy.deepcopy(manifest_entries)
    entries["solve_scan[relax=False]"]["metrics"]["max_carry_bytes"] += 1000
    findings, notes = _findings_for(report["measured"], entries)
    assert not any(f.text == "solve_scan[relax=False]" for f in findings)
    assert any("max_carry_bytes" in n for n in notes)


def test_budget_orphan_and_missing_policed(report, manifest_entries):
    entries = copy.deepcopy(manifest_entries)
    entries["ghost_kernel"] = {
        "justification": "x", "metrics": {"while_loops": 0},
    }
    del entries["_gather_xs"]
    findings, _ = _findings_for(report["measured"], entries)
    msgs = [f.message for f in findings]
    assert any("matches no traced entry point" in m for m in msgs)
    assert any("no budget entry" in m for m in msgs)


def test_budget_unknown_metric_policed(report, manifest_entries):
    entries = copy.deepcopy(manifest_entries)
    entries["_gather_xs"]["metrics"]["made_up_metric"] = 7
    findings, _ = _findings_for(report["measured"], entries)
    assert any("unknown metric" in f.message for f in findings)


def test_budget_unjustified_policed():
    m = budgets_mod.BudgetManifest(
        {
            "a": {"justification": "TODO: justify or fix", "metrics": {}},
            "b": {"justification": "  ", "metrics": {}},
            "c": {"justification": "real reason", "metrics": {}},
        }
    )
    assert m.unjustified() == ["a", "b"]


# ---------------------------------------------------------------------------
# ir-retrace


def test_structure_findings_flag_duplicated_step():
    measured = {
        "solve_scan[relax=False]": {"while_loops": 1},
        "solve_scan[relax=True]": {"while_loops": 3},  # step duplicated
    }
    fs = ir.structure_findings(measured)
    assert len(fs) == 1 and fs[0].rule == "ir-retrace"


def test_structure_findings_flag_tier_machinery_in_plain_path():
    measured = {
        "solve_runs[relax=False]": {"while_loops": 3},  # == relaxed: leak
        "solve_runs[relax=True]": {"while_loops": 3},
    }
    assert len(ir.structure_findings(measured)) == 1


def test_structure_findings_negative(report):
    assert ir.structure_findings(report["measured"]) == []


def test_trace_events_zero_on_cache_hit():
    @jax.jit
    def f(x):
        return x + 1

    f(jnp.ones(3))
    with ir.trace_events() as ev:
        f(jnp.ones(3))
    assert ev.traces == 0 and ev.compiles == 0


def test_trace_events_count_new_shape():
    @jax.jit
    def g(x):
        return x * 2

    g(jnp.ones(3))
    with ir.trace_events() as ev:
        g(jnp.ones(7))  # new shape -> retrace
    assert ev.traces >= 1


def test_retrace_budget_violation_surfaces(report, manifest_entries):
    entries = copy.deepcopy(manifest_entries)
    measured = copy.deepcopy(report["measured"])
    measured["solve[runtime]"]["second_solve_traces"] = 4
    findings, _ = _findings_for(measured, entries)
    assert any(
        f.rule == "ir-retrace" and "second_solve_traces" in f.message
        for f in findings
    )


# ---------------------------------------------------------------------------
# ir-transfer


def test_count_method_calls_counts_and_restores():
    class C:
        def m(self):
            return 42

    orig = C.m
    with ir.count_method_calls(C, ("m",)) as counts:
        assert C().m() == 42
        assert C().m() == 42
    assert counts["m"] == 2
    assert C.m is orig
    C().m()
    assert counts["m"] == 2  # counter detached after exit


def test_transfer_budget_violation_surfaces(report, manifest_entries):
    entries = copy.deepcopy(manifest_entries)
    measured = copy.deepcopy(report["measured"])
    measured["solve[runtime]"]["table_uploads"] = 2
    findings, _ = _findings_for(measured, entries)
    assert any(
        f.rule == "ir-transfer" and "table_uploads" in f.message
        for f in findings
    )


def test_partial_run_does_not_police_orphans(report, manifest_entries):
    """A --rules subset measures a slice of the entry points; manifest
    entries for out-of-scope kernels must not read as orphaned (only the
    full run polices rot — the AST tier's subset-run convention)."""
    entries = copy.deepcopy(manifest_entries)
    measured = {
        k: copy.deepcopy(v)
        for k, v in report["measured"].items()
        if k != "solve[runtime]"
    }
    findings, _ = _findings_for(
        measured, entries, rule_ids={"ir-carry-budget"}
    )
    assert findings == []
    # the full run still polices the same gap
    findings_full, _ = _findings_for(measured, entries)
    assert any("matches no traced entry point" in f.message for f in findings_full)


def test_trace_failure_is_not_reported_as_orphan(report, manifest_entries):
    """A kernel that fails to trace is a broken gate (error, exit 2) —
    its still-valid budget entry must NOT surface as 'orphaned, remove
    it', which would invite deleting the entry that masks the breakage."""
    entries = copy.deepcopy(manifest_entries)
    measured = {
        k: copy.deepcopy(v)
        for k, v in report["measured"].items()
        if k != "_step_relax"  # simulate: its trace raised
    }
    findings, _ = _findings_for(measured, entries)
    assert any("_step_relax" in f.message for f in findings)  # full run
    findings_err, _ = ir.budget_findings(
        measured,
        budgets_mod.BudgetManifest(entries),
        None,
        errored={"_step_relax"},
    )
    assert not any("_step_relax" in f.message for f in findings_err)


def test_cli_ir_trace_error_exits_2(monkeypatch, capsys):
    """Exit-code contract: trace errors dominate comparison findings."""

    def boom(rule_ids=None):
        return {}, [], ["_step_relax: RuntimeError: kernel broke"]

    monkeypatch.setattr(ir, "measure", boom)
    rc = graftlint_main(["--ir", "--root", REPO_ROOT])
    assert rc == 2
    assert "trace error" in capsys.readouterr().out


def test_rule_filter_scopes_budget_findings(report, manifest_entries):
    entries = copy.deepcopy(manifest_entries)
    measured = copy.deepcopy(report["measured"])
    measured["solve[runtime]"]["table_uploads"] = 2
    measured["solve_scan[relax=False]"]["while_loops"] = 5
    findings, _ = _findings_for(measured, entries, rule_ids={"ir-transfer"})
    assert findings and all(f.rule == "ir-transfer" for f in findings)


# ---------------------------------------------------------------------------
# baseline mechanics (shared engine.Baseline, IR identity = entry name)


def test_ir_findings_are_baselinable(report, manifest_entries):
    from karpenter_tpu.analysis.engine import Baseline

    entries = copy.deepcopy(manifest_entries)
    measured = copy.deepcopy(report["measured"])
    measured["solve[runtime]"]["table_uploads"] = 2
    findings, _ = _findings_for(measured, entries)
    target = [f for f in findings if f.rule == "ir-transfer"]
    baseline = Baseline(
        [
            {
                "rule": f.rule,
                "path": f.path,
                "text": f.text,
                "justification": "known double-upload under test",
            }
            for f in target
        ]
    )
    fresh, stale = baseline.apply(findings)
    assert not any(f.rule == "ir-transfer" for f in fresh)
    assert stale == []


# ---------------------------------------------------------------------------
# CLI


def test_cli_ir_full_tree_clean(capsys):
    assert graftlint_main(["--ir", "--root", REPO_ROOT]) == 0
    out = capsys.readouterr().out
    assert "0 findings" in out


def test_cli_ir_rejects_paths_and_changed_only(capsys):
    assert graftlint_main(["--ir", "--root", REPO_ROOT, "some.py"]) == 2
    assert graftlint_main(["--ir", "--root", REPO_ROOT, "--changed-only"]) == 2


def test_cli_ir_malformed_budgets_exits_2(tmp_path, capsys):
    """A hand-edit typo in kernel_budgets.json (the documented
    re-baseline workflow edits it) must surface as the exit-2 parse
    diagnostic naming the file, not a JSONDecodeError traceback."""
    bad = tmp_path / "kernel_budgets.json"
    bad.write_text('{"entries": {,}}', encoding="utf-8")
    rc = graftlint_main(
        ["--ir", "--root", REPO_ROOT, "--budgets", str(bad)]
    )
    assert rc == 2
    err = capsys.readouterr().err
    assert "cannot parse" in err and str(bad) in err


def test_cli_ir_rejects_unknown_rule_id(capsys):
    """A typo'd --rules id must exit 2, not measure nothing and read as
    a clean gate."""
    rc = graftlint_main(
        ["--ir", "--root", REPO_ROOT, "--rules", "ir-carrybudget"]
    )
    assert rc == 2
    assert "unknown IR rule" in capsys.readouterr().err


def test_cli_ir_write_baseline_rejects_rule_subset(tmp_path, capsys):
    rc = graftlint_main(
        [
            "--ir",
            "--root",
            REPO_ROOT,
            "--rules",
            "ir-callbacks",
            "--write-baseline",
            "--baseline",
            str(tmp_path / "bl.json"),
        ]
    )
    assert rc == 2
    assert not (tmp_path / "bl.json").exists()


def test_cli_ir_write_baseline_refuses_on_trace_errors(
    tmp_path, monkeypatch, capsys
):
    """A broken kernel trace must never rewrite the IR baseline as if the
    errored kernel's findings were resolved."""

    def boom(rule_ids=None):
        return {}, [], ["_step_relax: RuntimeError: kernel broke"]

    monkeypatch.setattr(ir, "measure", boom)
    bl = tmp_path / "bl.json"
    rc = graftlint_main(
        [
            "--ir",
            "--root",
            REPO_ROOT,
            "--write-baseline",
            "--baseline",
            str(bl),
        ]
    )
    assert rc == 2
    assert not bl.exists()
    assert "trace error" in capsys.readouterr().err


def test_cli_write_budgets_rejects_rule_subset(tmp_path, capsys):
    rc = graftlint_main(
        [
            "--ir",
            "--write-budgets",
            "--rules",
            "ir-callbacks",
            "--root",
            REPO_ROOT,
            "--budgets",
            str(tmp_path / "b.json"),
        ]
    )
    assert rc == 2
    assert not (tmp_path / "b.json").exists()


def test_cli_ir_budget_regression_exits_1(tmp_path, report, capsys):
    """A doctored manifest (one ceiling below the measurement) must fail
    the CLI gate — the seeded end-to-end positive for the budget rules."""
    entries = {
        name: copy.deepcopy(e)
        for name, e in report["manifest"].entries.items()
    }
    got = report["measured"]["solve_scan[relax=False]"]["max_carry_bytes"]
    entries["solve_scan[relax=False]"]["metrics"]["max_carry_bytes"] = got - 1
    p = tmp_path / "kernel_budgets.json"
    p.write_text(
        budgets_mod.BudgetManifest.dumps({"entries": entries}),
        encoding="utf-8",
    )
    rc = graftlint_main(
        ["--ir", "--root", REPO_ROOT, "--budgets", str(p), "--json"]
    )
    assert rc == 1
    data = json.loads(capsys.readouterr().out)
    assert any(
        "max_carry_bytes" in f["message"] for f in data["findings"]
    )
