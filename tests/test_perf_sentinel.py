"""Perf-regression sentinel (ISSUE 15): the bench.py --check gate's
comparison logic, and the runtime-vs-static cross-check — a pinned
problem's kernel-odometer iteration count must equal the IR tier's
scan-length budget (kernel_budgets.json), so the two measurement tiers
police each other.

The check_regression tests are pure (synthetic rows, no jax); the
odometer cross-check runs the real compiled kernel on the IR tier's own
representative kit. A slow-marked subprocess test drives the full
`bench.py --check --quick` CLI including the synthetically injected 2x
phase-share regression (the acceptance pin)."""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

import bench

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BASELINE_ROW = {
    "tpu_pods_per_sec": 1000.0,
    "phase_shares": {
        "dispatch": 0.60, "encode": 0.15, "decode": 0.10,
        "upload": 0.08, "order": 0.04, "regrow": 0.01,
    },
    "kernel_iterations": 512,
    "iterations_per_pod": 2.56,
}


def _current(**over):
    cur = {
        "tpu_pods_per_sec": 980.0,
        "phase_shares": dict(BASELINE_ROW["phase_shares"]),
        "kernel_iterations": 512,
        "iterations_per_pod": 2.56,
    }
    cur.update(over)
    return cur


# ---------------------------------------------------------------------------
# check_regression logic (pure)


@pytest.mark.perf
def test_check_passes_on_identical_measurement():
    assert bench.check_regression(_current(), BASELINE_ROW) == []


@pytest.mark.perf
def test_check_passes_inside_tolerances():
    cur = _current(tpu_pods_per_sec=700.0, iterations_per_pod=2.8)
    cur["phase_shares"]["decode"] = 0.15  # 1.5x < 1.75x
    assert bench.check_regression(cur, BASELINE_ROW) == []


@pytest.mark.perf
def test_throughput_drop_fails():
    fails = bench.check_regression(
        _current(tpu_pods_per_sec=500.0), BASELINE_ROW
    )
    assert any("throughput" in f for f in fails), fails


@pytest.mark.perf
def test_two_x_phase_share_regression_fails():
    # the acceptance shape: one phase's share doubles
    cur = _current()
    cur["phase_shares"]["decode"] = 0.20
    fails = bench.check_regression(cur, BASELINE_ROW)
    assert any("phase share" in f and "decode" in f for f in fails), fails


@pytest.mark.perf
def test_tiny_phase_shares_are_noise_immune():
    # regrow 0.01 -> 0.04 is 4x but under the 5% floor: never compared
    cur = _current()
    cur["phase_shares"]["regrow"] = 0.04
    assert bench.check_regression(cur, BASELINE_ROW) == []


@pytest.mark.perf
def test_iteration_growth_fails_tight():
    # iterations are deterministic: 20% growth must fail where the
    # throughput band would have shrugged
    fails = bench.check_regression(
        _current(iterations_per_pod=3.1), BASELINE_ROW
    )
    assert any("iterations" in f for f in fails), fails


@pytest.mark.perf
def test_run_check_exit_codes():
    code, report = bench.run_check(_current(), BASELINE_ROW, "quick_smoke")
    assert code == 0 and report["ok"]
    cur = _current()
    cur["phase_shares"]["decode"] = 0.20
    code, report = bench.run_check(cur, BASELINE_ROW, "quick_smoke")
    assert code == 1 and not report["ok"] and report["failures"]
    code, report = bench.run_check(_current(), None, "quick_smoke")
    assert code == 2 and "error" in report


@pytest.mark.perf
def test_baseline_rows_missing_metrics_are_skipped_not_crashed():
    # pre-odometer BENCH_DETAIL rows have no iterations_per_pod /
    # phase_shares: the check compares what exists and passes the rest
    assert bench.check_regression(
        _current(), {"tpu_pods_per_sec": 1000.0}
    ) == []


# ---------------------------------------------------------------------------
# runtime odometer vs static IR budget (the two tiers cross-check)


@pytest.mark.perf
def test_odometer_iterations_match_ir_scan_budget():
    """The pinned generic kit (the SAME problem the graftlint IR tier
    budgets) through the real compiled solve_scan: the runtime odometer's
    executed-iteration count must equal the static jaxpr tier's
    scan_total_length prediction in kernel_budgets.json. A drift in
    either direction means one measurement layer is lying."""
    from karpenter_tpu.analysis import ir
    from karpenter_tpu.solver import tpu_kernel as K

    with open(os.path.join(REPO_ROOT, "kernel_budgets.json")) as f:
        budgets = json.load(f)["entries"]

    kit = ir.build_kit("generic")
    _st, kinds, _slots, over, odo = K.solve_scan(
        kit.tb, kit.st, kit.xs, relax=False
    )
    predicted = budgets["solve_scan[relax=False]"]["metrics"][
        "scan_total_length"
    ]
    assert int(odo.steps) == int(predicted), (
        f"runtime odometer says {int(odo.steps)} scan iterations, the "
        f"IR budget predicts {predicted}"
    )
    assert not bool(over)
    # plain path: the tier machinery must report zero work
    assert int(odo.tier_steps) == 0
    assert int(odo.bulk_steps) == 0
    import numpy as np

    assert int(np.asarray(odo.tier_hist).sum()) == 0
    # and the decisions that rode along are real (not a zeroed dummy)
    assert int((np.asarray(kinds) != K.KIND_FAIL).sum()) > 0


@pytest.mark.perf
def test_odometer_relax_tier_accounting():
    """The mixed kit through solve_scan(relax=True): tier trips must be
    >= one per scan step (every pod pays at least tier 0) and the
    histogram must sum to the total."""
    import numpy as np

    from karpenter_tpu.analysis import ir
    from karpenter_tpu.solver import tpu_kernel as K

    kit = ir.build_kit("mixed")
    _st, _kinds, _slots, _over, odo = K.solve_scan(
        kit.tb, kit.st, kit.xs, relax=True
    )
    steps = int(odo.steps)
    tiers = int(odo.tier_steps)
    assert steps == int(kit.xs.valid.shape[0])
    assert tiers >= steps  # >= 1 tier trip per scan step
    assert int(np.asarray(odo.tier_hist).sum()) == tiers
    # tier 0 is attempted by every step
    assert int(np.asarray(odo.tier_hist)[0]) == steps


# ---------------------------------------------------------------------------
# the full CLI, end to end (slow tier: subprocess measurement)


@pytest.mark.perf
@pytest.mark.slow
def test_bench_check_quick_cli_end_to_end(tmp_path):
    """`bench.py --quick` pins a baseline row, `--check --quick` passes
    against it, and the synthetically injected 2x phase-share regression
    exits non-zero — the ISSUE 15 acceptance pin, against the real CLI.

    Runs with cwd=tmp_path: BENCH_DETAIL.json is cwd-relative, so the
    test's (pytest-contended — CLAUDE.md forbids benchmarking during a
    pytest run) numbers land in a scratch file and the repo's committed
    baseline is never touched."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")

    def run(*args):
        return subprocess.run(
            [sys.executable, os.path.join(REPO_ROOT, "bench.py"), *args],
            env=env, cwd=str(tmp_path), capture_output=True, text=True,
            timeout=1200,
        )

    out = run("--quick")
    assert out.returncode == 0, out.stderr[-2000:]
    out = run("--check", "--quick", "--json")
    assert out.returncode == 0, (out.stdout, out.stderr[-2000:])
    report = json.loads(out.stdout)
    assert report["ok"] and report["baseline_row"] == "quick_smoke"
    out = run(
        "--check", "--quick", "--inject-phase-regression", "dispatch:2.0"
    )
    assert out.returncode == 1, (out.returncode, out.stdout)
    report = json.loads(out.stdout)
    assert not report["ok"]
    assert any("dispatch" in f for f in report["failures"])
