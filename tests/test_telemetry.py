"""End-to-end solve telemetry (ISSUE 10): the tracing layer, the metric
exposition surface, and the probe endpoints.

Covers:
- karpenter_tpu.tracing unit behavior (spans, ring, detail gate, phase
  aggregation, bounded overhead);
- a Prometheus text-format lint of metrics.Registry.render() — HELP/TYPE
  ordering, name charset, label escaping, histogram bucket monotonicity
  and +Inf == _count;
- ProbeServer /debug/solves + /debug/solves/<id> (including under
  concurrent solves) and the /debug/pprof/profile seconds clamp;
- the sidecar acceptance path (test_service_faults.py-style harness):
  one solve through ResilientSolver with the sidecar active yields a
  single logical trace whose client- and server-side halves share the
  wire correlation id and cover encode/upload/dispatch/decode, and the
  oracle-degrade paths record the fallback reason as a span + labeled
  counter;
- the docs/observability.md metric-catalog drift test.
"""

from __future__ import annotations

import ast
import json
import os
import re
import tempfile
import threading
import time
import urllib.error
import urllib.request

import pytest

from karpenter_tpu import metrics, tracing
from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.controllers import probes as probes_mod
from karpenter_tpu.controllers.probes import ProbeServer
from karpenter_tpu.solver.hybrid import ResilientSolver, solve_in_process
from karpenter_tpu.solver.oracle import SchedulerOptions
from karpenter_tpu.solver.service import SolverServer
from karpenter_tpu.testing import fixtures

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _problem(n=6):
    fixtures.reset_rng(11)
    its = construct_instance_types(sizes=[2, 8])
    pools = [fixtures.node_pool(name="default")]
    pods = fixtures.make_diverse_pods(n)
    return pools, {"default": its}, pods


def _get(srv, path, timeout=15):
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}{path}", timeout=timeout
        ) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


# ---------------------------------------------------------------------------
# tracing unit behavior


def test_trace_spans_phases_and_ring():
    tracing.RING.clear()
    tr = tracing.new_trace("unit")
    with tr.span("encode", pods=3):
        pass
    with tr.span("dispatch"):
        with tr.span("kernel", detail=True):
            pass
    tr.event("oracle_fallback", reason="unsupported")
    tr.count("dispatches")
    tr.annotate(pods=3)
    tr.finish("ok")
    # detail off: the kernel sub-span folds into phases but records no Span
    names = [s.name for s in tr.spans]
    assert names == ["encode", "dispatch", "oracle_fallback"]
    assert set(tr.phases) == {"encode", "dispatch", "kernel"}
    # top_phases excludes nested names — safe to sum for shares
    assert set(tr.top_phases()) == {"encode", "dispatch"}
    assert tr.counts == {"dispatches": 1}
    assert tr.outcome == "ok" and tr.total_seconds >= 0.0
    got = tracing.RING.find(tr.trace_id)
    assert got == [tr]
    # finish is idempotent: no double ring push or double observe
    tr.finish("error")
    assert tr.outcome == "ok"
    assert len(tracing.RING.find(tr.trace_id)) == 1
    d = tr.to_dict()
    assert d["spans"][0]["attrs"] == {"pods": 3}
    assert "kernel" in d["phases"]


def test_detail_gate_records_subspans():
    tracing.set_detail(True)
    try:
        tr = tracing.new_trace("unit")
        with tr.span("dispatch"):
            with tr.span("kernel", detail=True):
                pass
        tr.finish()  # sorts spans into waterfall (start) order
        assert [s.name for s in tr.spans] == ["dispatch", "kernel"]
        assert [s.depth for s in tr.spans] == [0, 1]
    finally:
        tracing.set_detail(False)


def test_span_cap_degrades_to_aggregates():
    tr = tracing.new_trace("unit")
    for _ in range(tracing.MAX_SPANS + 10):
        with tr.span("tick"):
            pass
    assert len(tr.spans) == tracing.MAX_SPANS
    assert tr.truncated
    # the aggregate kept counting past the cap
    assert tr.phases["tick"] > 0.0
    tr.finish()


def test_wire_id_adoption():
    tr = tracing.new_trace("unit", side="client")
    tr.set_wire_id(42)
    assert tr.trace_id == "w42"
    tr.finish()
    assert tracing.RING.find("w42")[-1] is tr


def test_trace_overhead_bounded():
    """The default-tier cost of a fully instrumented solve (6 top-level
    phases + 40 dispatches with a folded detail sub-span each + finish)
    must stay far below the 2% bench acceptance band — docs/
    observability.md quotes this number."""
    n = 100
    t0 = time.monotonic()
    for _ in range(n):
        tr = tracing.new_trace("bench")
        for i in range(6):
            with tr.span(f"p{i}"):
                pass
        for _ in range(40):
            with tr.span("dispatch"):
                with tr.span("kernel", detail=True):
                    pass
        tr.count("dispatches", by=40)
        tr.finish()
    per_solve = (time.monotonic() - t0) / n
    # generous ceiling for a loaded CI box; measured ~80 µs
    assert per_solve < 0.005, f"tracing costs {per_solve * 1e6:.0f} µs/solve"


def test_trace_events_shared_with_ir_tier():
    """Satellite: the compile/retrace counters moved to the shared
    telemetry module; the IR tier re-exports the SAME object."""
    from karpenter_tpu.analysis import ir

    assert ir.trace_events is tracing.trace_events
    assert ir._COUNTS is tracing._COUNTS


def test_jax_compile_events_surface_as_metrics():
    """Runtime solves surface backend_compiles/cache_hits as metrics, not
    only inside graftlint runs: a fresh jit program bumps the listener's
    counter metric."""
    import jax
    import jax.numpy as jnp

    tracing.install_compile_listener()
    before = tracing.JAX_COMPILE_EVENTS.value({"event": "traces"})
    jax.jit(lambda x: x * 3 + 1)(jnp.arange(7))  # fresh lambda: new trace
    assert tracing.JAX_COMPILE_EVENTS.value({"event": "traces"}) >= before + 1


# ---------------------------------------------------------------------------
# Prometheus exposition lint

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*\Z")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})?\s(\S+)$"
)
_LABELS_RE = re.compile(
    r"\{(?:[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\\"|\\\\|\\n)*\"(?:,|(?=\})))*\}\Z"
)


def lint_prometheus(text: str) -> None:
    """Assert `text` is well-formed Prometheus text exposition: HELP then
    TYPE precede a family's samples, names are legal, label blocks parse
    with escaping, histogram buckets are cumulative/monotone with le
    ascending and +Inf == _count."""
    helped: set[str] = set()
    types: dict[str, str] = {}
    # family -> base-labels -> list[(le, count)], plus _sum/_count values
    buckets: dict[tuple, list[tuple[float, float]]] = {}
    counts: dict[tuple, float] = {}
    for ln in text.rstrip("\n").split("\n"):
        if ln.startswith("# HELP "):
            name = ln.split(" ", 3)[2]
            assert _NAME_RE.match(name), f"bad HELP name: {ln!r}"
            assert name not in helped, f"duplicate HELP for {name}"
            assert name not in types, f"HELP after TYPE for {name}"
            helped.add(name)
            continue
        if ln.startswith("# TYPE "):
            parts = ln.split(" ")
            name, kind = parts[2], parts[3]
            assert kind in ("counter", "gauge", "histogram"), ln
            assert name in helped, f"TYPE without HELP: {ln!r}"
            assert name not in types, f"duplicate TYPE for {name}"
            types[name] = kind
            continue
        assert not ln.startswith("#"), f"unknown comment line: {ln!r}"
        m = _SAMPLE_RE.match(ln)
        assert m, f"unparsable sample line: {ln!r}"
        name, labels, value = m.group(1), m.group(2) or "", m.group(3)
        val = float(value)  # must parse
        if labels:
            assert _LABELS_RE.match(labels), (
                f"label block fails escaping/charset lint: {ln!r}"
            )
        family = name
        for suffix in ("_bucket", "_sum", "_count"):
            base = name[: -len(suffix)] if name.endswith(suffix) else None
            if base and types.get(base) == "histogram":
                family = base
                break
        assert family in types, f"sample before TYPE: {ln!r}"
        if types[family] == "histogram" and name.endswith("_bucket"):
            le_m = re.search(r'le="([^"]*)"', labels)
            assert le_m, f"histogram bucket without le: {ln!r}"
            le = float("inf") if le_m.group(1) == "+Inf" else float(le_m.group(1))
            stripped = re.sub(r',?le="[^"]*"', "", labels)
            base_labels = (family, "" if stripped == "{}" else stripped)
            buckets.setdefault(base_labels, []).append((le, val))
        if types[family] == "histogram" and name.endswith("_count"):
            counts[(family, labels)] = val
    for (family, base_labels), series in buckets.items():
        les = [le for le, _ in series]
        vals = [v for _, v in series]
        assert les == sorted(les), f"{family}{base_labels}: le not ascending"
        assert les and les[-1] == float("inf"), f"{family}: missing +Inf"
        assert all(
            a <= b for a, b in zip(vals, vals[1:])
        ), f"{family}{base_labels}: bucket counts not monotone"
        cnt = counts.get((family, base_labels))
        assert cnt is not None and cnt == vals[-1], (
            f"{family}{base_labels}: +Inf bucket != _count"
        )


def test_registry_render_passes_format_lint():
    # populate a few series first so the lint sees real samples
    tracing.SOLVE_PHASE_SECONDS.observe(0.01, {"phase": "encode"})
    tracing.SOLVE_FALLBACKS.inc({"reason": "unsupported"})
    lint_prometheus(metrics.REGISTRY.render())


def test_label_and_help_escaping():
    r = metrics.Registry()
    c = r.counter(
        "karpenter_escape_total",
        'help with "quotes", a \\ backslash\nand a newline',
        ("reason",),
    )
    evil = 'fail: "quoted" \\ back\nslash'
    c.inc({"reason": evil})
    h = r.histogram("karpenter_escape_seconds", "H.", ("reason",))
    h.observe(0.2, {"reason": evil})
    text = r.render()
    assert "\\n" in text and '\\"' in text
    lint_prometheus(text)
    # the escaped value round-trips: one sample line carries the value 1
    line = next(
        ln for ln in text.splitlines() if ln.startswith("karpenter_escape_total{")
    )
    assert line.endswith(" 1.0")


def test_histogram_monotone_under_mixed_observations():
    r = metrics.Registry()
    h = r.histogram("karpenter_mono_seconds", "H.", buckets=[0.1, 1, 10])
    for v in (0.05, 0.5, 5.0, 50.0, 0.05):
        h.observe(v)
    lint_prometheus(r.render())
    assert h.count() == 5


# ---------------------------------------------------------------------------
# probe endpoints


def test_debug_solves_endpoints():
    tracing.RING.clear()
    tr = tracing.new_trace("unit")
    with tr.span("encode"):
        pass
    tr.set_wire_id(777)
    tr.finish()
    srv = ProbeServer(None, None)
    srv.start()
    try:
        code, body = _get(srv, "/debug/solves")
        assert code == 200
        listing = json.loads(body)
        assert listing and listing[0]["id"] == "w777"
        assert "spans" not in listing[0]  # summaries only
        code, body = _get(srv, "/debug/solves/w777")
        assert code == 200
        detail = json.loads(body)
        assert [s["name"] for s in detail["traces"][0]["spans"]] == ["encode"]
        code, _ = _get(srv, "/debug/solves/nosuch")
        assert code == 404
    finally:
        srv.stop()


def test_debug_solves_under_concurrent_solves():
    """The ring mutates while /debug/solves renders: every response must
    stay parseable JSON with a 200 — no torn snapshots."""
    tracing.RING.clear()
    pools, ibp, pods = _problem(3)
    stop = threading.Event()
    errors: list[BaseException] = []

    def solver_loop():
        while not stop.is_set():
            try:
                solve_in_process(pools, ibp, pods, force_oracle=True)
            except BaseException as e:  # surfaces in the assert below
                errors.append(e)
                return

    threads = [threading.Thread(target=solver_loop, daemon=True) for _ in range(3)]
    srv = ProbeServer(None, None)
    srv.start()
    try:
        for t in threads:
            t.start()
        deadline = time.monotonic() + 5.0
        seen = 0
        while time.monotonic() < deadline and seen < 20:
            code, body = _get(srv, "/debug/solves")
            assert code == 200
            json.loads(body)  # must always parse
            seen += 1
        assert seen >= 20
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
        srv.stop()
    assert not errors, errors
    assert tracing.RING.snapshot(), "concurrent solves landed no traces"


def test_probe_profiling_gate_flips_trace_detail():
    assert not tracing.detail_enabled()
    srv = ProbeServer(None, None, enable_profiling=True)
    srv.start()
    try:
        assert tracing.detail_enabled()
    finally:
        srv.stop()
    assert not tracing.detail_enabled()


def test_pprof_profile_seconds_clamped_and_validated(monkeypatch):
    """Satellite: /debug/pprof/profile?seconds=N clamps to
    MAX_PROFILE_SECONDS and 400s non-numeric/non-positive input — a
    handler thread must never block for whatever the query string says."""
    monkeypatch.setattr(probes_mod, "MAX_PROFILE_SECONDS", 0.2)
    srv = ProbeServer(None, None, enable_profiling=True)
    srv.start()
    try:
        t0 = time.monotonic()
        code, body = _get(srv, "/debug/pprof/profile?seconds=30&top=1")
        took = time.monotonic() - t0
        assert code == 200 and "samples:" in body
        assert took < 5.0, f"clamp did not hold: {took:.1f}s"
        assert _get(srv, "/debug/pprof/profile?seconds=abc")[0] == 400
        assert _get(srv, "/debug/pprof/profile?seconds=-3")[0] == 400
        assert _get(srv, "/debug/pprof/profile?seconds=nan")[0] == 400
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# fallback reasons: span + labeled counter


def test_small_batch_fallback_recorded_as_span_and_counter():
    tracing.RING.clear()
    pools, ibp, pods = _problem(4)
    before = tracing.SOLVE_FALLBACKS.value({"reason": "small_batch"})
    results, sched = solve_in_process(pools, ibp, pods)  # below crossover
    assert sched.used_tpu is False
    assert tracing.SOLVE_FALLBACKS.value({"reason": "small_batch"}) == before + 1
    tr = tracing.RING.snapshot()[-1]
    spans = {s.name: s for s in tr.spans}
    assert spans["oracle_fallback"].attrs["reason"] == "small_batch"
    assert "oracle" in spans
    assert tr.attrs.get("used_tpu") is False


def test_forced_oracle_fallback_recorded():
    tracing.RING.clear()
    pools, ibp, pods = _problem(3)
    before = tracing.SOLVE_FALLBACKS.value({"reason": "forced"})
    solve_in_process(pools, ibp, pods, force_oracle=True)
    assert tracing.SOLVE_FALLBACKS.value({"reason": "forced"}) == before + 1
    tr = tracing.RING.snapshot()[-1]
    assert any(
        s.name == "oracle_fallback" and s.attrs.get("reason") == "forced"
        for s in tr.spans
    )


# ---------------------------------------------------------------------------
# the sidecar acceptance path (fault-harness style)


@pytest.mark.faults
@pytest.mark.hard_timeout(240)
def test_sidecar_solve_yields_one_joined_trace():
    """Acceptance: one solve through ResilientSolver with the sidecar
    active yields a single logical trace — the client- and server-side
    halves share the wire correlation id, and together they cover
    encode/upload/dispatch/decode."""
    tracing.RING.clear()
    path = tempfile.mktemp(suffix=".sock")
    srv = SolverServer(path)
    srv.start()
    try:
        rs = ResilientSolver(socket_path=path)
        pools, ibp, pods = _problem(6)
        # tpu_min_pods=0: the sidecar's solve must ride the kernel so the
        # server half carries the encode/upload/dispatch/decode phases
        results = rs.solve(
            pools, ibp, pods, options=SchedulerOptions(tpu_min_pods=0)
        )
        assert rs.last_used == "sidecar"
        assert results.new_node_claims
    finally:
        srv.stop()
    wired: dict[str, list] = {}
    for t in tracing.RING.snapshot():
        if t.trace_id.startswith("w"):
            wired.setdefault(t.trace_id, []).append(t)
    pairs = [v for v in wired.values() if len(v) == 2]
    assert len(pairs) == 1, f"expected one joined trace, got {wired}"
    halves = pairs[0]
    sides = {t.side for t in halves}
    assert sides == {"local", "server"}
    client = next(t for t in halves if t.side == "local")
    server = next(t for t in halves if t.side == "server")
    assert client.outcome == "ok" and server.outcome == "ok"
    client_names = {s.name for s in client.spans}
    server_names = {s.name for s in server.spans}
    assert {"sidecar", "wire_encode", "wire_roundtrip", "wire_decode"} <= client_names
    assert {
        "wire_decode_request", "encode", "upload", "dispatch", "decode",
        "wire_encode_result",
    } <= server_names
    assert server.attrs.get("used_tpu") is True
    assert server.counts.get("dispatches", 0) >= 1
    assert server.counts.get("upload_bytes", 0) > 0


@pytest.mark.faults
@pytest.mark.hard_timeout(120)
def test_mid_prewarm_oracle_degrade_recorded():
    """The oracle-degrade path: a solve hitting a mid-prewarm sidecar is
    served by the oracle fallback, and the degrade is a span on the
    server-side trace plus the labeled counter."""
    tracing.RING.clear()
    release = threading.Event()
    path = tempfile.mktemp(suffix=".sock")
    srv = SolverServer(
        path, prewarm=True, prewarm_fn=lambda stop: release.wait(30)
    )
    srv.start()
    try:
        before = tracing.SOLVE_FALLBACKS.value({"reason": "prewarm_degraded"})
        rs = ResilientSolver(socket_path=path)
        pools, ibp, pods = _problem(4)
        results = rs.solve(pools, ibp, pods)
        assert rs.last_used == "sidecar"
        assert (
            tracing.SOLVE_FALLBACKS.value({"reason": "prewarm_degraded"})
            == before + 1
        )
        server = next(
            t for t in tracing.RING.snapshot() if t.side == "server"
        )
        fallbacks = [s for s in server.spans if s.name == "oracle_fallback"]
        assert any(
            s.attrs.get("reason") == "prewarm_degraded" for s in fallbacks
        )
        assert server.attrs.get("used_tpu") is False
    finally:
        release.set()
        srv.stop()


@pytest.mark.faults
@pytest.mark.hard_timeout(120)
def test_dead_sidecar_degrade_keeps_one_trace():
    """Sidecar unreachable: the trace stays one-sided (no server half),
    records the sidecar_failed marker, and the in-process fallback's
    phases land on the SAME trace."""
    tracing.RING.clear()
    rs = ResilientSolver(
        socket_path=tempfile.mktemp(suffix=".sock"),
        request_timeout_seconds=0.5,
    )
    rs.client.max_retries = 0
    pools, ibp, pods = _problem(4)
    results = rs.solve(pools, ibp, pods)
    assert rs.last_used in ("tpu", "oracle")
    traces = tracing.RING.snapshot()
    assert len(traces) == 1
    tr = traces[0]
    names = [s.name for s in tr.spans]
    assert "sidecar_failed" in names
    assert "oracle" in names or "dispatch" in names  # in-process floor ran
    assert tr.attrs.get("solver") == rs.last_used


# ---------------------------------------------------------------------------
# provisioning round trace


def test_provisioner_round_lands_one_trace():
    from karpenter_tpu.controllers.kube import FakeClock
    from karpenter_tpu.controllers.operator import Operator

    tracing.RING.clear()
    op = Operator(clock=FakeClock(), force_oracle=True)
    op.kube.create("NodePool", fixtures.node_pool(name="default"))
    fixtures.reset_rng(5)
    for p in fixtures.make_generic_pods(3):
        op.kube.create("Pod", p)
    op.step(2.0)
    op.step(2.0)
    op.stop()
    rounds = [t for t in tracing.RING.snapshot() if t.kind == "provisioning"]
    assert rounds, "provisioning reconcile produced no trace"
    tr = rounds[-1]
    assert tr.outcome == "ok"
    names = {s.name for s in tr.spans}
    assert "build_inputs" in names and "topology" in names
    assert tr.attrs.get("solver") == "oracle"
    assert tr.attrs.get("pods") == 3


# ---------------------------------------------------------------------------
# metric catalog drift (satellite): every registration appears in
# docs/observability.md, and the doc names nothing unregistered


def _registered_metric_names() -> set[str]:
    names: set[str] = set()
    for root, dirs, files in os.walk(os.path.join(REPO_ROOT, "karpenter_tpu")):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for fn in files:
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(root, fn), encoding="utf-8") as f:
                tree = ast.parse(f.read())
            for node in ast.walk(tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("counter", "gauge", "histogram")
                ):
                    continue
                recv = node.func.value
                if not (
                    (isinstance(recv, ast.Name) and recv.id == "REGISTRY")
                    or (
                        isinstance(recv, ast.Attribute)
                        and recv.attr == "REGISTRY"
                    )
                ):
                    continue
                if node.args and isinstance(node.args[0], ast.Constant):
                    names.add(node.args[0].value)
    return names


def test_metric_catalog_drift():
    registered = _registered_metric_names()
    assert registered, "source scan found no registrations"
    with open(os.path.join(REPO_ROOT, "docs", "observability.md")) as f:
        doc = f.read()
    documented = set(re.findall(r"`(karpenter_[a-zA-Z0-9_:]+)`", doc))
    missing = registered - documented
    assert not missing, (
        f"metrics registered but absent from docs/observability.md: "
        f"{sorted(missing)}"
    )
    phantom = documented - registered
    assert not phantom, (
        f"docs/observability.md catalogs metrics no source registers: "
        f"{sorted(phantom)}"
    )


# ---------------------------------------------------------------------------
# ISSUE 15: 404 JSON bodies, ring gauge, /debug/programs, kernel odometers


def test_debug_solves_404_is_json_for_unknown_and_garbage_ids():
    """Satellite: /debug/solves/<id> answers a machine-readable JSON 404
    for unknown AND garbage ids — the content type never depends on
    whether the lookup hit."""
    tracing.RING.clear()
    srv = ProbeServer(None, None)
    srv.start()
    try:
        for ident in ("nosuch", "w999999", "../../etc", "a%20b", "", "9" * 64):
            code, body = _get(srv, f"/debug/solves/{ident}")
            assert code == 404, (ident, code)
            got = json.loads(body)  # must parse as JSON
            assert got["error"]
            assert "id" in got
    finally:
        srv.stop()


def test_trace_ring_occupancy_gauge():
    """karpenter_trace_ring_traces tracks ring membership, pegging at
    capacity when eviction starts — a saturated 128-trace ring is
    visible instead of silently rotating."""
    tracing.RING.clear()
    assert tracing.RING_TRACES.value() == 0.0
    for i in range(3):
        t = tracing.new_trace("unit")
        t.finish()
    assert tracing.RING_TRACES.value() == 3.0
    for _ in range(tracing.RING_CAPACITY + 10):
        tracing.new_trace("unit").finish()
    assert tracing.RING_TRACES.value() == float(tracing.RING_CAPACITY)
    tracing.RING.clear()
    assert tracing.RING_TRACES.value() == 0.0


def test_debug_programs_serves_cost_catalog(monkeypatch, tmp_path):
    """/debug/programs serves the AOT manifest's cost catalog: combos
    with signature, compile seconds, and the cost/memory analysis blocks
    captured at compile time."""
    from karpenter_tpu.solver import aot

    manifest = {
        "version": aot.MANIFEST_VERSION,
        "jax": "0.0-test",
        "backend": "cpu",
        "combos": {
            "solve_scan[relax=False]@P=64,N=64": {
                "signature": [["pods", 64]],
                "seconds": 1.25,
                "cost": {"flops": 123456.0, "bytes_accessed": 789.0},
                "memory": {"argument_size_in_bytes": 4096,
                           "temp_size_in_bytes": 512},
            }
        },
    }
    with open(tmp_path / aot.MANIFEST_NAME, "w") as f:
        json.dump(manifest, f)
    from karpenter_tpu import jaxsetup

    monkeypatch.setattr(
        jaxsetup, "ensure_compilation_cache", lambda: str(tmp_path)
    )
    srv = ProbeServer(None, None)
    srv.start()
    try:
        code, body = _get(srv, "/debug/programs")
        assert code == 200
        got = json.loads(body)
        combo = got["programs"]["solve_scan[relax=False]@P=64,N=64"]
        assert combo["cost"]["flops"] == 123456.0
        assert combo["memory"]["argument_size_in_bytes"] == 4096
    finally:
        srv.stop()


def test_program_catalog_reads_manifest_directly(tmp_path):
    from karpenter_tpu.solver import aot

    manifest = {
        "version": aot.MANIFEST_VERSION,
        "jax": "0.0-test",
        "backend": "cpu",
        "combos": {"e@P=1": {"signature": [], "seconds": 0.1,
                             "cost": {}, "memory": {}}},
    }
    with open(tmp_path / aot.MANIFEST_NAME, "w") as f:
        json.dump(manifest, f)
    got = aot.program_catalog(str(tmp_path))
    assert got["backend"] == "cpu"
    assert "e@P=1" in got["programs"]
    # an empty/corrupt cache dir reads as an empty catalog, never raises
    empty = aot.program_catalog(str(tmp_path / "nope"))
    assert empty["programs"] == {}


def test_dispatch_spans_carry_kernel_odometer_block():
    """Tentpole: every solve dispatch span carries a `kernel` detail
    block with the fetched odometer, and the trace counts record the
    total — /debug/solves waterfalls show device work, not just host
    time."""
    from karpenter_tpu.solver.topology import Topology
    from karpenter_tpu.solver.tpu import TpuScheduler

    tracing.RING.clear()
    fixtures.reset_rng(11)
    its = construct_instance_types(sizes=[2, 8])
    pools = [fixtures.node_pool(name="default")]
    pods = fixtures.make_diverse_pods(24)
    topo = Topology(pools, {"default": its}, pods)
    sched = TpuScheduler(pools, {"default": its}, topo)
    sched.solve(pods)
    tr = sched.last_profile
    dispatch_spans = [s for s in tr.spans if s.name == "dispatch"]
    assert dispatch_spans, [s.name for s in tr.spans]
    blocks = [s.attrs.get("kernel") for s in dispatch_spans]
    assert all(b is not None for b in blocks), blocks
    assert sum(b["steps"] for b in blocks) == sched.last_odometer["steps"]
    assert tr.counts.get("kernel_iterations") == sched.last_odometer["steps"]


def test_kernel_metrics_accumulate_on_solve():
    from karpenter_tpu.solver.topology import Topology
    from karpenter_tpu.solver.tpu import TpuScheduler

    fixtures.reset_rng(11)
    its = construct_instance_types(sizes=[2, 8])
    pools = [fixtures.node_pool(name="default")]
    pods = fixtures.make_diverse_pods(24)
    topo = Topology(pools, {"default": its}, pods)
    sched = TpuScheduler(pools, {"default": its}, topo)
    path_before = {
        p: tracing.KERNEL_ITERATIONS.value({"path": p})
        for p in ("runs", "scan")
    }
    claims_before = tracing.KERNEL_CLAIMS_OPENED.value()
    occ_before = tracing.KERNEL_CLAIM_OCCUPANCY.count()
    sched.solve(pods)
    path = "runs" if sched.last_used_runs else "scan"
    got = tracing.KERNEL_ITERATIONS.value({"path": path}) - path_before[path]
    assert got == sched.last_odometer["steps"] > 0
    assert (
        tracing.KERNEL_CLAIMS_OPENED.value() - claims_before
        == sched.last_odometer["claims_opened"]
    )
    assert tracing.KERNEL_CLAIM_OCCUPANCY.count() == occ_before + 1
    lint_prometheus(metrics.REGISTRY.render())


def test_admission_ewma_and_table_cache_wait_metrics():
    """Satellite: the AdmissionGate EWMA and the DeviceTableCache
    single-flight wait are exported (and survive the exposition lint)."""
    from karpenter_tpu.solver import epochs

    gate = epochs.AdmissionGate(max_inflight=2)
    gate.observe(0.5)
    assert epochs.ADMISSION_EWMA.value() == pytest.approx(0.5)
    gate.observe(1.0)
    assert epochs.ADMISSION_EWMA.value() == pytest.approx(0.6)

    cache = epochs.DeviceTableCache()
    waits_before = epochs.TABLE_CACHE_WAIT.count()
    tb0, token = cache.begin_tables("fp1")
    assert tb0 is None and token == "fp1"
    got: list = []

    def waiter():
        tb, tok = cache.begin_tables("fp1")
        got.append((tb, tok))

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    time.sleep(0.15)
    cache.end_tables(token, {"tables": True})
    t.join(timeout=10)
    assert got and got[0][0] == {"tables": True} and got[0][1] is None
    assert epochs.TABLE_CACHE_WAIT.count() == waits_before + 1
    # the waiter's blocked time is at least the builder's hold time
    assert epochs.TABLE_CACHE_WAIT.sum() >= 0.1
    lint_prometheus(metrics.REGISTRY.render())
