"""graftlint protocol tier gate (analysis/proto.py + analysis/protorec.py):
corpus replay FIRST (every pinned counterexample still reproduces its
violation), canonical-dedup and BFS-shortest properties of the explorer,
the broken-knob matrix (each deliberately-broken model finds exactly its
property; the real knobs stay clean), model-trace refinement in both
directions, the refinement acceptors on hand-built traces, the two live
conformance scenarios, the recorder's zero-disabled-cost contract, the
CLI exit codes, and the five-tier `--all --jobs` merge.

The module-scoped `report` fixture does the expensive work once: the
full five-scenario exploration plus both live scenarios — the same run
`graftlint --proto` performs. Everything else is doctored-input unit
tests on the model, the acceptors, and the CLI plumbing.
"""

from __future__ import annotations

import ast
import dataclasses
import glob
import inspect
import json
import os
import time
from collections import deque

import pytest

from karpenter_tpu.analysis import proto, protorec
from karpenter_tpu.analysis.__main__ import main as graftlint_main
from karpenter_tpu.analysis.engine import Finding

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CORPUS_DIR = os.path.join(REPO_ROOT, "tests", "proto_corpus")
CORPUS_FILES = sorted(glob.glob(os.path.join(CORPUS_DIR, "*.json")))


def _cfg(scenario_name: str, knobs: proto.Knobs) -> proto.Config:
    scn = next(s for s in proto.SCENARIOS if s.name == scenario_name)
    return proto.Config(knobs, scn)


# ---------------------------------------------------------------------------
# corpus replay — FIRST: a pinned counterexample that stops reproducing
# means the model (or the property) drifted from what the corpus froze


@pytest.mark.parametrize(
    "path", CORPUS_FILES, ids=[os.path.basename(p) for p in CORPUS_FILES]
)
def test_corpus_case_still_violates(path):
    with open(path, encoding="utf-8") as fh:
        case = json.load(fh)
    assert case["rule"] in proto.replay_corpus_case(case), (
        f"{os.path.basename(path)}: the pinned schedule no longer "
        f"violates {case['rule']} — the model drifted from the corpus"
    )
    # the filename IS the (rule, scenario) key the emitter writes
    assert os.path.basename(path) == f"{case['rule']}__{case['scenario']}.json"
    assert case["repro"] == proto.REPRO_HINT


def test_corpus_covers_every_broken_knob():
    pinned = {os.path.basename(p).split("__")[0] for p in CORPUS_FILES}
    assert pinned == set(proto.BROKEN_KNOBS), (
        "every property's broken-knob counterexample must be pinned in "
        "tests/proto_corpus/ (regenerate with proto.emit_counterexample)"
    )


def test_corpus_serialization_is_canonical(tmp_path):
    """Re-emitting a pinned case is byte-identical: sorted keys, indent
    2, trailing newline — so corpus churn in review is always a real
    schedule change, never serializer noise."""
    for path in CORPUS_FILES:
        with open(path, encoding="utf-8") as fh:
            case = json.load(fh)
        ce = proto.Counterexample(
            rule=case["rule"],
            scenario=case["scenario"],
            knobs=proto.Knobs(**case["knobs"]),
            schedule=case["schedule"],
            message=case["message"],
        )
        out = proto.emit_counterexample(ce, str(tmp_path))
        with open(out, "rb") as fh_new, open(path, "rb") as fh_old:
            assert fh_new.read() == fh_old.read(), os.path.basename(path)


# ---------------------------------------------------------------------------
# the full tier run (module-scoped: the gate `graftlint --proto` enforces)


@pytest.fixture(scope="module")
def report():
    t0 = time.monotonic()
    rep = proto.run_proto_analysis(REPO_ROOT)
    rep["_wall_seconds"] = time.monotonic() - t0
    return rep


def test_full_run_clean(report):
    assert report["errors"] == []
    assert [f.render() for f in report["findings"]] == []
    assert report["stale"] == []
    assert report["unjustified"] == []
    assert all(v == "ok" for v in report["properties"].values()), report[
        "properties"
    ]


def test_report_budgets_never_silent(report):
    """Every scenario's exploration budgets ride the report (ISSUE: a
    truncated exploration must be visible, not silent), and the verdict
    table names every property."""
    assert set(report["scenarios"]) == {s.name for s in proto.SCENARIOS}
    for name, scn in report["scenarios"].items():
        assert set(scn) == {
            "states",
            "truncated",
            "seconds",
            "n_solves",
            "fault_budget",
            "max_ticks",
            "max_states",
        }, name
        assert scn["states"] > 0
        assert scn["states"] <= scn["max_states"]
    assert set(report["properties"]) == set(proto.PROTO_RULES)


def test_live_scenarios_ran_and_recorded(report):
    assert set(report["conformance"]) == {"live_breaker_retry", "live_drain"}
    for name, n_events in report["conformance"].items():
        assert n_events > 0, name


def test_tier_fits_one_core_budget(report):
    """ISSUE budget: the whole tier — five explorations plus both live
    scenarios — stays under 60s on the 1-core box so it can ride
    pre-commit and --all."""
    assert report["_wall_seconds"] < 60.0, report["scenarios"]


# ---------------------------------------------------------------------------
# canonical dedup


def test_canonical_renumbers_epoch_labels():
    """States differing only in which concrete epoch ids the run handed
    out dedup to one BFS node."""
    a = proto.World(acked_e=5, se=5, c2s=(("SOLVE", True, 5, 1),))
    b = proto.World(acked_e=9, se=9, c2s=(("SOLVE", True, 9, 1),))
    assert proto.canonical(a) == proto.canonical(b)


def test_canonical_keeps_epoch_relationships():
    """Renumbering is order-of-first-occurrence, not erasure: a client
    acked on a DIFFERENT epoch than the server stored must not collapse
    into the agreeing state."""
    agree = proto.World(acked_e=5, se=5)
    differ = proto.World(acked_e=5, se=7)
    assert proto.canonical(agree) != proto.canonical(differ)


def test_canonical_distinguishes_structure():
    assert proto.canonical(proto.World(phase="wait")) != proto.canonical(
        proto.World(phase="idle")
    )
    assert proto.canonical(proto.World()) == proto.canonical(proto.World())


# ---------------------------------------------------------------------------
# BFS shortest counterexample + shrink minimality


def _violating_schedules_up_to(cfg, rule, depth):
    """Every schedule of length <= depth whose replay violates `rule`
    (exhaustive DFS over enabled labels; only used at tiny depths)."""
    found = []

    def walk(w, path):
        if path:
            _, viols = proto.replay(cfg, path)
            if any(r == rule for r, _ in viols):
                found.append(list(path))
                return
        if len(path) >= depth:
            return
        for lab, w2, _ in proto.step(cfg, w):
            walk(w2, path + [lab])

    walk(proto.initial_world(cfg.scenario), [])
    return found


def test_bfs_returns_a_shortest_counterexample():
    """BFS order + one-label transitions means the first counterexample
    per property is a shortest one; exhaustive search at smaller depths
    confirms nothing shorter exists."""
    scn_name, knobs = proto.BROKEN_KNOBS["proto-converge"]
    cfg = _cfg(scn_name, knobs)
    res = proto.explore(cfg, stop_on_first=True)
    ce = next(c for c in res.counterexamples if c.rule == "proto-converge")
    ce = proto.shrink(cfg, ce)
    _, viols = proto.replay(cfg, ce.schedule)
    assert any(r == "proto-converge" for r, _ in viols)
    assert not _violating_schedules_up_to(
        cfg, "proto-converge", len(ce.schedule) - 1
    ), "a shorter schedule violates: BFS did not return a shortest path"


def test_shrink_result_is_one_minimal():
    """Greedy shrink's contract: dropping ANY single remaining label
    loses the violation."""
    scn_name, knobs = proto.BROKEN_KNOBS["proto-drain-bounded"]
    cfg = _cfg(scn_name, knobs)
    res = proto.explore(cfg, stop_on_first=True)
    ce = proto.shrink(
        cfg,
        next(c for c in res.counterexamples if c.rule == "proto-drain-bounded"),
    )
    for i in range(len(ce.schedule)):
        candidate = ce.schedule[:i] + ce.schedule[i + 1 :]
        _, viols = proto.replay(cfg, candidate)
        assert not any(r == ce.rule for r, _ in viols), (
            f"dropping step {i} ({ce.schedule[i]}) still violates — "
            "shrink returned a non-minimal schedule"
        )


# ---------------------------------------------------------------------------
# the broken-knob matrix: each pinned review fix, reverted in the MODEL,
# is found by the checker (positive); the real knobs stay clean (negative)


@pytest.mark.parametrize("rule", sorted(proto.BROKEN_KNOBS))
def test_broken_knob_finds_its_property(rule):
    scn_name, knobs = proto.BROKEN_KNOBS[rule]
    assert knobs != proto.REAL_KNOBS
    cfg = _cfg(scn_name, knobs)
    res = proto.explore(cfg, stop_on_first=True)
    ces = [c for c in res.counterexamples if c.rule == rule]
    assert ces, (
        f"{rule}: the deliberately-broken model found no counterexample "
        f"in scenario {scn_name!r}"
    )
    shrunk = proto.shrink(cfg, ces[0])
    _, viols = proto.replay(cfg, shrunk.schedule)
    assert any(r == rule for r, _ in viols)


def test_real_knobs_clean_on_quick_scenarios():
    """The negative half on the two fastest scenarios (the full
    five-scenario clean run is the module `report` fixture)."""
    for scn_name in ("steady", "drain"):
        res = proto.explore(_cfg(scn_name, proto.REAL_KNOBS))
        assert res.counterexamples == [], scn_name


def test_tick_budget_is_truncation_not_deadlock():
    """A state blocked only by the max_ticks budget is the exploration
    bound biting — reported as truncation, never a phantom converge
    violation (the same discrimination replay applies)."""
    scn = dataclasses.replace(
        next(s for s in proto.SCENARIOS if s.name == "steady"), max_ticks=1
    )
    cfg = proto.Config(proto.REAL_KNOBS, scn)
    res = proto.explore(cfg)
    assert res.truncated
    assert not any(
        c.rule == "proto-converge" for c in res.counterexamples
    ), "tick-budget exhaustion was misreported as a protocol deadlock"


# ---------------------------------------------------------------------------
# wire-kind and channel-fault parity with the real stack


def test_kind_table_matches_service():
    """proto.py duplicates the wire kinds (service.py imports numpy and
    the model must stay stdlib-only): the two tables must never drift."""
    from karpenter_tpu.solver import service

    for name in (
        "KIND_SOLVE",
        "KIND_RESULT",
        "KIND_ERROR",
        "KIND_PING",
        "KIND_PONG",
        "KIND_SOLVE_DELTA",
        "KIND_EPOCH_RESYNC",
        "KIND_RETRY",
    ):
        assert getattr(proto, name) == getattr(service, name), name


def test_channel_faults_mirror_fault_proxy_modes():
    """Every byte-level fault the FaultyProxy can inject has a model
    transition with the same observable effect, so the explorer covers
    (at least) the fault vocabulary the live suite soaks."""
    from karpenter_tpu.testing import faults

    step_src = inspect.getsource(proto.step)
    # proxy mode -> the model label family with the same client-visible
    # effect (blackhole swallows the request; truncate/corrupt both
    # poison framing beyond recovery; delay is pure elapsed time)
    for mode, label in {
        "blackhole": '"f_drop_c2s"',
        "truncate": '"f_trunc_s2c"',
        "corrupt": '"f_trunc_s2c"',
        "delay": '"tick"',
    }.items():
        assert mode in faults.FaultyProxy.__doc__, mode
        assert label in step_src, (mode, label)


def test_channel_fault_semantics():
    """The fault transitions do what their labels claim on the channel
    tuples (head drop, head duplicate, head poisoned to JUNK)."""
    cfg = _cfg("steady", proto.REAL_KNOBS)
    w = proto.World(
        phase="wait",
        sent="snap",
        conn=True,
        c2s=(("SOLVE", True, 1, 1),),
        s2c=(("RESULT", True, 1, 1),),
    )
    succs = {lab: w2 for lab, w2, _ in proto.step(cfg, w)}
    assert succs["f_drop_c2s"].c2s == ()
    assert succs["f_drop_s2c"].s2c == ()
    assert succs["f_dup_s2c"].s2c == (w.s2c[0], w.s2c[0])
    assert succs["f_trunc_s2c"].s2c[0][0] == "JUNK"
    for lab in ("f_drop_c2s", "f_drop_s2c", "f_dup_s2c", "f_trunc_s2c"):
        assert succs[lab].faults == w.faults + 1, lab


def test_fault_budget_gates_the_adversary():
    cfg = _cfg("steady", proto.REAL_KNOBS)
    spent = proto.World(
        phase="wait",
        sent="snap",
        conn=True,
        c2s=(("SOLVE", True, 1, 1),),
        faults=cfg.scenario.fault_budget,
    )
    labels = {lab for lab, _, _ in proto.step(cfg, spent)}
    assert not any(lab.startswith("f_") for lab in labels)


# ---------------------------------------------------------------------------
# refinement: model traces through the SAME acceptors as recorded traces


def test_model_done_trace_refines():
    """Soundness half: a real-knob model run to completion emits a trace
    the acceptors accept (else conformance findings could be acceptor
    bugs rather than code bugs)."""
    scn = proto.Scenario(
        "mini", n_solves=2, faults=("drop_s2c",), fault_budget=1, max_ticks=8
    )
    cfg = proto.Config(proto.REAL_KNOBS, scn)
    w0 = proto.initial_world(scn)
    seen = {proto.canonical(w0)}
    frontier = deque([(w0, [])])
    schedule = None
    while frontier:
        w, path = frontier.popleft()
        if proto.done(cfg, w):
            schedule = path
            break
        for lab, w2, _ in proto.step(cfg, w):
            k = proto.canonical(w2)
            if k not in seen:
                seen.add(k)
                frontier.append((w2, path + [lab]))
    assert schedule is not None
    events = proto.trace_of(cfg, schedule)
    assert events, "a completed solve emits protocol events"
    assert proto.check_refinement(events) == []


def test_broken_model_trace_fails_refinement():
    """Completeness half: the pinned broken-knob schedules, traced
    through the emitter, are REJECTED by the acceptors — the same
    machinery that judges recorded real traces catches the modeled
    regressions."""
    for rule in ("proto-breaker-wedge", "proto-drain-bounded"):
        path = os.path.join(CORPUS_DIR, f"{rule}__*.json")
        (corpus_file,) = glob.glob(path)
        with open(corpus_file, encoding="utf-8") as fh:
            case = json.load(fh)
        cfg = _cfg(case["scenario"], proto.Knobs(**case["knobs"]))
        events = proto.trace_of(cfg, case["schedule"])
        assert proto.check_refinement(events) != [], rule


# ---------------------------------------------------------------------------
# the acceptors on hand-built traces (one per pinned contract)


def test_acceptor_stranded_probe():
    events = [
        {
            "ev": "breaker_allow",
            "i": 0,
            "thread": 1,
            "granted": True,
            "probe": True,
            "state": "half-open",
            "failures": 2,
            "threshold": 2,
        },
        {
            "ev": "attempt",
            "i": 1,
            "thread": 1,
            "outcome": "overloaded",
            "breaker": "half",
        },
    ]
    viols = proto.check_refinement(events)
    assert any("STRANDED" in v for v in viols), viols


def test_acceptor_probe_resolved_is_clean():
    events = [
        {
            "ev": "breaker_allow",
            "i": 0,
            "thread": 1,
            "granted": True,
            "probe": True,
            "state": "half-open",
            "failures": 2,
            "threshold": 2,
        },
        {
            "ev": "breaker_success",
            "i": 1,
            "thread": 1,
            "prev": "half-open",
            "state": "closed",
            "failures": 0,
            "threshold": 2,
        },
        {
            "ev": "attempt",
            "i": 2,
            "thread": 1,
            "outcome": "overloaded",
            "breaker": "closed",
        },
    ]
    assert proto.check_refinement(events) == []


def test_acceptor_silent_drain_close():
    events = [
        {
            "ev": "srv_recv",
            "i": 0,
            "thread": 2,
            "conn": 0,
            "kind": proto.KIND_SOLVE,
            "draining": True,
        },
        {"ev": "srv_close", "i": 1, "thread": 2, "conn": 0, "draining": True},
    ]
    viols = proto.check_refinement(events)
    assert any("silent close" in v for v in viols), viols


def test_acceptor_one_refusal_then_close_is_clean():
    events = [
        {
            "ev": "srv_recv",
            "i": 0,
            "thread": 2,
            "conn": 0,
            "kind": proto.KIND_SOLVE,
            "draining": True,
        },
        {
            "ev": "srv_send",
            "i": 1,
            "thread": 2,
            "conn": 0,
            "kind": proto.KIND_RETRY,
            "draining": True,
            "refusal": True,
        },
        {"ev": "srv_close", "i": 2, "thread": 2, "conn": 0, "draining": True},
    ]
    assert proto.check_refinement(events) == []


def test_acceptor_second_refusal():
    recv = {
        "ev": "srv_recv",
        "thread": 2,
        "conn": 0,
        "kind": proto.KIND_SOLVE,
        "draining": True,
    }
    send = {
        "ev": "srv_send",
        "thread": 2,
        "conn": 0,
        "kind": proto.KIND_RETRY,
        "draining": True,
        "refusal": True,
    }
    events = [dict(recv, i=0), dict(send, i=1), dict(recv, i=2), dict(send, i=3)]
    viols = proto.check_refinement(events)
    assert any("second refusal" in v for v in viols), viols


def test_acceptor_commit_requires_store():
    orphan = [
        {
            "ev": "cli_epoch_commit",
            "i": 0,
            "thread": 1,
            "client": 7,
            "epoch": 3,
            "mode": "delta",
        }
    ]
    viols = proto.check_refinement(orphan)
    assert any("never stored" in v for v in viols), viols
    stored_first = [
        {
            "ev": "srv_epoch_store",
            "i": 0,
            "thread": 2,
            "client": 7,
            "epoch": 3,
        },
        {
            "ev": "cli_epoch_commit",
            "i": 1,
            "thread": 1,
            "client": 7,
            "epoch": 3,
            "mode": "delta",
        },
    ]
    assert proto.check_refinement(stored_first) == []


def test_acceptor_store_after_commit_is_the_ordering_revert():
    """The store-before-answer fix: a store that lands AFTER the commit
    riding its answer is the reverted ordering, even though the store
    eventually exists."""
    events = [
        {
            "ev": "cli_epoch_commit",
            "i": 0,
            "thread": 1,
            "client": 7,
            "epoch": 3,
            "mode": "snapshot",
        },
        {
            "ev": "srv_epoch_store",
            "i": 1,
            "thread": 2,
            "client": 7,
            "epoch": 3,
        },
    ]
    viols = proto.check_refinement(events)
    assert any("AFTER" in v for v in viols), viols


def test_acceptor_pre_epoch_snapshot_commit_is_the_fiction():
    """Mixed-version rollout: a pre-epoch server ignores the epoch key
    on snapshots, so a snapshot-mode commit with NO store at all is the
    deliberate client-side fiction (service.py pre-epoch branch) — the
    first delta's 'unknown kind' downgrade corrects it. Accepted; a
    delta-mode commit with no store stays a violation."""
    events = [
        {
            "ev": "cli_epoch_commit",
            "i": 0,
            "thread": 1,
            "client": 7,
            "epoch": 1,
            "mode": "snapshot",
        }
    ]
    assert proto.check_refinement(events) == []


def test_acceptor_snapshot_never_answered_resync():
    events = [
        {
            "ev": "cli_roundtrip",
            "i": 0,
            "thread": 1,
            "client": 7,
            "kind": proto.KIND_SOLVE,
            "resp_kind": proto.KIND_EPOCH_RESYNC,
            "req_id": 1,
        }
    ]
    viols = proto.check_refinement(events)
    assert any("no fallback" in v for v in viols), viols


def test_acceptor_resync_forces_full_snapshot_next():
    events = [
        {
            "ev": "cli_roundtrip",
            "i": 0,
            "thread": 1,
            "client": 7,
            "kind": proto.KIND_SOLVE_DELTA,
            "resp_kind": proto.KIND_EPOCH_RESYNC,
            "req_id": 1,
        },
        {
            "ev": "cli_roundtrip",
            "i": 1,
            "thread": 1,
            "client": 7,
            "kind": proto.KIND_SOLVE_DELTA,
            "resp_kind": proto.KIND_RESULT,
            "req_id": 2,
        },
    ]
    viols = proto.check_refinement(events)
    assert any("must be" in v and "snapshot" in v for v in viols), viols


def test_shrink_trace_keeps_only_the_implicated_stream():
    """The conformance repro in a finding is the few frames that matter:
    an unrelated healthy connection's events are dropped from the
    minimal sub-trace."""
    noise = [
        {
            "ev": "srv_recv",
            "i": 0,
            "thread": 9,
            "conn": 5,
            "kind": proto.KIND_PING,
            "draining": False,
        },
        {
            "ev": "srv_send",
            "i": 1,
            "thread": 9,
            "conn": 5,
            "kind": proto.KIND_PONG,
            "draining": False,
        },
    ]
    bad = [
        {
            "ev": "srv_recv",
            "i": 2,
            "thread": 2,
            "conn": 9,
            "kind": proto.KIND_SOLVE,
            "draining": True,
        },
        {"ev": "srv_close", "i": 3, "thread": 2, "conn": 9, "draining": True},
    ]
    events = noise + bad
    (violation,) = proto.check_refinement(events)
    sub = proto.shrink_trace(events, violation)
    assert sub == bad
    assert violation in proto.check_refinement(sub)


# ---------------------------------------------------------------------------
# live conformance scenarios (named so the findings' repro hints select
# them: `pytest tests/test_proto_analysis.py -k live_breaker_retry`)


@pytest.mark.hard_timeout(60)
def test_live_breaker_retry_trace_refines():
    """The scripted real ResilientSolver recovery story — failures trip
    the breaker, cooldown yields the half-open probe, an admission RETRY
    resolves it closed — records a trace the model accepts; deleting the
    RETRY-records-success event (what reverting the hybrid.py fix does)
    strands the probe and fails refinement."""
    events = proto.live_breaker_scenario()
    assert proto.check_refinement(events) == []
    outcomes = [e["outcome"] for e in events if e.get("ev") == "attempt"]
    assert "breaker_denied" in outcomes and "overloaded" in outcomes
    # simulated revert: drop the record_success that resolves the probe
    idx = next(
        i
        for i, e in enumerate(events)
        if e.get("ev") == "attempt" and e["outcome"] == "overloaded"
    )
    assert events[idx - 1]["ev"] == "breaker_success"
    doctored = events[: idx - 1] + events[idx:]
    assert any("STRANDED" in v for v in proto.check_refinement(doctored))


@pytest.mark.hard_timeout(60)
def test_live_drain_trace_refines():
    """The real SolverServer over raw sockets: stop() with one solve in
    flight and one arriving mid-drain — the refusal answer and the
    RESULT flush both precede their closes; deleting the refusal send
    (the service.py revert) is the silent drain close."""
    events = proto.live_drain_scenario()
    assert proto.check_refinement(events) == []
    refusals = [e for e in events if e.get("refusal")]
    assert len(refusals) == 1
    flushed = [
        e
        for e in events
        if e.get("ev") == "srv_send" and e.get("kind") == proto.KIND_RESULT
    ]
    assert flushed, "the in-flight solve's RESULT must flush during drain"
    doctored = [e for e in events if not e.get("refusal")]
    assert any(
        "silent close" in v for v in proto.check_refinement(doctored)
    )


# ---------------------------------------------------------------------------
# the recorder: zero disabled cost, and the autouse conformance fixture


def test_recorder_disabled_by_default():
    assert protorec.RECORDER is None
    assert protorec.active() is None


def test_hook_sites_guard_on_one_attribute_load():
    """Every protorec call in the serving code is inside an
    `if protorec.RECORDER is not None:` guard — the disabled cost is one
    module-attribute load and an identity test, nothing else (no dict
    building, no conn_id bookkeeping)."""

    def guard_test(node) -> bool:
        t = node.test
        return (
            isinstance(t, ast.Compare)
            and isinstance(t.ops[0], ast.IsNot)
            and ast.unparse(t.left) == "protorec.RECORDER"
        )

    for rel in ("karpenter_tpu/solver/hybrid.py", "karpenter_tpu/solver/service.py"):
        src = open(os.path.join(REPO_ROOT, rel), encoding="utf-8").read()
        tree = ast.parse(src)
        guarded_spans = [
            (n.lineno, max(x.end_lineno for x in n.body))
            for n in ast.walk(tree)
            if isinstance(n, ast.If) and guard_test(n)
        ]
        assert guarded_spans, rel
        uses = [
            n.lineno
            for n in ast.walk(tree)
            if isinstance(n, ast.Attribute)
            and ast.unparse(n).startswith("protorec.RECORDER.")
        ]
        assert uses, rel
        for line in uses:
            assert any(lo <= line <= hi for lo, hi in guarded_spans), (
                f"{rel}:{line}: protorec.RECORDER use outside the "
                "`is not None` guard — the disabled path must stay free"
            )


def test_disabled_hook_cost_micro_assert():
    """The pinned micro-assert from the protorec docstring: the disabled
    hook predicate averages well under 5µs/call on the 1-core box (real
    cost is tens of ns; the generous bound only catches accidental work
    on the disabled path, e.g. building the event dict eagerly)."""
    assert protorec.RECORDER is None
    n = 100_000
    t0 = time.perf_counter()
    hits = 0
    for _ in range(n):
        if protorec.RECORDER is not None:
            hits += 1  # pragma: no cover - recorder is off
    elapsed = time.perf_counter() - t0
    assert hits == 0
    assert elapsed / n < 5e-6, f"{elapsed / n * 1e9:.0f}ns per disabled hook"


def test_recorder_conn_ids_never_alias():
    rec = protorec.TraceRecorder()

    class Sock:
        pass

    a = Sock()
    ida = rec.conn_id(a)
    assert rec.conn_id(a) == ida  # stable while live
    assert rec.conn_closed(a) == ida
    b = Sock()  # may land on the recycled id() address
    assert rec.conn_id(b) != ida or id(b) != id(a)
    # the guarantee under recycling: a closed conn's id is retired
    assert rec.conn_id(b) == rec.conn_id(b)


@pytest.mark.proto
def test_proto_marker_installs_recorder_and_checks(request):
    """The satellite-2 end-to-end: `@pytest.mark.proto` (and every
    `faults` test) runs with a live recorder installed by the conftest
    fixture, and the teardown refinement check judges what we record
    here — a legal closed-breaker cycle."""
    assert protorec.RECORDER is not None, (
        "tests/conftest.py _proto_conformance must install a recorder "
        "for proto-marked tests"
    )
    from karpenter_tpu.solver.hybrid import CircuitBreaker

    br = CircuitBreaker(failure_threshold=2, cooldown_seconds=10.0)
    assert br.allow()
    br.record_success()
    evs = [e["ev"] for e in protorec.RECORDER.snapshot()]
    assert "breaker_allow" in evs and "breaker_success" in evs
    # teardown now runs check_refinement over exactly these events


# ---------------------------------------------------------------------------
# CLI: exit codes, flag discipline, and the five-tier --all merge


def _fake_report(findings=(), errors=(), stale=(), unjustified=()):
    return {
        "findings": list(findings),
        "all_findings": list(findings),
        "stale": list(stale),
        "unjustified": list(unjustified),
        "errors": list(errors),
        "total": len(findings),
        "scenarios": {
            "steady": {
                "states": 11,
                "truncated": False,
                "seconds": 0.1,
                "n_solves": 3,
                "fault_budget": 1,
                "max_ticks": 10,
                "max_states": 200_000,
            }
        },
        "properties": {r: "ok" for r in proto.PROTO_RULES},
        "conformance": {"live_breaker_retry": 14, "live_drain": 8},
    }


_FINDING = Finding(
    rule="proto-conformance",
    path="karpenter_tpu/solver/hybrid.py",
    line=1,
    message="doctored",
    text="live_breaker_retry:doctored",
)


def test_cli_proto_exit_codes(monkeypatch, capsys):
    monkeypatch.setattr(
        proto, "run_proto_analysis", lambda *a, **k: _fake_report()
    )
    assert graftlint_main(["--proto", "--root", REPO_ROOT, "--json"]) == 0
    data = json.loads(capsys.readouterr().out)
    # the budgets and verdicts ride the JSON payload (never silent)
    assert data["scenarios"]["steady"]["max_states"] == 200_000
    assert data["properties"]["proto-converge"] == "ok"
    assert data["conformance"]["live_drain"] == 8

    monkeypatch.setattr(
        proto,
        "run_proto_analysis",
        lambda *a, **k: _fake_report(findings=[_FINDING]),
    )
    assert graftlint_main(["--proto", "--root", REPO_ROOT]) == 1
    assert "proto-conformance" in capsys.readouterr().out

    monkeypatch.setattr(
        proto,
        "run_proto_analysis",
        lambda *a, **k: _fake_report(errors=["live_drain: died"]),
    )
    assert graftlint_main(["--proto", "--root", REPO_ROOT]) == 2
    assert "scenario error" in capsys.readouterr().out


def test_cli_proto_truncation_named_in_summary(monkeypatch, capsys):
    rep = _fake_report()
    rep["scenarios"]["steady"]["truncated"] = True
    monkeypatch.setattr(proto, "run_proto_analysis", lambda *a, **k: rep)
    assert graftlint_main(["--proto", "--root", REPO_ROOT]) == 0
    assert "truncated: steady" in capsys.readouterr().out


def test_cli_proto_rejects_meaningless_flags(capsys):
    assert graftlint_main(["--proto", "--root", REPO_ROOT, "x.py"]) == 2
    assert (
        graftlint_main(["--proto", "--root", REPO_ROOT, "--changed-only"]) == 2
    )
    assert (
        graftlint_main(
            ["--proto", "--root", REPO_ROOT, "--rules", "proto-converge"]
        )
        == 2
    )
    assert (
        graftlint_main(["--proto", "--root", REPO_ROOT, "--budgets", "x.json"])
        == 2
    )
    err = capsys.readouterr().err
    assert "one exploration" in err


def test_cli_proto_write_baseline_refused_on_errors(monkeypatch, capsys, tmp_path):
    monkeypatch.setattr(
        proto,
        "run_proto_analysis",
        lambda *a, **k: _fake_report(errors=["live_drain: died"]),
    )
    baseline = tmp_path / "graftlint.proto.baseline.json"
    rc = graftlint_main(
        [
            "--proto",
            "--root",
            REPO_ROOT,
            "--write-baseline",
            "--baseline",
            str(baseline),
        ]
    )
    capsys.readouterr()
    assert rc == 2
    assert not baseline.exists()


def test_cli_list_rules_shows_proto(capsys):
    assert graftlint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in proto.PROTO_RULES:
        assert rid in out
    assert "[proto]" in out


def test_cli_jobs_requires_all(capsys):
    assert graftlint_main(["--root", REPO_ROOT, "--jobs", "2"]) == 2
    assert graftlint_main(["--proto", "--root", REPO_ROOT, "--jobs", "2"]) == 2
    assert graftlint_main(["--all", "--root", REPO_ROOT, "--jobs", "0"]) == 2
    err = capsys.readouterr().err
    assert "--jobs" in err


def _stub_all_tiers(monkeypatch, proto_report=None, race_errors=()):
    import karpenter_tpu.analysis.__main__ as cli
    from karpenter_tpu.analysis import ir, locks, spmd

    flat = {
        "findings": [],
        "stale": [],
        "unjustified": [],
        "errors": [],
        "total": 0,
    }
    deep = dict(
        flat,
        all_findings=[],
        budget_unjustified=[],
        improvements=[],
        measured={},
    )
    monkeypatch.setattr(cli, "run_analysis", lambda *a, **k: dict(flat))
    monkeypatch.setattr(
        locks,
        "run_race_analysis",
        lambda *a, **k: dict(flat, errors=list(race_errors)),
    )
    monkeypatch.setattr(ir, "run_ir_analysis", lambda *a, **k: dict(deep))
    monkeypatch.setattr(spmd, "run_spmd_analysis", lambda *a, **k: dict(deep))
    monkeypatch.setattr(
        proto,
        "run_proto_analysis",
        lambda *a, **k: proto_report or _fake_report(),
    )


def test_cli_all_includes_proto_tier(monkeypatch, capsys):
    _stub_all_tiers(monkeypatch)
    rc = graftlint_main(["--all", "--root", REPO_ROOT, "--json"])
    assert rc == 0
    data = json.loads(capsys.readouterr().out)
    assert set(data) == {"ast", "race", "ir", "spmd", "proto", "exit_code"}
    assert data["proto"]["exit_code"] == 0
    assert data["proto"]["properties"]["proto-converge"] == "ok"
    assert isinstance(data["proto"]["seconds"], float)


def test_cli_all_jobs_parallel_merges_identically(monkeypatch, capsys):
    """--jobs N is a scheduling choice, not a semantic one: the merged
    payload has the same tiers, shapes, and worst exit code as the
    serial path."""
    _stub_all_tiers(monkeypatch)
    rc = graftlint_main(["--all", "--jobs", "3", "--root", REPO_ROOT, "--json"])
    assert rc == 0
    data = json.loads(capsys.readouterr().out)
    assert set(data) == {"ast", "race", "ir", "spmd", "proto", "exit_code"}
    for tier in ("ast", "race", "ir", "spmd", "proto"):
        assert data[tier]["exit_code"] == 0
        assert isinstance(data[tier]["seconds"], float)


def test_cli_all_jobs_worst_exit_propagates(monkeypatch, capsys):
    _stub_all_tiers(
        monkeypatch,
        proto_report=_fake_report(findings=[_FINDING]),
        race_errors=["parse error: doctored"],
    )
    rc = graftlint_main(["--all", "--jobs", "2", "--root", REPO_ROOT, "--json"])
    data = json.loads(capsys.readouterr().out)
    assert data["proto"]["exit_code"] == 1
    assert data["race"]["exit_code"] == 2
    assert rc == 2 and data["exit_code"] == 2


def test_cli_all_proto_crash_is_broken_gate(monkeypatch, capsys):
    _stub_all_tiers(monkeypatch)

    def boom(*a, **k):
        raise RuntimeError("live scenario wedged")

    monkeypatch.setattr(proto, "run_proto_analysis", boom)
    rc = graftlint_main(["--all", "--jobs", "2", "--root", REPO_ROOT, "--json"])
    data = json.loads(capsys.readouterr().out)
    assert rc == 2
    assert data["proto"]["exit_code"] == 2
    assert "live scenario wedged" in data["proto"]["unavailable"]
