"""Removal-set consolidation subsystem (disruption/setsweep.py).

The sequential simulator is the bit-exact referee for every proposed
removal set: the parity matrix below checks >= 100 randomized
(fleet, set) scenarios seeded from the KWOK generators, plus a pinned
scenario where only a NON-PREFIX set reaches the best savings — the
capability the prefix search (multinodeconsolidation.go:116) is
structurally blind to. Every SweepUnsupported gate gets a crafted
scenario asserting the gate fires AND the controller ladder (sets ->
batched prefixes -> binary) lands on an identical exact command.
"""

from __future__ import annotations

import math
from types import SimpleNamespace

import numpy as np
import pytest

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import (
    Budget,
    LabelSelector,
    PodAffinityTerm,
    PodPhase,
)
from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.controllers.disruption import (
    MultiNodeConsolidation,
    SetProposer,
    SetSweepContext,
    command_savings,
    simulate_scheduling,
)
from karpenter_tpu.controllers.disruption.consolidation import (
    SingleNodeConsolidation,
)
from karpenter_tpu.controllers.disruption.sweep import SweepUnsupported
from karpenter_tpu.controllers.disruption.types import (
    POD_DELETION_COST_ANNOTATION,
)
from karpenter_tpu.controllers.kube import FakeClock
from karpenter_tpu.controllers.operator import Operator
from karpenter_tpu.options import Options
from karpenter_tpu.testing import fixtures


def _fleet_op(
    seed: int,
    n_nodes: int,
    sizes: list[int],
    rider_cpu: str = "100m",
    seed_cpu: str = "700m",
    options: Options | None = None,
):
    """An under-utilized fleet through the real control plane (oracle
    provisioning keeps setup compile-free)."""
    return fixtures.underutilized_operator(
        n_nodes,
        seed=seed,
        sizes=sizes,
        rider_requests={"cpu": rider_cpu, "memory": "128Mi"},
        seed_requests={"cpu": seed_cpu, "memory": "512Mi"},
        force_oracle=True,
        options=options,
    )


def _candidates(op, **kwargs):
    mnc = MultiNodeConsolidation(
        op.kube, op.cluster, op.cloud, op.clock, options=op.opts,
        force_oracle=True, **kwargs,
    )
    return mnc.candidates()


def _referee(op, subset) -> bool:
    """The sequential simulator's feasibility verdict for removing
    `subset`: every reschedulable pod lands and at most one new claim
    opens (price/spot rules are compute_consolidation's business, not
    the kernel's)."""
    sim = simulate_scheduling(
        op.kube, op.cluster, op.cloud, subset, op.opts, force_oracle=True
    )
    return sim.all_pods_scheduled() and len(sim.non_empty_new_claims()) <= 1


# ---------------------------------------------------------------------------
# the set-parity matrix (acceptance: >= 100 randomized scenarios)


# each entry: (rng seed, nodes, instance sizes, rider cpu, seed cpu)
MATRIX_FLEETS = [
    (21, 6, [2, 32], "100m", "700m"),
    (11, 6, [2, 32], "1200m", "1500m"),
    (3, 6, [4, 16], "700m", "900m"),
    (7, 5, [2, 8, 32], "400m", "700m"),
    (13, 7, [2, 16], "900m", "1100m"),
    (17, 6, [4, 32], "1500m", "1800m"),
]


def test_set_parity_matrix():
    """Every proposed removal set's kernel feasibility bit equals the
    sequential simulator's verdict, across >= 100 randomized scenarios;
    and wherever any prefix is feasible, the sweep="sets" command saves
    at least as much as the best prefix command."""
    scenarios = 0
    savings_compared = 0
    for seed, n, sizes, rider, seedreq in MATRIX_FLEETS:
        op = _fleet_op(seed, n, sizes, rider_cpu=rider, seed_cpu=seedreq)
        cands = _candidates(op)
        assert len(cands) >= 4, (seed, len(cands))
        ctx = SetSweepContext.build(
            op.kube, op.cluster, op.cloud, cands, op.opts
        )
        proposer = SetProposer(cands, seed=seed)
        batch = proposer.first_round()
        extra = proposer._dedup(proposer._random(24))
        if len(extra):
            batch = np.concatenate([batch, extra], axis=0)

        # one bounded dispatch for the whole batch — no per-set round trips
        calls = {"n": 0}
        orig = SetSweepContext._dispatch

        def spy(self, member_dev):
            calls["n"] += 1
            return orig(self, member_dev)

        SetSweepContext._dispatch = spy
        try:
            feas = ctx.evaluate(batch)
        finally:
            SetSweepContext._dispatch = orig
        assert calls["n"] == 1, "a batch must be ONE device dispatch"

        for row, bit in zip(batch, feas):
            subset = [c for j, c in enumerate(cands) if row[j]]
            want = _referee(op, subset)
            assert bool(bit) == want, (
                f"fleet seed={seed}: set "
                f"{sorted(c.name for c in subset)} kernel={bool(bit)} "
                f"referee={want}"
            )
            scenarios += 1

        # ladder dominance: sets >= best prefix wherever a prefix works
        args = (op.kube, op.cluster, op.cloud, op.clock)
        cmd_sets = MultiNodeConsolidation(
            *args, sweep="sets", options=op.opts, force_oracle=False
        ).first_n_sets(cands)
        cmd_prefix = MultiNodeConsolidation(
            *args, sweep="binary", options=op.opts, force_oracle=True
        ).first_n_binary(cands)
        if cmd_prefix.candidates:
            assert (
                command_savings(cmd_sets)
                >= command_savings(cmd_prefix) - 1e-9
            ), (seed, command_savings(cmd_sets), command_savings(cmd_prefix))
            savings_compared += 1
    assert scenarios >= 100, scenarios
    assert savings_compared >= 3, savings_compared


# ---------------------------------------------------------------------------
# pinned non-prefix strict win


def _pinned_op():
    """Three candidates where the best removal set is NOT a prefix:
    c0 (cheap 4-cpu node, 1200m rider) sorts first by disruption cost
    (the 16-cpu nodes' riders carry a deletion-cost annotation), yet the
    best command removes BOTH 16-cpu nodes — their riders fit c0's
    slack — while every prefix either includes c0 (whose rider exhausts
    that slack, forcing a claim the spot-to-spot gate no-ops) or stops
    at one 16-cpu node."""
    op = Operator(clock=FakeClock(), force_oracle=True)
    op.raw_cloud.types = construct_instance_types(sizes=[4, 16])
    op.raw_cloud._by_name = {it.name: it for it in op.raw_cloud.types}
    fixtures.reset_rng(5)
    op.kube.create(
        "NodePool",
        fixtures.node_pool(name="default", budgets=[Budget(nodes="100%")]),
    )
    for i, cpu in enumerate(["2500m", "9", "9"]):
        op.kube.create(
            "Pod",
            fixtures.pod(
                name=f"seed-{i}",
                labels={"fleet": "seed"},
                requests={"cpu": cpu, "memory": "512Mi"},
                pod_anti_requirements=[
                    PodAffinityTerm(
                        topology_key=well_known.HOSTNAME_LABEL_KEY,
                        label_selector=LabelSelector(
                            match_labels={"fleet": "seed"}
                        ),
                    )
                ],
            ),
        )
    assert op.run_until_settled(max_ticks=60, advance_seconds=2.0) < 60
    riders = [("1200m", None), ("1", "134217728"), ("1", "134217728")]
    for i, (cpu, cost) in enumerate(riders):
        node_name = op.kube.get("Pod", f"seed-{i}").node_name
        op.kube.delete("Pod", f"seed-{i}")
        r = fixtures.pod(
            name=f"rider-{i}",
            labels={"fleet": "rider"},
            requests={"cpu": cpu, "memory": "128Mi"},
        )
        if cost:
            r.metadata.annotations[POD_DELETION_COST_ANNOTATION] = cost
        r.node_name = node_name
        r.phase = PodPhase.RUNNING
        op.kube.create("Pod", r)
    op.clock.advance(26.0)
    op.pod_events.reconcile_all()
    op.claim_conditions.reconcile_all()
    return op


def test_pinned_non_prefix_set_beats_every_prefix():
    """sweep="sets" must STRICTLY beat the prefix strategies here: the
    winning set {c1, c2} skips candidate 0 entirely, which no prefix of
    the cost order can express."""
    op = _pinned_op()
    cands = _candidates(op)
    assert len(cands) == 3
    # cost order pins c0 = the 4-cpu node (annotation-weighted riders
    # push the 16-cpu nodes after it)
    assert cands[0].instance_type_name.startswith("c-4x")
    assert cands[1].price == cands[2].price > cands[0].price

    args = (op.kube, op.cluster, op.cloud, op.clock)
    cmd_sets = MultiNodeConsolidation(
        *args, sweep="sets", options=op.opts, force_oracle=False
    ).first_n_sets(cands)
    cmd_prefix = MultiNodeConsolidation(
        *args, sweep="batched", options=op.opts, force_oracle=False
    ).first_n_batched(cands)
    cmd_binary = MultiNodeConsolidation(
        *args, sweep="binary", options=op.opts, force_oracle=True
    ).first_n_binary(cands)

    # the winner is exactly the two 16-cpu nodes — a non-prefix set
    assert sorted(c.name for c in cmd_sets.candidates) == sorted(
        c.name for c in cands[1:]
    )
    assert cmd_sets.decision == "delete"
    s_sets = command_savings(cmd_sets)
    s_prefix = command_savings(cmd_prefix)
    assert math.isclose(
        s_prefix, command_savings(cmd_binary), rel_tol=1e-12
    )
    assert s_sets > s_prefix + 1e-6, (s_sets, s_prefix)
    # referee agrees the winning set is feasible
    assert _referee(op, cmd_sets.candidates)


# ---------------------------------------------------------------------------
# SweepUnsupported gates: each fires on a crafted scenario AND the
# controller falls down the ladder to an exact strategy with an
# identical command


def _assert_ladder_identical(op, cands):
    """sweep="sets" (whole ladder active) and the exact binary search
    must produce the same command on the current cluster."""
    args = (op.kube, op.cluster, op.cloud, op.clock)
    cmd_l = MultiNodeConsolidation(
        *args, sweep="sets", options=op.opts, force_oracle=False
    ).first_n_sets(cands)
    cmd_b = MultiNodeConsolidation(
        *args, sweep="binary", options=op.opts, force_oracle=True
    ).first_n_binary(cands)
    assert sorted(c.name for c in cmd_l.candidates) == sorted(
        c.name for c in cmd_b.candidates
    )
    assert cmd_l.decision == cmd_b.decision


def _gate_nodepool_limits(op, cands, monkeypatch):
    from karpenter_tpu.utils import resources as res

    np_ = op.kube.list("NodePool")[0]
    np_.limits = res.parse_list({"cpu": "1000"})
    op.kube.update("NodePool", np_)
    with pytest.raises(SweepUnsupported, match="nodepool limits"):
        SetSweepContext.build(op.kube, op.cluster, op.cloud, cands, op.opts)


def _gate_max_prefixes(op, cands, monkeypatch):
    import karpenter_tpu.controllers.disruption.sweep as sweep_mod

    monkeypatch.setattr(sweep_mod, "MAX_SWEEP_PREFIXES", 2)
    with pytest.raises(SweepUnsupported, match="prefixes >"):
        sweep_mod.prefix_feasibility(
            op.kube, op.cluster, op.cloud, cands, op.opts
        )


def _gate_max_set_lanes(op, cands, monkeypatch):
    ctx = SetSweepContext.build(
        op.kube, op.cluster, op.cloud, cands, op.opts
    )
    import karpenter_tpu.controllers.disruption.setsweep as ss

    over = np.ones((ss.MAX_SET_LANES + 1, len(cands)), bool)
    with pytest.raises(SweepUnsupported, match="set lanes >"):
        ctx.evaluate(over)


def _gate_missing_candidate(op, cands, monkeypatch):
    # a candidate whose node is not among the schedulable views (e.g. it
    # went unready between candidate build and the sweep)
    ghost = SimpleNamespace(
        name="ghost-node",
        nodepool_name="default",
        price=1.0,
        reschedulable_pods=[],
    )
    with pytest.raises(SweepUnsupported, match="missing from schedulable"):
        SetSweepContext.build(
            op.kube, op.cluster, op.cloud, cands + [ghost], op.opts
        )


def _gate_host_ports(op, cands, monkeypatch):
    rider = next(
        p for p in op.kube.list("Pod") if p.name.startswith("rider-")
    )
    rider.host_ports = [("", "TCP", 8080)]
    op.kube.update("Pod", rider)
    cands = _candidates(op)  # re-snapshot the mutated rider
    with pytest.raises(SweepUnsupported, match="host ports"):
        SetSweepContext.build(op.kube, op.cluster, op.cloud, cands, op.opts)


def _gate_anti_affinity(op, cands, monkeypatch):
    rider = next(
        p for p in op.kube.list("Pod") if p.name.startswith("rider-")
    )
    rider.pod_anti_affinity = [
        PodAffinityTerm(
            topology_key=well_known.HOSTNAME_LABEL_KEY,
            label_selector=LabelSelector(match_labels={"fleet": "rider"}),
        )
    ]
    op.kube.update("Pod", rider)
    cands = _candidates(op)  # re-snapshot the mutated rider
    # the anti-affinity rider shows up as topology ownership / inverse
    # hostname groups among the union pods — either way the fast-shape
    # gate refuses it
    with pytest.raises(SweepUnsupported, match="set sweep needs the fast shape"):
        SetSweepContext.build(op.kube, op.cluster, op.cloud, cands, op.opts)


def _gate_int32_overflow(op, cands, monkeypatch):
    from karpenter_tpu.solver import tpu_problem as tp

    orig = tp.group_class_counts

    def inflated(ordered_cls, class_seq, group, n_groups):
        base, M = orig(ordered_cls, class_seq, group, n_groups)
        # sizes are pod-units (small ints): 2^28 base counts push the
        # worst-case total past 2^30 for any non-zero size column
        return base + (1 << 28), M

    monkeypatch.setattr(tp, "group_class_counts", inflated)
    with pytest.raises(SweepUnsupported, match="exceed int32"):
        SetSweepContext.build(op.kube, op.cluster, op.cloud, cands, op.opts)


GATE_CASES = {
    "nodepool-limits": _gate_nodepool_limits,
    "max-prefixes": _gate_max_prefixes,
    "max-set-lanes": _gate_max_set_lanes,
    "missing-candidate": _gate_missing_candidate,
    "host-ports": _gate_host_ports,
    "anti-affinity-pod": _gate_anti_affinity,
    "int32-overflow": _gate_int32_overflow,
}


@pytest.mark.parametrize("case", sorted(GATE_CASES), ids=sorted(GATE_CASES))
def test_sweep_unsupported_gate_falls_back_exact(case, monkeypatch):
    """Each gate raises SweepUnsupported on its crafted scenario, and the
    sets-mode controller still lands on the binary search's exact
    command via the strategy ladder."""
    op = _fleet_op(21, 5, [2, 32])
    cands = _candidates(op)
    assert len(cands) >= 4
    GATE_CASES[case](op, cands, monkeypatch)
    # the mutation stays live: the ladder must route around the gate
    cands_after = _candidates(op)
    _assert_ladder_identical(op, cands_after or cands)


def test_no_candidates_gate():
    op = _fleet_op(21, 5, [2, 32])
    with pytest.raises(SweepUnsupported, match="no candidates"):
        SetSweepContext.build(op.kube, op.cluster, op.cloud, [], op.opts)


# ---------------------------------------------------------------------------
# satellite 1: the SweepUnsupported fallback inside first_n_batched must
# be the O(log N) bisection, not the old O(N) largest-first scan


def test_batched_fallback_is_binary_not_linear(monkeypatch):
    import karpenter_tpu.controllers.disruption.sweep as sweep_mod

    op = _fleet_op(21, 8, [2, 32])
    cands = _candidates(op)
    n = len(cands)
    assert n >= 6

    def boom(consolidation, candidates):
        raise SweepUnsupported("forced for the regression test")

    monkeypatch.setattr(sweep_mod, "sweep_first_n", boom)
    args = (op.kube, op.cluster, op.cloud, op.clock)
    mnc = MultiNodeConsolidation(
        *args, sweep="batched", options=op.opts, force_oracle=False
    )
    calls = {"n": 0}
    orig = mnc.compute_consolidation

    def counting(candidates):
        calls["n"] += 1
        return orig(candidates)

    mnc.compute_consolidation = counting
    cmd = mnc.first_n_batched(cands)
    # binary search: at most ceil(log2(n)) + 1 full simulations — the old
    # largest-first scan could burn up to n
    assert calls["n"] <= math.ceil(math.log2(n)) + 1, calls["n"]
    ref = MultiNodeConsolidation(
        *args, sweep="binary", options=op.opts, force_oracle=True
    ).first_n_binary(cands)
    assert sorted(c.name for c in cmd.candidates) == sorted(
        c.name for c in ref.candidates
    )


# ---------------------------------------------------------------------------
# satellite 2: single-node consolidation budgets its walk with its OWN
# timeout (singlenodeconsolidation.go:31), not the multi-node one


def test_single_node_has_own_timeout():
    assert Options().singlenode_consolidation_timeout_seconds == 180.0
    assert Options().multinode_consolidation_timeout_seconds == 60.0

    # an exhausted MULTI-node budget must not starve the single-node walk
    opts = Options(multinode_consolidation_timeout_seconds=-1.0)
    op = _fleet_op(21, 4, [2, 32], options=opts)
    args = (op.kube, op.cluster, op.cloud, op.clock)
    snc = SingleNodeConsolidation(
        *args, options=opts, force_oracle=True
    )
    assert snc.compute_commands(), (
        "single-node walk must run on its own 3-minute budget"
    )
    mnc = MultiNodeConsolidation(
        *args, sweep="binary", options=opts, force_oracle=True
    )
    assert not mnc.compute_commands(), "multi-node budget is spent"

    # and an exhausted SINGLE-node budget stops only the single-node walk
    opts2 = Options(singlenode_consolidation_timeout_seconds=-1.0)
    snc2 = SingleNodeConsolidation(*args, options=opts2, force_oracle=True)
    assert not snc2.compute_commands()


# ---------------------------------------------------------------------------
# proposer mechanics (pure host-side)


def test_set_proposer_subsumes_prefixes_and_dedups():
    cands = [
        SimpleNamespace(name=f"c{i}", nodepool_name="default")
        for i in range(5)
    ]
    prop = SetProposer(cands, seed=1)
    rows = prop.first_round()
    assert rows.dtype == bool and rows.shape[1] == 5
    # every prefix of the cost order is a lane (strict subsumption of the
    # prefix sweep)
    for k in range(1, 6):
        want = np.zeros(5, bool)
        want[:k] = True
        assert any((r == want).all() for r in rows), k
    # no empty set, no duplicates
    assert all(r.any() for r in rows)
    keys = {np.packbits(r).tobytes() for r in rows}
    assert len(keys) == len(rows)
    # dedup persists across rounds
    again = prop._dedup(rows.copy())
    assert len(again) == 0

    best = rows[0]
    hood = prop.neighborhood(best)
    assert all(r.any() for r in hood)
    # neighborhood never re-proposes an already-scored set
    for r in hood:
        assert not any((r == s).all() for s in rows)


def test_unknown_price_and_strategy_guards():
    """MAX_FLOAT (unknown) candidate prices rank at 0 — never inf/NaN —
    in both the estimate and the real savings objective; and an invalid
    sweep strategy (env-overridable) fails fast with the valid rungs."""
    from karpenter_tpu.cloudprovider.types import MAX_FLOAT
    from karpenter_tpu.controllers.disruption.types import Command

    unknown = SimpleNamespace(price=MAX_FLOAT, nodepool_name="default")
    known = SimpleNamespace(price=1.5, nodepool_name="default")
    cmd = Command(reason="underutilized", candidates=[unknown, known])
    assert command_savings(cmd) == 0.0
    assert command_savings(
        Command(reason="underutilized", candidates=[known])
    ) == 1.5

    ctx = SetSweepContext(
        [unknown, known], None, None, None, None, None, None, None, None,
        None, trivial=True,
    )
    est = ctx.savings_estimate(np.ones((1, 2)))
    assert est.tolist() == [1.5]  # unknown contributes 0, not inf

    with pytest.raises(ValueError, match="sweep strategy"):
        MultiNodeConsolidation(None, None, None, None, sweep="prefix")
