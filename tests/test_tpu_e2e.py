"""The control plane ON the TPU path: end-to-end flows that assert the
batched kernel (not the oracle fallback) produced the decisions — through a
provisioner tick with daemonsets + existing nodes, and through a
consolidation simulation (the round-2 gap: every control-plane test forced
the oracle; here `used_tpu` is the assertion).
"""

from __future__ import annotations

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import Budget, PodPhase
from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.controllers.disruption.helpers import (
    build_candidates,
    simulate_scheduling,
)
from karpenter_tpu.controllers.kube import DaemonSet, FakeClock
from karpenter_tpu.controllers.operator import Operator
from karpenter_tpu.options import Options
from karpenter_tpu.testing import fixtures


def tpu_operator():
    # tpu_min_pods=0: these tests pin the KERNEL path on deliberately
    # tiny problems; production routing would send them to the oracle
    op = Operator(
        clock=FakeClock(), force_oracle=False, options=Options(tpu_min_pods=0)
    )
    op.raw_cloud.types = construct_instance_types(sizes=[2, 8])
    op.raw_cloud._by_name = {it.name: it for it in op.raw_cloud.types}
    fixtures.reset_rng(33)
    op.kube.create(
        "NodePool",
        fixtures.node_pool(name="default", budgets=[Budget(nodes="100%")]),
    )
    return op


def test_provision_e2e_rides_tpu():
    """pending -> bound entirely through the TPU kernel, with a daemonset
    shaping claim overhead and a second wave landing on EXISTING nodes."""
    op = tpu_operator()
    op.kube.create(
        "DaemonSet",
        DaemonSet(
            name="logging",
            pod_template=fixtures.pod(name="ds-template", requests={"cpu": "50m"}),
        ),
    )
    for i in range(4):
        op.kube.create(
            "Pod", fixtures.pod(name=f"w-{i}", requests={"cpu": "300m", "memory": "256Mi"})
        )
    op.run_until_settled(max_ticks=60)
    assert op.provisioner.last_solver_used == "tpu"
    assert all(p.node_name for p in op.kube.list("Pod"))
    nodes = op.kube.list("Node")
    assert nodes

    # second wave: pods must pack onto the EXISTING nodes via the kernel
    n_nodes = len(nodes)
    for i in range(3):
        op.kube.create(
            "Pod", fixtures.pod(name=f"w2-{i}", requests={"cpu": "100m"})
        )
    op.run_until_settled(max_ticks=60)
    assert op.provisioner.last_solver_used == "tpu"
    assert all(p.node_name for p in op.kube.list("Pod"))
    assert len(op.kube.list("Node")) == n_nodes, (
        "small second-wave pods must land on existing capacity"
    )


def test_consolidation_simulation_rides_tpu():
    """SimulateScheduling through the kernel with existing nodes and bound
    pods: the disruption decision is TPU-produced (helpers.go:52-143)."""
    op = tpu_operator()
    fixtures.make_underutilized_fleet(op, 5)
    op.clock.advance(26.0)
    op.pod_events.reconcile_all()
    op.claim_conditions.reconcile_all()

    cands = build_candidates(op.kube, op.cluster, op.cloud, op.clock, lambda c: True)
    assert len(cands) >= 3
    sim = simulate_scheduling(
        op.kube, op.cluster, op.cloud, cands[:3], op.opts, force_oracle=False
    )
    assert sim.used_tpu is True
    assert sim.all_pods_scheduled()


def test_consolidation_e2e_rides_tpu():
    """The full disruption loop (candidates -> simulate -> validate ->
    execute) with the kernel doing every simulation: the fleet shrinks and
    the workload survives."""
    op = tpu_operator()
    fixtures.make_underutilized_fleet(op, 4)
    before = len(op.kube.list("Node"))
    for _ in range(60):
        op.step(2.0)
        if len(op.kube.list("Node")) < before and not op.disruption.queue.busy:
            break
    assert len(op.kube.list("Node")) < before, "fleet must consolidate"
    pods = [p for p in op.kube.list("Pod")]
    assert pods and all(p.node_name for p in pods)
