"""Reference scheduler suite families ported as scenario matrices
(VERDICT r3 item #9): daemonset overhead, nodepool limits, in-flight claim
reuse, and per-pod error-text parity.

Sources (semantics, not code):
- daemonsets: /root/reference/pkg/controllers/provisioning/scheduling/
  suite_test.go:2204-2472 ("In-Flight Nodes > Daemonsets") and :2595-2653
  ("Existing Nodes > Daemonsets"), scheduler.go:806 isDaemonPodCompatible
- limits: scheduler.go:831 subtractMax / :851 filterByRemainingResources
- in-flight reuse: suite_test.go:1831-1959 ("In-Flight Nodes")
- error text: nodeclaim.go:296-370 rich per-pod failure reasons

Most matrices run on the oracle (the semantic referee); each family ends
with a kernel-parity case through solve-both so the TPU path is pinned to
the same behavior.
"""

from __future__ import annotations

import pytest

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import (
    NodeSelectorRequirement,
    Operator,
    Taint,
    TaintEffect,
    Toleration,
)
from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.solver import HybridScheduler, Scheduler, Topology
from karpenter_tpu.solver.oracle import SchedulerOptions
from karpenter_tpu.solver.nodes import StateNodeView
from karpenter_tpu.testing import fixtures

ZONE = well_known.TOPOLOGY_ZONE_LABEL_KEY


def _its(sizes=(2, 8)):
    return construct_instance_types(sizes=list(sizes))


def solve(
    pods,
    pools=None,
    views=None,
    daemons=None,
    options=None,
    kernel=False,
    sizes=(2, 8),
):
    its = _its(sizes)
    pools = pools or [fixtures.node_pool(name="default")]
    ibp = {np.name: its for np in pools}
    topo = Topology(pools, ibp, pods, state_node_views=views)
    cls = HybridScheduler if kernel else Scheduler
    kw = {}
    if kernel:
        kw["force_oracle"] = False
        options = options or SchedulerOptions()
        options.tpu_min_pods = 0
    s = cls(pools, ibp, topo, views, daemons, options, **kw)
    return s.solve(pods), s


def placements(r):
    out = {}
    for c in r.new_node_claims:
        for p in c.pods:
            out[p.name] = ("new", id(c))
    for n in r.existing_nodes:
        for p in n.pods:
            out[p.name] = ("existing", n.name)
    return out


def existing_view(name, zone="test-zone-a", cpu_avail=1500, itype="c-2x-amd64-linux"):
    return StateNodeView(
        name=name,
        labels={
            ZONE: zone,
            well_known.HOSTNAME_LABEL_KEY: name,
            well_known.INSTANCE_TYPE_LABEL_KEY: itype,
            well_known.CAPACITY_TYPE_LABEL_KEY: "on-demand",
            well_known.OS_LABEL_KEY: "linux",
            well_known.ARCH_LABEL_KEY: "amd64",
            well_known.NODEPOOL_LABEL_KEY: "default",
        },
        available={
            "cpu": cpu_avail,
            "memory": 3 * 1024**3 * 1000,
            "pods": 20_000,
        },
        capacity={"cpu": 2000, "memory": 4 * 1024**3 * 1000},
        initialized=True,
    )


# ---------------------------------------------------------------------------
# Daemonset overhead (suite_test.go:2204, scheduler.go:806)


def _daemon(cpu="500m", node_selector=None, tolerations=None, prefs=None):
    p = fixtures.pod(name="ds", requests={"cpu": cpu, "memory": "128Mi"})
    if node_selector:
        p.node_selector = dict(node_selector)
    if tolerations:
        p.tolerations = list(tolerations)
    if prefs:
        p.node_affinity = prefs
    return p


@pytest.mark.parametrize("ds_cpu", ["500m", "1000m", "1500m"])
def test_daemon_overhead_reduces_new_claim_capacity(ds_cpu):
    """A pod sized to the 2-cpu type's allocatable minus the daemonset
    overhead fits exactly; one milli more forces the bigger type."""
    its = _its()
    alloc2 = min(
        it.allocatable()["cpu"] for it in its if it.capacity["cpu"] == 2000
    )
    fit = alloc2 - int(ds_cpu[:-1])
    r, _ = solve(
        [fixtures.pod(name="exact", requests={"cpu": str(fit) + "m"})],
        daemons=[_daemon(cpu=ds_cpu)],
    )
    assert not r.pod_errors
    claim = [c for c in r.new_node_claims if c.pods][0]
    assert any(it.capacity["cpu"] == 2000 for it in claim.instance_type_options)

    r2, _ = solve(
        [fixtures.pod(name="over", requests={"cpu": str(fit + 1) + "m"})],
        daemons=[_daemon(cpu=ds_cpu)],
    )
    assert not r2.pod_errors
    claim2 = [c for c in r2.new_node_claims if c.pods][0]
    # the 2-cpu family no longer fits under the overhead
    assert all(it.capacity["cpu"] > 2000 for it in claim2.instance_type_options)


def test_daemon_overhead_sums_across_daemonsets():
    its = _its()
    alloc2 = min(
        it.allocatable()["cpu"] for it in its if it.capacity["cpu"] == 2000
    )
    daemons = [_daemon(cpu="300m"), _daemon(cpu="300m")]
    daemons[1].metadata.name = "ds-2"
    fit = alloc2 - 600
    r, _ = solve(
        [fixtures.pod(name="exact", requests={"cpu": f"{fit}m"})], daemons=daemons
    )
    claim = [c for c in r.new_node_claims if c.pods][0]
    assert any(it.capacity["cpu"] == 2000 for it in claim.instance_type_options)
    r2, _ = solve(
        [fixtures.pod(name="over", requests={"cpu": f"{fit + 1}m"})], daemons=daemons
    )
    claim2 = [c for c in r2.new_node_claims if c.pods][0]
    assert all(it.capacity["cpu"] > 2000 for it in claim2.instance_type_options)


def test_daemon_with_zone_selector_only_burdens_matching_claims():
    """A daemonset selecting zone-b adds no overhead to a zone-a-only
    pool (isDaemonPodCompatible: requirements must intersect)."""
    pool_a = fixtures.node_pool(
        name="zone-a",
        requirements=[NodeSelectorRequirement(ZONE, Operator.IN, ["test-zone-a"])],
    )
    its = _its()
    alloc2 = min(
        it.allocatable()["cpu"] for it in its if it.capacity["cpu"] == 2000
    )
    ds = _daemon(cpu="1000m", node_selector={ZONE: "test-zone-b"})
    r, _ = solve(
        [fixtures.pod(name="full", requests={"cpu": f"{alloc2}m"})],
        pools=[pool_a],
        daemons=[ds],
    )
    assert not r.pod_errors  # overhead not applied -> full allocatable usable
    claim = [c for c in r.new_node_claims if c.pods][0]
    assert any(it.capacity["cpu"] == 2000 for it in claim.instance_type_options)


def test_daemon_not_tolerating_pool_taint_adds_no_overhead():
    pool = fixtures.node_pool(
        name="tainted",
        taints=[Taint(key="team", value="a", effect=TaintEffect.NO_SCHEDULE)],
    )
    its = _its()
    alloc2 = min(
        it.allocatable()["cpu"] for it in its if it.capacity["cpu"] == 2000
    )
    workload = fixtures.pod(
        name="full",
        requests={"cpu": f"{alloc2}m"},
        tolerations=[Toleration(key="team", operator="Exists")],
    )
    r, _ = solve([workload], pools=[pool], daemons=[_daemon(cpu="1000m")])
    assert not r.pod_errors
    claim = [c for c in r.new_node_claims if c.pods][0]
    assert any(it.capacity["cpu"] == 2000 for it in claim.instance_type_options)

    # a tolerating daemonset DOES burden the pool
    ds = _daemon(
        cpu="1000m", tolerations=[Toleration(key="team", operator="Exists")]
    )
    r2, _ = solve([workload], pools=[pool], daemons=[ds])
    claim2 = [c for c in r2.new_node_claims if c.pods][0]
    assert all(it.capacity["cpu"] > 2000 for it in claim2.instance_type_options)


def test_daemon_overhead_counted_on_existing_nodes():
    """An existing node's remaining capacity already nets out its bound
    daemonset pods (StateNodeView.daemonset_requests); the solver must
    re-apply overhead only for daemonsets notyet bound (here: packing onto
    existing capacity respects available cpu)."""
    view = existing_view("node-1", cpu_avail=900)
    r, _ = solve(
        [fixtures.pod(name="small", requests={"cpu": "800m"})], views=[view]
    )
    assert placements(r)["small"] == ("existing", "node-1")
    r2, _ = solve(
        [fixtures.pod(name="big", requests={"cpu": "1000m"})], views=[view]
    )
    assert placements(r2)["big"][0] == "new"


def test_daemon_relaxes_required_affinity_for_compat():
    """scheduler.go:806: daemon compatibility relaxes the daemonset's own
    required node-affinity OR-terms until compatible — a first term naming
    a nonexistent zone does not exempt the daemon's overhead."""
    from karpenter_tpu.api.objects import NodeAffinity, NodeSelectorTerm

    its = _its()
    alloc2 = min(
        it.allocatable()["cpu"] for it in its if it.capacity["cpu"] == 2000
    )
    ds = _daemon(cpu="1000m")
    ds.node_affinity = NodeAffinity(
        required_terms=[
            NodeSelectorTerm(
                match_expressions=[
                    NodeSelectorRequirement(ZONE, Operator.IN, ["no-such-zone"])
                ]
            ),
            NodeSelectorTerm(match_expressions=[]),
        ]
    )
    r, _ = solve(
        [fixtures.pod(name="full", requests={"cpu": f"{alloc2}m"})], daemons=[ds]
    )
    claim = [c for c in r.new_node_claims if c.pods][0]
    assert all(it.capacity["cpu"] > 2000 for it in claim.instance_type_options)


def test_daemonset_family_kernel_parity():
    """One combined daemonset scenario, oracle vs kernel bit-parity."""
    its = _its()
    alloc2 = min(
        it.allocatable()["cpu"] for it in its if it.capacity["cpu"] == 2000
    )

    def build():
        fixtures.reset_rng(5)
        pods = [
            fixtures.pod(name=f"w-{i}", requests={"cpu": f"{alloc2 - 700}m"})
            for i in range(6)
        ]
        return pods, [_daemon(cpu="700m")]

    outs = []
    for kernel in (False, True):
        pods, daemons = build()
        r, s = solve(pods, daemons=daemons, kernel=kernel)
        outs.append((r, s))
    (orc, _), (hyb, hs) = outs
    assert hs.used_tpu is True, hs.fallback_reason
    assert not orc.pod_errors and not hyb.pod_errors

    def parts(r):
        return sorted(
            tuple(sorted(p.name for p in c.pods))
            for c in r.new_node_claims
            if c.pods
        )

    assert parts(orc) == parts(hyb)
    # daemon overhead baked into every claim's requests on both paths
    for r in (orc, hyb):
        for c in r.new_node_claims:
            if c.pods:
                assert c.daemon_resources.get("cpu", 0) == 700


# ---------------------------------------------------------------------------
# NodePool limits (scheduler.go:831, :851)


@pytest.mark.parametrize("limit_cpu,max_new_nodes", [("2", 1), ("4", 2), ("8", 4)])
def test_limits_cap_new_capacity(limit_cpu, max_new_nodes):
    """subtractMax: each new claim spends the max capacity of its allowed
    types against the pool's limit; pods beyond the cap error out."""
    pool = fixtures.node_pool(name="default", limits={"cpu": limit_cpu})
    pods = [
        fixtures.pod(name=f"w-{i}", requests={"cpu": "1500m"}) for i in range(8)
    ]
    r, _ = solve(pods, pools=[pool], sizes=(2,))
    claims = [c for c in r.new_node_claims if c.pods]
    assert len(claims) <= max_new_nodes
    assert len(r.pod_errors) == len(pods) - sum(len(c.pods) for c in claims)
    assert r.pod_errors, "the cap must actually bind in this scenario"
    uid_errors = set(r.pod_errors.values())
    assert any("limit" in e for e in uid_errors), uid_errors


def test_limits_memory_only():
    pool = fixtures.node_pool(name="default", limits={"memory": "4Gi"})
    pods = [
        fixtures.pod(
            name=f"w-{i}", requests={"cpu": "100m", "memory": "3Gi"}
        )
        for i in range(3)
    ]
    r, _ = solve(pods, pools=[pool], sizes=(2,))
    claims = [c for c in r.new_node_claims if c.pods]
    assert len(claims) == 1  # one 4Gi node exhausts the memory limit
    assert len(r.pod_errors) == 2


def test_limited_pool_spills_to_unlimited_pool():
    """Weight order: the limited high-weight pool takes what it can; the
    rest lands on the lower-weight unlimited pool instead of erroring."""
    limited = fixtures.node_pool(
        name="limited", limits={"cpu": "2"}, weight=10
    )
    fallback = fixtures.node_pool(name="fallback", weight=1)
    pods = [
        fixtures.pod(name=f"w-{i}", requests={"cpu": "1500m"}) for i in range(4)
    ]
    r, _ = solve(pods, pools=[limited, fallback], sizes=(2,))
    assert not r.pod_errors
    by_pool = {}
    for c in r.new_node_claims:
        if c.pods:
            by_pool.setdefault(c.template.nodepool_name, 0)
            by_pool[c.template.nodepool_name] += 1
    assert by_pool.get("limited", 0) == 1
    assert by_pool.get("fallback", 0) >= 1


def test_oversubscribed_pool_schedules_nothing_new():
    """A pool whose existing nodes already exceed its limits filters out
    every instance type for new claims."""
    pool = fixtures.node_pool(name="default", limits={"cpu": "1"})
    view = existing_view("node-1", cpu_avail=100)  # capacity 2000m > limit
    pods = [fixtures.pod(name="w", requests={"cpu": "1500m"})]
    r, _ = solve(pods, pools=[pool], views=[view], sizes=(2,))
    assert r.pod_errors, "no new capacity under an exhausted limit"
    assert not [c for c in r.new_node_claims if c.pods]


def test_limits_existing_capacity_still_usable():
    """Limits cap NEW capacity; packing onto existing nodes is free."""
    pool = fixtures.node_pool(name="default", limits={"cpu": "1"})
    view = existing_view("node-1", cpu_avail=1800)
    pods = [
        fixtures.pod(name=f"w-{i}", requests={"cpu": "800m"}) for i in range(2)
    ]
    r, _ = solve(pods, pools=[pool], views=[view], sizes=(2,))
    assert not r.pod_errors
    pl = placements(r)
    assert pl["w-0"] == ("existing", "node-1")
    assert pl["w-1"] == ("existing", "node-1")


def test_limits_family_kernel_parity():
    """Limits through the kernel (tlimit tensors + trem subtractMax) must
    match the oracle exactly, including which pods error."""

    def build():
        fixtures.reset_rng(9)
        pool = fixtures.node_pool(name="default", limits={"cpu": "4"})
        pods = [
            fixtures.pod(name=f"w-{i}", requests={"cpu": "1500m"})
            for i in range(5)
        ]
        return pool, pods

    outs = []
    for kernel in (False, True):
        pool, pods = build()
        r, s = solve(pods, pools=[pool], kernel=kernel, sizes=(2,))
        outs.append((r, s, pods))
    (orc, _, opods), (hyb, hs, hpods) = outs
    assert hs.used_tpu is True, hs.fallback_reason
    oerr = {p.name for p in opods if p.uid in orc.pod_errors}
    herr = {p.name for p in hpods if p.uid in hyb.pod_errors}
    assert oerr == herr and oerr, (oerr, herr)

    def parts(r):
        return sorted(
            tuple(sorted(p.name for p in c.pods))
            for c in r.new_node_claims
            if c.pods
        )

    assert parts(orc) == parts(hyb)


# ---------------------------------------------------------------------------
# In-flight claim reuse (suite_test.go:1831)


def test_second_pod_reuses_inflight_claim():
    pods = [
        fixtures.pod(name="a", requests={"cpu": "100m"}),
        fixtures.pod(name="b", requests={"cpu": "100m"}),
    ]
    r, _ = solve(pods)
    claims = [c for c in r.new_node_claims if c.pods]
    assert len(claims) == 1 and len(claims[0].pods) == 2


def test_reuse_respects_zone_intersection():
    """Pod A pins zone-b; the claim's requirements narrow to zone-b. Pod B
    allows zone-a/zone-b — the intersection is nonempty, so B reuses A's
    claim (suite_test.go:1849)."""
    pods = [
        fixtures.pod(
            name="a",
            requests={"cpu": "100m"},
            node_requirements=[
                NodeSelectorRequirement(ZONE, Operator.IN, ["test-zone-b"])
            ],
        ),
        fixtures.pod(
            name="b",
            requests={"cpu": "100m"},
            node_requirements=[
                NodeSelectorRequirement(
                    ZONE, Operator.IN, ["test-zone-a", "test-zone-b"]
                )
            ],
        ),
    ]
    r, _ = solve(pods)
    claims = [c for c in r.new_node_claims if c.pods]
    assert len(claims) == 1 and len(claims[0].pods) == 2
    zone_req = claims[0].requirements.get(ZONE)
    assert set(zone_req.values) == {"test-zone-b"}


def test_no_reuse_on_disjoint_zones():
    pods = [
        fixtures.pod(
            name="a",
            requests={"cpu": "100m"},
            node_requirements=[
                NodeSelectorRequirement(ZONE, Operator.IN, ["test-zone-b"])
            ],
        ),
        fixtures.pod(
            name="b",
            requests={"cpu": "100m"},
            node_requirements=[
                NodeSelectorRequirement(ZONE, Operator.IN, ["test-zone-a"])
            ],
        ),
    ]
    r, _ = solve(pods)
    claims = [c for c in r.new_node_claims if c.pods]
    assert len(claims) == 2


def test_no_reuse_when_capacity_exhausted():
    """Sized so exactly one big pod fits a 2-cpu node: the second pod must
    open a second claim, not overfill the first."""
    its = _its((2,))
    alloc2 = min(it.allocatable()["cpu"] for it in its)
    pods = [
        fixtures.pod(name=f"w-{i}", requests={"cpu": f"{alloc2 - 100}m"})
        for i in range(2)
    ]
    r, _ = solve(pods, sizes=(2,))
    claims = [c for c in r.new_node_claims if c.pods]
    assert len(claims) == 2
    assert not r.pod_errors


def test_reuse_prefers_emptiest_claim():
    """scheduler.go:499: in-flight claims are tried fewest-pods-first.
    Two anti-affinity seeds force two claims; a flood of small pods then
    balances across them instead of piling onto the first."""
    anti = fixtures.make_pod_anti_affinity_pods(2, well_known.HOSTNAME_LABEL_KEY)
    small = [
        fixtures.pod(name=f"s-{i}", requests={"cpu": "50m"}) for i in range(6)
    ]
    r, _ = solve(anti + small, sizes=(2,))
    assert not r.pod_errors
    claims = [c for c in r.new_node_claims if c.pods]
    assert len(claims) == 2
    sizes_ = sorted(len(c.pods) for c in claims)
    assert sizes_ == [4, 4], sizes_


def test_incompatible_taint_tolerations_fork_claims():
    """The tolerant pod is bigger, so FFD places it first: it lands on the
    higher-weight tainted pool. The plain pod cannot join that claim
    (in-flight claims are tried before new templates, scheduler.go:488,
    but the taint blocks it) and opens a default-pool claim."""
    pool_t = fixtures.node_pool(
        name="tainted",
        taints=[Taint(key="team", value="a", effect=TaintEffect.NO_SCHEDULE)],
        weight=10,
    )
    pool_d = fixtures.node_pool(name="default", weight=1)
    pods = [
        fixtures.pod(
            name="tolerant",
            requests={"cpu": "200m"},
            tolerations=[Toleration(key="team", operator="Exists")],
        ),
        fixtures.pod(name="plain", requests={"cpu": "100m"}),
    ]
    r, _ = solve(pods, pools=[pool_t, pool_d])
    assert not r.pod_errors
    by_pool = {
        c.template.nodepool_name: [p.name for p in c.pods]
        for c in r.new_node_claims
        if c.pods
    }
    assert by_pool.get("tainted") == ["tolerant"]
    assert by_pool.get("default") == ["plain"]


def test_inflight_family_kernel_parity():
    def build():
        fixtures.reset_rng(11)
        pods = [
            fixtures.pod(
                name="a",
                requests={"cpu": "100m"},
                node_requirements=[
                    NodeSelectorRequirement(ZONE, Operator.IN, ["test-zone-b"])
                ],
            ),
            fixtures.pod(
                name="b",
                requests={"cpu": "100m"},
                node_requirements=[
                    NodeSelectorRequirement(
                        ZONE, Operator.IN, ["test-zone-a", "test-zone-b"]
                    )
                ],
            ),
            fixtures.pod(name="c", requests={"cpu": "100m"}),
        ]
        return pods

    outs = []
    for kernel in (False, True):
        r, s = solve(build(), kernel=kernel)
        outs.append((r, s))
    (orc, _), (hyb, hs) = outs
    assert hs.used_tpu is True, hs.fallback_reason

    def parts(r):
        return sorted(
            tuple(sorted(p.name for p in c.pods))
            for c in r.new_node_claims
            if c.pods
        )

    assert parts(orc) == parts(hyb)


# ---------------------------------------------------------------------------
# Per-pod error-text parity (nodeclaim.go:296-370)


def _err_texts(r, pods):
    return {p.name: r.pod_errors[p.uid] for p in pods if p.uid in r.pod_errors}


@pytest.mark.parametrize(
    "case",
    ["incompatible-zone", "too-big", "limits", "custom-label-undefined"],
)
def test_error_text_parity_between_paths(case):
    """Failure-heavy problems: the kernel's reconstructed per-pod error
    text must MATCH the oracle's for template-level failures (topology
    failures are allowed a generic message, so none appear here)."""
    pool_kw = {}
    if case == "limits":
        pool_kw["limits"] = {"cpu": "2"}

    def build():
        fixtures.reset_rng(13)
        pool = fixtures.node_pool(name="default", **pool_kw)
        pods = [fixtures.pod(name="ok", requests={"cpu": "100m"})]
        if case == "incompatible-zone":
            pods.append(
                fixtures.pod(
                    name="bad",
                    requests={"cpu": "100m"},
                    node_requirements=[
                        NodeSelectorRequirement(ZONE, Operator.IN, ["mars"])
                    ],
                )
            )
        elif case == "too-big":
            pods.append(fixtures.pod(name="bad", requests={"cpu": "500"}))
        elif case == "limits":
            # FFD: 'ok' (bigger) schedules first and exhausts the cpu=2
            # limit; 'bad' then fails with the limits error on BOTH paths
            pods.append(fixtures.pod(name="bad", requests={"cpu": "1500m"}))
            pods[0] = fixtures.pod(name="ok", requests={"cpu": "1900m"})
        elif case == "custom-label-undefined":
            pods.append(
                fixtures.pod(
                    name="bad",
                    requests={"cpu": "100m"},
                    node_requirements=[
                        NodeSelectorRequirement(
                            "example.com/custom", Operator.IN, ["x"]
                        )
                    ],
                )
            )
        return pool, pods

    outs = []
    for kernel in (False, True):
        pool, pods = build()
        r, s = solve(pods, pools=[pool], kernel=kernel, sizes=(2,))
        outs.append((r, pods, s))
    (orc, opods, _), (hyb, hpods, hs) = outs
    oerr = _err_texts(orc, opods)
    herr = _err_texts(hyb, hpods)
    assert set(oerr) == set(herr) == {"bad"}, (oerr, herr)
    assert oerr["bad"] == herr["bad"], (oerr["bad"], herr["bad"])


def test_error_text_parity_failure_before_limit_exhaustion():
    """Ordering probe: the failing pod (zone=mars) first ATTEMPTS before
    later pods exhaust the cpu limit — but the oracle REQUEUES failures,
    so the error it finally reports comes from the LAST attempt, against
    end-of-solve state: the limits text. The kernel's reconstruction runs
    at end-of-solve state and must produce the same message."""

    def build():
        fixtures.reset_rng(17)
        pool = fixtures.node_pool(name="default", limits={"cpu": "4"})
        pods = [
            fixtures.pod(
                name="bad",
                requests={"cpu": "1800m"},
                node_requirements=[
                    NodeSelectorRequirement(ZONE, Operator.IN, ["mars"])
                ],
            ),
            fixtures.pod(name="w1", requests={"cpu": "1500m"}),
            fixtures.pod(name="w2", requests={"cpu": "1500m"}),
        ]
        return pool, pods

    outs = []
    for kernel in (False, True):
        pool, pods = build()
        r, s = solve(pods, pools=[pool], kernel=kernel, sizes=(2,))
        outs.append((r, pods))
    (orc, opods), (hyb, hpods) = outs
    oerr = _err_texts(orc, opods)
    herr = _err_texts(hyb, hpods)
    assert set(oerr) == set(herr) == {"bad"}, (oerr, herr)
    assert oerr["bad"] == herr["bad"], (oerr["bad"], herr["bad"])
    assert "exceed limits" in herr["bad"], herr["bad"]


def test_error_text_taint_failure_names_the_taint():
    """A tolerationless pod against an all-tainted universe fails with the
    oracle's tolerates_pod message on both paths (can_add checks taints
    FIRST, nodeclaim.go:114)."""

    def build():
        fixtures.reset_rng(19)
        pool = fixtures.node_pool(
            name="tainted",
            taints=[Taint(key="team", value="a", effect=TaintEffect.NO_SCHEDULE)],
        )
        pods = [fixtures.pod(name="bad", requests={"cpu": "100m"})]
        return pool, pods

    outs = []
    for kernel in (False, True):
        pool, pods = build()
        r, s = solve(pods, pools=[pool], kernel=kernel, sizes=(2,))
        outs.append((r, pods))
    (orc, opods), (hyb, hpods) = outs
    oerr = _err_texts(orc, opods)
    herr = _err_texts(hyb, hpods)
    assert set(oerr) == set(herr) == {"bad"}
    assert oerr["bad"] == herr["bad"], (oerr["bad"], herr["bad"])
    assert "team" in herr["bad"], herr["bad"]


def test_error_text_taxonomy():
    """nodeclaim.go:296-370 wording: an unknown ZONE fails at the instance
    type filter ('no instance type met...' — zone is an offering property,
    not a template requirement), while an undefined CUSTOM label fails
    template compat ('incompatible requirements, ...')."""
    r, _ = solve(
        [
            fixtures.pod(
                name="bad",
                requests={"cpu": "100m"},
                node_requirements=[
                    NodeSelectorRequirement(ZONE, Operator.IN, ["mars"])
                ],
            )
        ]
    )
    (text,) = r.pod_errors.values()
    assert "no instance type met the scheduling requirements" in text, text

    r2, _ = solve(
        [
            fixtures.pod(
                name="bad",
                requests={"cpu": "100m"},
                node_requirements=[
                    NodeSelectorRequirement(
                        "example.com/custom", Operator.IN, ["x"]
                    )
                ],
            )
        ]
    )
    (text2,) = r2.pod_errors.values()
    assert "incompatible requirements" in text2, text2
