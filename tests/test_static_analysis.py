"""graftlint gate: fixture-driven positive/negative cases per rule, the
suppression/baseline mechanics, and the real-tree run.

This module (and the analyzer itself) must work without importing JAX —
pure stdlib `ast` — so the gate costs seconds, not a device warmup
(docs/static-analysis.md). The subprocess test below pins the no-JAX
property where conftest's eager jax import can't mask it.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

from karpenter_tpu.analysis import (
    Baseline,
    Config,
    FileContext,
    all_rules,
    run_analysis,
)
from karpenter_tpu.analysis.__main__ import main as graftlint_main

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_rule(rule_id, source, relpath, config=None):
    """Run one rule over inline source posing as `relpath`."""
    rule = next(r for r in all_rules() if r.id == rule_id)
    assert rule.applies_to(relpath), f"{rule_id} must target {relpath}"
    cfg = config or Config(repo_root=REPO_ROOT)
    ctx = FileContext(relpath, relpath, textwrap.dedent(source), cfg)
    return rule.run(ctx)


# ---------------------------------------------------------------------------
# shared-comparator


def test_shared_comparator_flags_inline_key():
    findings = run_rule(
        "shared-comparator",
        """
        def order(pods):
            return sorted(pods, key=lambda p: (p.cpu, p.mem))
        """,
        "karpenter_tpu/solver/oracle.py",
    )
    assert [f.rule for f in findings] == ["shared-comparator"]


def test_shared_comparator_flags_method_sort():
    findings = run_rule(
        "shared-comparator",
        """
        def order(pods):
            pods.sort(key=lambda p: p.uid)
        """,
        "karpenter_tpu/solver/tpu_runs.py",
    )
    assert len(findings) == 1


def test_shared_comparator_allows_ordering_module_key():
    findings = run_rule(
        "shared-comparator",
        """
        from karpenter_tpu.solver.ordering import ffd_sort_key

        def order(pods, data):
            keyless = sorted([3, 1, 2])
            return sorted(pods, key=lambda p: ffd_sort_key(p, data[p.uid]))
        """,
        "karpenter_tpu/solver/oracle.py",
    )
    assert findings == []


# ---------------------------------------------------------------------------
# kernel-purity


def test_kernel_purity_flags_host_sync():
    findings = run_rule(
        "kernel-purity",
        """
        import numpy as np

        def _step(x):
            print("debug", x)
            y = float(x[0])
            z = x.item()
            return np.asarray(x) + y + z
        """,
        "karpenter_tpu/solver/tpu_kernel.py",
    )
    assert len(findings) == 4


def test_kernel_purity_allows_traced_code():
    findings = run_rule(
        "kernel-purity",
        """
        import jax.numpy as jnp

        def _step(x):
            n = int(x.shape[0])
            return jnp.where(x > 0, x, jnp.int32(0)).astype(jnp.float32), n
        """,
        "karpenter_tpu/solver/tpu_kernel.py",
    )
    assert findings == []


# ---------------------------------------------------------------------------
# tracer-leak


def test_tracer_leak_flags_python_branch_on_jnp():
    findings = run_rule(
        "tracer-leak",
        """
        import jax.numpy as jnp

        def _step(mask, x):
            if jnp.any(mask):
                return x + 1
            while jnp.sum(x) > 0:
                x = x - 1
            return x
        """,
        "karpenter_tpu/solver/tpu_runs.py",
    )
    assert len(findings) == 2


def test_tracer_leak_allows_static_and_lax():
    findings = run_rule(
        "tracer-leak",
        """
        import jax
        import jax.numpy as jnp

        def _step(x, E):
            if E > 0:  # static shape, fine
                x = x + 1
            return jax.lax.cond(x.sum() > 0, lambda: x, lambda: -x)
        """,
        "karpenter_tpu/solver/tpu_runs.py",
    )
    assert findings == []


# ---------------------------------------------------------------------------
# dtype-overflow


def test_dtype_overflow_flags_unguarded_accumulation():
    findings = run_rule(
        "dtype-overflow",
        """
        import numpy as np

        def feasibility(counts, sizes):
            caps = counts.astype(np.int32)
            return np.cumsum(caps, axis=0)
        """,
        "karpenter_tpu/controllers/disruption/sweep.py",
    )
    assert len(findings) == 1


def test_dtype_overflow_allows_guarded_accumulation():
    findings = run_rule(
        "dtype-overflow",
        """
        import numpy as np

        def feasibility(counts, sizes):
            worst = counts.astype(np.int64).sum()
            if worst >= (1 << 31):
                raise ValueError("would wrap int32")
            caps = counts.astype(np.int32)
            return np.cumsum(caps, axis=0)
        """,
        "karpenter_tpu/controllers/disruption/sweep.py",
    )
    assert findings == []


# ---------------------------------------------------------------------------
# milli-units


def test_milli_units_flags_division_and_float_literals():
    findings = run_rule(
        "milli-units",
        """
        def shave(requests):
            half = requests["cpu"] / 2
            padded = 1.5 * requests["memory"]
            return half, padded
        """,
        "karpenter_tpu/controllers/provisioning.py",
    )
    assert len(findings) == 2


def test_milli_units_covers_top_level_files_and_zero_literal():
    """`dir/**/*.py` targets must also match direct children (fnmatch has
    no recursive **), and 0.0 is a real float literal, not a falsy miss."""
    findings = run_rule(
        "milli-units",
        """
        def zero(requests):
            return 0.0 * requests["cpu"]
        """,
        "tests/test_x.py",  # top level of tests/, no subdirectory
    )
    assert len(findings) == 1


def test_milli_units_allows_integer_math_and_unrelated_floats():
    findings = run_rule(
        "milli-units",
        """
        def shave(requests, t0, t1):
            half = requests["cpu"] // 2
            speedup = t1 / t0  # seconds, not resources
            return half, speedup
        """,
        "karpenter_tpu/controllers/provisioning.py",
    )
    assert findings == []


# ---------------------------------------------------------------------------
# lock-discipline


def test_lock_discipline_flags_unguarded_write_and_augassign():
    findings = run_rule(
        "lock-discipline",
        """
        import threading

        class Server:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0
                self.state = "idle"

            def guarded(self):
                with self._lock:
                    self.state = "busy"

            def bypass(self):
                self.state = "idle"  # guarded elsewhere, bare here

            def bump(self):
                self.count += 1  # read-modify-write, no lock
        """,
        "karpenter_tpu/solver/service.py",
    )
    assert len(findings) == 2
    assert {"state" in f.message or "count" in f.message for f in findings} == {True}


def test_lock_discipline_allows_guarded_and_locked_suffix():
    findings = run_rule(
        "lock-discipline",
        """
        import threading

        class Server:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def bump(self):
                with self._lock:
                    self.count += 1

            def drain(self):
                with self._lock:
                    self._drain_locked()

            def _drain_locked(self):
                self.count = 0
        """,
        "karpenter_tpu/solver/service.py",
    )
    assert findings == []


# ---------------------------------------------------------------------------
# cache-invalidation


def test_cache_invalidation_flags_bare_mutation():
    findings = run_rule(
        "cache-invalidation",
        """
        def strip_tolerations(pod):
            pod.tolerations = []
            pod.topology_spread_constraints.pop()
        """,
        "karpenter_tpu/solver/tpu_problem.py",
    )
    assert len(findings) == 2


def test_cache_invalidation_allows_invalidating_scope():
    findings = run_rule(
        "cache-invalidation",
        """
        class Preferences:
            def relax(self, pod):
                pod.tolerations = []
                self._invalidate_class_caches(pod)

            @staticmethod
            def _invalidate_class_caches(pod):
                for attr in ("_ktpu_class_key", "_ktpu_class_repr"):
                    if hasattr(pod, attr):
                        delattr(pod, attr)
        """,
        "karpenter_tpu/solver/oracle.py",
    )
    assert findings == []


# ---------------------------------------------------------------------------
# citation-check


@pytest.fixture
def citation_config(tmp_path):
    repo = tmp_path / "repo"
    ref = tmp_path / "reference"
    (repo / "karpenter_tpu" / "solver").mkdir(parents=True)
    (repo / "karpenter_tpu" / "solver" / "ordering.py").write_text(
        "\n".join(f"# line {i}" for i in range(1, 51)) + "\n"
    )
    (ref / "pkg" / "scheduling").mkdir(parents=True)
    (ref / "pkg" / "scheduling" / "scheduler.go").write_text(
        "\n".join(f"// line {i}" for i in range(1, 201)) + "\n"
    )
    return Config(repo_root=str(repo), reference_root=str(ref))


def test_citation_check_flags_unresolvable_and_out_of_bounds(citation_config):
    findings = run_rule(
        "citation-check",
        '''
        def f():
            """Mirrors nosuchfile.go:12 and scheduler.go:999 exactly."""
        ''',
        "karpenter_tpu/solver/x.py",
        config=citation_config,
    )
    msgs = " ".join(f.message for f in findings)
    assert len(findings) == 2
    assert "nosuchfile.go:12" in msgs and "scheduler.go:999" in msgs


def test_citation_check_allows_resolvable_citations(citation_config):
    findings = run_rule(
        "citation-check",
        '''
        def f():
            """Mirrors scheduler.go:100-150 via solver/ordering.py:10."""
        ''',
        "karpenter_tpu/solver/x.py",
        config=citation_config,
    )
    assert findings == []


def test_citation_check_skips_go_without_reference_tree(tmp_path):
    cfg = Config(
        repo_root=str(tmp_path), reference_root=str(tmp_path / "missing")
    )
    findings = run_rule(
        "citation-check",
        '''
        def f():
            """Mirrors scheduler.go:100 (unverifiable: no checkout)."""
        ''',
        "karpenter_tpu/solver/x.py",
        config=cfg,
    )
    assert findings == []


# ---------------------------------------------------------------------------
# pytest-markers


def test_pytest_markers_flags_unregistered():
    cfg = Config(repo_root=REPO_ROOT, markers=frozenset({"faults"}))
    findings = run_rule(
        "pytest-markers",
        """
        import pytest

        @pytest.mark.fautls
        def test_x():
            pass
        """,
        "tests/test_x.py",
        config=cfg,
    )
    assert len(findings) == 1 and "fautls" in findings[0].message


def test_pytest_markers_allows_registered_and_builtin():
    cfg = Config(repo_root=REPO_ROOT, markers=frozenset({"faults", "slow"}))
    findings = run_rule(
        "pytest-markers",
        """
        import pytest

        pytestmark = [pytest.mark.faults, pytest.mark.slow]

        @pytest.mark.parametrize("x", [1, 2])
        def test_x(x):
            pass
        """,
        "tests/test_x.py",
        config=cfg,
    )
    assert findings == []


def test_registered_markers_parsed_from_pyproject():
    cfg = Config.for_repo(REPO_ROOT)
    assert {"slow", "faults", "hard_timeout"} <= cfg.markers


# ---------------------------------------------------------------------------
# metric-naming


def test_metric_naming_flags_prefix_help_and_nonliteral():
    findings = run_rule(
        "metric-naming",
        """
        from karpenter_tpu import metrics

        BAD_PREFIX = metrics.REGISTRY.counter(
            "solver_things_total", "Things.",
        )
        NO_HELP = metrics.REGISTRY.gauge("karpenter_things", "")
        COMPUTED_HELP = metrics.REGISTRY.gauge("karpenter_other", HELP_VAR)
        name = "karpenter_" + kind
        DYNAMIC = metrics.REGISTRY.histogram(name, "Dynamic.")
        """,
        "karpenter_tpu/solver/x.py",
    )
    msgs = " | ".join(f.message for f in findings)
    assert len(findings) == 4
    assert "karpenter_ namespace prefix" in msgs
    assert "non-empty help" in msgs
    assert "string literal" in msgs


def test_metric_naming_flags_duplicates_across_files():
    rule = next(r for r in all_rules() if r.id == "metric-naming")
    import textwrap

    cfg = Config(repo_root=REPO_ROOT)
    src_a = 'X = REGISTRY.counter("karpenter_dup_total", "First.")\n'
    src_b = 'Y = REGISTRY.counter("karpenter_dup_total", "Second.")\n'
    a = FileContext("a.py", "karpenter_tpu/a.py", textwrap.dedent(src_a), cfg)
    b = FileContext("b.py", "karpenter_tpu/b.py", textwrap.dedent(src_b), cfg)
    assert rule.run(a) == []
    dups = rule.run(b)
    assert len(dups) == 1 and "already registered at karpenter_tpu/a.py:1" in dups[0].message


def test_metric_naming_allows_clean_registration_and_foreign_registries():
    findings = run_rule(
        "metric-naming",
        """
        from karpenter_tpu import metrics
        from karpenter_tpu.metrics import Registry

        OK = metrics.REGISTRY.counter(
            "karpenter_good_total",
            "A well-formed registration.",
            ("reason",),
        )
        r = Registry()
        scratch = r.counter("not_karpenter", "")  # private registry: out of scope
        """,
        "karpenter_tpu/controllers/x.py",
    )
    assert findings == []


# ---------------------------------------------------------------------------
# suppression + baseline mechanics


def test_inline_suppression_silences_rule():
    findings = run_rule(
        "milli-units",
        """
        def shave(requests):
            return requests["cpu"] / 2  # graftlint: disable=milli-units
        """,
        "karpenter_tpu/controllers/provisioning.py",
    )
    assert findings == []


def test_def_line_suppression_covers_body():
    findings = run_rule(
        "milli-units",
        """
        # graftlint: disable=milli-units  price math is float by design
        def price(requests):
            a = requests["cpu"] / 2
            b = requests["memory"] / 4
            return a + b
        """,
        "karpenter_tpu/controllers/provisioning.py",
    )
    assert findings == []


def test_standalone_suppression_skips_blanks_and_decorators():
    """A standalone disable comment shields the next CODE line, across
    blank lines / further comments, and covers a decorated def's body."""
    findings = run_rule(
        "milli-units",
        """
        # graftlint: disable=milli-units  price math is float by design

        # (another comment in between)
        @staticmethod
        def price(requests):
            return requests["cpu"] / 2
        """,
        "karpenter_tpu/controllers/provisioning.py",
    )
    assert findings == []


def test_lock_discipline_sees_bare_lock_import():
    findings = run_rule(
        "lock-discipline",
        """
        from threading import Lock

        class Server:
            def __init__(self):
                self._lock = Lock()
                self.count = 0

            def bump(self):
                self.count += 1  # unguarded RMW must still be seen
        """,
        "karpenter_tpu/solver/service.py",
    )
    assert len(findings) == 1


def test_suppression_is_rule_specific():
    findings = run_rule(
        "milli-units",
        """
        def shave(requests):
            return requests["cpu"] / 2  # graftlint: disable=dtype-overflow
        """,
        "karpenter_tpu/controllers/provisioning.py",
    )
    assert len(findings) == 1


def test_baseline_matches_by_text_and_reports_stale():
    from karpenter_tpu.analysis.engine import Finding

    f1 = Finding("r", "a.py", 10, "m", "x = y / 2")
    bl = Baseline(
        [
            {"rule": "r", "path": "a.py", "text": "x = y / 2", "justification": "ok"},
            {"rule": "r", "path": "a.py", "text": "gone()", "justification": "ok"},
        ]
    )
    fresh, stale = bl.apply([f1])
    assert fresh == []
    assert [e["text"] for e in stale] == ["gone()"]
    assert bl.unjustified() == []


def test_cli_rule_subset_does_not_report_stale(capsys):
    """A --rules subset generates only that rule's findings; baseline
    entries for other rules must not read as stale (they'd otherwise be
    reported with 'remove it' advice on every documented per-rule run)."""
    from karpenter_tpu.analysis.__main__ import main as graftlint_main

    rc = graftlint_main(["--root", REPO_ROOT, "--rules", "pytest-markers"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "stale" not in out


def test_cli_unknown_rule_id_exits_2(capsys):
    """A typo'd --rules id must not read as 'nothing to check, clean'."""
    from karpenter_tpu.analysis.__main__ import main as graftlint_main

    rc = graftlint_main(["--root", REPO_ROOT, "--rules", "milli-unitz"])
    assert rc == 2
    assert "unknown rule id" in capsys.readouterr().err


def test_cli_write_baseline_rejects_rule_subset(tmp_path, capsys):
    """--write-baseline from a rule subset would truncate every
    out-of-scope curated entry — same guard as explicit paths."""
    from karpenter_tpu.analysis.__main__ import main as graftlint_main

    bl = tmp_path / "bl.json"
    rc = graftlint_main(
        [
            "--root",
            REPO_ROOT,
            "--rules",
            "milli-units",
            "--write-baseline",
            "--baseline",
            str(bl),
        ]
    )
    assert rc == 2
    assert not bl.exists()


def test_cli_malformed_baseline_exits_2(tmp_path, capsys):
    """A hand-edit typo in the baseline file must surface as the exit-2
    parse diagnostic naming the file, not a JSONDecodeError traceback."""
    from karpenter_tpu.analysis.__main__ import main as graftlint_main

    bad = tmp_path / "baseline.json"
    bad.write_text('{"entries": [,]}', encoding="utf-8")
    rc = graftlint_main(["--root", REPO_ROOT, "--baseline", str(bad)])
    assert rc == 2
    err = capsys.readouterr().err
    assert "cannot parse" in err and str(bad) in err


def test_checked_in_baseline_is_canonical():
    """graftlint.baseline.json must be in the canonical serialization
    `--write-baseline` produces (engine.canonical_json) — otherwise the
    first rewrite after a real change buries the meaningful diff hunk in
    a whole-file key-order churn."""
    import json

    from karpenter_tpu.analysis.engine import canonical_json

    path = os.path.join(REPO_ROOT, "graftlint.baseline.json")
    with open(path, encoding="utf-8") as f:
        content = f.read()
    assert canonical_json(json.loads(content)) == content


# ---------------------------------------------------------------------------
# the real tree


def test_repo_is_clean_under_graftlint():
    """The acceptance gate: zero unbaselined findings, no stale or
    unjustified baseline entries, no parse errors."""
    report = run_analysis(REPO_ROOT)
    assert report["errors"] == []
    assert [f.render() for f in report["findings"]] == []
    assert report["stale"] == []
    assert report["unjustified"] == []


# ---------------------------------------------------------------------------
# wire-enum-coverage (the rule reads the sibling objects.py from disk,
# so its fixtures are tmp files, not inline sources)

_WIRE_OBJECTS = """
    import enum
    from typing import Optional


    class NodePhase(str, enum.Enum):
        READY = "ready"


    class NodeClaim:
        phase: NodePhase
        taint_effect: Optional[NodePhase] = None
        name: str = ""
"""


def _wire_findings(tmp_path, codec_src, objects_src=_WIRE_OBJECTS):
    api = tmp_path / "karpenter_tpu" / "api"
    api.mkdir(parents=True)
    (api / "objects.py").write_text(
        textwrap.dedent(objects_src), encoding="utf-8"
    )
    codec = api / "codec.py"
    codec.write_text(textwrap.dedent(codec_src), encoding="utf-8")
    rule = next(r for r in all_rules() if r.id == "wire-enum-coverage")
    ctx = FileContext(
        str(codec),
        "karpenter_tpu/api/codec.py",
        codec.read_text(encoding="utf-8"),
        Config(repo_root=str(tmp_path)),
    )
    return rule.run(ctx)


def test_wire_enum_coverage_flags_unregistered_field(tmp_path):
    findings = _wire_findings(
        tmp_path,
        """
        _ENUM_FIELDS = {
            "NodeClaim": {"phase": NodePhase},
        }
        """,
    )
    # `taint_effect` is enum-typed through Optional[...] but unregistered
    # — the seed8505 shape: decodes as bare str, crashes on .value
    assert len(findings) == 1
    assert "taint_effect" in findings[0].message


def test_wire_enum_coverage_negative_all_registered(tmp_path):
    findings = _wire_findings(
        tmp_path,
        """
        _ENUM_FIELDS = {
            "NodeClaim": {"phase": NodePhase, "taint_effect": NodePhase},
        }
        """,
    )
    assert findings == []


def test_wire_enum_coverage_flags_missing_literal(tmp_path):
    findings = _wire_findings(tmp_path, "FIELDS = {}\n")
    assert len(findings) == 1
    assert "_ENUM_FIELDS" in findings[0].message


def test_wire_enum_coverage_ignores_plain_fields(tmp_path):
    findings = _wire_findings(
        tmp_path,
        """
        _ENUM_FIELDS = {}
        """,
        objects_src="""
        class NodeClaim:
            name: str = ""
            count: int = 0
        """,
    )
    assert findings == []


def test_wire_enum_coverage_clean_on_real_tree():
    """The real codec registers every enum-typed api field (the contract
    the full-tree run below also implies; pinned here for locality)."""
    codec = os.path.join(REPO_ROOT, "karpenter_tpu", "api", "codec.py")
    rule = next(r for r in all_rules() if r.id == "wire-enum-coverage")
    with open(codec, encoding="utf-8") as f:
        src = f.read()
    ctx = FileContext(
        codec, "karpenter_tpu/api/codec.py", src, Config(repo_root=REPO_ROOT)
    )
    assert rule.run(ctx) == []


def test_every_rule_has_fixture_coverage_here():
    """Adding a rule without positive/negative fixtures fails this."""
    covered = {
        "shared-comparator",
        "kernel-purity",
        "tracer-leak",
        "dtype-overflow",
        "milli-units",
        "lock-discipline",
        "cache-invalidation",
        "citation-check",
        "pytest-markers",
        "metric-naming",
        "wire-enum-coverage",
    }
    assert {r.id for r in all_rules()} == covered


def test_cli_exits_zero_on_clean_tree(capsys):
    assert graftlint_main(["--root", REPO_ROOT]) == 0
    out = capsys.readouterr().out
    assert "0 findings" in out


def test_cli_json_mode(capsys):
    assert graftlint_main(["--root", REPO_ROOT, "--json"]) == 0
    data = json.loads(capsys.readouterr().out)
    assert data["findings"] == [] and data["baselined"] >= 10


def test_cli_exits_nonzero_on_seeded_violation(tmp_path, capsys):
    pkg = tmp_path / "karpenter_tpu" / "controllers"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(
        "def shave(requests):\n    return requests['cpu'] / 2\n"
    )
    assert graftlint_main(["--root", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "milli-units" in out


def test_write_baseline_preserves_existing_justifications(tmp_path, capsys):
    pkg = tmp_path / "karpenter_tpu" / "controllers"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(
        "def shave(requests):\n"
        "    a = requests['cpu'] / 2\n"
        "    b = requests['memory'] / 4\n"
        "    return a, b\n"
    )
    bl = tmp_path / "graftlint.baseline.json"
    bl.write_text(
        json.dumps(
            {
                "entries": [
                    {
                        "rule": "milli-units",
                        "path": "karpenter_tpu/controllers/bad.py",
                        "text": "a = requests['cpu'] / 2",
                        "justification": "curated reason that must survive",
                    }
                ]
            }
        )
    )
    assert graftlint_main(["--root", str(tmp_path), "--write-baseline"]) == 0
    capsys.readouterr()
    data = json.loads(bl.read_text())
    by_text = {e["text"]: e["justification"] for e in data["entries"]}
    assert by_text["a = requests['cpu'] / 2"] == "curated reason that must survive"
    assert by_text["b = requests['memory'] / 4"].startswith("TODO")


def test_write_baseline_refuses_subset_runs(tmp_path, capsys):
    """A subset run sees a slice of the findings; rewriting the baseline
    from it would truncate every out-of-scope curated entry."""
    pkg = tmp_path / "karpenter_tpu"
    pkg.mkdir()
    (pkg / "ok.py").write_text("x = 1\n")
    rc = graftlint_main(
        ["--root", str(tmp_path), str(pkg / "ok.py"), "--write-baseline"]
    )
    assert rc == 2
    assert not (tmp_path / "graftlint.baseline.json").exists()


def test_analysis_package_does_not_import_jax():
    """The lint gate must stay device-free (seconds, not a jax warmup)."""
    code = (
        "import sys; import karpenter_tpu.analysis; "
        "from karpenter_tpu.analysis.__main__ import main; "
        "assert 'jax' not in sys.modules, 'analysis imported jax'; "
        "assert 'numpy' not in sys.modules, 'analysis imported numpy'"
    )
    res = subprocess.run(
        [sys.executable, "-c", code],
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert res.returncode == 0, res.stderr
