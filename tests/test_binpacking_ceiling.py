"""Effective pod requests at intake: the Ceiling rule (reference
pkg/utils/resources/resources.go:113) and its binpacking consequences,
ported from the reference provisioning suite's Binpacking context
(suite_test.go:1515-1829) — init containers, restartable (sidecar) init
containers, limits-as-requests, and pod overhead (VERDICT r5 missing #1).
"""

from __future__ import annotations

from karpenter_tpu.api.objects import Container, Pod
from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.solver import HybridScheduler, SchedulerOptions, Topology
from karpenter_tpu.testing import fixtures
from karpenter_tpu.utils import resources as res
from karpenter_tpu.utils.quantity import parse as q


def c(requests=None, limits=None, restart_policy=None) -> Container:
    return fixtures.container(requests, limits, restart_policy)


# ---------------------------------------------------------------------------
# the Ceiling rule itself (resources.go:113)


def test_main_containers_sum():
    got = res.ceiling([c({"cpu": "1"}), c({"cpu": "2", "memory": "1Gi"})])
    assert got == {"cpu": q("3"), "memory": q("1Gi")}


def test_init_containers_take_the_rolling_max():
    """Init containers run sequentially: the pod needs the LARGEST of
    them, not the sum (suite_test.go: 'should select a larger instance
    if initContainer requires more resources')."""
    got = res.ceiling(
        [c({"cpu": "1"})],
        [c({"cpu": "4"}), c({"cpu": "2"})],
    )
    assert got["cpu"] == q("4")


def test_main_wins_when_bigger_than_init():
    got = res.ceiling([c({"cpu": "3"})], [c({"cpu": "1"})])
    assert got["cpu"] == q("3")


def test_sidecar_rides_alongside_main_containers():
    """A restartable init container (RestartPolicy=Always) is a sidecar:
    its requests ADD to the main containers for the pod's whole life
    (KEP-753 / resources.go:113 restartableInitContainerReqs)."""
    got = res.ceiling(
        [c({"cpu": "1"})],
        [c({"cpu": "500m"}, restart_policy="Always")],
    )
    assert got["cpu"] == q("1500m")


def test_later_init_stacks_on_earlier_sidecars():
    """A non-restartable init container that starts AFTER a sidecar runs
    concurrently with it: its requirement stacks on the sidecar's."""
    got = res.ceiling(
        [c({"cpu": "1"})],
        [
            c({"cpu": "500m"}, restart_policy="Always"),
            c({"cpu": "2"}),  # runs while the sidecar holds 500m
        ],
    )
    # max(main 1 + sidecar 0.5, init 2 + sidecar 0.5) = 2.5
    assert got["cpu"] == q("2500m")


def test_sidecars_accumulate():
    got = res.ceiling(
        [c({"cpu": "1"})],
        [
            c({"cpu": "250m"}, restart_policy="Always"),
            c({"cpu": "250m"}, restart_policy="Always"),
        ],
    )
    assert got["cpu"] == q("1500m")


def test_limits_act_as_requests_when_requests_absent():
    """resources.go:96 MergeResourceLimitsIntoRequests: a resource present
    only in limits counts as its request."""
    got = res.ceiling([c(limits={"cpu": "2"})], [c(limits={"cpu": "3"})])
    assert got["cpu"] == q("3")
    # an explicit request wins over the limit
    got = res.ceiling([c({"cpu": "1"}, limits={"cpu": "2"})])
    assert got["cpu"] == q("1")


def test_overhead_added_on_top():
    """pod.Spec.Overhead (RuntimeClass) is charged to the pod on top of
    the container ceiling (suite_test.go: 'should take pod runtime class
    overhead into account')."""
    got = res.ceiling([c({"cpu": "1"})], overhead={"cpu": q("250m")})
    assert got["cpu"] == q("1250m")


def test_pod_resolves_effective_requests_at_intake():
    """Pod.__post_init__ collapses container-level specs into `requests`
    — every downstream consumer (solver encoding, binpacking, the wire)
    sees only the resolved form."""
    p = Pod(
        containers=[Container(requests={"cpu": q("1")})],
        init_containers=[Container(requests={"cpu": q("4")})],
        overhead={"cpu": q("100m")},
    )
    assert p.requests["cpu"] == q("4100m")
    # explicit requests are authoritative (codec round-trips, deep copies)
    p2 = Pod(requests={"cpu": q("7")}, containers=[Container(requests={"cpu": q("1")})])
    assert p2.requests["cpu"] == q("7")


def test_containers_survive_the_codec():
    from karpenter_tpu.api import codec

    p = fixtures.pod(
        name="x",
        requests={"cpu": "1"},
        init_containers=[c({"cpu": "4"}, restart_policy=None)],
        overhead={"cpu": "100m"},
    )
    rt = codec.from_jsonable(codec.to_jsonable(p))
    assert rt.requests == p.requests
    assert rt.requests["cpu"] == q("4100m")


# ---------------------------------------------------------------------------
# binpacking through the scheduler (suite_test.go:1515-1829)


def _solve(pods, sizes):
    fixtures.reset_rng(3)
    its = construct_instance_types(sizes=sizes)
    pools = [fixtures.node_pool(name="default")]
    topo = Topology(pools, {"default": its}, pods)
    s = HybridScheduler(
        pools, {"default": its}, topo, None, None, SchedulerOptions(),
        force_oracle=True,
    )
    return s.solve(pods)


def _min_cpu(claim) -> int:
    return min(it.capacity[res.CPU] for it in claim.instance_type_options)


def test_selects_larger_instance_for_hungry_init_container():
    """suite_test.go: 'should select a larger instance if initContainer
    requires more resources' — the main container alone fits a 2-cpu
    node; the init container forces a 16-cpu one."""
    p = fixtures.pod(
        name="init-hungry",
        requests={"cpu": "1"},
        init_containers=[c({"cpu": "10"})],
    )
    r = _solve([p], sizes=[2, 16])
    assert not r.pod_errors
    (claim,) = [cl for cl in r.new_node_claims if cl.pods]
    assert _min_cpu(claim) >= q("10")


def test_unschedulable_when_init_container_exceeds_every_instance():
    """suite_test.go: 'should not schedule if initContainer resources are
    too large'."""
    p = fixtures.pod(
        name="init-huge",
        requests={"cpu": "1"},
        init_containers=[c({"cpu": "100"})],
    )
    r = _solve([p], sizes=[2, 8])
    assert p.uid in r.pod_errors
    assert not any(cl.pods for cl in r.new_node_claims)


def test_schedules_with_no_requests_or_limits():
    """suite_test.go: 'should be able to schedule pods if resource
    requests and limits are not defined'."""
    p = Pod(containers=[Container()], init_containers=[Container()])
    p.metadata.name = "empty"
    r = _solve([p], sizes=[2])
    assert not r.pod_errors


def test_overhead_packs_fewer_pods_per_node():
    """Overhead is charged per pod: two 700m pods fit one 2-cpu node, but
    with 500m overhead each they no longer share it."""
    def mk(i, overhead):
        return fixtures.pod(
            name=f"p-{i}", requests={"cpu": "700m"}, overhead=overhead
        )

    r_plain = _solve([mk(0, None), mk(1, None)], sizes=[2])
    assert not r_plain.pod_errors
    assert len([cl for cl in r_plain.new_node_claims if cl.pods]) == 1

    r_heavy = _solve(
        [mk(0, {"cpu": "500m"}), mk(1, {"cpu": "500m"})], sizes=[2]
    )
    assert not r_heavy.pod_errors
    assert len([cl for cl in r_heavy.new_node_claims if cl.pods]) == 2


def test_sidecar_requests_count_toward_the_claim():
    """Sidecar (restartable init) requests ride the claim's running total,
    not just the transient init peak."""
    p = fixtures.pod(
        name="with-sidecar",
        requests={"cpu": "1"},
        init_containers=[c({"cpu": "1"}, restart_policy="Always")],
    )
    r = _solve([p], sizes=[4])
    assert not r.pod_errors
    (claim,) = [cl for cl in r.new_node_claims if cl.pods]
    assert claim.requests[res.CPU] >= q("2")
