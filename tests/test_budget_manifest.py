"""kernel_budgets.json manifest mechanics (analysis/budgets.py).

Pure stdlib — budgets.py must stay importable without JAX so these run
in milliseconds. The live measurements side of the manifest is exercised
by tests/test_ir_analysis.py; here the contract is the FILE: canonical
byte-stable serialization (a `--write-budgets` re-write with unchanged
content is byte-identical), justification policing, and stale/orphan
detection mirroring graftlint.baseline.json.
"""

from __future__ import annotations

import json
import os
import random
import string

from karpenter_tpu.analysis import budgets as B

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_checked_in_manifest_is_canonical_and_justified():
    path = os.path.join(REPO_ROOT, B.DEFAULT_MANIFEST)
    m = B.BudgetManifest.load(path)
    assert m.entries, "the checked-in manifest must not be empty"
    with open(path, encoding="utf-8") as f:
        content = f.read()
    assert B.BudgetManifest.dumps({"entries": m.entries}) == content, (
        "kernel_budgets.json is not in canonical form — regenerate with "
        "`graftlint --ir --write-budgets` (it preserves justifications)"
    )
    assert m.unjustified() == []
    # every budgeted metric is one the tool knows how to enforce
    for name, e in m.entries.items():
        for metric in e.get("metrics", {}):
            assert metric in B.METRIC_POLICY, (name, metric)


def test_write_budgets_roundtrip_property():
    """Property: render -> dumps -> load -> render -> dumps is a fixed
    point (byte-identical), and a manifest compared against its own
    measurements is clean — across randomized entry/metric subsets."""
    rng = random.Random(0xBEEF)
    metric_names = sorted(B.METRIC_POLICY)
    for _ in range(25):
        measured = {}
        for i in range(rng.randint(1, 6)):
            name = (
                "".join(rng.choice(string.ascii_lowercase) for _ in range(8))
                + f"[case={i}]"
            )
            picks = rng.sample(
                metric_names, rng.randint(1, len(metric_names))
            )
            measured[name] = {m: rng.randint(0, 1 << 20) for m in picks}
        data = B.BudgetManifest.render(measured)
        s1 = B.BudgetManifest.dumps(data)
        loaded = B.BudgetManifest(json.loads(s1)["entries"])
        measured2 = {
            k: dict(e["metrics"]) for k, e in loaded.entries.items()
        }
        s2 = B.BudgetManifest.dumps(
            B.BudgetManifest.render(measured2, loaded)
        )
        assert s2 == s1
        cmp = loaded.compare(measured2)
        assert cmp.issues == []
        assert cmp.improvements == []


def test_render_preserves_existing_justifications():
    existing = B.BudgetManifest(
        {
            "kept": {
                "justification": "hand-written reason",
                "metrics": {"while_loops": 1},
            }
        }
    )
    data = B.BudgetManifest.render(
        {"kept": {"while_loops": 2}, "new": {"scans": 0}}, existing
    )
    assert data["entries"]["kept"]["justification"] == "hand-written reason"
    assert data["entries"]["new"]["justification"].startswith("TODO")


def test_orphaned_and_missing_entries_policed():
    m = B.BudgetManifest(
        {"gone_kernel": {"justification": "x", "metrics": {"scans": 1}}}
    )
    cmp = m.compare({"new_kernel": {"scans": 1}})
    kinds = sorted(i.kind for i in cmp.issues)
    assert kinds == ["missing-entry", "orphaned-entry"]


def test_exact_policy_flags_any_drift():
    m = B.BudgetManifest(
        {"k": {"justification": "x", "metrics": {"while_loops": 2}}}
    )
    for measured_loops in (1, 3):
        cmp = m.compare({"k": {"while_loops": measured_loops}})
        assert [i.kind for i in cmp.issues] == ["structure-mismatch"]
    assert m.compare({"k": {"while_loops": 2}}).issues == []


def test_ceiling_policy_flags_only_growth():
    m = B.BudgetManifest(
        {"k": {"justification": "x", "metrics": {"max_carry_bytes": 100}}}
    )
    over = m.compare({"k": {"max_carry_bytes": 101}})
    assert [i.kind for i in over.issues] == ["regression"]
    under = m.compare({"k": {"max_carry_bytes": 99}})
    assert under.issues == [] and len(under.improvements) == 1
    note = under.improvements[0]
    assert note.kind == "improvement"
    # the note must state the actual relation (under, not exceeding)
    assert "under the budget" in note.render()
    assert "exceeds" not in note.render()


def test_unknown_and_stale_metrics_policed():
    # manifest carries a metric the tool doesn't know -> unknown-metric;
    # tool measures a metric the manifest lacks -> missing-metric
    m = B.BudgetManifest(
        {
            "k": {
                "justification": "x",
                "metrics": {"scans": 1, "typo_metric": 5},
            }
        }
    )
    cmp = m.compare({"k": {"scans": 1, "while_loops": 0}})
    kinds = sorted(i.kind for i in cmp.issues)
    assert kinds == ["missing-metric", "unknown-metric"]


def test_issue_render_strings_are_actionable():
    issues = [
        B.BudgetIssue("regression", "k", "max_carry_bytes", 10, 20),
        B.BudgetIssue("structure-mismatch", "k", "while_loops", 1, 2),
        B.BudgetIssue("missing-entry", "k", None, None, None),
        B.BudgetIssue("orphaned-entry", "k", None, None, None),
        B.BudgetIssue("missing-metric", "k", "scans", None, 1),
        B.BudgetIssue("unknown-metric", "k", "zzz", 1, None),
        B.BudgetIssue("improvement", "k", "max_carry_bytes", 10, 5),
    ]
    for issue in issues:
        text = issue.render()
        assert "k" in text and text  # every kind renders something useful
    assert "--write-budgets" in issues[0].render()
