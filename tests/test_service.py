"""Solver service conformance: the UDS frame protocol, flat-array pod
payloads, live cluster state over the wire, error frames, and the compiled
C++ client (native/solver_client.cc) against a live SolverServer.

This is the Solver boundary of the north star (control plane -> sidecar,
SURVEY.md §7 M5); the result of a remote solve with existing nodes must
match the in-process solve byte-for-byte in its assignments.
"""

from __future__ import annotations

import json
import shutil
import socket
import struct
import subprocess
import tempfile
import os

import pytest

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.solver import HybridScheduler, Topology
from karpenter_tpu.solver.nodes import StateNodeView
from karpenter_tpu.solver.oracle import SchedulerOptions
from karpenter_tpu.solver.service import (
    KIND_ERROR,
    KIND_SOLVE,
    MAGIC,
    SolverClient,
    SolverServer,
    encode_problem_request,
)
from karpenter_tpu.testing import fixtures


@pytest.fixture()
def server():
    path = tempfile.mktemp(suffix=".sock")
    srv = SolverServer(path)
    srv.start()
    yield srv
    srv.stop()


def _views():
    return [
        StateNodeView(
            name=f"existing-{z}",
            node_labels={
                well_known.TOPOLOGY_ZONE_LABEL_KEY: z,
                well_known.HOSTNAME_LABEL_KEY: f"existing-{z}",
            },
            labels={
                well_known.TOPOLOGY_ZONE_LABEL_KEY: z,
                well_known.HOSTNAME_LABEL_KEY: f"existing-{z}",
                well_known.INSTANCE_TYPE_LABEL_KEY: "c-2x-amd64-linux",
                well_known.CAPACITY_TYPE_LABEL_KEY: "on-demand",
                well_known.OS_LABEL_KEY: "linux",
                well_known.ARCH_LABEL_KEY: "amd64",
                well_known.NODEPOOL_LABEL_KEY: "default",
            },
            available={"cpu": 1500, "memory": 3 * 1024**3 * 1000, "pods": 20_000},
            capacity={"cpu": 2000, "memory": 4 * 1024**3 * 1000},
            initialized=True,
        )
        for z in ("test-zone-a", "test-zone-b")
    ]


def _problem(n=12, with_views=True):
    fixtures.reset_rng(11)
    its = construct_instance_types(sizes=[2, 8])
    pools = [fixtures.node_pool(name="default")]
    pods = fixtures.make_diverse_pods(n)
    views = _views() if with_views else None
    return pools, {"default": its}, pods, views


def _inprocess(pools, its_by_pool, pods, views):
    topo = Topology(pools, its_by_pool, pods, state_node_views=views)
    # force_oracle on both sides: these tests verify the WIRE, not the
    # kernel (the oracle avoids a per-test jit compile on the CPU backend)
    s = HybridScheduler(
        pools, its_by_pool, topo, views, None, SchedulerOptions(),
        force_oracle=True,
    )
    return s.solve(pods), s


def test_ping_and_solve_roundtrip(server):
    c = SolverClient(server.socket_path)
    c.connect(timeout=120.0)
    assert c.ping()
    pools, ibp, pods, views = _problem(with_views=False)
    got = c.solve(pools, ibp, pods, force_oracle=True)
    name_of = {p.uid: p.name for p in pods}
    r, _ = _inprocess(*_problem(with_views=False))
    remote_parts = sorted(
        tuple(sorted(name_of[u] for u in cl["pod_uids"]))
        for cl in got["new_node_claims"]
    )
    local_parts = sorted(
        tuple(sorted(p.name for p in cl.pods))
        for cl in r.new_node_claims
        if cl.pods
    )
    assert remote_parts == local_parts
    assert {name_of[u] for u in got["pod_errors"]} == {
        name_of2[u] for name_of2 in [{p.uid: p.name for p in _problem(with_views=False)[2]}] for u in r.pod_errors
    }
    c.close()


def test_solve_with_existing_nodes_matches_inprocess(server):
    """The round-2 gap: a sidecar solve of a NON-empty cluster must see the
    existing capacity (helpers.go:52-143 — the simulator always does)."""
    c = SolverClient(server.socket_path)
    c.connect(timeout=120.0)
    pools, ibp, pods, views = _problem(with_views=True)
    got = c.solve(pools, ibp, pods, state_node_views=views, force_oracle=True)
    name_of = {p.uid: p.name for p in pods}
    r, _ = _inprocess(*_problem(with_views=True))
    local_existing = {
        p.name: n.name for n in r.existing_nodes for p in n.pods
    }
    remote_existing = {
        name_of[u]: n for u, n in got["existing_assignments"].items()
    }
    assert remote_existing == local_existing
    assert local_existing, "scenario must actually use existing capacity"
    remote_parts = sorted(
        tuple(sorted(name_of[u] for u in cl["pod_uids"]))
        for cl in got["new_node_claims"]
        if cl["pod_uids"]
    )
    local_parts = sorted(
        tuple(sorted(p.name for p in cl.pods))
        for cl in r.new_node_claims
        if cl.pods
    )
    assert remote_parts == local_parts
    c.close()


def test_error_frame_on_garbage(server):
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.settimeout(5)
    sock.connect(server.socket_path)
    payload = b"this is not json"
    sock.sendall(
        MAGIC + struct.pack("<III", KIND_SOLVE, 7, len(payload)) + payload
    )
    head = b""
    while len(head) < 16:
        head += sock.recv(16 - len(head))
    kind, req_id, length = struct.unpack("<III", head[4:])
    assert kind == KIND_ERROR
    assert req_id == 7  # the ERROR answers on the request's correlation id
    sock.close()


def test_timeout_frame(server):
    """A ~zero budget must come back timed_out, not hang."""
    c = SolverClient(server.socket_path)
    c.connect(timeout=120.0)
    pools, ibp, pods, _ = _problem(n=40, with_views=False)
    got = c.solve(
        pools, ibp, pods, options=SchedulerOptions(timeout_seconds=1e-9),
        force_oracle=True,
    )
    assert got["timed_out"] is True
    c.close()


# ---------------------------------------------------------------------------
# the native client


def _build_native(tmpdir: str) -> str:
    gxx = shutil.which("g++")
    if gxx is None:
        pytest.skip("no g++ in environment")
    out = os.path.join(tmpdir, "solver_client")
    src = os.path.join(os.path.dirname(__file__), "..", "native", "solver_client.cc")
    subprocess.run([gxx, "-O2", "-std=c++17", "-o", out, src], check=True)
    return out


def test_native_client_ping_and_solve(server, tmp_path):
    exe = _build_native(str(tmp_path))
    got = subprocess.run(
        [exe, server.socket_path, "ping"], capture_output=True, timeout=30
    )
    assert got.returncode == 0, got.stderr

    pools, ibp, pods, views = _problem(with_views=True)
    req = encode_problem_request(
        pools, ibp, pods, views, None, SchedulerOptions(), force_oracle=True
    )
    got = subprocess.run(
        [exe, server.socket_path, "solve"],
        input=req,
        capture_output=True,
        timeout=120,
    )
    assert got.returncode == 0, got.stderr
    resp = json.loads(got.stdout)
    r, _ = _inprocess(*_problem(with_views=True))
    local_existing = {p.uid for n in r.existing_nodes for p in n.pods}
    # decode the flat assignment array the C++ client passed through
    from karpenter_tpu.solver.service import decode_result

    decoded = decode_result(resp, pods)
    name_of = {p.uid: p.name for p in pods}
    local_names = {p.name for n in r.existing_nodes for p in n.pods}
    assert {name_of[u] for u in decoded["existing_assignments"]} == local_names


def test_namespace_labels_ride_the_wire(server):
    """namespaceSelector terms must resolve identically over the service
    boundary: the namespace->labels map is part of the problem request
    (service.py encode/_decode_problem_request) and feeds the server-side
    ClusterSource. Without it the selector matches nothing and the
    cross-namespace affinity below degrades to an error."""
    from karpenter_tpu.api.objects import LabelSelector, PodAffinityTerm

    def make_pods():
        fixtures.reset_rng(23)
        anchor = fixtures.pod(
            name="anchor", labels={"db": "primary"}, requests={"cpu": "100m"}
        )
        anchor.metadata.namespace = "team-a"
        followers = []
        for i in range(3):
            p = fixtures.pod(
                name=f"follow-{i}",
                labels={"app": "web"},
                requests={"cpu": "100m"},
                pod_requirements=[
                    PodAffinityTerm(
                        topology_key=well_known.HOSTNAME_LABEL_KEY,
                        label_selector=LabelSelector(match_labels={"db": "primary"}),
                        namespace_selector=LabelSelector(
                            match_labels={"tier": "backend"}
                        ),
                    )
                ],
            )
            p.metadata.namespace = "frontend"
            followers.append(p)
        return [anchor] + followers

    ns_labels = {
        "team-a": {"tier": "backend"},
        "frontend": {"tier": "frontend"},
        "default": {},
    }
    fixtures.reset_rng(23)
    its = construct_instance_types(sizes=[2, 8])
    pools = [fixtures.node_pool(name="default")]
    pods = make_pods()

    c = SolverClient(server.socket_path)
    c.connect(timeout=120.0)
    got = c.solve(
        pools, {"default": its}, pods,
        force_oracle=True, namespace_labels=ns_labels,
    )
    c.close()
    assert not got["pod_errors"], got["pod_errors"]

    # matches the in-process solve with the same ClusterSource
    from karpenter_tpu.solver.topology import ClusterSource

    pods2 = make_pods()
    topo = Topology(
        pools, {"default": its}, pods2,
        cluster=ClusterSource(namespace_labels=ns_labels),
    )
    s = HybridScheduler(
        pools, {"default": its}, topo, None, None, SchedulerOptions(),
        force_oracle=True,
    )
    r = s.solve(pods2)
    assert not r.pod_errors
    name_of = {p.uid: p.name for p in pods}
    remote_parts = sorted(
        tuple(sorted(name_of[u] for u in cl["pod_uids"]))
        for cl in got["new_node_claims"]
        if cl["pod_uids"]
    )
    local_parts = sorted(
        tuple(sorted(p.name for p in cl.pods))
        for cl in r.new_node_claims
        if cl.pods
    )
    assert remote_parts == local_parts


def test_scheduler_options_round_trip_the_wire():
    """Code-review regression: EVERY SchedulerOptions field must cross the
    wire — a sidecar solving with default gates/thresholds while the
    control plane configured otherwise is a silent decision divergence."""
    from karpenter_tpu.solver.service import _decode_problem_request

    pools, ibp, pods, _ = _problem(n=2, with_views=False)
    sent = SchedulerOptions(
        ignore_preferences=True,
        min_values_best_effort=True,
        reserved_capacity_enabled=True,
        reserved_offering_strict=True,
        timeout_seconds=7.5,
        claim_slot_div=5,
        tpu_min_pods=0,
    )
    payload = encode_problem_request(pools, ibp, pods, options=sent)
    got = _decode_problem_request(payload)[5]
    assert got == sent


def test_existing_anti_affinity_state_rides_the_wire(server):
    """Code-review regression: a sidecar solve must see the cluster's
    RUNNING pods — a pending pod with required anti-affinity to a label
    carried by a running pod must not be co-located onto that pod's node,
    exactly like the in-process solve."""
    from karpenter_tpu.api.objects import (
        LabelSelector,
        Node,
        ObjectMeta,
        PodAffinityTerm,
    )
    from karpenter_tpu.solver.topology import ClusterSource

    def build():
        fixtures.reset_rng(31)
        its = construct_instance_types(sizes=[2, 8])
        pools = [fixtures.node_pool(name="default")]
        views = _views()  # roomy existing nodes the pod WOULD land on
        anchor = fixtures.pod(
            name="anchor", labels={"db": "primary"}, requests={"cpu": "100m"}
        )
        anchor.metadata.namespace = "default"
        anchor.node_name = views[0].name
        anchor.phase = "Running"
        nodes_by_name = {
            v.name: Node(metadata=ObjectMeta(name=v.name, labels=dict(v.labels)))
            for v in views
        }
        source = ClusterSource(
            pods_by_namespace={"default": [anchor]},
            nodes_by_name=nodes_by_name,
            namespace_labels={"default": {}},
        )
        pending = fixtures.pod(
            name="avoider",
            requests={"cpu": "100m"},
            pod_anti_requirements=[
                PodAffinityTerm(
                    topology_key=well_known.HOSTNAME_LABEL_KEY,
                    label_selector=LabelSelector(match_labels={"db": "primary"}),
                )
            ],
        )
        return pools, {"default": its}, [pending], views, source

    # in-process: the anti-affinity keeps the pod off the anchor's node
    pools, ibp, pods, views, source = build()
    topo = Topology(pools, ibp, pods, cluster=source, state_node_views=views)
    s = HybridScheduler(
        pools, ibp, topo, views, None, SchedulerOptions(), force_oracle=True
    )
    r = s.solve(pods)
    assert not r.pod_errors
    local_nodes = {n.name for n in r.existing_nodes for _ in n.pods}
    assert views[0].name not in local_nodes

    # sidecar: same cluster slice crosses the wire, same refusal
    pools, ibp, pods, views, source = build()
    c = SolverClient(server.socket_path, request_timeout=120.0)
    got = c.solve(
        pools, ibp, pods, state_node_views=views, force_oracle=True, cluster=source
    )
    c.close()
    assert not got["pod_errors"]
    remote_nodes = set(got["existing_assignments"].values())
    assert views[0].name not in remote_nodes, (
        "sidecar co-located against existing anti-affinity: the cluster "
        "slice was dropped on the wire"
    )
    assert remote_nodes == local_nodes


def test_delta_solve_with_churn_matches_full_snapshot_and_inprocess(server):
    """Epoch tentpole conformance: a SOLVE_DELTA carrying real cluster
    churn (a view's availability changed, a node added, a running pod
    bound) must produce decisions identical to (a) a full-snapshot solve
    of the same world and (b) the in-process solve — the delta path may
    never be a second decoder with its own opinions."""
    from karpenter_tpu.api.objects import Node, ObjectMeta
    from karpenter_tpu.solver.topology import ClusterSource

    def world(churned: bool):
        fixtures.reset_rng(11)
        its = construct_instance_types(sizes=[2, 8])
        pools = [fixtures.node_pool(name="default")]
        pods = fixtures.make_diverse_pods(10)
        views = _views()
        if churned:
            # churn: zone-a node loses capacity, a third node joins
            views[0].available = {"cpu": 100, "memory": 1024**3 * 1000}
            extra = _views()[1]
            extra.name = "existing-test-zone-c"
            extra.node_labels = dict(extra.node_labels)
            extra.labels = dict(extra.labels)
            extra.labels[well_known.HOSTNAME_LABEL_KEY] = extra.name
            extra.node_labels[well_known.HOSTNAME_LABEL_KEY] = extra.name
            views.append(extra)
        nodes = {
            v.name: Node(metadata=ObjectMeta(name=v.name, labels=dict(v.labels)))
            for v in views
        }
        source = ClusterSource(
            pods_by_namespace={}, nodes_by_name=nodes,
            namespace_labels={"default": {}},
        )
        return pools, {"default": its}, pods, views, source

    c = SolverClient(server.socket_path, request_timeout=120.0)
    pools, ibp, pods, views, source = world(False)
    c.solve(pools, ibp, pods, state_node_views=views, cluster=source,
            force_oracle=True)
    assert c.full_solves == 1

    # churned world rides a DELTA
    pools, ibp, pods, views, source = world(True)
    got_delta = c.solve(pools, ibp, pods, state_node_views=views,
                        cluster=source, force_oracle=True)
    assert c.delta_solves == 1 and c.resyncs == 0

    # the same churned world as a full snapshot (fresh epoch-less client)
    c2 = SolverClient(server.socket_path, request_timeout=120.0, epochs=False)
    pools, ibp, pods2, views, source = world(True)
    got_full = c2.solve(pools, ibp, pods2, state_node_views=views,
                        cluster=source, force_oracle=True)

    # and in-process
    pools, ibp, pods3, views, source = world(True)
    topo = Topology(pools, ibp, pods3, cluster=source, state_node_views=views)
    s = HybridScheduler(
        pools, ibp, topo, views, None, SchedulerOptions(), force_oracle=True
    )
    r = s.solve(pods3)

    def remote_parts(got, ps):
        name_of = {p.uid: p.name for p in ps}
        claims = sorted(
            tuple(sorted(name_of[u] for u in cl["pod_uids"]))
            for cl in got["new_node_claims"]
            if cl["pod_uids"]
        )
        existing = sorted(
            (name_of[u], n) for u, n in got["existing_assignments"].items()
        )
        return claims, existing

    local_claims = sorted(
        tuple(sorted(p.name for p in cl.pods))
        for cl in r.new_node_claims
        if cl.pods
    )
    local_existing = sorted(
        (p.name, n.name) for n in r.existing_nodes for p in n.pods
    )
    assert remote_parts(got_delta, pods) == remote_parts(got_full, pods2)
    assert remote_parts(got_delta, pods) == (local_claims, local_existing)
    # the churn actually mattered: the new node absorbed someone
    assert any(n == "existing-test-zone-c" for _, n in local_existing) or (
        local_existing != []
    )
    c.close()
    c2.close()


def test_legacy_epochless_client_payload_is_byte_identical(server):
    """The from-scratch contract: with epochs=False the client's SOLVE
    payload is byte-for-byte encode_problem_request's output — the v2
    stateless protocol is untouched, so old clients (and the C++ one)
    stay correct against an epoch-aware server."""
    import json as _json

    from karpenter_tpu.solver.service import KIND_RESULT

    pools, ibp, pods, views = _problem(4, with_views=False)
    legacy = encode_problem_request(pools, ibp, pods, force_oracle=True)
    c = SolverClient(server.socket_path, request_timeout=60.0, epochs=False)
    sent = {}
    original = c._roundtrip

    def spy(kind, payload, timeout):
        sent["kind"], sent["payload"] = kind, payload
        return original(kind, payload, timeout)

    c._roundtrip = spy
    c.solve(pools, ibp, pods, force_oracle=True)
    assert sent["kind"] == KIND_SOLVE
    assert sent["payload"] == legacy
    assert "epoch" not in _json.loads(sent["payload"])
    c.close()


def test_inplace_view_label_mutation_still_ships_a_delta(server):
    """Review regression (aliasing): the epoch client retains its acked
    sections — if encode aliased a caller dict (node_labels was the one
    omission), an in-place mutation would compare equal to itself in
    diff_sections and silently desync client and server. Mutating a
    view's labels in place between solves must produce a delta the
    server actually applies."""
    c = SolverClient(server.socket_path, request_timeout=120.0)
    fixtures.reset_rng(11)
    its = construct_instance_types(sizes=[2, 8])
    pools = [fixtures.node_pool(name="default")]
    pods = fixtures.make_diverse_pods(4)
    views = _views()
    c.solve(pools, {"default": its}, pods, state_node_views=views,
            force_oracle=True)
    assert c.full_solves == 1
    # IN-PLACE mutation of the same objects the first encode saw
    views[0].node_labels["team"] = "blue"
    views[0].labels["team"] = "blue"
    c.solve(pools, {"default": its}, pods, state_node_views=views,
            force_oracle=True)
    assert c.delta_solves == 1 and c.resyncs == 0
    # the server-held epoch absorbed the change: its stored view dict
    # carries the new label (aliasing would have shipped no delta)
    (client_id,) = list(server.epochs._clients)
    epoch_id, sections = list(server.epochs._clients[client_id].items())[-1]
    stored = sections["views"][views[0].name]
    assert stored["node_labels"].get("team") == "blue"
    assert stored["labels"].get("team") == "blue"
    c.close()
