"""Fault-injection harness for the solver service boundary (ISSUE tentpole).

A programmable proxy sits between SolverClient and SolverServer and
delays, truncates, corrupts, and black-holes frames; further scenarios
kill the server mid-solve and crash-loop it. The assertions are the
resilience contract (docs/resilience.md):

- no client call ever blocks past its deadline;
- a partial read after timeout poisons the connection (tear down and
  reconnect — never resynchronize mid-stream);
- the server answers ERROR instead of dying, survives anything a
  connection handler throws, serves connections concurrently, and drains
  in-flight solves on stop;
- the provisioning loop binds every pending pod via in-process fallback
  in the SAME reconcile the sidecar dies, and the circuit breaker closes
  again after the sidecar returns (chaos_test.go:48-90's convergence
  demand, applied to the service boundary).

Every test carries a SIGALRM-backed hard timeout (tests/conftest.py): a
bug that wedges a socket fails fast instead of hanging tier-1.
"""

from __future__ import annotations

import json
import socket
import struct
import tempfile
import threading
import time

import pytest

from karpenter_tpu import logging as klog
from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.solver import (
    CircuitBreaker,
    HybridScheduler,
    ResilientSolver,
    SchedulerOptions,
    Topology,
)
from karpenter_tpu.solver.hybrid import SIDECAR_REQUESTS, SOLVER_FALLBACK
from karpenter_tpu.solver.service import (
    KIND_EPOCH_RESYNC,
    KIND_ERROR,
    KIND_PING,
    KIND_PONG,
    KIND_RESULT,
    KIND_RETRY,
    KIND_SOLVE,
    KIND_SOLVE_DELTA,
    MAGIC,
    MAX_FRAME_LEN,
    ProtocolError,
    SolverClient,
    SolverError,
    SolverOverloaded,
    SolverServer,
    SolverUnavailable,
)
from karpenter_tpu.testing import fixtures

# the fault-injection proxy: shared with the differential chaos fuzzer
# (testing/fuzz.py chaos mode replays seeded cases through the same
# man-in-the-middle), so fault modes live in exactly one place
from karpenter_tpu.testing.faults import FaultyProxy

pytestmark = [pytest.mark.faults, pytest.mark.hard_timeout(120)]


# ---------------------------------------------------------------------------
# fixtures


@pytest.fixture()
def server():
    path = tempfile.mktemp(suffix=".sock")
    srv = SolverServer(path)
    srv.start()
    yield srv
    srv.stop()


@pytest.fixture()
def proxy(server):
    path = tempfile.mktemp(suffix=".proxy.sock")
    p = FaultyProxy(path, server.socket_path)
    yield p
    p.stop()


def _problem(n=6):
    fixtures.reset_rng(11)
    its = construct_instance_types(sizes=[2, 8])
    pools = [fixtures.node_pool(name="default")]
    pods = fixtures.make_diverse_pods(n)
    return pools, {"default": its}, pods


def _remote_parts(got, pods):
    name_of = {p.uid: p.name for p in pods}
    return sorted(
        tuple(sorted(name_of[u] for u in cl["pod_uids"]))
        for cl in got["new_node_claims"]
        if cl["pod_uids"]
    )


# ---------------------------------------------------------------------------
# client deadlines & reconnect


def test_blackhole_never_blocks_past_deadline(proxy):
    proxy.set_fault("blackhole", once=False)
    c = SolverClient(proxy.listen_path, request_timeout=0.6, max_retries=0)
    pools, ibp, pods = _problem()
    t0 = time.monotonic()
    with pytest.raises(SolverUnavailable):
        c.solve(pools, ibp, pods, force_oracle=True)
    elapsed = time.monotonic() - t0
    assert elapsed < 2.0, f"call blocked {elapsed:.2f}s past its 0.6s deadline"
    # the connection is poisoned — a late response must never be read
    assert c.poisoned >= 1
    assert c._sock is None


def test_truncated_response_poisons_then_retry_succeeds(proxy):
    """A response cut mid-frame closes the stream; the client reconnects
    (fresh correlation id, fresh stream) and the retry succeeds."""
    proxy.set_fault("truncate", once=True, truncate_after=10)
    c = SolverClient(proxy.listen_path, request_timeout=120.0, max_retries=2)
    pools, ibp, pods = _problem()
    got = c.solve(pools, ibp, pods, force_oracle=True)
    assert c.reconnects >= 2  # initial connect + post-truncation reconnect
    # parity with the in-process solve: the retry changed nothing
    pools2, ibp2, pods2 = _problem()
    topo = Topology(pools2, ibp2, pods2)
    s = HybridScheduler(
        pools2, ibp2, topo, None, None, SchedulerOptions(), force_oracle=True
    )
    r = s.solve(pods2)
    local_parts = sorted(
        tuple(sorted(p.name for p in cl.pods))
        for cl in r.new_node_claims
        if cl.pods
    )
    assert _remote_parts(got, pods) == local_parts
    c.close()


def test_corrupted_frame_poisons_connection(proxy):
    """A flipped magic byte is an unrecoverable framing loss: the client
    must poison the connection, not attempt to resynchronize."""
    proxy.set_fault("corrupt", once=True)
    c = SolverClient(proxy.listen_path, request_timeout=60.0, max_retries=0)
    pools, ibp, pods = _problem()
    with pytest.raises(ProtocolError):
        c.solve(pools, ibp, pods, force_oracle=True)
    assert c.poisoned >= 1 and c._sock is None
    # next call reconnects cleanly
    assert c.ping(timeout=30.0)
    c.close()


def test_delayed_response_within_deadline_succeeds(proxy):
    proxy.set_fault("delay", once=True, delay=0.3)
    c = SolverClient(proxy.listen_path, request_timeout=120.0)
    assert c.ping()
    c.close()


def test_reconnect_backoff_respects_deadline():
    """With no server at all, the retry schedule (backoff + jitter) must
    still give up inside the request deadline."""
    c = SolverClient(
        tempfile.mktemp(suffix=".gone.sock"),
        request_timeout=1.0,
        max_retries=50,  # far more than the deadline can fund
        backoff_base=0.05,
    )
    t0 = time.monotonic()
    with pytest.raises(SolverUnavailable):
        c.ping()
    assert time.monotonic() - t0 < 3.0


# ---------------------------------------------------------------------------
# server-side guards


def test_error_frame_keeps_the_connection_serving(server):
    c = SolverClient(server.socket_path, request_timeout=120.0)
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.settimeout(10)
    sock.connect(server.socket_path)
    bad = b"{not json"
    sock.sendall(MAGIC + struct.pack("<III", KIND_SOLVE, 9, len(bad)) + bad)
    head = _read_exact(sock, 16)
    kind, rid, length = struct.unpack("<III", head[4:])
    _read_exact(sock, length)
    assert (kind, rid) == (KIND_ERROR, 9)
    # same connection, next request still served
    sock.sendall(MAGIC + struct.pack("<III", KIND_PING, 10, 0))
    head = _read_exact(sock, 16)
    kind, rid, _ = struct.unpack("<III", head[4:])
    assert (kind, rid) == (KIND_PONG, 10)
    sock.close()
    assert c.ping()
    c.close()


def _read_exact(sock, n):
    buf = b""
    while len(buf) < n:
        got = sock.recv(n - len(buf))
        assert got, "peer closed early"
        buf += got
    return buf


def test_oversized_frame_drained_and_connection_kept_usable(server, monkeypatch):
    """Satellite (epoch PR): an oversized frame — the shape a mass-churn
    delta would take if the client didn't pre-check — is refused with an
    ERROR after its body is DRAINED, and the SAME connection keeps
    serving: the stream stayed in sync, so refusing the frame must not
    cost the client its connection."""
    from karpenter_tpu.solver import service as svc

    monkeypatch.setattr(svc, "MAX_FRAME_LEN", 1024)
    monkeypatch.setattr(svc, "OVERSIZE_DRAIN_MAX", 4 * 1024)
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.settimeout(10)
    sock.connect(server.socket_path)
    body = b"x" * 2048  # over MAX, under the drain cap, body fully sent
    sock.sendall(MAGIC + struct.pack("<III", KIND_SOLVE, 3, len(body)) + body)
    head = _read_exact(sock, 16)
    kind, rid, length = struct.unpack("<III", head[4:])
    payload = _read_exact(sock, length)
    assert (kind, rid) == (KIND_ERROR, 3)
    assert b"exceeds max" in payload
    # the stream is in sync: the SAME connection serves the next frame
    sock.sendall(MAGIC + struct.pack("<III", KIND_PING, 4, 0))
    head = _read_exact(sock, 16)
    kind, rid, length = struct.unpack("<III", head[4:])
    _read_exact(sock, length)
    assert (kind, rid) == (KIND_PONG, 4)
    sock.close()


def test_oversized_frame_beyond_drain_cap_closes(server, monkeypatch):
    """A length field past OVERSIZE_DRAIN_MAX is corruption, not a real
    payload: the server answers ERROR and closes (draining gigabytes on
    a liar's say-so would itself be a denial of service)."""
    from karpenter_tpu.solver import service as svc

    monkeypatch.setattr(svc, "MAX_FRAME_LEN", 1024)
    monkeypatch.setattr(svc, "OVERSIZE_DRAIN_MAX", 4 * 1024)
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.settimeout(10)
    sock.connect(server.socket_path)
    sock.sendall(MAGIC + struct.pack("<III", KIND_SOLVE, 3, 1 << 30))
    head = _read_exact(sock, 16)
    kind, rid, length = struct.unpack("<III", head[4:])
    payload = _read_exact(sock, length)
    assert (kind, rid) == (KIND_ERROR, 3)
    assert b"exceeds max" in payload
    assert sock.recv(1) == b""
    sock.close()
    # but the listener is untouched
    c = SolverClient(server.socket_path)
    assert c.ping(timeout=10.0)
    c.close()


def test_solver_error_is_clean_and_non_fatal(server):
    """A server-side solve failure answers ERROR on the same correlation
    id (surfaced as SolverError); transport stays healthy."""
    c = SolverClient(server.socket_path, request_timeout=60.0)
    pools, ibp, pods = _problem(2)
    kind, resp = c._roundtrip(KIND_SOLVE, b'{"no": "such schema"}', 60.0)
    assert kind == KIND_ERROR and resp  # malformed schema answers ERROR
    with pytest.raises(SolverError):
        # the public path wraps the ERROR frame in a typed exception: a
        # type-broken solve budget detonates server-side, mid-solve
        c.solve(
            pools, ibp, pods,
            options=SchedulerOptions(timeout_seconds="bogus"),
            force_oracle=True,
        )
    assert c.ping()
    got = c.solve(pools, ibp, pods, force_oracle=True)
    assert got["new_node_claims"]
    c.close()


def test_accept_loop_survives_unexpected_handler_error(server, monkeypatch):
    """Satellite: an exception escaping a connection handler that is not
    ConnectionError/ValueError must be logged and must NOT kill serving."""
    original = SolverServer._handle

    def exploding(self, conn):
        raise RuntimeError("synthetic handler explosion")

    monkeypatch.setattr(SolverServer, "_handle", exploding)
    with klog.capture(level="error") as records:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(10)
        sock.connect(server.socket_path)
        # handler dies on connect; the server closes this connection (EOF,
        # or RST when the ping bytes were still unread at close — which can
        # land before sendall() even completes)
        try:
            sock.sendall(MAGIC + struct.pack("<III", KIND_PING, 1, 0))
            assert sock.recv(1) == b""
        except ConnectionError:
            pass
        sock.close()
        time.sleep(0.1)
    assert any(
        "unexpected error" in r["msg"]
        and "synthetic handler explosion" in r.get("error", "")
        for r in records.refresh()
    ), records
    monkeypatch.setattr(SolverServer, "_handle", original)
    c = SolverClient(server.socket_path)
    assert c.ping(timeout=10.0)
    c.close()


def test_concurrent_connections_are_served(server, monkeypatch):
    """One slow solve must not head-of-line-block a second connection."""
    original = SolverServer._solve

    def slow(self, payload, req_id=0):
        time.sleep(1.0)
        return original(self, payload, req_id)

    monkeypatch.setattr(SolverServer, "_solve", slow)
    pools, ibp, pods = _problem(2)
    a = SolverClient(server.socket_path, request_timeout=120.0)
    done = {}

    def solve_a():
        done["a"] = a.solve(pools, ibp, pods, force_oracle=True)

    t = threading.Thread(target=solve_a, daemon=True)
    t.start()
    time.sleep(0.2)  # solve in flight on connection A
    b = SolverClient(server.socket_path, request_timeout=120.0)
    t0 = time.monotonic()
    assert b.ping()
    assert time.monotonic() - t0 < 0.5, "second connection queued behind a solve"
    t.join(timeout=60)
    assert done["a"]["new_node_claims"]
    a.close()
    b.close()


def test_graceful_drain_flushes_inflight_solve(server, monkeypatch):
    original = SolverServer._solve

    def slow(self, payload, req_id=0):
        time.sleep(0.5)
        return original(self, payload, req_id)

    monkeypatch.setattr(SolverServer, "_solve", slow)
    pools, ibp, pods = _problem(2)
    c = SolverClient(server.socket_path, request_timeout=120.0)
    box = {}

    def solve():
        box["got"] = c.solve(pools, ibp, pods, force_oracle=True)

    t = threading.Thread(target=solve, daemon=True)
    t.start()
    time.sleep(0.2)  # request accepted, solve sleeping
    server.stop()  # must drain, not sever
    t.join(timeout=30)
    assert "got" in box and box["got"]["new_node_claims"]
    c.close()


# ---------------------------------------------------------------------------
# the failure ladder end to end: breaker, fallback, recovery


def _mini_cluster(op):
    from karpenter_tpu.api.objects import Budget

    op.raw_cloud.types = construct_instance_types(sizes=[2, 8])
    op.raw_cloud._by_name = {it.name: it for it in op.raw_cloud.types}
    fixtures.reset_rng(5)
    op.kube.create(
        "NodePool", fixtures.node_pool(name="default", budgets=[Budget(nodes="100%")])
    )


def _placed(results) -> int:
    """Pods that received a decision: new claims + existing capacity
    (incl. in-flight claims from earlier reconciles — unbound pods stay
    provisionable and re-solve onto them)."""
    return sum(len(cl.pods) for cl in results.new_node_claims) + sum(
        len(n.pods) for n in results.existing_nodes
    )


def _pending(op) -> int:
    from karpenter_tpu.controllers.state import is_provisionable

    return sum(1 for p in op.kube.list("Pod") if is_provisionable(p))


def test_sidecar_killed_mid_solve_falls_back_same_reconcile(server):
    """THE acceptance scenario: kill the sidecar, reconcile — every
    pending pod still gets a decision in that same reconcile via the
    in-process ladder; after the sidecar returns and the cooldown lapses,
    the breaker closes and solves ride the sidecar again."""
    from karpenter_tpu.controllers.kube import FakeClock
    from karpenter_tpu.controllers.operator import Operator

    clock = FakeClock()
    rs = ResilientSolver(
        server.socket_path,
        failure_threshold=2,
        cooldown_seconds=30.0,
        request_timeout_seconds=2.0,
        clock=clock.now,
    )
    rs.client.backoff_base = 0.01  # keep retry sleeps test-sized
    op = Operator(clock=clock, force_oracle=True, solver=rs)
    _mini_cluster(op)

    # round 1: sidecar healthy — solve rides the wire
    for i in range(4):
        op.kube.create("Pod", fixtures.pod(name=f"a-{i}", requests={"cpu": "400m"}))
    n = _pending(op)
    res1 = op.provisioner.reconcile(ignore_batcher=True)
    assert op.provisioner.last_solver_used == "sidecar"
    assert server.solves >= 1
    assert not res1.results.pod_errors
    assert _placed(res1.results) == n == 4

    # round 2: kill the server mid-flight — SAME-reconcile fallback
    server.stop()
    fallback_before = SOLVER_FALLBACK.value({"reason": "sidecar_unavailable"})
    for i in range(4):
        op.kube.create("Pod", fixtures.pod(name=f"b-{i}", requests={"cpu": "400m"}))
    n = _pending(op)
    res2 = op.provisioner.reconcile(ignore_batcher=True)
    assert op.provisioner.last_solver_used == "oracle"
    assert not res2.results.pod_errors
    assert _placed(res2.results) == n
    assert SOLVER_FALLBACK.value({"reason": "sidecar_unavailable"}) > fallback_before
    assert rs.breaker.state == "closed"  # one failure, threshold 2

    # round 3: second consecutive failure trips the breaker open
    for i in range(2):
        op.kube.create("Pod", fixtures.pod(name=f"c-{i}", requests={"cpu": "400m"}))
    n = _pending(op)
    res3 = op.provisioner.reconcile(ignore_batcher=True)
    assert not res3.results.pod_errors
    assert _placed(res3.results) == n
    assert rs.breaker.state == "open"

    # round 4: breaker open — straight to in-process, no sidecar attempt
    attempts = rs.client.reconnects
    open_before = SOLVER_FALLBACK.value({"reason": "circuit_open"})
    for i in range(2):
        op.kube.create("Pod", fixtures.pod(name=f"d-{i}", requests={"cpu": "400m"}))
    n = _pending(op)
    res4 = op.provisioner.reconcile(ignore_batcher=True)
    assert not res4.results.pod_errors
    assert _placed(res4.results) == n
    assert rs.client.reconnects == attempts, "open breaker must not dial the sidecar"
    assert SOLVER_FALLBACK.value({"reason": "circuit_open"}) > open_before

    # recovery: sidecar back + cooldown elapsed -> half-open probe -> closed
    server.start()
    clock.advance(31.0)
    solves_before = server.solves
    for i in range(2):
        op.kube.create("Pod", fixtures.pod(name=f"e-{i}", requests={"cpu": "400m"}))
    res5 = op.provisioner.reconcile(ignore_batcher=True)
    assert op.provisioner.last_solver_used == "sidecar"
    assert rs.breaker.state == "closed"
    assert server.solves > solves_before
    assert not res5.results.pod_errors


def test_crash_loop_keeps_breaker_open_until_recovery(server):
    """A crash-looping sidecar (up, dies, up, dies) must not pull the
    control plane into paying full retry budgets every solve: once open,
    only the half-open probe touches the socket."""
    from karpenter_tpu.controllers.kube import FakeClock

    clock = FakeClock()
    rs = ResilientSolver(
        server.socket_path,
        failure_threshold=1,
        cooldown_seconds=10.0,
        request_timeout_seconds=1.0,
        clock=clock.now,
    )
    rs.client.backoff_base = 0.01
    pools, ibp, pods = _problem(3)
    server.stop()  # crash

    r = rs.solve(pools, ibp, pods, force_oracle=True)
    assert rs.breaker.state == "open"
    assert rs.last_used == "oracle"
    assert not r.pod_errors

    # crash-loop: server flaps up and down while the breaker is open —
    # in-cooldown solves never touch it
    attempts = rs.client.reconnects
    for _ in range(3):
        server.start()
        server.stop()
        r = rs.solve(pools, ibp, pods, force_oracle=True)
        assert not r.pod_errors and rs.last_used == "oracle"
    assert rs.client.reconnects == attempts

    # half-open probe against a STILL-dead server re-opens immediately
    clock.advance(11.0)
    r = rs.solve(pools, ibp, pods, force_oracle=True)
    assert rs.breaker.state == "open" and not r.pod_errors

    # and against a recovered server, closes
    server.start()
    clock.advance(11.0)
    ok_before = SIDECAR_REQUESTS.value({"outcome": "success"})
    r = rs.solve(pools, ibp, pods, force_oracle=True)
    assert rs.breaker.state == "closed"
    assert rs.last_used == "sidecar"
    assert SIDECAR_REQUESTS.value({"outcome": "success"}) > ok_before
    assert not r.pod_errors


def test_circuit_breaker_state_machine():
    t = {"now": 0.0}
    b = CircuitBreaker(failure_threshold=3, cooldown_seconds=5.0, clock=lambda: t["now"])
    assert b.state == "closed" and b.allow()
    b.record_failure()
    b.record_failure()
    assert b.state == "closed" and b.allow()
    b.record_failure()
    assert b.state == "open" and not b.allow()
    t["now"] = 4.9
    assert not b.allow()
    t["now"] = 5.0
    assert b.allow() and b.state == "half-open"
    b.record_failure()  # probe failed: re-open, fresh cooldown
    assert b.state == "open" and not b.allow()
    t["now"] = 10.0
    assert b.allow() and b.state == "half-open"
    b.record_success()
    assert b.state == "closed" and b.allow()


def test_circuit_breaker_recovers_from_lost_half_open_probe():
    """A probe that never reports back (its thread killed between
    allow() and record_*) must not wedge the breaker refusing the
    sidecar forever: after a full cooldown with no verdict, half-open
    re-admits exactly one fresh probe."""
    t = {"now": 0.0}
    b = CircuitBreaker(failure_threshold=1, cooldown_seconds=5.0, clock=lambda: t["now"])
    b.record_failure()
    assert b.state == "open"
    t["now"] = 5.0
    assert b.allow()  # probe admitted...
    assert b.state == "half-open" and not b.allow()  # ...and is exclusive
    # the probe vanishes without a record_*; a cooldown later the
    # breaker hands the probe slot to a new caller instead of wedging
    t["now"] = 10.0
    assert b.allow()
    assert b.state == "half-open" and not b.allow()  # still one at a time
    b.record_success()
    assert b.state == "closed" and b.allow()


def test_circuit_breaker_is_thread_safe_under_concurrent_failures():
    """Race-tier satellite regression: the breaker is driven from every
    concurrent request path (server handler threads, worker-pool
    reconciles), so `consecutive_failures += 1` and the trip decision
    must run under the breaker's lock. 8 threads x 8 failures against a
    threshold of exactly 64: one lost update and the count comes up
    short, the breaker never opens, and this test fails."""
    threads_n, per_thread = 8, 8
    b = CircuitBreaker(
        failure_threshold=threads_n * per_thread,
        cooldown_seconds=5.0,
        clock=lambda: 0.0,
    )
    barrier = threading.Barrier(threads_n)

    def hammer():
        barrier.wait()
        for _ in range(per_thread):
            b.record_failure()

    workers = [threading.Thread(target=hammer) for _ in range(threads_n)]
    for t in workers:
        t.start()
    for t in workers:
        t.join(timeout=30)
    assert b.consecutive_failures == threads_n * per_thread, (
        "lost update: racing record_failure() calls dropped increments"
    )
    assert b.state == "open" and not b.allow()
    # reclose path stays consistent after the storm
    b.record_success()
    assert b.state == "closed" and b.consecutive_failures == 0 and b.allow()


def test_remote_solve_matches_in_process_through_resilient_solver(server):
    """The resilience layer must not alter any scheduling decision: a
    sidecar solve through ResilientSolver partitions pods identically to
    the in-process HybridScheduler."""
    rs = ResilientSolver(server.socket_path, request_timeout_seconds=120.0)
    pools, ibp, pods = _problem(8)
    r_remote = rs.solve(pools, ibp, pods, force_oracle=True)
    assert rs.last_used == "sidecar"

    pools2, ibp2, pods2 = _problem(8)
    topo = Topology(pools2, ibp2, pods2)
    s = HybridScheduler(
        pools2, ibp2, topo, None, None, SchedulerOptions(), force_oracle=True
    )
    r_local = s.solve(pods2)

    def parts(r):
        return sorted(
            tuple(sorted(p.name for p in cl.pods))
            for cl in r.new_node_claims
            if cl.pods
        )

    assert parts(r_remote) == parts(r_local)
    assert r_remote.pod_errors == r_local.pod_errors
    # remote claims are launchable: the full NodeClaim crossed the wire
    for cl in r_remote.new_node_claims:
        nc = cl.to_node_claim()
        assert nc.requirements, "wire NodeClaim lost its requirements"
        assert any(
            req.key == "karpenter.sh/nodepool" or True for req in nc.requirements
        )
        assert nc.resources_requests


def test_wire_deadline_covers_server_solve_budget():
    """Code-review regression: a solve legitimately using its full
    server-side budget (which at worst returns partial results with
    timed_out=True) must not be cut off by a SHORTER client deadline —
    that would poison the connection and feed the breaker on a healthy
    sidecar. The wire deadline derives from the solve budget + grace."""
    from karpenter_tpu.solver.hybrid import SOLVE_DEADLINE_GRACE_SECONDS

    class StubClient:
        def __init__(self):
            self.seen_timeout = None

        def solve(self, *args, timeout=None, **kwargs):
            self.seen_timeout = timeout
            raise SolverUnavailable("stub: not actually dialing")

    stub = StubClient()
    rs = ResilientSolver(client=stub, request_timeout_seconds=5.0)
    pools, ibp, pods = _problem(2)
    r = rs.solve(
        pools, ibp, pods,
        options=SchedulerOptions(timeout_seconds=60.0), force_oracle=True,
    )
    assert stub.seen_timeout >= 60.0 + SOLVE_DEADLINE_GRACE_SECONDS
    assert rs.last_used == "oracle" and not r.pod_errors
    # with no solve budget, the configured request timeout is the floor
    rs.solve(pools, ibp, pods, options=SchedulerOptions(), force_oracle=True)
    assert stub.seen_timeout == 5.0


def test_trickling_frame_cannot_wedge_a_handler(server, monkeypatch):
    """Code-review regression: the server's mid-frame stall guard is WALL
    CLOCK, not per-recv — a peer trickling one byte per poll interval
    must lose its connection at the stall deadline, not hold the handler
    thread forever."""
    from karpenter_tpu.solver import service as svc

    monkeypatch.setattr(svc, "FRAME_STALL_SECONDS", 0.6)
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.settimeout(10)
    sock.connect(server.socket_path)
    t0 = time.monotonic()
    sock.sendall(MAGIC[:2])  # start a frame...
    time.sleep(0.3)
    sock.sendall(MAGIC[2:3])  # ...keep trickling inside the per-recv window
    # never finish the header; the WALL-CLOCK deadline must fire
    try:
        got = sock.recv(1)
    except ConnectionError:
        got = b""
    assert got == b"" or got, "connection should close (EOF/RST)"
    assert time.monotonic() - t0 < 5.0, "stall guard did not fire at wall clock"
    sock.close()
    # the listener is untouched
    c = SolverClient(server.socket_path)
    assert c.ping(timeout=10.0)
    c.close()


# ---------------------------------------------------------------------------
# prewarm / readiness (ISSUE 8: the AOT ladder — docs/compile.md)


def test_client_mid_prewarm_degrades_to_oracle_then_recovers():
    """A client connecting MID-PREWARM must be served immediately — the
    solve degrades to the (decision-identical) oracle fallback, never an
    uncompiled device path — and PONG payloads expose readiness so
    orchestration probes can gate traffic. After prewarm completes the
    same problem solves on the normal path with the identical partition."""
    release = threading.Event()

    def stub_prewarm(stop):
        # a deterministic stand-in for aot.prewarm: "compiling" until
        # released, polling the server's stop flag like the real one
        while not release.is_set() and not stop.is_set():
            time.sleep(0.02)

    path = tempfile.mktemp(suffix=".sock")
    srv = SolverServer(path, prewarm=True, prewarm_fn=stub_prewarm)
    srv.start()
    try:
        client = SolverClient(path)
        # readiness surfaces on the wire while the ladder compiles — the
        # legacy empty-payload PING keeps its bare-token PONG (wire
        # compat pin), and the v2 form carries the same status
        kind, payload = client._roundtrip(KIND_PING, b"", 10.0)
        assert kind == KIND_PONG and payload == b"prewarming"
        assert client.ping_status(10.0)["status"] == "prewarming"
        assert not srv.ready.is_set()

        pools, ibp, pods = _problem(8)
        got = client.solve(
            pools, ibp, pods,
            options=SchedulerOptions(tpu_min_pods=0),
            timeout=120.0,
        )
        # served DURING prewarm: degraded to the oracle, never the device
        assert got["used_tpu"] is False
        assert srv.oracle_degraded_solves == 1
        degraded_parts = _remote_parts(got, pods)

        release.set()
        assert srv.ready.wait(timeout=10.0)
        kind, payload = client._roundtrip(KIND_PING, b"", 10.0)
        assert payload == b"ready"

        pools, ibp, pods = _problem(8)
        got2 = client.solve(
            pools, ibp, pods,
            options=SchedulerOptions(tpu_min_pods=0),
            timeout=120.0,
        )
        # decision-identical across the degrade boundary
        assert _remote_parts(got2, pods) == degraded_parts
        assert srv.oracle_degraded_solves == 1  # no further degrades
        client.close()
    finally:
        release.set()
        srv.stop()


def test_server_stop_interrupts_prewarm():
    """stop() during prewarm must not hang on the ladder: the prewarm
    loop polls the server's stop flag between combos."""
    started = threading.Event()
    aborted = threading.Event()

    def stub_prewarm(stop):
        started.set()
        while not stop.is_set():
            time.sleep(0.02)
        aborted.set()

    path = tempfile.mktemp(suffix=".sock")
    srv = SolverServer(path, prewarm=True, prewarm_fn=stub_prewarm)
    srv.start()
    assert started.wait(timeout=5.0)
    t0 = time.monotonic()
    srv.stop()
    assert time.monotonic() - t0 < 10.0
    assert aborted.wait(timeout=5.0)


@pytest.mark.slow
@pytest.mark.hard_timeout(600)
def test_kill_mid_prewarm_does_not_poison_cache(tmp_path):
    """SIGKILL during the AOT prewarm must leave the on-disk cache usable:
    JAX writes cache entries atomically and the ladder manifest is
    temp-file + rename (solver/aot.py), so the next process either reads
    valid artifacts or recompiles — it never crashes on torn state."""
    import os
    import signal
    import subprocess
    import sys

    cache_dir = str(tmp_path / "xla-cache")
    script = (
        "import os\n"
        f"os.environ['KARPENTER_COMPILATION_CACHE_DIR'] = {cache_dir!r}\n"
        "from karpenter_tpu.solver import aot\n"
        "out = aot.prewarm(max_pods=64, include_sweeps=False)\n"
        # combos recorded before the kill are legitimately SKIPPED by the
        # second run (their executables are already persisted); the
        # ladder is complete when compiled + skipped covers it
        "print('PREWARM_DONE', out['compiled'] + out['skipped'])\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo

    # kill mid-flight: the first solve_runs compile takes ~15s cold, so
    # 8s lands inside it (and after the cache dir exists)
    proc = subprocess.Popen(
        [sys.executable, "-c", script], env=env, cwd=repo,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE,
    )
    time.sleep(8.0)
    proc.send_signal(signal.SIGKILL)
    proc.wait(timeout=30)

    # torn state must read as "nothing recorded", never crash
    from karpenter_tpu.solver import aot

    manifest = aot.load_manifest(cache_dir)
    assert isinstance(manifest.get("combos"), dict)

    # a fresh process completes the SAME ladder against the survivor
    # cache (partial entries are either valid — reused — or recompiled)
    out = subprocess.run(
        [sys.executable, "-c", script], env=env, cwd=repo,
        capture_output=True, text=True, timeout=500,
    )
    assert out.returncode == 0, out.stderr[-4000:]
    assert "PREWARM_DONE 4" in out.stdout, out.stdout


# ---------------------------------------------------------------------------
# epoch/resync state machine + admission + drain (epoch PR tentpole)


def _in_process_parts(n=8):
    """The decision-identity referee: the same problem solved entirely
    in-process on the oracle."""
    pools, ibp, pods = _problem(n)
    topo = Topology(pools, ibp, pods)
    s = HybridScheduler(
        pools, ibp, topo, None, None, SchedulerOptions(), force_oracle=True
    )
    r = s.solve(pods)
    return sorted(
        tuple(sorted(p.name for p in cl.pods))
        for cl in r.new_node_claims
        if cl.pods
    )


def test_epoch_mismatch_storm_converges_without_resync_loop(server):
    """A storm of epoch desyncs (the server's store evicted before every
    delta) must cost exactly ONE resync hop per solve — the full-snapshot
    fallback re-establishes the epoch in the same call, never loops —
    and every answer stays decision-identical to in-process."""
    c = SolverClient(server.socket_path, request_timeout=120.0)
    pools, ibp, pods = _problem(8)
    referee = _in_process_parts(8)
    assert _remote_parts(
        c.solve(pools, ibp, pods, force_oracle=True), pods
    ) == referee
    for round_i in range(4):
        server.epochs.clear()  # desync: every resident epoch evicted
        got = c.solve(pools, ibp, pods, force_oracle=True)
        assert _remote_parts(got, pods) == referee
    # one establishing snapshot + one resync-driven snapshot per storm
    # round; NO delta round trips were wasted re-trying
    assert c.resyncs == 4, c.resyncs
    assert c.full_solves == 5 and c.delta_solves == 0
    # with the store stable again, deltas resume
    got = c.solve(pools, ibp, pods, force_oracle=True)
    assert _remote_parts(got, pods) == referee
    assert c.delta_solves == 1 and c.resyncs == 4
    c.close()


def test_malformed_delta_answers_resync_and_keeps_serving(server):
    """Garbage SOLVE_DELTA payloads (bad JSON, unknown sections, keyed
    deltas against nothing) answer a retriable EPOCH_RESYNC on the same
    connection — never an ERROR, never a closed stream."""
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.settimeout(10)
    sock.connect(server.socket_path)
    for i, payload in enumerate(
        (
            b"{not json",
            b'{"client": "x", "base_epoch": 1}',  # missing fields
            b'{"client": "x", "base_epoch": 9, "epoch": 10, '
            b'"pods_flat": {}, "delta": {}}',  # unknown epoch
        )
    ):
        sock.sendall(
            MAGIC + struct.pack("<III", KIND_SOLVE_DELTA, 20 + i, len(payload))
            + payload
        )
        head = _read_exact(sock, 16)
        kind, rid, length = struct.unpack("<III", head[4:])
        body = _read_exact(sock, length)
        assert (kind, rid) == (KIND_EPOCH_RESYNC, 20 + i), body
    # connection still serves
    sock.sendall(MAGIC + struct.pack("<III", KIND_PING, 30, 0))
    head = _read_exact(sock, 16)
    kind, rid, length = struct.unpack("<III", head[4:])
    _read_exact(sock, length)
    assert (kind, rid) == (KIND_PONG, 30)
    sock.close()


def test_mid_delta_kill_of_server_resyncs_decision_identically(server):
    """Mid-delta SIGKILL analog, server side: the server dies between an
    established epoch and the next delta. The replacement (fresh process
    => empty epoch store) answers EPOCH_RESYNC and the SAME call's full
    resync returns a schedule decision-identical to in-process."""
    c = SolverClient(server.socket_path, request_timeout=120.0)
    c.backoff_base = 0.01  # keep reconnect sleeps test-sized
    pools, ibp, pods = _problem(8)
    referee = _in_process_parts(8)
    c.solve(pools, ibp, pods, force_oracle=True)  # epoch established
    server.stop()  # "kill": the store dies with the process
    replacement = SolverServer(server.socket_path)
    replacement.start()
    try:
        got = c.solve(pools, ibp, pods, force_oracle=True)
        assert c.resyncs == 1 and c.full_solves == 2
        assert _remote_parts(got, pods) == referee
        # and the very next solve rides a delta against the NEW epoch
        got = c.solve(pools, ibp, pods, force_oracle=True)
        assert c.delta_solves == 1
        assert _remote_parts(got, pods) == referee
    finally:
        replacement.stop()
    c.close()


def test_mid_delta_kill_of_client_leaves_full_resync_identical(server):
    """Mid-delta SIGKILL analog, client side: a client dies after sending
    HALF a delta frame (the server never sees the rest). A fresh client —
    no epoch memory, like a restarted control plane — must solve full
    snapshot, decision-identical to in-process."""
    c1 = SolverClient(server.socket_path, request_timeout=120.0)
    pools, ibp, pods = _problem(8)
    referee = _in_process_parts(8)
    c1.solve(pools, ibp, pods, force_oracle=True)
    # half a delta frame, then the "process" dies
    partial = b'{"client": "' + c1.client_id.encode()
    c1._sock.sendall(
        MAGIC + struct.pack("<III", KIND_SOLVE_DELTA, 99, len(partial) + 64)
        + partial
    )
    c1._sock.close()  # SIGKILL analog: mid-frame, no goodbye
    c1._sock = None

    c2 = SolverClient(server.socket_path, request_timeout=120.0)
    got = c2.solve(pools, ibp, pods, force_oracle=True)
    assert c2.full_solves == 1 and c2.resyncs == 0
    assert _remote_parts(got, pods) == referee
    c2.close()


def test_drain_answers_new_solves_with_immediate_retriable_error(server, monkeypatch):
    """Graceful-drain satellite: while stop() drains an in-flight solve,
    a NEW solve on a surviving connection is answered with an immediate
    'draining' ERROR — the caller degrades to the oracle NOW instead of
    waiting out its wire deadline in silence."""
    original = SolverServer._solve

    def slow(self, payload, req_id=0):
        time.sleep(1.5)
        return original(self, payload, req_id)

    monkeypatch.setattr(SolverServer, "_solve", slow)
    pools, ibp, pods = _problem(2)
    a = SolverClient(server.socket_path, request_timeout=120.0)
    box = {}

    def solve_a():
        box["a"] = a.solve(pools, ibp, pods, force_oracle=True)

    t = threading.Thread(target=solve_a, daemon=True)
    t.start()
    time.sleep(0.3)  # solve in flight on connection A

    b = SolverClient(server.socket_path, request_timeout=120.0)
    assert b.ping()  # B's connection established pre-drain

    stopper = threading.Thread(target=server.stop, daemon=True)
    stopper.start()
    time.sleep(0.2)  # drain window open, A still solving
    t0 = time.monotonic()
    with pytest.raises(SolverError, match="draining"):
        b.solve(pools, ibp, pods, force_oracle=True)
    elapsed = time.monotonic() - t0
    assert elapsed < 2.0, f"draining refusal took {elapsed:.2f}s, not immediate"
    # the in-flight solve still drained to completion
    t.join(timeout=30)
    assert "a" in box and box["a"]["new_node_claims"]
    stopper.join(timeout=30)
    a.close()
    b.close()


def test_admission_rejection_degrades_to_oracle_without_breaker_trip(server):
    """Admission tentpole: with the gate full, the server answers RETRY
    (not ERROR) and ResilientSolver degrades to the oracle WITHOUT
    scoring a breaker failure, then honors the backoff hint before
    re-dialing."""
    from karpenter_tpu.solver import epochs as epochs_mod

    # a gate with zero inflight slots rejects everything
    server.admission.max_inflight = 0
    fake_now = {"t": 1000.0}
    rs = ResilientSolver(
        server.socket_path,
        request_timeout_seconds=30.0,
        clock=lambda: fake_now["t"],
    )
    pools, ibp, pods = _problem(4)
    rejected_before = epochs_mod.ADMISSION_REJECTED.value()
    r = rs.solve(pools, ibp, pods, force_oracle=True)
    assert not r.pod_errors
    assert rs.last_used == "oracle"
    assert "admission rejected" in rs.fallback_reason
    assert rs.breaker.state == "closed", "backpressure must not trip the breaker"
    assert rs.breaker.consecutive_failures == 0
    assert epochs_mod.ADMISSION_REJECTED.value() > rejected_before
    assert rs._admission_retry_at > fake_now["t"]

    # inside the backoff window the sidecar is not even dialed
    dials = rs.client.reconnects
    r = rs.solve(pools, ibp, pods, force_oracle=True)
    assert rs.last_used == "oracle" and rs.client.reconnects == dials

    # capacity restored + hint elapsed -> sidecar serves again
    server.admission.max_inflight = 4
    fake_now["t"] += 3600.0
    r = rs.solve(pools, ibp, pods, force_oracle=True)
    assert rs.last_used == "sidecar" and not r.pod_errors


def test_pong_surfaces_epoch_and_admission_backpressure(server):
    """Satellite: the v2 PONG carries epoch residency + admission queue
    depth so probes can observe backpressure — while the legacy
    empty-payload PING answers the bare token byte-for-byte (old probes
    comparing `== b"ready"` keep working against an epoch server)."""
    c = SolverClient(server.socket_path, request_timeout=60.0)
    pools, ibp, pods = _problem(2)
    c.solve(pools, ibp, pods, force_oracle=True)
    kind, payload = c._roundtrip(KIND_PING, b"", 10.0)
    assert (kind, payload) == (KIND_PONG, b"ready")  # legacy form intact
    pong = c.ping_status(10.0)
    assert pong["status"] == "ready"
    assert pong["epochs"] >= 1 and pong["epoch_clients"] >= 1
    assert pong["admission_queue_depth"] == 0
    from karpenter_tpu.solver import epochs as epochs_mod

    assert epochs_mod.EPOCHS_RESIDENT.value() >= 1
    c.close()


# ---------------------------------------------------------------------------
# the steady-workload chaos soak (epoch desync + mid-delta kill +
# admission rejection + concurrent-client partial failure)


@pytest.mark.soak
def test_chaos_soak_epoch_service_decision_identical():
    """THE epoch acceptance scenario: a steady provision/consolidate
    workload rides the sidecar through the fault proxy while the soak
    injects, in rotation: epoch desync (store cleared), mid-delta kill
    (response truncated/corrupted mid-frame), admission rejection (gate
    closed for a tick), and a drain/restart. Every returned schedule must
    leave the control plane on the SAME trajectory as the in-process
    oracle referee — same per-tick node counts, same final partition —
    and the racert witness (armed by the soak marker) must see zero
    lock-order inversions."""
    from karpenter_tpu.controllers.kube import FakeClock
    from karpenter_tpu.controllers.operator import Operator
    from karpenter_tpu.api.objects import Budget, PodPhase

    def steady_op(solver=None):
        op = Operator(clock=FakeClock(), force_oracle=True, solver=solver)
        op.raw_cloud.types = construct_instance_types(sizes=[2, 8, 32])
        op.raw_cloud._by_name = {it.name: it for it in op.raw_cloud.types}
        fixtures.reset_rng(5)
        op.kube.create(
            "NodePool",
            fixtures.node_pool(
                name="default",
                budgets=[Budget(nodes="100%")],
                consolidate_after_seconds=0.0,
            ),
        )
        for i in range(10):
            op.kube.create(
                "Pod",
                fixtures.pod(
                    name=f"w-{i}", requests={"cpu": "400m", "memory": "256Mi"}
                ),
            )
        op.run_until_settled(max_ticks=60)
        for p in op.kube.list("Pod"):
            p.phase = PodPhase.RUNNING
            op.kube.update("Pod", p)
        return op

    def run(solver=None, chaos=None):
        # deterministic per-tick churn (identical in both runs — chaos
        # touches only the service layer): one pod leaves, one arrives,
        # so EVERY tick carries a provisioning solve through the sidecar
        # and the epoch store sees real delta traffic to desync
        op = steady_op(solver=solver)
        counts = []
        next_id = 10
        for tick in range(30):
            if chaos is not None:
                chaos(tick)
            bound = sorted(
                (p for p in op.kube.list("Pod") if p.node_name),
                key=lambda p: p.name,
            )
            if bound:
                op.kube.delete("Pod", bound[0].name)
            op.kube.create(
                "Pod",
                fixtures.pod(
                    name=f"w-{next_id}",
                    requests={"cpu": "400m", "memory": "256Mi"},
                ),
            )
            next_id += 1
            op.step(2.0)
            for p in op.kube.list("Pod"):
                if p.node_name and p.phase == PodPhase.PENDING:
                    p.phase = PodPhase.RUNNING
                    op.kube.update("Pod", p)
            counts.append(len(op.kube.list("Node")))
        by_node: dict[str, set] = {}
        for p in op.kube.list("Pod"):
            by_node.setdefault(p.node_name, set()).add(p.name)
        return counts, sorted(tuple(sorted(s)) for s in by_node.values())

    counts_ref, partition_ref = run()

    sock_path = tempfile.mktemp(suffix=".soak.sock")
    srv = SolverServer(sock_path)
    srv.start()
    proxy_path = tempfile.mktemp(suffix=".soakproxy.sock")
    proxy = FaultyProxy(proxy_path, sock_path)
    rs = ResilientSolver(
        proxy_path, request_timeout_seconds=120.0, failure_threshold=50
    )
    rs.client.backoff_base = 0.01
    state = {"srv": srv}

    def chaos(tick):
        if tick == 4:
            state["srv"].epochs.clear()  # epoch desync
        elif tick == 8:
            proxy.set_fault("truncate", once=True, truncate_after=12)
        elif tick == 12:
            proxy.set_fault("corrupt", once=True)
        elif tick == 16:
            state["srv"].admission.max_inflight = 0  # admission storm...
        elif tick == 17:
            state["srv"].admission.max_inflight = 4  # ...one tick long
            rs._admission_retry_at = 0.0  # hint elapsed (wall-clock gate)
        elif tick == 20:
            # drain + replace: the replacement has an empty epoch store,
            # so the next delta resyncs
            state["srv"].stop()
            state["srv"] = SolverServer(sock_path)
            state["srv"].start()

    try:
        counts_soak, partition_soak = run(solver=rs, chaos=chaos)
    finally:
        proxy.stop()
        state["srv"].stop()

    assert counts_soak == counts_ref, (
        f"soak diverged from the oracle referee: {counts_soak} != {counts_ref}"
    )
    assert partition_soak == partition_ref
    # the faults actually happened and actually recovered
    assert rs.client.resyncs >= 1, "epoch desync never exercised the resync path"
    assert rs.client.delta_solves >= 1, "the delta path never carried a solve"
    assert SOLVER_FALLBACK.value({"reason": "admission_rejected"}) >= 1


@pytest.mark.soak
def test_chaos_soak_concurrent_client_partial_failure():
    """Coalesced-batch partial failure: two clients share the server; one
    connection's response is corrupted mid-batch while its sibling's
    concurrent solve must complete untouched and both end decision-
    identical to in-process (one lane's failure never poisons another)."""
    sock_path = tempfile.mktemp(suffix=".pair.sock")
    srv = SolverServer(sock_path)
    srv.start()
    proxy_path = tempfile.mktemp(suffix=".pairproxy.sock")
    proxy = FaultyProxy(proxy_path, sock_path)
    try:
        referee = _in_process_parts(8)
        pools, ibp, pods = _problem(8)
        # victim rides the proxy (its next response gets corrupted);
        # sibling dials the server directly, concurrently
        victim = SolverClient(proxy_path, request_timeout=120.0, max_retries=2)
        sibling = SolverClient(sock_path, request_timeout=120.0)
        victim.solve(pools, ibp, pods, force_oracle=True)  # epoch established
        proxy.set_fault("corrupt", once=True)
        results = {}
        errors = {}

        def solve(name, client):
            try:
                results[name] = client.solve(pools, ibp, pods, force_oracle=True)
            except Exception as e:  # the victim may legitimately fail
                errors[name] = e

        threads = [
            threading.Thread(target=solve, args=("victim", victim), daemon=True),
            threading.Thread(target=solve, args=("sibling", sibling), daemon=True),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        # the sibling lane is untouched by the victim's corruption
        assert "sibling" in results
        assert _remote_parts(results["sibling"], pods) == referee
        # the victim either recovered via reconnect-retry in the same call
        # or surfaced a clean typed error; either way the NEXT solve is
        # decision-identical again
        got = victim.solve(pools, ibp, pods, force_oracle=True)
        assert _remote_parts(got, pods) == referee
        victim.close()
        sibling.close()
    finally:
        proxy.stop()
        srv.stop()


def test_half_open_probe_landing_on_retry_recloses_breaker(server):
    """Review regression (finding: stranded probe): a half-open probe
    that lands on an admission RETRY must resolve the probe — the
    transport round-tripped, so the breaker closes and pacing is the
    admission backoff's job. Without record_success the probe would be
    stranded and every caller wedged in-process for an extra cooldown."""
    server.admission.max_inflight = 0  # healthy but shedding
    t = {"now": 1000.0}
    rs = ResilientSolver(
        server.socket_path,
        failure_threshold=1,
        cooldown_seconds=10.0,
        request_timeout_seconds=5.0,
        clock=lambda: t["now"],
    )
    rs.client.backoff_base = 0.01
    pools, ibp, pods = _problem(3)
    server.stop()  # a real outage trips the breaker
    rs.solve(pools, ibp, pods, force_oracle=True)
    assert rs.breaker.state == "open"
    server.start()  # back, but still overloaded
    t["now"] += 11.0  # cooldown elapsed -> half-open probe
    r = rs.solve(pools, ibp, pods, force_oracle=True)
    assert not r.pod_errors and rs.last_used == "oracle"
    assert rs.breaker.state == "closed", (
        "RETRY answer must resolve the half-open probe, not strand it"
    )
    # capacity restored + hint elapsed -> sidecar serves immediately
    server.admission.max_inflight = 4
    t["now"] += 3600.0
    r = rs.solve(pools, ibp, pods, force_oracle=True)
    assert rs.last_used == "sidecar" and not r.pod_errors


def test_pre_epoch_server_downgrades_client_to_snapshots(server, monkeypatch):
    """Review regression (mixed-version rollout, control plane upgraded
    first): a pre-epoch server answers 'unknown kind 6' to SOLVE_DELTA
    and silently ignores the epoch key on snapshots. The client must
    fall back to the plain snapshot IN THE SAME CALL and disable epoch
    mode for its lifetime — never retry deltas into the same error and
    feed the breaker against a healthy old sidecar."""
    from karpenter_tpu.solver import service as svc

    def legacy_handle(self, conn):
        # the pre-epoch _handle: PING + SOLVE only, no epoch storage
        while True:
            try:
                kind, req_id, payload = self._recv_frame_idle(conn)
            except socket.timeout as e:
                raise ProtocolError(f"peer stalled mid-frame: {e}") from e
            if kind == KIND_PING:
                self._send_response(conn, KIND_PONG, b"ready", req_id)
                continue
            if kind != KIND_SOLVE:
                self._send_response(
                    conn, KIND_ERROR, f"unknown kind {kind}".encode(), req_id
                )
                continue
            result = self._solve(payload, req_id)
            self._send_response(conn, KIND_RESULT, result, req_id)

    monkeypatch.setattr(svc.SolverServer, "_handle", legacy_handle)
    monkeypatch.setattr(
        svc.SolverServer, "_store_epoch", lambda self, *a, **k: None
    )
    c = SolverClient(server.socket_path, request_timeout=120.0)
    pools, ibp, pods = _problem(6)
    referee = _in_process_parts(6)
    r1 = c.solve(pools, ibp, pods, force_oracle=True)  # snapshot, epoch key ignored
    assert _remote_parts(r1, pods) == referee
    r2 = c.solve(pools, ibp, pods, force_oracle=True)  # delta refused -> downgrade
    assert _remote_parts(r2, pods) == referee
    assert c.epochs_enabled is False and c.resyncs == 1
    r3 = c.solve(pools, ibp, pods, force_oracle=True)  # plain snapshot from now on
    assert _remote_parts(r3, pods) == referee
    assert c.resyncs == 1, "must not keep probing deltas at an old server"
    c.close()


def test_admission_gate_idle_escape_after_pathological_observation():
    """Review regression: one solve slower than max_cost_seconds pushes
    the observed-cost EWMA above the budget; since observe() only fires
    on completed solves, rejecting at depth 0 would be PERMANENT. An
    idle gate must always admit (serial execution can't oversubscribe),
    letting the EWMA recover from real measurements."""
    from karpenter_tpu.solver import epochs as epochs_mod

    g = epochs_mod.AdmissionGate(max_inflight=4, max_cost_seconds=10.0)
    g.observe(500.0)  # pathological: one solve blew the whole budget
    token, hint, depth = g.try_admit(100)
    assert token is not None, "idle gate must admit despite the EWMA"
    # with one in flight the cost budget binds again
    t2, hint2, _ = g.try_admit(100)
    assert t2 is None and hint2 > 0
    g.release(token)
    t3, _, _ = g.try_admit(100)
    assert t3 is not None
    g.release(t3)
    assert g.depth() == 0


def test_drain_closes_connection_after_any_answered_frame(server, monkeypatch):
    """Review regression: the one-refusal-then-close drain bound must
    cover PING traffic too — a peer pinging in a tight loop during drain
    must lose its connection after one answer, not hold the handler
    thread past stop()'s bounded join."""
    original = SolverServer._solve

    def slow(self, payload, req_id=0):
        time.sleep(1.0)
        return original(self, payload, req_id)

    monkeypatch.setattr(SolverServer, "_solve", slow)
    pools, ibp, pods = _problem(2)
    a = SolverClient(server.socket_path, request_timeout=120.0)
    t = threading.Thread(
        target=lambda: a.solve(pools, ibp, pods, force_oracle=True), daemon=True
    )
    t.start()
    time.sleep(0.2)  # solve in flight holds the drain window open
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    sock.settimeout(10)
    sock.connect(server.socket_path)
    stopper = threading.Thread(target=server.stop, daemon=True)
    stopper.start()
    time.sleep(0.2)  # drain window open
    sock.sendall(MAGIC + struct.pack("<III", KIND_PING, 5, 0))
    head = _read_exact(sock, 16)
    kind, rid, length = struct.unpack("<III", head[4:])
    _read_exact(sock, length)
    assert (kind, rid) == (KIND_PONG, 5)  # one answer...
    got = b""
    try:
        got = sock.recv(1)
    except ConnectionError:
        pass
    assert got == b"", "connection must close after the drained answer"
    sock.close()
    t.join(timeout=30)
    stopper.join(timeout=30)
    a.close()


# ---------------------------------------------------------------------------
# fleet-axis lane isolation (solver/fleet.py): one lane's fault — corrupt
# frame, oversized frame, blown deadline, vanished client — must never
# poison its window siblings, whose decisions stay identical to solo


def _fleet_problem(cpu):
    """One fleet lane: the shared scan-path fixture
    (fixtures.make_self_spread_pods); `cpu` varies the request profile
    without changing the table fingerprint (tests/test_fleet.py)."""
    fixtures.reset_rng(5)
    its = construct_instance_types(sizes=[2, 8])
    pools = [fixtures.node_pool(name="default")]
    return pools, {"default": its}, fixtures.make_self_spread_pods(6, cpu)


def _fleet_referee(cpu):
    """Solo in-process kernel solve of the same lane problem."""
    from karpenter_tpu.solver.tpu import TpuScheduler

    pools, ibp, pods = _fleet_problem(cpu)
    topo = Topology(pools, ibp, pods)
    r = TpuScheduler(pools, ibp, topo).solve(pods)
    return sorted(
        tuple(sorted(p.name for p in cl.pods))
        for cl in r.new_node_claims
        if cl.pods
    )


def _fleet_server(max_lanes, window=10.0):
    from karpenter_tpu.solver import epochs as epochs_mod

    path = tempfile.mktemp(suffix=".fleet.sock")
    srv = SolverServer(
        path,
        fleet_window_seconds=window,
        fleet_max_lanes=max_lanes,
        admission=epochs_mod.AdmissionGate(max_inflight=32),
    )
    srv.start()
    return srv


def _fleet_clients(srv, profiles, options_of=None, results=None, errors=None):
    """Concurrent sidecar solves, one thread per profile; returns
    (results, errors) keyed by profile."""
    results = {} if results is None else results
    errors = {} if errors is None else errors
    barrier = threading.Barrier(len(profiles))

    def run(cpu):
        try:
            c = SolverClient(srv.socket_path, request_timeout=600.0)
            pools, ibp, pods = _fleet_problem(cpu)
            opts = options_of(cpu) if options_of else None
            barrier.wait()
            got = c.solve(pools, ibp, pods, options=opts)
            results[cpu] = (got, pods)
            c.close()
        except Exception as e:
            errors[cpu] = e

    threads = [
        threading.Thread(target=run, args=(cpu,), daemon=True)
        for cpu in profiles
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    return results, errors


def _fleet_remote_parts(got, pods):
    name_of = {p.uid: p.name for p in pods}
    return sorted(
        tuple(sorted(name_of[u] for u in cl["pod_uids"]))
        for cl in got["new_node_claims"]
        if cl["pod_uids"]
    )


def test_fleet_deadline_blown_lane_does_not_poison_siblings():
    """A lane whose solve budget is already exhausted when the window
    drains must come back timed_out with no decisions — exactly the solo
    partial-result contract — while its three siblings land
    decision-identical to solo in the SAME coalesced window."""
    healthy = ["100m", "200m", "300m"]
    refs = {cpu: _fleet_referee(cpu) for cpu in healthy}
    srv = _fleet_server(max_lanes=4)
    try:
        results, errors = _fleet_clients(
            srv,
            healthy + ["400m"],
            options_of=lambda cpu: (
                SchedulerOptions(timeout_seconds=1e-9)
                if cpu == "400m"
                else None
            ),
        )
    finally:
        srv.stop()
    assert not errors, errors
    got, _pods = results["400m"]
    assert got["timed_out"] is True
    assert not got["new_node_claims"] or not any(
        cl["pod_uids"] for cl in got["new_node_claims"]
    )
    for cpu in healthy:
        got, pods = results[cpu]
        assert got["timed_out"] is False
        assert not got["pod_errors"]
        assert _fleet_remote_parts(got, pods) == refs[cpu], cpu


def test_fleet_corrupt_and_oversized_lanes_do_not_poison_the_window(
    monkeypatch,
):
    """Corrupt and oversized frames arriving alongside a coalescing
    window cost THEIR senders one ERROR answer each — the siblings'
    coalesced window never sees them and lands decision-identical to
    solo."""
    from karpenter_tpu.solver import fleet as fleet_mod
    from karpenter_tpu.solver import service as svc

    # above the real ~130 KB lane payloads, far below the production cap
    monkeypatch.setattr(svc, "MAX_FRAME_LEN", 512 * 1024)
    monkeypatch.setattr(svc, "OVERSIZE_DRAIN_MAX", 2 * 1024 * 1024)
    healthy = ["100m", "200m", "300m"]
    refs = {cpu: _fleet_referee(cpu) for cpu in healthy}
    srv = _fleet_server(max_lanes=3)
    c0 = fleet_mod.FLEET_SOLVES.value({"mode": "coalesced"})
    try:
        # the faulty traffic rides raw sockets concurrently with the
        # window: garbage JSON on a valid frame + an oversized frame
        def corrupt():
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.settimeout(30)
            sock.connect(srv.socket_path)
            body = b"this is not a problem payload"
            sock.sendall(
                MAGIC + struct.pack("<III", KIND_SOLVE, 21, len(body)) + body
            )
            head = _read_exact(sock, 16)
            kind, rid, length = struct.unpack("<III", head[4:])
            _read_exact(sock, length)
            assert (kind, rid) == (KIND_ERROR, 21)
            sock.close()

        def oversized():
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.settimeout(30)
            sock.connect(srv.socket_path)
            body = b"x" * (1024 * 1024)  # over MAX, under the drain cap
            sock.sendall(
                MAGIC + struct.pack("<III", KIND_SOLVE, 22, len(body)) + body
            )
            head = _read_exact(sock, 16)
            kind, rid, length = struct.unpack("<III", head[4:])
            payload = _read_exact(sock, length)
            assert (kind, rid) == (KIND_ERROR, 22)
            assert b"exceeds max" in payload
            sock.close()

        fault_threads = [
            threading.Thread(target=corrupt, daemon=True),
            threading.Thread(target=oversized, daemon=True),
        ]
        for t in fault_threads:
            t.start()
        results, errors = _fleet_clients(srv, healthy)
        for t in fault_threads:
            t.join(timeout=60)
    finally:
        srv.stop()
    assert not errors, errors
    for cpu in healthy:
        got, pods = results[cpu]
        assert not got["pod_errors"]
        assert _fleet_remote_parts(got, pods) == refs[cpu], cpu
    # the healthy lanes really shared a window despite the fault traffic
    assert fleet_mod.FLEET_SOLVES.value({"mode": "coalesced"}) - c0 == 3


@pytest.mark.soak
def test_chaos_soak_fleet_rotating_lane_faults(monkeypatch):
    """Steady coalesced traffic with a rotating per-lane fault — corrupt
    frame, blown deadline, client vanishing mid-solve, oversized frame —
    one faulty lane per round against three healthy siblings. Every
    round, every healthy lane must land decision-identical to the solo
    referee (runs under racert-instrumented locks via the soak marker:
    the coalescer's window lock and event handoffs are witnessed too)."""
    from karpenter_tpu.solver import service as svc

    # above the real ~130 KB lane payloads, far below the production cap
    monkeypatch.setattr(svc, "MAX_FRAME_LEN", 512 * 1024)
    monkeypatch.setattr(svc, "OVERSIZE_DRAIN_MAX", 2 * 1024 * 1024)
    healthy = ["100m", "200m", "300m"]
    refs = {cpu: _fleet_referee(cpu) for cpu in healthy}
    srv = _fleet_server(max_lanes=4, window=2.0)
    try:
        for round_i, fault in enumerate(
            ["corrupt", "deadline", "vanish", "oversized"]
        ):
            results, errors = {}, {}

            def faulty():
                try:
                    if fault == "deadline":
                        c = SolverClient(srv.socket_path, request_timeout=600.0)
                        pools, ibp, pods = _fleet_problem("400m")
                        got = c.solve(
                            pools, ibp, pods,
                            options=SchedulerOptions(timeout_seconds=1e-9),
                        )
                        assert got["timed_out"] is True
                        c.close()
                        return
                    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                    sock.settimeout(30)
                    sock.connect(srv.socket_path)
                    if fault == "corrupt":
                        body = b"{not json"
                    elif fault == "oversized":
                        body = b"x" * (1024 * 1024)
                    else:  # vanish: a real-looking frame, then hang up
                        body = b"{}"
                    sock.sendall(
                        MAGIC
                        + struct.pack("<III", KIND_SOLVE, 31, len(body))
                        + body
                    )
                    if fault == "vanish":
                        sock.close()
                        return
                    head = _read_exact(sock, 16)
                    kind, rid, length = struct.unpack("<III", head[4:])
                    _read_exact(sock, length)
                    assert kind == KIND_ERROR
                    sock.close()
                except Exception as e:  # surfaced via errors dict below
                    errors["faulty"] = e

            ft = threading.Thread(target=faulty, daemon=True)
            ft.start()
            _fleet_clients(srv, healthy, results=results, errors=errors)
            ft.join(timeout=120)
            assert not ft.is_alive(), f"round {round_i}: faulty lane wedged"
            faulty_err = errors.pop("faulty", None)
            assert faulty_err is None, (round_i, fault, faulty_err)
            assert not errors, (round_i, fault, errors)
            for cpu in healthy:
                got, pods = results[cpu]
                assert not got["pod_errors"], (round_i, fault)
                assert _fleet_remote_parts(got, pods) == refs[cpu], (
                    round_i,
                    fault,
                    cpu,
                )
    finally:
        srv.stop()
