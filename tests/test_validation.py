"""NodePool validation — the CRD CEL rule table absorbed into runtime
checks (reference nodepool.go markers + nodepool_validation.go:28
RuntimeValidate). Scenario families mirror
/root/reference/pkg/apis/v1/nodepool_validation_cel_test.go.
"""

from __future__ import annotations

import pytest

from karpenter_tpu.api.objects import (
    Budget,
    NodeSelectorRequirement,
    Operator,
    Taint,
    TaintEffect,
)
from karpenter_tpu.controllers.nodepool_aux import NodePoolValidation
from karpenter_tpu.testing import fixtures


def ok(np):
    assert NodePoolValidation.validate(np) is None


def bad(np, fragment: str = ""):
    err = NodePoolValidation.validate(np)
    assert err is not None, "expected a validation error"
    if fragment:
        assert fragment in err, err


# -- budgets (cel_test.go:149-260) ------------------------------------------


def test_budget_valid_shapes():
    ok(fixtures.node_pool(budgets=[Budget(nodes="10")]))
    ok(fixtures.node_pool(budgets=[Budget(nodes="100%")]))
    ok(fixtures.node_pool(budgets=[Budget(nodes="0")]))
    # both schedule and duration
    ok(
        fixtures.node_pool(
            budgets=[
                Budget(nodes="10", schedule="* * * * *", duration_seconds=3600)
            ]
        )
    )
    # hours and minutes in duration
    ok(
        fixtures.node_pool(
            budgets=[
                Budget(
                    nodes="10", schedule="@daily", duration_seconds=2 * 3600 + 300
                )
            ]
        )
    )
    # neither
    ok(fixtures.node_pool(budgets=[Budget(nodes="10")]))
    # special-cased crons
    for special in ("@annually", "@yearly", "@monthly", "@weekly", "@daily",
                    "@midnight", "@hourly"):
        ok(
            fixtures.node_pool(
                budgets=[
                    Budget(nodes="10", schedule=special, duration_seconds=60)
                ]
            )
        )


def test_budget_invalid_cron():
    bad(
        fixtures.node_pool(
            budgets=[Budget(nodes="10", schedule="*", duration_seconds=60)]
        ),
        "schedule",
    )
    bad(
        fixtures.node_pool(
            budgets=[
                Budget(nodes="10", schedule="* * * *", duration_seconds=60)
            ]
        ),
        "schedule",
    )
    bad(
        fixtures.node_pool(
            budgets=[
                Budget(nodes="10", schedule="@crazy", duration_seconds=60)
            ]
        ),
        "schedule",
    )


def test_budget_duration_rules():
    # negative duration
    bad(
        fixtures.node_pool(
            budgets=[
                Budget(nodes="10", schedule="* * * * *", duration_seconds=-60)
            ]
        ),
        "duration",
    )
    # seconds granularity (CRD pattern admits h/m only)
    bad(
        fixtures.node_pool(
            budgets=[
                Budget(nodes="10", schedule="* * * * *", duration_seconds=30)
            ]
        ),
        "duration",
    )


def test_budget_nodes_value_rules():
    bad(fixtures.node_pool(budgets=[Budget(nodes="-1")]), "nodes")
    bad(fixtures.node_pool(budgets=[Budget(nodes="-10%")]), "nodes")
    bad(fixtures.node_pool(budgets=[Budget(nodes="101%")]), "nodes")
    bad(fixtures.node_pool(budgets=[Budget(nodes="1000%")]), "nodes")
    bad(fixtures.node_pool(budgets=[Budget(nodes="five")]), "nodes")


def test_budget_schedule_requires_duration_and_vice_versa():
    bad(
        fixtures.node_pool(
            budgets=[Budget(nodes="10", schedule="* * * * *")]
        ),
        "'schedule' must be set with 'duration'",
    )
    bad(
        fixtures.node_pool(budgets=[Budget(nodes="10", duration_seconds=60)]),
        "'schedule' must be set with 'duration'",
    )


def test_one_bad_budget_among_many_fails():
    bad(
        fixtures.node_pool(
            budgets=[
                Budget(nodes="10"),
                Budget(nodes="10", schedule="@invalid", duration_seconds=60),
            ]
        )
    )


# -- taints (cel_test.go:313-377) -------------------------------------------


def test_taint_valid():
    ok(
        fixtures.node_pool(
            taints=[
                Taint(key="a", effect=TaintEffect.NO_SCHEDULE),
                Taint(key="dev/a", value="v", effect=TaintEffect.NO_EXECUTE),
                Taint(key="a.b.c/d-e_f", effect=TaintEffect.PREFER_NO_SCHEDULE),
            ]
        )
    )
    # same key, different effects (cel_test.go:369)
    ok(
        fixtures.node_pool(
            taints=[
                Taint(key="a", effect=TaintEffect.NO_SCHEDULE),
                Taint(key="a", effect=TaintEffect.NO_EXECUTE),
            ]
        )
    )


def test_taint_invalid_keys_and_values():
    bad(
        fixtures.node_pool(
            taints=[Taint(key="???", effect=TaintEffect.NO_SCHEDULE)]
        ),
        "taint key",
    )
    bad(
        fixtures.node_pool(
            taints=[Taint(key="", effect=TaintEffect.NO_SCHEDULE)]
        ),
        "required",
    )
    bad(
        fixtures.node_pool(
            taints=[Taint(key="a" * 64, effect=TaintEffect.NO_SCHEDULE)]
        ),
        "taint key",
    )
    bad(
        fixtures.node_pool(
            taints=[Taint(key="ok", value="bad value!", effect=TaintEffect.NO_SCHEDULE)]
        ),
        "taint value",
    )
    # startup taints run the same rules
    bad(
        fixtures.node_pool(
            startup_taints=[Taint(key="???", effect=TaintEffect.NO_SCHEDULE)]
        ),
        "taint key",
    )


# -- requirements (cel_test.go:379-553) --------------------------------------


def _np_req(*reqs):
    return fixtures.node_pool(requirements=list(reqs))


def test_requirement_valid_keys_and_ops():
    ok(
        _np_req(
            NodeSelectorRequirement("custom-key", Operator.IN, ["a"]),
            NodeSelectorRequirement("dev.example.com/key", Operator.NOT_IN, ["b"]),
            NodeSelectorRequirement("exists-key", Operator.EXISTS),
            NodeSelectorRequirement("absent-key", Operator.DOES_NOT_EXIST),
            NodeSelectorRequirement("gt-key", Operator.GT, ["5"]),
            NodeSelectorRequirement("lt-key", Operator.LT, ["0"]),
        )
    )


def test_requirement_invalid_keys():
    bad(_np_req(NodeSelectorRequirement("???", Operator.EXISTS)), "qualified name")
    bad(
        _np_req(NodeSelectorRequirement("a" * 64, Operator.EXISTS)),
        "qualified name",
    )
    bad(
        _np_req(
            NodeSelectorRequirement("karpenter.sh/nodepool", Operator.IN, ["x"])
        ),
        "restricted",
    )


def test_requirement_restricted_domains():
    bad(
        _np_req(
            NodeSelectorRequirement("kubernetes.io/custom", Operator.EXISTS)
        ),
        "restricted",
    )
    bad(
        _np_req(NodeSelectorRequirement("k8s.io/custom", Operator.EXISTS)),
        "restricted",
    )
    bad(
        _np_req(
            NodeSelectorRequirement("sub.kubernetes.io/custom", Operator.EXISTS)
        ),
        "restricted",
    )
    # exceptions (cel_test.go:452-487)
    ok(_np_req(NodeSelectorRequirement("kops.k8s.io/custom", Operator.EXISTS)))
    ok(
        _np_req(
            NodeSelectorRequirement(
                "node-restriction.kubernetes.io/custom", Operator.EXISTS
            )
        )
    )
    # well-known labels inside restricted domains are allowed
    ok(
        _np_req(
            NodeSelectorRequirement(
                "topology.kubernetes.io/zone", Operator.IN, ["z1"]
            )
        )
    )


def test_requirement_in_needs_values():
    bad(
        _np_req(NodeSelectorRequirement("key", Operator.IN, [])),
        "operator 'In' must have a value defined",
    )


def test_requirement_gt_lt_values():
    for vals in ([], ["1", "2"], ["notanum"]):
        bad(
            _np_req(NodeSelectorRequirement("key", Operator.GT, vals)),
            "single positive integer",
        )
        bad(
            _np_req(NodeSelectorRequirement("key", Operator.LT, vals)),
            "single positive integer",
        )
    # "-1" fails label-value validation first (the reference's multierr
    # reports both; the first error wins here)
    bad(_np_req(NodeSelectorRequirement("key", Operator.GT, ["-1"])))
    bad(_np_req(NodeSelectorRequirement("key", Operator.LT, ["-1"])))


def test_requirement_min_values_bounds():
    bad(
        _np_req(
            NodeSelectorRequirement("key", Operator.IN, ["a"], min_values=-1)
        ),
        "minValues",
    )
    bad(
        _np_req(
            NodeSelectorRequirement("key", Operator.IN, ["a"], min_values=0)
        ),
        "minValues",
    )
    bad(
        _np_req(
            NodeSelectorRequirement(
                "key", Operator.IN, [str(i) for i in range(60)], min_values=51
            )
        ),
        "minValues",
    )
    # more values than 50 is fine without minValues (cel_test.go:536)
    ok(
        _np_req(
            NodeSelectorRequirement(
                "key", Operator.IN, [str(i) for i in range(60)]
            )
        )
    )
    # raw length counts (no dedup — nodeclaim_validation.go:142); three
    # values with duplicates still satisfy minValues=3
    ok(
        _np_req(
            NodeSelectorRequirement(
                "key", Operator.IN, ["a", "b", "a"], min_values=3
            )
        )
    )
    bad(
        _np_req(
            NodeSelectorRequirement("key", Operator.IN, ["a", "b"], min_values=3)
        ),
        "at least that many values",
    )
    ok(
        _np_req(
            NodeSelectorRequirement("key", Operator.IN, ["a", "b"], min_values=2)
        )
    )


def test_requirement_count_cap():
    reqs = [
        NodeSelectorRequirement(f"key-{i}", Operator.EXISTS) for i in range(101)
    ]
    bad(fixtures.node_pool(requirements=reqs), "100")


# -- template labels (cel_test.go:554-647) -----------------------------------


def test_labels_rules():
    ok(fixtures.node_pool(labels={"custom": "v", "dev.example.com/x": "y"}))
    bad(
        fixtures.node_pool(labels={"karpenter.sh/nodepool": "x"}), "restricted"
    )
    bad(fixtures.node_pool(labels={"???": "v"}), "labels")
    bad(fixtures.node_pool(labels={"ok": "bad value!"}), "label")
    bad(fixtures.node_pool(labels={"kubernetes.io/custom": "v"}), "restricted")
    # exceptions
    ok(fixtures.node_pool(labels={"kops.k8s.io/x": "v"}))
    ok(fixtures.node_pool(labels={"node-restriction.kubernetes.io/x": "v"}))
    ok(fixtures.node_pool(labels={"topology.kubernetes.io/zone": "z1"}))
    # too-long key
    bad(fixtures.node_pool(labels={"a" * 64: "v"}), "labels")


# -- scalar/static fields ----------------------------------------------------


def test_weight_and_replicas_rules():
    ok(fixtures.node_pool(weight=1))
    ok(fixtures.node_pool(weight=100))
    bad(fixtures.node_pool(weight=101), "weight")
    np = fixtures.node_pool(replicas=3)
    ok(np)
    np = fixtures.node_pool(replicas=3, weight=5)
    bad(np, "static")
    np = fixtures.node_pool(replicas=3, limits={"cpu": "100"})
    bad(np, "limits.nodes")
    np = fixtures.node_pool(replicas=3, limits={"nodes": "5"})
    ok(np)
    np = fixtures.node_pool(replicas=-1)
    bad(np, "replicas")


def test_consolidate_after_non_negative():
    np = fixtures.node_pool()
    np.disruption.consolidate_after_seconds = -1
    bad(np, "consolidateAfter")


def test_budget_name_based_cron_accepted():
    """Name-based cron fields are valid (the reference CRD pattern is
    permissive; robfig cron accepts MON-FRI at parse time)."""
    ok(
        fixtures.node_pool(
            budgets=[
                Budget(
                    nodes="10", schedule="0 9 * * MON-FRI", duration_seconds=3600
                )
            ]
        )
    )


def test_requirement_min_values_counts_raw_length_and_known_values():
    """nodeclaim_validation.go:142 compares raw len(values) — duplicates
    count; validateWellKnownValues:187 requires minValues VALID values for
    keys with a known universe."""
    # duplicates count toward minValues (no dedup in the reference)
    ok(
        _np_req(
            NodeSelectorRequirement("key", Operator.IN, ["a", "a"], min_values=2)
        )
    )
    # capacity-type: enough raw values but too few KNOWN ones
    from karpenter_tpu.api import labels as well_known

    bad(
        _np_req(
            NodeSelectorRequirement(
                well_known.CAPACITY_TYPE_LABEL_KEY,
                Operator.IN,
                ["spot", "bogus1", "bogus2"],
                min_values=2,
            )
        ),
        "valid values",
    )
    ok(
        _np_req(
            NodeSelectorRequirement(
                well_known.CAPACITY_TYPE_LABEL_KEY,
                Operator.IN,
                ["spot", "on-demand", "bogus"],
                min_values=2,
            )
        )
    )
