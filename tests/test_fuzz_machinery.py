"""The fuzz machinery's own tests (ISSUE satellite: a silent generator
gap or a broken shrinker would fake coverage while testing nothing).

- generator determinism + wire-roundtrip identity;
- DISTRIBUTION: every scheduling family in fuzz.FAMILIES actually
  appears across a seeded batch;
- shrinker: monotone (no accepted candidate ever grows), minimal-repro
  stability (shrinking a shrunk case is a fixpoint), and
  predicate-error containment (an erroring candidate is never adopted);
- corpus round-trip through the service codec.

Generation and shrinking are pure host-side work (no solves), so this
module costs milliseconds of tier-1.
"""

from __future__ import annotations

import pytest

from karpenter_tpu.testing import fuzz

pytestmark = [pytest.mark.fuzz]


def test_generator_is_deterministic():
    for seed in (fuzz.fuzz_seed_base(), 31337):
        a = fuzz.generate_case(seed)
        b = fuzz.generate_case(seed)
        assert a.problem == b.problem
        assert a.families == b.families


def test_generated_case_roundtrips_the_wire_codec():
    case = fuzz.generate_case(fuzz.fuzz_seed_base())
    pools, ibp, pods, views, daemons, options, src = case.materialize()
    re_encoded = fuzz.encode_case_problem(
        pools, ibp, pods, views, daemons, options, src
    )
    assert re_encoded == case.problem


def test_generator_distribution_covers_every_family():
    """Across a 250-seed batch every family in fuzz.FAMILIES must
    appear — a generator path that silently stopped emitting (a
    probability typo, a dead branch) fakes coverage for its whole
    scheduling family."""
    seen: dict[str, int] = {}
    for seed in range(fuzz.fuzz_seed_base(), fuzz.fuzz_seed_base() + 250):
        for fam in fuzz.generate_case(seed).families:
            seen[fam] = seen.get(fam, 0) + 1
    missing = [f for f in fuzz.FAMILIES if not seen.get(f)]
    assert not missing, (
        f"generator never emitted families {missing} in 250 seeds "
        f"(distribution: {dict(sorted(seen.items()))})"
    )


def test_generated_pod_identity_is_owned():
    """Names, uids, and creation timestamps come from the seed — the FFD
    tiebreak sorts on uid, so random identity would make the same corpus
    file order (and thus decide) differently across replays."""
    _p, _i, pods, _v, _d, _o, _s = fuzz.generate_case(4242).materialize()
    for p in pods:
        assert p.uid.startswith("fz-4242-"), p.uid
        assert p.name.startswith("fz-4242-"), p.name
    _p, _i, pods2, _v, _d, _o, _s = fuzz.generate_case(4242).materialize()
    assert [p.uid for p in pods] == [p.uid for p in pods2]


# ---------------------------------------------------------------------------
# shrinker


def _volume_pod_case() -> fuzz.FuzzCase:
    """First seed whose case carries volume-claim pods (deterministic)."""
    seed = fuzz.fuzz_seed_base()
    while True:
        case = fuzz.generate_case(seed)
        if "volumes" in case.families:
            return case
        seed += 1


def test_shrinker_is_monotone_and_reaches_a_small_repro():
    """Predicate: the case still contains a volume-claim pod. The shrunk
    case must keep reproducing, every ACCEPTED candidate must be <= its
    predecessor under case_size (monotone), and the result must be small
    (one pod, no cluster structure left)."""
    case = _volume_pod_case()
    accepted_sizes = []

    def failing(c: fuzz.FuzzCase) -> bool:
        ok = any(p.volume_claims for p in c.materialize()[2])
        if ok:
            accepted_sizes.append(fuzz.case_size(c))
        return ok

    shrunk = fuzz.shrink(case, failing, max_evals=400)
    assert any(p.volume_claims for p in shrunk.materialize()[2])
    # monotone: the adopted-candidate trajectory never grows. (every
    # reproducing candidate is adopted by construction, so the recorded
    # True-candidates ARE the adoption sequence)
    assert accepted_sizes == sorted(accepted_sizes, reverse=True) or all(
        b <= a for a, b in zip(accepted_sizes, accepted_sizes[1:])
    )
    assert fuzz.case_size(shrunk) <= fuzz.case_size(case)
    pools, _ibp, pods, views, daemons, _opts, _src = shrunk.materialize()
    assert len(pods) == 1
    assert not views and not daemons
    assert len(pools) == 1
    p = pods[0]
    assert not p.topology_spread_constraints and not p.pod_anti_affinity
    assert not p.host_ports and not p.node_selector


def test_shrinker_minimal_repro_is_stable():
    """Shrinking an already-minimal case is a fixpoint: same size, same
    problem payload — the corpus never churns on re-shrink."""
    case = _volume_pod_case()

    def failing(c: fuzz.FuzzCase) -> bool:
        return any(p.volume_claims for p in c.materialize()[2])

    once = fuzz.shrink(case, failing, max_evals=400)
    twice = fuzz.shrink(once, failing, max_evals=400)
    assert fuzz.case_size(twice) == fuzz.case_size(once)
    assert twice.problem == once.problem


def test_shrinker_treats_predicate_errors_as_not_reproducing():
    """A candidate that makes the predicate ERROR (a malformed shrink —
    not the bug under investigation) must never be adopted; the original
    case survives."""
    case = fuzz.generate_case(fuzz.fuzz_seed_base())
    n_pods = len(case.materialize()[2])

    def failing(c: fuzz.FuzzCase) -> bool:
        if len(c.materialize()[2]) < n_pods:
            raise RuntimeError("different bug entirely")
        return True

    shrunk = fuzz.shrink(case, failing, max_evals=50)
    assert len(shrunk.materialize()[2]) == n_pods


def test_shrinker_respects_eval_budget():
    calls = []

    def failing(c: fuzz.FuzzCase) -> bool:
        calls.append(1)
        return True

    fuzz.shrink(fuzz.generate_case(fuzz.fuzz_seed_base()), failing, max_evals=7)
    assert len(calls) <= 7


# ---------------------------------------------------------------------------
# corpus plumbing


def test_corpus_save_load_roundtrip(tmp_path):
    case = fuzz.generate_case(999)
    path = fuzz.save_corpus_case(
        case, "parity", "example violation", dirpath=str(tmp_path)
    )
    entries = fuzz.load_corpus(str(tmp_path))
    assert len(entries) == 1
    fn, entry = entries[0]
    assert fn in path
    assert entry["seed"] == 999 and entry["mode"] == "parity"
    assert fuzz.corpus_case(entry).problem == case.problem
    # the repro command names the seed and the fuzz marker
    assert "FUZZ_SEED=999" in entry["repro"] and "-m fuzz" in entry["repro"]
