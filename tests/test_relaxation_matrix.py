"""The preference-relaxation scenario matrix: the reference's suite_test.go
relaxation families (preferences.go:38-161) driven through the HYBRID
dispatch — so every scenario also exercises the per-pod partitioning (the
relaxable pod rides the oracle continuation against the kernel's state).

Ladder order under test (preferences.go:38 Relax):
  1. drop a required node-affinity OR-term (when >1 remain)
  2. drop the highest-weight preferred pod affinity
  3. drop the highest-weight preferred pod anti-affinity
  4. drop the highest-weight preferred node affinity
  5. drop a ScheduleAnyway topology spread constraint
"""

from __future__ import annotations

import pytest

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import (
    LabelSelector,
    NodeAffinity,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    Operator,
    PodAffinityTerm,
    PreferredSchedulingTerm,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
    WhenUnsatisfiable,
)
from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.solver import HybridScheduler, Scheduler, Topology
from karpenter_tpu.testing import fixtures

ZONE = well_known.TOPOLOGY_ZONE_LABEL_KEY
HOSTNAME = well_known.HOSTNAME_LABEL_KEY


def solve_both(pods_fn, pools_fn=None):
    """Oracle and hybrid must agree on errors and pod placement counts."""
    outs = []
    for cls in (Scheduler, HybridScheduler):
        fixtures.reset_rng(17)
        its = construct_instance_types(sizes=[2, 8])
        pools = pools_fn() if pools_fn else [fixtures.node_pool(name="default")]
        pods = pods_fn()
        topo = Topology(pools, {np.name: its for np in pools}, pods)
        # tpu_min_pods=0: the matrix pins KERNEL semantics on tiny batches;
        # production size-routing would shunt them to the oracle
        from karpenter_tpu.solver.oracle import SchedulerOptions

        s = cls(
            pools,
            {np.name: its for np in pools},
            topo,
            options=SchedulerOptions(tpu_min_pods=0),
        )
        outs.append((s.solve(pods), pods, s))
    (orc, orc_pods, _), (hyb, hyb_pods, hs) = outs
    orc_names = {p.uid: p.name for p in orc_pods}
    hyb_names = {p.uid: p.name for p in hyb_pods}
    assert {orc_names[u] for u in orc.pod_errors} == {
        hyb_names[u] for u in hyb.pod_errors
    }
    return orc, hyb, hs


def base_pods(n=4):
    return [
        fixtures.pod(name=f"base-{i}", requests={"cpu": "200m"})
        for i in range(n)
    ]


# -- rung 1: required node-affinity OR-terms ---------------------------------


def test_unsatisfiable_first_affinity_term_relaxes_to_second():
    """Term[0] matches nothing; term[1] is satisfiable — the reference
    keeps only term[0] initially, then drops it on failure."""

    def pods():
        p = fixtures.pod(name="multi-term", requests={"cpu": "100m"})
        p.node_affinity = NodeAffinity(
            required_terms=[
                NodeSelectorTerm(
                    match_expressions=[
                        NodeSelectorRequirement(ZONE, Operator.IN, ["no-such-zone"])
                    ]
                ),
                NodeSelectorTerm(
                    match_expressions=[
                        NodeSelectorRequirement(ZONE, Operator.IN, ["test-zone-b"])
                    ]
                ),
            ]
        )
        return base_pods() + [p]

    orc, hyb, hs = solve_both(pods)
    assert not orc.pod_errors
    assert hyb.pod_errors == {}
    assert hs.used_tpu is True  # the base pods rode the kernel


def test_single_unsatisfiable_required_term_fails():
    """One required term, unsatisfiable: relaxation cannot drop the last
    term; the pod must error on both paths."""

    def pods():
        p = fixtures.pod(name="stuck", requests={"cpu": "100m"})
        p.node_affinity = NodeAffinity(
            required_terms=[
                NodeSelectorTerm(
                    match_expressions=[
                        NodeSelectorRequirement(ZONE, Operator.IN, ["no-such-zone"])
                    ]
                )
            ]
        )
        return base_pods() + [p]

    orc, hyb, _ = solve_both(pods)
    assert len(orc.pod_errors) == 1


# -- rungs 2-3: preferred pod (anti-)affinity --------------------------------


@pytest.mark.parametrize("anti", [False, True])
def test_unsatisfiable_preferred_pod_affinity_drops(anti):
    """A preferred (anti-)affinity to a label that exists on every base pod
    (anti) / no pod (affinity) would block scheduling if required; as a
    preference it relaxes away and everything lands."""

    def pods():
        out = []
        for i, p in enumerate(base_pods()):
            p.metadata.labels["app"] = "base"
            out.append(p)
        p = fixtures.pod(name="pref", labels={"app": "base"}, requests={"cpu": "100m"})
        term = WeightedPodAffinityTerm(
            weight=100,
            term=PodAffinityTerm(
                topology_key=HOSTNAME,
                label_selector=LabelSelector(match_labels={"app": "base"}),
            ),
        )
        if anti:
            p.pod_anti_affinity_preferred = [term]
        else:
            p.pod_affinity_preferred = [
                WeightedPodAffinityTerm(
                    weight=100,
                    term=PodAffinityTerm(
                        topology_key=HOSTNAME,
                        label_selector=LabelSelector(match_labels={"app": "missing"}),
                    ),
                )
            ]
        out.append(p)
        return out

    orc, hyb, hs = solve_both(pods)
    assert not orc.pod_errors and not hyb.pod_errors
    # round 4: relaxable preferences ride the kernel (tier ladder inside
    # the step) — no oracle continuation
    assert hs.used_tpu is True
    assert hs.fallback_reason is None, hs.fallback_reason


def test_weighted_preferences_drop_highest_first():
    """preferences.go:85: among several preferred terms the HIGHEST weight
    drops first; a low-weight satisfiable preference plus a high-weight
    unsatisfiable one still schedules."""

    def pods():
        p = fixtures.pod(name="weighted", labels={"app": "w"}, requests={"cpu": "100m"})
        p.pod_affinity_preferred = [
            WeightedPodAffinityTerm(
                weight=90,
                term=PodAffinityTerm(
                    topology_key=ZONE,
                    label_selector=LabelSelector(match_labels={"app": "missing"}),
                ),
            ),
            WeightedPodAffinityTerm(
                weight=10,
                term=PodAffinityTerm(
                    topology_key=ZONE,
                    label_selector=LabelSelector(match_labels={"app": "w"}),
                ),
            ),
        ]
        return base_pods() + [p]

    orc, hyb, _ = solve_both(pods)
    assert not orc.pod_errors and not hyb.pod_errors


# -- rung 4: preferred node affinity -----------------------------------------


@pytest.mark.parametrize("satisfiable", [True, False])
def test_preferred_node_affinity(satisfiable):
    def pods():
        p = fixtures.pod(name="nodepref", requests={"cpu": "100m"})
        zone = "test-zone-a" if satisfiable else "no-such-zone"
        p.node_affinity = NodeAffinity(
            preferred=[
                PreferredSchedulingTerm(
                    weight=1,
                    preference=NodeSelectorTerm(
                        match_expressions=[
                            NodeSelectorRequirement(ZONE, Operator.IN, [zone])
                        ]
                    ),
                )
            ]
        )
        return base_pods() + [p]

    orc, hyb, _ = solve_both(pods)
    assert not orc.pod_errors and not hyb.pod_errors


# -- rung 5: ScheduleAnyway spread -------------------------------------------


@pytest.mark.parametrize("n", [6, 12])
def test_schedule_anyway_mixed_batch(n):
    """ScheduleAnyway pods in a mostly-supported batch: the bulk rides the
    kernel, the relaxable tail lands via the continuation, nothing errors."""

    def pods():
        out = base_pods(n)
        for i in range(3):
            out.append(
                fixtures.pod(
                    name=f"anyway-{i}",
                    labels={"app": "sa"},
                    requests={"cpu": "100m"},
                    topology_spread_constraints=[
                        TopologySpreadConstraint(
                            max_skew=1,
                            topology_key=ZONE,
                            when_unsatisfiable=WhenUnsatisfiable.SCHEDULE_ANYWAY,
                            label_selector=LabelSelector(match_labels={"app": "sa"}),
                        )
                    ],
                )
            )
        return out

    orc, hyb, hs = solve_both(pods)
    assert not orc.pod_errors and not hyb.pod_errors
    assert hs.used_tpu is True


# -- the reference's preference benchmark mix --------------------------------


@pytest.mark.parametrize("n", [10, 25])
def test_preference_mix_all_schedule(n):
    """makePreferencePods (scheduling_benchmark_test.go:378): every pod has
    one unsatisfiable and one satisfiable preference; all must land."""

    def pods():
        return fixtures.make_preference_pods(n)

    orc, hyb, _ = solve_both(pods)
    assert not orc.pod_errors and not hyb.pod_errors


def test_ignore_preferences_policy_matches_oracle():
    """PreferencePolicy=Ignore (scheduler.go:74): preferences are stripped
    up front — no relaxation ladder exists, so the kernel encodes the
    strict problem DIRECTLY (round-4: the former PreferencePolicy=Ignore
    encode gate is gone) and must match the oracle bit-for-bit."""
    from karpenter_tpu.solver.oracle import SchedulerOptions

    results = []
    for cls in (Scheduler, HybridScheduler):
        fixtures.reset_rng(17)
        its = construct_instance_types(sizes=[2, 8])
        pool = fixtures.node_pool(name="default")
        pods = fixtures.make_preference_pods(8)
        topo = Topology([pool], {"default": its}, pods, ignore_preferences=True)
        s = cls(
            [pool], {"default": its}, topo,
            options=SchedulerOptions(ignore_preferences=True, tpu_min_pods=0),
        )
        results.append((s.solve(pods), s))
    (orc, _), (hyb, hs) = results
    assert not orc.pod_errors and not hyb.pod_errors
    assert hs.used_tpu is True, hs.fallback_reason  # Ignore rides the kernel
    parts = lambda r: sorted(
        tuple(sorted(p.name for p in c.pods)) for c in r.new_node_claims if c.pods
    )
    assert parts(orc) == parts(hyb)


def test_ignore_preferences_multiple_required_terms_matches_oracle():
    """Under Ignore, multiple required node-affinity OR-terms never relax:
    only term[0] applies (strict_from_pod) — kernel and oracle must agree,
    including the pod erroring when term[0] is unsatisfiable."""
    from karpenter_tpu.solver.oracle import SchedulerOptions

    def build():
        fixtures.reset_rng(19)
        its = construct_instance_types(sizes=[2, 8])
        pool = fixtures.node_pool(name="default")
        pods = base_pods()
        p = fixtures.pod(name="multi-term", requests={"cpu": "100m"})
        p.node_affinity = NodeAffinity(
            required_terms=[
                NodeSelectorTerm(
                    match_expressions=[
                        NodeSelectorRequirement(ZONE, Operator.IN, ["no-such-zone"])
                    ]
                ),
                NodeSelectorTerm(
                    match_expressions=[
                        NodeSelectorRequirement(ZONE, Operator.IN, ["test-zone-b"])
                    ]
                ),
            ]
        )
        pods.append(p)
        topo = Topology([pool], {"default": its}, pods, ignore_preferences=True)
        return pool, its, topo, pods

    outs = []
    for cls in (Scheduler, HybridScheduler):
        pool, its, topo, pods = build()
        s = cls(
            [pool], {"default": its}, topo,
            options=SchedulerOptions(ignore_preferences=True, tpu_min_pods=0),
        )
        outs.append((s.solve(pods), s))
    (orc, _), (hyb, hs) = outs
    # OR-terms still relax under Ignore (they are requirements, not
    # preferences): the kernel's tier ladder lands the pod via term[1]
    assert hs.used_tpu is True, hs.fallback_reason
    assert not orc.pod_errors and not hyb.pod_errors


def test_preference_pods_under_inverse_anti_affinity_match_oracle():
    """The c6 shape in miniature: required-anti pods (app=nginx) register
    INVERSE groups whose selector also matches the preference pods
    (app=nginx with preferred anti + node preference). Inverse rows are
    tier-independent (ownership = required anti only; selection = labels),
    so the kernel's tier ladder must still match the oracle exactly."""

    def pods():
        out = fixtures.make_pod_anti_affinity_pods(6, HOSTNAME)
        out += fixtures.make_preference_pods(4)
        return out

    orc, hyb, hs = solve_both(pods)
    assert hs.used_tpu is True, hs.fallback_reason
    assert hs.fallback_reason is None, hs.fallback_reason
    assert not orc.pod_errors and not hyb.pod_errors
