"""Differential chaos fuzzer gates (karpenter_tpu/testing/fuzz.py).

Three tiers over the same seeded case stream:

- the PINNED CORPUS replays first: every counterexample the fuzzer ever
  shrank is a permanent regression scenario (tests/fuzz_corpus/*.json),
  replayed through the mode that caught it;
- the SMOKE tier: a fixed-seed batch (FUZZ_SEED overrides the base,
  FUZZ_CASES the count; default 64) through parity + invariant modes —
  runs inside tier-1's budget, zero violations tolerated;
- the DEEP tier (`-m "fuzz and slow"`): 1000+ cases plus the chaos-mode
  scenario rotation through a live sidecar under the shared fault proxy.

On any violation the failing case auto-shrinks, lands in the corpus, and
the assertion message prints the exact repro command (fuzz.repro_command)
— seed in, bug out, forever.
"""

from __future__ import annotations

import os
import threading

import pytest

from karpenter_tpu.testing import fuzz

pytestmark = [pytest.mark.fuzz]

SMOKE_CASES = max(1, int(os.environ.get("FUZZ_CASES", "64")))
BASE_SEED = fuzz.fuzz_seed_base()


def _check_mode(case: fuzz.FuzzCase, mode: str, tmp_path=None) -> list[str]:
    if mode == "parity":
        return fuzz.check_parity(case)
    if mode == "invariants":
        return fuzz.check_invariants(case)
    if mode.startswith("chaos:"):
        return fuzz.chaos_violations(case, mode.split(":", 1)[1], str(tmp_path))
    raise ValueError(mode)


def _fail_with_repro(failures: list) -> None:
    lines = []
    for seed, mode, violation, corpus_path in failures:
        lines.append(
            f"seed {seed} [{mode}]: {violation}\n"
            f"  shrunk case pinned at {corpus_path}\n"
            f"  repro: {fuzz.repro_command(seed, mode)}"
        )
    pytest.fail(
        f"{len(failures)} fuzz violation(s):\n" + "\n".join(lines), pytrace=False
    )


def _run_batch(seeds, tight_every: int = 4) -> None:
    failures = []
    for i, seed in enumerate(seeds):
        case = fuzz.generate_case(seed)
        mode = "parity"
        viols = fuzz.check_parity(case, tight_slots=(i % tight_every == 0))
        if not viols:
            mode = "invariants"
            viols = fuzz.check_invariants(case)
        if viols:
            # auto-shrink under the SAME mode, pin, and report the seed
            checker = (
                fuzz.check_parity if mode == "parity" else fuzz.check_invariants
            )
            shrunk = fuzz.shrink(
                case, lambda c: bool(checker(c)), max_evals=60
            )
            path = fuzz.save_corpus_case(shrunk, mode, viols[0])
            failures.append((seed, mode, viols[0], path))
    if failures:
        _fail_with_repro(failures)


# ---------------------------------------------------------------------------
# 1. the pinned corpus replays FIRST — counterexamples are regressions


@pytest.mark.faults  # chaos-mode entries drive a live server + proxy
@pytest.mark.hard_timeout(600)
def test_corpus_exists_and_replays_clean(tmp_path):
    entries = fuzz.load_corpus()
    assert entries, (
        "the pinned counterexample corpus (tests/fuzz_corpus/) is empty — "
        "it must ship with the fuzzer"
    )
    failures = []
    for fn, entry in entries:
        case = fuzz.corpus_case(entry)
        mode = entry["mode"]
        viols = _check_mode(case, mode, tmp_path)
        if viols:
            failures.append(
                (entry["seed"], f"corpus:{fn}", viols[0], "already pinned")
            )
    if failures:
        _fail_with_repro(failures)


def test_corpus_entries_are_replayable_and_named():
    """Every corpus file names its seed, mode, and repro command, and its
    problem dict decodes through the service codec (the replay path)."""
    for fn, entry in fuzz.load_corpus():
        assert {"seed", "mode", "violation", "repro", "problem"} <= set(entry), fn
        assert str(entry["seed"]) in fn
        case = fuzz.corpus_case(entry)
        pools, ibp, pods, _views, _daemons, _opts, _src = case.materialize()
        assert pools and ibp
        assert str(entry["seed"]) in entry["repro"]


# ---------------------------------------------------------------------------
# 2. the fixed-seed smoke tier (tier-1: ~64 cases, parity + invariants)


@pytest.mark.hard_timeout(780)
def test_seeded_smoke_parity_and_invariants():
    """The tier-1 gate: SMOKE_CASES seeded cases through parity (both
    kernel paths, sampled regrow differential, relax on/off) and the
    invariant catalog — zero violations. FUZZ_SEED replays a CI batch."""
    _run_batch(range(BASE_SEED, BASE_SEED + SMOKE_CASES))


# ---------------------------------------------------------------------------
# 3. chaos smoke: the same seeded cases through a live sidecar


def _small_case() -> fuzz.FuzzCase:
    """The first case at/after the base seed with a small pod count —
    chaos replays several solves per scenario, so the smoke tier keeps
    the per-solve cost bounded. Deterministic: same base, same case."""
    seed = BASE_SEED
    while True:
        case = fuzz.generate_case(seed)
        if len(case.materialize()[2]) <= 12:
            return case
        seed += 1


@pytest.mark.faults
@pytest.mark.hard_timeout(240)
@pytest.mark.parametrize("scenario", ["wire", "desync", "kill", "retry"])
def test_chaos_smoke_scenarios(scenario, tmp_path):
    """A seeded fuzz case driven through a live SolverServer under fault
    injection (shared FaultyProxy / epoch desync / server kill /
    admission RETRY) answers decision-identically to the in-process
    oracle referee, every time."""
    case = _small_case()
    viols = fuzz.chaos_violations(case, scenario, str(tmp_path))
    if viols:
        _fail_with_repro(
            [(case.seed, f"chaos:{scenario}", v, "not pinned (rerun shrinks)")
             for v in viols]
        )


@pytest.mark.faults
@pytest.mark.hard_timeout(600)
def test_chaos_fleet_window_with_sibling_lanes(tmp_path):
    """Fleet-window chaos: seeded sibling lanes (distinct request
    profiles of the shared scan-path fixture) coalesce through one
    window on a live fleet server behind the fault proxy — a one-shot
    delayed response lands mid-window — and every lane's claims equal
    its solo in-process solve."""
    from karpenter_tpu.cloudprovider.kwok import construct_instance_types
    from karpenter_tpu.solver import epochs, fleet
    from karpenter_tpu.solver.service import SolverClient, SolverServer
    from karpenter_tpu.solver.topology import Topology
    from karpenter_tpu.solver.tpu import TpuScheduler
    from karpenter_tpu.testing import fixtures
    from karpenter_tpu.testing.faults import FaultyProxy

    lanes = 3
    base = 1 + BASE_SEED % 3
    profiles = [f"{100 * (base + k)}m" for k in range(lanes)]

    def _problem(cpu):
        fixtures.reset_rng(5)
        its = construct_instance_types(sizes=[2, 8])
        pools = [fixtures.node_pool(name="default")]
        pods = fixtures.make_self_spread_pods(6, cpu)
        return pools, {"default": its}, pods

    def _solo(cpu):
        pools, ibp, pods = _problem(cpu)
        topo = Topology(pools, ibp, pods)
        sched = TpuScheduler(pools, ibp, topo)
        r = sched.solve(pods)
        assert not sched.last_used_runs
        return sorted(
            tuple(sorted(p.name for p in c.pods))
            for c in r.new_node_claims
            if c.pods
        )

    refs = {cpu: _solo(cpu) for cpu in profiles}
    sock = str(tmp_path / "fz-fleet.sock")
    srv = SolverServer(
        sock,
        fleet_window_seconds=10.0,
        fleet_max_lanes=lanes,
        admission=epochs.AdmissionGate(max_inflight=32),
    )
    srv.start()
    proxy = FaultyProxy(str(tmp_path / "fz-fleet.proxy.sock"), sock)
    proxy.set_fault("delay", once=True, delay=0.2)
    c0 = fleet.FLEET_SOLVES.value({"mode": "coalesced"})
    out: dict[str, list] = {}
    errors: dict[str, BaseException] = {}
    barrier = threading.Barrier(lanes)

    def client(cpu: str) -> None:
        try:
            c = SolverClient(proxy.listen_path, request_timeout=600.0)
            pools, ibp, pods = _problem(cpu)
            barrier.wait()
            got = c.solve(pools, ibp, pods)
            name = {p.uid: p.name for p in pods}
            out[cpu] = sorted(
                tuple(sorted(name[u] for u in cl["pod_uids"]))
                for cl in got["new_node_claims"]
                if cl["pod_uids"]
            )
            c.close()
        except BaseException as e:  # asserted below
            errors[cpu] = e

    try:
        threads = [
            threading.Thread(target=client, args=(cpu,), daemon=True)
            for cpu in profiles
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
    finally:
        proxy.stop()
        srv.stop()
    assert not errors, errors
    for cpu in profiles:
        assert out[cpu] == refs[cpu], cpu
    assert fleet.FLEET_SOLVES.value({"mode": "coalesced"}) - c0 == lanes


# ---------------------------------------------------------------------------
# 4. the deep tier (`-m "fuzz and slow"`): breadth + chaos rotation


@pytest.mark.slow
@pytest.mark.hard_timeout(3600)
@pytest.mark.parametrize("batch", range(10))
def test_seeded_deep_batch(batch):
    """1000 cases beyond the smoke window, 100 per batch — the
    adversarial sweep every kernel/serving PR reruns."""
    start = BASE_SEED + 1000 + batch * 100
    _run_batch(range(start, start + 100), tight_every=8)


@pytest.mark.slow
@pytest.mark.faults
@pytest.mark.hard_timeout(1800)
@pytest.mark.parametrize("scenario", ["wire", "desync", "kill", "retry"])
def test_chaos_deep_rotation(scenario, tmp_path):
    """Chaos breadth: a rotation of seeded cases (not just the small
    one) through every fault scenario."""
    failures = []
    for seed in range(BASE_SEED + 500, BASE_SEED + 512):
        case = fuzz.generate_case(seed)
        for v in fuzz.chaos_violations(case, scenario, str(tmp_path)):
            failures.append((seed, f"chaos:{scenario}", v, "not pinned"))
    if failures:
        _fail_with_repro(failures)
