"""Disruption subsystem: candidates, budgets, simulation, consolidation
decisions (delete vs replace-with-cheaper), multi-node prefix search
(batched sweep == binary search), emptiness, drift, validation, and the
end-to-end consolidate loop through the operator.

Reference behaviors: /root/reference/pkg/controllers/disruption/
{consolidation,multinodeconsolidation,emptiness,drift,helpers}.go
"""

from __future__ import annotations

import math

import pytest

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import (
    COND_CONSOLIDATABLE,
    COND_DRIFTED,
    PodPhase,
)
from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.controllers.disruption import (
    DECISION_DELETE,
    DECISION_REPLACE,
    MultiNodeConsolidation,
    build_budget_mapping,
    build_candidates,
    simulate_scheduling,
)
from karpenter_tpu.controllers.kube import FakeClock
from karpenter_tpu.controllers.operator import Operator
from karpenter_tpu.testing import fixtures


def settled_operator(n_pods=6, pod_kw=None, nodepool_kw=None, force_oracle=True):
    """An operator with a provisioned, initialized cluster and RUNNING pods.
    force_oracle=False runs every control-plane solve through the kernel
    (tpu_min_pods=0 so tiny scenario batches don't size-route back to the
    oracle) — the dual-path parametrization below keeps kernel<->controller
    integration continuously exercised (VERDICT r3 weak #5)."""
    from karpenter_tpu.options import Options

    op = Operator(
        clock=FakeClock(),
        force_oracle=force_oracle,
        options=None if force_oracle else Options(tpu_min_pods=0),
    )
    op.raw_cloud.types = construct_instance_types(sizes=[2, 8, 32])
    op.raw_cloud._by_name = {it.name: it for it in op.raw_cloud.types}
    fixtures.reset_rng(21)
    op.kube.create(
        "NodePool", fixtures.node_pool(name="default", **(nodepool_kw or {}))
    )
    for i in range(n_pods):
        kw = dict(requests={"cpu": "500m", "memory": "512Mi"})
        kw.update(pod_kw or {})
        op.kube.create("Pod", fixtures.pod(name=f"w-{i}", **kw))
    assert op.run_until_settled(max_ticks=40) < 40
    for p in op.kube.list("Pod"):
        p.phase = PodPhase.RUNNING
        op.kube.update("Pod", p)
    return op


def mark_consolidatable(op):
    """Advance past the nomination window and consolidateAfter, then stamp
    conditions."""
    op.clock.advance(1.0)
    op.pod_events.reconcile_all()
    op.clock.advance(25.0)  # nomination window is 20s (statenode.go:431)
    op.claim_conditions.reconcile_all()


def test_candidates_and_gates():
    op = settled_operator()
    mark_consolidatable(op)
    cands = build_candidates(
        op.kube, op.cluster, op.cloud, op.clock, lambda c: True
    )
    assert cands, "initialized nodes should be candidates"
    c = cands[0]
    assert c.instance_type_name
    assert c.price < 1e9
    assert c.reschedulable_pods

    # do-not-disrupt pod blocks its node
    pod = c.reschedulable_pods[0]
    stored = op.kube.get("Pod", pod.name)
    stored.metadata.annotations[well_known.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
    op.kube.update("Pod", stored)
    cands2 = build_candidates(
        op.kube, op.cluster, op.cloud, op.clock, lambda c: True
    )
    assert c.name not in [x.name for x in cands2]


def test_budget_mapping():
    op = settled_operator()
    n_nodes = len(op.kube.list("Node"))
    budgets = build_budget_mapping(op.kube, op.cluster, "underutilized")
    # default budget is 10% rounded UP (nodepool.go:359 roundUp=true):
    # even a 1-node pool allows one disruption
    assert budgets.allowed["default"] == math.ceil(n_nodes * 0.10)

    np = op.kube.list("NodePool")[0]
    np.disruption.budgets[0].nodes = "100%"
    op.kube.update("NodePool", np)
    budgets = build_budget_mapping(op.kube, op.cluster, "underutilized")
    assert budgets.allowed["default"] == n_nodes


def test_simulate_scheduling_excludes_candidates():
    op = settled_operator()
    mark_consolidatable(op)
    cands = build_candidates(op.kube, op.cluster, op.cloud, op.clock, lambda c: True)
    sim = simulate_scheduling(op.kube, op.cluster, op.cloud, cands, op.opts,
                              force_oracle=True)
    # removing every node means every reschedulable pod must be re-solved
    total_resched = sum(len(c.reschedulable_pods) for c in cands)
    assert len(sim.pods) == total_resched
    assert sim.all_pods_scheduled()
    # all candidate nodes excluded -> replacements must be new claims
    assert sim.non_empty_new_claims()


@pytest.mark.parametrize("force_oracle", [True, False], ids=["oracle", "tpu"])
def test_emptiness_deletes_empty_nodes(force_oracle):
    op = settled_operator(force_oracle=force_oracle, n_pods=2)
    # delete the workload -> nodes become empty
    for p in op.kube.list("Pod"):
        op.kube.delete("Pod", p.name)
    mark_consolidatable(op)
    n_nodes = len(op.kube.list("Node"))
    assert n_nodes >= 1
    np = op.kube.list("NodePool")[0]
    np.disruption.budgets[0].nodes = "100%"
    op.kube.update("NodePool", np)

    # run the controller through poll + validation TTL
    for _ in range(30):
        op.step(2.0)
        if not op.kube.list("Node") and not op.kube.list("NodeClaim"):
            break
    assert not op.kube.list("NodeClaim"), "empty claims should be consolidated away"
    assert not op.kube.list("Node")


@pytest.mark.parametrize("force_oracle", [True, False], ids=["oracle", "tpu"])
def test_drift_replaces_drifted_node(force_oracle):
    op = settled_operator(force_oracle=force_oracle, n_pods=3)
    claims = op.kube.list("NodeClaim")
    assert claims
    # change the nodepool template -> hash drift
    np = op.kube.list("NodePool")[0]
    np.template.labels["fleet"] = "v2"
    np.disruption.budgets[0].nodes = "100%"
    op.kube.update("NodePool", np)
    op.nodepool_hash.reconcile_all()
    mark_consolidatable(op)
    op.claim_conditions.reconcile_all()
    drifted = [
        c
        for c in op.kube.list("NodeClaim")
        if c.status.conditions.get(COND_DRIFTED) == "True"
    ]
    assert drifted, "hash change must mark claims drifted"

    old_names = {c.name for c in claims}
    for _ in range(40):
        op.step(2.0)
        current = {c.name for c in op.kube.list("NodeClaim")}
        if current and not (current & old_names):
            break
    current = {c.name for c in op.kube.list("NodeClaim")}
    assert current and not (current & old_names), "drifted claims replaced"
    # new claims carry the new hash -> not drifted
    for c in op.kube.list("NodeClaim"):
        assert c.status.conditions.get(COND_DRIFTED) != "True"
    # workload survived
    assert all(p.node_name for p in op.kube.list("Pod"))


def test_multi_node_consolidation_batched_equals_binary():
    """The TPU-era prefix sweep and the reference's binary search must pick
    the same (largest feasible) prefix."""
    # many small pods spread over many small nodes; they all fit on one
    # bigger replacement -> multi-node consolidation finds a big prefix
    op = settled_operator(
        n_pods=8, pod_kw=dict(requests={"cpu": "300m", "memory": "256Mi"})
    )
    mark_consolidatable(op)
    np = op.kube.list("NodePool")[0]
    np.disruption.budgets[0].nodes = "100%"
    op.kube.update("NodePool", np)

    args = (op.kube, op.cluster, op.cloud, op.clock)
    kwargs = dict(options=op.opts, force_oracle=True)
    batched = MultiNodeConsolidation(*args, sweep="batched", **kwargs)
    binary = MultiNodeConsolidation(*args, sweep="binary", **kwargs)
    cmd_a = batched.compute_commands()
    cmd_b = binary.compute_commands()
    names_a = sorted(c.name for cmd in cmd_a for c in cmd.candidates)
    names_b = sorted(c.name for cmd in cmd_b for c in cmd.candidates)
    assert names_a == names_b
    if cmd_a:
        assert cmd_a[0].decision == cmd_b[0].decision


@pytest.mark.parametrize("force_oracle", [True, False], ids=["oracle", "tpu"])
def test_consolidation_e2e_shrinks_cluster(force_oracle):
    """Full loop: over-provisioned cluster consolidates down and every pod
    survives on the remaining capacity."""
    op = settled_operator(force_oracle=force_oracle, 
        n_pods=6, pod_kw=dict(requests={"cpu": "200m", "memory": "200Mi"})
    )
    np = op.kube.list("NodePool")[0]
    np.disruption.budgets[0].nodes = "100%"
    op.kube.update("NodePool", np)
    n_before = len(op.kube.list("Node"))
    cost_before = sum(
        c.price
        for c in build_candidates(op.kube, op.cluster, op.cloud, op.clock, lambda c: True)
    )
    mark_consolidatable(op)
    for _ in range(60):
        op.step(2.0)
    n_after = len(op.kube.list("Node"))
    assert n_after <= n_before
    # every pod still bound somewhere real
    node_names = {n.name for n in op.kube.list("Node")}
    for p in op.kube.list("Pod"):
        assert p.node_name in node_names


@pytest.mark.parametrize("force_oracle", [True, False], ids=["oracle", "tpu"])
def test_validation_vetoes_on_pod_churn(force_oracle):
    op = settled_operator(force_oracle=force_oracle, n_pods=2)
    for p in op.kube.list("Pod"):
        op.kube.delete("Pod", p.name)
    mark_consolidatable(op)
    np = op.kube.list("NodePool")[0]
    np.disruption.budgets[0].nodes = "100%"
    op.kube.update("NodePool", np)
    # let the controller pick an emptiness command (pending validation)
    op.disruption.reconcile()
    assert op.disruption._pending_validation is not None
    # new pod lands on the node during the TTL -> validation must veto
    p = fixtures.pod(name="intruder", requests={"cpu": "100m"})
    op.kube.create("Pod", p)
    node = op.kube.list("Node")[0]
    op.kube.bind("intruder", node.name)
    op.clock.advance(16.0)
    op.disruption.reconcile()
    assert op.kube.list("Node"), "validation should veto deleting a now-used node"


def test_consolidatable_condition_lifecycle():
    op = settled_operator(
        n_pods=1, nodepool_kw=dict(consolidate_after_seconds=30.0)
    )
    claim = op.kube.list("NodeClaim")[0]
    op.claim_conditions.reconcile_all()
    claim = op.kube.get("NodeClaim", claim.name)
    assert claim.status.conditions.get(COND_CONSOLIDATABLE) == "False"
    op.clock.advance(31.0)
    op.claim_conditions.reconcile_all()
    claim = op.kube.get("NodeClaim", claim.name)
    assert claim.status.conditions.get(COND_CONSOLIDATABLE) == "True"


def test_expiration_controller():
    op = settled_operator(n_pods=1)
    claim = op.kube.list("NodeClaim")[0]
    claim.expire_after_seconds = 60.0
    claim.metadata.creation_timestamp = op.clock.now()
    op.kube.update("NodeClaim", claim)
    assert op.expiration.reconcile_all() == 0
    op.clock.advance(61.0)
    assert op.expiration.reconcile_all() == 1
    # the deleted claim drains through termination; replacement comes up
    op.run_until_settled(max_ticks=40)
    assert all(p.node_name for p in op.kube.list("Pod"))


def test_garbage_collection_both_directions():
    op = settled_operator(n_pods=1)
    claim = op.kube.list("NodeClaim")[0]
    # direction 2: instance vanishes -> claim deleted
    op.cloud.instances.pop(claim.status.provider_id)
    orphans, lost = op.garbage_collection.reconcile()
    assert (orphans, lost) == (0, 1)
    # deletion initiated; the termination finalizer completes it
    stored = op.kube.try_get("NodeClaim", claim.name)
    assert stored is None or stored.metadata.deletion_timestamp is not None
    for _ in range(10):
        op.step(2.0)
        if op.kube.try_get("NodeClaim", claim.name) is None:
            break
    assert op.kube.try_get("NodeClaim", claim.name) is None

    # direction 1: orphan instance with no claim -> terminated
    from karpenter_tpu.api.objects import NodeClaim, NodeClaimStatus

    ghost = NodeClaim()
    ghost.metadata.name = "ghost"
    ghost.status = NodeClaimStatus(provider_id="kwok://ghost")
    op.cloud.instances["kwok://ghost"] = ghost
    orphans, lost = op.garbage_collection.reconcile()
    assert orphans == 1
    assert "kwok://ghost" not in op.cloud.instances


def test_static_drift_replaces_drifted_static_node():
    """staticdrift.go:35-117: drifted static-pool nodes are replaced by the
    StaticDrift method (regular Drift/consolidation must skip them)."""
    from karpenter_tpu.controllers.operator import Operator
    from karpenter_tpu.controllers.kube import FakeClock
    from karpenter_tpu.options import FeatureGates, Options

    op = Operator(
        clock=FakeClock(),
        force_oracle=True,
        options=Options(feature_gates=FeatureGates(static_capacity=True)),
    )
    op.kube.create("NodePool", fixtures.node_pool(name="warm", replicas=2))
    op.run_until_settled(max_ticks=40)
    claims = op.kube.list("NodeClaim")
    assert len(claims) == 2
    old_names = {c.name for c in claims}
    assert op.cluster.nodepool_state.node_counts("warm") == (2, 0, 0)

    # drift the pool: template change -> hash drift on existing claims
    np = op.kube.list("NodePool")[0]
    np.template.labels["fleet"] = "v2"
    np.disruption.budgets[0].nodes = "100%"
    op.kube.update("NodePool", np)
    op.nodepool_hash.reconcile_all()
    op.claim_conditions.reconcile_all()
    drifted = [
        c
        for c in op.kube.list("NodeClaim")
        if c.status.conditions.get(COND_DRIFTED) == "True"
    ]
    assert drifted, "hash change must mark static claims drifted"

    for _ in range(80):
        op.step(2.0)
        current = {c.name for c in op.kube.list("NodeClaim")}
        if current and not (current & old_names):
            break
    current = {c.name for c in op.kube.list("NodeClaim")}
    assert current and not (current & old_names), "drifted static claims replaced"
    # replica count is preserved throughout and afterwards
    assert len(op.kube.list("Node")) == 2
    assert op.cluster.nodepool_state.node_counts("warm")[0] == 2


def test_static_drift_respects_node_limit_reservations():
    """statenodepool.go ReserveNodeCount: with a `nodes` limit equal to the
    replica count, StaticDrift cannot reserve a replacement slot, so the
    drifted node stays (no burst over the limit)."""
    from karpenter_tpu.controllers.operator import Operator
    from karpenter_tpu.controllers.kube import FakeClock
    from karpenter_tpu.options import FeatureGates, Options

    op = Operator(
        clock=FakeClock(),
        force_oracle=True,
        options=Options(feature_gates=FeatureGates(static_capacity=True)),
    )
    op.kube.create(
        "NodePool",
        fixtures.node_pool(name="warm", replicas=2, limits={"nodes": "2"}),
    )
    op.run_until_settled(max_ticks=40)
    old_names = {c.name for c in op.kube.list("NodeClaim")}
    assert len(old_names) == 2

    np = op.kube.list("NodePool")[0]
    np.template.labels["fleet"] = "v2"
    np.disruption.budgets[0].nodes = "100%"
    op.kube.update("NodePool", np)
    op.nodepool_hash.reconcile_all()
    op.claim_conditions.reconcile_all()

    for _ in range(40):
        op.step(2.0)
    # limit 2 == replicas 2: reservation is denied, nothing is replaced
    assert {c.name for c in op.kube.list("NodeClaim")} == old_names


def test_static_drift_reservations_do_not_leak():
    """Discarded/serialized StaticDrift commands must hand their node-count
    reservations back; otherwise a later scale-up stalls below the limit."""
    op = Operator(
        clock=FakeClock(),
        force_oracle=True,
        options=__import__("karpenter_tpu.options", fromlist=["Options"]).Options(
            feature_gates=__import__(
                "karpenter_tpu.options", fromlist=["FeatureGates"]
            ).FeatureGates(static_capacity=True)
        ),
    )
    op.kube.create(
        "NodePool",
        fixtures.node_pool(name="warm", replicas=3, limits={"nodes": "6"}),
    )
    op.run_until_settled(max_ticks=40)
    assert len(op.kube.list("NodeClaim")) == 3

    # drift all three claims
    np = op.kube.list("NodePool")[0]
    np.template.labels["fleet"] = "v2"
    np.disruption.budgets[0].nodes = "100%"
    op.kube.update("NodePool", np)
    op.nodepool_hash.reconcile_all()
    op.claim_conditions.reconcile_all()

    # let the rollout finish (commands serialize one at a time)
    for _ in range(200):
        op.step(2.0)
        claims = op.kube.list("NodeClaim")
        if len(claims) == 3 and all(
            c.status.conditions.get(COND_DRIFTED) != "True" for c in claims
        ) and not op.disruption.queue.busy:
            break
    assert op.cluster.nodepool_state._reserved.get("warm", 0) == 0

    # scale up to the limit: must reach 6, not stall below it
    np = op.kube.list("NodePool")[0]
    np.replicas = 6
    op.kube.update("NodePool", np)
    op.run_until_settled(max_ticks=60)
    assert len(op.kube.list("NodeClaim")) == 6


def test_static_drift_replaces_node_with_pods():
    """A drifted static node carrying pods must still be replaced: StaticDrift
    is eventual-class, so the consolidation re-simulation (which excludes
    static pools) must not veto it."""
    from karpenter_tpu.options import FeatureGates, Options

    op = Operator(
        clock=FakeClock(),
        force_oracle=True,
        options=Options(feature_gates=FeatureGates(static_capacity=True)),
    )
    op.kube.create("NodePool", fixtures.node_pool(name="warm", replicas=1))
    op.run_until_settled(max_ticks=40)
    # bind a pod onto the static node
    node = op.kube.list("Node")[0]
    p = fixtures.pod(name="rider", requests={"cpu": "100m"})
    p.node_name = node.name
    p.phase = PodPhase.RUNNING
    op.kube.create("Pod", p)

    old_names = {c.name for c in op.kube.list("NodeClaim")}
    np = op.kube.list("NodePool")[0]
    np.template.labels["fleet"] = "v2"
    np.disruption.budgets[0].nodes = "100%"
    op.kube.update("NodePool", np)
    op.nodepool_hash.reconcile_all()
    op.claim_conditions.reconcile_all()

    for _ in range(120):
        op.step(2.0)
        current = {c.name for c in op.kube.list("NodeClaim")}
        if current and not (current & old_names):
            break
    current = {c.name for c in op.kube.list("NodeClaim")}
    assert current and not (current & old_names), (
        "drifted static node with pods must be replaced (eventual class, "
        "no simulation veto)"
    )
    assert op.cluster.nodepool_state._reserved.get("warm", 0) == 0


def test_batched_sweep_equals_binary_on_fleet():
    """The one-invocation prefix sweep (disruption/sweep.py) must choose the
    same command as the reference-shaped sequential binary search on a real
    under-utilized fleet."""
    from karpenter_tpu.api.objects import Budget

    op = Operator(clock=FakeClock(), force_oracle=True)
    op.raw_cloud.types = construct_instance_types(sizes=[2, 32])
    op.raw_cloud._by_name = {it.name: it for it in op.raw_cloud.types}
    fixtures.reset_rng(21)
    op.kube.create(
        "NodePool",
        fixtures.node_pool(name="default", budgets=[Budget(nodes="100%")]),
    )
    fixtures.make_underutilized_fleet(op, 8)
    op.clock.advance(26.0)
    op.pod_events.reconcile_all()
    op.claim_conditions.reconcile_all()

    args = (op.kube, op.cluster, op.cloud, op.clock)
    sweep = MultiNodeConsolidation(*args, sweep="batched", options=op.opts,
                                   force_oracle=False)
    binary = MultiNodeConsolidation(*args, sweep="binary", options=op.opts,
                                    force_oracle=True)
    ca = sweep.compute_commands()
    cb = binary.compute_commands()
    na = sorted(c.name for cmd in ca for c in cmd.candidates)
    nb = sorted(c.name for cmd in cb for c in cmd.candidates)
    assert na == nb and len(na) >= 5, (na, nb)
    assert ca[0].decision == cb[0].decision


def test_prefix_feasibility_one_invocation():
    """prefix_feasibility evaluates every removal prefix in one vmapped
    device call and its verdicts match per-prefix sequential simulation."""
    from karpenter_tpu.api.objects import Budget
    from karpenter_tpu.controllers.disruption.sweep import prefix_feasibility

    op = Operator(clock=FakeClock(), force_oracle=True)
    op.raw_cloud.types = construct_instance_types(sizes=[2, 32])
    op.raw_cloud._by_name = {it.name: it for it in op.raw_cloud.types}
    fixtures.reset_rng(21)
    op.kube.create(
        "NodePool",
        fixtures.node_pool(name="default", budgets=[Budget(nodes="100%")]),
    )
    fixtures.make_underutilized_fleet(op, 6)
    op.clock.advance(26.0)
    op.pod_events.reconcile_all()
    op.claim_conditions.reconcile_all()

    args = (op.kube, op.cluster, op.cloud, op.clock)
    mnc = MultiNodeConsolidation(*args, options=op.opts, force_oracle=True)
    cands = mnc.candidates()
    assert len(cands) >= 4
    feas = prefix_feasibility(op.kube, op.cluster, op.cloud, cands, op.opts)
    assert len(feas) == len(cands)
    # sequential referee: full simulation per prefix
    for k in range(1, len(cands) + 1):
        sim = simulate_scheduling(
            op.kube, op.cluster, op.cloud, cands[:k], op.opts, force_oracle=True
        )
        seq_ok = sim.all_pods_scheduled() and len(sim.non_empty_new_claims()) <= 1
        assert feas[k - 1] == seq_ok, f"prefix {k}: sweep={feas[k-1]} seq={seq_ok}"


@pytest.mark.parametrize("force_oracle", [True, False], ids=["oracle", "tpu"])
def test_spot_to_spot_consolidation_floor(force_oracle):
    """consolidation.go:237: replacing a single spot node with spot requires
    >= 15 cheaper instance types; below the floor the command is a no-op,
    and the gate being off blocks spot-to-spot entirely."""
    from karpenter_tpu.options import FeatureGates, Options

    def build(gate_on, sizes):
        op = Operator(
            clock=FakeClock(),
            force_oracle=force_oracle,
            options=Options(
                feature_gates=FeatureGates(spot_to_spot_consolidation=gate_on),
                tpu_min_pods=0,  # tiny scenario batches must ride the kernel
            ),
        )
        op.raw_cloud.types = construct_instance_types(sizes=sizes)
        op.raw_cloud._by_name = {it.name: it for it in op.raw_cloud.types}
        fixtures.reset_rng(21)
        from karpenter_tpu.api.objects import Budget, NodeSelectorRequirement, Operator as OpEnum

        op.kube.create(
            "NodePool",
            fixtures.node_pool(
                name="default",
                budgets=[Budget(nodes="100%")],
                requirements=[
                    NodeSelectorRequirement(
                        well_known.CAPACITY_TYPE_LABEL_KEY,
                        OpEnum.IN,
                        ["spot"],
                    )
                ],
            ),
        )
        # provision a BIG spot node with a big seed pod, then swap the seed
        # for a tiny bound rider -> over-sized node, cheaper spot types exist
        p = fixtures.pod(name="seed", requests={"cpu": "7", "memory": "6Gi"})
        op.kube.create("Pod", p)
        op.run_until_settled(max_ticks=40)
        node_name = op.kube.get("Pod", "seed").node_name
        op.kube.delete("Pod", "seed")
        rider = fixtures.pod(name="rider", requests={"cpu": "100m"})
        rider.node_name = node_name
        rider.phase = PodPhase.RUNNING
        op.kube.create("Pod", rider)
        mark_consolidatable(op)
        from karpenter_tpu.controllers.disruption.consolidation import (
            SingleNodeConsolidation,
        )

        return op, SingleNodeConsolidation(
            op.kube, op.cluster, op.cloud, op.clock,
            options=op.opts, force_oracle=force_oracle,
        )

    # gate off: spot->spot never happens
    many_sizes = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 14, 16, 24, 32, 48]
    op, snc = build(False, many_sizes)
    cmds = snc.compute_commands()
    assert not any(c.replacements for c in cmds), "gate off must block spot->spot"

    # gate on with a rich universe (>= 15 cheaper types): replacement allowed
    op, snc = build(True, many_sizes)
    cmds = snc.compute_commands()
    assert any(
        cmd.replacements for cmd in cmds
    ), "gate on with >=15 cheaper types must replace"
    # the replacement's options are capped at the 15 cheapest types
    repl = next(cmd for cmd in cmds if cmd.replacements).replacements[0]
    assert len(repl.instance_type_options) <= 15

    # gate on but a poor universe (< 15 cheaper types): no-op
    op, snc = build(True, [8, 16])
    cmds = snc.compute_commands()
    assert not any(c.replacements for c in cmds), "below the 15-type floor"


def test_when_empty_policy_blocks_underutilized_consolidation():
    """consolidationPolicy=WhenEmpty (nodepool.go): non-empty nodes are not
    consolidation candidates even when underutilized; empty nodes still
    are."""
    from karpenter_tpu.api.objects import Budget

    op = Operator(clock=FakeClock(), force_oracle=True)
    op.raw_cloud.types = construct_instance_types(sizes=[2, 32])
    op.raw_cloud._by_name = {it.name: it for it in op.raw_cloud.types}
    fixtures.reset_rng(21)
    op.kube.create(
        "NodePool",
        fixtures.node_pool(name="default", budgets=[Budget(nodes="100%")]),
    )
    fixtures.make_underutilized_fleet(op, 4)
    np_ = op.kube.list("NodePool")[0]
    np_.disruption.consolidation_policy = "WhenEmpty"
    op.kube.update("NodePool", np_)
    op.clock.advance(26.0)
    op.pod_events.reconcile_all()
    op.claim_conditions.reconcile_all()
    before = {n.name for n in op.kube.list("Node")}
    assert len(before) >= 4
    for _ in range(30):
        op.step(2.0)
    assert {n.name for n in op.kube.list("Node")} == before, (
        "WhenEmpty must not consolidate nodes that still hold pods"
    )
    # the same under-utilized fleet with the default policy DOES shrink
    np_ = op.kube.list("NodePool")[0]
    np_.disruption.consolidation_policy = "WhenEmptyOrUnderutilized"
    op.kube.update("NodePool", np_)
    for _ in range(60):
        op.step(2.0)
        if len(op.kube.list("Node")) < len(before):
            break
    assert len(op.kube.list("Node")) < len(before)


def test_budget_reasons_filter():
    """nodepool.go Budget.Reasons: a zero budget scoped to 'drifted' blocks
    drift replacement but leaves emptiness free to act."""
    from karpenter_tpu.api.objects import Budget

    op = settled_operator(n_pods=3)
    np_ = op.kube.list("NodePool")[0]
    np_.disruption.budgets = [
        Budget(nodes="0", reasons=["drifted"]),
        Budget(nodes="100%", reasons=["empty", "underutilized"]),
    ]
    np_.template.labels["fleet"] = "v2"  # drift everything
    op.kube.update("NodePool", np_)
    op.nodepool_hash.reconcile_all()
    mark_consolidatable(op)
    op.claim_conditions.reconcile_all()
    old_names = {c.name for c in op.kube.list("NodeClaim")}
    for _ in range(40):
        op.step(2.0)
    # drift is budget-blocked: the drifted claims survive
    assert old_names <= {c.name for c in op.kube.list("NodeClaim")}, (
        "a zero drifted-budget must block drift replacement"
    )

    # but emptiness still works under its own budget: empty the nodes
    for p in op.kube.list("Pod"):
        op.kube.delete("Pod", p.name)
    mark_consolidatable(op)
    for _ in range(40):
        op.step(2.0)
        if not op.kube.list("NodeClaim"):
            break
    assert not op.kube.list("NodeClaim"), "emptiness budget was 100%"


@pytest.mark.parametrize("force_oracle", [True, False], ids=["oracle", "tpu"])
def test_orchestration_rollback_on_replacement_failure(force_oracle):
    """queue.go:181 waitOrTerminate: when a replacement NodeClaim dies
    before initializing (liveness), the command rolls back — the original
    nodes are un-tainted, un-marked, and keep running."""
    from karpenter_tpu.api.objects import Budget
    from karpenter_tpu.controllers.state import DISRUPTED_TAINT
    from karpenter_tpu.options import FeatureGates, Options

    op = Operator(
        clock=FakeClock(),
        force_oracle=force_oracle,
        # KWOK seeds land on spot; replacing all five needs the gate
        options=Options(
            feature_gates=FeatureGates(spot_to_spot_consolidation=True),
            tpu_min_pods=0,  # tiny scenario batches must ride the kernel
        ),
    )
    op.raw_cloud.types = construct_instance_types(sizes=[2, 8])
    op.raw_cloud._by_name = {it.name: it for it in op.raw_cloud.types}
    fixtures.reset_rng(21)
    op.kube.create(
        "NodePool",
        fixtures.node_pool(name="default", budgets=[Budget(nodes="100%")]),
    )
    # five OVERSIZED (8-cpu) nodes with small riders: removing all five
    # needs one fresh 8-cpu node, strictly cheaper than five -> REPLACE
    fixtures.make_underutilized_fleet(
        op, 5,
        rider_requests={"cpu": "1200m"},
        seed_requests={"cpu": "7", "memory": "6Gi"},
    )
    op.clock.advance(26.0)
    op.pod_events.reconcile_all()
    op.claim_conditions.reconcile_all()
    originals = {c.name for c in op.kube.list("NodeClaim")}

    # drive until a replace command starts (replacements created)
    started = None
    for _ in range(40):
        op.disruption._last_run = -1e18  # poll immediately
        op.step(2.0)
        if op.disruption.queue.in_flight and op.disruption.queue.in_flight[0].replacement_names:
            started = op.disruption.queue.in_flight[0]
            break
    assert started is not None and started.replacement_names, (
        "scenario must produce a replace command"
    )
    candidate_names = {c.name for c in started.command.candidates}

    # kill the replacement before it initializes (liveness analog)
    for name in started.replacement_names:
        op.kube.delete("NodeClaim", name)
        # force-complete the delete (strip finalizers) like GC would
        claim = op.kube.try_get("NodeClaim", name)
        if claim is not None:
            claim.metadata.finalizers = []
            try:
                op.kube.update("NodeClaim", claim)
            except Exception:
                pass

    op.disruption.queue.reconcile()
    # rollback: originals survive, no disruption taints, unmarked
    still = {c.name for c in op.kube.list("NodeClaim")}
    assert candidate_names <= still, "rollback must keep the originals"
    for c in started.command.candidates:
        node = op.kube.try_get("Node", c.name)
        assert node is not None
        assert DISRUPTED_TAINT not in node.taints, "taint must roll back"
        sn = op.cluster.node_by_name(c.name)
        assert sn is not None and not sn.marked_for_deletion
    assert not op.disruption.queue.busy


# ---------------------------------------------------------------------------
# candidate-gate matrix (statenode.go:202-260 ValidateNodeDisruptable)


def test_do_not_disrupt_node_annotation_blocks_candidacy():
    op = settled_operator(n_pods=2)
    mark_consolidatable(op)
    node = op.kube.list("Node")[0]
    node.metadata.annotations[well_known.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
    op.kube.update("Node", node)
    cands = build_candidates(op.kube, op.cluster, op.cloud, op.clock, lambda c: True)
    assert node.name not in [c.name for c in cands]


def test_nominated_node_blocks_candidacy():
    """A node holding a fresh scheduling nomination (statenode.go:431
    20s window) is off-limits to disruption until the window lapses."""
    op = settled_operator(n_pods=2)
    mark_consolidatable(op)
    cands = build_candidates(op.kube, op.cluster, op.cloud, op.clock, lambda c: True)
    assert cands
    name = cands[0].name
    op.cluster.node_by_name(name).nominate(op.clock.now())
    cands2 = build_candidates(op.kube, op.cluster, op.cloud, op.clock, lambda c: True)
    assert name not in [c.name for c in cands2]
    op.clock.advance(25.0)  # window closes
    cands3 = build_candidates(op.kube, op.cluster, op.cloud, op.clock, lambda c: True)
    assert name in [c.name for c in cands3]


def test_pdb_fully_blocked_pod_blocks_candidacy():
    """maxUnavailable=0 makes every covered pod non-evictable; its node
    must never become a candidate (helpers.go:174 GetCandidates)."""
    from karpenter_tpu.api.objects import LabelSelector, ObjectMeta, PodDisruptionBudget

    op = settled_operator(n_pods=2, pod_kw=dict(labels={"app": "frozen"}))
    mark_consolidatable(op)
    op.kube.create(
        "PodDisruptionBudget",
        PodDisruptionBudget(
            metadata=ObjectMeta(name="freeze"),
            selector=LabelSelector(match_labels={"app": "frozen"}),
            max_unavailable="0",
        ),
    )
    cands = build_candidates(op.kube, op.cluster, op.cloud, op.clock, lambda c: True)
    pod_nodes = {p.node_name for p in op.kube.list("Pod")}
    assert not any(c.name in pod_nodes for c in cands)


def test_candidates_sorted_by_disruption_cost():
    """consolidation.go:127 sortCandidates: cheapest-to-move first; pod
    priority and do-not-disrupt preferences raise the cost."""
    op = settled_operator(n_pods=0)
    # two single-pod nodes: one carries a high-priority pod
    from karpenter_tpu.api.objects import PodAffinityTerm, LabelSelector

    anti = [
        PodAffinityTerm(
            topology_key=well_known.HOSTNAME_LABEL_KEY,
            label_selector=LabelSelector(match_labels={"spread": "x"}),
        )
    ]
    op.kube.create(
        "Pod",
        fixtures.pod(
            name="cheap", labels={"spread": "x"},
            requests={"cpu": "500m"}, pod_anti_requirements=[t for t in anti],
        ),
    )
    expensive = fixtures.pod(
        name="precious", labels={"spread": "x"},
        requests={"cpu": "500m"}, pod_anti_requirements=[t for t in anti],
    )
    expensive.priority = 1_000_000
    op.kube.create("Pod", expensive)
    assert op.run_until_settled(max_ticks=40) < 40
    for p in op.kube.list("Pod"):
        p.phase = PodPhase.RUNNING
        op.kube.update("Pod", p)
    mark_consolidatable(op)
    cands = build_candidates(op.kube, op.cluster, op.cloud, op.clock, lambda c: True)
    assert len(cands) == 2
    ordered = sorted(cands, key=lambda c: (c.disruption_cost, c.name))
    pod_of = {p.node_name: p.name for p in op.kube.list("Pod")}
    assert pod_of[ordered[0].name] == "cheap"
    assert pod_of[ordered[1].name] == "precious"


# ---------------------------------------------------------------------------
# method precedence (controller.go:98 NewMethods order)


@pytest.mark.parametrize("force_oracle", [True, False], ids=["oracle", "tpu"])
def test_emptiness_precedes_consolidation(force_oracle):
    """One controller round on a cluster with BOTH an empty node and an
    underutilized node must pick the emptiness command first
    (controller.go:98 NewMethods order)."""
    from karpenter_tpu.api.objects import LabelSelector, PodAffinityTerm

    anti = [
        PodAffinityTerm(
            topology_key=well_known.HOSTNAME_LABEL_KEY,
            label_selector=LabelSelector(match_labels={"spread": "e"}),
        )
    ]
    op = settled_operator(force_oracle=force_oracle, 
        n_pods=2,
        pod_kw=dict(
            labels={"spread": "e"}, pod_anti_requirements=[t for t in anti]
        ),
    )
    assert len(op.kube.list("Node")) == 2
    # empty one node by deleting its pod; the other stays underutilized
    pods = op.kube.list("Pod")
    op.kube.delete("Pod", pods[0].name)
    mark_consolidatable(op)
    np = op.kube.list("NodePool")[0]
    np.disruption.budgets[0].nodes = "100%"
    op.kube.update("NodePool", np)
    op.clock.advance(op.opts.disruption_poll_seconds + 1)
    op.disruption.reconcile()
    pending = op.disruption._pending_validation
    assert pending is not None
    _, cmd = pending
    assert cmd.reason == "empty", f"emptiness must win, got {cmd.reason}"


# ---------------------------------------------------------------------------
# drift budget gating (drift.go:38-116)


@pytest.mark.parametrize("force_oracle", [True, False], ids=["oracle", "tpu"])
def test_drift_respects_budget_per_round(force_oracle):
    """With a nodes=1 budget, one disruption round may only taint/replace
    one drifted node even when several are drifted (drift.go:38-116
    budget gating)."""
    # hostname anti-affinity forces one node per pod -> a real multi-node
    # cluster on the small universe
    from karpenter_tpu.api.objects import LabelSelector, PodAffinityTerm

    anti = [
        PodAffinityTerm(
            topology_key=well_known.HOSTNAME_LABEL_KEY,
            label_selector=LabelSelector(match_labels={"spread": "d"}),
        )
    ]
    op = settled_operator(force_oracle=force_oracle, 
        n_pods=3,
        pod_kw=dict(
            labels={"spread": "d"}, pod_anti_requirements=[t for t in anti]
        ),
    )
    claims = op.kube.list("NodeClaim")
    assert len(claims) >= 2
    from karpenter_tpu.api.objects import Budget

    # drift EVERY claim via a template-hash change (drift.go:50 hash drift)
    np = op.kube.list("NodePool")[0]
    np.template.labels["fleet"] = "v2"
    np.disruption.budgets = [Budget(nodes="1")]
    op.kube.update("NodePool", np)
    op.nodepool_hash.reconcile_all()
    mark_consolidatable(op)
    op.claim_conditions.reconcile_all()
    drifted = [
        c for c in op.kube.list("NodeClaim")
        if c.status.conditions.get(COND_DRIFTED) == "True"
    ]
    assert len(drifted) == len(claims)
    op.clock.advance(op.opts.disruption_poll_seconds + 1)
    op.disruption.reconcile()
    pending = op.disruption._pending_validation
    assert pending is not None
    _, cmd = pending
    assert len(cmd.candidates) == 1, "budget caps the round at 1 node"


# ---------------------------------------------------------------------------
# stale-taint cleanup (controller.go:143-157)


def test_stale_disruption_taint_cleaned():
    """A node carrying the disruption taint without being part of any
    in-flight or pending command gets un-tainted on the next round."""
    from karpenter_tpu.controllers.state import DISRUPTED_TAINT

    op = settled_operator(n_pods=2)
    mark_consolidatable(op)
    node = op.kube.list("Node")[0]
    node.taints = list(node.taints) + [DISRUPTED_TAINT]
    op.kube.update("Node", node)
    op.clock.advance(op.opts.disruption_poll_seconds + 1)
    op.disruption.reconcile()
    node = op.kube.get("Node", node.name)
    assert DISRUPTED_TAINT not in node.taints, "stale taint must be removed"


# ---------------------------------------------------------------------------
# replace waits for replacement readiness (queue.go:137-249)


@pytest.mark.parametrize("force_oracle", [True, False], ids=["oracle", "tpu"])
def test_originals_survive_until_replacement_initialized(force_oracle):
    """During a replace command, the original nodes must keep running
    until every replacement claim is registered+initialized; only then are
    originals deleted."""
    op = settled_operator(force_oracle=force_oracle, n_pods=3)
    claims = op.kube.list("NodeClaim")
    np = op.kube.list("NodePool")[0]
    np.template.labels["fleet"] = "v2"  # hash drift -> replace path
    np.disruption.budgets[0].nodes = "100%"
    op.kube.update("NodePool", np)
    op.nodepool_hash.reconcile_all()
    mark_consolidatable(op)
    op.claim_conditions.reconcile_all()
    old_names = {c.name for c in claims}

    op.clock.advance(op.opts.disruption_poll_seconds + 1)
    op.disruption.reconcile()  # proposes
    op.clock.advance(16.0)  # validation TTL
    op.disruption.reconcile()  # validates + starts the command
    assert op.disruption.queue.busy
    # the instant the command starts, originals still exist while the
    # replacement claim is launching
    live = {c.name for c in op.kube.list("NodeClaim")}
    assert old_names & live, "originals must not vanish before replacements"
    replacements_launching = live - old_names
    assert replacements_launching, "replacement claims must be created"

    # drive to completion: replacements initialize, originals drain away
    for _ in range(60):
        op.step(2.0)
        live = {c.name for c in op.kube.list("NodeClaim")}
        if live and not (live & old_names):
            break
    assert live and not (live & old_names)
    assert all(p.node_name for p in op.kube.list("Pod"))


# ---------------------------------------------------------------------------
# consolidation decision shape (consolidation.go:137-230)


@pytest.mark.parametrize("force_oracle", [True, False], ids=["oracle", "tpu"])
def test_consolidation_deletes_when_capacity_remains(force_oracle):
    """computeConsolidation: when the surviving nodes can absorb every
    rescheduled pod, the command is a pure DELETE (no replacements,
    consolidation.go:184). Built in two waves so the cluster genuinely
    holds two nodes with slack on the first."""
    op = settled_operator(force_oracle=force_oracle, 
        n_pods=3, pod_kw=dict(requests={"cpu": "600m", "memory": "200Mi"})
    )
    # wave 2: one more pod after the first node filled -> second node
    op.kube.create(
        "Pod",
        fixtures.pod(name="late", requests={"cpu": "600m", "memory": "200Mi"}),
    )
    assert op.run_until_settled(max_ticks=40) < 40
    for p in op.kube.list("Pod"):
        if p.phase != PodPhase.RUNNING:
            p.phase = PodPhase.RUNNING
            op.kube.update("Pod", p)
    if len(op.kube.list("Node")) < 2:
        pytest.skip("universe packed both waves onto one node")
    # free most of node 1 so the late pod can move there
    for name in ("w-0", "w-1"):
        op.kube.delete("Pod", name)
    mark_consolidatable(op)
    np = op.kube.list("NodePool")[0]
    np.disruption.budgets[0].nodes = "100%"
    op.kube.update("NodePool", np)
    from karpenter_tpu.controllers.disruption.consolidation import (
        SingleNodeConsolidation,
    )

    sc = SingleNodeConsolidation(
        op.kube, op.cluster, op.cloud, op.clock, options=op.opts, force_oracle=force_oracle
    )
    cmds = sc.compute_commands()
    assert cmds, "an underutilized multi-node cluster must yield a command"
    assert cmds[0].decision == DECISION_DELETE
    assert not cmds[0].replacements


def test_fast_sweep_partial_feasibility_agrees_with_fallbacks():
    """The delta-state sweep kernel (sweep.py _fast_sweep_kernel) must pick
    the same feasibility vector as the vmapped full-state scan AND the same
    largest prefix as the oracle binary search on a fleet where only a
    strict prefix is consolidation-feasible (big riders exhaust the
    keepers' free capacity plus one new claim)."""
    import karpenter_tpu.controllers.disruption.sweep as sweep_mod
    from karpenter_tpu.api.objects import Budget
    from karpenter_tpu.controllers.disruption.consolidation import (
        MultiNodeConsolidation,
    )
    from karpenter_tpu.controllers.kube import FakeClock
    from karpenter_tpu.controllers.operator import Operator

    op = Operator(clock=FakeClock(), force_oracle=False)
    op.kube.create(
        "NodePool",
        fixtures.node_pool(name="default", budgets=[Budget(nodes="100%")]),
    )
    fixtures.reset_rng(11)
    fixtures.make_underutilized_fleet(
        op,
        10,
        rider_requests={"cpu": "1200m", "memory": "256Mi"},
        seed_requests={"cpu": "1500m", "memory": "512Mi"},
    )
    op.clock.advance(30.0)
    op.pod_events.reconcile_all()
    op.claim_conditions.reconcile_all()
    args = (op.kube, op.cluster, op.cloud, op.clock)
    mnc = MultiNodeConsolidation(*args, options=op.opts, force_oracle=True)
    candidates = mnc.candidates()[:10]
    assert len(candidates) == 10

    calls = {"fast": 0}
    orig = sweep_mod._fast_prefix_feasibility

    def spy(*a, **k):
        r = orig(*a, **k)
        if r is not None:
            calls["fast"] += 1
        return r

    sweep_mod._fast_prefix_feasibility = spy
    try:
        fast = sweep_mod.prefix_feasibility(
            op.kube, op.cluster, op.cloud, candidates, op.opts
        )
        assert calls["fast"] == 1, "gates must admit the fast path here"
        # force the vmapped full-state fallback on the same problem
        sweep_mod._fast_prefix_feasibility = lambda *a, **k: None
        slow = sweep_mod.prefix_feasibility(
            op.kube, op.cluster, op.cloud, candidates, op.opts
        )
    finally:
        sweep_mod._fast_prefix_feasibility = orig
    assert fast == slow, (fast, slow)

    # ground truth: per-prefix oracle simulation (the sweep's feasibility
    # contract is SCHEDULABILITY with <= 1 new claim; price/spot rules are
    # applied afterwards by compute_consolidation, not by the sweep)
    from karpenter_tpu.controllers.disruption.helpers import simulate_scheduling

    want = []
    for k in range(len(candidates)):
        sim = simulate_scheduling(
            op.kube, op.cluster, op.cloud, candidates[: k + 1], op.opts,
            force_oracle=True,
        )
        claims = [c for c in sim.results.new_node_claims if c.pods]
        want.append(sim.all_pods_scheduled() and len(claims) <= 1)
    assert fast == want, (fast, want)


def test_consolidation_simulation_partitions_on_tpu_path():
    """Kernel<->controller integration for the PARTITIONED continuation
    under consolidation: one reschedulable pod carries host ports (outside
    the tensor encoding), so the simulation's solve runs the kernel for
    the bulk and the oracle continuation for that pod — against the
    kernel's decoded state (VERDICT r3 item #8)."""
    op = settled_operator(force_oracle=False, n_pods=5)
    # give one running pod host ports so the simulation must partition
    p = op.kube.list("Pod")[0]
    p.host_ports = [("", "TCP", 8080)]
    op.kube.update("Pod", p)
    mark_consolidatable(op)
    cands = build_candidates(op.kube, op.cluster, op.cloud, op.clock, lambda c: True)
    assert cands
    sim = simulate_scheduling(
        op.kube, op.cluster, op.cloud, cands, op.opts, force_oracle=False
    )
    assert sim.used_tpu is True, "bulk must ride the kernel"
    assert sim.all_pods_scheduled()
    # the ported pod was actually placed by the continuation
    names = {q.name for c in sim.results.new_node_claims for q in c.pods}
    names |= {q.name for n in sim.results.existing_nodes for q in n.pods}
    assert p.name in names


# ---------------------------------------------------------------------------
# Pod eviction cost (reference suite_test.go:843-897, utils/disruption
# disruption.go:37-78) — round 5


def test_pod_eviction_cost_standard():
    from karpenter_tpu.controllers.disruption.types import eviction_cost

    assert eviction_cost(fixtures.pod(name="p")) == 1.0


def test_pod_eviction_cost_deletion_cost_annotation():
    from karpenter_tpu.controllers.disruption.types import (
        POD_DELETION_COST_ANNOTATION,
        eviction_cost,
    )

    def with_cost(v):
        p = fixtures.pod(name=f"p{v}")
        p.metadata.annotations[POD_DELETION_COST_ANNOTATION] = str(v)
        return p

    assert eviction_cost(with_cost(100)) > 1.0
    assert eviction_cost(with_cost(-100)) < 1.0
    # monotone in the annotation value (suite_test.go:865)
    assert (
        eviction_cost(with_cost(101))
        > eviction_cost(with_cost(100))
        > eviction_cost(with_cost(99))
    )
    # clamp to [-10, 10]
    assert eviction_cost(with_cost(2**40)) == 10.0
    assert eviction_cost(with_cost(-(2**40))) == -10.0
    # malformed annotation ignored
    p = fixtures.pod(name="bad")
    p.metadata.annotations[POD_DELETION_COST_ANNOTATION] = "not-a-number"
    assert eviction_cost(p) == 1.0


def test_pod_eviction_cost_priority():
    from karpenter_tpu.controllers.disruption.types import eviction_cost

    hi = fixtures.pod(name="hi")
    hi.priority = 1
    lo_ = fixtures.pod(name="lo")
    lo_.priority = -1
    assert eviction_cost(hi) > 1.0
    assert eviction_cost(lo_) < 1.0


def test_lifetime_remaining_scales_disruption_cost():
    """types.go:132 — cost scales by the fraction of expireAfter left."""
    from karpenter_tpu.api.objects import NodeClaim, ObjectMeta
    from karpenter_tpu.controllers.disruption.types import disruption_cost

    clock = FakeClock()
    claim = NodeClaim(metadata=ObjectMeta(name="c"))
    claim.metadata.creation_timestamp = clock.now()
    claim.expire_after_seconds = 100.0
    pods = [fixtures.pod(name="p")]
    full = disruption_cost(pods, clock, claim)
    clock.advance(50.0)
    half = disruption_cost(pods, clock, claim)
    clock.advance(100.0)
    expired = disruption_cost(pods, clock, claim)
    assert full == 1.0 and abs(half - 0.5) < 1e-9 and expired == 0.0
    # no expiry -> no scaling
    claim.expire_after_seconds = None
    assert disruption_cost(pods, clock, claim) == 1.0


# ---------------------------------------------------------------------------
# Candidate filtering x TerminationGracePeriod x disruption class
# (suite_test.go:1022-1176; types.go:47-48, 118) — round 5


def test_candidate_filtering_tgp_matrix():
    """do-not-disrupt pods: block GRACEFUL disruption always; block
    EVENTUAL disruption only when the claim has no TerminationGracePeriod."""
    op = settled_operator(
        n_pods=2, pod_kw=dict(labels={"app": "hold"})
    )
    mark_consolidatable(op)
    # pin a do-not-disrupt pod
    pod = op.kube.list("Pod")[0]
    pod.metadata.annotations[well_known.DO_NOT_DISRUPT_ANNOTATION_KEY] = "true"
    op.kube.update("Pod", pod)
    node_name = pod.node_name

    def names(disruption_class):
        return [
            c.name
            for c in build_candidates(
                op.kube, op.cluster, op.cloud, op.clock, lambda c: True,
                disruption_class=disruption_class,
            )
        ]

    # no TGP: blocked for both classes (suite_test.go:1148)
    assert node_name not in names("graceful")
    assert node_name not in names("eventual")

    # TGP set on the claim: eventual may proceed, graceful still blocked
    (claim,) = [
        c for c in op.kube.list("NodeClaim") if c.status.node_name == node_name
    ]
    claim.termination_grace_period_seconds = 300.0
    op.kube.update("NodeClaim", claim)
    assert node_name not in names("graceful")  # suite_test.go:1083
    assert node_name in names("eventual")  # suite_test.go:1022


def test_candidate_filtering_tgp_matrix_pdb():
    """Fully-blocking PDBs follow the same class x TGP rule
    (suite_test.go:1051/1112/1176)."""
    from karpenter_tpu.api.objects import LabelSelector, ObjectMeta, PodDisruptionBudget

    op = settled_operator(n_pods=2, pod_kw=dict(labels={"app": "frozen"}))
    mark_consolidatable(op)
    op.kube.create(
        "PodDisruptionBudget",
        PodDisruptionBudget(
            metadata=ObjectMeta(name="freeze"),
            selector=LabelSelector(match_labels={"app": "frozen"}),
            max_unavailable="0",
        ),
    )
    pod_nodes = {p.node_name for p in op.kube.list("Pod") if p.node_name}

    def names(disruption_class):
        return [
            c.name
            for c in build_candidates(
                op.kube, op.cluster, op.cloud, op.clock, lambda c: True,
                disruption_class=disruption_class,
            )
        ]

    assert not any(n in pod_nodes for n in names("graceful"))
    assert not any(n in pod_nodes for n in names("eventual"))
    for claim in op.kube.list("NodeClaim"):
        claim.termination_grace_period_seconds = 300.0
        op.kube.update("NodeClaim", claim)
    assert not any(n in pod_nodes for n in names("graceful"))
    assert any(n in pod_nodes for n in names("eventual"))


# ---------------------------------------------------------------------------
# Emptiness considers pending pods (emptiness_test.go:497) — round 5


def test_emptiness_considers_pending_pods():
    """An empty node a pending pod is about to land on must not be deleted
    out from under it: the nomination window + validation veto keep the
    node alive until the pod binds."""
    op = settled_operator(n_pods=1)
    # free the node: delete the pod, stamp conditions, make consolidatable
    op.kube.delete("Pod", "w-0")
    op.clock.advance(25.0)
    op.claim_conditions.reconcile_all()
    n_nodes = len(op.kube.list("Node"))
    assert n_nodes == 1

    # a pending pod arrives that fits the empty node
    op.kube.create("Pod", fixtures.pod(name="late", requests={"cpu": "500m"}))
    # drive full operator ticks: provisioning must win the race with
    # emptiness — the pod binds to the EXISTING node, no deletion, no new
    # node
    assert op.run_until_settled(max_ticks=40) < 40
    assert len(op.kube.list("Node")) == 1
    late = op.kube.get("Pod", "late")
    assert late.node_name


# ---------------------------------------------------------------------------
# Batched single-node consolidation (round 5, VERDICT #7):
# singlenodeconsolidation.go:56 loops per-candidate simulations; the
# singleton sweep evaluates every candidate as an independent device lane.


def _snc_fleet(n=8):
    from karpenter_tpu.api.objects import Budget

    op = Operator(clock=FakeClock(), force_oracle=True)
    op.raw_cloud.types = construct_instance_types(sizes=[2, 32])
    op.raw_cloud._by_name = {it.name: it for it in op.raw_cloud.types}
    fixtures.reset_rng(21)
    op.kube.create(
        "NodePool",
        fixtures.node_pool(name="default", budgets=[Budget(nodes="100%")]),
    )
    fixtures.make_underutilized_fleet(op, n)
    op.clock.advance(26.0)
    op.pod_events.reconcile_all()
    op.claim_conditions.reconcile_all()
    return op


def test_singleton_feasibility_matches_sequential_simulation():
    """Every singleton lane's verdict must equal a full sequential
    simulation of removing exactly that candidate."""
    from karpenter_tpu.controllers.disruption.consolidation import (
        SingleNodeConsolidation,
    )
    from karpenter_tpu.controllers.disruption.helpers import simulate_scheduling
    from karpenter_tpu.controllers.disruption.sweep import singleton_feasibility

    op = _snc_fleet(6)
    args = (op.kube, op.cluster, op.cloud, op.clock)
    snc = SingleNodeConsolidation(*args, options=op.opts, force_oracle=True)
    cands = snc.candidates()
    assert len(cands) >= 4
    feas = singleton_feasibility(op.kube, op.cluster, op.cloud, cands, op.opts)
    assert len(feas) == len(cands)
    for j, c in enumerate(cands):
        sim = simulate_scheduling(
            op.kube, op.cluster, op.cloud, [c], op.opts, force_oracle=True
        )
        seq_ok = (
            sim.all_pods_scheduled() and len(sim.non_empty_new_claims()) <= 1
        )
        assert feas[j] == seq_ok, f"cand {c.name}: lane={feas[j]} seq={seq_ok}"


def test_single_node_batched_agrees_with_sequential():
    """The batched SNC must pick the same command the sequential walk
    picks (the lane skip is exact: an infeasible lane is always a no-op)."""
    from karpenter_tpu.controllers.disruption.consolidation import (
        SingleNodeConsolidation,
    )

    op = _snc_fleet(8)
    args = (op.kube, op.cluster, op.cloud, op.clock)
    batched = SingleNodeConsolidation(
        *args, sweep="batched", options=op.opts, force_oracle=True
    )
    sequential = SingleNodeConsolidation(
        *args, sweep="sequential", options=op.opts, force_oracle=True
    )
    ca = batched.compute_commands()
    cb = sequential.compute_commands()
    na = sorted(c.name for cmd in ca for c in cmd.candidates)
    nb = sorted(c.name for cmd in cb for c in cmd.candidates)
    assert na == nb and na, (na, nb)
    assert (ca[0].decision if ca else None) == (cb[0].decision if cb else None)
