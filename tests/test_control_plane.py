"""End-to-end control plane: pending pods -> NodeClaims -> Nodes -> bound
pods with no manual scheduler calls (VERDICT round-1 item 4; reference flow
SURVEY.md §3.1).

Also unit-level coverage for the state cache, batcher, lifecycle state
machine, KWOK provider, volume topology, and node termination."""

from __future__ import annotations

import pytest

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import (
    COND_INITIALIZED,
    COND_LAUNCHED,
    COND_REGISTERED,
    LabelSelector,
    PersistentVolumeClaim,
    Pod,
    PodDisruptionBudget,
    PodPhase,
    StorageClass,
)
from karpenter_tpu.cloudprovider.kwok import KwokCloudProvider, construct_instance_types
from karpenter_tpu.controllers.kube import FakeClock
from karpenter_tpu.controllers.operator import Operator
from karpenter_tpu.controllers.state import UNREGISTERED_TAINT
from karpenter_tpu.testing import fixtures


def small_operator(**kw) -> Operator:
    clock = FakeClock()
    op = Operator(clock=clock, force_oracle=kw.pop("force_oracle", True), **kw)
    op.raw_cloud.types = construct_instance_types(sizes=[2, 8, 32])
    op.raw_cloud._by_name = {it.name: it for it in op.raw_cloud.types}
    return op


def test_e2e_pending_pods_to_bound_pods():
    """The headline flow: create a NodePool and pods, tick the operator,
    and observe claims -> nodes -> bindings with no manual scheduling."""
    op = small_operator()
    fixtures.reset_rng(5)
    op.kube.create("NodePool", fixtures.node_pool(name="default"))
    pods = fixtures.make_generic_pods(10)
    for p in pods:
        op.kube.create("Pod", p)

    ticks = op.run_until_settled(max_ticks=30)
    assert op.settled(), f"not settled after {ticks} ticks"

    claims = op.kube.list("NodeClaim")
    nodes = op.kube.list("Node")
    assert claims, "no NodeClaims created"
    assert nodes, "no Nodes fabricated"
    assert len(nodes) == len(claims)
    for c in claims:
        assert c.status.conditions.get(COND_LAUNCHED) == "True"
        assert c.status.conditions.get(COND_REGISTERED) == "True"
        assert c.status.conditions.get(COND_INITIALIZED) == "True"
        assert c.status.provider_id.startswith("kwok://")
    # every pod bound to a real node
    node_names = {n.name for n in nodes}
    for p in op.kube.list("Pod"):
        assert p.node_name in node_names, f"pod {p.name} unbound"
    # nodes carry no unregistered taint and the nodepool label
    for n in nodes:
        assert UNREGISTERED_TAINT not in n.taints
        assert n.metadata.labels[well_known.NODEPOOL_LABEL_KEY] == "default"
    # state cache reflects the bindings
    for n in nodes:
        sn = op.cluster.node_by_name(n.name)
        assert sn is not None and sn.initialized()
    assert sum(len(op.cluster.pods_on(n.name)) for n in nodes) == len(pods)


def test_e2e_scales_existing_capacity_first():
    """Second wave of pods lands on existing nodes when they fit."""
    op = small_operator()
    fixtures.reset_rng(6)
    op.kube.create("NodePool", fixtures.node_pool(name="default"))
    for p in fixtures.make_generic_pods(4):
        op.kube.create("Pod", p)
    op.run_until_settled(max_ticks=30)
    n_nodes = len(op.kube.list("Node"))
    assert n_nodes >= 1

    # tiny pod fits on the existing node -> no new claim
    p = fixtures.pod(name="late", requests={"cpu": "10m", "memory": "10Mi"})
    op.kube.create("Pod", p)
    op.run_until_settled(max_ticks=30)
    assert op.kube.get("Pod", "late").node_name
    assert len(op.kube.list("Node")) == n_nodes


def test_lifecycle_liveness_deletes_stuck_claims():
    from karpenter_tpu.cloudprovider.types import CreateError

    op = small_operator()
    fixtures.reset_rng(7)
    op.kube.create("NodePool", fixtures.node_pool(name="default"))
    op.kube.create("Pod", fixtures.make_generic_pods(1)[0])
    # every launch fails
    op.cloud.create = lambda claim: (_ for _ in ()).throw(
        CreateError("simulated capacity failure", reason="InsufficientCapacity")
    )
    op.step(2.0)
    op.step(2.0)
    assert op.kube.list("NodeClaim"), "claim should exist while retrying"
    # launch TTL elapses -> liveness deletes the claim
    op.clock.advance(op.opts.launch_ttl_seconds + 1)
    op.lifecycle.reconcile_all()
    op.lifecycle.reconcile_all()  # finalizer pass
    assert not op.kube.list("NodeClaim")
    assert op.recorder.for_reason("LivenessTimeout")


def test_node_termination_drains_and_removes():
    op = small_operator()
    fixtures.reset_rng(8)
    op.kube.create("NodePool", fixtures.node_pool(name="default"))
    for p in fixtures.make_generic_pods(3):
        op.kube.create("Pod", p)
    op.run_until_settled(max_ticks=30)
    node = op.kube.list("Node")[0]
    claim = op.kube.list("NodeClaim")[0]
    pods_on_node = [p for p in op.kube.list("Pod") if p.node_name == node.name]
    assert pods_on_node

    op.kube.delete("NodeClaim", claim.name)
    for _ in range(12):
        op.step(2.0)
    # node + claim gone, instance terminated
    assert op.kube.try_get("Node", node.name) is None
    assert op.kube.try_get("NodeClaim", claim.name) is None
    assert claim.status.provider_id in op.cloud.deleted
    # evicted workload pods were rescheduled onto a replacement
    for p in op.kube.list("Pod"):
        assert p.node_name != node.name


def test_pdb_blocks_eviction():
    op = small_operator()
    fixtures.reset_rng(9)
    op.kube.create("NodePool", fixtures.node_pool(name="default"))
    pod = fixtures.pod(name="guarded", labels={"app": "db"}, requests={"cpu": "100m"})
    op.kube.create("Pod", pod)
    op.run_until_settled(max_ticks=30)
    stored = op.kube.get("Pod", "guarded")
    stored.phase = PodPhase.RUNNING
    op.kube.update("Pod", stored)
    op.kube.create(
        "PodDisruptionBudget",
        PodDisruptionBudget(
            metadata=fixtures.pod(name="pdb-db").metadata,
            selector=LabelSelector(match_labels={"app": "db"}),
            max_unavailable="0",
        ),
    )
    node = op.kube.list("Node")[0]
    op.kube.delete("Node", node.name)
    for _ in range(5):
        op.termination.reconcile_all()
    # the pod is still there, eviction blocked by the PDB
    assert op.kube.get("Pod", "guarded").node_name == node.name
    assert not op.kube.get("Pod", "guarded").terminating
    assert op.recorder.for_reason("EvictionBlocked")


def test_volume_topology_injection():
    op = small_operator()
    fixtures.reset_rng(10)
    op.kube.create("NodePool", fixtures.node_pool(name="default"))
    sc = StorageClass()
    sc.metadata.name = "zonal"
    sc.zones = ["test-zone-b"]
    op.kube.create("StorageClass", sc)
    pvc = PersistentVolumeClaim(storage_class_name="zonal")
    pvc.metadata.name = "data"
    op.kube.create("PersistentVolumeClaim", pvc)

    p = fixtures.pod(name="zonal-pod", requests={"cpu": "100m"})
    p.volume_claims = ["data"]
    op.kube.create("Pod", p)
    op.run_until_settled(max_ticks=30)

    bound = op.kube.get("Pod", "zonal-pod")
    assert bound.node_name
    node = op.kube.get("Node", bound.node_name)
    assert node.metadata.labels[well_known.TOPOLOGY_ZONE_LABEL_KEY] == "test-zone-b"


def test_missing_pvc_blocks_pod():
    op = small_operator()
    op.kube.create("NodePool", fixtures.node_pool(name="default"))
    p = fixtures.pod(name="orphan", requests={"cpu": "100m"})
    p.volume_claims = ["missing"]
    op.kube.create("Pod", p)
    op.run_until_settled(max_ticks=5)
    assert not op.kube.get("Pod", "orphan").node_name
    assert any(
        "missing persistent volume claim" in e.message
        for e in op.recorder.for_reason("FailedScheduling")
    )


def test_batcher_window():
    clock = FakeClock()
    from karpenter_tpu.controllers.provisioning import Batcher

    b = Batcher(clock, idle_seconds=1.0, max_seconds=10.0)
    assert not b.ready()
    b.trigger("a")
    assert not b.ready()  # idle window open
    clock.advance(0.5)
    b.trigger("b")
    clock.advance(1.1)
    assert b.ready()  # idle elapsed since the last distinct trigger
    b.reset()
    start = clock.now()
    # max window forces readiness under constant triggering
    for i in range(100):
        b.trigger(f"t{i}")
        clock.advance(0.2)
        if b.ready():
            break
    assert b.ready()
    assert clock.now() - start <= 10.0 + 0.3


def test_cluster_synced_barrier():
    op = small_operator()
    # claims created out-of-band are seen synchronously via informers
    assert op.cluster.synced(op.kube)
    fixtures.reset_rng(11)
    op.kube.create("NodePool", fixtures.node_pool(name="default"))
    op.kube.create("Pod", fixtures.make_generic_pods(1)[0])
    assert op.cluster.synced(op.kube)


def test_namespace_selector_wired_through_operator():
    """Namespace objects in the store reach the scheduling Topology via the
    shared cluster_source factory: an affinity namespaceSelector resolves
    against their labels in a real provisioner tick (topology.go:503)."""
    from karpenter_tpu.api.objects import LabelSelector, PodAffinityTerm
    from karpenter_tpu.api import labels as wk
    from karpenter_tpu.controllers.kube import Namespace

    op = small_operator()
    op.kube.create("NodePool", fixtures.node_pool(name="default"))
    op.kube.create("Namespace", Namespace(name="team-a", labels={"tier": "backend"}))
    op.kube.create("Namespace", Namespace(name="frontend", labels={"tier": "web"}))

    anchor = fixtures.pod(
        name="anchor", labels={"db": "primary"}, requests={"cpu": "100m"}
    )
    anchor.metadata.namespace = "team-a"
    op.kube.create("Pod", anchor)
    follower = fixtures.pod(
        name="follower",
        labels={"app": "web"},
        requests={"cpu": "100m"},
        pod_requirements=[
            PodAffinityTerm(
                topology_key=wk.HOSTNAME_LABEL_KEY,
                label_selector=LabelSelector(match_labels={"db": "primary"}),
                namespace_selector=LabelSelector(match_labels={"tier": "backend"}),
            )
        ],
    )
    follower.metadata.namespace = "frontend"
    op.kube.create("Pod", follower)
    op.run_until_settled(max_ticks=60)

    a = op.kube.get("Pod", "anchor")
    f = op.kube.get("Pod", "follower")
    assert a.node_name and f.node_name
    assert a.node_name == f.node_name, (
        "hostname affinity across a selector-matched namespace must co-locate"
    )


def test_scheduling_gates_defer_provisioning():
    """A pod with schedulingGates is not provisionable until the gates are
    cleared (pod/scheduling.go:42 IsProvisionable excludes gated pods)."""
    op = small_operator()
    op.kube.create("NodePool", fixtures.node_pool(name="default"))
    gated = fixtures.pod(name="gated", requests={"cpu": "500m"})
    gated.scheduling_gates = ["example.com/wait"]
    op.kube.create("Pod", gated)
    for _ in range(20):
        op.step(2.0)
    assert not op.kube.list("Node"), "gated pod must not trigger capacity"

    stored = op.kube.get("Pod", "gated")
    stored.scheduling_gates = []
    op.kube.update("Pod", stored)
    for _ in range(30):
        op.step(2.0)
        if op.kube.get("Pod", "gated").node_name:
            break
    assert op.kube.get("Pod", "gated").node_name, "ungated pod provisions"


def test_terminal_and_terminating_pods_do_not_provision():
    """Succeeded/Failed/terminating pods never open capacity
    (pod/scheduling.go IsProvisionable)."""
    from karpenter_tpu.api.objects import PodPhase

    op = small_operator()
    op.kube.create("NodePool", fixtures.node_pool(name="default"))
    done = fixtures.pod(name="done", requests={"cpu": "500m"})
    done.phase = PodPhase.SUCCEEDED
    op.kube.create("Pod", done)
    dying = fixtures.pod(name="dying", requests={"cpu": "500m"})
    dying.terminating = True
    op.kube.create("Pod", dying)
    for _ in range(20):
        op.step(2.0)
    assert not op.kube.list("Node")


def test_nodepool_opt_out_selector():
    """A pod requiring karpenter.sh/nodepool DoesNotExist opts out of
    provisioning entirely (provisioner.go:504-586 pod validation)."""
    from karpenter_tpu.api.objects import NodeSelectorRequirement, Operator as Oper

    op = small_operator()
    op.kube.create("NodePool", fixtures.node_pool(name="default"))
    optout = fixtures.pod(
        name="optout",
        requests={"cpu": "500m"},
        node_requirements=[
            NodeSelectorRequirement(
                well_known.NODEPOOL_LABEL_KEY, Oper.DOES_NOT_EXIST, []
            )
        ],
    )
    op.kube.create("Pod", optout)
    for _ in range(20):
        op.step(2.0)
    assert not op.kube.list("Node"), "opt-out pod must not be provisioned for"


def test_termination_grace_period_force_drains_past_pdb():
    """terminator.go:140-176 + termination/controller.go:289: once the
    claim's terminationGracePeriod expires, the drain turns forced and
    evicts even PDB-blocked pods, so a stuck node cannot wedge forever."""
    op = small_operator()
    fixtures.reset_rng(9)
    op.kube.create("NodePool", fixtures.node_pool(name="default"))
    pod = fixtures.pod(name="guarded", labels={"app": "db"}, requests={"cpu": "100m"})
    op.kube.create("Pod", pod)
    op.run_until_settled(max_ticks=30)
    stored = op.kube.get("Pod", "guarded")
    stored.phase = PodPhase.RUNNING
    op.kube.update("Pod", stored)
    op.kube.create(
        "PodDisruptionBudget",
        PodDisruptionBudget(
            metadata=fixtures.pod(name="pdb-db2").metadata,
            selector=LabelSelector(match_labels={"app": "db"}),
            max_unavailable="0",
        ),
    )
    claim = op.kube.list("NodeClaim")[0]
    claim.termination_grace_period_seconds = 30.0
    op.kube.update("NodeClaim", claim)
    node = op.kube.list("Node")[0]
    op.kube.delete("Node", node.name)
    # within the grace period: blocked
    op.termination.reconcile_all()
    assert not op.kube.get("Pod", "guarded").terminating
    # past it: forced
    op.clock.advance(31.0)
    for _ in range(10):
        op.step(2.0)
        if op.kube.try_get("Node", node.name) is None:
            break
    assert op.kube.try_get("Node", node.name) is None, "forced drain completes"


def test_termination_waits_for_volume_detachment():
    """termination/controller.go:223-252: after drain, instance deletion
    blocks until the node's VolumeAttachments are deleted (the external
    attach-detach controller's job, simulated here); attachments owned by
    non-drainable pods never block."""
    from karpenter_tpu.api.objects import ObjectMeta, VolumeAttachment

    op = small_operator()
    fixtures.reset_rng(21)
    op.kube.create("NodePool", fixtures.node_pool(name="default"))
    op.kube.create("Pod", fixtures.pod(name="w", requests={"cpu": "100m"}))
    op.run_until_settled(max_ticks=30)
    node = op.kube.list("Node")[0]
    claim = op.kube.list("NodeClaim")[0]

    op.kube.create(
        "VolumeAttachment",
        VolumeAttachment(
            metadata=ObjectMeta(name="va-1"),
            node_name=node.name,
            volume_name="pvc-data",
        ),
    )
    op.kube.delete("NodeClaim", claim.name)
    for _ in range(10):
        op.step(2.0)
    # drained, but the node must still exist: the attachment is pending
    assert op.kube.try_get("Node", node.name) is not None
    assert claim.status.provider_id not in op.cloud.deleted
    assert op.recorder.for_reason("AwaitingVolumeDetachment")

    # the attach-detach controller finishes -> termination completes
    op.kube.delete("VolumeAttachment", "va-1")
    for _ in range(10):
        op.step(2.0)
    assert op.kube.try_get("Node", node.name) is None
    assert claim.status.provider_id in op.cloud.deleted


def test_termination_grace_period_skips_volume_wait():
    """controller.go:257-263: once the claim's terminationGracePeriod
    elapses, pending attachments stop blocking instance deletion."""
    from karpenter_tpu.api.objects import ObjectMeta, VolumeAttachment

    op = small_operator()
    fixtures.reset_rng(22)
    op.kube.create("NodePool", fixtures.node_pool(name="default"))
    op.kube.create("Pod", fixtures.pod(name="w", requests={"cpu": "100m"}))
    op.run_until_settled(max_ticks=30)
    node = op.kube.list("Node")[0]
    claim = op.kube.get("NodeClaim", op.kube.list("NodeClaim")[0].name)
    claim.termination_grace_period_seconds = 10.0
    op.kube.update("NodeClaim", claim)

    op.kube.create(
        "VolumeAttachment",
        VolumeAttachment(
            metadata=ObjectMeta(name="va-stuck"),
            node_name=node.name,
            volume_name="pvc-stuck",
        ),
    )
    op.kube.delete("NodeClaim", claim.name)
    op.step(2.0)
    assert op.kube.try_get("Node", node.name) is not None  # still waiting
    op.clock.advance(12.0)  # past the grace period
    for _ in range(10):
        op.step(2.0)
    assert op.kube.try_get("Node", node.name) is None
    assert claim.status.provider_id in op.cloud.deleted


def test_requirements_drift_marks_and_replaces_node():
    """drift.go:168-174 areRequirementsDrifted: a nodepool whose
    requirements change out from under its nodes drifts them — the claim's
    labels (populated at launch, launch.go:126-140) no longer satisfy the
    pool's requirements, the Drifted condition goes True, and the
    disruption loop replaces the node."""
    from karpenter_tpu.api import labels as well_known
    from karpenter_tpu.api.objects import (
        COND_DRIFTED,
        NodeSelectorRequirement,
        Operator,
    )

    op = small_operator()
    fixtures.reset_rng(23)
    op.kube.create(
        "NodePool",
        fixtures.node_pool(
            name="default",
            requirements=[
                NodeSelectorRequirement(
                    well_known.TOPOLOGY_ZONE_LABEL_KEY,
                    Operator.IN,
                    ["test-zone-a"],
                )
            ],
        ),
    )
    op.kube.create("Pod", fixtures.pod(name="w", requests={"cpu": "100m"}))
    op.run_until_settled(max_ticks=30)
    claim = op.kube.list("NodeClaim")[0]
    # launch populated the claim's labels from the resolved offering
    assert claim.metadata.labels.get(well_known.TOPOLOGY_ZONE_LABEL_KEY) == "test-zone-a"
    op.claim_conditions.reconcile_all()
    assert op.kube.get("NodeClaim", claim.name).status.conditions.get(COND_DRIFTED) != "True"

    # the pool's requirements move to zone-b: existing claim labels no
    # longer satisfy them -> RequirementsDrifted
    pool = op.kube.get("NodePool", "default")
    pool.template.requirements = [
        NodeSelectorRequirement(
            well_known.TOPOLOGY_ZONE_LABEL_KEY, Operator.IN, ["test-zone-b"]
        )
    ]
    op.kube.update("NodePool", pool)
    op.claim_conditions.reconcile_all()
    assert (
        op.kube.get("NodeClaim", claim.name).status.conditions.get(COND_DRIFTED)
        == "True"
    )


def test_per_driver_csi_volume_limits():
    """volumeusage.go:187: attachable-volume budgets are PER CSI DRIVER
    (CSINode allocatable), not one per-node number — a node saturated on
    driver A still accepts driver-B volumes, and vice versa."""
    from karpenter_tpu.api.objects import (
        ObjectMeta,
        PersistentVolumeClaim,
        StorageClass,
    )
    from karpenter_tpu.scheduling.volumeusage import VolumeUsage
    from karpenter_tpu.solver.nodes import StateNodeView
    from karpenter_tpu.solver.oracle import Scheduler
    from karpenter_tpu.solver.topology import Topology

    # unit: per-driver accounting
    vu = VolumeUsage()
    bound = fixtures.pod(name="bound")
    bound.volume_claims = ["a1", "a2"]
    bound.volume_drivers = {"a1": "ebs.csi", "a2": "ebs.csi"}
    vu.add(bound)
    ebs_pod = fixtures.pod(name="p1")
    ebs_pod.volume_claims = ["a3"]
    ebs_pod.volume_drivers = {"a3": "ebs.csi"}
    efs_pod = fixtures.pod(name="p2")
    efs_pod.volume_claims = ["b1"]
    efs_pod.volume_drivers = {"b1": "efs.csi"}
    limits = {"ebs.csi": 2, "efs.csi": 2}
    assert vu.exceeds_limit(ebs_pod, limits) is not None  # 3 > 2 on ebs
    assert vu.exceeds_limit(efs_pod, limits) is None  # efs bucket empty

    # solver: an existing node with per-driver budgets blocks only the
    # saturated driver's pods
    its = construct_instance_types(sizes=[2, 8])
    view = StateNodeView(
        name="node-1",
        labels={
            well_known.TOPOLOGY_ZONE_LABEL_KEY: "test-zone-a",
            well_known.HOSTNAME_LABEL_KEY: "node-1",
            well_known.INSTANCE_TYPE_LABEL_KEY: "c-2x-amd64-linux",
            well_known.CAPACITY_TYPE_LABEL_KEY: "on-demand",
            well_known.OS_LABEL_KEY: "linux",
            well_known.ARCH_LABEL_KEY: "amd64",
            well_known.NODEPOOL_LABEL_KEY: "default",
        },
        available={"cpu": 1800, "memory": 3 * 1024**3 * 1000, "pods": 20000},
        capacity={"cpu": 2000, "memory": 4 * 1024**3 * 1000},
        initialized=True,
        csi_allocatable={"ebs.csi": 0, "efs.csi": 1},
    )
    pool = fixtures.node_pool(name="default")
    p_ebs = fixtures.pod(name="ebs-pod", requests={"cpu": "100m"})
    p_ebs.volume_claims = ["v1"]
    p_ebs.volume_drivers = {"v1": "ebs.csi"}
    p_efs = fixtures.pod(name="efs-pod", requests={"cpu": "100m"})
    p_efs.volume_claims = ["v2"]
    p_efs.volume_drivers = {"v2": "efs.csi"}
    pods = [p_ebs, p_efs]
    topo = Topology([pool], {"default": its}, pods, state_node_views=[view])
    r = Scheduler([pool], {"default": its}, topo, [view]).solve(pods)
    assert not r.pod_errors
    on_existing = {p.name for n in r.existing_nodes for p in n.pods}
    assert "efs-pod" in on_existing  # efs budget (1) admits it
    assert "ebs-pod" not in on_existing  # ebs budget (0) forces a new node

    # control plane: the driver resolves through PVC -> StorageClass, and
    # BOUND pods' volumes land in the right driver bucket too (the cluster
    # cache resolves drivers via wire_informers before tallying — a bound
    # ebs pod must count against ebs budgets on later solves)
    op = small_operator()
    op.kube.create("NodePool", fixtures.node_pool(name="default"))
    sc = StorageClass(metadata=ObjectMeta(name="fast"), provisioner="ebs.csi")
    op.kube.create("StorageClass", sc)
    pvc = PersistentVolumeClaim(storage_class_name="fast")
    pvc.metadata.name = "data"
    op.kube.create("PersistentVolumeClaim", pvc)
    p = fixtures.pod(name="vol-pod", requests={"cpu": "100m"})
    p.volume_claims = ["data"]
    op.kube.create("Pod", p)
    op.run_until_settled(max_ticks=30)
    bound = op.kube.get("Pod", "vol-pod")
    assert bound.node_name
    sn = op.cluster.node_by_name(bound.node_name)
    vols = sn.volume_usage.distinct_volumes()
    assert ("ebs.csi", "data") in vols, vols


def test_ephemeral_taint_assumed_schedulable_until_initialized():
    """suite_test.go:2042 — node.kubernetes.io/not-ready:NoExecute on an
    UNINITIALIZED managed node is ephemeral: the scheduler assumes pods
    can land there (statenode.go:311 rejects known ephemeral taints until
    initialization). Once the node is initialized, the same taint is
    taken at face value and a tolerating-nothing pod provisions a NEW
    node instead."""
    from karpenter_tpu.api.objects import (
        COND_INITIALIZED,
        Taint,
        TaintEffect,
    )

    op = small_operator()
    op.kube.create("NodePool", fixtures.node_pool(name="default"))
    op.kube.create("Pod", fixtures.pod(name="first", requests={"cpu": "500m"}))
    assert op.run_until_settled(max_ticks=40) < 40
    op.kube.delete("Pod", "first")
    (node,) = op.kube.list("Node")
    (claim,) = op.kube.list("NodeClaim")

    # make the node UNINITIALIZED again and not-ready-tainted (the window
    # between registration and initialization)
    claim.status.conditions[COND_INITIALIZED] = "False"
    op.kube.update("NodeClaim", claim)
    node = op.kube.get("Node", node.name)
    node.taints = list(node.taints) + [
        Taint("node.kubernetes.io/not-ready", TaintEffect.NO_EXECUTE)
    ]
    op.kube.update("Node", node)

    op.kube.create("Pod", fixtures.pod(name="second", requests={"cpu": "300m"}))
    for _ in range(6):  # settled() needs initialized claims; step manually
        op.step(2.0)
    second = op.kube.get("Pod", "second")
    assert second.node_name == node.name, (
        second.node_name,
        "ephemeral taint must not block an uninitialized node",
    )
    assert len(op.kube.list("Node")) == 1

    # initialize the node; the (still present) taint now counts
    op.kube.delete("Pod", "second")
    claim = op.kube.get("NodeClaim", claim.name)
    claim.status.conditions[COND_INITIALIZED] = "True"
    op.kube.update("NodeClaim", claim)
    op.kube.create("Pod", fixtures.pod(name="third", requests={"cpu": "300m"}))
    assert op.run_until_settled(max_ticks=40) < 40
    third = op.kube.get("Pod", "third")
    assert third.node_name and third.node_name != node.name, (
        "a real taint on an initialized node must not be assumed away"
    )


def test_custom_taint_never_assumed_schedulable():
    """suite_test.go:2080 — a NON-ephemeral taint on a node is never
    assumed away, initialized or not: the intolerant pod gets a new
    node."""
    from karpenter_tpu.api.objects import COND_INITIALIZED, Taint, TaintEffect

    op = small_operator()
    op.kube.create("NodePool", fixtures.node_pool(name="default"))
    op.kube.create("Pod", fixtures.pod(name="first", requests={"cpu": "500m"}))
    assert op.run_until_settled(max_ticks=40) < 40
    op.kube.delete("Pod", "first")
    (node,) = op.kube.list("Node")
    (claim,) = op.kube.list("NodeClaim")
    claim.status.conditions[COND_INITIALIZED] = "False"  # even uninitialized
    op.kube.update("NodeClaim", claim)
    node = op.kube.get("Node", node.name)
    node.taints = list(node.taints) + [
        Taint("example.com/custom", TaintEffect.NO_SCHEDULE)
    ]
    op.kube.update("Node", node)

    op.kube.create("Pod", fixtures.pod(name="second", requests={"cpu": "300m"}))
    assert op.run_until_settled(max_ticks=40) < 40
    second = op.kube.get("Pod", "second")
    assert second.node_name and second.node_name != node.name


def test_startup_taint_assumed_until_initialized():
    """suite_test.go:2112/2145 — a claim's custom STARTUP taint is
    assumed removable while the node is uninitialized; after
    initialization a still-present startup taint blocks like any other."""
    from karpenter_tpu.api.objects import COND_INITIALIZED, Taint, TaintEffect

    startup = Taint("example.com/boot", TaintEffect.NO_SCHEDULE)
    op = small_operator()
    op.kube.create(
        "NodePool",
        fixtures.node_pool(name="default", startup_taints=[startup]),
    )
    op.kube.create("Pod", fixtures.pod(name="first", requests={"cpu": "500m"}))
    assert op.run_until_settled(max_ticks=40) < 40
    op.kube.delete("Pod", "first")
    (node,) = op.kube.list("Node")
    (claim,) = op.kube.list("NodeClaim")

    # un-initialize + re-apply the startup taint (the boot window)
    claim.status.conditions[COND_INITIALIZED] = "False"
    op.kube.update("NodeClaim", claim)
    node = op.kube.get("Node", node.name)
    node.taints = list(node.taints) + [startup]
    op.kube.update("Node", node)
    op.kube.create("Pod", fixtures.pod(name="second", requests={"cpu": "300m"}))
    for _ in range(6):  # settled() needs initialized claims; step manually
        op.step(2.0)
    assert op.kube.get("Pod", "second").node_name == node.name

    # initialized with the startup taint still on: no longer assumed away
    op.kube.delete("Pod", "second")
    claim = op.kube.get("NodeClaim", claim.name)
    claim.status.conditions[COND_INITIALIZED] = "True"
    op.kube.update("NodeClaim", claim)
    op.kube.create("Pod", fixtures.pod(name="third", requests={"cpu": "300m"}))
    assert op.run_until_settled(max_ticks=40) < 40
    third = op.kube.get("Pod", "third")
    assert third.node_name and third.node_name != node.name


def test_inflight_claim_takes_cross_batch_pods():
    """suite_test.go:1832 — a pod arriving while a launched claim is still
    in its registration window packs onto the IN-FLIGHT claim (nominate,
    stay pending, bind once the node registers) instead of forking a
    second node. Round 5: claim-only StateNodes are schedulable views."""
    from karpenter_tpu.api.objects import Taint, TaintEffect

    op = small_operator()
    op.raw_cloud.registration_delay = 30.0  # hold the claim in flight
    op.kube.create(
        "NodePool",
        fixtures.node_pool(
            name="default",
            startup_taints=[Taint("example.com/boot", TaintEffect.NO_SCHEDULE)],
        ),
    )
    op.kube.create("Pod", fixtures.pod(name="a", requests={"cpu": "300m"}))
    op.step(2.0)
    assert len(op.kube.list("NodeClaim")) == 1
    assert not op.kube.list("Node")  # still in the registration window

    op.kube.create("Pod", fixtures.pod(name="b", requests={"cpu": "300m"}))
    op.step(2.0)
    op.step(2.0)
    assert len(op.kube.list("NodeClaim")) == 1, (
        "cross-batch pod must reuse the in-flight claim"
    )
    # once the node registers, both pods bind to the single node
    assert op.run_until_settled(max_ticks=60) < 60
    assert len(op.kube.list("Node")) == 1
    assert all(p.node_name for p in op.kube.list("Pod"))
