"""graftlint SPMD tier gate (analysis/spmd.py): per-rule positive and
negative fixtures, the compiled-program censuses against doctored jits,
the budget comparison against doctored manifests, the launch-lock AST
rule on synthetic dispatch sites, the `--all` merge, and the full-tree
run — every solver program compiles collective-free/donation-free and
matches the `spmd:` half of kernel_budgets.json.

The module-scoped `report` fixture does the expensive work once:
compiles the seven programs (including the lane-sharded fleet entry over
the conftest-pinned 8-virtual-device mesh). Everything else is
doctored-input unit tests on the censuses, the comparison, and the CLI.
"""

from __future__ import annotations

import copy
import json
import os
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from karpenter_tpu.analysis import budgets as budgets_mod
from karpenter_tpu.analysis import engine
from karpenter_tpu.analysis import spmd
from karpenter_tpu.analysis.__main__ import main as graftlint_main

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def report():
    return spmd.run_spmd_analysis(REPO_ROOT)


@pytest.fixture(scope="module")
def manifest_entries(report):
    """Deep-copyable real `spmd:` manifest entries for doctoring."""
    return {
        name: copy.deepcopy(e)
        for name, e in report["manifest"].entries.items()
    }


# ---------------------------------------------------------------------------
# full-tree cleanliness (the gate)


def test_full_tree_clean(report):
    assert report["errors"] == []
    assert [f.render() for f in report["findings"]] == []
    assert report["stale"] == []
    assert report["unjustified"] == []
    assert report["budget_unjustified"] == []


def test_manifest_covers_every_program(report):
    names = set(spmd._entry_paths())
    assert all(n.startswith(budgets_mod.SPMD_PREFIX) for n in names)
    assert spmd.FLEET_ENTRY in names
    assert set(report["measured"]) == names
    assert set(report["manifest"].entries) == names


def test_collective_and_donation_contracts_hold(report):
    """The absolute contracts, independent of what the manifest says:
    every program — the lane-SHARDED fleet entry included — compiles
    with zero collectives and zero donated inputs today."""
    for name, metrics in report["measured"].items():
        for m in (
            "collectives_all_gather",
            "collectives_all_reduce",
            "collectives_permute",
            "collectives_other",
            "donated_args",
        ):
            assert metrics[m] == 0, (name, m, metrics[m])


def test_sharded_fleet_hbm_is_one_lane(report):
    """Per-device argument bytes of the 8-lane sharded fleet program
    must match the SOLO program's (each device holds one lane) — the
    capacity axis docs/sharding.md claims, measured."""
    sh = report["measured"][spmd.FLEET_ENTRY]["hbm_argument_bytes"]
    solo = report["measured"]["spmd:solve_scan[relax=False]"][
        "hbm_argument_bytes"
    ]
    assert sh == solo


# ---------------------------------------------------------------------------
# spmd-collectives: census on doctored compiled programs


def _lane_sharding():
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()), ("fleet",))
    return NamedSharding(mesh, P("fleet"))


def test_census_counts_sharded_reduction():
    """A sharded-input program whose output is a full reduction forces
    GSPMD to insert a cross-device all-reduce — the census must see it
    in the COMPILED module."""
    x = jax.device_put(jnp.arange(64, dtype=jnp.float32), _lane_sharding())
    compiled = jax.jit(lambda a: a.sum()).lower(x).compile()
    census = spmd.collective_census(compiled.as_text())
    assert census["all-reduce"] + census["all-gather"] >= 1
    metrics = spmd.collective_metrics(census)
    assert sum(metrics.values()) >= 1


def test_census_zero_on_unsharded_program():
    compiled = (
        jax.jit(lambda a: a.sum()).lower(jnp.arange(64.0)).compile()
    )
    assert not any(spmd.collective_census(compiled.as_text()).values())


def test_census_text_counts_start_not_done():
    """Async collective pairs are ONE collective: count the `-start`,
    skip the `-done`; variable REFERENCES like `%all-reduce.5` never
    count (the opcode is only an op when directly followed by `(`)."""
    hlo = textwrap.dedent(
        """\
        %ar-s = (f32[4], f32[4]) all-reduce-start(f32[4] %p0), to_apply=%add
        %ar-d = f32[4] all-reduce-done((f32[4], f32[4]) %ar-s)
        %g = f32[8] all-gather(f32[4] %ar-d), dimensions={0}
        %use = f32[8] add(f32[8] %g, f32[8] %all-reduce.5)
        """
    )
    census = spmd.collective_census(hlo)
    assert census["all-reduce"] == 1
    assert census["all-gather"] == 1
    assert sum(census.values()) == 2


def test_collectives_budget_mismatch_is_exact(report, manifest_entries):
    """A collective appearing where the budget pins zero is a finding
    even when it is 'only one' — and a budget expecting one that
    disappears is ALSO a finding (exact, both directions)."""
    measured = copy.deepcopy(report["measured"])
    measured[spmd.FLEET_ENTRY]["collectives_all_reduce"] = 1
    findings, _ = spmd.budget_findings(
        measured, budgets_mod.BudgetManifest(copy.deepcopy(manifest_entries))
    )
    assert any(
        f.rule == "spmd-collectives" and f.text == spmd.FLEET_ENTRY
        for f in findings
    )
    entries = copy.deepcopy(manifest_entries)
    entries[spmd.FLEET_ENTRY]["metrics"]["collectives_all_gather"] = 2
    findings, _ = spmd.budget_findings(
        report["measured"], budgets_mod.BudgetManifest(entries)
    )
    assert any(f.rule == "spmd-collectives" for f in findings)


# ---------------------------------------------------------------------------
# spmd-donation: census on doctored lowered programs


def test_donation_census_counts_donated_argument():
    lowered = jax.jit(lambda a: a + 1, donate_argnums=0).lower(
        jnp.arange(8.0)
    )
    assert spmd.donation_census(lowered.as_text()) == 1


def test_donation_census_zero_without_donation():
    lowered = jax.jit(lambda a: a + 1).lower(jnp.arange(8.0))
    assert spmd.donation_census(lowered.as_text()) == 0


def test_donation_budget_flip_needs_rebaseline(report, manifest_entries):
    """The carry-donation PR (ROADMAP item 1) flipping donated_args must
    trip the exact budget until the manifest is intentionally updated."""
    measured = copy.deepcopy(report["measured"])
    measured["spmd:solve_scan[relax=True]"]["donated_args"] = 3
    findings, _ = spmd.budget_findings(
        measured, budgets_mod.BudgetManifest(copy.deepcopy(manifest_entries))
    )
    assert any(
        f.rule == "spmd-donation" and "donated_args" in f.message
        for f in findings
    )


# ---------------------------------------------------------------------------
# spmd-hbm: ceilings and the predicted-vs-measured cross-check


def test_hbm_ceiling_breach_detected(report, manifest_entries):
    entries = copy.deepcopy(manifest_entries)
    got = report["measured"][spmd.FLEET_ENTRY]["hbm_temp_bytes"]
    entries[spmd.FLEET_ENTRY]["metrics"]["hbm_temp_bytes"] = got - 1
    findings, _ = spmd.budget_findings(
        report["measured"], budgets_mod.BudgetManifest(entries)
    )
    assert any(
        f.rule == "spmd-hbm" and "regressed" in f.message for f in findings
    )


def test_hbm_ceiling_slack_is_not_a_finding(report, manifest_entries):
    entries = copy.deepcopy(manifest_entries)
    entries[spmd.FLEET_ENTRY]["metrics"]["hbm_temp_bytes"] += 1 << 20
    findings, notes = spmd.budget_findings(
        report["measured"], budgets_mod.BudgetManifest(entries)
    )
    assert not any(f.rule == "spmd-hbm" for f in findings)
    assert any("hbm_temp_bytes" in n for n in notes)


def test_hbm_agrees_with_cost_catalog_helper(report):
    """The shared aot._cost_blocks helper (which fills aot_manifest.json)
    must extract the same bytes the tier measures — the cross-check
    measure() runs; here pinned directly for one program."""
    from karpenter_tpu.solver import aot

    prog = next(
        p for p in spmd._programs()
        if p.name == "spmd:solve_scan[relax=False]"
    )
    _, compiled = spmd.compile_program(prog)
    _, mem = aot._cost_blocks(compiled)
    assert mem["argument_size_in_bytes"] == report["measured"][prog.name][
        "hbm_argument_bytes"
    ]


def test_hbm_manifest_row_without_memory_is_flagged(monkeypatch, report):
    """A live aot_manifest.json row recorded by THIS jax/backend but
    lacking memory data means the capacity catalog has holes — flagged.
    An absent or other-backend manifest passes vacuously."""
    from karpenter_tpu.solver import aot

    rows = {
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "combos": {"solve@P=64": {"signature": "x", "seconds": 1.0}},
    }
    monkeypatch.setattr(aot, "load_manifest", lambda cache_dir: rows)
    measured = {
        k: copy.deepcopy(v) for k, v in report["measured"].items()
    }
    prog = next(
        p for p in spmd._programs()
        if p.name == "spmd:solve_scan[relax=False]"
    )
    _, compiled = spmd.compile_program(prog)
    findings = spmd._hbm_cross_checks(
        {prog.name: measured[prog.name]}, {prog.name: compiled}, [], set()
    )
    assert any("lacks memory data" in f.message for f in findings)
    # other-backend rows are not this backend's contract
    rows["backend"] = "not-this-backend"
    findings = spmd._hbm_cross_checks(
        {prog.name: measured[prog.name]}, {prog.name: compiled}, [], set()
    )
    assert not any("lacks memory data" in f.message for f in findings)


# ---------------------------------------------------------------------------
# budget mechanics shared with the IR tier (scoped manifest)


def test_scoped_manifest_splits_tiers():
    m = budgets_mod.BudgetManifest(
        {
            "solve_scan[relax=False]": {"justification": "ir", "metrics": {}},
            "spmd:solve_scan[relax=False]": {
                "justification": "spmd", "metrics": {},
            },
        }
    )
    assert set(m.scoped(spmd=True).entries) == {"spmd:solve_scan[relax=False]"}
    assert set(m.scoped(spmd=False).entries) == {"solve_scan[relax=False]"}


def test_render_carries_sibling_tier_entries():
    """--write-budgets under either tier must not truncate the other
    tier's half of the shared manifest."""
    existing = budgets_mod.BudgetManifest(
        {
            "ir_entry": {"justification": "keep me", "metrics": {"scans": 1}},
            "spmd:old": {"justification": "stale spmd", "metrics": {}},
        }
    )
    data = budgets_mod.BudgetManifest.render(
        {"spmd:new": {"donated_args": 0}}, existing, spmd_scope=True
    )
    assert set(data["entries"]) == {"ir_entry", "spmd:new"}
    assert data["entries"]["ir_entry"]["justification"] == "keep me"
    data = budgets_mod.BudgetManifest.render(
        {"ir_entry": {"scans": 2}}, existing, spmd_scope=False
    )
    assert set(data["entries"]) == {"ir_entry", "spmd:old"}


def test_partial_run_does_not_police_orphans(report, manifest_entries):
    measured = {
        k: copy.deepcopy(v)
        for k, v in report["measured"].items()
        if k != spmd.FLEET_ENTRY
    }
    findings, _ = spmd.budget_findings(
        measured,
        budgets_mod.BudgetManifest(copy.deepcopy(manifest_entries)),
        rule_ids={"spmd-hbm"},
    )
    assert not any("matches no traced entry point" in f.message for f in findings)
    findings_full, _ = spmd.budget_findings(
        measured, budgets_mod.BudgetManifest(copy.deepcopy(manifest_entries))
    )
    assert any(
        "matches no traced entry point" in f.message for f in findings_full
    )


def test_compile_failure_is_not_reported_as_orphan(report, manifest_entries):
    measured = {
        k: copy.deepcopy(v)
        for k, v in report["measured"].items()
        if k != spmd.FLEET_ENTRY
    }
    findings, _ = spmd.budget_findings(
        measured,
        budgets_mod.BudgetManifest(copy.deepcopy(manifest_entries)),
        errored={spmd.FLEET_ENTRY},
    )
    assert not any(spmd.FLEET_ENTRY in f.message for f in findings)


# ---------------------------------------------------------------------------
# spmd-launch-lock: synthetic dispatch sites


def _lock_findings(tmp_path, source: str):
    root = tmp_path / "repo"
    path = root / "karpenter_tpu" / "solver" / "snippet.py"
    path.parent.mkdir(parents=True)
    path.write_text(textwrap.dedent(source), encoding="utf-8")
    config = engine.Config.for_repo(str(root))
    findings, errors = engine.analyze_files(
        [str(path)], config, rules=[spmd.LaunchLockRule()]
    )
    assert errors == []
    return findings


def test_launch_lock_flags_unlocked_dispatch(tmp_path):
    findings = _lock_findings(
        tmp_path,
        """\
        def go(tb, st_b, xs_b):
            st_b, xs_b = shard_lanes(st_b, xs_b)
            out = fleet_dispatch(tb, st_b, xs_b)
            return out
        """,
    )
    assert len(findings) == 1
    assert findings[0].rule == "spmd-launch-lock"
    assert "outside" in findings[0].message


def test_launch_lock_flags_missing_fetch(tmp_path):
    findings = _lock_findings(
        tmp_path,
        """\
        def go(tb, st_b, xs_b):
            st_b, xs_b = shard_lanes(st_b, xs_b)
            with _MESH_DISPATCH_LOCK:
                out = fleet_dispatch(tb, st_b, xs_b)
            return out
        """,
    )
    assert len(findings) == 1
    assert "fetches no result" in findings[0].message


def test_launch_lock_negative_locked_with_fetch(tmp_path):
    findings = _lock_findings(
        tmp_path,
        """\
        def go(tb, st_b, xs_b, sharded):
            st_b, xs_b = shard_lanes(st_b, xs_b)
            with _MESH_DISPATCH_LOCK if sharded else contextlib.nullcontext():
                out = fleet_dispatch(tb, st_b, xs_b)
                out = jax.device_get(out)
            return out
        """,
    )
    assert findings == []


def test_launch_lock_negative_unsharded_scope(tmp_path):
    """fleet_dispatch over operands never derived from shard_lanes in
    this scope is a single-device dispatch — no lock required (the
    fleet.py contract is about SHARDED launches)."""
    findings = _lock_findings(
        tmp_path,
        """\
        def go(tb, st_b, xs_b):
            out = fleet_dispatch(tb, st_b, xs_b)
            return out

        def other(st_b, xs_b):
            st_b, xs_b = shard_lanes(st_b, xs_b)
            return jax.device_put(st_b, None)
        """,
    )
    assert findings == []


def test_launch_lock_module_level_scope(tmp_path):
    """Module-level (script-style) dispatches are checked too — the
    __graft_entry__.py dry run was exactly this shape."""
    findings = _lock_findings(
        tmp_path,
        """\
        st_b, xs_b = shard_lanes(st_b, xs_b)
        out = fleet_dispatch(tb, st_b, xs_b)
        """,
    )
    assert len(findings) == 1


def test_launch_lock_suppression_comment(tmp_path):
    findings = _lock_findings(
        tmp_path,
        """\
        def go(tb, st_b, xs_b):
            st_b, xs_b = shard_lanes(st_b, xs_b)
            out = fleet_dispatch(tb, st_b, xs_b)  # graftlint: disable=spmd-launch-lock
            return out
        """,
    )
    assert findings == []


def test_launch_lock_repo_is_clean(report):
    assert not any(
        f.rule == "spmd-launch-lock" for f in report["all_findings"]
    )


# ---------------------------------------------------------------------------
# CLI


def _stub_measure(report):
    measured = {k: copy.deepcopy(v) for k, v in report["measured"].items()}

    def stub(rule_ids=None):
        return copy.deepcopy(measured), [], [], set()

    return stub


def test_cli_spmd_full_tree_clean(capsys, monkeypatch, report):
    # reuse the fixture's measurements — the CLI wiring under test is
    # budgets/baseline/exit-code plumbing, not the compiles themselves
    monkeypatch.setattr(spmd, "measure", _stub_measure(report))
    assert graftlint_main(["--spmd", "--root", REPO_ROOT]) == 0
    assert "0 findings" in capsys.readouterr().out


def test_cli_spmd_rejects_paths_and_changed_only(capsys):
    assert graftlint_main(["--spmd", "--root", REPO_ROOT, "x.py"]) == 2
    assert (
        graftlint_main(["--spmd", "--root", REPO_ROOT, "--changed-only"])
        == 2
    )


def test_cli_spmd_rejects_unknown_rule_id(capsys):
    rc = graftlint_main(
        ["--spmd", "--root", REPO_ROOT, "--rules", "spmd-collective"]
    )
    assert rc == 2
    assert "unknown SPMD rule" in capsys.readouterr().err


def test_cli_spmd_compile_error_exits_2(monkeypatch, capsys):
    """Exit-code contract: compile errors dominate comparison findings."""

    def boom(rule_ids=None):
        return {}, [], ["spmd:fleet_solve_scan[B=8,sharded]: RuntimeError: x"], {
            "spmd:fleet_solve_scan[B=8,sharded]"
        }

    monkeypatch.setattr(spmd, "measure", boom)
    rc = graftlint_main(["--spmd", "--root", REPO_ROOT])
    assert rc == 2
    assert "compile error" in capsys.readouterr().out


def test_cli_spmd_budget_regression_exits_1(
    tmp_path, report, monkeypatch, capsys
):
    """A doctored manifest (one ceiling below the measurement) must fail
    the CLI gate — the end-to-end positive for the budget rules."""
    monkeypatch.setattr(spmd, "measure", _stub_measure(report))
    entries = {
        name: copy.deepcopy(e)
        for name, e in report["manifest"].entries.items()
    }
    got = report["measured"][spmd.FLEET_ENTRY]["hbm_argument_bytes"]
    entries[spmd.FLEET_ENTRY]["metrics"]["hbm_argument_bytes"] = got - 1
    p = tmp_path / "kernel_budgets.json"
    p.write_text(
        budgets_mod.BudgetManifest.dumps({"entries": entries}),
        encoding="utf-8",
    )
    rc = graftlint_main(
        ["--spmd", "--root", REPO_ROOT, "--budgets", str(p), "--json"]
    )
    assert rc == 1
    data = json.loads(capsys.readouterr().out)
    assert any(
        "hbm_argument_bytes" in f["message"] for f in data["findings"]
    )


def test_cli_spmd_collective_injection_exits_1(
    tmp_path, report, monkeypatch, capsys
):
    """The headline doctored fixture: a collective appearing in the
    lane-sharded fleet program (simulated at the measurement layer —
    the compiled-program census is exercised directly above) fails the
    gate with an exact structure-mismatch."""
    measured = {k: copy.deepcopy(v) for k, v in report["measured"].items()}
    measured[spmd.FLEET_ENTRY]["collectives_all_reduce"] = 1

    monkeypatch.setattr(
        spmd,
        "measure",
        lambda rule_ids=None: (copy.deepcopy(measured), [], [], set()),
    )
    rc = graftlint_main(["--spmd", "--root", REPO_ROOT, "--json"])
    assert rc == 1
    data = json.loads(capsys.readouterr().out)
    assert any(
        f["rule"] == "spmd-collectives" for f in data["findings"]
    )


def test_cli_spmd_donation_injection_exits_1(report, monkeypatch, capsys):
    measured = {k: copy.deepcopy(v) for k, v in report["measured"].items()}
    measured["spmd:solve_scan[relax=False]"]["donated_args"] = 1
    monkeypatch.setattr(
        spmd,
        "measure",
        lambda rule_ids=None: (copy.deepcopy(measured), [], [], set()),
    )
    rc = graftlint_main(["--spmd", "--root", REPO_ROOT, "--json"])
    assert rc == 1
    data = json.loads(capsys.readouterr().out)
    assert any(f["rule"] == "spmd-donation" for f in data["findings"])


def test_cli_spmd_malformed_budgets_exits_2(tmp_path, capsys):
    bad = tmp_path / "kernel_budgets.json"
    bad.write_text('{"entries": {,}}', encoding="utf-8")
    rc = graftlint_main(
        ["--spmd", "--root", REPO_ROOT, "--budgets", str(bad)]
    )
    assert rc == 2
    err = capsys.readouterr().err
    assert "cannot parse" in err and str(bad) in err


def test_cli_spmd_write_budgets_rejects_rule_subset(tmp_path, capsys):
    rc = graftlint_main(
        [
            "--spmd",
            "--write-budgets",
            "--rules",
            "spmd-hbm",
            "--root",
            REPO_ROOT,
            "--budgets",
            str(tmp_path / "b.json"),
        ]
    )
    assert rc == 2
    assert not (tmp_path / "b.json").exists()


def test_cli_spmd_write_budgets_preserves_ir_half(
    tmp_path, report, monkeypatch
):
    """`--spmd --write-budgets` rewrites only the `spmd:` entries; the
    IR tier's half of the shared file survives byte-for-byte."""
    monkeypatch.setattr(spmd, "measure", _stub_measure(report))
    src = json.load(
        open(os.path.join(REPO_ROOT, "kernel_budgets.json"), encoding="utf-8")
    )
    p = tmp_path / "kernel_budgets.json"
    p.write_text(
        budgets_mod.BudgetManifest.dumps(src), encoding="utf-8"
    )
    rc = graftlint_main(
        ["--spmd", "--write-budgets", "--root", REPO_ROOT, "--budgets", str(p)]
    )
    assert rc == 0
    after = json.loads(p.read_text(encoding="utf-8"))
    ir_before = {
        k: v
        for k, v in src["entries"].items()
        if not k.startswith(budgets_mod.SPMD_PREFIX)
    }
    ir_after = {
        k: v
        for k, v in after["entries"].items()
        if not k.startswith(budgets_mod.SPMD_PREFIX)
    }
    assert ir_after == ir_before
    assert set(after["entries"]) == set(src["entries"])


def test_cli_mutually_exclusive_tier_flags(capsys):
    assert graftlint_main(["--spmd", "--ir", "--root", REPO_ROOT]) == 2
    assert "mutually" in capsys.readouterr().err


def test_cli_list_rules_shows_spmd(capsys):
    assert graftlint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in spmd.SPMD_RULES:
        assert rid in out
    assert "[spmd]" in out


# ---------------------------------------------------------------------------
# --all merge (stubbed tiers: the merge/exit/seconds plumbing under test)


def _stub_tier_reports(monkeypatch, report, spmd_findings=()):
    import karpenter_tpu.analysis.__main__ as cli
    from karpenter_tpu.analysis import ir, locks, proto

    flat = {
        "findings": [],
        "stale": [],
        "unjustified": [],
        "errors": [],
        "total": 0,
    }
    deep = {
        "findings": list(spmd_findings),
        "all_findings": list(spmd_findings),
        "stale": [],
        "unjustified": [],
        "budget_unjustified": [],
        "improvements": [],
        "errors": [],
        "measured": {},
    }
    monkeypatch.setattr(cli, "run_analysis", lambda *a, **k: dict(flat))
    monkeypatch.setattr(
        locks, "run_race_analysis", lambda *a, **k: dict(flat)
    )
    monkeypatch.setattr(ir, "run_ir_analysis", lambda *a, **k: dict(deep, findings=[], all_findings=[]))
    monkeypatch.setattr(spmd, "run_spmd_analysis", lambda *a, **k: deep)
    monkeypatch.setattr(
        proto,
        "run_proto_analysis",
        lambda *a, **k: dict(
            flat,
            all_findings=[],
            scenarios={},
            properties={},
            conformance={},
        ),
    )


def test_cli_all_merges_five_tiers_with_seconds(
    monkeypatch, capsys, report
):
    _stub_tier_reports(monkeypatch, report)
    rc = graftlint_main(["--all", "--root", REPO_ROOT, "--json"])
    assert rc == 0
    data = json.loads(capsys.readouterr().out)
    assert set(data) >= {"ast", "race", "ir", "spmd", "proto", "exit_code"}
    for tier in ("ast", "race", "ir", "spmd", "proto"):
        assert data[tier]["exit_code"] == 0
        # the drive-by: per-tier wall-clock in the merged payload
        assert isinstance(data[tier]["seconds"], float)


def test_cli_all_spmd_finding_sets_worst_exit(monkeypatch, capsys, report):
    from karpenter_tpu.analysis.engine import Finding

    _stub_tier_reports(
        monkeypatch,
        report,
        spmd_findings=[
            Finding(
                rule="spmd-collectives",
                path="karpenter_tpu/solver/fleet.py",
                line=1,
                message="doctored",
                text="spmd:x",
            )
        ],
    )
    rc = graftlint_main(["--all", "--root", REPO_ROOT, "--json"])
    assert rc == 1
    data = json.loads(capsys.readouterr().out)
    assert data["spmd"]["exit_code"] == 1
    assert data["exit_code"] == 1


def test_cli_all_rejects_write_and_subset_flags(capsys):
    assert graftlint_main(["--all", "--root", REPO_ROOT, "--rules", "x"]) == 2
    assert (
        graftlint_main(["--all", "--root", REPO_ROOT, "--write-budgets"])
        == 2
    )
