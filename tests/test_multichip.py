"""Multi-device SPMD correctness: the full solve scan sharded over an
8-device virtual CPU mesh (conftest sets xla_force_host_platform_device_count)
must be bit-identical to the unsharded run.

This is the in-tree counterpart of __graft_entry__.dryrun_multichip — same
sharding layout (claim-slot rows sharded, tables replicated, hostname counts
sharded along the slot axis), asserted as a pytest so regressions surface in
CI rather than only in the driver's dryrun."""

from __future__ import annotations

import numpy as np
import pytest

import __graft_entry__ as ge


@pytest.fixture(scope="module")
def jax_mesh():
    import jax
    from jax.sharding import Mesh

    devices = jax.devices("cpu")
    if len(devices) < 8:
        pytest.skip(f"need 8 virtual CPU devices, have {len(devices)}")
    return Mesh(np.array(devices[:8]), ("slots",))


def test_sharded_solve_scan_matches_unsharded(jax_mesh):
    import jax

    from karpenter_tpu.solver import tpu_kernel as K

    tb, st, xs, _, _ = ge._small_problem(n_pods=16)
    assert st.active.shape[0] % 8 == 0

    st_ref, kinds_ref, slots_ref, _, _ = jax.jit(K.solve_scan)(tb, st, xs)
    kinds_ref, slots_ref = np.asarray(kinds_ref), np.asarray(slots_ref)
    # sanity: the problem actually schedules pods
    assert int(np.sum(kinds_ref != K.KIND_FAIL)) > 0

    tb_s, st_s, xs_s = ge.shard_problem(jax_mesh, tb, st, xs)
    with jax_mesh:
        st_out, kinds, slots, _, _ = jax.jit(K.solve_scan)(tb_s, st_s, xs_s)
        jax.block_until_ready(st_out)

    assert np.array_equal(np.asarray(kinds), kinds_ref)
    assert np.array_equal(np.asarray(slots), slots_ref)
    assert int(st_out.n_claims) == int(st_ref.n_claims)
    assert np.array_equal(np.asarray(st_out.count), np.asarray(st_ref.count))
    assert np.array_equal(np.asarray(st_out.crequests), np.asarray(st_ref.crequests))


def test_dryrun_multichip_entrypoint():
    """The driver-facing function end-to-end (platform already CPU under
    conftest; the env setup inside is idempotent)."""
    ge.dryrun_multichip(8)
