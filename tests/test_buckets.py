"""Shape-bucket parity matrix (solver/buckets.py; ISSUE 8).

Two contracts:

1. Decision invisibility: for problems straddling every bucketed axis's
   pow-2 edge (pods N, instance types I, existing nodes E — just below /
   at / above the edge), the bucketed TPU solve is bit-identical to the
   oracle. The pads are sentinel rows the kernel provably cannot select;
   this matrix is the empirical proof the module docstring's arguments
   point at.

2. Shape stability: two DIFFERENT real sizes in the same bucket hit the
   identical compiled program — zero jaxpr traces and zero compiles on
   the second solve, counted with the same jax.monitoring counter the
   graftlint IR tier budgets (analysis/ir.py trace_events), so this gate
   and `graftlint --ir` cannot drift on what "a retrace" means.
"""

from __future__ import annotations

import numpy as np
import pytest

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.solver import buckets
from karpenter_tpu.solver.nodes import StateNodeView
from karpenter_tpu.solver.oracle import Scheduler
from karpenter_tpu.solver.topology import Topology
from karpenter_tpu.solver.tpu import TpuScheduler
from karpenter_tpu.testing import fixtures


def _views(n: int, its) -> list[StateNodeView]:
    it = its[0]
    return [
        StateNodeView(
            name=f"bucket-node-{i}",
            node_labels={well_known.TOPOLOGY_ZONE_LABEL_KEY: "test-zone-a"},
            labels={
                well_known.TOPOLOGY_ZONE_LABEL_KEY: "test-zone-a",
                well_known.INSTANCE_TYPE_LABEL_KEY: it.name,
                well_known.NODEPOOL_LABEL_KEY: "default",
            },
            available=dict(it.allocatable()),
            capacity=dict(it.capacity),
            initialized=True,
        )
        for i in range(n)
    ]


def _solve_pair(n_pods: int, n_types: int, n_existing: int):
    """(tpu partition, oracle partition) for one problem size. Fresh
    object graphs per side — shared mutable state would void the
    comparison."""

    def build():
        fixtures.reset_rng(11)
        its = construct_instance_types(sizes=[2, 8])[:n_types]
        pool = fixtures.node_pool(name="default")
        pods = fixtures.make_diverse_pods(n_pods)
        views = _views(n_existing, its) if n_existing else None
        topo = Topology(
            [pool], {"default": its}, pods, state_node_views=views
        )
        return [pool], {"default": its}, pods, views, topo

    def parts(r, pods):
        names = {p.uid: p.name for p in pods}
        claims = sorted(
            tuple(sorted(names[p.uid] for p in c.pods))
            for c in r.new_node_claims
        )
        existing = sorted(
            (n.name, tuple(sorted(names[p.uid] for p in n.pods)))
            for n in r.existing_nodes
        )
        return claims, existing, sorted(r.pod_errors)

    pools, ibp, pods, views, topo = build()
    r_t = TpuScheduler(pools, ibp, topo, views).solve(pods)
    out_t = parts(r_t, pods)
    pools, ibp, pods, views, topo = build()
    r_o = Scheduler(pools, ibp, topo, views).solve(pods)
    return out_t, parts(r_o, pods)


def _edge_cases(edge: int) -> tuple[int, int, int]:
    return (edge - 1, edge, edge + 1)


@pytest.mark.parametrize("n_pods", _edge_cases(16))
def test_pod_bucket_edges_oracle_parity(n_pods):
    """Pods just below/at/above a pow-2 edge decide identically."""
    got, want = _solve_pair(n_pods, n_types=12, n_existing=3)
    assert got == want


@pytest.mark.parametrize("n_existing", _edge_cases(8))
def test_existing_bucket_edges_oracle_parity(n_existing):
    """Existing-node slots straddling the E rung decide identically
    (padded slots carry eavail=-1 and all-False tolerations)."""
    got, want = _solve_pair(24, n_types=12, n_existing=n_existing)
    assert got == want


@pytest.mark.parametrize("n_types", _edge_cases(8))
def test_type_bucket_edges_oracle_parity(n_types):
    """Instance types straddling the I rung decide identically (padded
    types are members of no template; padded offerings are ovalid=False)."""
    got, want = _solve_pair(24, n_types=n_types, n_existing=0)
    assert got == want


def test_bucketing_is_on_by_default():
    assert buckets.enabled()


def test_padded_problem_shapes_are_rungs():
    """The encoded problem's bucketed axes land on pow-2 rungs and the
    sentinel rows carry their documented inert values."""
    from karpenter_tpu.solver.tpu_problem import encode_problem

    fixtures.reset_rng(11)
    its = construct_instance_types(sizes=[2])[:9]  # 9 types -> rung 16
    pool = fixtures.node_pool(name="default")
    pods = fixtures.make_diverse_pods(10)
    views = _views(3, its)  # 3 existing -> rung 8
    topo = Topology([pool], {"default": its}, pods, state_node_views=views)
    sched = TpuScheduler([pool], {"default": its}, topo, views)
    p = encode_problem(sched.oracle, pods)
    assert p.num_types == 16
    assert p.num_existing == 8
    assert p.otype.shape[0] == buckets.bucket(p.num_offerings_real)
    # padded types belong to no template; padded offerings are invalid
    assert not np.unpackbits(
        p.ttypes.astype("<u4").view(np.uint8), axis=-1, bitorder="little"
    )[:, 9:].any()
    assert not p.ovalid[p.num_offerings_real :].any()
    assert p.ovalid[: p.num_offerings_real].all()
    # padded existing slots can fit nothing
    assert (p.eavail[3:] == -1).all()
    # vocab rungs: key count is a rung, phantom keys hold no values
    assert p.vocab.num_keys == buckets.bucket_keys(p.vocab.num_keys)
    for kid in range(p.vocab.num_keys):
        if p.vocab.keys[kid].startswith(buckets.PAD_KEY_PREFIX):
            assert p.vocab.values[kid] == []


def test_same_bucket_sizes_share_the_compiled_program():
    """Two different real sizes in one bucket: the second solve traces
    and compiles NOTHING (the jax.monitoring counter test_compilecache
    and the ir-retrace budget also ride)."""
    from karpenter_tpu.analysis.ir import trace_events

    def solve(n_pods):
        fixtures.reset_rng(11)
        its = construct_instance_types(sizes=[2])
        pool = fixtures.node_pool(name="default")
        pods = fixtures.make_generic_pods(n_pods)
        topo = Topology([pool], {"default": its}, pods)
        return TpuScheduler([pool], {"default": its}, topo).solve(pods)

    solve(12)  # warm the 16-bucket programs
    with trace_events() as ev:
        r = solve(14)  # same rung, different real size
    assert sum(len(c.pods) for c in r.new_node_claims) == 14
    assert ev.traces == 0, f"same-bucket solve traced {ev.traces} programs"
    assert ev.compiles == 0
