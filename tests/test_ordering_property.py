"""Property test for the shared-comparator invariant (CLAUDE.md: the
oracle and the TPU path MUST sort with the same key or parity breaks).

graftlint's shared-comparator rule enforces this statically (ordering may
only flow through solver/ordering.py); this module checks the RUNTIME
half independently: across seeded randomized pod sets, the oracle's sort
(solver/oracle.py Queue, sorted by ffd_sort_key) and the TPU path's
vectorized sort (solver/tpu.py:666 via ffd_order_cols) must produce the
IDENTICAL permutation — not merely an equivalent packing.
"""

from __future__ import annotations

import random

import pytest

from karpenter_tpu.api.objects import Toleration
from karpenter_tpu.solver.oracle import Queue
from karpenter_tpu.solver.nodes import PodData
from karpenter_tpu.scheduling import Requirements
from karpenter_tpu.solver.ordering import (
    ffd_order,
    ffd_order_cols,
    ffd_sort_key,
    pod_class_signature,
)
from karpenter_tpu.testing import fixtures
from karpenter_tpu.utils import resources as res


def _random_pods(rng: random.Random, n: int, ts_mode: str) -> list:
    """Pods engineered to stress every tie-break level: a small discrete
    CPU/memory grid forces request ties, a few scheduling-class variants
    force class-signature grouping, and timestamps either fit float64 or
    (ts_mode="wide") exceed 2^53 to force ffd_order_cols' exact-sort
    fallback (nanosecond epochs don't round-trip through float64)."""
    pods = []
    for i in range(n):
        cpu = rng.choice(["100m", "250m", "1", "2"])
        mem = rng.choice(["128Mi", "1Gi"])
        variant = rng.randrange(3)
        selector = {"disktype": "ssd"} if variant == 1 else None
        tols = (
            [Toleration(key="dedicated", operator="Exists")]
            if variant == 2
            else None
        )
        if ts_mode == "wide":
            # > 2^53: adjacent ints collapse in float64, so the lexsort
            # column would be lossy — the comparator must detect it
            ts = (1 << 53) + rng.randrange(0, 64)
        else:
            ts = rng.randrange(0, 1000)
        p = fixtures.pod(
            name=f"p-{i}",
            requests={"cpu": cpu, "memory": mem},
            node_selector=selector,
            tolerations=tols,
            creation_timestamp=ts,
        )
        p.metadata.uid = f"uid-{rng.randrange(10**9):09d}-{i}"
        pods.append(p)
    return pods


def _requests_of(pod):
    return res.requests_for_pods([pod])


@pytest.mark.parametrize("seed", [0, 1, 7, 42, 1337])
@pytest.mark.parametrize("ts_mode", ["narrow", "wide"])
def test_oracle_and_tpu_orderings_identical(seed, ts_mode):
    rng = random.Random(seed)
    pods = _random_pods(rng, 200, ts_mode)

    # oracle side: solver/oracle.py Queue sorts by ffd_sort_key
    oracle_order = sorted(
        range(len(pods)),
        key=lambda i: ffd_sort_key(pods[i], _requests_of(pods[i])),
    )

    # TPU side: solver/tpu.py:666 builds columns and calls ffd_order_cols;
    # ffd_order gathers the same columns from pod objects
    tpu_order = ffd_order(pods, _requests_of)

    assert tpu_order == oracle_order


@pytest.mark.parametrize("seed", [3, 99])
def test_queue_pops_in_comparator_order(seed):
    """The oracle's actual Queue (scheduler entry) agrees with the raw
    comparator — no hidden re-keying between ffd_sort_key and the solve
    loop (queue.go:72-108)."""
    rng = random.Random(seed)
    pods = _random_pods(rng, 64, "narrow")
    data = {
        p.uid: PodData(
            requests=_requests_of(p),
            requirements=Requirements(),
            strict_requirements=Requirements(),
        )
        for p in pods
    }
    q = Queue(list(pods), data)
    popped = []
    while True:
        p = q.pop()
        if p is None:
            break
        popped.append(p.uid)
    expected = [
        p.uid
        for p in sorted(pods, key=lambda p: ffd_sort_key(p, _requests_of(p)))
    ]
    assert popped == expected


def test_wide_timestamps_hit_exact_fallback():
    """ffd_order_cols must not silently lexsort a lossy float64 timestamp
    column: two pods whose nanosecond timestamps differ by 1 ULP-sub-f64
    must still order by the exact integer (solver/ordering.py:228-239)."""
    rng = random.Random(5)
    pods = _random_pods(rng, 2, "narrow")
    for p in pods:
        p.requests = res.parse_list({"cpu": "1", "memory": "1Gi"})
        p.node_selector = {}
        p.tolerations = []
        for attr in ("_ktpu_class_key", "_ktpu_class_repr", "_ktpu_class_sig"):
            if hasattr(p, attr):
                delattr(p, attr)
    base = 1 << 54  # adjacent ints are NOT representable in float64
    pods[0].metadata.creation_timestamp = base + 1
    pods[1].metadata.creation_timestamp = base
    sig = [pod_class_signature(p) for p in pods]
    reqs = [_requests_of(p) for p in pods]
    order = ffd_order_cols(
        [r[res.CPU] for r in reqs],
        [r[res.MEMORY] for r in reqs],
        sig,
        [p.metadata.creation_timestamp for p in pods],
        [p.uid for p in pods],
    )
    assert order == [1, 0]  # exact integer order, not float64-collapsed
