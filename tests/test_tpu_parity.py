"""Oracle/TPU parity: the batched solver must make bit-identical decisions.

Every scenario runs the same problem through the oracle Scheduler and the
TpuScheduler and compares the full assignment (pod -> node partition), the
surviving instance types per claim, and accumulated requests. The scenarios
cover the reference benchmark's pod classes (scheduling_benchmark_test.go:257
makeDiversePods) plus existing nodes, limits, weights, taints, and minValues.
"""

from __future__ import annotations

import pytest

from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import (
    NodeSelectorRequirement,
    Operator,
    Taint,
    TaintEffect,
    Toleration,
)
from karpenter_tpu.cloudprovider.fake import instance_types as fake_types
from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.solver.nodes import StateNodeView
from karpenter_tpu.solver.oracle import Scheduler, SchedulerOptions
from karpenter_tpu.solver.topology import Topology
from karpenter_tpu.solver.tpu import TpuScheduler
from karpenter_tpu.solver.tpu_problem import UnsupportedBySolver
from karpenter_tpu.testing import fixtures
from karpenter_tpu.utils import resources as res


def run_both(make_problem, options=None):
    """Build the problem twice (fresh Topology per scheduler) and solve."""
    results = []
    for cls in (Scheduler, TpuScheduler):
        node_pools, its_by_pool, pods, views, daemons = make_problem()
        topo = Topology(
            node_pools,
            its_by_pool,
            pods,
            state_node_views=views,
            ignore_preferences=bool(options and options.ignore_preferences),
        )
        s = cls(node_pools, its_by_pool, topo, views, daemons, options)
        results.append((s.solve(pods), pods))
    return results


def assert_parity(results, allow_errors=False):
    (orc, orc_pods), (tpu, tpu_pods) = results
    orc_names = {p.uid: p.name for p in orc_pods}
    tpu_names = {p.uid: p.name for p in tpu_pods}
    assert {orc_names[u] for u in orc.pod_errors} == {
        tpu_names[u] for u in tpu.pod_errors
    }
    if not allow_errors:
        assert not orc.pod_errors, orc.pod_errors
    # node partition by pod-name sets
    def parts(r):
        out = []
        for c in r.new_node_claims:
            out.append(("new", tuple(sorted(p.name for p in c.pods))))
        for n in r.existing_nodes:
            if n.pods:
                out.append((n.name, tuple(sorted(p.name for p in n.pods))))
        return sorted(out)

    assert parts(orc) == parts(tpu)
    # per-claim surviving instance types + requests
    def claim_map(r):
        return {
            tuple(sorted(p.name for p in c.pods)): (
                [it.name for it in c.instance_type_options],
                dict(c.requests),
                c.template.nodepool_name,
            )
            for c in r.new_node_claims
        }

    assert claim_map(orc) == claim_map(tpu)


def kwok_problem(n_pods, maker=None, seed=42, pools=None, views=None, daemons=None):
    def make():
        fixtures.reset_rng(seed)
        its = construct_instance_types()
        node_pools = pools() if pools else [fixtures.node_pool(name="default")]
        pods = (maker or fixtures.make_diverse_pods)(n_pods)
        return (
            node_pools,
            {np.name: its for np in node_pools},
            pods,
            views() if views else None,
            daemons() if daemons else None,
        )

    return make


def test_generic_pods():
    assert_parity(run_both(kwok_problem(80, fixtures.make_generic_pods)))


def test_diverse_mix():
    assert_parity(run_both(kwok_problem(150)))


def test_zonal_spread():
    assert_parity(
        run_both(
            kwok_problem(
                60,
                lambda n: fixtures.make_topology_spread_pods(
                    n, well_known.TOPOLOGY_ZONE_LABEL_KEY
                ),
            )
        )
    )


def test_hostname_spread():
    assert_parity(
        run_both(
            kwok_problem(
                60,
                lambda n: fixtures.make_topology_spread_pods(
                    n, well_known.HOSTNAME_LABEL_KEY
                ),
            )
        )
    )


def test_zonal_self_affinity():
    assert_parity(
        run_both(
            kwok_problem(
                60,
                lambda n: fixtures.make_pod_affinity_pods(
                    n, well_known.TOPOLOGY_ZONE_LABEL_KEY
                ),
            )
        )
    )


def test_hostname_anti_affinity():
    assert_parity(
        run_both(
            kwok_problem(
                40,
                lambda n: fixtures.make_pod_anti_affinity_pods(
                    n, well_known.HOSTNAME_LABEL_KEY
                ),
            )
        )
    )


def test_nodepool_weights_and_requirements():
    def pools():
        return [
            fixtures.node_pool(
                name="small",
                weight=10,
                requirements=[
                    NodeSelectorRequirement(
                        well_known.TOPOLOGY_ZONE_LABEL_KEY,
                        Operator.IN,
                        ["test-zone-a", "test-zone-b"],
                    )
                ],
            ),
            fixtures.node_pool(name="big", weight=1),
        ]

    assert_parity(run_both(kwok_problem(60, pools=pools)))


def test_nodepool_limits():
    def pools():
        return [
            fixtures.node_pool(name="capped", weight=5, limits={"cpu": "30"}),
            fixtures.node_pool(name="overflow"),
        ]

    assert_parity(run_both(kwok_problem(80, pools=pools)))


def test_taints_and_tolerations():
    def pools():
        return [
            fixtures.node_pool(
                name="tainted",
                weight=10,
                taints=[Taint("dedicated", TaintEffect.NO_SCHEDULE, "gpu")],
            ),
            fixtures.node_pool(name="open"),
        ]

    def maker(n):
        fixtures.reset_rng(7)
        pods = fixtures.make_generic_pods(n)
        for i, p in enumerate(pods):
            if i % 3 == 0:
                p.tolerations.append(
                    Toleration(
                        key="dedicated",
                        operator="Equal",
                        value="gpu",
                        effect=TaintEffect.NO_SCHEDULE,
                    )
                )
        return pods

    assert_parity(run_both(kwok_problem(45, maker, pools=pools)))


def test_existing_nodes():
    def views():
        its = construct_instance_types()
        it = its[0]
        out = []
        for i in range(4):
            out.append(
                StateNodeView(
                    name=f"existing-{i}",
                    node_labels={well_known.TOPOLOGY_ZONE_LABEL_KEY: "test-zone-a"},
                    labels={
                        well_known.TOPOLOGY_ZONE_LABEL_KEY: "test-zone-a",
                        well_known.INSTANCE_TYPE_LABEL_KEY: it.name,
                        well_known.NODEPOOL_LABEL_KEY: "default",
                    },
                    available=dict(it.allocatable()),
                    capacity=dict(it.capacity),
                    initialized=True,
                )
            )
        return out

    assert_parity(run_both(kwok_problem(40, fixtures.make_generic_pods, views=views)))


def test_pod_node_selector():
    def maker(n):
        fixtures.reset_rng(13)
        pods = fixtures.make_generic_pods(n)
        for i, p in enumerate(pods):
            if i % 2 == 0:
                p.node_selector[well_known.TOPOLOGY_ZONE_LABEL_KEY] = "test-zone-b"
        return pods

    assert_parity(run_both(kwok_problem(30, maker)))


def test_unschedulable_pod_reports_error():
    def maker(n):
        fixtures.reset_rng(17)
        pods = fixtures.make_generic_pods(n)
        pods[0].requests = res.parse_list({"cpu": "10000"})  # fits nothing
        return pods

    assert_parity(run_both(kwok_problem(10, maker)), allow_errors=True)


def test_min_values():
    def pools():
        return [
            fixtures.node_pool(
                name="flexible",
                requirements=[
                    NodeSelectorRequirement(
                        well_known.INSTANCE_TYPE_LABEL_KEY,
                        Operator.EXISTS,
                        min_values=10,
                    )
                ],
            )
        ]

    assert_parity(run_both(kwok_problem(25, fixtures.make_generic_pods, pools=pools)))


def test_min_values_undefined_key_not_counted():
    """Regression (review finding): instance types that don't define a
    minValues key contribute NO values — an Exists encoding must not count
    the full vocab. Both solvers must fail these pods identically."""
    from karpenter_tpu.cloudprovider.types import InstanceTypes
    from karpenter_tpu.testing.fixtures import pod

    def make():
        fixtures.reset_rng(3)
        its = construct_instance_types(sizes=[4])
        # two values exist across types (so the template survives init with
        # minValues=2), but pods select custom=a: the claim's surviving set
        # is {custom=a types} ∪ {undefined types} -> distinct values {a}
        from karpenter_tpu.scheduling import Requirement as Req

        its[0].requirements.add(Req("example.com/custom", Operator.IN, ["a"]))
        its[1].requirements.add(Req("example.com/custom", Operator.IN, ["b"]))
        pools = [
            fixtures.node_pool(
                name="default",
                requirements=[
                    NodeSelectorRequirement(
                        "example.com/custom", Operator.EXISTS, min_values=2
                    )
                ],
            )
        ]
        pods = fixtures.make_generic_pods(6)
        for p in pods:
            p.node_selector["example.com/custom"] = "a"
        return pools, {"default": InstanceTypes(its)}, pods, None, None

    assert_parity(run_both(make), allow_errors=True)


def test_fallback_when_no_templates_survive():
    """All instance types filtered out by nodepool requirements -> the
    encoder must raise UnsupportedBySolver (oracle fallback), not crash."""
    fixtures.reset_rng(5)
    its = construct_instance_types(sizes=[2])
    pools = [
        fixtures.node_pool(
            name="default",
            requirements=[
                NodeSelectorRequirement(
                    well_known.TOPOLOGY_ZONE_LABEL_KEY, Operator.IN, ["no-such-zone"]
                )
            ],
        )
    ]
    pods = fixtures.make_generic_pods(4)
    topo = Topology(pools, {"default": its}, pods)
    t = TpuScheduler(pools, {"default": its}, topo)
    with pytest.raises(UnsupportedBySolver):
        t.solve(pods)


def test_preference_pods_match_oracle_on_kernel():
    """Round 4: preference pods ride the kernel (tier ladder in the step,
    tpu_kernel._step_relax mirrors scheduler.go:434 trySchedule — relax
    all the way per ATTEMPT on a copy, retry from tier 0 next round) and
    must make BIT-IDENTICAL decisions (CLAUDE.md parity invariant)."""
    assert_parity(
        run_both(kwok_problem(8, maker=fixtures.make_preference_pods))
    )


def test_preference_mix_matches_oracle_on_kernel():
    """Diverse pods + a relaxable tail in ONE kernel solve — the c6 bench
    shape in miniature, per-pod decision parity."""

    def mix(n):
        pods = fixtures.make_diverse_pods(n - 4)
        pods += fixtures.make_preference_pods(4)
        return pods

    assert_parity(run_both(kwok_problem(40, maker=mix)))


def test_adaptive_slots_overflow_retry():
    """Anti-affinity pods need one claim each; the adaptive claim-slot count
    starts below that (pods/4) and must grow via the kernel's overflow
    signal until the solve fits — results identical to the oracle."""

    def make():
        fixtures.reset_rng(31)
        its = construct_instance_types(sizes=[2, 8])
        np_ = fixtures.node_pool(name="default")
        pods = fixtures.make_pod_anti_affinity_pods(
            96, well_known.HOSTNAME_LABEL_KEY
        )
        return [np_], {"default": its}, pods, None, None

    assert_parity(run_both(make))


def test_dedup_decode_path_parity(monkeypatch):
    """The large-solve decode fetch (device-side row dedup + inverse
    rematerialization, tpu._dedup_decode_state) must be byte-equivalent to
    the raw fetch: same claims, same requirements, same surviving types.
    The threshold is lowered so a normal-size problem rides the dedup
    path."""
    from karpenter_tpu.solver import tpu as tpu_mod

    def solve_once():
        fixtures.reset_rng(77)
        its = construct_instance_types(sizes=[2, 8])
        pool = fixtures.node_pool(name="default")
        pods = fixtures.make_diverse_pods(120)
        topo = Topology([pool], {"default": its}, pods)
        s = TpuScheduler([pool], {"default": its}, topo)
        r = s.solve(pods)
        def claim_view(c):
            return (
                tuple(sorted(p.name for p in c.pods)),
                repr(sorted(str(c.requirements.get(k)) for k in c.requirements)),
                tuple(sorted(it.name for it in c.instance_type_options)),
                tuple(sorted(c.requests.items())),
            )
        return sorted(claim_view(c) for c in r.new_node_claims if c.pods)

    raw = solve_once()
    monkeypatch.setattr(tpu_mod, "_DEDUP_DECODE_MIN", 64)
    dedup = solve_once()
    assert raw == dedup


def test_overflow_growth_continuation_parity():
    """Overflow continuation (round 5): the runs kernel stops at the pod
    that found no free claim slot; the host pads the carried state and
    resumes from exactly that pod. Decisions are N-invariant (slot count
    only gates creation), so a deliberately undersized slot pool that
    forces several growth events must reproduce the oracle bit-for-bit."""
    from karpenter_tpu.cloudprovider.kwok import construct_instance_types
    from karpenter_tpu.solver.oracle import Scheduler, SchedulerOptions
    from karpenter_tpu.solver.topology import Topology
    from karpenter_tpu.solver.tpu import TpuScheduler
    from karpenter_tpu.testing import fixtures

    its = construct_instance_types(sizes=[2, 8])
    pool = fixtures.node_pool(name="default")

    def solve_with(cls, **kw):
        fixtures.reset_rng(11)
        pods = fixtures.make_diverse_pods(400)
        topo = Topology([pool], {"default": its}, pods)
        return cls([pool], {"default": its}, topo, **kw).solve(pods)

    opts = SchedulerOptions()
    opts.claim_slot_div = 64  # tiny start: forces growth mid-solve
    rt = solve_with(TpuScheduler, options=opts)
    ro = solve_with(Scheduler)

    def snap(r):
        out = {}
        for c in r.new_node_claims:
            group = tuple(sorted(p.name for p in c.pods))
            for p in c.pods:
                out[p.name] = group
        return out

    assert snap(rt) == snap(ro)
    assert len(rt.new_node_claims) == len(ro.new_node_claims)
    assert rt.pod_errors == ro.pod_errors


def test_single_step_overflow_pod_is_retried_not_failed():
    """A pod that overflows the slot pool in the EXACT per-pod path is not
    a decided failure: the kernel leaves ptr on it and the host retries it
    on the grown state (round-5 fix — advancing past it could let the
    stall check end the solve with the pod wrongly unschedulable).
    70 hostname-anti-affinity pods (one claim each, exact path) against a
    64-slot start must all schedule, matching the oracle."""
    from karpenter_tpu.cloudprovider.kwok import construct_instance_types
    from karpenter_tpu.solver.oracle import Scheduler, SchedulerOptions
    from karpenter_tpu.solver.topology import Topology
    from karpenter_tpu.solver.tpu import TpuScheduler
    from karpenter_tpu.testing import fixtures

    its = construct_instance_types(sizes=[2])
    pool = fixtures.node_pool(name="default")

    def make_pods():
        fixtures.reset_rng(5)
        from karpenter_tpu.api import labels as well_known

        return fixtures.make_pod_anti_affinity_pods(
            70, well_known.HOSTNAME_LABEL_KEY
        )

    opts = SchedulerOptions()
    opts.claim_slot_div = 10_000  # floor of 64 slots -> overflow at pod 65
    pods = make_pods()
    topo = Topology([pool], {"default": its}, pods)
    rt = TpuScheduler([pool], {"default": its}, topo, options=opts).solve(pods)
    pods2 = make_pods()
    topo2 = Topology([pool], {"default": its}, pods2)
    ro = Scheduler([pool], {"default": its}, topo2).solve(pods2)
    assert len(rt.pod_errors) == len(ro.pod_errors) == 0
    assert len(rt.new_node_claims) == len(ro.new_node_claims) == 70


def test_host_ports_with_existing_nodes_and_claim_reuse():
    """Host-port usage seeds from existing nodes screen candidates, and
    committed ports accumulate on claim slots (hostportusage.go:35) —
    placements bit-identical to the oracle."""
    from karpenter_tpu.cloudprovider.kwok import construct_instance_types
    from karpenter_tpu.solver.nodes import StateNodeView
    from karpenter_tpu.solver.oracle import Scheduler, SchedulerOptions
    from karpenter_tpu.solver.topology import Topology
    from karpenter_tpu.solver.tpu import TpuScheduler
    from karpenter_tpu.testing import fixtures
    from karpenter_tpu.api import labels as wk

    its = construct_instance_types(sizes=[2, 8])

    def make_view():
        v = StateNodeView(
            name="existing-1",
            labels={
                wk.TOPOLOGY_ZONE_LABEL_KEY: "test-zone-a",
                wk.HOSTNAME_LABEL_KEY: "existing-1",
                wk.INSTANCE_TYPE_LABEL_KEY: "c-2x-amd64-linux",
                wk.CAPACITY_TYPE_LABEL_KEY: "on-demand",
                wk.OS_LABEL_KEY: "linux",
                wk.ARCH_LABEL_KEY: "amd64",
                wk.NODEPOOL_LABEL_KEY: "default",
            },
            available={"cpu": 1800, "memory": 3 * 1024**3 * 1000, "pods": 100},
            capacity={"cpu": 2000, "memory": 4 * 1024**3 * 1000},
            initialized=True,
        )
        # the node already serves 443/TCP on the wildcard ip
        squatter = fixtures.pod(name="squatter")
        v.host_port_usage.add(squatter, [("0.0.0.0", "TCP", 443)])
        return v

    def solve(cls, **kw):
        fixtures.reset_rng(13)
        pods = [
            fixtures.pod(name="wants-443", requests={"cpu": "100m"}),
            fixtures.pod(name="plain", requests={"cpu": "100m"}),
            fixtures.pod(name="wants-443-too", requests={"cpu": "100m"}),
        ]
        pods[0].host_ports = [("", "TCP", 443)]
        pods[2].host_ports = [("10.1.1.1", "TCP", 443)]
        pool = fixtures.node_pool(name="default")
        views = [make_view()]
        topo = Topology([pool], {"default": its}, pods, state_node_views=views)
        s = cls([pool], {"default": its}, topo, views, None, SchedulerOptions(), **kw)
        return s.solve(pods)

    rt = solve(TpuScheduler)
    ro = solve(Scheduler)

    def snap(r):
        out = {}
        for n in r.existing_nodes:
            for p in n.pods:
                out[p.name] = ("existing", n.view.name)
        for c in r.new_node_claims:
            for p in c.pods:
                out[p.name] = ("new", tuple(sorted(q.name for q in c.pods)))
        return out

    a, b = snap(rt), snap(ro)
    assert a == b, (a, b)
    # the 443/TCP pods must avoid the existing node (wildcard squatter)
    assert a["wants-443"][0] == "new"
    assert a["wants-443-too"][0] == "new"
    assert not rt.pod_errors and not ro.pod_errors


def test_daemonset_host_ports_force_per_pod_path_and_match_oracle():
    """A template whose daemonset claims a host port disables the bulk
    phases (bulk-created claims would miss the thp seed); a later
    host-port pod must refuse the daemonset's port on every claim, same
    as the oracle (hostportusage.go:35)."""
    from karpenter_tpu.cloudprovider.kwok import construct_instance_types
    from karpenter_tpu.solver.oracle import Scheduler, SchedulerOptions
    from karpenter_tpu.solver.topology import Topology
    from karpenter_tpu.solver.tpu import TpuScheduler
    from karpenter_tpu.testing import fixtures

    its = construct_instance_types(sizes=[8])

    def solve(cls, **kw):
        fixtures.reset_rng(17)
        daemon = fixtures.pod(name="ds-proxy", requests={"cpu": "100m"})
        daemon.host_ports = [("0.0.0.0", "TCP", 443)]
        pods = [
            fixtures.pod(name=f"w-{i}", requests={"cpu": "500m"})
            for i in range(6)
        ]
        clash = fixtures.pod(name="clash", requests={"cpu": "100m"})
        clash.host_ports = [("", "TCP", 443)]
        pods.append(clash)
        pool = fixtures.node_pool(name="default")
        topo = Topology([pool], {"default": its}, pods)
        s = cls(
            [pool], {"default": its}, topo, None, [daemon],
            SchedulerOptions(), **kw,
        )
        return s.solve(pods), {p.uid: p.name for p in pods}

    rt, rt_names = solve(TpuScheduler)
    ro, ro_names = solve(Scheduler)

    def snap(r):
        return {
            p.name: tuple(sorted(q.name for q in c.pods))
            for c in r.new_node_claims
            for p in c.pods
        }

    assert snap(rt) == snap(ro)
    # the clash pod conflicts with EVERY claim's daemonset port: it must
    # be unschedulable on both paths (compare by NAME — each run builds
    # its own pod objects with fresh uids)
    errs_t = {rt_names[u] for u in rt.pod_errors}
    errs_o = {ro_names[u] for u in ro.pod_errors}
    assert errs_t == errs_o == {"clash"}


def test_odometer_inertness_and_determinism():
    """Kernel odometers (ISSUE 15) are write-only device counters: every
    scenario above already re-proves oracle parity WITH the counters
    carried — the whole matrix is the inertness gate. This pins the
    remaining properties explicitly: decisions are identical across the
    runs and forced-scan compiled programs while their odometers differ
    (structural proof the counters feed no decision), a repeat solve's
    odometer is byte-equal (nothing host- or time-dependent leaks into
    the device block), and the block is self-consistent."""

    def solve_once(force_scan=False):
        fixtures.reset_rng(55)
        its = construct_instance_types(sizes=[2, 8])
        pool = fixtures.node_pool(name="default")
        pods = fixtures.make_diverse_pods(96)
        topo = Topology([pool], {"default": its}, pods)
        s = TpuScheduler([pool], {"default": its}, topo)
        if force_scan:
            s.debug_force_scan = True
        r = s.solve(pods)
        snap = sorted(
            (tuple(sorted(p.name for p in c.pods)),
             tuple(sorted(it.name for it in c.instance_type_options)))
            for c in r.new_node_claims
        )
        return r, snap, dict(s.last_odometer), s.last_used_runs

    r1, snap1, odo1, used_runs = solve_once()
    _r2, snap2, odo2, _ = solve_once()
    assert snap1 == snap2
    assert odo1 == odo2, (odo1, odo2)  # deterministic, incl. tier_hist

    # self-consistency
    assert odo1["steps"] > 0 and odo1["dispatches"] >= 1
    assert odo1["claims_opened"] == len(r1.new_node_claims)
    assert 0 < odo1["claims_opened"] <= odo1["claim_slots"]
    assert odo1["claim_occupancy"] == pytest.approx(
        odo1["claims_opened"] / odo1["claim_slots"], abs=1e-3
    )
    assert odo1["tier_steps"] == 0  # diverse mix carries no preferences
    assert sum(odo1["tier_hist"]) == odo1["tier_steps"]

    # dual-path structural proof: the OTHER compiled program (forced
    # exact scan) decides identically while counting differently
    _r3, snap3, odo3, _ = solve_once(force_scan=True)
    assert used_runs, "diverse mix should take the runs path naturally"
    assert snap3 == snap1
    assert odo3["bulk_steps"] == 0  # scan has no bulk phases
    assert odo3["steps"] != odo1["steps"]


def test_odometer_relax_accounting_on_preference_mix():
    """A relaxable batch must book tier work in the odometer (and stay
    oracle-identical — assert_parity runs the same shape above)."""
    fixtures.reset_rng(21)
    its = construct_instance_types(sizes=[2, 8])
    pool = fixtures.node_pool(name="default")
    pods = fixtures.make_diverse_pods(24) + fixtures.make_preference_pods(8)
    topo = Topology([pool], {"default": its}, pods)
    s = TpuScheduler([pool], {"default": its}, topo)
    s.solve(pods)
    odo = s.last_odometer
    assert odo["tier_steps"] > 0
    assert sum(odo["tier_hist"]) == odo["tier_steps"]
    assert odo["tier_hist"][0] > 0  # every relaxed pod paid tier 0
