"""Test configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding logic is
exercised without TPU hardware (the driver separately dry-run-compiles the
multi-chip path via __graft_entry__.dryrun_multichip). The env vars must be set
before jax initializes its backends, hence this conftest does it at import time.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# persistent compile cache: the solver scan is expensive to build
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/karpenter-tpu-jax-cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")

# The axon TPU plugin ignores the JAX_PLATFORMS env var and would grab the
# real chip; force the CPU backend through the config instead.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# The suite compiles hundreds of distinct kernel shapes in one process; the
# accumulated executable cache has segfaulted XLA's CPU compiler late in
# long runs. Dropping caches between test MODULES bounds memory at the cost
# of a few re-compiles.
import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    yield
    try:
        jax.clear_caches()
    except Exception:
        pass


# Hard per-test timeout for suites that exercise sockets and faults
# (tests/test_service_faults.py): a wedged recv() must FAIL the test, never
# hang tier-1. SIGALRM interrupts blocking syscalls in the main thread —
# where pytest runs test bodies — and the handler raises into the test.
import signal  # noqa: E402


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_call(item):
    marker = item.get_closest_marker("hard_timeout")
    if marker is None or not hasattr(signal, "SIGALRM"):
        yield
        return
    seconds = float(marker.args[0]) if marker.args else 60.0

    def _abort(signum, frame):
        raise TimeoutError(
            f"hard_timeout: test exceeded its {seconds:.0f}s budget "
            "(wedged socket? missed deadline?)"
        )

    old = signal.signal(signal.SIGALRM, _abort)
    signal.setitimer(signal.ITIMER_REAL, seconds)
    try:
        yield
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0)
        signal.signal(signal.SIGALRM, old)


# tsan-lite runtime race witness (karpenter_tpu/analysis/racert.py): every
# `faults`-marked test — the whole fault-injection/chaos envelope exercises
# the service boundary's real thread interleavings — runs with instrumented
# locks, and fails on any observed lock-order inversion or uncaught
# background-thread exception. The `soak` marker (the epoch/admission
# steady-workload chaos soak) rides the same instrumentation: its
# acceptance criterion is literally "zero racert inversions witnessed".
# Opt in from any other test with @pytest.mark.racert. Overhead is a raw
# frame walk per acquire (microseconds), so the tier-1 budget is
# untouched.
@pytest.fixture(autouse=True)
def _racert_witness(request):
    if (
        request.node.get_closest_marker("faults") is None
        and request.node.get_closest_marker("racert") is None
        and request.node.get_closest_marker("soak") is None
    ):
        yield
        return
    from karpenter_tpu.analysis import racert

    witness = racert.instrument()
    try:
        yield witness
    finally:
        racert.uninstrument()
    witness.assert_no_inversions()
    witness.assert_no_thread_exceptions()


# Protocol-trace conformance witness (karpenter_tpu/analysis/protorec.py +
# proto.check_refinement): every `faults`-marked test records the real
# wire/breaker events its fault schedule provokes, and the recorded trace
# must refine the protocol model — breaker transition legality and probe
# obligations, the drain answer-then-close bound, epoch
# commit-implies-store, the resync one-hop rule. The fault matrix thus
# doubles as a model-conformance suite on every tier-1 run (the racert
# pattern, one layer up the stack). Opt in from any other test with
# @pytest.mark.proto. Overhead when not recording is one module-attribute
# load per hook site (tests/test_proto_analysis.py pins it).
@pytest.fixture(autouse=True)
def _proto_conformance(request):
    if (
        request.node.get_closest_marker("faults") is None
        and request.node.get_closest_marker("proto") is None
    ):
        yield
        return
    from karpenter_tpu.analysis import proto, protorec

    recorder = protorec.install()
    try:
        yield recorder
    finally:
        protorec.uninstall()
    violations = proto.check_refinement(recorder.snapshot())
    assert not violations, (
        "recorded protocol trace does not refine the model "
        "(analysis/proto.py):\n" + "\n".join(violations)
    )
