from karpenter_tpu.utils import quantity, resources


def test_parse_milli():
    assert quantity.parse("100m") == 100
    assert quantity.parse("1500m") == 1500
    assert quantity.parse("0") == 0


def test_parse_units():
    assert quantity.parse("1") == 1000
    assert quantity.parse("2") == 2000
    assert quantity.parse(4) == 4000
    assert quantity.parse("0.5") == 500


def test_parse_binary_suffixes():
    assert quantity.parse("1Ki") == 1024 * 1000
    assert quantity.parse("2Gi") == 2 * 1024**3 * 1000
    assert quantity.parse("1.5Gi") == 3 * 1024**3 * 1000 // 2
    assert quantity.parse("256Mi") == 256 * 1024**2 * 1000


def test_parse_decimal_suffixes():
    assert quantity.parse("1k") == 10**3 * 1000
    assert quantity.parse("10M") == 10 * 10**6 * 1000
    assert quantity.parse("1e3") == 10**3 * 1000


def test_parse_negative():
    assert quantity.parse("-1") == -1000
    assert quantity.parse("-500m") == -500


def test_format_roundtrip():
    for s in ["100m", "1", "2Gi", "256Mi", "10", "1500m"]:
        assert quantity.parse(quantity.format_milli(quantity.parse(s))) == quantity.parse(s)


def test_merge_subtract():
    a = resources.parse_list({"cpu": "1", "memory": "1Gi"})
    b = resources.parse_list({"cpu": "500m", "pods": 3})
    m = resources.merge(a, b)
    assert m["cpu"] == 1500
    assert m["pods"] == 3000
    s = resources.subtract(m, a)
    assert s["cpu"] == 500
    assert s["memory"] == 0


def test_fits():
    total = resources.parse_list({"cpu": "4", "memory": "8Gi", "pods": 10})
    assert resources.fits(resources.parse_list({"cpu": "4"}), total)
    assert not resources.fits(resources.parse_list({"cpu": "4100m"}), total)
    # missing resource in total counts as zero
    assert not resources.fits(resources.parse_list({"fake.com/gpu": 1}), total)
    # zero-valued request for a missing resource fits
    assert resources.fits({"fake.com/gpu": 0}, total)
    # negative total never fits
    assert not resources.fits({}, {"cpu": -1})


def test_max_resources():
    a = resources.parse_list({"cpu": "1", "memory": "2Gi"})
    b = resources.parse_list({"cpu": "2", "memory": "1Gi"})
    m = resources.max_resources(a, b)
    assert m["cpu"] == 2000
    assert m["memory"] == quantity.parse("2Gi")
