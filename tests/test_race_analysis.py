"""graftlint race-tier gate: fixture-driven positive/negative cases per
static rule, the runtime witness (inversion, long-hold, background
exceptions), CLI exit codes, the no-JAX subprocess pin, and the
full-tree run.

Mirrors tests/test_static_analysis.py's structure; the static fixtures
are written into a throwaway repo layout because the race tier is
whole-program (a cycle's two halves may sit in different methods or
files — inline single-rule snippets would under-test the
interprocedural plumbing).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from karpenter_tpu.analysis import racert
from karpenter_tpu.analysis.__main__ import main as graftlint_main
from karpenter_tpu.analysis.locks import RACE_RULES, run_race_analysis

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def race_findings(tmp_path, files: dict[str, str], rule_ids=None):
    """Write `files` (relpath -> source) into a throwaway repo and run
    the static race analysis over it."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src), encoding="utf-8")
    report = run_race_analysis(str(tmp_path), rule_ids=rule_ids)
    assert report["errors"] == []
    return report["findings"]


# ---------------------------------------------------------------------------
# race-lock-order


def test_lock_order_flags_two_order_cycle(tmp_path):
    findings = race_findings(
        tmp_path,
        {
            "karpenter_tpu/x.py": """
            import threading

            class Inverted:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        with self._b:
                            pass

                def two(self):
                    with self._b:
                        with self._a:
                            pass
            """
        },
        rule_ids={"race-lock-order"},
    )
    assert [f.rule for f in findings] == ["race-lock-order"]
    assert "cycle" in findings[0].message


def test_lock_order_follows_same_class_calls(tmp_path):
    """Interprocedural: one half of the inversion hides behind a method
    call made while a lock is held."""
    findings = race_findings(
        tmp_path,
        {
            "karpenter_tpu/x.py": """
            import threading

            class Hidden:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        self._grab_b()

                def _grab_b(self):
                    with self._b:
                        pass

                def two(self):
                    with self._b:
                        with self._a:
                            pass
            """
        },
        rule_ids={"race-lock-order"},
    )
    assert len(findings) == 1 and "cycle" in findings[0].message


def test_lock_order_flags_self_deadlock_on_plain_lock(tmp_path):
    findings = race_findings(
        tmp_path,
        {
            "karpenter_tpu/x.py": """
            import threading

            class SelfDead:
                def __init__(self):
                    self._lock = threading.Lock()

                def bump(self):
                    with self._lock:
                        self.flush()

                def flush(self):
                    with self._lock:
                        pass
            """
        },
        rule_ids={"race-lock-order"},
    )
    assert len(findings) == 1 and "self-deadlock" in findings[0].message


def test_lock_order_allows_consistent_order_and_rlock_reentry(tmp_path):
    findings = race_findings(
        tmp_path,
        {
            "karpenter_tpu/x.py": """
            import threading

            class Consistent:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        with self._b:
                            pass

                def two(self):
                    with self._a:
                        with self._b:
                            pass

            class Reentrant:
                def __init__(self):
                    self._lock = threading.RLock()

                def outer(self):
                    with self._lock:
                        self.inner()

                def inner(self):
                    with self._lock:
                        pass
            """
        },
        rule_ids={"race-lock-order"},
    )
    assert findings == []


def test_lock_order_ignores_branch_alternative_acquires(tmp_path):
    """`if fast: lock.acquire() else: lock.acquire()` is ONE hold — the
    two acquires can never coexist, and an acquire-statement span (which
    runs to the next release line, textually covering the sibling
    branch) must not read as a self-deadlock. The genuinely sequential
    double-acquire right below must still be flagged."""
    findings = race_findings(
        tmp_path,
        {
            "karpenter_tpu/x.py": """
            import threading

            class BranchAlternative:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0

                def bump(self, fast):
                    if fast:
                        self._lock.acquire()
                    else:
                        self._lock.acquire()
                    self.n += 1
                    self._lock.release()
            """
        },
        rule_ids={"race-lock-order"},
    )
    assert findings == []
    findings = race_findings(
        tmp_path,
        {
            "karpenter_tpu/y.py": """
            import threading

            class Sequential:
                def __init__(self):
                    self._lock = threading.Lock()

                def bad(self):
                    self._lock.acquire()
                    self._lock.acquire()
                    self._lock.release()
                    self._lock.release()
            """
        },
        rule_ids={"race-lock-order"},
    )
    assert len(findings) == 1 and "self-deadlock" in findings[0].message


# ---------------------------------------------------------------------------
# race-blocking-hold


def test_blocking_hold_flags_sleep_socket_and_untimed_get(tmp_path):
    findings = race_findings(
        tmp_path,
        {
            "karpenter_tpu/x.py": """
            import threading
            import time

            class Blocky:
                def __init__(self, sock, q):
                    self._lock = threading.Lock()
                    self.sock = sock
                    self.q = q

                def hold_sleep(self):
                    with self._lock:
                        time.sleep(1.0)

                def hold_recv(self):
                    with self._lock:
                        return self.sock.recv(1024)

                def hold_get(self):
                    with self._lock:
                        return self.q.get()
            """
        },
        rule_ids={"race-blocking-hold"},
    )
    assert len(findings) == 3
    msgs = " ".join(f.message for f in findings)
    assert "time.sleep" in msgs and ".recv" in msgs and ".get() with no timeout" in msgs


def test_blocking_hold_follows_calls_and_locked_contract(tmp_path):
    findings = race_findings(
        tmp_path,
        {
            "karpenter_tpu/x.py": """
            import threading
            import time

            class Indirect:
                def __init__(self):
                    self._lock = threading.Lock()

                def hold(self):
                    with self._lock:
                        self._slow()

                def _slow(self):
                    time.sleep(0.5)

                def _flush_locked(self):
                    time.sleep(0.1)
            """
        },
        rule_ids={"race-blocking-hold"},
    )
    # one at the call site (hold -> _slow), one inside the *_locked
    # method (the caller holds a lock by contract)
    assert len(findings) == 2
    msgs = " ".join(f.message for f in findings)
    assert "_slow" in msgs and "_flush_locked" in msgs


def test_blocking_hold_locked_callee_reported_once(tmp_path):
    """A blocking call inside a *_locked method is one defect: reported
    at the definition (the caller-holds contract), NOT again at every
    locked call site — one suppression/baseline entry must cover it."""
    findings = race_findings(
        tmp_path,
        {
            "karpenter_tpu/x.py": """
            import threading
            import time

            class Svc:
                def __init__(self):
                    self._lock = threading.Lock()

                def flush(self):
                    with self._lock:
                        self._drain_locked()

                def _drain_locked(self):
                    time.sleep(0.1)
            """
        },
        rule_ids={"race-blocking-hold"},
    )
    assert len(findings) == 1
    assert "_drain_locked" in findings[0].message


def test_blocking_hold_ignores_sleep_in_opposite_branch(tmp_path):
    """An acquire() span runs to the next release line, textually
    covering the else branch — but a sleep there never runs with the
    lock held."""
    findings = race_findings(
        tmp_path,
        {
            "karpenter_tpu/x.py": """
            import threading
            import time

            class BranchWait:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.n = 0

                def acquire_or_wait(self, fast):
                    if fast:
                        self._lock.acquire()
                    else:
                        time.sleep(0.1)
                        return False
                    self.n += 1
                    self._lock.release()
                    return True
            """
        },
        rule_ids={"race-blocking-hold"},
    )
    assert findings == []


def test_blocking_hold_allows_unlocked_sleep_and_timed_get(tmp_path):
    findings = race_findings(
        tmp_path,
        {
            "karpenter_tpu/x.py": """
            import threading
            import time

            class Fine:
                def __init__(self, q):
                    self._lock = threading.Lock()
                    self.q = q
                    self.n = 0

                def snapshot_then_wait(self):
                    with self._lock:
                        n = self.n
                    time.sleep(0.1)
                    return n

                def timed_get(self):
                    with self._lock:
                        return self.q.get(timeout=1.0)
            """
        },
        rule_ids={"race-blocking-hold"},
    )
    assert findings == []


def test_blocking_hold_device_sync_only_in_jax_modules(tmp_path):
    files = {
        "karpenter_tpu/jaxy.py": """
        import threading
        import jax
        import numpy as np

        class Dev:
            def __init__(self):
                self._lock = threading.Lock()

            def fetch(self, x):
                with self._lock:
                    return np.asarray(x)
        """,
        "karpenter_tpu/hosty.py": """
        import threading
        import numpy as np

        class Host:
            def __init__(self):
                self._lock = threading.Lock()

            def fetch(self, x):
                with self._lock:
                    return np.asarray(x)
        """,
    }
    findings = race_findings(tmp_path, files, rule_ids={"race-blocking-hold"})
    assert [f.path for f in findings] == ["karpenter_tpu/jaxy.py"]
    assert "device fetch" in findings[0].message


def test_blocking_hold_sees_module_level_locks_in_methods(tmp_path):
    """A class method holding a MODULE-level lock must land on the same
    graph node as module functions holding it — and its blocking calls
    must be flagged."""
    findings = race_findings(
        tmp_path,
        {
            "karpenter_tpu/x.py": """
            import threading
            import time

            _mod_lock = threading.Lock()

            def module_hold():
                with _mod_lock:
                    time.sleep(0.2)

            class UsesModuleLock:
                def hold(self):
                    with _mod_lock:
                        time.sleep(0.1)
            """
        },
        rule_ids={"race-blocking-hold"},
    )
    assert len(findings) == 2  # module function AND the class method


def test_lock_order_cycle_across_class_and_module_lock(tmp_path):
    """A cycle whose halves mix a class lock and a module lock: the
    class-scope ref and the module-scope ref must unify to one node."""
    findings = race_findings(
        tmp_path,
        {
            "karpenter_tpu/x.py": """
            import threading

            _mod_lock = threading.Lock()

            class Mixed:
                def __init__(self):
                    self._own = threading.Lock()

                def one(self):
                    with self._own:
                        with _mod_lock:
                            pass

                def two(self):
                    with _mod_lock:
                        with self._own:
                            pass
            """
        },
        rule_ids={"race-lock-order"},
    )
    assert len(findings) == 1 and "cycle" in findings[0].message


def test_blocking_hold_flags_block_true_get_and_allows_block_false(tmp_path):
    findings = race_findings(
        tmp_path,
        {
            "karpenter_tpu/x.py": """
            import threading

            class Q:
                def __init__(self, q):
                    self._lock = threading.Lock()
                    self.q = q

                def blocking(self):
                    with self._lock:
                        return self.q.get(block=True)

                def non_blocking(self):
                    with self._lock:
                        return self.q.get(block=False)
            """
        },
        rule_ids={"race-blocking-hold"},
    )
    assert len(findings) == 1
    assert findings[0].line and "no timeout" in findings[0].message


def test_lock_inventory_skips_nested_classes(tmp_path):
    """An inner class's `self._mu` is a different object than the outer
    class's — inventorying it on the outer class invents phantom held
    spans (false blocking-hold findings) and splits one lock role across
    two graph identities."""
    findings = race_findings(
        tmp_path,
        {
            "karpenter_tpu/x.py": """
            import threading
            import time

            class Outer:
                def __init__(self):
                    self._mu = object()  # NOT a lock

                    class Inner:
                        def __init__(self):
                            self._mu = threading.Lock()

                    self.inner = Inner()

                def work(self):
                    with self._mu:  # plain context manager, no held span
                        time.sleep(1)
            """
        },
        rule_ids={"race-blocking-hold"},
    )
    assert findings == []


def test_lock_inventory_sees_annotated_assignments(tmp_path):
    """`self._lock: threading.Lock = threading.Lock()` declares the same
    shared lock as the bare assignment — an AnnAssign-blind inventory
    would silently drop every rule over it and the gate would stay
    green (whole-program completeness claim broken)."""
    findings = race_findings(
        tmp_path,
        {
            "karpenter_tpu/x.py": """
            import threading
            import time

            ann_mod_lock: threading.Lock = threading.Lock()

            class Annotated:
                def __init__(self):
                    self._lock: threading.Lock = threading.Lock()

                def hold(self):
                    with self._lock:
                        time.sleep(1)

            def mod_hold():
                with ann_mod_lock:
                    time.sleep(1)
            """
        },
        rule_ids={"race-blocking-hold"},
    )
    assert sorted(f.rule for f in findings) == ["race-blocking-hold"] * 2


# ---------------------------------------------------------------------------
# race-unguarded-shared


def test_unguarded_shared_flags_thread_vs_public_write(tmp_path):
    findings = race_findings(
        tmp_path,
        {
            "karpenter_tpu/x.py": """
            import threading

            class Shared:
                def __init__(self):
                    self.count = 0
                    self._t = threading.Thread(target=self._loop, daemon=True)

                def _loop(self):
                    self.count += 1

                def reset(self):
                    self.count = 0
            """
        },
        rule_ids={"race-unguarded-shared"},
    )
    assert len(findings) == 1
    assert "Shared.count" in findings[0].message


def test_unguarded_shared_flags_disjoint_locks(tmp_path):
    """Both sides guarded — by DIFFERENT locks — is still a race: the
    rule demands one common lock across every write."""
    findings = race_findings(
        tmp_path,
        {
            "karpenter_tpu/x.py": """
            import threading

            class Disjoint:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()
                    self.count = 0
                    self._t = threading.Thread(target=self._loop, daemon=True)

                def _loop(self):
                    with self._a:
                        self.count += 1

                def reset(self):
                    with self._b:
                        self.count = 0
            """
        },
        rule_ids={"race-unguarded-shared"},
    )
    assert len(findings) == 1 and "no common lock" in findings[0].message


def test_unguarded_shared_follows_public_delegation(tmp_path):
    """The public side is interprocedural too: `stop()` delegating the
    write to a private helper races exactly like an inline assignment —
    and the same delegation with a common lock stays clean."""
    findings = race_findings(
        tmp_path,
        {
            "karpenter_tpu/x.py": """
            import threading

            class Delegating:
                def __init__(self):
                    self._running = True
                    self._t = threading.Thread(target=self._serve, daemon=True)

                def _serve(self):
                    self._running = True

                def stop(self):
                    self._shutdown()

                def _shutdown(self):
                    self._running = False
            """
        },
        rule_ids={"race-unguarded-shared"},
    )
    assert len(findings) == 1
    assert "Delegating._running" in findings[0].message
    findings = race_findings(
        tmp_path,
        {
            "karpenter_tpu/y.py": """
            import threading

            class Guarded:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._running = True
                    self._t = threading.Thread(target=self._serve, daemon=True)

                def _serve(self):
                    with self._lock:
                        self._running = True

                def stop(self):
                    self._shutdown()

                def _shutdown(self):
                    with self._lock:
                        self._running = False
            """
        },
        rule_ids={"race-unguarded-shared"},
    )
    assert [f.message for f in findings if "Guarded" in f.message] == []


def test_unguarded_shared_inherits_callers_held_locks(tmp_path):
    """Guard sets follow the call closure exactly like write sites do:
    `with self._lock: self._shutdown()` keeps `_shutdown`'s writes
    guarded (guarded delegation is the ordinary pattern, not a finding),
    while a caller holding the WRONG lock — or a second caller holding
    nothing — does not count."""
    findings = race_findings(
        tmp_path,
        {
            "karpenter_tpu/x.py": """
            import threading

            class CallerGuarded:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._flag = True
                    self._t = threading.Thread(target=self._serve, daemon=True)

                def _serve(self):
                    with self._lock:
                        self._flag = True

                def stop(self):
                    with self._lock:
                        self._shutdown()

                def _shutdown(self):
                    self._flag = False

            class WrongLock:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._other = threading.Lock()
                    self._flag = True
                    self._t = threading.Thread(target=self._serve, daemon=True)

                def _serve(self):
                    with self._lock:
                        self._flag = True

                def stop(self):
                    with self._other:
                        self._shutdown()

                def _shutdown(self):
                    self._flag = False

            class LeakyCaller:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._flag = True
                    self._t = threading.Thread(target=self._serve, daemon=True)

                def _serve(self):
                    with self._lock:
                        self._flag = True

                def stop(self):
                    with self._lock:
                        self._shutdown()

                def kick(self):
                    self._shutdown()

                def _shutdown(self):
                    self._flag = False
            """
        },
        rule_ids={"race-unguarded-shared"},
    )
    msgs = [f.message for f in findings]
    assert not any("CallerGuarded" in m for m in msgs), msgs
    assert any("WrongLock._flag" in m for m in msgs), msgs
    assert any("LeakyCaller._flag" in m for m in msgs), msgs
    assert len(findings) == 2


def test_unguarded_shared_allows_common_lock_and_thread_closure(tmp_path):
    findings = race_findings(
        tmp_path,
        {
            "karpenter_tpu/x.py": """
            import threading

            class Guarded:
                def __init__(self):
                    self._lock = threading.Lock()
                    self.count = 0
                    self.private_only = 0
                    self._t = threading.Thread(target=self._loop, daemon=True)

                def _loop(self):
                    with self._lock:
                        self.count += 1
                    self._helper()

                def _helper(self):
                    self.private_only += 1

                def reset(self):
                    with self._lock:
                        self.count = 0

                def reset_locked(self):
                    self.count = -1
            """
        },
        rule_ids={"race-unguarded-shared"},
    )
    # count: common lock (the *_locked method counts as guarded by
    # contract); private_only: written only from thread-side/private code
    assert findings == []


# ---------------------------------------------------------------------------
# suppression + baseline mechanics (engine integration)


def test_race_findings_honor_suppressions(tmp_path):
    findings = race_findings(
        tmp_path,
        {
            "karpenter_tpu/x.py": """
            import threading
            import time

            class Blocky:
                def __init__(self):
                    self._lock = threading.Lock()

                def hold(self):
                    with self._lock:
                        time.sleep(1.0)  # graftlint: disable=race-blocking-hold
            """
        },
        rule_ids={"race-blocking-hold"},
    )
    assert findings == []


def test_race_baseline_hides_and_reports_stale(tmp_path):
    (tmp_path / "karpenter_tpu").mkdir(parents=True)
    (tmp_path / "karpenter_tpu" / "x.py").write_text(
        textwrap.dedent(
            """
            import threading
            import time

            class Blocky:
                def __init__(self):
                    self._lock = threading.Lock()

                def hold(self):
                    with self._lock:
                        time.sleep(1.0)
            """
        )
    )
    bl = tmp_path / "graftlint.race.baseline.json"
    bl.write_text(
        json.dumps(
            {
                "entries": [
                    {
                        "rule": "race-blocking-hold",
                        "path": "karpenter_tpu/x.py",
                        "text": "time.sleep(1.0)",
                        "justification": "fixture: intentional hold",
                    },
                    {
                        "rule": "race-lock-order",
                        "path": "karpenter_tpu/gone.py",
                        "text": "with self._a:",
                        "justification": "rotted",
                    },
                ]
            }
        )
    )
    report = run_race_analysis(str(tmp_path), baseline_path=str(bl))
    assert report["findings"] == []
    assert [e["path"] for e in report["stale"]] == ["karpenter_tpu/gone.py"]


# ---------------------------------------------------------------------------
# runtime half: the racert witness


@pytest.fixture()
def witness():
    w = racert.instrument(hold_ms=30.0)
    try:
        yield w
    finally:
        racert.uninstrument()


def test_racert_witnesses_inversion_with_both_stacks(witness):
    """Inversion detection needs no unlucky interleaving — taking the
    locks in both orders SEQUENTIALLY is already the evidence (the
    deadlock just has not fired yet)."""
    a = threading.Lock()
    b = threading.Lock()
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    assert len(witness.inversions) == 1
    inv = witness.inversions[0]
    assert set(inv["locks"]) == {a._racert_site, b._racert_site}
    assert inv["order_a_then_b"]["stack"] and inv["order_b_then_a"]["stack"]
    # the report's innermost frame is the USER's `with` statement, not
    # the __enter__/acquire wrapper frames inside racert itself
    for side in ("order_a_then_b", "order_b_then_a"):
        stack = inv[side]["stack"]
        assert "analysis/racert.py" not in stack[0], stack
        assert "test_race_analysis.py" in stack[0], stack
    with pytest.raises(AssertionError, match="inversion"):
        witness.assert_no_inversions()


def test_racert_identities_survive_chdir(witness, tmp_path, monkeypatch):
    """Lock sites are ROLE identities (creation site, Eraser-style) and
    are anchored to the cwd resolved ONCE at import — a chdir between
    two creations of the same role must not split it into repo-relative
    and absolute identities, or the two halves of this inversion could
    never pair up."""

    def mk():
        a = threading.Lock()  # role A: this line IS the identity
        b = threading.Lock()  # role B
        return a, b

    a1, b1 = mk()
    with a1:
        with b1:
            pass
    monkeypatch.chdir(tmp_path)
    a2, b2 = mk()  # same creation sites, cwd moved underneath
    assert a2._racert_site == a1._racert_site
    with b2:
        with a2:
            pass
    assert len(witness.inversions) == 1


def test_racert_consistent_order_and_rlock_reentry_are_clean(witness):
    a = threading.Lock()
    b = threading.Lock()
    r = threading.RLock()
    for _ in range(3):
        with a:
            with b:
                pass
    with r:
        with r:  # re-entry is not an edge, let alone an inversion
            with a:
                pass
    witness.assert_no_inversions()
    assert witness.edges[(a._racert_site, b._racert_site)]["count"] == 3


def test_racert_flags_long_hold(witness):
    lock = threading.Lock()
    with lock:
        time.sleep(0.06)  # witness instrumented with hold_ms=30
    assert any(
        h["site"] == lock._racert_site and h["held_ms"] >= 30.0
        for h in witness.long_holds
    )


def test_racert_condition_wait_on_reentrant_rlock_is_not_a_hold(witness):
    """Condition.wait drops EVERY RLock recursion level at once; the
    witness must surrender them all too (on_release_save), or a
    re-entrantly held lock parked in wait() is tracked as still held and
    the whole blocked wait reports as a spurious long hold. The depth
    comes back after the wake so the outer releases still balance."""
    c = threading.Condition()
    site = c._lock._racert_site

    def waiter():
        with c:
            with c:  # depth 2: _release_save must surrender both
                c.wait(timeout=10)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.08)  # > hold_ms=30 while the waiter is parked in wait()
    with c:
        c.notify()
    t.join(timeout=10)
    assert [h for h in witness.long_holds if h["site"] == site] == []
    witness.assert_no_inversions()
    witness.assert_no_thread_exceptions()
    # the restored depth drained back to zero on the way out: the lock
    # is genuinely free, not leaked at depth 1
    assert c.acquire(timeout=1)
    c.release()


def test_racert_captures_background_thread_exception(witness):
    def boom():
        raise RuntimeError("synthetic background failure")

    t = threading.Thread(target=boom, name="boom-thread")
    t.start()
    t.join(timeout=10)
    assert [e["exc_type"] for e in witness.thread_exceptions] == ["RuntimeError"]
    with pytest.raises(AssertionError, match="synthetic background failure"):
        witness.assert_no_thread_exceptions()


def test_racert_condition_and_queue_stay_functional(witness):
    """Patched constructors must compose with stdlib users: queue.Queue
    builds a Condition over an instrumented Lock and must still block,
    wake, and witness cleanly."""
    import queue

    q = queue.Queue()
    got = {}

    def consume():
        got["v"] = q.get(timeout=10)

    t = threading.Thread(target=consume)
    t.start()
    q.put(41 + 1)
    t.join(timeout=10)
    assert got["v"] == 42
    witness.assert_no_inversions()
    witness.assert_no_thread_exceptions()
    assert witness.acquire_count > 0


def test_racert_locked_matches_uninstrumented_surface(witness):
    """The wrappers must not invent API the raw lock lacks: before 3.14
    `_thread.RLock` has no locked(), so code calling it must fail under
    instrumentation exactly as it does without (not work in prod and
    crash only inside racert-marked tests, or vice versa)."""
    lock = threading.Lock()
    assert lock.locked() is False
    with lock:
        assert lock.locked() is True

    raw_has_locked = hasattr(racert._RAW_RLOCK(), "locked")
    r = threading.RLock()
    if raw_has_locked:  # 3.14+
        assert r.locked() is False
    else:
        with pytest.raises(AttributeError):
            r.locked()


def test_racert_uninstrument_restores_and_quiets_wrappers():
    w = racert.instrument()
    lock = threading.Lock()
    assert isinstance(lock, racert._InstrumentedLock)
    racert.uninstrument()
    assert threading.Lock is racert._RAW_LOCK
    assert racert.current() is None
    before = w.acquire_count
    with lock:  # leftover wrapper stays usable but reports nowhere
        pass
    assert w.acquire_count == before


def test_logging_capture_records_background_thread_exception():
    """Satellite: klog.capture() chains threading.excepthook so a dying
    background thread surfaces as an ERROR record and on
    records.thread_exceptions, instead of vanishing to stderr."""
    from karpenter_tpu import logging as klog

    hook_before = threading.excepthook
    with klog.capture(level="error") as records:
        assert threading.excepthook is not hook_before  # hook installed

        def boom():
            raise ValueError("conn thread died")

        t = threading.Thread(target=boom, name="conn-7")
        t.start()
        t.join(timeout=10)
    assert [e["exc_type"] for e in records.thread_exceptions] == ["ValueError"]
    rec = next(r for r in records if r["logger"] == "karpenter.threading")
    assert rec["level"] == "error"
    assert "ValueError: conn thread died" in rec["error"]
    assert rec["thread"] == "conn-7"
    assert threading.excepthook is hook_before  # restored after the block


def test_capture_chains_excepthook_into_racert_witness(witness):
    """klog.capture() must CHAIN the previous excepthook: recording a
    background exception for log assertions must not hide it from the
    racert witness running the same test."""
    from karpenter_tpu import logging as klog

    with klog.capture(level="error") as records:

        def boom():
            raise OSError("handler died under capture")

        t = threading.Thread(target=boom, name="conn-9")
        t.start()
        t.join(timeout=10)
    assert [e["exc_type"] for e in records.thread_exceptions] == ["OSError"]
    assert [e["exc_type"] for e in witness.thread_exceptions] == ["OSError"]


@pytest.mark.racert
def test_racert_marker_fixture_instruments_this_test():
    """The conftest fixture turns instrumentation on for racert-marked
    (and all faults-marked) tests."""
    assert racert.current() is not None
    lock = threading.Lock()
    assert isinstance(lock, racert._InstrumentedLock)


# ---------------------------------------------------------------------------
# CLI + full tree


def test_cli_race_clean_on_real_tree(capsys):
    """THE acceptance gate: the race tier runs clean on the tree —
    findings either fixed or baselined with justifications."""
    rc = graftlint_main(["--root", REPO_ROOT, "--race"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "0 findings" in out


def test_full_tree_report_has_no_stale_or_unjustified_entries():
    report = run_race_analysis(REPO_ROOT)
    assert report["errors"] == []
    assert [f.render() for f in report["findings"]] == []
    assert report["stale"] == []
    assert report["unjustified"] == []


def test_cli_race_json_mode(capsys):
    assert graftlint_main(["--root", REPO_ROOT, "--race", "--json"]) == 0
    data = json.loads(capsys.readouterr().out)
    assert data["findings"] == []
    assert set(data) >= {"findings", "stale_baseline", "errors", "baselined"}


def test_cli_race_exits_1_on_seeded_violation(tmp_path, capsys):
    pkg = tmp_path / "karpenter_tpu"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(
        textwrap.dedent(
            """
            import threading
            import time

            class Blocky:
                def __init__(self):
                    self._lock = threading.Lock()

                def hold(self):
                    with self._lock:
                        time.sleep(1.0)
            """
        )
    )
    rc = graftlint_main(["--root", str(tmp_path), "--race"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "race-blocking-hold" in out


def test_cli_race_errors_dominate_findings(tmp_path, capsys):
    """Whole-program analysis over a partial program is a broken gate:
    a parse error exits 2 even when other files still yield findings —
    the unparsable file could hold the other half of an inversion."""
    pkg = tmp_path / "karpenter_tpu"
    pkg.mkdir(parents=True)
    (pkg / "broken.py").write_text("def broken(:\n")
    (pkg / "bad.py").write_text(
        textwrap.dedent(
            """
            import threading
            import time

            class Blocky:
                def __init__(self):
                    self._lock = threading.Lock()

                def hold(self):
                    with self._lock:
                        time.sleep(1.0)
            """
        )
    )
    rc = graftlint_main(["--root", str(tmp_path), "--race"])
    out = capsys.readouterr().out
    assert rc == 2
    assert "race-blocking-hold" in out and "parse error" in out


def test_cli_race_unknown_rule_exits_2(capsys):
    rc = graftlint_main(["--root", REPO_ROOT, "--race", "--rules", "race-lock-ordr"])
    assert rc == 2
    assert "unknown race rule" in capsys.readouterr().err


def test_cli_race_rejects_paths_and_changed_only(capsys):
    """Whole-program analysis: a path subset would hide exactly the
    cross-module inversions the tier exists for."""
    assert graftlint_main(["--root", REPO_ROOT, "--race", "somefile.py"]) == 2
    assert graftlint_main(["--root", REPO_ROOT, "--race", "--changed-only"]) == 2


def test_cli_race_rejects_other_tiers_options(capsys):
    """An explicitly passed option --race never reads must be refused,
    not silently ignored — an operator pointing the gate at an alternate
    budget manifest must not get a green run that never opened it."""
    rc = graftlint_main(
        ["--root", REPO_ROOT, "--race", "--budgets", "/nonexistent.json"]
    )
    assert rc == 2
    assert "--budgets" in capsys.readouterr().err
    rc = graftlint_main(
        ["--root", REPO_ROOT, "--race", "--reference-root", "/elsewhere"]
    )
    assert rc == 2
    assert "--reference-root" in capsys.readouterr().err


def test_cli_race_write_baseline_preserves_justifications(tmp_path, capsys):
    pkg = tmp_path / "karpenter_tpu"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(
        textwrap.dedent(
            """
            import threading
            import time

            class Blocky:
                def __init__(self):
                    self._lock = threading.Lock()

                def hold(self):
                    with self._lock:
                        time.sleep(1.0)
            """
        )
    )
    bl = tmp_path / "graftlint.race.baseline.json"
    assert graftlint_main(["--root", str(tmp_path), "--race", "--write-baseline"]) == 0
    capsys.readouterr()
    data = json.loads(bl.read_text())
    assert len(data["entries"]) == 1
    assert data["entries"][0]["justification"].startswith("TODO")
    data["entries"][0]["justification"] = "curated: must survive"
    bl.write_text(json.dumps(data))
    assert graftlint_main(["--root", str(tmp_path), "--race", "--write-baseline"]) == 0
    capsys.readouterr()
    data = json.loads(bl.read_text())
    assert data["entries"][0]["justification"] == "curated: must survive"
    # a rule-subset rewrite would truncate out-of-scope entries: refused
    rc = graftlint_main(
        [
            "--root",
            str(tmp_path),
            "--race",
            "--rules",
            "race-lock-order",
            "--write-baseline",
        ]
    )
    assert rc == 2


def test_cli_list_rules_includes_race_tier(capsys):
    assert graftlint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in RACE_RULES:
        assert rid in out
    assert "[race]" in out


def _proto_tier_stub(*a, **kw):
    """Clean proto-tier report: the real exploration has its own tier-1
    gate in test_proto_analysis.py; --all plumbing tests stub it."""
    return {
        "findings": [], "all_findings": [], "stale": [], "unjustified": [],
        "errors": [], "total": 0, "scenarios": {}, "properties": {},
        "conformance": {},
    }


def test_cli_all_rejects_write_and_subset_modes(capsys):
    assert graftlint_main(["--root", REPO_ROOT, "--all", "--write-baseline"]) == 2
    assert graftlint_main(["--root", REPO_ROOT, "--all", "--rules", "milli-units"]) == 2
    assert graftlint_main(["--root", REPO_ROOT, "--all", "somefile.py"]) == 2
    # --budgets would be silently ignored (the IR tier hardcodes the
    # default manifest under --all) — an explicitly passed option that
    # does nothing must be refused like --baseline is
    assert graftlint_main(["--root", REPO_ROOT, "--all", "--budgets", "x.json"]) == 2


def test_cli_tier_flags_are_mutually_exclusive(tmp_path, capsys):
    """Silent tier precedence is a disabled gate: `--ir --race` must not
    go green having never run the race tier, and `--race
    --write-budgets` must not rewrite kernel_budgets.json unasked."""
    (tmp_path / "karpenter_tpu").mkdir()
    (tmp_path / "karpenter_tpu" / "x.py").write_text("x = 1\n", encoding="utf-8")
    root = str(tmp_path)
    assert graftlint_main(["--root", root, "--ir", "--race"]) == 2
    assert graftlint_main(["--root", root, "--race", "--write-budgets"]) == 2
    assert graftlint_main(["--root", root, "--all", "--race"]) == 2
    assert not (tmp_path / "kernel_budgets.json").exists()
    assert "mutually exclusive" in capsys.readouterr().err


def test_cli_all_forwards_reference_root(tmp_path, monkeypatch, capsys):
    """--all must hand --reference-root to the AST tier: on a machine
    whose reference checkout lives elsewhere, citation-check would
    otherwise be silently vacuous inside the one-command gate."""
    import karpenter_tpu.analysis.__main__ as cli

    seen = {}

    def fake_run_analysis(repo_root, reference_root=None, **kw):
        seen["reference_root"] = reference_root
        return {"findings": [], "stale": [], "unjustified": [], "errors": [], "total": 0}

    def fake_race(repo_root, **kw):
        return {
            "findings": [], "stale": [], "unjustified": [], "errors": [],
            "total": 0, "all_findings": [],
        }

    from karpenter_tpu.analysis import ir, locks, proto, spmd

    monkeypatch.setattr(cli, "run_analysis", fake_run_analysis)
    monkeypatch.setattr(locks, "run_race_analysis", fake_race)
    traced_tier_stub = lambda *a, **kw: {
        "findings": [], "all_findings": [], "stale": [], "unjustified": [],
        "budget_unjustified": [], "improvements": [], "errors": [], "measured": {},
    }
    monkeypatch.setattr(ir, "run_ir_analysis", traced_tier_stub)
    monkeypatch.setattr(spmd, "run_spmd_analysis", traced_tier_stub)
    monkeypatch.setattr(proto, "run_proto_analysis", _proto_tier_stub)
    (tmp_path / "karpenter_tpu").mkdir()
    rc = graftlint_main(
        ["--root", str(tmp_path), "--all", "--reference-root", "/elsewhere/ref"]
    )
    capsys.readouterr()
    assert rc == 0
    assert seen["reference_root"] == "/elsewhere/ref"


def test_cli_all_preflights_gate_files(tmp_path, capsys):
    """A trailing-comma typo in any hand-edited gate file must be the
    documented exit-2 diagnostic from --all too, not a JSONDecodeError
    traceback out of the first tier that loads it."""
    (tmp_path / "karpenter_tpu").mkdir()
    (tmp_path / "karpenter_tpu" / "x.py").write_text("x = 1\n", encoding="utf-8")
    (tmp_path / "graftlint.race.baseline.json").write_text("{bad,}", encoding="utf-8")
    assert graftlint_main(["--root", str(tmp_path), "--all"]) == 2
    err = capsys.readouterr().err
    assert "cannot parse" in err and "graftlint.race.baseline.json" in err


def test_cli_all_text_mode_itemizes_baseline_problems(tmp_path, capsys, monkeypatch):
    """An exit-1 --all run must name each stale/unjustified entry (with
    its tier prefix) exactly as the single-tier modes do — an aggregate
    count alone is not actionable in a CI log."""
    from karpenter_tpu.analysis import ir, proto, spmd

    monkeypatch.setattr(proto, "run_proto_analysis", _proto_tier_stub)

    def fake_ir(repo_root, budgets_path=None, baseline_path=None, rule_ids=None):
        return {
            "findings": [],
            "all_findings": [],
            "stale": [],
            "unjustified": [],
            "budget_unjustified": ["solve_scan[relax=False].carry_bytes"],
            "improvements": [],
            "errors": [],
            "measured": {},
        }

    def fake_spmd(repo_root, budgets_path=None, baseline_path=None, rule_ids=None):
        out = fake_ir(repo_root)
        out["budget_unjustified"] = []
        return out

    monkeypatch.setattr(ir, "run_ir_analysis", fake_ir)
    monkeypatch.setattr(spmd, "run_spmd_analysis", fake_spmd)
    (tmp_path / "karpenter_tpu").mkdir()
    (tmp_path / "karpenter_tpu" / "x.py").write_text("x = 1\n", encoding="utf-8")
    (tmp_path / "graftlint.race.baseline.json").write_text(
        json.dumps(
            {
                "entries": [
                    {
                        "rule": "race-lock-order",
                        "path": "karpenter_tpu/gone.py",
                        "text": "no longer here",
                        "justification": "was justified once",
                    }
                ]
            }
        ),
        encoding="utf-8",
    )
    rc = graftlint_main(["--root", str(tmp_path), "--all"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "[race] stale baseline entry: [race-lock-order]" in out
    assert "unjustified budget entry: solve_scan[relax=False].carry_bytes" in out


def test_cli_all_merges_tiers_with_worst_exit_code(capsys, monkeypatch):
    """--all = AST + race + IR + SPMD + proto with one worst-case exit
    code. The traced tiers are stubbed here (the real trace/compile/
    exploration runs have their own tier-1 gates in test_ir_analysis.py /
    test_spmd_analysis.py / test_proto_analysis.py; running them twice
    per suite would double that cost for no new coverage)."""
    from karpenter_tpu.analysis import ir, proto, spmd

    monkeypatch.setattr(proto, "run_proto_analysis", _proto_tier_stub)

    def fake_ir(repo_root, budgets_path=None, baseline_path=None, rule_ids=None):
        return {
            "findings": [],
            "all_findings": [],
            "stale": [],
            "unjustified": [],
            "budget_unjustified": [],
            "improvements": [],
            "errors": [],
            "measured": {"solve_scan[relax=False]": {}},
        }

    monkeypatch.setattr(ir, "run_ir_analysis", fake_ir)
    monkeypatch.setattr(
        spmd,
        "run_spmd_analysis",
        lambda repo_root, budgets_path=None, baseline_path=None, rule_ids=None: {
            "findings": [],
            "all_findings": [],
            "stale": [],
            "unjustified": [],
            "budget_unjustified": [],
            "improvements": [],
            "errors": [],
            "measured": {"spmd:solve_scan[relax=False]": {}},
        },
    )
    rc = graftlint_main(["--root", REPO_ROOT, "--all", "--json"])
    data = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert set(data) == {"ast", "race", "ir", "spmd", "proto", "exit_code"}
    assert data["exit_code"] == 0
    assert data["ast"]["findings"] == [] and data["race"]["findings"] == []
    assert data["ir"]["exit_code"] == 0
    # each tier's payload mirrors its single-tier --json shape
    # (docs/static-analysis.md) — the IR extras must survive the merge
    assert data["ir"]["improvements"] == [] and "measured" in data["ir"]

    # worst-case propagation: a failing IR tier dominates clean AST/race
    def broken_ir(repo_root, budgets_path=None, baseline_path=None, rule_ids=None):
        out = fake_ir(repo_root)
        out["errors"] = ["solve_scan: trace exploded"]
        return out

    monkeypatch.setattr(ir, "run_ir_analysis", broken_ir)
    rc = graftlint_main(["--root", REPO_ROOT, "--all", "--json"])
    data = json.loads(capsys.readouterr().out)
    assert rc == 2 and data["exit_code"] == 2 and data["ir"]["exit_code"] == 2


def test_every_race_rule_has_fixture_coverage_here():
    """Adding a race rule without positive/negative fixtures fails this."""
    assert set(RACE_RULES) == {
        "race-lock-order",
        "race-blocking-hold",
        "race-unguarded-shared",
    }


def test_race_tier_does_not_import_jax():
    """Both halves of the race tier must stay device-free: the static
    gate costs seconds and the runtime witness instruments the faults
    suite without dragging a second JAX init into it."""
    code = (
        "import sys; "
        "from karpenter_tpu.analysis import locks, racert; "
        "locks.run_race_analysis('.'); "
        "w = racert.instrument(); racert.uninstrument(); "
        "assert 'jax' not in sys.modules, 'race tier imported jax'; "
        "assert 'numpy' not in sys.modules, 'race tier imported numpy'"
    )
    res = subprocess.run(
        [sys.executable, "-c", code],
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert res.returncode == 0, res.stderr
