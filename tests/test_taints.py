from karpenter_tpu.api.objects import Pod, Taint, TaintEffect, Toleration
from karpenter_tpu.scheduling import Taints


def taint(key, value="", effect=TaintEffect.NO_SCHEDULE):
    return Taint(key, effect, value)


def test_no_taints_tolerates_all():
    assert Taints().tolerates_pod(Pod()) is None


def test_untolerated_taint():
    ts = Taints([taint("gpu", "true")])
    assert ts.tolerates_pod(Pod()) is not None


def test_equal_toleration():
    ts = Taints([taint("gpu", "true")])
    pod = Pod(tolerations=[Toleration(key="gpu", operator="Equal", value="true")])
    assert ts.tolerates_pod(pod) is None
    pod_wrong = Pod(tolerations=[Toleration(key="gpu", operator="Equal", value="false")])
    assert ts.tolerates_pod(pod_wrong) is not None


def test_exists_toleration():
    ts = Taints([taint("gpu", "true")])
    pod = Pod(tolerations=[Toleration(key="gpu", operator="Exists")])
    assert ts.tolerates_pod(pod) is None


def test_empty_key_exists_tolerates_everything():
    ts = Taints([taint("a"), taint("b", effect=TaintEffect.NO_EXECUTE)])
    pod = Pod(tolerations=[Toleration(operator="Exists")])
    assert ts.tolerates_pod(pod) is None


def test_effect_scoping():
    ts = Taints([taint("a", effect=TaintEffect.NO_EXECUTE)])
    pod = Pod(tolerations=[Toleration(key="a", operator="Exists", effect=TaintEffect.NO_SCHEDULE)])
    assert ts.tolerates_pod(pod) is not None


def test_prefer_no_schedule_is_hard_until_relaxed():
    # The scheduler treats PreferNoSchedule as a hard constraint; the
    # relaxation ladder adds a toleration later (reference preferences.go:140).
    ts = Taints([taint("a", effect=TaintEffect.PREFER_NO_SCHEDULE)])
    assert ts.tolerates_pod(Pod()) is not None
    pod = Pod(tolerations=[Toleration(operator="Exists", effect=TaintEffect.PREFER_NO_SCHEDULE)])
    assert ts.tolerates_pod(pod) is None


def test_merge_keyed_by_key_and_effect():
    ts = Taints([taint("a")])
    merged = ts.merge([taint("a", "different-value"), taint("b")])
    assert len(merged) == 2  # "a"/NoSchedule already present, "b" added
