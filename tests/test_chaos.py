"""Chaos guard: under a steady workload, the provision/consolidate loop
must converge and STAY converged — no runaway scale-up or node-count
oscillation (reference test/suites/regression/chaos_test.go:48-90, which
watches for consolidation and emptiness fighting the provisioner).
"""

from __future__ import annotations

from karpenter_tpu.api.objects import Budget, PodPhase
from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.controllers.kube import FakeClock
from karpenter_tpu.controllers.operator import Operator
from karpenter_tpu.testing import fixtures


def steady_operator(n_pods: int = 10, solver=None) -> Operator:
    op = Operator(clock=FakeClock(), force_oracle=True, solver=solver)
    op.raw_cloud.types = construct_instance_types(sizes=[2, 8, 32])
    op.raw_cloud._by_name = {it.name: it for it in op.raw_cloud.types}
    fixtures.reset_rng(5)
    op.kube.create(
        "NodePool",
        fixtures.node_pool(
            name="default",
            budgets=[Budget(nodes="100%")],
            consolidate_after_seconds=0.0,
        ),
    )
    for i in range(n_pods):
        op.kube.create(
            "Pod",
            fixtures.pod(name=f"w-{i}", requests={"cpu": "400m", "memory": "256Mi"}),
        )
    op.run_until_settled(max_ticks=60)
    for p in op.kube.list("Pod"):
        p.phase = PodPhase.RUNNING
        op.kube.update("Pod", p)
    return op


def test_no_runaway_scaleup_under_steady_workload():
    """chaos_test.go:48 ScaleUp guard: with nothing changing, node count
    must converge within a bounded number of loop iterations and then hold
    perfectly still — every later tick sees the same node set."""
    op = steady_operator()
    history = []
    for _ in range(120):  # 4 simulated minutes of control loops
        op.step(2.0)
        history.append(len(op.kube.list("Node")))

    # convergence: the last 40 ticks (80s — several disruption TTL windows)
    # hold one value
    tail = history[-40:]
    assert len(set(tail)) == 1, f"node count oscillates: {history[-60:]}"
    # no runaway: the fleet never exceeds a sane multiple of its converged
    # size (the chaos test's scale-up guard)
    assert max(history) <= max(2 * tail[0], tail[0] + 2), history
    # the workload survived every disruption decision
    pods = op.kube.list("Pod")
    assert pods and all(p.node_name for p in pods)
    # and the exact node SET is stable, not just the count
    names_a = {n.name for n in op.kube.list("Node")}
    for _ in range(10):
        op.step(2.0)
    names_b = {n.name for n in op.kube.list("Node")}
    assert names_a == names_b, "steady state must not churn nodes"


def test_no_oscillation_after_consolidation():
    """After a consolidation shrinks the fleet, the provisioner must not
    re-expand it (the classic runaway loop: delete -> reprovision ->
    delete...). Converge, then hold."""
    op = steady_operator(n_pods=6)
    # over-provision by hand: add then remove load so nodes turn empty
    for i in range(6):
        op.kube.create(
            "Pod",
            fixtures.pod(name=f"burst-{i}", requests={"cpu": "1500m"}),
        )
    op.run_until_settled(max_ticks=60)
    for i in range(6):
        op.kube.delete("Pod", f"burst-{i}")
    # let consolidation clean up, then require stability. A replica
    # controller keeps the steady workload alive (the reference chaos test
    # runs a Deployment): evicted pods are recreated pending
    history = []
    for _ in range(150):
        op.step(2.0)
        live = {p.name for p in op.kube.list("Pod")}
        for i in range(6):
            if f"w-{i}" not in live:
                op.kube.create(
                    "Pod",
                    fixtures.pod(
                        name=f"w-{i}",
                        requests={"cpu": "400m", "memory": "256Mi"},
                    ),
                )
        history.append(len(op.kube.list("Node")))
    tail = history[-40:]
    assert len(set(tail)) == 1, f"post-consolidation oscillation: {history[-60:]}"
    assert tail[0] <= history[0], "fleet must not grow after load drops"
    pods = op.kube.list("Pod")
    assert pods and all(p.node_name for p in pods)


def test_long_horizon_churn_with_all_disruption_methods_armed():
    """VERDICT r5 #10 / chaos_test.go:48-90 extended: 60 reconcile loops of
    pod churn (arrivals + departures every loop) with consolidation,
    drift, expiration, and node repair ALL armed at once. The fleet must
    track the workload — no runaway scale-up, no oscillation, every
    surviving pod bound at the end — and the run is deterministic."""
    from karpenter_tpu.options import FeatureGates, Options

    opts = Options(feature_gates=FeatureGates(node_repair=True))
    op = Operator(clock=FakeClock(), force_oracle=True, options=opts)
    op.raw_cloud.types = construct_instance_types(sizes=[2, 8, 32])
    op.raw_cloud._by_name = {it.name: it for it in op.raw_cloud.types}
    fixtures.reset_rng(9)
    np_ = fixtures.node_pool(
        name="default",
        budgets=[Budget(nodes="100%")],
        consolidate_after_seconds=10.0,
    )
    # expiration armed: nodes older than 10 simulated minutes recycle
    np_.template.expire_after_seconds = 600.0
    op.kube.create("NodePool", np_)
    for i in range(8):
        op.kube.create(
            "Pod",
            fixtures.pod(name=f"w-{i}", requests={"cpu": "400m", "memory": "256Mi"}),
        )
    op.run_until_settled(max_ticks=60)
    for p in op.kube.list("Pod"):
        p.phase = PodPhase.RUNNING
        op.kube.update("Pod", p)

    history = []
    next_id = 8
    drift_done = repair_done = False
    for loop in range(60):
        # churn: one pod leaves, one arrives (names keep advancing so the
        # workload is never the same object twice)
        pods = sorted(
            (p for p in op.kube.list("Pod") if p.node_name),
            key=lambda p: p.metadata.creation_timestamp,
        )
        if pods:
            op.kube.delete("Pod", pods[0].name)
        op.kube.create(
            "Pod",
            fixtures.pod(
                name=f"w-{next_id}", requests={"cpu": "400m", "memory": "256Mi"}
            ),
        )
        next_id += 1
        if loop == 20 and not drift_done:
            # drift: change the nodepool template mid-run
            np_live = op.kube.get("NodePool", "default")
            np_live.template.labels["generation"] = "two"
            op.kube.update("NodePool", np_live)
            drift_done = True
        if loop == 35 and not repair_done:
            # repair: one node goes NotReady and stays there
            nodes = op.kube.list("Node")
            if nodes:
                nodes[0].conditions["Ready"] = "False"
                nodes[0].ready = False
                op.kube.update("Node", nodes[0])
                repair_done = True
        # a few control-plane ticks per loop; pods that bound go Running
        for _ in range(3):
            op.step(10.0)
        for p in op.kube.list("Pod"):
            if p.node_name and p.phase == PodPhase.PENDING:
                p.phase = PodPhase.RUNNING
                op.kube.update("Pod", p)
        history.append(len(op.kube.list("Node")))

    # bounded fleet: churn of a constant-size workload must never balloon
    assert max(history) <= 8, f"runaway fleet: {history}"
    # let the dust settle fully, then converge
    op.run_until_settled(max_ticks=80)
    for _ in range(20):
        op.step(5.0)
        for p in op.kube.list("Pod"):
            if p.node_name and p.phase == PodPhase.PENDING:
                p.phase = PodPhase.RUNNING
                op.kube.update("Pod", p)
    pods = op.kube.list("Pod")
    assert pods and all(p.node_name for p in pods), [
        p.name for p in pods if not p.node_name
    ]
    # claims and nodes agree (no leaked claims from the churn)
    assert len(op.kube.list("NodeClaim")) == len(op.kube.list("Node"))
    # the drifted + repaired nodes were recycled: every surviving node
    # carries the new template generation label
    for n in op.kube.list("Node"):
        assert n.metadata.labels.get("generation") == "two", n.name
        assert n.ready


def test_steady_workload_converges_identically_through_sidecar():
    """Satellite (ISSUE): the steady-workload chaos scenario with the
    sidecar in the loop — every provisioning solve rides SolverClient over
    the UDS boundary instead of solving in-process. Convergence must be
    IDENTICAL: same per-tick node counts, same final pod partition. The
    resilience layer must not alter any scheduling decision."""
    import tempfile

    from karpenter_tpu.solver import ResilientSolver
    from karpenter_tpu.solver.service import SolverServer

    def run(solver=None):
        op = steady_operator(solver=solver)
        counts = []
        for _ in range(40):
            op.step(2.0)
            counts.append(len(op.kube.list("Node")))
        # the final partition: which pod names share which node, node
        # names erased (the claim-name sequence is process-global)
        by_node: dict[str, set] = {}
        for p in op.kube.list("Pod"):
            by_node.setdefault(p.node_name, set()).add(p.name)
        partition = sorted(tuple(sorted(s)) for s in by_node.values())
        return counts, partition

    counts_local, partition_local = run(solver=None)

    path = tempfile.mktemp(suffix=".sock")
    srv = SolverServer(path)
    srv.start()
    try:
        rs = ResilientSolver(path, request_timeout_seconds=120.0)
        counts_remote, partition_remote = run(solver=rs)
        assert srv.solves > 0, "the sidecar was never consulted"
        assert rs.breaker.state == "closed"
    finally:
        srv.stop()

    assert counts_remote == counts_local, (
        f"sidecar run diverged: {counts_remote} != {counts_local}"
    )
    assert partition_remote == partition_local
    # converged and stayed converged, like the in-process guard demands
    tail = counts_remote[-10:]
    assert len(set(tail)) == 1, f"node count oscillates: {counts_remote}"
