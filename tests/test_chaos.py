"""Chaos guard: under a steady workload, the provision/consolidate loop
must converge and STAY converged — no runaway scale-up or node-count
oscillation (reference test/suites/regression/chaos_test.go:48-90, which
watches for consolidation and emptiness fighting the provisioner).
"""

from __future__ import annotations

from karpenter_tpu.api.objects import Budget, PodPhase
from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.controllers.kube import FakeClock
from karpenter_tpu.controllers.operator import Operator
from karpenter_tpu.testing import fixtures


def steady_operator(n_pods: int = 10) -> Operator:
    op = Operator(clock=FakeClock(), force_oracle=True)
    op.raw_cloud.types = construct_instance_types(sizes=[2, 8, 32])
    op.raw_cloud._by_name = {it.name: it for it in op.raw_cloud.types}
    fixtures.reset_rng(5)
    op.kube.create(
        "NodePool",
        fixtures.node_pool(
            name="default",
            budgets=[Budget(nodes="100%")],
            consolidate_after_seconds=0.0,
        ),
    )
    for i in range(n_pods):
        op.kube.create(
            "Pod",
            fixtures.pod(name=f"w-{i}", requests={"cpu": "400m", "memory": "256Mi"}),
        )
    op.run_until_settled(max_ticks=60)
    for p in op.kube.list("Pod"):
        p.phase = PodPhase.RUNNING
        op.kube.update("Pod", p)
    return op


def test_no_runaway_scaleup_under_steady_workload():
    """chaos_test.go:48 ScaleUp guard: with nothing changing, node count
    must converge within a bounded number of loop iterations and then hold
    perfectly still — every later tick sees the same node set."""
    op = steady_operator()
    history = []
    for _ in range(120):  # 4 simulated minutes of control loops
        op.step(2.0)
        history.append(len(op.kube.list("Node")))

    # convergence: the last 40 ticks (80s — several disruption TTL windows)
    # hold one value
    tail = history[-40:]
    assert len(set(tail)) == 1, f"node count oscillates: {history[-60:]}"
    # no runaway: the fleet never exceeds a sane multiple of its converged
    # size (the chaos test's scale-up guard)
    assert max(history) <= max(2 * tail[0], tail[0] + 2), history
    # the workload survived every disruption decision
    pods = op.kube.list("Pod")
    assert pods and all(p.node_name for p in pods)
    # and the exact node SET is stable, not just the count
    names_a = {n.name for n in op.kube.list("Node")}
    for _ in range(10):
        op.step(2.0)
    names_b = {n.name for n in op.kube.list("Node")}
    assert names_a == names_b, "steady state must not churn nodes"


def test_no_oscillation_after_consolidation():
    """After a consolidation shrinks the fleet, the provisioner must not
    re-expand it (the classic runaway loop: delete -> reprovision ->
    delete...). Converge, then hold."""
    op = steady_operator(n_pods=6)
    # over-provision by hand: add then remove load so nodes turn empty
    for i in range(6):
        op.kube.create(
            "Pod",
            fixtures.pod(name=f"burst-{i}", requests={"cpu": "1500m"}),
        )
    op.run_until_settled(max_ticks=60)
    for i in range(6):
        op.kube.delete("Pod", f"burst-{i}")
    # let consolidation clean up, then require stability. A replica
    # controller keeps the steady workload alive (the reference chaos test
    # runs a Deployment): evicted pods are recreated pending
    history = []
    for _ in range(150):
        op.step(2.0)
        live = {p.name for p in op.kube.list("Pod")}
        for i in range(6):
            if f"w-{i}" not in live:
                op.kube.create(
                    "Pod",
                    fixtures.pod(
                        name=f"w-{i}",
                        requests={"cpu": "400m", "memory": "256Mi"},
                    ),
                )
        history.append(len(op.kube.list("Node")))
    tail = history[-40:]
    assert len(set(tail)) == 1, f"post-consolidation oscillation: {history[-60:]}"
    assert tail[0] <= history[0], "fleet must not grow after load drops"
    pods = op.kube.list("Pod")
    assert pods and all(p.node_name for p in pods)
