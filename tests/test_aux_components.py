"""Decorators, NodeOverlay, static capacity, metrics controllers, options,
events, and the metrics registry."""

from __future__ import annotations

import pytest

from karpenter_tpu import metrics
from karpenter_tpu.api import labels as well_known
from karpenter_tpu.api.objects import NodeSelectorRequirement, ObjectMeta, Operator
from karpenter_tpu.cloudprovider.decorators import (
    SPI_DURATION,
    InstanceTypeStore,
    MetricsCloudProvider,
    OverlayCloudProvider,
)
from karpenter_tpu.cloudprovider.kwok import construct_instance_types
from karpenter_tpu.controllers.kube import FakeClock
from karpenter_tpu.controllers.nodeoverlay import NodeOverlay, NodeOverlayController
from karpenter_tpu.controllers.operator import Operator as Op
from karpenter_tpu.events import Event, Recorder
from karpenter_tpu.options import FeatureGates, Options
from karpenter_tpu.testing import fixtures


def small_op(**kw):
    op = Op(clock=FakeClock(), force_oracle=True, **kw)
    op.raw_cloud.types = construct_instance_types(sizes=[2, 8])
    op.raw_cloud._by_name = {it.name: it for it in op.raw_cloud.types}
    return op


def test_metrics_decorator_times_calls():
    op = small_op()
    before = SPI_DURATION.count(
        {"controller": "", "method": "get_instance_types", "provider": "kwok"}
    )
    np_ = fixtures.node_pool(name="default")
    op.cloud.get_instance_types(np_)
    after = SPI_DURATION.count(
        {"controller": "", "method": "get_instance_types", "provider": "kwok"}
    )
    assert after == before + 1


def test_overlay_price_adjustment():
    op = small_op()
    np_ = fixtures.node_pool(name="default")
    op.kube.create("NodePool", np_)
    store = InstanceTypeStore()
    overlay_cloud = OverlayCloudProvider(op.cloud, store)
    ctrl = NodeOverlayController(op.kube, op.cloud, store)

    ov = NodeOverlay(
        metadata=ObjectMeta(name="spot-discount"),
        requirements=[
            NodeSelectorRequirement(
                "karpenter.kwok.sh/instance-family", Operator.IN, ["c"]
            )
        ],
        price_adjustment="-50%",
    )
    op.kube.create("NodeOverlay", ov)
    assert ctrl.reconcile_all() == {}

    base = {it.name: it for it in op.cloud.get_instance_types(np_)}
    patched = {it.name: it for it in overlay_cloud.get_instance_types(np_)}
    for name, it in patched.items():
        if name.startswith("c-"):
            assert it.offerings[0].price == pytest.approx(
                base[name].offerings[0].price * 0.5
            )
        else:
            assert it.offerings[0].price == base[name].offerings[0].price


def test_overlay_capacity_patch_and_validation():
    op = small_op()
    op.kube.create("NodePool", fixtures.node_pool(name="default"))
    store = InstanceTypeStore()
    ctrl = NodeOverlayController(op.kube, op.cloud, store)

    good = NodeOverlay(
        metadata=ObjectMeta(name="add-gpu"),
        requirements=[],
        capacity={"example.com/gpu": 4000},
    )
    bad = NodeOverlay(
        metadata=ObjectMeta(name="conflicted"),
        price=1.0,
        price_adjustment="+10%",
    )
    op.kube.create("NodeOverlay", good)
    op.kube.create("NodeOverlay", bad)
    problems = ctrl.reconcile_all()
    assert "conflicted" in problems
    patched = store.get("default")
    assert all(it.capacity.get("example.com/gpu") == 4000 for it in patched)


def test_static_provisioning_and_deprovisioning():
    gates = FeatureGates(static_capacity=True)
    op = small_op(options=Options(feature_gates=gates))
    from karpenter_tpu.controllers.static import (
        StaticDeprovisioning,
        StaticProvisioning,
    )

    np_ = fixtures.node_pool(name="warmpool", replicas=3)
    op.kube.create("NodePool", np_)
    prov = StaticProvisioning(op.kube, op.cluster, op.recorder)
    deprov = StaticDeprovisioning(op.kube, op.cluster, op.recorder)

    assert prov.reconcile_all() == 3
    assert prov.reconcile_all() == 0  # idempotent
    assert len(op.kube.list("NodeClaim")) == 3
    op.run_until_settled(max_ticks=30)
    assert len(op.kube.list("Node")) == 3

    # scale down
    np_ = op.kube.list("NodePool")[0]
    np_.replicas = 1
    op.kube.update("NodePool", np_)
    assert deprov.reconcile_all() == 2
    for _ in range(12):
        op.step(2.0)
    assert len(op.kube.list("Node")) == 1
    assert prov.reconcile_all() == 0


def test_node_and_pod_metrics_controllers():
    from karpenter_tpu.controllers.metrics_controllers import (
        NODE_ALLOCATABLE,
        POD_STATE,
        NodeMetricsController,
        NodePoolMetricsController,
        PodMetricsController,
    )

    op = small_op()
    fixtures.reset_rng(3)
    op.kube.create("NodePool", fixtures.node_pool(name="default", limits={"cpu": "100"}))
    for p in fixtures.make_generic_pods(3):
        op.kube.create("Pod", p)
    op.run_until_settled(max_ticks=30)

    NodeMetricsController(op.cluster).reconcile_all()
    NodePoolMetricsController(op.kube).reconcile_all()
    PodMetricsController(op.kube, op.cluster, op.clock).reconcile_all()

    node = op.kube.list("Node")[0]
    got = NODE_ALLOCATABLE.value(
        {"node_name": node.name, "nodepool": "default", "resource_type": "cpu"}
    )
    assert got > 0
    assert POD_STATE.value({"phase": "Pending"}) >= 0

    # metric GC: node vanishes -> series vanish
    claim = op.kube.list("NodeClaim")[0]
    op.kube.delete("NodeClaim", claim.name)
    for _ in range(12):
        op.step(2.0)
    NodeMetricsController(op.cluster).reconcile_all()
    assert (
        NODE_ALLOCATABLE.value(
            {"node_name": node.name, "nodepool": "default", "resource_type": "cpu"}
        )
        == 0.0
    )


def test_options_env_and_gates():
    opts = Options.from_env(
        {
            "KARPENTER_BATCH_IDLE_DURATION": "2.5",
            "KARPENTER_PREFERENCE_POLICY": "Ignore",
            "KARPENTER_FEATURE_GATES": "SpotToSpotConsolidation,NodeRepair=true,NodeOverlay=false",
        }
    )
    assert opts.batch_idle_duration_seconds == 2.5
    assert opts.preference_policy == "Ignore"
    assert opts.feature_gates.spot_to_spot_consolidation
    assert opts.feature_gates.node_repair
    assert not opts.feature_gates.node_overlay


def test_recorder_dedupe():
    clock = FakeClock()
    r = Recorder(clock)
    e = Event("Pod", "p1", "Warning", "FailedScheduling", "no capacity")
    r.publish(e)
    r.publish(e)
    assert len(r.events) == 1
    # different message is a different cause -> published
    r.publish(Event("Pod", "p1", "Warning", "FailedScheduling", "taint mismatch"))
    assert len(r.events) == 2
    clock.advance(121.0)
    r.publish(e)
    assert len(r.events) == 3


def test_metrics_render_exposition():
    reg = metrics.Registry()
    c = reg.counter("karpenter_test_total", "help text", ("kind",))
    c.inc({"kind": "a"})
    h = reg.histogram("karpenter_test_seconds", "help", buckets=[0.1, 1.0])
    h.observe(0.5)
    h.observe(5.0)  # above the last bucket -> only +Inf
    text = reg.render()
    assert 'karpenter_test_total{kind="a"} 1.0' in text
    assert 'le="+Inf"} 2' in text
    assert "karpenter_test_seconds_count 2" in text


def test_structured_logging_of_control_loop():
    """operator/logging/logging.go analog: controllers emit machine-
    parseable JSON records with named loggers and structured fields."""
    from karpenter_tpu import logging as klog

    with klog.capture(level="debug") as records:
        op = small_op()
        op.kube.create("NodePool", fixtures.node_pool(name="default"))
        op.kube.create(
            "Pod", fixtures.pod(name="w", requests={"cpu": "200m"})
        )
        op.run_until_settled(max_ticks=40)
        records.refresh()
    loggers = {r["logger"] for r in records}
    assert "karpenter.provisioner" in loggers
    assert "karpenter.nodeclaim.lifecycle" in loggers
    prov = next(r for r in records if r["logger"] == "karpenter.provisioner")
    assert prov["msg"] == "provisioning round complete"
    assert prov["new_claims"] >= 1 and prov["solver"] in ("tpu", "oracle")
    launch = next(
        r for r in records if r["logger"] == "karpenter.nodeclaim.lifecycle"
    )
    assert launch["nodeclaim"]
    # level gating: info filter drops nothing here, but a warn-only root
    # must silence the info records
    with klog.capture(level="warn") as quiet:
        klog.root.named("provisioner").info("hidden")
        klog.root.named("provisioner").warn("visible")
    assert [r["msg"] for r in quiet] == ["visible"]


def test_probe_server_endpoints():
    """operator.go:183-221: /healthz always ok, /readyz gated on the state
    cache's synced barrier, /metrics serves the exposition."""
    import urllib.request

    from karpenter_tpu.controllers.probes import ProbeServer

    op = small_op()
    op.kube.create("NodePool", fixtures.node_pool(name="default"))
    srv = ProbeServer(op.kube, op.cluster)
    srv.start()
    try:
        base = f"http://127.0.0.1:{srv.port}"

        def get(path):
            try:
                with urllib.request.urlopen(base + path, timeout=5) as r:
                    return r.status, r.read().decode()
            except urllib.error.HTTPError as e:
                return e.code, e.read().decode()

        assert get("/healthz") == (200, "ok")
        code, _ = get("/readyz")
        assert code == 200  # synced: nothing in the store the cache lacks
        code, body = get("/metrics")
        assert code == 200 and "karpenter" in body
        # a claim the informers haven't... (simulate a stale cache by
        # poking a claim into the raw store without events)
        claim_store = op.kube._store("NodeClaim")
        from karpenter_tpu.api.objects import NodeClaim, ObjectMeta

        claim_store["ghost"] = NodeClaim(metadata=ObjectMeta(name="ghost"))
        code, body = get("/readyz")
        assert code == 503 and "not synced" in body
    finally:
        srv.stop()


def test_operator_stop_releases_probe_port_and_clock():
    """Operator.stop(): the probe socket/thread are released (a second
    operator can bind the SAME port) and the global logger's sim clock is
    detached."""
    from karpenter_tpu import logging as klog
    from karpenter_tpu.options import Options

    op = Op(clock=FakeClock(), force_oracle=True, options=Options(probe_port=0))
    port = op.probes.port
    op.stop()
    assert op.probes is None
    assert klog.root._clock is None
    op2 = Op(clock=FakeClock(), force_oracle=True, options=Options(probe_port=port))
    assert op2.probes.port == port
    op2.stop()


def test_profiling_sampler_and_heap():
    """profiling.py: the sampling profiler captures a busy thread's stack
    (pprof CPU analog) and the heap snapshot reports allocation sites."""
    import threading

    from karpenter_tpu import profiling

    stop = threading.Event()

    def busy_beaver():
        while not stop.is_set():
            sum(i * i for i in range(2000))

    t = threading.Thread(target=busy_beaver, daemon=True)
    t.start()
    try:
        sampler = profiling.profile_cpu(seconds=0.4, hz=200)
    finally:
        stop.set()
        t.join()
    assert sampler.total > 0
    collapsed = sampler.render_collapsed()
    assert "busy_beaver" in collapsed
    top = sampler.render_top()
    # render_top attributes to LEAF frames — the busy thread's leaf is the
    # generator inside sum(), not the enclosing function
    assert "samples:" in top and "genexpr" in top

    # keep_tracing=True holds tracemalloc open so the next snapshot can see
    # allocations made in between (the default stops tracing per request)
    profiling.heap_snapshot(keep_tracing=True)
    blob = [bytearray(64) for _ in range(2000)]  # now-visible allocation
    heap = profiling.heap_snapshot()
    assert "bytes traced" in heap
    assert "B " in heap
    del blob
    import tracemalloc

    assert not tracemalloc.is_tracing()  # second call stopped it


def test_pprof_endpoints_gated_by_flag():
    """operator.go:183 --enable-profiling: the pprof endpoints exist only
    when the flag is set; /profile returns collapsed stacks, /heap the
    tracemalloc table."""
    import urllib.error
    import urllib.request

    from karpenter_tpu.controllers.probes import ProbeServer

    op = small_op()

    def get(srv, path):
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}{path}", timeout=15
            ) as r:
                return r.status, r.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode()

    off = ProbeServer(op.kube, op.cluster)
    off.start()
    try:
        code, _ = get(off, "/debug/pprof/profile?seconds=0.1")
        assert code == 404  # gate closed
    finally:
        off.stop()

    on = ProbeServer(op.kube, op.cluster, enable_profiling=True)
    on.start()
    try:
        code, body = get(on, "/debug/pprof/profile?seconds=0.2&top=1")
        assert code == 200 and "samples:" in body
        code, body = get(on, "/debug/pprof/heap")
        assert code == 200 and "bytes traced" in body
    finally:
        on.stop()


def test_solve_profile_phases():
    """The per-solve phase breakdown now rides tracing.Trace (the
    SolveProfile successor): phases accumulate per name and render as a
    share table."""
    from karpenter_tpu import tracing

    prof = tracing.new_trace("unit")
    with prof.span("a"):
        pass
    with prof.span("b"):
        with prof.span("a"):
            pass
    prof.finish()
    out = prof.render()
    assert "a" in out and "b" in out
    assert prof.phases["a"] >= 0.0
    assert set(prof.top_phases()) == {"a", "b"}


def test_leader_election_lease_lifecycle(tmp_path):
    """leaderelection.py: single holder, renewal, expiry takeover, and
    voluntary release (operator.go:157-182 Lease semantics)."""
    from karpenter_tpu.controllers.kube import FakeClock
    from karpenter_tpu.leaderelection import LeaderElector

    clock = FakeClock()
    lease = str(tmp_path / "lease.json")
    a = LeaderElector(lease, identity="a", lease_duration=15, renew_period=5, clock=clock)
    b = LeaderElector(lease, identity="b", lease_duration=15, renew_period=5, clock=clock)

    assert a.ensure() is True
    assert b.ensure() is False  # a holds
    assert a.holder() == "a" and b.holder() == "a"

    # renewal keeps the lease across many periods
    for _ in range(5):
        clock.advance(5.0)
        assert a.ensure() is True
        assert b.ensure() is False

    # a goes silent -> b takes over after the lease expires
    clock.advance(15.1)
    assert b.ensure() is True
    assert b.holder() == "b"
    # the deposed holder notices: ensure() re-reads and fails
    assert a.ensure() is False
    assert a.is_leader is False

    # voluntary release hands off without waiting out the lease
    b.release()
    assert a.ensure() is True


def test_leader_election_fences_stale_holder(tmp_path):
    """A holder that cannot renew within its own lease duration stops
    counting itself leader even before a successor appears."""
    from karpenter_tpu.controllers.kube import FakeClock
    from karpenter_tpu.leaderelection import LeaderElector

    clock = FakeClock()
    a = LeaderElector(
        str(tmp_path / "l.json"), identity="a",
        lease_duration=15, renew_period=5, clock=clock,
    )
    assert a.ensure()
    assert a.is_leader
    clock.advance(15.1)  # wedged: no ensure() happened in time
    assert a.is_leader is False


def test_operator_standby_until_leader(tmp_path):
    """An Operator configured with a lease acts only while holding it: the
    standby provisions nothing; after the leader releases, the standby's
    next step takes over and provisions."""
    from karpenter_tpu.controllers.kube import FakeClock
    from karpenter_tpu.controllers.operator import Operator
    from karpenter_tpu.options import Options

    lease = str(tmp_path / "op-lease.json")
    clock = FakeClock()

    def opts():
        return Options(
            leader_elect_lease_path=lease,
            leader_elect_lease_seconds=30,
            leader_elect_renew_seconds=5,
        )

    leader = Operator(clock=clock, force_oracle=True, options=opts())
    standby = Operator(clock=clock, force_oracle=True, options=opts())
    leader.step()  # acquires
    standby.step()  # sees the lease held
    assert leader.elector.is_leader
    assert not standby.elector.is_leader

    standby.kube.create("NodePool", fixtures.node_pool(name="default"))
    fixtures.reset_rng(5)
    for p in fixtures.make_generic_pods(4):
        standby.kube.create("Pod", p)
    for _ in range(20):
        leader.step(0.0)  # keep renewing (the clock is shared)
        standby.step(2.0)
    assert not standby.kube.list("Node"), "standby must not provision"

    leader.stop()  # releases the lease
    for _ in range(30):
        standby.step(2.0)
    assert standby.elector.is_leader
    assert standby.kube.list("Node"), "new leader provisions"
    standby.stop()


def test_parallelize_until_drains_and_collects_errors():
    """utils/workerpool.py: every index runs even when siblings fail; the
    caller gets per-index errors (reconcile semantics — no abort)."""
    import threading

    from karpenter_tpu.utils.workerpool import parallelize_until

    seen = set()
    lock = threading.Lock()

    def fn(i):
        with lock:
            seen.add(i)
        if i % 3 == 0:
            raise RuntimeError(f"boom-{i}")

    errs = parallelize_until(4, 10, fn)
    assert seen == set(range(10))
    assert [i for i, e in enumerate(errs) if e is not None] == [0, 3, 6, 9]
    # sequential path: same contract
    seen.clear()
    errs = parallelize_until(1, 4, fn)
    assert seen == set(range(4)) and errs[0] is not None and errs[1] is None


def test_concurrent_termination_drains_fleet():
    """The termination reconciler pool (termination/controller.go:58-60):
    deleting many nodes with a multi-worker pool converges to the same
    fully-drained end state as the sequential pool."""
    op = small_op(options=Options(termination_workers=8))
    assert op.termination.workers == 8
    op.kube.create("NodePool", fixtures.node_pool(name="default"))
    fixtures.reset_rng(9)
    for p in fixtures.make_generic_pods(12):
        op.kube.create("Pod", p)
    op.run_until_settled(max_ticks=40)
    nodes = op.kube.list("Node")
    assert nodes

    for n in nodes:
        op.kube.delete("Node", n.name)
    for _ in range(40):
        op.step(2.0)
        if not op.kube.list("Node"):
            break
    assert not op.kube.list("Node"), "all nodes must finish termination"


def test_parallel_eviction_respects_shared_pdb():
    """Two deleting nodes whose pods share a maxUnavailable=1 PDB: a
    multi-worker termination round must start at most ONE eviction — the
    eviction path serializes PDB accounting (terminator/eviction.go:93 is
    a single queue in the reference for exactly this reason)."""
    from karpenter_tpu.api.objects import PodDisruptionBudget, LabelSelector

    op = small_op(options=Options(termination_workers=8))
    op.kube.create("NodePool", fixtures.node_pool(name="default"))
    fixtures.reset_rng(9)
    # two pods forced onto separate nodes via hostname anti-affinity
    from karpenter_tpu.api.objects import PodAffinityTerm

    for i in range(2):
        op.kube.create(
            "Pod",
            fixtures.pod(
                name=f"guarded-{i}",
                labels={"app": "guarded"},
                requests={"cpu": "100m"},
                pod_anti_requirements=[
                    PodAffinityTerm(
                        topology_key=well_known.HOSTNAME_LABEL_KEY,
                        label_selector=LabelSelector(
                            match_labels={"app": "guarded"}
                        ),
                    )
                ],
            ),
        )
    op.run_until_settled(max_ticks=40)
    nodes = op.kube.list("Node")
    assert len(nodes) == 2
    from karpenter_tpu.api.objects import PodPhase

    for p in op.kube.list("Pod"):
        p.phase = PodPhase.RUNNING
        op.kube.update("Pod", p)
    op.kube.create(
        "PodDisruptionBudget",
        PodDisruptionBudget(
            metadata=ObjectMeta(name="guard"),
            selector=LabelSelector(match_labels={"app": "guarded"}),
            max_unavailable="1",
        ),
    )
    for n in nodes:
        op.kube.delete("Node", n.name)
    op.termination.reconcile_all()  # ONE parallel round
    terminating = [p for p in op.kube.list("Pod") if p.terminating]
    assert len(terminating) <= 1, "PDB allows one disruption, not two"
    assert len(terminating) == 1, "one eviction should have proceeded"


def test_short_lease_challenger_cannot_depose_long_lease_holder(tmp_path):
    """Expiry is judged by the HOLDER's advertised lease duration (stored
    in the record), not the challenger's config — a 15s-lease candidate
    must not steal from a healthy 60s-lease holder mid-lease."""
    from karpenter_tpu.controllers.kube import FakeClock
    from karpenter_tpu.leaderelection import LeaderElector

    clock = FakeClock()
    lease = str(tmp_path / "lease.json")
    long_ = LeaderElector(
        lease, identity="long", lease_duration=60, renew_period=20, clock=clock
    )
    short = LeaderElector(
        lease, identity="short", lease_duration=15, renew_period=5, clock=clock
    )
    assert long_.ensure()
    clock.advance(16.0)  # past short's duration, well inside long's
    assert short.ensure() is False
    assert long_.is_leader
    # but once the holder's OWN duration lapses, the takeover is legal
    clock.advance(60.0)
    assert short.ensure() is True


# ---------------------------------------------------------------------------
# nodeclaim/podevents: event-driven lastPodEventTime stamping
# (podevents/controller.go:63-99 + the Register filter at controller.go:104)


def _settled_claim_op(consolidate_after: float = 30.0):
    """One nodepool, two running pods on one claim, conditions settled."""
    from karpenter_tpu.api.objects import PodPhase

    op = small_op()
    op.kube.create(
        "NodePool",
        fixtures.node_pool(
            name="default", consolidate_after_seconds=consolidate_after
        ),
    )
    for i in range(2):
        op.kube.create(
            "Pod",
            fixtures.pod(
                name=f"w-{i}", requests={"cpu": "500m", "memory": "512Mi"}
            ),
        )
    assert op.run_until_settled(max_ticks=40) < 40
    for p in op.kube.list("Pod"):
        p.phase = PodPhase.RUNNING
        op.kube.update("Pod", p)
    (claim,) = op.kube.list("NodeClaim")
    assert claim.status.node_name
    return op, claim.name


def test_podevents_equal_count_churn_blocks_consolidatable():
    """One pod leaves and another binds between reconcile ticks: the pod
    COUNT is unchanged, but the node is busy — the claim must NOT become
    Consolidatable (the r4 count-delta heuristic missed exactly this;
    podevents/controller.go stamps on the events themselves)."""
    op, claim_name = _settled_claim_op(consolidate_after=30.0)
    node_name = op.kube.get("NodeClaim", claim_name).status.node_name

    # go quiet long enough that, absent fresh pod events, consolidateAfter
    # has elapsed (also clears the 10s stamp dedupe window)
    op.clock.advance(60.0)
    # churn: one pod out, one pod in — count net zero, no reconcile between
    op.kube.delete("Pod", "w-0")
    op.kube.create("Pod", fixtures.pod(name="w-new", requests={"cpu": "500m"}))
    op.kube.bind("w-new", node_name)

    op.clock.advance(1.0)
    op.pod_events.reconcile_all()  # a no-op tick: stamping is watch-driven
    op.claim_conditions.reconcile_all()
    claim = op.kube.get("NodeClaim", claim_name)
    assert claim.status.last_pod_event_time >= 60.0
    from karpenter_tpu.api.objects import COND_CONSOLIDATABLE

    assert claim.status.conditions.get(COND_CONSOLIDATABLE) == "False"

    # and with no further events, quiet time elapses and it DOES fire
    op.clock.advance(31.0)
    op.claim_conditions.reconcile_all()
    claim = op.kube.get("NodeClaim", claim_name)
    assert claim.status.conditions.get(COND_CONSOLIDATABLE) == "True"


def test_podevents_stamps_on_terminal_and_terminating_transitions():
    """The Register filter (controller.go:110-117): newly-terminal and
    newly-terminating pods stamp; unrelated updates don't."""
    from karpenter_tpu.api.objects import PodPhase

    op, claim_name = _settled_claim_op()
    t0 = op.kube.get("NodeClaim", claim_name).status.last_pod_event_time

    # unrelated update (labels) — no stamp
    op.clock.advance(20.0)
    p = op.kube.get("Pod", "w-0")
    p.metadata.labels["x"] = "y"
    op.kube.update("Pod", p)
    assert op.kube.get("NodeClaim", claim_name).status.last_pod_event_time == t0

    # newly terminal
    p = op.kube.get("Pod", "w-0")
    p.phase = PodPhase.SUCCEEDED
    op.kube.update("Pod", p)
    t1 = op.kube.get("NodeClaim", claim_name).status.last_pod_event_time
    assert t1 > t0

    # dedupe window: a second event within 10s does not re-stamp
    op.clock.advance(5.0)
    p = op.kube.get("Pod", "w-1")
    p.phase = PodPhase.FAILED
    op.kube.update("Pod", p)
    assert op.kube.get("NodeClaim", claim_name).status.last_pod_event_time == t1

    # past the window, a delete (the sim's compressed terminating
    # transition) stamps again
    op.clock.advance(11.0)
    op.kube.delete("Pod", "w-1")
    t2 = op.kube.get("NodeClaim", claim_name).status.last_pod_event_time
    assert t2 > t1


def test_podevents_ignores_daemonset_pods():
    """controller.go:66 — daemonset-owned pods never stamp."""
    op, claim_name = _settled_claim_op()
    node_name = op.kube.get("NodeClaim", claim_name).status.node_name
    t0 = op.kube.get("NodeClaim", claim_name).status.last_pod_event_time

    op.clock.advance(20.0)
    ds = fixtures.pod(name="ds-0", requests={"cpu": "10m"})
    ds.metadata.annotations["karpenter.sh/daemonset"] = "true"
    op.kube.create("Pod", ds)
    op.kube.bind("ds-0", node_name)
    assert op.kube.get("NodeClaim", claim_name).status.last_pod_event_time == t0


# ---------------------------------------------------------------------------
# nodepool/registrationhealth — reference tracker semantics
# (pkg/state/nodepoolhealth/tracker.go + registrationhealth/controller.go)


def test_reghealth_tracker_thresholds():
    from karpenter_tpu.api.objects import COND_NODE_REGISTRATION_HEALTHY
    from karpenter_tpu.controllers.nodepool_aux import RegistrationHealth

    op = small_op()
    op.kube.create("NodePool", fixtures.node_pool(name="default"))
    rh = RegistrationHealth(op.kube)

    # empty buffer = Unknown
    assert rh.status("default") == rh.UNKNOWN
    # one success flips the condition True at record time (dry-run Healthy)
    rh.record_launch("default", True)
    np = op.kube.get("NodePool", "default")
    assert np.conditions[COND_NODE_REGISTRATION_HEALTHY] == "True"
    assert rh.status("default") == rh.HEALTHY

    # ONE failure after a success is 1/4 falses — still healthy
    rh.record_launch("default", False)
    assert rh.status("default") == rh.HEALTHY
    np = op.kube.get("NodePool", "default")
    assert np.conditions[COND_NODE_REGISTRATION_HEALTHY] == "True"

    # the second failure reaches 2/4 = 50% -> Unhealthy, condition False
    rh.record_launch("default", False)
    assert rh.status("default") == rh.UNHEALTHY
    np = op.kube.get("NodePool", "default")
    assert np.conditions[COND_NODE_REGISTRATION_HEALTHY] == "False"

    # denominator is BUFFER CAPACITY even when partially filled: a fresh
    # pool with a single failure is 1/4 -> Healthy (tracker.go:75)
    assert rh.dry_run("other", False) == rh.HEALTHY


def test_reghealth_hydration_and_spec_reset():
    from karpenter_tpu.api.objects import COND_NODE_REGISTRATION_HEALTHY
    from karpenter_tpu.controllers.nodepool_aux import RegistrationHealth

    op = small_op()
    np = fixtures.node_pool(name="default")
    np.conditions[COND_NODE_REGISTRATION_HEALTHY] = "False"
    op.kube.create("NodePool", np)
    rh = RegistrationHealth(op.kube)

    # restart hydration: buffer empty + condition False -> Unhealthy buffer
    rh.reconcile_all()
    assert rh.status("default") == rh.UNHEALTHY

    # spec change resets to Unknown (controller.go:83-88)
    np = op.kube.get("NodePool", "default")
    np.template.labels["changed"] = "yes"
    op.kube.update("NodePool", np)
    rh.reconcile_all()
    assert rh.status("default") == rh.UNKNOWN
    np = op.kube.get("NodePool", "default")
    assert np.conditions[COND_NODE_REGISTRATION_HEALTHY] == "Unknown"


def test_reghealth_rides_lifecycle_registration():
    """End-to-end: registration success through the lifecycle controller
    flips NodeRegistrationHealthy True (registration.go:113-123)."""
    from karpenter_tpu.api.objects import COND_NODE_REGISTRATION_HEALTHY

    op = small_op()
    op.kube.create("NodePool", fixtures.node_pool(name="default"))
    op.kube.create("Pod", fixtures.pod(name="w", requests={"cpu": "500m"}))
    assert op.run_until_settled(max_ticks=40) < 40
    np = op.kube.get("NodePool", "default")
    assert np.conditions.get(COND_NODE_REGISTRATION_HEALTHY) == "True"


# ---------------------------------------------------------------------------
# nodeclaim/consistency — NodeShape (consistency/nodeshape.go:35-58)


def test_consistency_nodeshape_tolerance():
    from karpenter_tpu.api.objects import COND_CONSISTENT_STATE_FOUND

    op = small_op()
    op.kube.create("NodePool", fixtures.node_pool(name="default"))
    op.kube.create("Pod", fixtures.pod(name="w", requests={"cpu": "500m"}))
    assert op.run_until_settled(max_ticks=40) < 40
    (claim,) = op.kube.list("NodeClaim")
    node = op.kube.get("Node", claim.status.node_name)

    # healthy: within 10% of expected capacity
    problems = op.consistency.reconcile_all()
    assert problems == []
    claim = op.kube.get("NodeClaim", claim.name)
    assert claim.status.conditions[COND_CONSISTENT_STATE_FOUND] == "True"

    # shrink a REQUESTED resource on the node below 90% of expected
    name = claim.name
    from karpenter_tpu.utils import resources as res

    assert claim.resources_requests.get(res.CPU)
    node = op.kube.get("Node", claim.status.node_name)
    node.capacity[res.CPU] = claim.status.capacity[res.CPU] // 2
    op.kube.update("Node", node)
    problems = op.consistency.reconcile_all()
    assert problems and "50.0% of expected" in problems[0]
    claim = op.kube.get("NodeClaim", name)
    assert claim.status.conditions[COND_CONSISTENT_STATE_FOUND] == "False"

    # a small (<10%) shortfall is tolerated (nodeshape.go:51 pct < 0.90)
    node = op.kube.get("Node", claim.status.node_name)
    node.capacity[res.CPU] = claim.status.capacity[res.CPU] * 95 // 100
    op.kube.update("Node", node)
    assert op.consistency.reconcile_all() == []

    # an UNREQUESTED resource's shape is not checked (nodeshape.go:47)
    node = op.kube.get("Node", claim.status.node_name)
    node.capacity["vendor/gpu"] = 0
    claim = op.kube.get("NodeClaim", name)
    claim.status.capacity["vendor/gpu"] = 100
    op.kube.update("NodeClaim", claim)
    op.kube.update("Node", node)
    assert op.consistency.reconcile_all() == []


# ---------------------------------------------------------------------------
# hydration — node-class label backfill (nodeclaim/hydration + node/hydration)


def test_hydration_backfills_nodeclass_labels():
    op = small_op()
    op.kube.create("NodePool", fixtures.node_pool(name="default"))
    op.kube.create("Pod", fixtures.pod(name="w", requests={"cpu": "500m"}))
    assert op.run_until_settled(max_ticks=40) < 40
    (claim,) = op.kube.list("NodeClaim")
    # simulate a pre-upgrade object: strip the label
    claim.metadata.labels.pop(well_known.NODECLASS_LABEL_KEY, None)
    op.kube.update("NodeClaim", claim)

    op.hydration.reconcile_all()
    claim = op.kube.get("NodeClaim", claim.name)
    assert (
        claim.metadata.labels[well_known.NODECLASS_LABEL_KEY]
        == claim.node_class_ref
    )
    node = op.kube.get("Node", claim.status.node_name)
    assert (
        node.metadata.labels[well_known.NODECLASS_LABEL_KEY]
        == claim.node_class_ref
    )


def test_podevents_stamps_on_eviction_terminating():
    """The sim's eviction path sets pod.terminating (no deletion
    timestamp); that IS the newly-terminating transition
    (podevents/controller.go:114) and must stamp."""
    op, claim_name = _settled_claim_op()
    op.clock.advance(20.0)
    t0 = op.kube.get("NodeClaim", claim_name).status.last_pod_event_time
    p = op.kube.get("Pod", "w-0")
    p.terminating = True
    op.kube.update("Pod", p)
    t1 = op.kube.get("NodeClaim", claim_name).status.last_pod_event_time
    assert t1 > t0


def test_pod_lifecycle_timing_metrics():
    """metrics/pod/controller.go:286-447 family: unbound/unstarted waiting
    gauges live while the pod waits and are deleted on resolution;
    bound/startup/decision durations observe once."""
    from karpenter_tpu.api.objects import PodPhase
    from karpenter_tpu.controllers.metrics_controllers import (
        POD_BOUND_DURATION,
        POD_SCHEDULING_DECISION,
        POD_UNBOUND_TIME,
        POD_UNSTARTED_TIME,
    )

    op = small_op()
    op.kube.create("NodePool", fixtures.node_pool(name="default"))
    op.kube.create("Pod", fixtures.pod(name="w", requests={"cpu": "500m"}))
    bound_before = POD_BOUND_DURATION.count()
    decision_before = POD_SCHEDULING_DECISION.count()

    # one tick later: pod is pending/unbound -> waiting gauges are live
    op.clock.advance(5.0)
    op.pod_metrics.reconcile_all()
    labels = {"name": "w", "namespace": "default"}
    assert POD_UNBOUND_TIME.value(labels) >= 5.0
    assert POD_UNSTARTED_TIME.value(labels) >= 5.0

    # settle: pod binds -> bound duration observed, unbound gauge deleted
    assert op.run_until_settled(max_ticks=40) < 40
    op.pod_metrics.reconcile_all()
    assert POD_BOUND_DURATION.count() == bound_before + 1
    assert POD_SCHEDULING_DECISION.count() >= decision_before + 1
    assert POD_UNBOUND_TIME.value(labels) == 0.0  # deleted on binding
    # still pending-not-running: unstarted gauge persists
    assert POD_UNSTARTED_TIME.value(labels) > 0.0

    # pod runs -> startup observed, unstarted gauge deleted
    p = op.kube.get("Pod", "w")
    p.phase = PodPhase.RUNNING
    op.kube.update("Pod", p)
    op.pod_metrics.reconcile_all()
    assert POD_UNSTARTED_TIME.value(labels) == 0.0
