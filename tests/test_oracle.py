"""Oracle scheduler behavior tests.

Scenarios modeled on the reference's scheduler suite
(/root/reference/pkg/controllers/provisioning/scheduling/suite_test.go and
topology_test.go): resource packing, node selectors, taints, topology spread,
pod (anti-)affinity, preference relaxation, nodepool limits/weights, existing
nodes.
"""

import pytest

from karpenter_tpu.api import labels as wk
from karpenter_tpu.api.objects import (
    LabelSelector,
    NodeSelectorRequirement,
    Operator,
    Taint,
    TaintEffect,
    Toleration,
    TopologySpreadConstraint,
    WhenUnsatisfiable,
)
from karpenter_tpu.cloudprovider import fake
from karpenter_tpu.cloudprovider.types import InstanceTypes
from karpenter_tpu.scheduling import Requirements
from karpenter_tpu.solver.nodes import StateNodeView
from karpenter_tpu.solver.oracle import Scheduler, SchedulerOptions
from karpenter_tpu.solver.topology import Topology
from karpenter_tpu.testing import fixtures
from karpenter_tpu.utils import resources as res
from karpenter_tpu.utils.quantity import parse as q


def build(pods, node_pools=None, instance_types=None, state_nodes=None, options=None):
    node_pools = node_pools or [fixtures.node_pool()]
    its = instance_types if instance_types is not None else fake.instance_types(20)
    by_pool = {np.name: InstanceTypes(its) for np in node_pools}
    topology = Topology(
        node_pools,
        by_pool,
        pods,
        state_node_views=state_nodes or [],
        ignore_preferences=bool(options and options.ignore_preferences),
    )
    return Scheduler(
        node_pools,
        by_pool,
        topology,
        state_nodes=state_nodes,
        options=options,
    )


def test_single_pod_gets_a_node():
    pods = [fixtures.pod(requests={"cpu": "1"})]
    results = build(pods).solve(pods)
    assert results.all_pods_scheduled()
    assert len(results.new_node_claims) == 1
    claim = results.new_node_claims[0]
    assert len(claim.pods) == 1
    # hostname was stripped at finalize
    assert not claim.requirements.has(wk.HOSTNAME_LABEL_KEY)


def test_resource_packing_binpacks():
    fixtures.reset_rng()
    pods = [fixtures.pod(requests={"cpu": "1"}) for _ in range(30)]
    results = build(pods).solve(pods)
    assert results.all_pods_scheduled()
    # pods-per-node resource cap: fake-it-N has N+1 cpu and 10(N+1) pods; the
    # bin-packer should use far fewer than 30 nodes
    assert len(results.new_node_claims) < 10
    # accumulated requests never exceed the largest surviving instance type
    for claim in results.new_node_claims:
        for it in claim.instance_type_options:
            assert res.fits(claim.requests, it.allocatable())


def test_too_big_pod_fails_with_reason():
    pods = [fixtures.pod(requests={"cpu": "10000"})]
    results = build(pods).solve(pods)
    assert not results.all_pods_scheduled()
    reason = next(iter(results.pod_errors.values()))
    assert "no instance type" in reason


def test_node_selector_constrains_node():
    pods = [
        fixtures.pod(
            requests={"cpu": "1"},
            node_selector={wk.TOPOLOGY_ZONE_LABEL_KEY: "test-zone-2"},
        )
    ]
    results = build(pods).solve(pods)
    assert results.all_pods_scheduled()
    claim = results.new_node_claims[0]
    assert claim.requirements.get(wk.TOPOLOGY_ZONE_LABEL_KEY).values == {"test-zone-2"}


def test_unknown_zone_fails():
    pods = [
        fixtures.pod(
            requests={"cpu": "1"},
            node_selector={wk.TOPOLOGY_ZONE_LABEL_KEY: "mars"},
        )
    ]
    results = build(pods).solve(pods)
    assert not results.all_pods_scheduled()


def test_custom_label_must_be_defined_on_nodepool():
    pods = [fixtures.pod(requests={"cpu": "1"}, node_selector={"team": "ml"})]
    # default nodepool doesn't define "team" -> unschedulable
    assert not build(pods).solve(pods).all_pods_scheduled()
    # nodepool with the label -> schedules and carries the label requirement
    np = fixtures.node_pool(labels={"team": "ml"})
    pods2 = [fixtures.pod(requests={"cpu": "1"}, node_selector={"team": "ml"})]
    results = build(pods2, node_pools=[np]).solve(pods2)
    assert results.all_pods_scheduled()
    assert results.new_node_claims[0].requirements.get("team").values == {"ml"}


def test_tainted_nodepool_requires_toleration():
    np = fixtures.node_pool(taints=[Taint("gpu", TaintEffect.NO_SCHEDULE, "true")])
    pods = [fixtures.pod(requests={"cpu": "1"})]
    assert not build(pods, node_pools=[np]).solve(pods).all_pods_scheduled()
    tolerating = [
        fixtures.pod(
            requests={"cpu": "1"},
            tolerations=[Toleration(key="gpu", operator="Exists")],
        )
    ]
    assert build(tolerating, node_pools=[np]).solve(tolerating).all_pods_scheduled()


def test_zonal_topology_spread():
    fixtures.reset_rng()
    sel = {"app": "spread"}
    pods = [
        fixtures.pod(
            name=f"s-{i}",
            labels=dict(sel),
            requests={"cpu": "1"},
            topology_spread_constraints=[
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=wk.TOPOLOGY_ZONE_LABEL_KEY,
                    label_selector=LabelSelector(match_labels=dict(sel)),
                )
            ],
        )
        for i in range(9)
    ]
    results = build(pods).solve(pods)
    assert results.all_pods_scheduled()
    # count pods per zone across claims
    zone_counts = {}
    for claim in results.new_node_claims:
        zones = claim.requirements.get(wk.TOPOLOGY_ZONE_LABEL_KEY).values
        assert len(zones) == 1  # spread forces a concrete zone per node
        zone_counts[next(iter(zones))] = zone_counts.get(next(iter(zones)), 0) + len(
            claim.pods
        )
    assert sum(zone_counts.values()) == 9
    assert max(zone_counts.values()) - min(zone_counts.values()) <= 1
    assert len(zone_counts) == 3


def test_hostname_anti_affinity_one_pod_per_node():
    labels = {"app": "nginx"}
    pods = [
        fixtures.pod(
            name=f"a-{i}",
            labels=dict(labels),
            requests={"cpu": "100m"},
            pod_anti_requirements=[
                __import__(
                    "karpenter_tpu.api.objects", fromlist=["PodAffinityTerm"]
                ).PodAffinityTerm(
                    topology_key=wk.HOSTNAME_LABEL_KEY,
                    label_selector=LabelSelector(match_labels=dict(labels)),
                )
            ],
        )
        for i in range(5)
    ]
    results = build(pods).solve(pods)
    assert results.all_pods_scheduled()
    assert len(results.new_node_claims) == 5
    assert all(len(c.pods) == 1 for c in results.new_node_claims)


def test_zonal_self_affinity_lands_in_one_zone():
    from karpenter_tpu.api.objects import PodAffinityTerm

    labels = {"group": "g1"}
    pods = [
        fixtures.pod(
            name=f"aff-{i}",
            labels=dict(labels),
            requests={"cpu": "100m"},
            pod_requirements=[
                PodAffinityTerm(
                    topology_key=wk.TOPOLOGY_ZONE_LABEL_KEY,
                    label_selector=LabelSelector(match_labels=dict(labels)),
                )
            ],
        )
        for i in range(6)
    ]
    results = build(pods).solve(pods)
    assert results.all_pods_scheduled()
    zones = set()
    for claim in results.new_node_claims:
        zones |= claim.requirements.get(wk.TOPOLOGY_ZONE_LABEL_KEY).values
    assert len(zones) == 1


def test_preference_relaxation():
    # an unsatisfiable required preference... preferred node affinity to a
    # nonexistent zone must be relaxed away
    pods = [
        fixtures.pod(
            requests={"cpu": "1"},
            node_preferences=[
                NodeSelectorRequirement(wk.TOPOLOGY_ZONE_LABEL_KEY, Operator.IN, ["mars"])
            ],
        )
    ]
    results = build(pods).solve(pods)
    assert results.all_pods_scheduled()


def test_ignore_preferences_policy():
    pods = [
        fixtures.pod(
            requests={"cpu": "1"},
            node_preferences=[
                NodeSelectorRequirement(wk.TOPOLOGY_ZONE_LABEL_KEY, Operator.IN, ["mars"])
            ],
        )
    ]
    results = build(pods, options=SchedulerOptions(ignore_preferences=True)).solve(pods)
    assert results.all_pods_scheduled()
    # preference never constrained the node
    claim = results.new_node_claims[0]
    assert "mars" not in claim.requirements.get(wk.TOPOLOGY_ZONE_LABEL_KEY).values


def test_nodepool_limits_cap_capacity():
    np = fixtures.node_pool(limits={"cpu": "4"})
    # fake-it-3 is 4cpu; anything larger is filtered by limits
    pods = [fixtures.pod(requests={"cpu": "3"}) for _ in range(3)]
    results = build(pods, node_pools=[np]).solve(pods)
    # first node consumes up to 4 cpu pessimistically -> only 1 node fits limits
    assert len(results.new_node_claims) == 1
    assert len(results.pod_errors) == 2
    assert "exceed limits" in next(iter(results.pod_errors.values()))


def test_nodepool_weight_order():
    heavy = fixtures.node_pool(name="heavy", weight=10, labels={"pool": "heavy"})
    light = fixtures.node_pool(name="light", weight=1, labels={"pool": "light"})
    pods = [fixtures.pod(requests={"cpu": "1"})]
    results = build(pods, node_pools=[light, heavy]).solve(pods)
    assert results.all_pods_scheduled()
    assert results.new_node_claims[0].nodepool_name == "heavy"


def test_existing_node_preferred_over_new():
    view = StateNodeView(
        name="existing-1",
        node_labels={wk.HOSTNAME_LABEL_KEY: "existing-1"},
        labels={
            wk.HOSTNAME_LABEL_KEY: "existing-1",
            wk.NODEPOOL_LABEL_KEY: "default",
            wk.TOPOLOGY_ZONE_LABEL_KEY: "test-zone-1",
        },
        available=res.parse_list({"cpu": "4", "memory": "8Gi", "pods": 10}),
        capacity=res.parse_list({"cpu": "4", "memory": "8Gi", "pods": 10}),
        initialized=True,
    )
    pods = [fixtures.pod(requests={"cpu": "1"})]
    results = build(pods, state_nodes=[view]).solve(pods)
    assert results.all_pods_scheduled()
    assert len(results.new_node_claims) == 0
    assert len(results.existing_nodes[0].pods) == 1


def test_existing_node_overflow_to_new():
    view = StateNodeView(
        name="existing-1",
        node_labels={wk.HOSTNAME_LABEL_KEY: "existing-1"},
        labels={
            wk.HOSTNAME_LABEL_KEY: "existing-1",
            wk.NODEPOOL_LABEL_KEY: "default",
        },
        available=res.parse_list({"cpu": "2", "pods": 10}),
        capacity=res.parse_list({"cpu": "2", "pods": 10}),
        initialized=True,
    )
    pods = [fixtures.pod(name=f"p{i}", requests={"cpu": "1"}) for i in range(4)]
    results = build(pods, state_nodes=[view]).solve(pods)
    assert results.all_pods_scheduled()
    assert len(results.existing_nodes[0].pods) == 2
    assert sum(len(c.pods) for c in results.new_node_claims) == 2


def test_min_values_instance_type_flexibility():
    pods = [
        fixtures.pod(requests={"cpu": "1"}),
    ]
    np = fixtures.node_pool(
        requirements=[
            NodeSelectorRequirement(
                wk.INSTANCE_TYPE_LABEL_KEY,
                Operator.EXISTS,
                min_values=5,
            )
        ]
    )
    results = build(pods, node_pools=[np]).solve(pods)
    assert results.all_pods_scheduled()
    claim = results.new_node_claims[0]
    assert len(claim.instance_type_options) >= 5


def test_diverse_pods_all_schedule():
    fixtures.reset_rng()
    pods = fixtures.make_diverse_pods(100)
    results = build(pods, instance_types=fake.instance_types(50)).solve(pods)
    assert results.all_pods_scheduled(), list(results.pod_errors.values())[:3]
    total = sum(len(c.pods) for c in results.new_node_claims) + sum(
        len(n.pods) for n in results.existing_nodes
    )
    assert total == 100


def test_preference_pods_all_schedule():
    fixtures.reset_rng()
    pods = fixtures.make_preference_pods(50)
    results = build(pods, instance_types=fake.instance_types(50)).solve(pods)
    assert results.all_pods_scheduled()


# ---------------------------------------------------------------------------
# daemonset overhead (scheduler.go:806 isDaemonPodCompatible + daemon
# resource accounting in NewScheduler)


def test_daemonset_overhead_reduces_node_capacity():
    """A 1-vCPU daemonset rides every node: a 1.5-vCPU workload pod then
    needs >= 2.5 vCPU allocatable, so the 1- and 2-vCPU types must drop
    out of the claim's surviving options."""
    pods = [fixtures.pod(name="w", requests={"cpu": "1500m"})]
    daemon = fixtures.pod(name="ds", requests={"cpu": "1"})
    node_pools = [fixtures.node_pool()]
    its = fake.instance_types(5)  # 1..5 vCPU
    by_pool = {np.name: InstanceTypes(its) for np in node_pools}
    topology = Topology(node_pools, by_pool, pods)
    s = Scheduler(node_pools, by_pool, topology, daemonset_pods=[daemon])
    results = s.solve(pods)
    assert results.all_pods_scheduled()
    claim = results.new_node_claims[0]
    names = {it.name for it in claim.instance_type_options}
    assert "fake-it-0" not in names and "fake-it-1" not in names
    assert names, "larger types must survive"
    # the claim's accounted requests include the daemon overhead
    assert claim.daemon_resources.get(res.CPU, 0) == 1000


def test_daemonset_with_node_selector_counts_only_on_matching_templates():
    """scheduler.go:806: a daemonset constrained to zone-1 adds overhead
    only to templates that can land in zone-1."""
    from karpenter_tpu.api import labels as well_known

    daemon = fixtures.pod(
        name="ds",
        requests={"cpu": "1"},
        node_selector={well_known.TOPOLOGY_ZONE_LABEL_KEY: "test-zone-1"},
    )
    pools = [
        fixtures.node_pool(
            name="z1",
            requirements=[
                NodeSelectorRequirement(
                    well_known.TOPOLOGY_ZONE_LABEL_KEY, Operator.IN, ["test-zone-1"]
                )
            ],
        ),
        fixtures.node_pool(
            name="z2",
            requirements=[
                NodeSelectorRequirement(
                    well_known.TOPOLOGY_ZONE_LABEL_KEY, Operator.IN, ["test-zone-2"]
                )
            ],
        ),
    ]
    pods = [fixtures.pod(name="w", requests={"cpu": "100m"})]
    its = fake.instance_types_assorted()
    by_pool = {np.name: InstanceTypes(its) for np in pools}
    topology = Topology(pools, by_pool, pods)
    s = Scheduler(pools, by_pool, topology)
    s2 = Scheduler(pools, by_pool, Topology(pools, by_pool, pods), daemonset_pods=[daemon])
    overhead = {nct.nodepool_name: r for nct, r in s2.daemon_overhead.items()}
    assert overhead["z1"].get(res.CPU, 0) == 1000
    assert overhead["z2"].get(res.CPU, 0) == 0
    assert s.daemon_overhead  # baseline sanity: templates exist


def test_startup_taints_do_not_block_scheduling():
    """Startup taints (nodepool.go spec.template.startupTaints) gate node
    INITIALIZATION, not scheduling: pods need no toleration for them."""
    from karpenter_tpu.api.objects import Taint, TaintEffect

    np_ = fixtures.node_pool(
        startup_taints=[
            Taint(key="node.cilium.io/agent-not-ready", value="true",
                  effect=TaintEffect.NO_SCHEDULE)
        ]
    )
    pods = [fixtures.pod(requests={"cpu": "1"})]
    results = build(pods, node_pools=[np_]).solve(pods)
    assert results.all_pods_scheduled()
    claim = results.new_node_claims[0]
    assert claim.template.startup_taints, "claim must carry the startup taints"


def test_host_port_conflict_forces_second_node():
    """Two pods publishing the same hostPort cannot share a node
    (hostportusage.go:35); everything else about them fits together."""
    a = fixtures.pod(name="a", requests={"cpu": "100m"})
    b = fixtures.pod(name="b", requests={"cpu": "100m"})
    a.host_ports = [("", "TCP", 8080)]
    b.host_ports = [("", "TCP", 8080)]
    results = build([a, b]).solve([a, b])
    assert results.all_pods_scheduled()
    assert len([c for c in results.new_node_claims if c.pods]) == 2


def test_pods_resource_caps_pods_per_node():
    """The 'pods' resource is a packing dimension like cpu/memory
    (fake types carry pods=10*(i+1))."""
    its = fake.instance_types(1)  # 1 vCPU, pods=10
    pods = [
        fixtures.pod(name=f"tiny-{i}", requests={"cpu": "10m"}) for i in range(15)
    ]
    results = build(pods, instance_types=its).solve(pods)
    assert results.all_pods_scheduled()
    filled = [len(c.pods) for c in results.new_node_claims if c.pods]
    assert sorted(filled, reverse=True)[0] <= 10
    assert len(filled) == 2
