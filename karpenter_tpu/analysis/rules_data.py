"""Rules guarding numeric exactness.

- dtype-overflow: CLAUDE.md "int32 totals must never wrap" — the
  consolidation sweep's exactness gates include host-side int64 overflow
  guards; any function in the sweep path that accumulates int32 totals
  (cumsum / axis-sum / matmul) must carry one.
- milli-units: resource quantities are integer milli-units everywhere
  (utils/resources.py); true division or float arithmetic touching a
  resource-named value either truncates wrongly or leaks floats into
  ResourceLists.
"""

from __future__ import annotations

import ast
import re

from karpenter_tpu.analysis.engine import (
    FileContext,
    Finding,
    Rule,
    iter_functions,
)

# the delta-state consolidation sweeps (disruption/sweep.py and the
# removal-set generalization, disruption/setsweep.py)
SWEEP_MODULES = (
    "karpenter_tpu/controllers/disruption/sweep.py",
    "karpenter_tpu/controllers/disruption/setsweep.py",
)

_GUARD_BOUND_RE = re.compile(r"1\s*<<\s*3[01]|2\s*\*\*\s*3[01]|2147483647")


class DtypeOverflowRule(Rule):
    id = "dtype-overflow"
    summary = (
        "int32 accumulations in the sweep path need an explicit int64 "
        "host guard (CLAUDE.md: int32 totals must never wrap)"
    )
    targets = SWEEP_MODULES

    def check(self, ctx: FileContext) -> list[Finding]:
        out = []
        for fn in iter_functions(ctx.tree):
            seg = ctx.segment(fn)
            if "int32" not in seg:
                continue
            if not self._accumulates(fn):
                continue
            if "int64" in seg and _GUARD_BOUND_RE.search(seg):
                continue
            out.append(
                ctx.finding(
                    self.id,
                    fn,
                    f"{fn.name}() accumulates int32 totals (cumsum/sum/"
                    "matmul) without an int64 guard against a 2^31 bound; "
                    "verify the worst-case total host-side in int64 first",
                )
            )
        return out

    @staticmethod
    def _accumulates(fn: ast.AST) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.MatMult):
                return True
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("cumsum", "sum")
            ):
                return True
        return False


# identifiers that mark a value as a resource quantity (integer milli-units)
_RESOURCE_NAME_RE = re.compile(
    r"\b(requests?|capacity|limits|allocatable|avail\w*|overhead|millis?)\b"
)

_EXEMPT = (
    "karpenter_tpu/utils/resources.py",  # the arithmetic home (the invariant)
    "karpenter_tpu/utils/quantity.py",  # parses human floats INTO milli ints
)


class MilliUnitsRule(Rule):
    id = "milli-units"
    summary = (
        "no true division or float arithmetic on resource quantities "
        "outside utils/resources.py (integer milli-units everywhere)"
    )
    targets = ("karpenter_tpu/**/*.py", "tests/**/*.py")

    def applies_to(self, relpath: str) -> bool:
        if relpath.replace("\\", "/") in _EXEMPT:
            return False
        return super().applies_to(relpath)

    def check(self, ctx: FileContext) -> list[Finding]:
        out = []
        seen_lines: set[int] = set()  # one finding per offending line
        for node in ast.walk(ctx.tree):
            if getattr(node, "lineno", None) in seen_lines:
                continue
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Div):
                seg = ctx.segment(node)
                if _RESOURCE_NAME_RE.search(seg):
                    seen_lines.add(node.lineno)
                    out.append(
                        ctx.finding(
                            self.id,
                            node,
                            "true division on a resource-named quantity; "
                            "milli-unit arithmetic must stay integer "
                            "(// or utils/resources.py helpers)",
                        )
                    )
            elif isinstance(node, ast.BinOp) and isinstance(
                node.op, (ast.Mult, ast.Add, ast.Sub)
            ):
                left_lit = self._float_literal(node.left)
                lit = left_lit if left_lit is not None else self._float_literal(
                    node.right
                )
                if lit is None:
                    continue
                other = node.right if left_lit is not None else node.left
                if _RESOURCE_NAME_RE.search(ctx.segment(other) or ""):
                    seen_lines.add(node.lineno)
                    out.append(
                        ctx.finding(
                            self.id,
                            node,
                            f"float literal {lit} combined with a resource-"
                            "named quantity; resource math is integer "
                            "milli-units",
                        )
                    )
        return out

    @staticmethod
    def _float_literal(node: ast.AST):
        # returns the literal (0.0 is a legitimate hit — callers must
        # compare against None, never truthiness)
        if isinstance(node, ast.Constant) and isinstance(node.value, float):
            return node.value
        if (
            isinstance(node, ast.UnaryOp)
            and isinstance(node.operand, ast.Constant)
            and isinstance(node.operand.value, float)
        ):
            return node.operand.value
        return None


RULES = (DtypeOverflowRule, MilliUnitsRule)
