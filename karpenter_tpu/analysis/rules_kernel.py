"""Rules guarding the solver kernels' parity and trace discipline.

- shared-comparator: CLAUDE.md "the oracle and the TPU path MUST sort
  with the same key or parity breaks" — pod/solver ordering in the parity
  modules has to flow through solver/ordering.py, never an inline key.
- kernel-purity: host-sync constructs inside the jitted modules either
  crash at trace time or silently fall off the device (a `.item()` in a
  traced body blocks on the slow tunnel per CLAUDE.md's transfer note).
- tracer-leak: a data-dependent Python `if`/`while` on a jnp value raises
  ConcretizationTypeError at trace time — catch it at review time.
"""

from __future__ import annotations

import ast

from karpenter_tpu.analysis.engine import (
    FileContext,
    Finding,
    Rule,
    base_name,
    ordering_import_names,
)

# modules whose decisions must stay bit-identical between oracle and kernel
PARITY_MODULES = (
    "karpenter_tpu/solver/oracle.py",
    "karpenter_tpu/solver/tpu_runs.py",
    "karpenter_tpu/solver/tpu.py",
    "karpenter_tpu/controllers/disruption/sweep.py",
)

# modules whose function bodies are traced into XLA programs
KERNEL_MODULES = (
    "karpenter_tpu/solver/tpu_kernel.py",
    "karpenter_tpu/solver/tpu_runs.py",
    "karpenter_tpu/ops/kernels.py",
)


class SharedComparatorRule(Rule):
    id = "shared-comparator"
    summary = (
        "sorts in parity modules must key through solver/ordering.py "
        "(CLAUDE.md: oracle and TPU path must sort with the same key)"
    )
    targets = PARITY_MODULES

    def check(self, ctx: FileContext) -> list[Finding]:
        allowed = ordering_import_names(ctx.tree)
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            is_sorted = isinstance(node.func, ast.Name) and node.func.id == "sorted"
            is_sort = (
                isinstance(node.func, ast.Attribute) and node.func.attr == "sort"
            )
            if not (is_sorted or is_sort):
                continue
            key = next((k.value for k in node.keywords if k.arg == "key"), None)
            if key is None:
                continue  # keyless sorts order primitives, not pods
            if self._key_uses_ordering(key, allowed):
                continue
            out.append(
                ctx.finding(
                    self.id,
                    node,
                    "inline sort key in a parity module; route the "
                    "ordering through solver/ordering.py (ffd_sort_key / "
                    "ffd_order_cols) or baseline with a justification",
                )
            )
        return out

    @staticmethod
    def _key_uses_ordering(key: ast.AST, allowed: set[str]) -> bool:
        if isinstance(key, ast.Name) and key.id in allowed:
            return True
        for sub in ast.walk(key):
            if isinstance(sub, ast.Call):
                root = base_name(sub.func)
                fn = (
                    sub.func.attr
                    if isinstance(sub.func, ast.Attribute)
                    else getattr(sub.func, "id", None)
                )
                if root in allowed or fn in allowed:
                    return True
        return False


# host-sync calls that must never appear in a traced body
_HOST_SYNC_ATTRS = frozenset({"item", "block_until_ready", "tolist"})
_NUMPY_ALIASES = frozenset({"np", "numpy", "onp"})
_NUMPY_SYNC_FNS = frozenset(
    {"asarray", "array", "frombuffer", "concatenate", "stack", "copy"}
)


class KernelPurityRule(Rule):
    id = "kernel-purity"
    summary = (
        "no host-sync constructs (print, .item(), numpy materialization, "
        "float()/int() on traced values) inside kernel modules"
    )
    targets = KERNEL_MODULES

    def check(self, ctx: FileContext) -> list[Finding]:
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            msg = self._violation(node)
            if msg:
                out.append(ctx.finding(self.id, node, msg))
        return out

    @staticmethod
    def _violation(node: ast.Call) -> str:
        f = node.func
        if isinstance(f, ast.Name):
            if f.id == "print":
                return (
                    "print() in a kernel module runs at trace time only "
                    "(use jax.debug.print for traced values)"
                )
            if f.id in ("float", "int", "bool") and node.args:
                arg = node.args[0]
                if not isinstance(arg, ast.Constant) and "shape" not in ast.dump(
                    arg
                ):
                    return (
                        f"{f.id}() on a possibly-traced value forces a "
                        "host sync; keep scalars on device (jnp casts) or "
                        "derive from static shapes"
                    )
        if isinstance(f, ast.Attribute):
            if f.attr in _HOST_SYNC_ATTRS:
                return (
                    f".{f.attr}() pulls a traced value to the host — a "
                    "per-call tunnel round-trip (CLAUDE.md transfer note)"
                )
            root = base_name(f)
            if root in _NUMPY_ALIASES and f.attr in _NUMPY_SYNC_FNS:
                return (
                    f"{root}.{f.attr}() materializes on the host inside a "
                    "kernel module; use jnp equivalents"
                )
            if root == "jax" and f.attr == "device_get":
                return "jax.device_get inside a kernel module is a host sync"
        return ""


_TRACED_ROOTS = frozenset({"jnp", "lax"})


class TracerLeakRule(Rule):
    id = "tracer-leak"
    summary = (
        "no data-dependent Python if/while on jnp values in kernel "
        "modules (use lax.cond / lax.while_loop / jnp.where)"
    )
    targets = KERNEL_MODULES

    def check(self, ctx: FileContext) -> list[Finding]:
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            leak = self._traced_expr(node.test)
            if leak:
                kw = "if" if isinstance(node, ast.If) else "while"
                out.append(
                    ctx.finding(
                        self.id,
                        node,
                        f"`{kw}` branches on a traced value ({leak}); "
                        "control flow on device values must use lax.cond/"
                        "lax.while_loop or jnp.where",
                    )
                )
        return out

    @staticmethod
    def _traced_expr(test: ast.AST) -> str:
        for sub in ast.walk(test):
            if isinstance(sub, ast.Call):
                root = base_name(sub.func)
                if root in _TRACED_ROOTS:
                    fn = getattr(sub.func, "attr", root)
                    return f"{root}.{fn}(...)"
                if (
                    root == "jax"
                    and isinstance(sub.func, ast.Attribute)
                    and isinstance(sub.func.value, ast.Attribute)
                    and sub.func.value.attr in ("numpy", "lax")
                ):
                    return f"jax.{sub.func.value.attr}.{sub.func.attr}(...)"
        return ""


RULES = (SharedComparatorRule, KernelPurityRule, TracerLeakRule)
