"""Rules guarding shared mutable state.

- lock-discipline: the threaded service boundary (solver/service.py
  handler threads, metrics scraped while worker pools observe) relies on
  every write to a lock-guarded attribute actually holding the lock.
  Two checks per class that owns a threading lock:
    (a) an attribute ever written under `with self._lock:` must never be
        written outside one (construction in __init__ is exempt — the
        object is not shared yet);
    (b) `self.x += ...` outside a lock is a read-modify-write race even
        when the attribute was never formally guarded.
- cache-invalidation: relax mutations change every field the memoized
  `_ktpu_*` class keys cover (solver/ordering.py); CLAUDE.md requires
  mutations of preference state to invalidate those caches, or the
  encoder dedups a relaxed pod into its pre-relaxation class.
"""

from __future__ import annotations

import ast

from karpenter_tpu.analysis.engine import FileContext, Finding, Rule

LOCK_MODULES = (
    "karpenter_tpu/solver/service.py",
    "karpenter_tpu/solver/hybrid.py",
    "karpenter_tpu/metrics.py",
    "tests/*.py",
    "tests/**/*.py",
)

_LOCK_TYPES = frozenset({"Lock", "RLock", "Condition", "Semaphore"})
_MUTATORS = frozenset(
    {
        "add",
        "append",
        "appendleft",
        "clear",
        "discard",
        "extend",
        "insert",
        "pop",
        "popleft",
        "remove",
        "setdefault",
        "update",
    }
)


def _self_attr(node: ast.AST) -> str:
    """'x' for self.x / self.x[...] targets, else ''."""
    if isinstance(node, ast.Subscript):
        node = node.value
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return ""


class LockDisciplineRule(Rule):
    id = "lock-discipline"
    summary = (
        "attributes written under a threading lock must never be written "
        "outside a `with self.<lock>:` block"
    )
    targets = LOCK_MODULES

    def check(self, ctx: FileContext) -> list[Finding]:
        out = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                out.extend(self._check_class(ctx, node))
        return out

    def _check_class(self, ctx: FileContext, cls: ast.ClassDef) -> list[Finding]:
        lock_attrs = self._lock_attrs(cls)
        if not lock_attrs:
            return []
        methods = [
            m
            for m in cls.body
            if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
            and m.name != "__init__"
        ]
        guarded_spans: list[tuple[int, int]] = []
        writes: list[tuple[ast.AST, str, bool]] = []  # (node, attr, is_aug)
        for m in methods:
            if m.name.endswith("_locked"):
                # the `_locked` suffix is the contract that the caller
                # holds the lock; the whole body counts as guarded
                guarded_spans.append((m.lineno, m.end_lineno or m.lineno))
            for node in ast.walk(m):
                if isinstance(node, ast.With):
                    if any(
                        _self_attr(item.context_expr) in lock_attrs
                        for item in node.items
                    ):
                        guarded_spans.append(
                            (node.lineno, node.end_lineno or node.lineno)
                        )
                elif isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = (
                        node.targets
                        if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for t in targets:
                        attr = _self_attr(t)
                        if attr:
                            writes.append(
                                (node, attr, isinstance(node, ast.AugAssign))
                            )
                elif isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute
                ):
                    if node.func.attr in _MUTATORS:
                        attr = _self_attr(node.func.value)
                        if attr:
                            writes.append((node, attr, False))

        def under_lock(n: ast.AST) -> bool:
            return any(lo <= n.lineno <= hi for lo, hi in guarded_spans)

        guarded_attrs = {
            attr for n, attr, _ in writes if under_lock(n)
        } - lock_attrs
        findings = []
        for n, attr, is_aug in writes:
            if attr in lock_attrs or under_lock(n):
                continue
            if attr in guarded_attrs:
                findings.append(
                    ctx.finding(
                        self.id,
                        n,
                        f"{cls.name}.{attr} is written under a lock "
                        "elsewhere but written here without one — a "
                        "torn/lost update under the handler threads",
                    )
                )
            elif is_aug:
                findings.append(
                    ctx.finding(
                        self.id,
                        n,
                        f"read-modify-write on {cls.name}.{attr} outside "
                        "any lock in a lock-owning class; increments can "
                        "be lost under preemption",
                    )
                )
        return findings

    @staticmethod
    def _lock_attrs(cls: ast.ClassDef) -> set[str]:
        attrs: set[str] = set()
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign):
                continue
            v = node.value
            # both spellings: threading.Lock() and bare Lock() from a
            # `from threading import Lock`
            ctor = (
                v.func.attr
                if isinstance(v, ast.Call) and isinstance(v.func, ast.Attribute)
                else v.func.id
                if isinstance(v, ast.Call) and isinstance(v.func, ast.Name)
                else None
            )
            if ctor in _LOCK_TYPES:
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr:
                        attrs.add(attr)
        return attrs


# pod fields covered by the memoized class key (solver/ordering.py
# pod_class_key); mutating any of these without dropping the _ktpu_* caches
# dedups the pod into a stale class
_SENSITIVE = frozenset(
    {
        "node_affinity",
        "required_terms",
        "preferred",
        "tolerations",
        "topology_spread_constraints",
        "pod_affinity",
        "pod_anti_affinity",
        "pod_affinity_preferred",
        "pod_anti_affinity_preferred",
        "node_selector",
    }
)
_LIST_MUTATORS = frozenset(
    {"sort", "pop", "append", "remove", "insert", "extend", "clear"}
)


class CacheInvalidationRule(Rule):
    id = "cache-invalidation"
    summary = (
        "mutations of relax/preference pod state must pair with _ktpu_* "
        "class-key invalidation (CLAUDE.md relax invariant)"
    )
    targets = (
        "karpenter_tpu/solver/oracle.py",
        "karpenter_tpu/solver/tpu_problem.py",
    )

    def check(self, ctx: FileContext) -> list[Finding]:
        out = []
        # regions that handle invalidation: a class or function whose
        # source mentions the cache attrs or the invalidator
        safe_spans: list[tuple[int, int]] = []
        for node in ast.walk(ctx.tree):
            if isinstance(
                node, (ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                seg = ctx.segment(node)
                if "_ktpu_" in seg or "_invalidate_class_caches" in seg:
                    safe_spans.append((node.lineno, node.end_lineno or node.lineno))

        def safe(n: ast.AST) -> bool:
            return any(lo <= n.lineno <= hi for lo, hi in safe_spans)

        for node in ast.walk(ctx.tree):
            attr = self._sensitive_mutation(node)
            if attr and not safe(node):
                out.append(
                    ctx.finding(
                        self.id,
                        node,
                        f"mutation of relax-sensitive field `{attr}` with "
                        "no _ktpu_* cache invalidation in scope; the "
                        "encoder would dedup the pod into its stale class "
                        "(Preferences._invalidate_class_caches)",
                    )
                )
        return out

    @staticmethod
    def _sensitive_mutation(node: ast.AST) -> str:
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                if isinstance(t, ast.Subscript):
                    t = t.value
                if isinstance(t, ast.Attribute) and t.attr in _SENSITIVE:
                    return t.attr
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr in _LIST_MUTATORS:
                v = node.func.value
                if isinstance(v, ast.Attribute) and v.attr in _SENSITIVE:
                    return v.attr
        return ""


RULES = (LockDisciplineRule, CacheInvalidationRule)
