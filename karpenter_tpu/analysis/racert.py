"""graftlint race tier, runtime half: a tsan-lite lock witness.

The static half (analysis/locks.py) proves what it can from source; this
module witnesses the rest at runtime, the way ThreadSanitizer's
happens-before machinery does — but scoped to what a pytest-sized
harness can afford:

- `instrument()` replaces `threading.Lock`, `threading.RLock` and
  `threading.Condition` with factories returning thin instrumented
  wrappers. Every lock CREATED while instrumented reports its acquire/
  release to a process-global `Witness`; locks created before stay raw
  (their wrappers also go quiet again after `uninstrument()`).
- The witness keeps a per-thread stack of held locks. Acquiring B while
  holding A records the ordered pair (A, B), keyed by each lock's
  CREATION SITE (file:line) — the Eraser-style move that makes "the
  SolverServer stats lock" one identity across every server instance.
  Observing both (A, B) and (B, A) is a lock-order inversion: a
  deadlock that has not fired yet only because the two threads have not
  interleaved unluckily. Both acquisition stacks are captured so the
  report shows each side of the inversion.
- Holds longer than `hold_ms` are recorded (`long_holds`) — the runtime
  analog of the static `race-blocking-hold` rule.
- `threading.excepthook` is chained so background-thread exceptions are
  captured (`thread_exceptions`) instead of vanishing into stderr.

The conftest fixture (tests/conftest.py) turns this on for every
`faults`/`racert`-marked test, so the whole fault-injection suite
doubles as a race harness: `Witness.assert_no_inversions()` fails the
test with both stacks when an inversion was observed.

Stack capture is a raw frame walk (no traceback formatting) so the
per-acquire overhead stays in the microseconds and the fault suite's
tier-1 budget is untouched.

Pure stdlib — importing this module must never pull in JAX or numpy
(tests/test_race_analysis.py pins it alongside the static half).
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Optional

# the raw primitives, captured before any patching so the witness's own
# synchronization and the restore path never recurse through wrappers
_RAW_LOCK = threading.Lock
_RAW_RLOCK = threading.RLock
_RAW_CONDITION = threading.Condition

_WITNESS: Optional["Witness"] = None
_SAVED: Optional[tuple] = None

_STACK_LIMIT = 8


def _callsite(depth: int) -> str:
    f = sys._getframe(depth)
    return f"{_shorten(f.f_code.co_filename)}:{f.f_lineno}"


# Resolved ONCE: sites double as report identities, so the prefix must
# not move underneath them — a test chdir-ing mid-run would otherwise
# split one lock role into two identities and edges over the halves
# could never pair up into an inversion. Also keeps the per-acquire
# frame walk syscall-free (up to _STACK_LIMIT+1 _shorten calls each).
_PREFIX = os.getcwd() + os.sep


def _shorten(path: str) -> str:
    # repo-relative when possible: sites double as report identities
    if path.startswith(_PREFIX):
        return path[len(_PREFIX) :]
    return path


_THIS_FILE = __file__


def _stack(skip: int) -> tuple[str, ...]:
    """Cheap acquisition stack: (file:line in func, ...) innermost first,
    skipping the wrapper frames. No format_stack — a faults solve takes
    thousands of lock ops and formatting would dominate the test."""
    out = []
    try:
        f: Any = sys._getframe(skip)
    except ValueError:
        return ()
    # a `with lock:` adds an __enter__ frame the fixed skip cannot see;
    # the report must lead with the USER frame, not wrapper noise (which
    # would also burn one of the _STACK_LIMIT slots)
    while f is not None and f.f_code.co_filename == _THIS_FILE:
        f = f.f_back
    while f is not None and len(out) < _STACK_LIMIT:
        co = f.f_code
        out.append(f"{_shorten(co.co_filename)}:{f.f_lineno} in {co.co_name}")
        f = f.f_back
    return tuple(out)


class Witness:
    """Process-global race evidence: acquisition-order edges, observed
    inversions, long holds, background-thread exceptions."""

    def __init__(self, hold_ms: float = 250.0):
        self.hold_ms = hold_ms
        self._mu = _RAW_LOCK()
        self._tls = threading.local()
        # (held_site, acquired_site) -> first-observation record
        self.edges: dict[tuple[str, str], dict] = {}
        self.inversions: list[dict] = []
        self._inverted: set[frozenset] = set()
        self.long_holds: list[dict] = []
        self.thread_exceptions: list[dict] = []
        # per-thread count cells, registered once per thread (under _mu)
        # and bumped lock-free after that: the no-held fast path must not
        # funnel every lock op in the program through one global mutex —
        # that contention would perturb exactly the interleavings the
        # witness exists to observe
        self._count_cells: list[list[int]] = []

    @property
    def acquire_count(self) -> int:
        with self._mu:
            return sum(c[0] for c in self._count_cells)

    # -- wrapper callbacks --------------------------------------------------

    def _held(self) -> list[dict]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
            cell = self._tls.count = [0]
            with self._mu:
                self._count_cells.append(cell)
        return held

    def on_acquire(self, lock: "_LockBase") -> None:
        held = self._held()
        for entry in held:
            if entry["lock"] is lock:
                entry["depth"] += 1  # reentrant re-acquire: no new edge
                return
        stack = _stack(3)
        entry = {
            "lock": lock,
            "site": lock._racert_site,
            "t0": time.monotonic(),
            "depth": 1,
            "stack": stack,
        }
        self._tls.count[0] += 1  # own cell: no lock, no cross-thread race
        if held:
            with self._mu:
                for h in held:
                    a, b = h["site"], lock._racert_site
                    if a == b:
                        continue
                    key = (a, b)
                    rec = self.edges.get(key)
                    if rec is None:
                        self.edges[key] = {
                            "count": 1,
                            "held_stack": h["stack"],
                            "acquire_stack": stack,
                            "thread": threading.current_thread().name,
                        }
                    else:
                        rec["count"] += 1
                    other = self.edges.get((b, a))
                    pair = frozenset(key)
                    if other is not None and pair not in self._inverted:
                        self._inverted.add(pair)
                        mine = self.edges[key]
                        self.inversions.append(
                            {
                                "locks": (a, b),
                                "order_a_then_b": {
                                    "thread": mine["thread"],
                                    "holding": a,
                                    "acquiring": b,
                                    "stack": mine["acquire_stack"],
                                },
                                "order_b_then_a": {
                                    "thread": other["thread"],
                                    "holding": b,
                                    "acquiring": a,
                                    "stack": other["acquire_stack"],
                                },
                            }
                        )
        held.append(entry)

    def _finish_hold(self, entry: dict) -> None:
        ms = (time.monotonic() - entry["t0"]) * 1000.0
        if ms > self.hold_ms:
            with self._mu:
                self.long_holds.append(
                    {
                        "site": entry["site"],
                        "held_ms": round(ms, 1),
                        "stack": entry["stack"],
                        "thread": threading.current_thread().name,
                    }
                )

    def on_release(self, lock: "_LockBase") -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i]["lock"] is lock:
                held[i]["depth"] -= 1
                if held[i]["depth"] == 0:
                    self._finish_hold(held.pop(i))
                return
        # release of a hold this witness never saw (acquired before
        # instrument(), or Condition.wait internals): not our evidence

    def on_release_save(self, lock: "_LockBase") -> int:
        """Condition.wait dropping EVERY recursion level at once: pop the
        entry whole (the raw `_release_save` fully releases, so tracking
        it as still held would report the entire blocked wait as a hold —
        a spurious long_hold, and phantom edges for anything acquired
        while 'holding' it). Returns the depth to re-establish after the
        wait, 0 when this witness never saw the hold."""
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i]["lock"] is lock:
                entry = held.pop(i)
                self._finish_hold(entry)
                return entry["depth"]
        return 0

    def on_acquire_restore(self, lock: "_LockBase", depth: int) -> None:
        """The wake side of on_release_save: one fresh acquisition (fresh
        t0 — the wait was not a hold) restored to the saved depth."""
        self.on_acquire(lock)
        held = self._held()
        for entry in reversed(held):
            if entry["lock"] is lock:
                entry["depth"] = depth
                return

    def on_thread_exception(self, args) -> None:
        with self._mu:
            self.thread_exceptions.append(
                {
                    "thread": getattr(args.thread, "name", "?"),
                    "exc_type": getattr(args.exc_type, "__name__", "?"),
                    "exc": str(args.exc_value),
                }
            )

    # -- reporting ----------------------------------------------------------

    def report(self) -> dict:
        with self._mu:
            return {
                # not via the acquire_count property: it takes _mu too
                "acquires": sum(c[0] for c in self._count_cells),
                "edges": {
                    f"{a} -> {b}": rec["count"]
                    for (a, b), rec in sorted(self.edges.items())
                },
                "inversions": list(self.inversions),
                "long_holds": list(self.long_holds),
                "thread_exceptions": list(self.thread_exceptions),
            }

    @staticmethod
    def _render_side(side: dict) -> str:
        head = (
            f"    [{side['thread']}] holding {side['holding']}, "
            f"acquiring {side['acquiring']}:"
        )
        frames = "".join(f"\n      {fr}" for fr in side["stack"])
        return head + frames

    def render_inversions(self) -> str:
        parts = []
        for inv in self.inversions:
            a, b = inv["locks"]
            parts.append(
                f"lock-order inversion between {a} and {b}:\n"
                + self._render_side(inv["order_a_then_b"])
                + "\n"
                + self._render_side(inv["order_b_then_a"])
            )
        return "\n".join(parts)

    def assert_no_inversions(self) -> None:
        if self.inversions:
            raise AssertionError(
                f"racert witnessed {len(self.inversions)} lock-order "
                "inversion(s) — a deadlock waiting for the right "
                "interleaving:\n" + self.render_inversions()
            )

    def assert_no_thread_exceptions(self) -> None:
        if self.thread_exceptions:
            lines = "\n".join(
                f"  [{e['thread']}] {e['exc_type']}: {e['exc']}"
                for e in self.thread_exceptions
            )
            raise AssertionError(
                f"racert captured {len(self.thread_exceptions)} uncaught "
                "background-thread exception(s):\n" + lines
            )


# ---------------------------------------------------------------------------
# instrumented wrappers


class _LockBase:
    """Shared wrapper plumbing. Wrappers outlive uninstrument(): every
    callback goes through the CURRENT module-global witness and becomes a
    no-op when none is installed, so a lock created during one
    instrumented test is inert in the next."""

    _racert_kind = "Lock"

    def __init__(self, raw, site: str):
        self._raw = raw
        self._racert_site = site

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._raw.acquire(blocking, timeout)
        if got:
            w = _WITNESS
            if w is not None:
                w.on_acquire(self)
        return got

    def release(self) -> None:
        w = _WITNESS
        if w is not None:
            w.on_release(self)
        self._raw.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        # _thread.RLock grows .locked() only in 3.14 — the wrapper must
        # not invent API the raw lock lacks, or code works uninstrumented
        # and crashes only inside racert-marked tests
        fn = getattr(self._raw, "locked", None)
        if fn is None:
            raise AttributeError(
                f"{type(self._raw).__name__!r} object has no attribute "
                "'locked' on this Python version"
            )
        return fn()

    def _at_fork_reinit(self) -> None:
        # stdlib modules register lock._at_fork_reinit with os.register_
        # at_fork at IMPORT time (concurrent.futures.thread does, via its
        # global shutdown lock) — a module first imported inside an
        # instrumented test must get the real reinit hook, not an
        # AttributeError (found by the epoch chaos soak, whose Operator
        # import pulled in ThreadPoolExecutor under instrumentation)
        self._raw._at_fork_reinit()

    def __repr__(self) -> str:
        return f"<racert {self._racert_kind} from {self._racert_site}>"


class _InstrumentedLock(_LockBase):
    _racert_kind = "Lock"


class _InstrumentedRLock(_LockBase):
    _racert_kind = "RLock"

    # threading.Condition over an RLock uses these to drop every
    # recursion level around wait(); the witness must drop ALL levels too
    # (on_release_save), not just one, or a re-entrantly held RLock stays
    # "held" for the whole wait. Condition treats the saved state as
    # opaque, so the wrapper piggybacks the witnessed depth on it.
    def _release_save(self):
        w = _WITNESS
        depth = w.on_release_save(self) if w is not None else 0
        return (self._raw._release_save(), depth)

    def _acquire_restore(self, state) -> None:
        raw_state, depth = state
        self._raw._acquire_restore(raw_state)
        w = _WITNESS
        if w is not None and depth:
            w.on_acquire_restore(self, depth)

    def _is_owned(self) -> bool:
        return self._raw._is_owned()


def _lock_factory():
    return _InstrumentedLock(_RAW_LOCK(), _callsite(2))


def _rlock_factory():
    return _InstrumentedRLock(_RAW_RLOCK(), _callsite(2))


def _condition_factory(lock=None):
    # a real Condition over an instrumented lock: Condition's own
    # acquire/release/wait delegate to the wrapper (via _release_save /
    # _acquire_restore for RLocks, plain release/acquire for Locks), so
    # every hold is still witnessed
    if lock is None:
        lock = _InstrumentedRLock(_RAW_RLOCK(), _callsite(2))
    return _RAW_CONDITION(lock)


# ---------------------------------------------------------------------------
# install / remove


def instrument(hold_ms: float = 250.0) -> Witness:
    """Patch threading's lock constructors and excepthook; returns the
    fresh process-global Witness. Re-entrant calls return the existing
    witness (one harness owns the patch at a time)."""
    global _WITNESS, _SAVED
    if _WITNESS is not None:
        return _WITNESS
    _WITNESS = Witness(hold_ms=hold_ms)
    _SAVED = (
        threading.Lock,
        threading.RLock,
        threading.Condition,
        threading.excepthook,
    )
    def _hook(args):
        # the witness is the loud path (the conftest fixture asserts on
        # it at teardown); the previous hook is NOT chained, so the same
        # exception is not double-reported through pytest's
        # threadexception warning on top of the witness failure
        w = _WITNESS
        if w is not None:
            w.on_thread_exception(args)

    threading.Lock = _lock_factory
    threading.RLock = _rlock_factory
    threading.Condition = _condition_factory
    threading.excepthook = _hook
    return _WITNESS


def uninstrument() -> Optional[Witness]:
    """Restore threading's constructors; returns the retired witness.
    Wrappers already handed out stay functional but stop reporting."""
    global _WITNESS, _SAVED
    witness = _WITNESS
    if _SAVED is not None:
        (
            threading.Lock,
            threading.RLock,
            threading.Condition,
            threading.excepthook,
        ) = _SAVED
        _SAVED = None
    _WITNESS = None
    return witness


def current() -> Optional[Witness]:
    return _WITNESS
