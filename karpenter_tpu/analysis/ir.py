"""graftlint IR tier: jaxpr-level kernel contract analyzer.

The AST tier (analysis/engine.py + rules_*) sees source text; the
contracts that actually govern solver performance — trace-time statics,
loop-carry bytes (the carry is copied every device iteration), one
upload of the per-class tables per solve, int32-only device dtypes —
live in what XLA compiles, which `ast` cannot see. This module traces
the REAL solver entry points on small representative problems, walks the
resulting jaxprs, and enforces measured budgets from the checked-in
`kernel_budgets.json` (analysis/budgets.py).

Rules:

- `ir-callbacks`: no `pure_callback`/`io_callback`/`debug_callback`/
  infeed/outfeed primitives anywhere in a jitted solver program — a host
  callback inside the kernel rides the slow host<->device tunnel once per
  invocation and defeats the dense-tensor design.
- `ir-dtype`: no 64-bit avals on device (the documented int64 overflow
  guards are HOST-side numpy and never appear in a jaxpr) and no
  weakly-typed loop carries (a weak carry re-promotes per iteration and
  destabilizes the compiled-shape identity).
- `ir-carry-budget`: loop-carry bytes and while/scan structure, computed
  from the traced program's carry avals, pinned by kernel_budgets.json.
- `ir-retrace`: the trace-time-static contract — a zero-preference
  problem compiles the plain step (`relax=True` adds EXACTLY one
  while loop: the tier ladder; more means the step got duplicated, the
  historical cond(plain, tiers) bug), and a repeated same-shape solve
  causes zero retraces and zero compiles (budgeted exact-0).
- `ir-transfer`: per-solve upload accounting — the per-class tables ship
  exactly once per solve (`TpuScheduler._upload_pod_tables` contract)
  and per-round pod batches stay within budget.

Unlike the rest of the analysis package this module DOES import JAX
(lazily, inside functions): `import karpenter_tpu.analysis` stays
JAX-free (the no-JAX subprocess test pins it), and the CLI only loads
this module under `--ir`.
"""

from __future__ import annotations

import contextlib
import dataclasses
import functools
from typing import Any, Callable, Iterable, Iterator, Optional

from karpenter_tpu.analysis import budgets as budgets_mod
from karpenter_tpu.analysis.engine import IR_DEFAULT_BASELINE, Finding

IR_RULES: dict[str, str] = {
    "ir-callbacks": (
        "no host-callback/infeed/outfeed primitives in jitted solver "
        "programs"
    ),
    "ir-dtype": (
        "no 64-bit avals on device; loop carries must not be weakly typed"
    ),
    "ir-carry-budget": (
        "loop-carry bytes and while/scan structure pinned by "
        "kernel_budgets.json"
    ),
    "ir-retrace": (
        "trace-time-static contract: relax adds exactly one while loop; "
        "a repeated same-shape solve retraces nothing"
    ),
    "ir-transfer": (
        "per-class tables upload once per solve; per-round batch uploads "
        "within budget"
    ),
}

# metric -> owning rule (budget comparisons surface under the rule whose
# contract the metric measures)
_METRIC_RULE = {
    "table_uploads": "ir-transfer",
    "pod_table_uploads": "ir-transfer",
    "pod_batch_uploads": "ir-transfer",
    "first_solve_traces": "ir-retrace",
    "second_solve_traces": "ir-retrace",
    "second_solve_compiles": "ir-retrace",
    "same_bucket_solve_traces": "ir-retrace",
    "same_bucket_solve_compiles": "ir-retrace",
    # removal-set sweep accounting (setsweep_runtime_metrics)
    "set_table_uploads": "ir-transfer",
    "set_pod_table_uploads": "ir-transfer",
    "set_eval_dispatches": "ir-transfer",
    "set_second_eval_traces": "ir-retrace",
    "set_second_eval_compiles": "ir-retrace",
    # epoch steady-state accounting (epoch_runtime_metrics): a repeat
    # same-epoch solve through the device-table cache uploads ONLY the
    # pending-pod batch — exact-zero per-class table re-uploads
    "epoch_first_table_uploads": "ir-transfer",
    "epoch_repeat_table_uploads": "ir-transfer",
    "epoch_repeat_pod_table_uploads": "ir-transfer",
    "epoch_repeat_pod_batch_uploads": "ir-transfer",
    # fleet coalescing accounting (fleet_runtime_metrics): a coalesced
    # window shares one device-table materialization (repeat window =
    # zero table uploads), runs ONE vmapped dispatch, and a repeat
    # same-bucket batch hits every jit cache
    "fleet_first_window_table_uploads": "ir-transfer",
    "fleet_repeat_window_table_uploads": "ir-transfer",
    "fleet_repeat_window_dispatches": "ir-transfer",
    "fleet_repeat_window_traces": "ir-retrace",
    "fleet_repeat_window_compiles": "ir-retrace",
}

_FORBIDDEN_EXACT = frozenset(
    {"infeed", "outfeed", "outside_call", "host_local_array_to_global_array"}
)


def is_forbidden_primitive(name: str) -> bool:
    """pure_callback / io_callback / debug_callback / any *callback*
    primitive, plus the explicit host-transfer ops."""
    return "callback" in name or name in _FORBIDDEN_EXACT


# ---------------------------------------------------------------------------
# jaxpr walking (duck-typed on jaxpr structure; no jax import required, so
# the helpers are unit-testable against hand-built stand-ins)


def _closed(j: Any) -> Any:
    """ClosedJaxpr -> Jaxpr; Jaxpr passes through."""
    return j.jaxpr if hasattr(j, "jaxpr") and hasattr(j.jaxpr, "eqns") else j


def _subjaxprs(eqn: Any) -> Iterator[Any]:
    """Inner jaxprs of one equation (pjit/scan `jaxpr`, while
    `cond_jaxpr`/`body_jaxpr`, cond `branches`, ...)."""
    for v in eqn.params.values():
        for s in v if isinstance(v, (list, tuple)) else (v,):
            if hasattr(s, "eqns"):
                yield s
            elif hasattr(s, "jaxpr") and hasattr(s.jaxpr, "eqns"):
                yield s.jaxpr


def iter_eqns(jaxpr: Any) -> Iterator[Any]:
    """Every equation in the program, recursing into sub-jaxprs."""
    for eqn in _closed(jaxpr).eqns:
        yield eqn
        for sub in _subjaxprs(eqn):
            yield from iter_eqns(sub)


def aval_bytes(aval: Any) -> int:
    n = 1
    for d in aval.shape:
        n *= int(d)
    return n * aval.dtype.itemsize


@dataclasses.dataclass
class LoopStat:
    """One device loop (lax.scan or lax.while_loop) in a traced program."""

    kind: str  # "scan" | "while"
    length: Optional[int]  # scan trip count; None for while
    carry_bytes: int
    weak_carries: int  # carried avals with weak_type=True


def loop_stats(jaxpr: Any) -> list[LoopStat]:
    """Carry avals of every scan/while: the loop carry is copied every
    device iteration, so carry bytes dominate per-step cost (CLAUDE.md
    cost model) — this is the measurement kernel_budgets.json pins."""
    out = []
    for eqn in iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name == "scan":
            nc = eqn.params["num_consts"]
            ncar = eqn.params["num_carry"]
            inner = _closed(eqn.params["jaxpr"])
            carry = inner.invars[nc : nc + ncar]
            length = eqn.params.get("length")
            length = int(length) if length is not None else None
        elif name == "while":
            inner = _closed(eqn.params["body_jaxpr"])
            carry = inner.invars[eqn.params["body_nconsts"] :]
            length = None
        else:
            continue
        out.append(
            LoopStat(
                kind=name,
                length=length,
                carry_bytes=sum(aval_bytes(v.aval) for v in carry),
                weak_carries=sum(
                    1 for v in carry if getattr(v.aval, "weak_type", False)
                ),
            )
        )
    return out


def forbidden_primitives(jaxpr: Any) -> list[str]:
    found = []
    for eqn in iter_eqns(jaxpr):
        if is_forbidden_primitive(eqn.primitive.name):
            found.append(eqn.primitive.name)
    return sorted(set(found))


def wide_dtypes(jaxpr: Any) -> list[str]:
    """dtype names of any 8-byte aval appearing in the program."""
    found = set()
    for eqn in iter_eqns(jaxpr):
        for v in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(v, "aval", None)
            dtype = getattr(aval, "dtype", None)
            if dtype is not None and dtype.itemsize == 8:
                found.add(str(dtype))
    return sorted(found)


def kernel_metrics(jaxpr: Any) -> dict[str, int]:
    """The budgeted structure/carry measurements for one traced program."""
    stats = loop_stats(jaxpr)
    return {
        "while_loops": sum(1 for s in stats if s.kind == "while"),
        "scans": sum(1 for s in stats if s.kind == "scan"),
        "max_carry_bytes": max((s.carry_bytes for s in stats), default=0),
        "total_carry_bytes": sum(s.carry_bytes for s in stats),
        "scan_total_length": sum(s.length or 0 for s in stats),
    }


# ---------------------------------------------------------------------------
# trace/compile event counter (jax.monitoring duration events fire once
# per jaxpr trace / backend compile and NOT on cache hits — the counter
# the retrace contract and tests/test_compilecache.py both ride).
# The implementation lives in karpenter_tpu.tracing (shared telemetry:
# runtime solves export the same events as metrics); re-exported here so
# the IR tier and its historical importers keep one spelling.

from karpenter_tpu.tracing import (  # noqa: E402  (re-export)
    _COUNTS,
    trace_events,
)
from karpenter_tpu.tracing import (  # noqa: E402  (re-export)
    install_compile_listener as _install_listener,
)


@contextlib.contextmanager
def count_method_calls(cls: type, names: Iterable[str]):
    """Temporarily wrap methods of `cls` with call counters; yields the
    live {name: count} dict. Restores the original methods on exit."""
    counts = {n: 0 for n in names}
    originals = {n: getattr(cls, n) for n in counts}

    def _wrap(name: str, fn: Callable) -> Callable:
        @functools.wraps(fn)
        def wrapper(*args: Any, **kwargs: Any) -> Any:
            counts[name] += 1
            return fn(*args, **kwargs)

        return wrapper

    for n, fn in originals.items():
        setattr(cls, n, _wrap(n, fn))
    try:
        yield counts
    finally:
        for n, fn in originals.items():
            setattr(cls, n, fn)


# ---------------------------------------------------------------------------
# representative problems


@dataclasses.dataclass
class ProblemKit:
    """One small encoded problem with every artifact the entry points
    need. Built once per process (build_kit is cached): the kits are tiny
    (6 pods, 3 existing nodes, 8 claim slots) so tracing stays in the
    seconds range on JAX_PLATFORMS=cpu."""

    sched: Any
    problem: Any
    tb: Any
    st: Any
    order: list
    xs: Any
    x_row: Any
    idx_d: Any
    n_d: Any
    rx: Any
    seq: Any
    next_seq: Any
    relax: bool


def _make_views(n: int = 3) -> list:
    from karpenter_tpu.api import labels as well_known
    from karpenter_tpu.cloudprovider.kwok import construct_instance_types
    from karpenter_tpu.solver.nodes import StateNodeView

    it = construct_instance_types(sizes=[2])[0]
    return [
        StateNodeView(
            name=f"ir-existing-{i}",
            node_labels={well_known.TOPOLOGY_ZONE_LABEL_KEY: "test-zone-a"},
            labels={
                well_known.TOPOLOGY_ZONE_LABEL_KEY: "test-zone-a",
                well_known.INSTANCE_TYPE_LABEL_KEY: it.name,
                well_known.NODEPOOL_LABEL_KEY: "default",
            },
            available=dict(it.allocatable()),
            capacity=dict(it.capacity),
            initialized=True,
        )
        for i in range(n)
    ]


def _make_pods(kind: str, n: int = 6) -> list:
    from karpenter_tpu.testing import fixtures

    fixtures.reset_rng(7)
    if kind == "generic":
        return fixtures.make_generic_pods(n)
    # mixed: relaxable preference pods AND plain pods in one batch — the
    # shape the one-step-instance contract is about
    return fixtures.make_generic_pods(n // 2) + fixtures.make_preference_pods(
        n - n // 2
    )


def _make_sched(kind: str, n_pods: int = 6, table_cache=None) -> tuple:
    """(TpuScheduler, pods) for one representative problem — the SINGLE
    construction both the jaxpr tier (build_kit) and the runtime
    accounting (_runtime_solve) measure, so their budgets can never
    silently describe different problems. `n_pods` varies the REAL size
    within a shape bucket (solver/buckets.py) for the same-bucket
    zero-retrace contract; `table_cache` (epochs.DeviceTableCache)
    threads the epoch steady-state path for epoch_runtime_metrics."""
    from karpenter_tpu.cloudprovider.kwok import construct_instance_types
    from karpenter_tpu.solver.topology import Topology
    from karpenter_tpu.solver.tpu import TpuScheduler
    from karpenter_tpu.testing import fixtures

    fixtures.reset_rng(7)
    its = construct_instance_types(sizes=[2])
    pool = fixtures.node_pool(name="default")
    pods = _make_pods(kind, n_pods)
    views = _make_views()
    topo = Topology([pool], {"default": its}, pods, state_node_views=views)
    return (
        TpuScheduler(
            [pool], {"default": its}, topo, views, table_cache=table_cache
        ),
        pods,
    )


@functools.lru_cache(maxsize=None)
def build_kit(kind: str) -> ProblemKit:
    """kind: "generic" (zero-preference, existing nodes, bulkable) or
    "mixed" (relaxable + plain pods in one batch). The persistent compile
    cache is configured by the solver package import below."""
    import jax
    import jax.numpy as jnp

    from karpenter_tpu.solver.tpu import _bulk_class_flags, _bulk_gates
    from karpenter_tpu.solver.tpu_problem import encode_problem

    sched, pods = _make_sched(kind)
    problem = encode_problem(sched.oracle, pods)
    tb = sched._tables(problem)
    sched._upload_pod_tables(problem)
    st = sched._init_state(problem, 8)
    order = sched._order_pods(problem)
    gates_ok = _bulk_gates(problem, strict_types=False)
    sched._bulk_flags_c = _bulk_class_flags(problem, gates_ok)
    sched._set_runflags_dev()
    xs, idx_d, n_d = sched._pod_xs_with_idx(problem, order)
    rx = sched._run_x(xs, idx_d, n_d)
    x_row = jax.tree_util.tree_map(lambda a: a[0], xs)
    return ProblemKit(
        sched=sched,
        problem=problem,
        tb=tb,
        st=st,
        order=order,
        xs=xs,
        x_row=x_row,
        idx_d=idx_d,
        n_d=n_d,
        rx=rx,
        seq=jnp.zeros(8, jnp.int32),
        next_seq=jnp.zeros((), jnp.int32),
        relax=bool((problem.ntiers_r > 1).any()),
    )


# ---------------------------------------------------------------------------
# entry points


@dataclasses.dataclass(frozen=True)
class EntryPoint:
    """One traced kernel entry. `build` returns (fn, args) ready for
    jax.make_jaxpr; `path` is the repo-relative module the finding cites."""

    name: str
    path: str
    kit: str
    build: Callable[[ProblemKit], tuple]


def _ep_solve_scan(relax: bool) -> Callable[[ProblemKit], tuple]:
    def build(kit: ProblemKit) -> tuple:
        from karpenter_tpu.solver import tpu_kernel as K

        return (
            lambda tb, st, xs: K.solve_scan(tb, st, xs, relax=relax),
            (kit.tb, kit.st, kit.xs),
        )

    return build


def _ep_solve_runs(relax: bool) -> Callable[[ProblemKit], tuple]:
    def build(kit: ProblemKit) -> tuple:
        import jax.numpy as jnp

        from karpenter_tpu.solver import tpu_runs as KR

        return (
            lambda tb, st, rx, seq, nseq, n: KR.solve_runs(
                tb, st, rx, seq, nseq, n, relax=relax
            ),
            (
                kit.tb,
                kit.st,
                kit.rx,
                kit.seq,
                kit.next_seq,
                jnp.int32(len(kit.order)),
            ),
        )

    return build


def _ep_step_relax(kit: ProblemKit) -> tuple:
    from karpenter_tpu.solver import tpu_kernel as K

    return K._step_relax, (kit.tb, kit.st, kit.x_row)


def _ep_sweep(kit: ProblemKit) -> tuple:
    import jax.numpy as jnp
    import numpy as np

    from karpenter_tpu.controllers.disruption import sweep as SW

    p = kit.problem
    B = 4  # lanes; shape-only — the trace never executes
    sizes = jnp.asarray(p.prequests_c[:1].astype(np.int32))
    counts = jnp.ones((B, 1), jnp.int32)
    cand_idx = jnp.asarray(
        np.arange(p.num_existing, dtype=np.int32) % B
    )
    return (
        functools.partial(SW._fast_sweep_kernel, singleton=False),
        (
            kit.tb,
            kit.st,
            kit.x_row,
            jnp.asarray(p.eavail),
            cand_idx,
            counts,
            sizes,
        ),
    )


def _ep_set_sweep(kit: ProblemKit) -> tuple:
    """The removal-set kernel at the bounded-dispatch contract shape:
    1024 membership lanes (>= the 1000-sets-per-dispatch capability the
    subsystem exists for) over the generic kit's union problem. Shape-
    only — the trace never executes; the lane count pins the carry/
    structure budget at the scale the bench demonstrates."""
    import jax.numpy as jnp
    import numpy as np

    from karpenter_tpu.controllers.disruption import setsweep as SS

    p = kit.problem
    B, J = 1024, 8  # lanes x candidates (J padded pow2, setsweep build)
    sizes = jnp.asarray(p.prequests_c[:1].astype(np.int32))
    base_counts = jnp.zeros((1,), jnp.int32)
    percand = jnp.ones((J, 1), jnp.int32)
    member = jnp.asarray(
        (np.arange(B)[:, None] >> np.arange(J)[None, :]) & 1, jnp.int32
    )
    slot_cand = jnp.asarray(
        np.arange(p.num_existing, dtype=np.int32) % (J + 1)
    )
    return (
        SS._set_sweep_kernel,
        (
            kit.tb,
            kit.st,
            kit.x_row,
            jnp.asarray(p.eavail),
            slot_cand,
            member,
            base_counts,
            percand,
            sizes,
        ),
    )


def _ep_typeok(kit: ProblemKit) -> tuple:
    import jax.numpy as jnp

    from karpenter_tpu.ops.encode import Reqs
    from karpenter_tpu.solver.tpu import _typeok_chunk_impl

    p = kit.problem
    chunk = Reqs(*(jnp.asarray(a[p.rclass_creps]) for a in p.preq_c))
    iw = max(1, (p.num_types + 31) // 32)
    return (
        functools.partial(_typeok_chunk_impl, iw=iw),
        (kit.tb.ireq, kit.tb.va, chunk),
    )


def _ep_fleet(kit: ProblemKit) -> tuple:
    """The lane-batched serving entry (solver/fleet.py) at a pinned
    8-lane bucket: vmap(solve_scan) over the generic kit's state/pod
    batch replicated per lane. The vmapped program must keep the solo
    kernel's structure — one scan, one exact-verify while loop — with
    the carry scaled by the lane count; extra loops would mean the lane
    axis leaked into control flow instead of batching it."""
    import functools

    import jax

    from karpenter_tpu.solver import fleet as fleet_mod
    from karpenter_tpu.solver import tpu_kernel as K

    B = 8
    st_b, xs_b = fleet_mod.stack_lanes([kit.st] * B, [kit.xs] * B)
    return (
        jax.vmap(
            functools.partial(K.solve_scan, relax=False),
            in_axes=(None, 0, 0),
        ),
        (kit.tb, st_b, xs_b),
    )


def _ep_gather_xs(kit: ProblemKit) -> tuple:
    from karpenter_tpu.solver import tpu as T

    return (
        lambda tables, idx, n: T._gather_xs(tables, idx, n),
        (kit.sched._dev_tables, kit.idx_d, kit.n_d),
    )


_KERNEL_PATH = "karpenter_tpu/solver/tpu_kernel.py"
_RUNS_PATH = "karpenter_tpu/solver/tpu_runs.py"
_TPU_PATH = "karpenter_tpu/solver/tpu.py"
_SWEEP_PATH = "karpenter_tpu/controllers/disruption/sweep.py"
_SETSWEEP_PATH = "karpenter_tpu/controllers/disruption/setsweep.py"
_FLEET_PATH = "karpenter_tpu/solver/fleet.py"

ENTRY_POINTS: tuple[EntryPoint, ...] = (
    EntryPoint(
        "solve_scan[relax=False]", _KERNEL_PATH, "generic",
        _ep_solve_scan(False),
    ),
    EntryPoint(
        "solve_scan[relax=True]", _KERNEL_PATH, "mixed", _ep_solve_scan(True)
    ),
    EntryPoint(
        "solve_runs[relax=False]", _RUNS_PATH, "generic",
        _ep_solve_runs(False),
    ),
    EntryPoint(
        "solve_runs[relax=True]", _RUNS_PATH, "mixed", _ep_solve_runs(True)
    ),
    EntryPoint("_step_relax", _KERNEL_PATH, "mixed", _ep_step_relax),
    EntryPoint("_fast_sweep_kernel", _SWEEP_PATH, "generic", _ep_sweep),
    EntryPoint("_set_sweep_kernel", _SETSWEEP_PATH, "generic", _ep_set_sweep),
    EntryPoint("_typeok_chunk", _TPU_PATH, "generic", _ep_typeok),
    EntryPoint("_gather_xs", _TPU_PATH, "generic", _ep_gather_xs),
    EntryPoint("fleet_solve_scan[B=8]", _FLEET_PATH, "generic", _ep_fleet),
)

# the trace-time-static contract pairs: relax=True must contain EXACTLY
# one more while loop (the tier ladder) than its relax=False twin —
# equal counts mean the plain path compiled tier machinery; +2 or more
# means the step got duplicated (the historical cond(plain, tiers) bug)
STRUCTURE_PAIRS: tuple[tuple[str, str, str], ...] = (
    ("solve_scan[relax=False]", "solve_scan[relax=True]", _KERNEL_PATH),
    ("solve_runs[relax=False]", "solve_runs[relax=True]", _RUNS_PATH),
)


def trace_entry(ep: EntryPoint) -> Any:
    """ClosedJaxpr of one entry point on its representative problem."""
    import jax

    kit = build_kit(ep.kit)
    fn, args = ep.build(kit)
    return jax.make_jaxpr(fn)(*args)


def structure_findings(
    measured: dict[str, dict[str, int]]
) -> list[Finding]:
    out = []
    for plain, relaxed, path in STRUCTURE_PAIRS:
        if plain not in measured or relaxed not in measured:
            continue
        wp = measured[plain]["while_loops"]
        wr = measured[relaxed]["while_loops"]
        if wr != wp + 1:
            out.append(
                Finding(
                    rule="ir-retrace",
                    path=path,
                    line=1,
                    message=(
                        f"{relaxed} has {wr} while loops vs {wp} in "
                        f"{plain} — the relax ladder must add exactly one "
                        "(equal: plain path compiled tier machinery; +2: "
                        "the step instance got duplicated)"
                    ),
                    text=relaxed,
                )
            )
    return out


# ---------------------------------------------------------------------------
# runtime accounting (retrace + transfer): two REAL solves of the generic
# problem with fresh schedulers. The second has identical shapes, so the
# trace-time-static contract demands zero new traces and zero compiles.


def _runtime_solve(n_pods: int = 6) -> Any:
    sched, pods = _make_sched("generic", n_pods)
    return sched.solve(pods)


def runtime_metrics() -> dict[str, int]:
    """The budgeted runtime measurements (entry `solve[runtime]`).

    The same_bucket pair is the mechanical pin on the shape-bucket
    contract (solver/buckets.py): a solve of a DIFFERENT real problem
    size that lands in the same pow-2 bucket must hit every jit cache —
    zero traces and zero compiles — which is exactly what makes a
    prewarmed steady-state replica compile-free at traffic time."""
    from karpenter_tpu.solver.tpu import TpuScheduler

    counted = ("_tables", "_upload_pod_tables", "_pod_xs_with_idx")
    with trace_events() as ev1, count_method_calls(
        TpuScheduler, counted
    ) as calls:
        _runtime_solve()
        first_traces = ev1.traces
    with trace_events() as ev2:
        _runtime_solve()
    with trace_events() as ev3:
        _runtime_solve(n_pods=7)  # same pow-2 bucket, different real size
    return {
        "table_uploads": calls["_tables"],
        "pod_table_uploads": calls["_upload_pod_tables"],
        "pod_batch_uploads": calls["_pod_xs_with_idx"],
        "first_solve_traces": first_traces,
        "second_solve_traces": ev2.traces,
        "second_solve_compiles": ev2.compiles,
        "same_bucket_solve_traces": ev3.traces,
        "same_bucket_solve_compiles": ev3.compiles,
    }


def epoch_runtime_metrics() -> dict[str, int]:
    """Entry `epoch[runtime]`: the steady-state incremental-solve upload
    contract (ROADMAP item 3 / the epoch PR's acceptance pin). With a
    shared epochs.DeviceTableCache — exactly how SolverServer serves a
    repeat same-epoch solve — the SECOND solve of an identical table
    encoding must call `_tables`/`_upload_pod_tables` exactly ZERO times:
    the only remaining per-solve upload is the pending-pod index batch
    (`_pod_xs_with_idx`). The first solve still uploads once, pinning
    that the cache never changes the cold path."""
    from karpenter_tpu.solver import epochs
    from karpenter_tpu.solver.tpu import TpuScheduler

    cache = epochs.DeviceTableCache()
    counted = ("_tables", "_upload_pod_tables", "_pod_xs_with_idx")

    def solve_once():
        sched, pods = _make_sched("generic", table_cache=cache)
        return sched.solve(pods)

    with count_method_calls(TpuScheduler, counted) as first:
        solve_once()
    with count_method_calls(TpuScheduler, counted) as repeat:
        solve_once()
    return {
        "epoch_first_table_uploads": first["_tables"],
        "epoch_repeat_table_uploads": repeat["_tables"],
        "epoch_repeat_pod_table_uploads": repeat["_upload_pod_tables"],
        "epoch_repeat_pod_batch_uploads": repeat["_pod_xs_with_idx"],
    }


def _make_fleet_sched(table_cache=None, fleet=None):
    """(TpuScheduler, pods) for the fleet runtime contract: the shared
    scan-path fixture (fixtures.make_self_spread_pods — self-selecting
    zone spread forces the exact per-pod SCAN path, the only path the
    coalescer serves)."""
    from karpenter_tpu.cloudprovider.kwok import construct_instance_types
    from karpenter_tpu.solver.topology import Topology
    from karpenter_tpu.solver.tpu import TpuScheduler
    from karpenter_tpu.testing import fixtures

    fixtures.reset_rng(7)
    its = construct_instance_types(sizes=[2])
    pool = fixtures.node_pool(name="default")
    pods = fixtures.make_self_spread_pods(6)
    topo = Topology([pool], {"default": its}, pods)
    return (
        TpuScheduler(
            [pool], {"default": its}, topo,
            table_cache=table_cache, fleet=fleet,
        ),
        pods,
    )


def fleet_runtime_metrics() -> dict[str, int]:
    """Entry `fleet[runtime]`: the coalesced-window transfer/retrace
    contract (solver/fleet.py). Two concurrent scan-path lanes through
    one FleetCoalescer + shared DeviceTableCache — exactly how a
    fleet-serving SolverServer stacks sibling solves:

    - the FIRST window materializes the shared `Tables` pytree exactly
      ONCE: the cache's table-level single-flight
      (epochs.DeviceTableCache.begin_tables) elects one builder per
      table fingerprint, closing the old both-lanes-encode-before-
      either-put race (the budget's former ceiling of 2);
    - a REPEAT window of the same table encoding uploads exactly ZERO
      per-class tables (every lane hits the server's resident cache —
      one materialization serves the whole window),
    - runs exactly ONE vmapped dispatch, and
    - retraces/compiles nothing (the same-bucket zero-compile contract
      extends to the lane-batched entry)."""
    import threading

    from karpenter_tpu import tracing as tracing_mod
    from karpenter_tpu.solver import epochs as epochs_mod
    from karpenter_tpu.solver import fleet as fleet_mod
    from karpenter_tpu.solver.tpu import TpuScheduler

    cache = epochs_mod.DeviceTableCache()
    coalescer = fleet_mod.FleetCoalescer(window_seconds=10.0, max_lanes=2)

    def window() -> None:
        lanes = [_make_fleet_sched(cache, coalescer) for _ in range(2)]
        errors: list[BaseException] = []

        def run(sched, pods) -> None:
            try:
                sched.solve(pods)
            except BaseException as e:  # surfaced below, never swallowed
                errors.append(e)

        threads = [
            threading.Thread(target=run, args=lane, daemon=True)
            for lane in lanes
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        if errors:
            raise errors[0]
        if not all(s.last_used_fleet for s, _ in lanes):
            raise RuntimeError(
                "fleet runtime contract: lanes did not coalesce"
            )

    with count_method_calls(TpuScheduler, ("_tables",)) as first:
        window()
    d0 = tracing_mod.SOLVE_DISPATCHES.value({"path": "fleet"})
    with count_method_calls(TpuScheduler, ("_tables",)) as repeat:
        with trace_events() as ev:
            window()
    dispatches = int(
        tracing_mod.SOLVE_DISPATCHES.value({"path": "fleet"}) - d0
    )
    return {
        "fleet_first_window_table_uploads": first["_tables"],
        "fleet_repeat_window_table_uploads": repeat["_tables"],
        "fleet_repeat_window_dispatches": dispatches,
        "fleet_repeat_window_traces": ev.traces,
        "fleet_repeat_window_compiles": ev.compiles,
    }


def _make_set_fleet():
    """A tiny real under-utilized fleet (5 one-rider nodes through the
    actual control plane) — the smallest scenario that exercises the
    removal-set subsystem end to end. Oracle-forced provisioning keeps
    the setup JAX-compile-free; only the set sweep itself compiles."""
    from karpenter_tpu.controllers.disruption.consolidation import (
        MultiNodeConsolidation,
    )
    from karpenter_tpu.testing import fixtures

    op = fixtures.underutilized_operator(
        5, seed=7, force_oracle=True, max_ticks=120
    )
    mnc = MultiNodeConsolidation(
        op.kube, op.cluster, op.cloud, op.clock, options=op.opts,
        force_oracle=True,
    )
    return op, mnc.candidates()


def setsweep_runtime_metrics() -> dict[str, int]:
    """Entry `setsweep[runtime]`: the removal-set subsystem's transfer
    and retrace contracts on a real (tiny) fleet — context build uploads
    the device tables exactly once, a 1024-lane membership batch (the
    >=1000-sets bounded-dispatch capability) is ONE device dispatch with
    no per-set host round-trips, and a second same-bucket batch hits
    every jit cache (0 traces, 0 compiles)."""
    import numpy as np

    from karpenter_tpu.controllers.disruption.setsweep import (
        SetProposer,
        SetSweepContext,
    )
    from karpenter_tpu.solver.tpu import TpuScheduler

    op, candidates = _make_set_fleet()
    with count_method_calls(
        TpuScheduler, ("_tables", "_upload_pod_tables")
    ) as uploads:
        ctx = SetSweepContext.build(
            op.kube, op.cluster, op.cloud, candidates, op.opts
        )
    proposer = SetProposer(candidates, seed=7, max_lanes=1024)
    member = proposer._dedup(proposer._random(8 * 1024))
    pad = np.zeros((1024, len(candidates)), bool)
    pad[: len(member)] = member[:1024]
    with count_method_calls(SetSweepContext, ("_dispatch",)) as calls:
        ctx.evaluate(pad)
    with trace_events() as ev2:
        ctx.evaluate(pad[::-1].copy())
    return {
        "set_table_uploads": uploads["_tables"],
        "set_pod_table_uploads": uploads["_upload_pod_tables"],
        "set_eval_dispatches": calls["_dispatch"],
        "set_second_eval_traces": ev2.traces,
        "set_second_eval_compiles": ev2.compiles,
    }


# ---------------------------------------------------------------------------
# the runner


def _active(rule_ids: Optional[set]) -> set:
    return set(IR_RULES) if rule_ids is None else set(rule_ids) & set(IR_RULES)


def measure(
    rule_ids: Optional[set] = None,
) -> tuple[dict[str, dict[str, int]], list[Finding], list[str]]:
    """Trace every entry point (and, when the retrace/transfer rules are
    active, run the runtime accounting) on the representative problems.
    Returns (measured metrics by entry, direct findings, errors)."""
    active = _active(rule_ids)
    measured: dict[str, dict[str, int]] = {}
    findings: list[Finding] = []
    errors: list[str] = []
    need_traces = active & {
        "ir-callbacks", "ir-dtype", "ir-carry-budget", "ir-retrace",
    }
    if need_traces:
        for ep in ENTRY_POINTS:
            try:
                jaxpr = trace_entry(ep)
            except Exception as e:  # a kernel that no longer traces is a
                # broken gate, not a silent skip
                errors.append(f"{ep.name}: {type(e).__name__}: {e}")
                continue
            measured[ep.name] = kernel_metrics(jaxpr)
            if "ir-callbacks" in active:
                for prim in forbidden_primitives(jaxpr):
                    findings.append(
                        Finding(
                            rule="ir-callbacks",
                            path=ep.path,
                            line=1,
                            message=(
                                f"{ep.name}: forbidden host primitive "
                                f"`{prim}` in the compiled program"
                            ),
                            text=ep.name,
                        )
                    )
            if "ir-dtype" in active:
                for dt in wide_dtypes(jaxpr):
                    findings.append(
                        Finding(
                            rule="ir-dtype",
                            path=ep.path,
                            line=1,
                            message=(
                                f"{ep.name}: 64-bit aval `{dt}` on device "
                                "(int64 guards belong on the host)"
                            ),
                            text=ep.name,
                        )
                    )
                weak = sum(
                    s.weak_carries for s in loop_stats(jaxpr)
                )
                if weak:
                    findings.append(
                        Finding(
                            rule="ir-dtype",
                            path=ep.path,
                            line=1,
                            message=(
                                f"{ep.name}: {weak} weakly-typed loop "
                                "carry aval(s) — pin the dtype"
                            ),
                            text=ep.name,
                        )
                    )
        if "ir-retrace" in active:
            findings.extend(structure_findings(measured))
    if active & {"ir-retrace", "ir-transfer"}:
        try:
            measured["solve[runtime]"] = runtime_metrics()
        except Exception as e:
            errors.append(f"solve[runtime]: {type(e).__name__}: {e}")
        try:
            measured["setsweep[runtime]"] = setsweep_runtime_metrics()
        except Exception as e:
            errors.append(f"setsweep[runtime]: {type(e).__name__}: {e}")
        try:
            measured["epoch[runtime]"] = epoch_runtime_metrics()
        except Exception as e:
            errors.append(f"epoch[runtime]: {type(e).__name__}: {e}")
        try:
            measured["fleet[runtime]"] = fleet_runtime_metrics()
        except Exception as e:
            errors.append(f"fleet[runtime]: {type(e).__name__}: {e}")
    return measured, findings, errors


def budget_findings(
    measured: dict[str, dict[str, int]],
    manifest: budgets_mod.BudgetManifest,
    rule_ids: Optional[set] = None,
    errored: Optional[set] = None,
) -> tuple[list[Finding], list[str]]:
    """Compare measurements against the manifest; returns (findings,
    improvement notes). Issues surface under the rule owning the metric
    (ir-transfer / ir-retrace for runtime metrics, ir-carry-budget for
    structure/carry and entry-level issues). `errored` names entries
    whose trace FAILED — their budget entries must not read as orphaned
    (the breakage is reported as an error, exit 2, not as 'remove the
    budget entry')."""
    active = _active(rule_ids)
    cmp = manifest.compare(measured)
    path = _entry_paths()
    findings = []
    for issue in cmp.issues:
        if issue.kind == "orphaned-entry" and (
            rule_ids is not None or issue.entry in (errored or ())
        ):
            # a partial run measures a slice of the entry points, and a
            # trace failure leaves its entry unmeasured; neither makes
            # the budget entry rot — only a full, error-free absence does
            continue
        rule = _METRIC_RULE.get(issue.metric or "", "ir-carry-budget")
        if rule not in active:
            continue
        findings.append(
            Finding(
                rule=rule,
                path=path.get(issue.entry, _TPU_PATH),
                line=1,
                message=issue.render(),
                text=issue.entry,
            )
        )
    notes = [i.render() for i in cmp.improvements]
    return findings, notes


def _entry_paths() -> dict[str, str]:
    paths = {ep.name: ep.path for ep in ENTRY_POINTS}
    paths["solve[runtime]"] = _TPU_PATH
    paths["setsweep[runtime]"] = _SETSWEEP_PATH
    paths["epoch[runtime]"] = "karpenter_tpu/solver/epochs.py"
    paths["fleet[runtime]"] = _FLEET_PATH
    return paths


def run_ir_analysis(
    repo_root: str,
    budgets_path: Optional[str] = None,
    baseline_path: Optional[str] = None,
    rule_ids: Optional[set] = None,
) -> dict:
    """The IR pipeline: trace, account, compare to kernel_budgets.json,
    apply the IR baseline. Mirrors engine.run_analysis's report shape:
    {"findings": fresh, "all_findings", "stale", "unjustified",
     "budget_unjustified", "improvements", "errors", "measured"}."""
    import os

    from karpenter_tpu.analysis.engine import Baseline

    budgets_path = budgets_path or os.path.join(
        repo_root, budgets_mod.DEFAULT_MANIFEST
    )
    baseline_path = (
        baseline_path
        if baseline_path is not None
        else os.path.join(repo_root, IR_DEFAULT_BASELINE)
    )
    # this tier owns the un-prefixed half of the shared manifest; the
    # `spmd:` entries belong to analysis/spmd.py (budgets.SPMD_PREFIX)
    manifest = budgets_mod.BudgetManifest.load(budgets_path).scoped(
        spmd=False
    )
    measured, findings, errors = measure(rule_ids)
    errored = {e.split(":", 1)[0] for e in errors}
    bfindings, improvements = budget_findings(
        measured, manifest, rule_ids, errored=errored
    )
    findings = sorted(
        findings + bfindings, key=lambda f: (f.path, f.rule, f.text)
    )
    baseline = Baseline.load(baseline_path)
    fresh, stale = baseline.apply(findings)
    budget_unjustified = (
        manifest.unjustified()
        if _active(rule_ids)
        >= {"ir-carry-budget", "ir-retrace", "ir-transfer"}
        else []
    )
    return {
        "findings": fresh,
        "all_findings": findings,
        "stale": stale,
        "unjustified": baseline.unjustified(),
        "budget_unjustified": budget_unjustified,
        "improvements": improvements,
        "errors": errors,
        "measured": measured,
        "manifest": manifest,
    }
