"""Rule guarding the wire codec's enum coverage.

- wire-enum-coverage: every str-enum-typed field across the
  karpenter_tpu/api dataclasses must appear in `codec._ENUM_FIELDS`.
  A bare wire value decodes as `str`, which compares EQUAL to its
  str-enum member, so every selector/taint/phase comparison keeps
  working — until a `.value` access crashes in some error path (the
  differential fuzzer's find, corpus pin seed8505). This rule makes
  that bug class unrepresentable: adding an enum-typed field to
  api/objects.py without registering its coercion fails the lint.
"""

from __future__ import annotations

import ast
import os
from typing import Optional

from karpenter_tpu.analysis.engine import FileContext, Finding, Rule

_CODEC_PATH = "karpenter_tpu/api/codec.py"


def _str_enum_names(tree: ast.Module) -> set[str]:
    """Class names subclassing both `str` and `Enum` (the wire-value
    enums; plain Enums ride the codec's `__enum__` envelope instead)."""
    out = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        bases = set()
        for b in node.bases:
            if isinstance(b, ast.Name):
                bases.add(b.id)
            elif isinstance(b, ast.Attribute):
                bases.add(b.attr)
        if "str" in bases and "Enum" in bases:
            out.add(node.name)
    return out


def _enum_typed_fields(
    tree: ast.Module, enums: set[str]
) -> list[tuple[str, str, str]]:
    """(class, field, enum) for every annotated field whose annotation
    references a str-enum class — including Optional[...] and other
    wrappers (the annotation subtree is walked for enum Names)."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef) or node.name in enums:
            continue
        for stmt in node.body:
            if not isinstance(stmt, ast.AnnAssign) or not isinstance(
                stmt.target, ast.Name
            ):
                continue
            hit = next(
                (
                    sub.id
                    for sub in ast.walk(stmt.annotation)
                    if isinstance(sub, ast.Name) and sub.id in enums
                ),
                None,
            )
            if hit is not None:
                out.append((node.name, stmt.target.id, hit))
    return out


def _enum_fields_literal(
    tree: ast.Module,
) -> tuple[Optional[ast.AST], dict[str, set[str]]]:
    """The `_ENUM_FIELDS` dict literal parsed statically: {class name ->
    registered field names}. Returns (assign node, mapping)."""
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        else:
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == "_ENUM_FIELDS"
            for t in targets
        ):
            continue
        value = node.value
        mapping: dict[str, set[str]] = {}
        if isinstance(value, ast.Dict):
            for k, v in zip(value.keys, value.values):
                if not (
                    isinstance(k, ast.Constant) and isinstance(k.value, str)
                ):
                    continue
                fields = set()
                if isinstance(v, ast.Dict):
                    fields = {
                        fk.value
                        for fk in v.keys
                        if isinstance(fk, ast.Constant)
                        and isinstance(fk.value, str)
                    }
                mapping[k.value] = fields
        return node, mapping
    return None, {}


class WireEnumCoverageRule(Rule):
    id = "wire-enum-coverage"
    summary = (
        "every str-enum-typed field in karpenter_tpu/api dataclasses "
        "must be registered in codec._ENUM_FIELDS (seed8505 bug class)"
    )
    targets = (_CODEC_PATH,)

    def check(self, ctx: FileContext) -> list[Finding]:
        assign, registered = _enum_fields_literal(ctx.tree)
        if assign is None:
            return [
                ctx.finding(
                    self.id,
                    1,
                    "codec has no statically-parsable _ENUM_FIELDS dict "
                    "literal — the decode-time enum coercion table is the "
                    "wire contract this rule polices",
                )
            ]
        objects_path = os.path.join(os.path.dirname(ctx.path), "objects.py")
        try:
            with open(objects_path, encoding="utf-8") as f:
                objects_tree = ast.parse(f.read(), filename=objects_path)
        except (OSError, SyntaxError) as e:
            return [
                ctx.finding(
                    self.id,
                    assign,
                    f"cannot parse sibling objects.py ({type(e).__name__}: "
                    f"{e}) — enum coverage is unverifiable",
                )
            ]
        enums = _str_enum_names(objects_tree)
        out = []
        for cls, field, enum_name in _enum_typed_fields(objects_tree, enums):
            if field not in registered.get(cls, set()):
                out.append(
                    ctx.finding(
                        self.id,
                        assign,
                        f"{cls}.{field} is typed {enum_name} (a str enum) "
                        "but missing from _ENUM_FIELDS — it would decode "
                        "as bare str and crash on .value access (seed8505)",
                    )
                )
        return out


RULES = (WireEnumCoverageRule,)
