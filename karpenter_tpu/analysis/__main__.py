"""graftlint CLI: `python -m karpenter_tpu.analysis` (also installed as
the `graftlint` console script).

Five tiers share this entry point:

- the AST tier (default): stdlib-`ast` source analysis, JAX-free;
- the IR tier (`--ir`): traces the real solver kernels and walks the
  jaxprs (analysis/ir.py) — imports JAX, needs JAX_PLATFORMS=cpu or a
  device, and enforces kernel_budgets.json (`--write-budgets` to
  re-baseline after an intentional kernel change);
- the race tier (`--race`): whole-program lock analysis (analysis/
  locks.py) — acquisition-graph cycles, blocking calls under locks,
  thread-vs-public unguarded writes. JAX-free like the AST tier; the
  runtime half (analysis/racert.py) runs under pytest, not here.
- the SPMD tier (`--spmd`): compiles the real solver programs —
  including the lane-sharded fleet entry on an 8-virtual-device mesh —
  and walks the compiled/StableHLO modules (analysis/spmd.py):
  collective census, per-device HBM ceilings, donation census (the
  `spmd:` half of kernel_budgets.json) plus the launch-lock AST rule.
  The CLI pins the virtual mesh env BEFORE the first jax import.
- the protocol tier (`--proto`): explicit-state model checking of the
  solver wire/epoch/breaker state machines under channel faults
  (analysis/proto.py), plus live conformance — it drives the REAL
  ResilientSolver and a REAL drained SolverServer under the
  analysis/protorec.py trace recorder and verifies the recorded traces
  refine the model. Counterexamples ship as shrunk, replayable fault
  schedules (tests/proto_corpus/).

`--all` runs every tier (AST + race + IR + SPMD + proto) with merged
`--json` output, per-tier wall-clock seconds, and a single worst-case
exit code — the one-command CI gate; `--jobs N` runs the tiers in up
to N worker threads.

Exit codes: 0 clean (baseline-covered findings allowed), 1 findings or
stale/unjustified baseline or budget entries, 2 usage/parse/trace errors.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

from karpenter_tpu.analysis.engine import (
    IR_DEFAULT_BASELINE,
    PROTO_DEFAULT_BASELINE,
    SPMD_DEFAULT_BASELINE,
    Baseline,
    all_rules,
    canonical_json,
    run_analysis,
)

_DEFAULT_REFERENCE_ROOT = "/root/reference"


def _detect_repo_root() -> str:
    # the package lives at <root>/karpenter_tpu/analysis
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def _json_files_parse(*paths: str) -> bool:
    """Pre-flight the hand-editable JSON inputs (baselines, budgets): a
    trailing-comma typo must surface as the documented exit-2 parse
    diagnostic naming the file, not a raw JSONDecodeError traceback."""
    ok = True
    for p in paths:
        if not p or not os.path.exists(p):
            continue
        try:
            with open(p, encoding="utf-8") as f:
                json.load(f)
        except (json.JSONDecodeError, OSError) as e:
            print(f"graftlint: cannot parse {p}: {e}", file=sys.stderr)
            ok = False
    return ok


def _write_baseline_file(baseline_path: str, findings) -> int:
    """Shared --write-baseline tail for both tiers: regeneration keeps
    hand-written justifications (entries that still match a finding carry
    their text over; only genuinely new findings get the TODO
    placeholder)."""
    existing = Baseline.load(baseline_path)
    data = Baseline.render_entries(findings)
    fresh = existing.merge_justifications(data)
    with open(baseline_path, "w", encoding="utf-8") as f:
        f.write(canonical_json(data))
    print(
        f"graftlint: wrote {len(data['entries'])} entr"
        f"{'y' if len(data['entries']) == 1 else 'ies'} to "
        f"{baseline_path}"
        + (f" — justify the {fresh} new one(s)" if fresh else "")
    )
    return 0


def _tier_payload(findings, stale, unjustified, errors, baselined) -> dict:
    """The `--json` report shape every tier shares (IR adds its budget
    keys on top). One builder, or the tiers' payloads drift apart."""
    return {
        "findings": [f.to_dict() for f in findings],
        "stale_baseline": stale,
        "unjustified_baseline": unjustified,
        "errors": errors,
        "baselined": baselined,
    }


def _print_baseline_problems(stale, unjustified, prefix: str = "") -> None:
    """Itemize the stale/unjustified baseline entries behind an exit-1:
    a red gate must name each entry to act on, in `--all` (which tags a
    `[tier] ` prefix) exactly as in the single-tier modes."""
    for e in stale:
        print(
            f"{prefix}stale baseline entry: [{e.get('rule')}] "
            f"{e.get('path')}: {e.get('text')!r} no longer matches — "
            "remove it"
        )
    for e in unjustified:
        print(
            f"{prefix}unjustified baseline entry: [{e.get('rule')}] "
            f"{e.get('path')}: add a one-line justification"
        )


def _print_report_entries(findings, stale, unjustified) -> None:
    """The text-mode finding/stale/unjustified lines every tier shares
    (errors and the summary line stay per-tier: the error word and the
    counts genuinely differ)."""
    for f in findings:
        print(f.render())
    _print_baseline_problems(stale, unjustified)


def _changed_files(repo_root: str):
    """Modified + untracked .py files (git), for pre-commit `--changed-only`.
    Returns None when git itself fails — the caller must surface that as an
    error, never as 'nothing to lint'."""
    out: set[str] = set()
    for args in (
        ["git", "-C", repo_root, "diff", "--name-only", "HEAD"],
        ["git", "-C", repo_root, "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            res = subprocess.run(
                args, capture_output=True, text=True, timeout=10
            )
        except (OSError, subprocess.TimeoutExpired) as e:
            print(f"graftlint: git failed ({e})", file=sys.stderr)
            return None
        if res.returncode != 0:
            print(
                f"graftlint: git failed: {res.stderr.strip()}", file=sys.stderr
            )
            return None
        out.update(line.strip() for line in res.stdout.splitlines() if line.strip())
    return sorted(
        os.path.join(repo_root, p)
        for p in out
        if p.endswith(".py") and os.path.exists(os.path.join(repo_root, p))
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftlint",
        description="AST-based invariant analyzer (docs/static-analysis.md)",
    )
    parser.add_argument(
        "paths", nargs="*", help="files/dirs to lint (default: package + tests)"
    )
    parser.add_argument("--root", default=None, help="repo root (default: auto)")
    parser.add_argument(
        "--baseline",
        default=None,
        help="baseline file (default: <root>/graftlint.baseline.json)",
    )
    parser.add_argument(
        "--reference-root",
        default=_DEFAULT_REFERENCE_ROOT,
        help="reference checkout for .go citation resolution",
    )
    parser.add_argument(
        "--rules", default=None, help="comma-separated rule ids to run"
    )
    parser.add_argument("--json", action="store_true", help="JSON output")
    parser.add_argument(
        "--changed-only",
        action="store_true",
        help="lint only git-modified/untracked files (pre-commit fast mode)",
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="write current findings to the baseline file (justify each!)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rule ids and exit"
    )
    parser.add_argument(
        "--ir",
        action="store_true",
        help="run the IR tier: trace the solver kernels and walk the "
        "jaxprs (imports JAX; see docs/static-analysis.md)",
    )
    parser.add_argument(
        "--race",
        action="store_true",
        help="run the race tier's static half: whole-program lock-order/"
        "blocking-hold/unguarded-shared analysis (JAX-free; the runtime "
        "witness runs under pytest — see docs/static-analysis.md)",
    )
    parser.add_argument(
        "--spmd",
        action="store_true",
        help="run the SPMD tier: compile the solver programs (incl. the "
        "lane-sharded fleet entry on an 8-virtual-device mesh) and "
        "enforce the collective/HBM/donation budgets plus the "
        "launch-lock rule (imports JAX; see docs/static-analysis.md)",
    )
    parser.add_argument(
        "--proto",
        action="store_true",
        help="run the protocol tier: explicit-state model checking of "
        "the wire/epoch/breaker state machines under channel faults, "
        "plus live conformance against the real client/server/breaker "
        "(imports the solver stack; see docs/static-analysis.md)",
    )
    parser.add_argument(
        "--all",
        action="store_true",
        help="run every tier (AST + race + IR + SPMD + proto) with "
        "merged --json output, per-tier seconds, and a single "
        "worst-case exit code",
    )
    parser.add_argument(
        "--jobs",
        type=int,
        default=1,
        help="with --all: run the tiers in up to N worker threads "
        "(default 1, sequential; per-tier seconds stay wall-clock)",
    )
    parser.add_argument(
        "--budgets",
        default=None,
        help="IR budget manifest (default: <root>/kernel_budgets.json)",
    )
    parser.add_argument(
        "--write-budgets",
        action="store_true",
        help="re-baseline kernel_budgets.json from current measurements "
        "(implies --ir; justify each changed entry!)",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for r in all_rules():
            print(f"{r.id:20s} {r.summary}")
        from karpenter_tpu.analysis.ir import IR_RULES
        from karpenter_tpu.analysis.locks import RACE_RULES
        from karpenter_tpu.analysis.proto import PROTO_RULES
        from karpenter_tpu.analysis.spmd import SPMD_RULES

        for rid, summary in IR_RULES.items():
            print(f"{rid:20s} [ir] {summary}")
        for rid, summary in RACE_RULES.items():
            print(f"{rid:20s} [race] {summary}")
        for rid, summary in SPMD_RULES.items():
            print(f"{rid:20s} [spmd] {summary}")
        for rid, summary in PROTO_RULES.items():
            print(f"{rid:20s} [proto] {summary}")
        return 0

    repo_root = os.path.abspath(args.root or _detect_repo_root())
    # tier modes are mutually exclusive; silent precedence would let
    # `--ir --race` go green having never run the race tier, and
    # `--race --write-budgets` rewrite kernel_budgets.json unasked
    picked = [
        flag
        for flag, on in (
            ("--all", args.all),
            # --write-budgets without a tier flag keeps its historical
            # meaning (--ir); under --spmd it rewrites the spmd: half
            ("--ir", args.ir or (args.write_budgets and not args.spmd)),
            ("--race", args.race),
            ("--spmd", args.spmd),
            ("--proto", args.proto),
        )
        if on
    ]
    if len(picked) > 1:
        print(
            "graftlint: " + " and ".join(picked) + " are mutually "
            "exclusive — pick one tier mode (--all runs every tier; "
            "--write-budgets alone implies --ir)",
            file=sys.stderr,
        )
        return 2
    if args.jobs != 1 and not args.all:
        # an explicitly passed option that does nothing must be refused:
        # a single-tier run has no tiers to parallelize
        print(
            "graftlint: --jobs only applies to --all",
            file=sys.stderr,
        )
        return 2
    if args.jobs < 1:
        print("graftlint: --jobs must be >= 1", file=sys.stderr)
        return 2
    if args.all:
        return _main_all(args, repo_root)
    if args.proto:
        return _main_proto(args, repo_root)
    if args.spmd:
        return _main_spmd(args, repo_root)
    if args.write_budgets:
        args.ir = True
    if args.ir:
        return _main_ir(args, repo_root)
    if args.race:
        return _main_race(args, repo_root)
    paths = [os.path.abspath(p) for p in args.paths] or None
    if args.changed_only:
        paths = _changed_files(repo_root)
        if paths is None:
            return 2  # git failure must not read as a clean lint
        if not paths:
            print("graftlint: no changed python files")
            return 0
    rule_ids = (
        {r.strip() for r in args.rules.split(",")} if args.rules else None
    )
    if rule_ids is not None:
        # a typo'd rule id must not read as "nothing to check, clean"
        unknown = rule_ids - {r.id for r in all_rules()}
        if unknown:
            print(
                "graftlint: unknown rule id(s): "
                + ", ".join(sorted(unknown))
                + " (see --list-rules; ir-* rules need --ir, race-* "
                "rules need --race, spmd-* rules need --spmd)",
                file=sys.stderr,
            )
            return 2
    baseline_path = args.baseline or os.path.join(
        repo_root, "graftlint.baseline.json"
    )
    if not _json_files_parse(baseline_path):
        return 2

    report = run_analysis(
        repo_root,
        paths=paths,
        baseline_path=baseline_path,
        reference_root=args.reference_root,
        rule_ids=rule_ids,
    )

    if args.write_baseline:
        if paths is not None or rule_ids is not None:
            # a subset run sees only a slice of the findings; rewriting
            # from it would truncate every out-of-scope curated entry
            print(
                "graftlint: --write-baseline requires a full-tree, "
                "all-rules run (no explicit paths / --changed-only / "
                "--rules)",
                file=sys.stderr,
            )
            return 2
        return _write_baseline_file(baseline_path, report["all_findings"])

    findings = report["findings"]
    # subset runs (--changed-only, explicit paths, --rules) leave baseline
    # entries for out-of-scope files or rules unmatched — that is
    # expected, not staleness; only the default full run polices rot
    stale = (
        []
        if (paths is not None or rule_ids is not None)
        else report["stale"]
    )
    unjustified = report["unjustified"]
    errors = report["errors"]

    baselined = report["total"] - len(findings)
    if args.json:
        print(
            json.dumps(
                _tier_payload(findings, stale, unjustified, errors, baselined),
                indent=2,
            )
        )
    else:
        _print_report_entries(findings, stale, unjustified)
        for e in errors:
            print(f"parse error: {e}")
        print(
            f"graftlint: {len(findings)} finding"
            f"{'' if len(findings) == 1 else 's'}"
            + (f", {baselined} baselined" if baselined else "")
            + (f", {len(stale)} stale" if stale else "")
        )

    if findings or stale or unjustified:
        return 1
    if errors:
        return 2
    return 0


def _main_ir(args: argparse.Namespace, repo_root: str) -> int:
    """The `--ir` tier (analysis/ir.py): trace kernels, enforce
    kernel_budgets.json, apply graftlint.ir.baseline.json."""
    if args.paths or args.changed_only:
        # IR rules trace kernel entry points, not files — a path subset
        # has no meaning and must not read as a clean run
        print(
            "graftlint: --ir traces kernel entry points; it takes no "
            "paths and no --changed-only",
            file=sys.stderr,
        )
        return 2
    try:
        from karpenter_tpu.analysis import budgets as budgets_mod
        from karpenter_tpu.analysis import ir
    except ImportError as e:
        print(f"graftlint: IR tier unavailable ({e})", file=sys.stderr)
        return 2

    rule_ids = (
        {r.strip() for r in args.rules.split(",")} if args.rules else None
    )
    if rule_ids is not None:
        # a typo'd id would intersect IR_RULES to the empty set: the tier
        # would measure nothing and exit 0 — a silently disabled gate
        unknown = rule_ids - set(ir.IR_RULES)
        if unknown:
            print(
                "graftlint: unknown IR rule id(s): "
                + ", ".join(sorted(unknown))
                + " (see --list-rules)",
                file=sys.stderr,
            )
            return 2
    budgets_path = args.budgets or os.path.join(
        repo_root, budgets_mod.DEFAULT_MANIFEST
    )
    baseline_path = args.baseline or os.path.join(
        repo_root, IR_DEFAULT_BASELINE
    )
    if not _json_files_parse(budgets_path, baseline_path):
        return 2

    if args.write_budgets:
        if rule_ids is not None:
            # a partial run measures a slice; rewriting from it would
            # truncate every out-of-scope entry
            print(
                "graftlint: --write-budgets requires a full IR run "
                "(no --rules)",
                file=sys.stderr,
            )
            return 2
        measured, _, errors = ir.measure(None)
        if errors:
            for e in errors:
                print(f"trace error: {e}", file=sys.stderr)
            return 2
        existing = budgets_mod.BudgetManifest.load(budgets_path)
        # spmd_scope=False: carry the SPMD tier's `spmd:` entries over
        # verbatim — an IR rewrite must not truncate the sibling tier
        data = budgets_mod.BudgetManifest.render(
            measured, existing, spmd_scope=False
        )
        fresh = sum(
            1
            for e in data["entries"].values()
            if str(e["justification"]).startswith("TODO")
        )
        with open(budgets_path, "w", encoding="utf-8") as f:
            f.write(budgets_mod.BudgetManifest.dumps(data))
        print(
            f"graftlint: wrote {len(data['entries'])} budget entr"
            f"{'y' if len(data['entries']) == 1 else 'ies'} to "
            f"{budgets_path}"
            + (f" — justify the {fresh} new one(s)" if fresh else "")
        )
        return 0

    report = ir.run_ir_analysis(
        repo_root,
        budgets_path=budgets_path,
        baseline_path=baseline_path,
        rule_ids=rule_ids,
    )

    if args.write_baseline:
        if rule_ids is not None:
            # a partial run sees a slice of the findings; rewriting from
            # it would truncate every out-of-scope curated entry
            print(
                "graftlint: --write-baseline under --ir requires a full "
                "IR run (no --rules)",
                file=sys.stderr,
            )
            return 2
        if report["errors"]:
            # a partial measurement must never rewrite the baseline as if
            # the errored kernel's findings were resolved
            for e in report["errors"]:
                print(f"trace error: {e}", file=sys.stderr)
            return 2
        return _write_baseline_file(baseline_path, report["all_findings"])

    findings = report["findings"]
    # partial runs (--rules) leave baseline entries for out-of-scope
    # rules unmatched — expected, not staleness (the AST tier's subset
    # convention); only the full run polices baseline rot
    stale = [] if rule_ids is not None else report["stale"]
    unjustified = report["unjustified"]
    budget_unjustified = report["budget_unjustified"]
    errors = report["errors"]

    baselined = len(report["all_findings"]) - len(findings)
    if args.json:
        payload = _tier_payload(findings, stale, unjustified, errors, baselined)
        payload["unjustified_budgets"] = budget_unjustified
        payload["improvements"] = report["improvements"]
        payload["measured"] = report["measured"]
        print(json.dumps(payload, indent=2))
    else:
        _print_report_entries(findings, stale, unjustified)
        for name in budget_unjustified:
            print(
                f"unjustified budget entry: {name}: add a one-line "
                "justification in kernel_budgets.json"
            )
        for e in errors:
            print(f"trace error: {e}")
        print(
            f"graftlint --ir: {len(findings)} finding"
            f"{'' if len(findings) == 1 else 's'}, "
            f"{len(report['measured'])} entry points measured"
            + (f", {baselined} baselined" if baselined else "")
            + (
                f", {len(report['improvements'])} budget(s) with slack"
                if report["improvements"]
                else ""
            )
        )

    if errors:
        # a kernel that no longer traces is a broken gate, not a lint
        # verdict — exit 2 even when comparison findings also exist
        return 2
    if findings or stale or unjustified or budget_unjustified:
        return 1
    return 0


def _main_spmd(args: argparse.Namespace, repo_root: str) -> int:
    """The `--spmd` tier (analysis/spmd.py): compile the solver
    programs, enforce the `spmd:` half of kernel_budgets.json, run the
    launch-lock rule, apply graftlint.spmd.baseline.json."""
    if args.paths or args.changed_only:
        # SPMD rules compile kernel entry points (plus one fixed-scope
        # AST rule) — a path subset has no meaning and must not read as
        # a clean run
        print(
            "graftlint: --spmd compiles kernel entry points; it takes "
            "no paths and no --changed-only",
            file=sys.stderr,
        )
        return 2
    try:
        from karpenter_tpu.analysis import budgets as budgets_mod
        from karpenter_tpu.analysis import spmd
    except ImportError as e:
        print(f"graftlint: SPMD tier unavailable ({e})", file=sys.stderr)
        return 2
    # the 8-virtual-device mesh env must be pinned before the first jax
    # import or the lane-sharded fleet program cannot be compiled
    spmd.ensure_host_devices()

    rule_ids = (
        {r.strip() for r in args.rules.split(",")} if args.rules else None
    )
    if rule_ids is not None:
        # a typo'd id would intersect SPMD_RULES to the empty set: the
        # tier would compile nothing and exit 0 — a silently disabled gate
        unknown = rule_ids - set(spmd.SPMD_RULES)
        if unknown:
            print(
                "graftlint: unknown SPMD rule id(s): "
                + ", ".join(sorted(unknown))
                + " (see --list-rules)",
                file=sys.stderr,
            )
            return 2
    budgets_path = args.budgets or os.path.join(
        repo_root, budgets_mod.DEFAULT_MANIFEST
    )
    baseline_path = args.baseline or os.path.join(
        repo_root, SPMD_DEFAULT_BASELINE
    )
    if not _json_files_parse(budgets_path, baseline_path):
        return 2

    if args.write_budgets:
        if rule_ids is not None:
            # a partial run measures a slice; rewriting from it would
            # truncate every out-of-scope entry
            print(
                "graftlint: --write-budgets requires a full SPMD run "
                "(no --rules)",
                file=sys.stderr,
            )
            return 2
        measured, _, errors, _ = spmd.measure(None)
        if errors:
            for e in errors:
                print(f"compile error: {e}", file=sys.stderr)
            return 2
        existing = budgets_mod.BudgetManifest.load(budgets_path)
        # spmd_scope=True: carry the IR tier's entries over verbatim —
        # an SPMD rewrite must not truncate the sibling tier
        data = budgets_mod.BudgetManifest.render(
            measured, existing, spmd_scope=True
        )
        fresh = sum(
            1
            for e in data["entries"].values()
            if str(e["justification"]).startswith("TODO")
        )
        with open(budgets_path, "w", encoding="utf-8") as f:
            f.write(budgets_mod.BudgetManifest.dumps(data))
        print(
            f"graftlint: wrote {len(data['entries'])} budget entr"
            f"{'y' if len(data['entries']) == 1 else 'ies'} to "
            f"{budgets_path}"
            + (f" — justify the {fresh} new one(s)" if fresh else "")
        )
        return 0

    report = spmd.run_spmd_analysis(
        repo_root,
        budgets_path=budgets_path,
        baseline_path=baseline_path,
        rule_ids=rule_ids,
    )

    if args.write_baseline:
        if rule_ids is not None:
            # a partial run sees a slice of the findings; rewriting from
            # it would truncate every out-of-scope curated entry
            print(
                "graftlint: --write-baseline under --spmd requires a "
                "full SPMD run (no --rules)",
                file=sys.stderr,
            )
            return 2
        if report["errors"]:
            # a partial measurement must never rewrite the baseline as if
            # the errored program's findings were resolved
            for e in report["errors"]:
                print(f"compile error: {e}", file=sys.stderr)
            return 2
        return _write_baseline_file(baseline_path, report["all_findings"])

    findings = report["findings"]
    # partial runs (--rules) leave baseline entries for out-of-scope
    # rules unmatched — expected, not staleness (the AST tier's subset
    # convention); only the full run polices baseline rot
    stale = [] if rule_ids is not None else report["stale"]
    unjustified = report["unjustified"]
    budget_unjustified = report["budget_unjustified"]
    errors = report["errors"]

    baselined = len(report["all_findings"]) - len(findings)
    if args.json:
        payload = _tier_payload(findings, stale, unjustified, errors, baselined)
        payload["unjustified_budgets"] = budget_unjustified
        payload["improvements"] = report["improvements"]
        payload["measured"] = report["measured"]
        print(json.dumps(payload, indent=2))
    else:
        _print_report_entries(findings, stale, unjustified)
        for name in budget_unjustified:
            print(
                f"unjustified budget entry: {name}: add a one-line "
                "justification in kernel_budgets.json"
            )
        for e in errors:
            print(f"compile error: {e}")
        print(
            f"graftlint --spmd: {len(findings)} finding"
            f"{'' if len(findings) == 1 else 's'}, "
            f"{len(report['measured'])} program(s) compiled"
            + (f", {baselined} baselined" if baselined else "")
            + (
                f", {len(report['improvements'])} budget(s) with slack"
                if report["improvements"]
                else ""
            )
        )

    if errors:
        # a program that no longer compiles is a broken gate, not a lint
        # verdict — exit 2 even when comparison findings also exist
        return 2
    if findings or stale or unjustified or budget_unjustified:
        return 1
    return 0


def _main_race(args: argparse.Namespace, repo_root: str) -> int:
    """The `--race` tier's static half (analysis/locks.py): whole-program
    lock analysis under graftlint.race.baseline.json."""
    if args.paths or args.changed_only:
        # lock-order inversions are a property of the PROGRAM: thread 1's
        # half may live in an unchanged file — a path subset would hide
        # exactly the cross-module bugs the tier exists for
        print(
            "graftlint: --race is whole-program; it takes no paths and "
            "no --changed-only",
            file=sys.stderr,
        )
        return 2
    if args.budgets or args.reference_root != _DEFAULT_REFERENCE_ROOT:
        # an explicitly passed option that does nothing must be refused
        # (same principle --all enforces): a green run that never read
        # the manifest the operator pointed at is a lie
        print(
            "graftlint: --budgets/--reference-root are not used by "
            "--race (budgets belong to --ir; citations to the AST tier)",
            file=sys.stderr,
        )
        return 2
    from karpenter_tpu.analysis import locks

    rule_ids = (
        {r.strip() for r in args.rules.split(",")} if args.rules else None
    )
    if rule_ids is not None:
        # a typo'd id must not read as "nothing to check, clean"
        unknown = rule_ids - set(locks.RACE_RULES)
        if unknown:
            print(
                "graftlint: unknown race rule id(s): "
                + ", ".join(sorted(unknown))
                + " (see --list-rules)",
                file=sys.stderr,
            )
            return 2
    baseline_path = args.baseline or os.path.join(
        repo_root, locks.DEFAULT_BASELINE
    )
    if not _json_files_parse(baseline_path):
        return 2

    report = locks.run_race_analysis(
        repo_root, baseline_path=baseline_path, rule_ids=rule_ids
    )

    if args.write_baseline:
        if rule_ids is not None:
            # a partial run sees a slice of the findings; rewriting from
            # it would truncate every out-of-scope curated entry
            print(
                "graftlint: --write-baseline under --race requires a "
                "full run (no --rules)",
                file=sys.stderr,
            )
            return 2
        return _write_baseline_file(baseline_path, report["all_findings"])

    findings = report["findings"]
    # partial runs (--rules) leave baseline entries for out-of-scope
    # rules unmatched — expected, not staleness (the AST tier's subset
    # convention); only the full run polices baseline rot
    stale = [] if rule_ids is not None else report["stale"]
    unjustified = report["unjustified"]
    errors = report["errors"]

    baselined = report["total"] - len(findings)
    if args.json:
        print(
            json.dumps(
                _tier_payload(findings, stale, unjustified, errors, baselined),
                indent=2,
            )
        )
    else:
        _print_report_entries(findings, stale, unjustified)
        for e in errors:
            print(f"parse error: {e}")
        print(
            f"graftlint --race: {len(findings)} finding"
            f"{'' if len(findings) == 1 else 's'}"
            + (f", {baselined} baselined" if baselined else "")
            + (f", {len(stale)} stale" if stale else "")
        )

    if errors:
        # whole-program analysis over a partial program is a broken
        # gate, not a lint verdict: the unparsable file could hold the
        # other half of an inversion — exit 2 even when findings also
        # exist (the IR tier's trace-error convention, not the AST
        # tier's, because only these two tiers claim completeness)
        return 2
    if findings or stale or unjustified:
        return 1
    return 0


def _main_proto(args: argparse.Namespace, repo_root: str) -> int:
    """The `--proto` tier (analysis/proto.py): model-check the wire/
    epoch/breaker protocol under channel faults and refinement-check
    live traces of the real code, under graftlint.proto.baseline.json."""
    if args.paths or args.changed_only:
        # the protocol is a property of the composed client/server/
        # breaker machines, not of files — a path subset has no meaning
        # and must not read as a clean run
        print(
            "graftlint: --proto model-checks the wire protocol; it "
            "takes no paths and no --changed-only",
            file=sys.stderr,
        )
        return 2
    if args.rules or args.budgets or args.reference_root != _DEFAULT_REFERENCE_ROOT:
        # an explicitly passed option that does nothing must be refused:
        # the properties are checked in ONE exploration per scenario —
        # there is no per-rule subset to run (and no budget manifest)
        print(
            "graftlint: --rules/--budgets/--reference-root are not used "
            "by --proto (every protocol property rides one exploration)",
            file=sys.stderr,
        )
        return 2
    try:
        from karpenter_tpu.analysis import proto as proto_mod
    except ImportError as e:
        print(f"graftlint: protocol tier unavailable ({e})", file=sys.stderr)
        return 2

    baseline_path = args.baseline or os.path.join(
        repo_root, PROTO_DEFAULT_BASELINE
    )
    if not _json_files_parse(baseline_path):
        return 2

    report = proto_mod.run_proto_analysis(
        repo_root, baseline_path=baseline_path
    )

    if args.write_baseline:
        if report["errors"]:
            # a crashed live scenario means the conformance half never
            # ran; rewriting from the partial result would bless it
            for e in report["errors"]:
                print(f"scenario error: {e}", file=sys.stderr)
            return 2
        return _write_baseline_file(baseline_path, report["all_findings"])

    findings = report["findings"]
    stale = report["stale"]
    unjustified = report["unjustified"]
    errors = report["errors"]

    baselined = len(report["all_findings"]) - len(findings)
    if args.json:
        payload = _tier_payload(findings, stale, unjustified, errors, baselined)
        payload["scenarios"] = report["scenarios"]
        payload["properties"] = report["properties"]
        payload["conformance"] = report["conformance"]
        print(json.dumps(payload, indent=2))
    else:
        _print_report_entries(findings, stale, unjustified)
        for e in errors:
            print(f"scenario error: {e}")
        states = sum(s["states"] for s in report["scenarios"].values())
        truncated = [
            n for n, s in report["scenarios"].items() if s["truncated"]
        ]
        print(
            f"graftlint --proto: {len(findings)} finding"
            f"{'' if len(findings) == 1 else 's'}, "
            f"{states} states over {len(report['scenarios'])} scenario(s), "
            f"{len(report['conformance'])} live trace(s)"
            + (f", {baselined} baselined" if baselined else "")
            + (
                f", truncated: {', '.join(truncated)}"
                if truncated
                else ""
            )
        )

    if errors:
        # a live scenario that no longer runs is a broken gate, not a
        # lint verdict — exit 2 even when model findings also exist
        return 2
    if findings or stale or unjustified:
        return 1
    return 0


def _main_all(args: argparse.Namespace, repo_root: str) -> int:
    """`--all`: AST + race + IR + SPMD + proto in one invocation,
    merged `--json` output with per-tier wall-clock seconds, worst-case
    exit code (2 > 1 > 0). Read-only by design — the write modes stay
    per-tier so a rewrite is always an explicit, single-tier act.
    `--jobs N` runs the tiers in up to N worker threads; the payload
    order and each tier's call style are identical either way."""
    if (
        args.paths
        or args.changed_only
        or args.rules
        or args.write_baseline
        or args.write_budgets
        or args.baseline
        or args.budgets
    ):
        print(
            "graftlint: --all runs every tier full-tree with each tier's "
            "default baseline and budget manifest; it takes no paths/"
            "--changed-only/--rules/--baseline/--budgets/--write-* (use "
            "the per-tier modes for those)",
            file=sys.stderr,
        )
        return 2

    # the same pre-flight every single-tier mode runs: a trailing-comma
    # typo in a hand-edited gate file must be the documented exit-2
    # diagnostic, not a JSONDecodeError traceback out of the first tier
    # that loads it
    from karpenter_tpu.analysis import locks

    gate_files = [
        os.path.join(repo_root, "graftlint.baseline.json"),
        os.path.join(repo_root, locks.DEFAULT_BASELINE),
        os.path.join(repo_root, IR_DEFAULT_BASELINE),
        os.path.join(repo_root, SPMD_DEFAULT_BASELINE),
        os.path.join(repo_root, PROTO_DEFAULT_BASELINE),
    ]
    try:
        from karpenter_tpu.analysis import budgets as _budgets_preflight

        gate_files.append(
            os.path.join(repo_root, _budgets_preflight.DEFAULT_MANIFEST)
        )
    except ImportError:
        pass  # IR tier will report itself unavailable below
    if not _json_files_parse(*gate_files):
        return 2

    # the SPMD tier needs the 8-virtual-device mesh pinned BEFORE the
    # first jax import — and the IR tier two blocks down is what
    # performs that first import, so the pin happens here
    try:
        from karpenter_tpu.analysis import spmd as spmd_mod

        spmd_mod.ensure_host_devices()
    except ImportError:
        spmd_mod = None  # the tier reports itself unavailable below

    def _tier_code(report: dict, extra_unjustified: int = 0) -> int:
        if (
            report["findings"]
            or report["stale"]
            or report["unjustified"]
            or extra_unjustified
        ):
            return 1
        if report["errors"]:
            return 2
        return 0

    # each tier is one thunk returning its finished payload (exit_code
    # included); the driver below runs them sequentially or in a worker
    # pool (--jobs) with IDENTICAL call styles, so the per-tier report
    # shapes — and the tests that stub run_analysis & friends — cannot
    # drift between the two paths. Per-tier `seconds` stays wall-clock
    # inside the thunk: under --jobs it reports that tier's own runtime,
    # not the pool's.

    def _run_ast() -> dict:
        ast_report = run_analysis(
            repo_root, reference_root=args.reference_root
        )
        out = _tier_payload(
            ast_report["findings"],
            ast_report["stale"],
            ast_report["unjustified"],
            ast_report["errors"],
            ast_report["total"] - len(ast_report["findings"]),
        )
        out["exit_code"] = _tier_code(ast_report)
        return out

    def _run_race() -> dict:
        race_report = locks.run_race_analysis(repo_root)
        out = _tier_payload(
            race_report["findings"],
            race_report["stale"],
            race_report["unjustified"],
            race_report["errors"],
            race_report["total"] - len(race_report["findings"]),
        )
        # parse errors make the whole-program claim false: broken gate
        # (2), mirroring the IR tier's trace-error convention
        out["exit_code"] = (
            2 if race_report["errors"] else _tier_code(race_report)
        )
        return out

    def _run_ir() -> dict:
        try:
            from karpenter_tpu.analysis import budgets as budgets_mod
            from karpenter_tpu.analysis import ir
        except ImportError as e:
            return {"unavailable": str(e), "exit_code": 2}
        ir_report = ir.run_ir_analysis(
            repo_root,
            budgets_path=os.path.join(repo_root, budgets_mod.DEFAULT_MANIFEST),
            baseline_path=os.path.join(repo_root, IR_DEFAULT_BASELINE),
        )
        out = _tier_payload(
            ir_report["findings"],
            ir_report["stale"],
            ir_report["unjustified"],
            ir_report["errors"],
            len(ir_report["all_findings"]) - len(ir_report["findings"]),
        )
        out["unjustified_budgets"] = ir_report["budget_unjustified"]
        out["improvements"] = ir_report["improvements"]
        out["measured"] = ir_report["measured"]
        # mirror _main_ir: a kernel that no longer traces is a broken
        # gate (2), even when comparison findings also exist
        out["exit_code"] = (
            2
            if ir_report["errors"]
            else _tier_code(
                ir_report,
                extra_unjustified=len(ir_report["budget_unjustified"]),
            )
        )
        return out

    def _run_spmd() -> dict:
        if spmd_mod is None:
            return {
                "unavailable": "karpenter_tpu.analysis.spmd failed to import",
                "exit_code": 2,
            }
        spmd_report = spmd_mod.run_spmd_analysis(
            repo_root,
            budgets_path=os.path.join(
                repo_root, _budgets_preflight.DEFAULT_MANIFEST
            ),
            baseline_path=os.path.join(repo_root, SPMD_DEFAULT_BASELINE),
        )
        out = _tier_payload(
            spmd_report["findings"],
            spmd_report["stale"],
            spmd_report["unjustified"],
            spmd_report["errors"],
            len(spmd_report["all_findings"]) - len(spmd_report["findings"]),
        )
        out["unjustified_budgets"] = spmd_report["budget_unjustified"]
        out["improvements"] = spmd_report["improvements"]
        out["measured"] = spmd_report["measured"]
        # mirror _main_spmd: a program that no longer compiles is a
        # broken gate (2), even when comparison findings also exist
        out["exit_code"] = (
            2
            if spmd_report["errors"]
            else _tier_code(
                spmd_report,
                extra_unjustified=len(spmd_report["budget_unjustified"]),
            )
        )
        return out

    def _run_proto() -> dict:
        try:
            from karpenter_tpu.analysis import proto as proto_mod
        except ImportError as e:
            return {"unavailable": str(e), "exit_code": 2}
        proto_report = proto_mod.run_proto_analysis(
            repo_root,
            baseline_path=os.path.join(repo_root, PROTO_DEFAULT_BASELINE),
        )
        out = _tier_payload(
            proto_report["findings"],
            proto_report["stale"],
            proto_report["unjustified"],
            proto_report["errors"],
            len(proto_report["all_findings"]) - len(proto_report["findings"]),
        )
        out["scenarios"] = proto_report["scenarios"]
        out["properties"] = proto_report["properties"]
        out["conformance"] = proto_report["conformance"]
        # mirror _main_proto: a live scenario that no longer runs is a
        # broken gate (2), even when model findings also exist
        out["exit_code"] = (
            2 if proto_report["errors"] else _tier_code(proto_report)
        )
        return out

    tiers = (
        ("ast", _run_ast),
        ("race", _run_race),
        ("ir", _run_ir),
        ("spmd", _run_spmd),
        ("proto", _run_proto),
    )

    def _timed(fn):
        t0 = time.monotonic()
        try:
            out = fn()
        except Exception as e:  # a crashed tier is a broken gate, not a pass
            out = {"unavailable": f"{type(e).__name__}: {e}", "exit_code": 2}
        out["seconds"] = round(time.monotonic() - t0, 3)
        return out

    payload: dict = {}
    if args.jobs > 1:
        from concurrent.futures import ThreadPoolExecutor

        # The IR and SPMD tiers both trace/compile JAX programs in THIS
        # process, and the IR tier's retrace accounting reads the
        # process-global trace counter — another tier compiling inside
        # its measurement window manufactures phantom ir-retrace
        # regressions. The two JAX tiers therefore share one worker
        # (serialized against each other, in tier order); the
        # stdlib-only tiers (ast, race, proto) parallelize freely.
        jax_tiers = ("ir", "spmd")
        fns = dict(tiers)

        def _run_jax_chain() -> dict:
            return {name: _timed(fns[name]) for name in jax_tiers}

        with ThreadPoolExecutor(max_workers=args.jobs) as pool:
            chain = pool.submit(_run_jax_chain)
            futures = [
                (name, pool.submit(_timed, fn))
                for name, fn in tiers
                if name not in jax_tiers
            ]
            for name, fut in futures:
                payload[name] = fut.result()
            payload.update(chain.result())
        payload = {name: payload[name] for name, _ in tiers}
    else:
        for name, fn in tiers:
            payload[name] = _timed(fn)

    worst = max(payload[name]["exit_code"] for name, _ in tiers)
    if args.json:
        payload["exit_code"] = worst
        print(json.dumps(payload, indent=2))
    else:
        for tier in ("ast", "race", "ir", "spmd", "proto"):
            rep = payload[tier]
            if "unavailable" in rep:
                print(f"[{tier}] unavailable: {rep['unavailable']}")
                continue
            for f in rep["findings"]:
                print(f"[{tier}] {f['path']}:{f['line']}: [{f['rule']}] {f['message']}")
            _print_baseline_problems(
                rep["stale_baseline"],
                rep["unjustified_baseline"],
                prefix=f"[{tier}] ",
            )
            for name in rep.get("unjustified_budgets", []):
                print(
                    f"[{tier}] unjustified budget entry: {name}: add a "
                    "one-line justification in kernel_budgets.json"
                )
            for e in rep["errors"]:
                print(f"[{tier}] error: {e}")
            problems = (
                len(rep["findings"])
                + len(rep["stale_baseline"])
                + len(rep["unjustified_baseline"])
                + len(rep.get("unjustified_budgets", []))
            )
            print(
                f"graftlint --all [{tier}]: {len(rep['findings'])} finding"
                f"{'' if len(rep['findings']) == 1 else 's'}"
                + (f", {rep['baselined']} baselined" if rep["baselined"] else "")
                + ("" if problems == len(rep["findings"]) else
                   f", {problems - len(rep['findings'])} baseline/budget problem(s)")
                + f" ({rep['seconds']}s, exit {rep['exit_code']})"
            )
        print(f"graftlint --all: worst exit {worst}")
    return worst


if __name__ == "__main__":
    sys.exit(main())
