"""graftlint CLI: `python -m karpenter_tpu.analysis` (also installed as
the `graftlint` console script).

Two tiers share this entry point:

- the AST tier (default): stdlib-`ast` source analysis, JAX-free;
- the IR tier (`--ir`): traces the real solver kernels and walks the
  jaxprs (analysis/ir.py) — imports JAX, needs JAX_PLATFORMS=cpu or a
  device, and enforces kernel_budgets.json (`--write-budgets` to
  re-baseline after an intentional kernel change).

Exit codes: 0 clean (baseline-covered findings allowed), 1 findings or
stale/unjustified baseline or budget entries, 2 usage/parse/trace errors.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from karpenter_tpu.analysis.engine import (
    Baseline,
    all_rules,
    canonical_json,
    run_analysis,
)


def _detect_repo_root() -> str:
    # the package lives at <root>/karpenter_tpu/analysis
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def _json_files_parse(*paths: str) -> bool:
    """Pre-flight the hand-editable JSON inputs (baselines, budgets): a
    trailing-comma typo must surface as the documented exit-2 parse
    diagnostic naming the file, not a raw JSONDecodeError traceback."""
    ok = True
    for p in paths:
        if not p or not os.path.exists(p):
            continue
        try:
            with open(p, encoding="utf-8") as f:
                json.load(f)
        except (json.JSONDecodeError, OSError) as e:
            print(f"graftlint: cannot parse {p}: {e}", file=sys.stderr)
            ok = False
    return ok


def _write_baseline_file(baseline_path: str, findings) -> int:
    """Shared --write-baseline tail for both tiers: regeneration keeps
    hand-written justifications (entries that still match a finding carry
    their text over; only genuinely new findings get the TODO
    placeholder)."""
    existing = Baseline.load(baseline_path)
    data = Baseline.render_entries(findings)
    fresh = existing.merge_justifications(data)
    with open(baseline_path, "w", encoding="utf-8") as f:
        f.write(canonical_json(data))
    print(
        f"graftlint: wrote {len(data['entries'])} entr"
        f"{'y' if len(data['entries']) == 1 else 'ies'} to "
        f"{baseline_path}"
        + (f" — justify the {fresh} new one(s)" if fresh else "")
    )
    return 0


def _changed_files(repo_root: str):
    """Modified + untracked .py files (git), for pre-commit `--changed-only`.
    Returns None when git itself fails — the caller must surface that as an
    error, never as 'nothing to lint'."""
    out: set[str] = set()
    for args in (
        ["git", "-C", repo_root, "diff", "--name-only", "HEAD"],
        ["git", "-C", repo_root, "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            res = subprocess.run(
                args, capture_output=True, text=True, timeout=10
            )
        except (OSError, subprocess.TimeoutExpired) as e:
            print(f"graftlint: git failed ({e})", file=sys.stderr)
            return None
        if res.returncode != 0:
            print(
                f"graftlint: git failed: {res.stderr.strip()}", file=sys.stderr
            )
            return None
        out.update(line.strip() for line in res.stdout.splitlines() if line.strip())
    return sorted(
        os.path.join(repo_root, p)
        for p in out
        if p.endswith(".py") and os.path.exists(os.path.join(repo_root, p))
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftlint",
        description="AST-based invariant analyzer (docs/static-analysis.md)",
    )
    parser.add_argument(
        "paths", nargs="*", help="files/dirs to lint (default: package + tests)"
    )
    parser.add_argument("--root", default=None, help="repo root (default: auto)")
    parser.add_argument(
        "--baseline",
        default=None,
        help="baseline file (default: <root>/graftlint.baseline.json)",
    )
    parser.add_argument(
        "--reference-root",
        default="/root/reference",
        help="reference checkout for .go citation resolution",
    )
    parser.add_argument(
        "--rules", default=None, help="comma-separated rule ids to run"
    )
    parser.add_argument("--json", action="store_true", help="JSON output")
    parser.add_argument(
        "--changed-only",
        action="store_true",
        help="lint only git-modified/untracked files (pre-commit fast mode)",
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="write current findings to the baseline file (justify each!)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rule ids and exit"
    )
    parser.add_argument(
        "--ir",
        action="store_true",
        help="run the IR tier: trace the solver kernels and walk the "
        "jaxprs (imports JAX; see docs/static-analysis.md)",
    )
    parser.add_argument(
        "--budgets",
        default=None,
        help="IR budget manifest (default: <root>/kernel_budgets.json)",
    )
    parser.add_argument(
        "--write-budgets",
        action="store_true",
        help="re-baseline kernel_budgets.json from current measurements "
        "(implies --ir; justify each changed entry!)",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for r in all_rules():
            print(f"{r.id:20s} {r.summary}")
        from karpenter_tpu.analysis.ir import IR_RULES

        for rid, summary in IR_RULES.items():
            print(f"{rid:20s} [ir] {summary}")
        return 0

    repo_root = os.path.abspath(args.root or _detect_repo_root())
    if args.write_budgets:
        args.ir = True
    if args.ir:
        return _main_ir(args, repo_root)
    paths = [os.path.abspath(p) for p in args.paths] or None
    if args.changed_only:
        paths = _changed_files(repo_root)
        if paths is None:
            return 2  # git failure must not read as a clean lint
        if not paths:
            print("graftlint: no changed python files")
            return 0
    rule_ids = (
        {r.strip() for r in args.rules.split(",")} if args.rules else None
    )
    if rule_ids is not None:
        # a typo'd rule id must not read as "nothing to check, clean"
        unknown = rule_ids - {r.id for r in all_rules()}
        if unknown:
            print(
                "graftlint: unknown rule id(s): "
                + ", ".join(sorted(unknown))
                + " (see --list-rules; ir-* rules need --ir)",
                file=sys.stderr,
            )
            return 2
    baseline_path = args.baseline or os.path.join(
        repo_root, "graftlint.baseline.json"
    )
    if not _json_files_parse(baseline_path):
        return 2

    report = run_analysis(
        repo_root,
        paths=paths,
        baseline_path=baseline_path,
        reference_root=args.reference_root,
        rule_ids=rule_ids,
    )

    if args.write_baseline:
        if paths is not None or rule_ids is not None:
            # a subset run sees only a slice of the findings; rewriting
            # from it would truncate every out-of-scope curated entry
            print(
                "graftlint: --write-baseline requires a full-tree, "
                "all-rules run (no explicit paths / --changed-only / "
                "--rules)",
                file=sys.stderr,
            )
            return 2
        return _write_baseline_file(baseline_path, report["all_findings"])

    findings = report["findings"]
    # subset runs (--changed-only, explicit paths, --rules) leave baseline
    # entries for out-of-scope files or rules unmatched — that is
    # expected, not staleness; only the default full run polices rot
    stale = (
        []
        if (paths is not None or rule_ids is not None)
        else report["stale"]
    )
    unjustified = report["unjustified"]
    errors = report["errors"]

    if args.json:
        print(
            json.dumps(
                {
                    "findings": [f.to_dict() for f in findings],
                    "stale_baseline": stale,
                    "unjustified_baseline": unjustified,
                    "errors": errors,
                    "baselined": report["total"] - len(findings),
                },
                indent=2,
            )
        )
    else:
        for f in findings:
            print(f.render())
        for e in stale:
            print(
                f"stale baseline entry: [{e.get('rule')}] {e.get('path')}: "
                f"{e.get('text')!r} no longer matches — remove it"
            )
        for e in unjustified:
            print(
                f"unjustified baseline entry: [{e.get('rule')}] "
                f"{e.get('path')}: add a one-line justification"
            )
        for e in errors:
            print(f"parse error: {e}")
        baselined = report["total"] - len(findings)
        print(
            f"graftlint: {len(findings)} finding"
            f"{'' if len(findings) == 1 else 's'}"
            + (f", {baselined} baselined" if baselined else "")
            + (f", {len(stale)} stale" if stale else "")
        )

    if findings or stale or unjustified:
        return 1
    if errors:
        return 2
    return 0


def _main_ir(args: argparse.Namespace, repo_root: str) -> int:
    """The `--ir` tier (analysis/ir.py): trace kernels, enforce
    kernel_budgets.json, apply graftlint.ir.baseline.json."""
    if args.paths or args.changed_only:
        # IR rules trace kernel entry points, not files — a path subset
        # has no meaning and must not read as a clean run
        print(
            "graftlint: --ir traces kernel entry points; it takes no "
            "paths and no --changed-only",
            file=sys.stderr,
        )
        return 2
    try:
        from karpenter_tpu.analysis import budgets as budgets_mod
        from karpenter_tpu.analysis import ir
    except ImportError as e:
        print(f"graftlint: IR tier unavailable ({e})", file=sys.stderr)
        return 2

    rule_ids = (
        {r.strip() for r in args.rules.split(",")} if args.rules else None
    )
    if rule_ids is not None:
        # a typo'd id would intersect IR_RULES to the empty set: the tier
        # would measure nothing and exit 0 — a silently disabled gate
        unknown = rule_ids - set(ir.IR_RULES)
        if unknown:
            print(
                "graftlint: unknown IR rule id(s): "
                + ", ".join(sorted(unknown))
                + " (see --list-rules)",
                file=sys.stderr,
            )
            return 2
    budgets_path = args.budgets or os.path.join(
        repo_root, budgets_mod.DEFAULT_MANIFEST
    )
    baseline_path = args.baseline or os.path.join(
        repo_root, "graftlint.ir.baseline.json"
    )
    if not _json_files_parse(budgets_path, baseline_path):
        return 2

    if args.write_budgets:
        if rule_ids is not None:
            # a partial run measures a slice; rewriting from it would
            # truncate every out-of-scope entry
            print(
                "graftlint: --write-budgets requires a full IR run "
                "(no --rules)",
                file=sys.stderr,
            )
            return 2
        measured, _, errors = ir.measure(None)
        if errors:
            for e in errors:
                print(f"trace error: {e}", file=sys.stderr)
            return 2
        existing = budgets_mod.BudgetManifest.load(budgets_path)
        data = budgets_mod.BudgetManifest.render(measured, existing)
        fresh = sum(
            1
            for e in data["entries"].values()
            if str(e["justification"]).startswith("TODO")
        )
        with open(budgets_path, "w", encoding="utf-8") as f:
            f.write(budgets_mod.BudgetManifest.dumps(data))
        print(
            f"graftlint: wrote {len(data['entries'])} budget entr"
            f"{'y' if len(data['entries']) == 1 else 'ies'} to "
            f"{budgets_path}"
            + (f" — justify the {fresh} new one(s)" if fresh else "")
        )
        return 0

    report = ir.run_ir_analysis(
        repo_root,
        budgets_path=budgets_path,
        baseline_path=baseline_path,
        rule_ids=rule_ids,
    )

    if args.write_baseline:
        if rule_ids is not None:
            # a partial run sees a slice of the findings; rewriting from
            # it would truncate every out-of-scope curated entry
            print(
                "graftlint: --write-baseline under --ir requires a full "
                "IR run (no --rules)",
                file=sys.stderr,
            )
            return 2
        if report["errors"]:
            # a partial measurement must never rewrite the baseline as if
            # the errored kernel's findings were resolved
            for e in report["errors"]:
                print(f"trace error: {e}", file=sys.stderr)
            return 2
        return _write_baseline_file(baseline_path, report["all_findings"])

    findings = report["findings"]
    # partial runs (--rules) leave baseline entries for out-of-scope
    # rules unmatched — expected, not staleness (the AST tier's subset
    # convention); only the full run polices baseline rot
    stale = [] if rule_ids is not None else report["stale"]
    unjustified = report["unjustified"]
    budget_unjustified = report["budget_unjustified"]
    errors = report["errors"]

    if args.json:
        print(
            json.dumps(
                {
                    "findings": [f.to_dict() for f in findings],
                    "stale_baseline": stale,
                    "unjustified_baseline": unjustified,
                    "unjustified_budgets": budget_unjustified,
                    "improvements": report["improvements"],
                    "errors": errors,
                    "measured": report["measured"],
                    "baselined": len(report["all_findings"])
                    - len(findings),
                },
                indent=2,
            )
        )
    else:
        for f in findings:
            print(f.render())
        for e in stale:
            print(
                f"stale baseline entry: [{e.get('rule')}] {e.get('path')}: "
                f"{e.get('text')!r} no longer matches — remove it"
            )
        for e in unjustified:
            print(
                f"unjustified baseline entry: [{e.get('rule')}] "
                f"{e.get('path')}: add a one-line justification"
            )
        for name in budget_unjustified:
            print(
                f"unjustified budget entry: {name}: add a one-line "
                "justification in kernel_budgets.json"
            )
        for e in errors:
            print(f"trace error: {e}")
        baselined = len(report["all_findings"]) - len(findings)
        print(
            f"graftlint --ir: {len(findings)} finding"
            f"{'' if len(findings) == 1 else 's'}, "
            f"{len(report['measured'])} entry points measured"
            + (f", {baselined} baselined" if baselined else "")
            + (
                f", {len(report['improvements'])} budget(s) with slack"
                if report["improvements"]
                else ""
            )
        )

    if errors:
        # a kernel that no longer traces is a broken gate, not a lint
        # verdict — exit 2 even when comparison findings also exist
        return 2
    if findings or stale or unjustified or budget_unjustified:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
