"""graftlint CLI: `python -m karpenter_tpu.analysis` (also installed as
the `graftlint` console script).

Exit codes: 0 clean (baseline-covered findings allowed), 1 findings or
stale/unjustified baseline entries, 2 usage or parse errors.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from karpenter_tpu.analysis.engine import (
    Baseline,
    all_rules,
    run_analysis,
)


def _detect_repo_root() -> str:
    # the package lives at <root>/karpenter_tpu/analysis
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def _changed_files(repo_root: str):
    """Modified + untracked .py files (git), for pre-commit `--changed-only`.
    Returns None when git itself fails — the caller must surface that as an
    error, never as 'nothing to lint'."""
    out: set[str] = set()
    for args in (
        ["git", "-C", repo_root, "diff", "--name-only", "HEAD"],
        ["git", "-C", repo_root, "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            res = subprocess.run(
                args, capture_output=True, text=True, timeout=10
            )
        except (OSError, subprocess.TimeoutExpired) as e:
            print(f"graftlint: git failed ({e})", file=sys.stderr)
            return None
        if res.returncode != 0:
            print(
                f"graftlint: git failed: {res.stderr.strip()}", file=sys.stderr
            )
            return None
        out.update(line.strip() for line in res.stdout.splitlines() if line.strip())
    return sorted(
        os.path.join(repo_root, p)
        for p in out
        if p.endswith(".py") and os.path.exists(os.path.join(repo_root, p))
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftlint",
        description="AST-based invariant analyzer (docs/static-analysis.md)",
    )
    parser.add_argument(
        "paths", nargs="*", help="files/dirs to lint (default: package + tests)"
    )
    parser.add_argument("--root", default=None, help="repo root (default: auto)")
    parser.add_argument(
        "--baseline",
        default=None,
        help="baseline file (default: <root>/graftlint.baseline.json)",
    )
    parser.add_argument(
        "--reference-root",
        default="/root/reference",
        help="reference checkout for .go citation resolution",
    )
    parser.add_argument(
        "--rules", default=None, help="comma-separated rule ids to run"
    )
    parser.add_argument("--json", action="store_true", help="JSON output")
    parser.add_argument(
        "--changed-only",
        action="store_true",
        help="lint only git-modified/untracked files (pre-commit fast mode)",
    )
    parser.add_argument(
        "--write-baseline",
        action="store_true",
        help="write current findings to the baseline file (justify each!)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="list rule ids and exit"
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for r in all_rules():
            print(f"{r.id:20s} {r.summary}")
        return 0

    repo_root = os.path.abspath(args.root or _detect_repo_root())
    paths = [os.path.abspath(p) for p in args.paths] or None
    if args.changed_only:
        paths = _changed_files(repo_root)
        if paths is None:
            return 2  # git failure must not read as a clean lint
        if not paths:
            print("graftlint: no changed python files")
            return 0
    rule_ids = (
        {r.strip() for r in args.rules.split(",")} if args.rules else None
    )
    baseline_path = args.baseline or os.path.join(
        repo_root, "graftlint.baseline.json"
    )

    report = run_analysis(
        repo_root,
        paths=paths,
        baseline_path=baseline_path,
        reference_root=args.reference_root,
        rule_ids=rule_ids,
    )

    if args.write_baseline:
        if paths is not None:
            # a subset run sees only a slice of the findings; rewriting
            # from it would truncate every out-of-scope curated entry
            print(
                "graftlint: --write-baseline requires a full-tree run "
                "(no explicit paths / --changed-only)",
                file=sys.stderr,
            )
            return 2
        # regeneration must keep hand-written justifications: entries that
        # still match a finding carry their text over, only genuinely new
        # findings get the TODO placeholder
        existing = Baseline.load(baseline_path)
        keep: dict[tuple, list[str]] = {}
        for e in existing.entries:
            k = (e.get("rule"), e.get("path"), e.get("text"))
            keep.setdefault(k, []).append(str(e.get("justification", "")))
        data = Baseline.render_entries(report["all_findings"])
        fresh = 0
        for entry in data["entries"]:
            k = (entry["rule"], entry["path"], entry["text"])
            bucket = keep.get(k)
            if bucket:
                entry["justification"] = bucket.pop(0)
            else:
                fresh += 1
        with open(baseline_path, "w", encoding="utf-8") as f:
            json.dump(data, f, indent=2)
            f.write("\n")
        print(
            f"graftlint: wrote {len(data['entries'])} entr"
            f"{'y' if len(data['entries']) == 1 else 'ies'} to "
            f"{baseline_path}"
            + (f" — justify the {fresh} new one(s)" if fresh else "")
        )
        return 0

    findings = report["findings"]
    # subset runs (--changed-only, explicit paths) leave baseline entries
    # for out-of-scope files unmatched — that is expected, not staleness;
    # only the default full-tree run polices baseline rot
    stale = [] if paths is not None else report["stale"]
    unjustified = report["unjustified"]
    errors = report["errors"]

    if args.json:
        print(
            json.dumps(
                {
                    "findings": [f.to_dict() for f in findings],
                    "stale_baseline": stale,
                    "unjustified_baseline": unjustified,
                    "errors": errors,
                    "baselined": report["total"] - len(findings),
                },
                indent=2,
            )
        )
    else:
        for f in findings:
            print(f.render())
        for e in stale:
            print(
                f"stale baseline entry: [{e.get('rule')}] {e.get('path')}: "
                f"{e.get('text')!r} no longer matches — remove it"
            )
        for e in unjustified:
            print(
                f"unjustified baseline entry: [{e.get('rule')}] "
                f"{e.get('path')}: add a one-line justification"
            )
        for e in errors:
            print(f"parse error: {e}")
        baselined = report["total"] - len(findings)
        print(
            f"graftlint: {len(findings)} finding"
            f"{'' if len(findings) == 1 else 's'}"
            + (f", {baselined} baselined" if baselined else "")
            + (f", {len(stale)} stale" if stale else "")
        )

    if findings or stale or unjustified:
        return 1
    if errors:
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
