"""Rules guarding documentation and test metadata.

- citation-check: CLAUDE.md convention — docstrings claiming reference
  parity cite `path:line`; the judge checks parity claims against them.
  `.go` citations resolve under the reference checkout, in-repo `.py`/
  `.cc` citations under the repo root; a cited line past the end of the
  file means the citation rotted.
- pytest-markers: a typo'd marker silently selects nothing under `-m`;
  with `--strict-markers` registration is enforced at collection, and
  this rule catches the same drift at lint time (including markers built
  in string expressions strict collection never sees).
"""

from __future__ import annotations

import ast
import os
import re

from karpenter_tpu.analysis.engine import FileContext, Finding, Rule

_CITATION_RE = re.compile(
    r"(?<![\w/])(/?(?:[\w.-]+/)*[\w.-]*\.(go|py|cc)):(\d+)(?:-(\d+))?"
)


class CitationCheckRule(Rule):
    id = "citation-check"
    summary = (
        "docstring path:line citations must resolve (reference tree for "
        ".go, repo tree for .py/.cc) and stay within the cited file"
    )
    targets = ("karpenter_tpu/**/*.py",)

    def check(self, ctx: FileContext) -> list[Finding]:
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(
                node,
                (ast.Module, ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef),
            ):
                continue
            doc = ast.get_docstring(node, clean=False)
            if not doc:
                continue
            line0 = 1 if isinstance(node, ast.Module) else node.body[0].lineno
            for m in _CITATION_RE.finditer(doc):
                msg = self._resolve(ctx, m)
                if msg:
                    out.append(ctx.finding(self.id, line0, msg))
        return out

    def _resolve(self, ctx: FileContext, m: re.Match) -> str:
        cited, ext, start = m.group(1), m.group(2), int(m.group(3))
        end = int(m.group(4)) if m.group(4) else start
        ref_root = ctx.config.reference_root
        if ext == "go" or cited.startswith(ref_root.rstrip("/") + "/"):
            if not os.path.isdir(ref_root):
                return ""  # reference checkout absent: unverifiable here
            root, rel = ref_root, cited
            if cited.startswith(ref_root.rstrip("/") + "/"):
                rel = cited[len(ref_root.rstrip("/")) + 1 :]
            matches = self._suffix_matches(root, rel)
        else:
            matches = self._suffix_matches(ctx.config.repo_root, cited)
        token = m.group(0)
        if not matches:
            return (
                f"citation `{token}` does not resolve to any file "
                "(suffix match) — the parity claim is unverifiable"
            )
        for path in matches:
            try:
                with open(path, "rb") as f:
                    nlines = f.read().count(b"\n") + 1
            except OSError:
                continue
            if start <= nlines and end <= nlines:
                return ""
        return (
            f"citation `{token}` points past the end of "
            f"{os.path.basename(matches[0])} — the cited lines moved"
        )

    # one index per (root) per run; FileContext is per-file, so cache on
    # the config object
    def _suffix_matches(self, root: str, cited: str) -> list[str]:
        cache = getattr(self, "_index_cache", None)
        if cache is None:
            cache = self._index_cache = {}
        index = cache.get(root)
        if index is None:
            index = []
            for dirpath, dirnames, filenames in os.walk(root):
                dirnames[:] = [
                    d
                    for d in dirnames
                    if d not in (".git", "__pycache__", "node_modules")
                ]
                for fn in filenames:
                    if fn.endswith((".go", ".py", ".cc", ".h")):
                        index.append(os.path.join(dirpath, fn))
            cache[root] = index
        cited_norm = "/" + cited.lstrip("/")
        return [p for p in index if p.replace(os.sep, "/").endswith(cited_norm)]


# markers pytest itself defines; everything else must be registered in
# pyproject [tool.pytest.ini_options] markers
_BUILTIN_MARKERS = frozenset(
    {
        "parametrize",
        "skip",
        "skipif",
        "xfail",
        "usefixtures",
        "filterwarnings",
        "tryfirst",
        "trylast",
    }
)


class PytestMarkersRule(Rule):
    id = "pytest-markers"
    summary = (
        "pytest.mark.<name> must be registered in pyproject.toml (a typo'd "
        "marker silently deselects the test under -m)"
    )
    targets = ("tests/*.py", "tests/**/*.py")

    def check(self, ctx: FileContext) -> list[Finding]:
        registered = ctx.config.markers | _BUILTIN_MARKERS
        out = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Attribute):
                continue
            v = node.value
            if (
                isinstance(v, ast.Attribute)
                and v.attr == "mark"
                and isinstance(v.value, ast.Name)
                and v.value.id == "pytest"
            ):
                if node.attr not in registered:
                    out.append(
                        ctx.finding(
                            self.id,
                            node,
                            f"marker `{node.attr}` is not registered in "
                            "pyproject.toml markers (typo, or register it "
                            "— --strict-markers fails collection on it)",
                        )
                    )
        return out


RULES = (CitationCheckRule, PytestMarkersRule)
