"""graftlint SPMD tier: compiled-program contracts for sharded solvers.

The IR tier (analysis/ir.py) walks jaxprs — what the PROGRAMMER wrote.
Sharding contracts live one layer lower: GSPMD inserts collectives at
COMPILE time (sharding propagation over the lowered module), donation is
an aliasing annotation on the lowered program, and per-device HBM is an
XLA buffer-assignment fact. None of them are visible in a jaxpr. This
tier compiles the REAL solver entry points — `solve_scan` relax on/off,
`solve_runs`, the sweep/setsweep kernels, and the lane-sharded
`fleet_solve_scan` placed by `shard_lanes` over an 8-virtual-device mesh
— and walks the compiled HLO / StableHLO text for four rule families:

- `spmd-collectives`: per-program census of collective primitives
  (all-gather / all-reduce / collective-permute / …) pinned EXACT in
  kernel_budgets.json. Every single-device program and the lane-sharded
  fleet program budget to exact-zero: the fleet axis is independent
  whole solves, so a collective appearing there means the lane axis
  leaked into a cross-device reduction (the GSPMD silent-insertion
  failure mode docs/sharding.md warns about).
- `spmd-hbm`: per-device argument/output/temp bytes from
  `compiled.memory_analysis()` pinned as ceilings, plus a predicted-vs-
  measured cross-check against the `aot_manifest.json` cost-catalog rows
  (solver/aot.py `_cost_blocks`) so the "predict the largest-solvable-
  problem curve" claim (ROADMAP item 4) stays mechanically honest.
- `spmd-donation`: `input_output_aliases`/donation census per program,
  pinned at today's exact-zero — the carry-donation PR (ROADMAP item 1)
  must flip the budget intentionally, and the temp-byte delta shows up
  in the same report.
- `spmd-launch-lock`: an AST rule — any call dispatching a sharded
  program (`fleet_dispatch` / `shard_lanes`-derived operands) must sit
  inside the module launch-lock critical section WITH the result fetch
  (solver/fleet.py `_MESH_DISPATCH_LOCK`: two sharded programs in
  flight interleave their collective rendezvous and deadlock — observed
  live; the fetch rides inside the lock so the program has retired
  before the next launch).

Budget entries share kernel_budgets.json with the IR tier under the
`spmd:` name prefix (analysis/budgets.py SPMD_PREFIX); each tier
compares against its own `scoped()` slice. The baseline is
graftlint.spmd.baseline.json (engine.SPMD_DEFAULT_BASELINE).

Like ir.py, this module imports JAX lazily inside functions: importing
`karpenter_tpu.analysis` stays JAX-free, and the CLI loads this module
only under `--spmd` (after `ensure_host_devices()` has pinned the
8-virtual-device CPU mesh, which must happen before the first jax
import).
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
import sys
from typing import Any, Callable, Iterable, Optional

from karpenter_tpu.analysis import budgets as budgets_mod
from karpenter_tpu.analysis import engine
from karpenter_tpu.analysis.engine import (
    SPMD_DEFAULT_BASELINE,
    FileContext,
    Finding,
    Rule,
    iter_functions,
)

SPMD_RULES: dict[str, str] = {
    "spmd-collectives": (
        "collective-primitive census of every compiled solver program "
        "pinned exact in kernel_budgets.json (fleet/lane programs: zero)"
    ),
    "spmd-hbm": (
        "per-device argument/output/temp HBM bytes pinned as ceilings; "
        "cross-checked against the aot_manifest.json cost catalog"
    ),
    "spmd-donation": (
        "input/output aliasing (buffer donation) census per program, "
        "pinned exact (zero until the carry-donation PR flips it)"
    ),
    "spmd-launch-lock": (
        "sharded dispatches must ride inside the fleet launch-lock "
        "critical section with the result fetch included"
    ),
}

# metric -> owning rule (budget comparisons surface under the rule whose
# contract the metric measures; entry-level issues default to the census)
_METRIC_RULE = {
    "collectives_all_gather": "spmd-collectives",
    "collectives_all_reduce": "spmd-collectives",
    "collectives_permute": "spmd-collectives",
    "collectives_other": "spmd-collectives",
    "donated_args": "spmd-donation",
    "hbm_argument_bytes": "spmd-hbm",
    "hbm_output_bytes": "spmd-hbm",
    "hbm_temp_bytes": "spmd-hbm",
}

_MESH_DEVICES = 8


def ensure_host_devices(n_devices: int = _MESH_DEVICES) -> None:
    """Pin the virtual CPU mesh BEFORE the first jax import (the env is
    read once at backend init; tests/conftest.py does the same for
    pytest). A no-op when jax is already imported — the caller then gets
    whatever device count exists, and the fleet program errors out with
    a diagnostic instead of silently measuring an unsharded stand-in."""
    if "jax" in sys.modules:
        return
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n_devices}"
        ).strip()


# ---------------------------------------------------------------------------
# compiled-module censuses (pure text walking — unit-testable on any
# HLO/StableHLO string, and shared with __graft_entry__.dryrun_multichip
# so the dry run and the lint gate cannot drift)

# HLO opcodes of cross-device collectives. `-start`/`-done` are the
# async-pair forms; a pair is ONE collective (the `-done` is skipped).
_COLLECTIVE_OPS = (
    "all-gather",
    "all-reduce",
    "all-to-all",
    "collective-permute",
    "collective-broadcast",
    "reduce-scatter",
)
_COLLECTIVE_RE = re.compile(
    r"\b(" + "|".join(_COLLECTIVE_OPS) + r")(-start|-done)?\("
)


def collective_census(hlo_text: str) -> dict[str, int]:
    """Collective-primitive counts in one compiled (post-GSPMD) HLO
    module. Must run on `compiled.as_text()`: sharding propagation
    inserts collectives at compile time, so jaxpr/StableHLO text from
    before compilation cannot see them."""
    census = {op: 0 for op in _COLLECTIVE_OPS}
    for m in _COLLECTIVE_RE.finditer(hlo_text):
        if m.group(2) == "-done":
            continue
        census[m.group(1)] += 1
    return census


def collective_metrics(census: dict[str, int]) -> dict[str, int]:
    """Fold a census into the budgeted metric names (permute and the
    rarer ops get their own buckets so a budget diff names the family)."""
    return {
        "collectives_all_gather": census.get("all-gather", 0),
        "collectives_all_reduce": census.get("all-reduce", 0),
        "collectives_permute": census.get("collective-permute", 0),
        "collectives_other": (
            census.get("all-to-all", 0)
            + census.get("reduce-scatter", 0)
            + census.get("collective-broadcast", 0)
        ),
    }


# donation surfaces as `tf.aliasing_output` (jax donate_argnums) or
# `jax.buffer_donor` attributes in the lowered StableHLO — one
# occurrence per donated input argument
_DONATION_RE = re.compile(r"tf\.aliasing_output|jax\.buffer_donor")


def donation_census(stablehlo_text: str) -> int:
    """Donated/aliased input count in one lowered StableHLO module
    (`lowered.as_text()`)."""
    return len(_DONATION_RE.findall(stablehlo_text))


# memory_analysis attributes backing the budgeted per-device HBM metrics
_HBM_ATTRS = {
    "hbm_argument_bytes": "argument_size_in_bytes",
    "hbm_output_bytes": "output_size_in_bytes",
    "hbm_temp_bytes": "temp_size_in_bytes",
}


def hbm_metrics(compiled: Any) -> dict[str, int]:
    """Per-device argument/output/temp bytes from XLA buffer assignment.
    A backend without memory_analysis raises — a broken gate (exit 2),
    never a silently un-policed ceiling."""
    ma = compiled.memory_analysis()
    out = {}
    for metric, attr in _HBM_ATTRS.items():
        v = getattr(ma, attr, None)
        if not isinstance(v, (int, float)):
            raise RuntimeError(
                f"memory_analysis() exposes no {attr} on this backend"
            )
        out[metric] = int(v)
    return out


# ---------------------------------------------------------------------------
# the compiled-program set

_KERNEL_PATH = "karpenter_tpu/solver/tpu_kernel.py"
_RUNS_PATH = "karpenter_tpu/solver/tpu_runs.py"
_SWEEP_PATH = "karpenter_tpu/controllers/disruption/sweep.py"
_SETSWEEP_PATH = "karpenter_tpu/controllers/disruption/setsweep.py"
_FLEET_PATH = "karpenter_tpu/solver/fleet.py"
_AOT_PATH = "karpenter_tpu/solver/aot.py"

FLEET_ENTRY = budgets_mod.SPMD_PREFIX + "fleet_solve_scan[B=8,sharded]"


@dataclasses.dataclass(frozen=True)
class SpmdProgram:
    """One compiled entry. `build` returns (fn, args) — the same builder
    closures the IR tier traces (analysis/ir.py), so the two tiers can
    never measure different programs under one name."""

    name: str  # `spmd:`-prefixed kernel_budgets.json entry name
    path: str
    kit: str
    build: Callable[[Any], tuple]


def _build_fleet_sharded(kit: Any) -> tuple:
    """The headline program: fleet_fn's vmapped solve over lane operands
    PLACED by solver/fleet.py shard_lanes on the 8-device `fleet` mesh.
    Lanes are independent whole solves — the compiled module must carry
    ZERO collectives (the batch axis propagates end to end; anything
    else means GSPMD turned a lane-local op into a cross-device one)."""
    import jax

    from karpenter_tpu.solver import fleet as fleet_mod

    B = _MESH_DEVICES
    if len(jax.devices()) < B or not fleet_mod._mesh_active(B):
        raise RuntimeError(
            f"lane sharding needs a {B}-device mesh (set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={B} before the "
            "first jax import; ensure_host_devices() does this for the "
            "CLI)"
        )
    st_b, xs_b = fleet_mod.stack_lanes([kit.st] * B, [kit.xs] * B)
    st_b, xs_b = fleet_mod.shard_lanes(st_b, xs_b)
    return fleet_mod.fleet_fn(False, sharded=True), (kit.tb, st_b, xs_b)


def _programs() -> tuple[SpmdProgram, ...]:
    from karpenter_tpu.analysis import ir

    P = budgets_mod.SPMD_PREFIX
    return (
        SpmdProgram(
            P + "solve_scan[relax=False]", _KERNEL_PATH, "generic",
            ir._ep_solve_scan(False),
        ),
        SpmdProgram(
            P + "solve_scan[relax=True]", _KERNEL_PATH, "mixed",
            ir._ep_solve_scan(True),
        ),
        SpmdProgram(
            P + "solve_runs[relax=False]", _RUNS_PATH, "generic",
            ir._ep_solve_runs(False),
        ),
        SpmdProgram(
            P + "solve_runs[relax=True]", _RUNS_PATH, "mixed",
            ir._ep_solve_runs(True),
        ),
        SpmdProgram(
            P + "_fast_sweep_kernel", _SWEEP_PATH, "generic", ir._ep_sweep
        ),
        SpmdProgram(
            P + "_set_sweep_kernel", _SETSWEEP_PATH, "generic",
            ir._ep_set_sweep,
        ),
        SpmdProgram(FLEET_ENTRY, _FLEET_PATH, "generic", _build_fleet_sharded),
    )


def _lower(fn: Any, args: tuple) -> Any:
    """jax Lowered for one builder result. Already-jitted entries
    (fleet_fn) lower directly; partials with keyword-bound flags
    (sweep's `singleton`) jit with those names static — mirroring the
    AOT prewarm (solver/aot.py), so the compiled program is the one
    production dispatches."""
    import functools

    import jax

    if isinstance(fn, functools.partial) and fn.keywords:
        jitted = jax.jit(fn.func, static_argnames=tuple(fn.keywords))
        return jitted.lower(*args, **fn.keywords)
    if hasattr(fn, "lower"):
        return fn.lower(*args)
    return jax.jit(fn).lower(*args)


def compile_program(prog: SpmdProgram) -> tuple[Any, Any]:
    """(lowered, compiled) for one program on its representative kit."""
    from karpenter_tpu.analysis import ir

    kit = ir.build_kit(prog.kit)
    fn, args = prog.build(kit)
    lowered = _lower(fn, args)
    return lowered, lowered.compile()


def _entry_paths() -> dict[str, str]:
    return {p.name: p.path for p in _programs()}


# ---------------------------------------------------------------------------
# spmd-launch-lock: the one AST rule of the tier (runs through the
# engine's FileContext so suppressions and the baseline work unchanged)

_LOCK_RE = re.compile(r"DISPATCH_LOCK")
_FETCH_RE = re.compile(r"\b(device_get|block_until_ready)\b")

# callees that consume sharded operands WITHOUT launching a program:
# placement/fetch/tree plumbing, and `.lower`/`.compile` (the AOT
# prewarm compiles sharded fleet combos ahead of time — compilation is
# not a launch and takes no lock, solver/aot.py)
_ALLOWED_CALLEES = frozenset(
    {
        "lower", "compile", "shard_lanes", "stack_lanes", "device_put",
        "device_get", "block_until_ready", "tree_map", "tree_leaves",
        "asarray", "array", "len", "print",
    }
)


class LaunchLockRule(Rule):
    id = "spmd-launch-lock"
    summary = SPMD_RULES["spmd-launch-lock"]
    targets = ("karpenter_tpu/**/*.py", "__graft_entry__.py")

    def check(self, ctx: FileContext) -> list[Finding]:
        out = []
        for scope in self._scopes(ctx.tree):
            out.extend(self._check_scope(ctx, scope))
        return out

    @staticmethod
    def _scopes(tree: ast.Module) -> Iterable[list[ast.AST]]:
        """Per-function analysis (sharded-name tracking must not leak
        between functions: fleet.py's dispatch primitive takes sharded
        PARAMETERS, which its callers — not its body — lock around),
        plus one pseudo-scope of module-level statements."""
        for fn in iter_functions(tree):
            yield [fn]
        yield [
            node
            for node in tree.body
            if not isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            )
        ]

    @staticmethod
    def _callee(call: ast.Call) -> Optional[str]:
        f = call.func
        if isinstance(f, ast.Attribute):
            return f.attr
        if isinstance(f, ast.Name):
            return f.id
        return None

    @classmethod
    def _is_shard_call(cls, node: ast.AST) -> bool:
        return isinstance(node, ast.Call) and cls._callee(node) == "shard_lanes"

    def _check_scope(
        self, ctx: FileContext, scope: list[ast.AST]
    ) -> list[Finding]:
        sharded: set[str] = set()
        locked: list[tuple[int, int, bool]] = []  # (lo, hi, has_fetch)
        calls: list[ast.Call] = []
        for root in scope:
            for node in ast.walk(root):
                if isinstance(node, ast.Assign) and self._is_shard_call(
                    node.value
                ):
                    for t in node.targets:
                        elts = t.elts if isinstance(t, ast.Tuple) else [t]
                        sharded.update(
                            e.id for e in elts if isinstance(e, ast.Name)
                        )
                elif isinstance(node, ast.With):
                    # the conditional form `LOCK if sharded else
                    # nullcontext()` counts: its segment names the lock
                    if any(
                        _LOCK_RE.search(ctx.segment(item.context_expr))
                        for item in node.items
                    ):
                        locked.append(
                            (
                                node.lineno,
                                node.end_lineno or node.lineno,
                                bool(_FETCH_RE.search(ctx.segment(node))),
                            )
                        )
                elif isinstance(node, ast.Call):
                    calls.append(node)
        out = []
        for call in calls:
            callee = self._callee(call)
            if callee in _ALLOWED_CALLEES:
                continue
            args = list(call.args) + [kw.value for kw in call.keywords]
            dispatches = (callee == "fleet_dispatch" and sharded) or any(
                isinstance(a, ast.Name) and a.id in sharded for a in args
            ) or any(self._is_shard_call(a) for a in args)
            if not dispatches:
                continue
            enclosing = [w for w in locked if w[0] <= call.lineno <= w[1]]
            if not enclosing:
                out.append(
                    ctx.finding(
                        self.id,
                        call,
                        f"`{callee}(...)` dispatches a sharded program "
                        "outside the `_MESH_DISPATCH_LOCK` critical "
                        "section — concurrent sharded launches interleave "
                        "their collective rendezvous and deadlock "
                        "(solver/fleet.py launch-order contract)",
                    )
                )
            elif not any(has_fetch for _, _, has_fetch in enclosing):
                out.append(
                    ctx.finding(
                        self.id,
                        call,
                        f"`{callee}(...)` holds the launch lock but the "
                        "critical section fetches no result (device_get/"
                        "block_until_ready) — the program must RETIRE "
                        "before the lock releases, or the next sharded "
                        "launch can still interleave its rendezvous",
                    )
                )
        return out


def launch_lock_findings(
    repo_root: str, rule_ids: Optional[set] = None
) -> tuple[list[Finding], list[str]]:
    """Run the launch-lock rule over the package plus the driver entry
    (`__graft_entry__.py` dispatches the fleet program too — the dry run
    must obey the same contract it validates)."""
    if "spmd-launch-lock" not in _active(rule_ids):
        return [], []
    config = engine.Config.for_repo(repo_root)
    files = engine.discover_files(repo_root)
    entry = os.path.join(repo_root, "__graft_entry__.py")
    if os.path.exists(entry):
        files = sorted(set(files) | {entry})
    return engine.analyze_files(
        files, config, rules=[LaunchLockRule()],
        rule_ids={"spmd-launch-lock"},
    )


# ---------------------------------------------------------------------------
# the runner


def _active(rule_ids: Optional[set]) -> set:
    return (
        set(SPMD_RULES)
        if rule_ids is None
        else set(rule_ids) & set(SPMD_RULES)
    )


def _hbm_cross_checks(
    measured: dict[str, dict[str, int]],
    compiled_by_name: dict[str, Any],
    errors: list[str],
    errored: set[str],
) -> list[Finding]:
    """The predicted-vs-measured half of spmd-hbm:

    1. `aot._cost_blocks` (the SHARED helper that fills the
       aot_manifest.json cost catalog) must report the same byte totals
       as the direct memory_analysis() read for every program this tier
       compiled — if the catalog's extraction path rots, /debug/programs
       would mispredict per-device HBM while this tier still passed.
    2. Every live manifest row recorded by the same jax/backend must
       carry well-formed memory data (a pre-catalog or rotted row means
       the capacity curve is built on holes — re-run the prewarm).
    3. The lane-sharded fleet program must pin STRICTLY fewer argument
       bytes per device than its unsharded twin — the capacity claim
       sharding exists for (docs/sharding.md)."""
    findings: list[Finding] = []
    if not compiled_by_name:
        return findings
    import jax

    from karpenter_tpu.solver import aot

    for name in sorted(compiled_by_name):
        _, mem = aot._cost_blocks(compiled_by_name[name])
        for metric, attr in _HBM_ATTRS.items():
            if mem.get(attr) != measured[name][metric]:
                findings.append(
                    Finding(
                        rule="spmd-hbm",
                        path=_AOT_PATH,
                        line=1,
                        message=(
                            f"{name}: aot._cost_blocks reports "
                            f"{attr}={mem.get(attr)} but memory_analysis() "
                            f"measures {measured[name][metric]} — the "
                            "/debug/programs cost catalog would mispredict "
                            "per-device HBM (ROADMAP item 4 input)"
                        ),
                        text=name,
                    )
                )
    try:
        from karpenter_tpu.jaxsetup import ensure_compilation_cache

        cache_dir = ensure_compilation_cache()
        manifest = aot.load_manifest(cache_dir)
    except Exception as e:
        errors.append(f"aot_manifest: {type(e).__name__}: {e}")
        errored.add("aot_manifest")
        manifest = {}
    if (
        manifest.get("jax") == jax.__version__
        and manifest.get("backend") == jax.default_backend()
    ):
        for combo in sorted(manifest.get("combos", {})):
            mem = manifest["combos"][combo].get("memory") or {}
            missing = [
                attr
                for attr in _HBM_ATTRS.values()
                if not isinstance(mem.get(attr), int)
            ]
            if missing:
                findings.append(
                    Finding(
                        rule="spmd-hbm",
                        path=_AOT_PATH,
                        line=1,
                        message=(
                            f"aot_manifest.json combo `{combo}` lacks "
                            f"memory data ({', '.join(missing)}) although "
                            "this backend supports memory_analysis() — "
                            "re-run the prewarm so the capacity catalog "
                            "stays predictive"
                        ),
                        text=combo,
                    )
                )
    if FLEET_ENTRY in measured:
        try:
            from karpenter_tpu.analysis import ir

            kit = ir.build_kit("generic")
            fn, args = ir._ep_fleet(kit)
            unsharded = _lower(fn, args).compile()
            un_arg = hbm_metrics(unsharded)["hbm_argument_bytes"]
            sh_arg = measured[FLEET_ENTRY]["hbm_argument_bytes"]
            if not sh_arg < un_arg:
                findings.append(
                    Finding(
                        rule="spmd-hbm",
                        path=_FLEET_PATH,
                        line=1,
                        message=(
                            f"lane-sharded fleet program pins {sh_arg} "
                            "argument bytes per device, not fewer than the "
                            f"unsharded program's {un_arg} — lane sharding "
                            "stopped dividing per-device HBM (the capacity "
                            "axis docs/sharding.md claims)"
                        ),
                        text=FLEET_ENTRY,
                    )
                )
        except Exception as e:
            errors.append(
                f"{FLEET_ENTRY} (unsharded twin): {type(e).__name__}: {e}"
            )
            errored.add(FLEET_ENTRY)
    return findings


def measure(
    rule_ids: Optional[set] = None,
) -> tuple[dict[str, dict[str, int]], list[Finding], list[str], set[str]]:
    """Compile every program and take its censuses. Returns (measured
    metrics by entry, direct findings, errors, errored entry names) — a
    program that no longer compiles is a broken gate (exit 2), and its
    budget entry must not read as orphaned."""
    active = _active(rule_ids)
    measured: dict[str, dict[str, int]] = {}
    findings: list[Finding] = []
    errors: list[str] = []
    errored: set[str] = set()
    if not active & {"spmd-collectives", "spmd-hbm", "spmd-donation"}:
        return measured, findings, errors, errored
    compiled_by_name: dict[str, Any] = {}
    for prog in _programs():
        try:
            lowered, compiled = compile_program(prog)
            metrics = collective_metrics(
                collective_census(compiled.as_text())
            )
            metrics["donated_args"] = donation_census(lowered.as_text())
            metrics.update(hbm_metrics(compiled))
        except Exception as e:
            errors.append(f"{prog.name}: {type(e).__name__}: {e}")
            errored.add(prog.name)
            continue
        measured[prog.name] = metrics
        compiled_by_name[prog.name] = compiled
    if "spmd-hbm" in active:
        findings.extend(
            _hbm_cross_checks(measured, compiled_by_name, errors, errored)
        )
    return measured, findings, errors, errored


def budget_findings(
    measured: dict[str, dict[str, int]],
    manifest: budgets_mod.BudgetManifest,
    rule_ids: Optional[set] = None,
    errored: Optional[set] = None,
) -> tuple[list[Finding], list[str]]:
    """Compare measurements against the tier's manifest slice (the
    caller passes `manifest.scoped(spmd=True)`); same orphan suppression
    as the IR tier: partial runs and errored entries never read as rot."""
    active = _active(rule_ids)
    cmp = manifest.compare(measured)
    paths = _entry_paths()
    findings = []
    for issue in cmp.issues:
        if issue.kind == "orphaned-entry" and (
            rule_ids is not None or issue.entry in (errored or ())
        ):
            continue
        rule = _METRIC_RULE.get(issue.metric or "", "spmd-collectives")
        if rule not in active:
            continue
        findings.append(
            Finding(
                rule=rule,
                path=paths.get(issue.entry, _FLEET_PATH),
                line=1,
                message=issue.render(),
                text=issue.entry,
            )
        )
    return findings, [i.render() for i in cmp.improvements]


def run_spmd_analysis(
    repo_root: str,
    budgets_path: Optional[str] = None,
    baseline_path: Optional[str] = None,
    rule_ids: Optional[set] = None,
) -> dict:
    """The SPMD pipeline: compile, census, compare against the `spmd:`
    slice of kernel_budgets.json, run the launch-lock AST rule, apply
    graftlint.spmd.baseline.json. Mirrors ir.run_ir_analysis's report
    shape exactly."""
    from karpenter_tpu.analysis.engine import Baseline

    budgets_path = budgets_path or os.path.join(
        repo_root, budgets_mod.DEFAULT_MANIFEST
    )
    baseline_path = (
        baseline_path
        if baseline_path is not None
        else os.path.join(repo_root, SPMD_DEFAULT_BASELINE)
    )
    manifest = budgets_mod.BudgetManifest.load(budgets_path).scoped(spmd=True)
    measured, findings, errors, errored = measure(rule_ids)
    bfindings, improvements = budget_findings(
        measured, manifest, rule_ids, errored=errored
    )
    ll_findings, ll_errors = launch_lock_findings(repo_root, rule_ids)
    findings = sorted(
        findings + bfindings + ll_findings,
        key=lambda f: (f.path, f.rule, f.text),
    )
    baseline = Baseline.load(baseline_path)
    fresh, stale = baseline.apply(findings)
    budget_unjustified = (
        manifest.unjustified()
        if _active(rule_ids)
        >= {"spmd-collectives", "spmd-hbm", "spmd-donation"}
        else []
    )
    return {
        "findings": fresh,
        "all_findings": findings,
        "stale": stale,
        "unjustified": baseline.unjustified(),
        "budget_unjustified": budget_unjustified,
        "improvements": improvements,
        "errors": errors + ll_errors,
        "measured": measured,
        "manifest": manifest,
    }
