"""kernel_budgets.json: the IR tier's checked-in budget manifest.

The AST tier's baseline grandfathers *findings*; this manifest pins
*measurements* — loop-carry bytes, loop structure, upload and retrace
counts taken from the traced solver kernels (analysis/ir.py). Both follow
the same workflow: every entry carries a one-line justification, stale or
orphaned entries fail the gate so the file cannot rot, and re-baselining
is an explicit `graftlint --ir --write-budgets` followed by justifying
the diff.

Metric policy — two kinds, declared in `METRIC_POLICY`:

- `exact`: the measured value must EQUAL the budget. Used for structure
  (while/scan counts: an extra device loop is a compiled-program change
  that needs a justified re-baseline even when it is "better") and for
  absolute contracts (second-solve retraces, per-solve table uploads).
- `ceiling`: the measured value must not EXCEED the budget. Used for byte
  and iteration totals, where warm in-process caches can legitimately
  lower a measurement (a pytest run that already compiled a kernel traces
  less than a cold CLI run) but growth is always a regression.

Pure stdlib — importable without JAX so the manifest mechanics are
testable in milliseconds (tests/test_budget_manifest.py).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

from karpenter_tpu.analysis.engine import canonical_json

DEFAULT_MANIFEST = "kernel_budgets.json"

# The SPMD tier (analysis/spmd.py) shares this manifest file but owns a
# disjoint namespace: its entries are prefixed `spmd:` and carry the
# compiled-program metrics below. Each tier compares against a `scoped()`
# view so the IR tier never reads SPMD entries as orphaned (and vice
# versa), and `render(..., spmd_scope=...)` carries the other tier's
# entries over verbatim on `--write-budgets`.
SPMD_PREFIX = "spmd:"

# metric name -> enforcement policy; a manifest metric outside this table
# is reported as unknown (the manifest rotted or the tool regressed)
METRIC_POLICY: dict[str, str] = {
    # jaxpr structure (analysis/ir.py kernel_metrics)
    "while_loops": "exact",
    "scans": "exact",
    "max_carry_bytes": "ceiling",
    "total_carry_bytes": "ceiling",
    "scan_total_length": "ceiling",
    # runtime accounting (analysis/ir.py runtime_metrics)
    "table_uploads": "exact",
    "pod_table_uploads": "exact",
    "pod_batch_uploads": "ceiling",
    "first_solve_traces": "ceiling",
    "second_solve_traces": "exact",
    "second_solve_compiles": "exact",
    # the shape-bucket contract (solver/buckets.py): a different REAL
    # problem size in the same pow-2 bucket compiles and traces nothing
    "same_bucket_solve_traces": "exact",
    "same_bucket_solve_compiles": "exact",
    # removal-set sweep accounting (analysis/ir.py
    # setsweep_runtime_metrics): the bounded-dispatch contract — tables
    # upload once per context, a >=1000-lane batch is ONE dispatch, a
    # repeated same-bucket batch retraces and recompiles nothing
    "set_table_uploads": "exact",
    "set_pod_table_uploads": "exact",
    "set_eval_dispatches": "exact",
    "set_second_eval_traces": "exact",
    "set_second_eval_compiles": "exact",
    # epoch steady-state accounting (analysis/ir.py epoch_runtime_
    # metrics): a repeat same-epoch solve through the device-table cache
    # (solver/epochs.py) uploads ONLY the pending-pod batch — the
    # per-class table re-upload counts are absolute-zero contracts
    "epoch_first_table_uploads": "exact",
    "epoch_repeat_table_uploads": "exact",
    "epoch_repeat_pod_table_uploads": "exact",
    "epoch_repeat_pod_batch_uploads": "ceiling",
    # fleet coalescing accounting (analysis/ir.py fleet_runtime_metrics):
    # a coalesced batch window (solver/fleet.py) shares one device-table
    # materialization — the repeat window re-uploads nothing, runs ONE
    # vmapped dispatch, and the same-bucket zero-compile contract holds
    # for the lane-batched entry. The first window's upload count is a
    # ceiling: a cache-miss race (both lanes encode before either's put
    # lands) may legally upload per lane once.
    "fleet_first_window_table_uploads": "ceiling",
    "fleet_repeat_window_table_uploads": "exact",
    "fleet_repeat_window_dispatches": "exact",
    "fleet_repeat_window_traces": "exact",
    "fleet_repeat_window_compiles": "exact",
    # SPMD tier (analysis/spmd.py, `spmd:`-prefixed entries): collective
    # census of the compiled (post-GSPMD) program — exact, because a
    # collective appearing where the budget pins zero is a sharding
    # regression even when it is "only one" (the lane axis leaked into a
    # cross-device reduction), and a collective DISAPPEARING from the
    # slots/types path would mean the program stopped sharding at all
    "collectives_all_gather": "exact",
    "collectives_all_reduce": "exact",
    "collectives_permute": "exact",
    "collectives_other": "exact",
    # donated/aliased inputs per program — exact-zero today; the carry-
    # donation PR (ROADMAP item 1) must flip these budgets intentionally
    "donated_args": "exact",
    # per-device HBM from compiled.memory_analysis() — ceilings: the
    # capacity numbers ROADMAP item 4 predicts from; growth is always a
    # regression, shrinkage (donation landing, layout wins) is slack
    "hbm_argument_bytes": "ceiling",
    "hbm_output_bytes": "ceiling",
    "hbm_temp_bytes": "ceiling",
}


@dataclasses.dataclass
class BudgetIssue:
    """One manifest-vs-measurement discrepancy."""

    kind: str  # regression | structure-mismatch | missing-entry |
    #            orphaned-entry | unknown-metric | missing-metric
    entry: str
    metric: Optional[str]
    budget: Optional[int]
    measured: Optional[int]

    def render(self) -> str:
        if self.kind == "regression":
            return (
                f"{self.entry}: {self.metric} regressed — measured "
                f"{self.measured} exceeds the budget {self.budget} "
                "(--write-budgets to re-baseline, then justify)"
            )
        if self.kind == "structure-mismatch":
            return (
                f"{self.entry}: {self.metric} changed — measured "
                f"{self.measured}, budget pins {self.budget} (loop "
                "structure is exact-match; re-baseline with justification)"
            )
        if self.kind == "missing-entry":
            return (
                f"{self.entry}: no budget entry — new kernel entry point; "
                "run --write-budgets and justify it"
            )
        if self.kind == "orphaned-entry":
            return (
                f"{self.entry}: budget entry matches no traced entry point "
                "— remove it (the kernel moved or was renamed)"
            )
        if self.kind == "missing-metric":
            return (
                f"{self.entry}: budget has no `{self.metric}` value but the "
                "tool measures it — re-baseline"
            )
        if self.kind == "improvement":
            return (
                f"{self.entry}: {self.metric} measured {self.measured} is "
                f"under the budget {self.budget} — consider tightening the "
                "ceiling (--write-budgets)"
            )
        return (
            f"{self.entry}: unknown metric `{self.metric}` in the manifest "
            "— remove it"
        )


@dataclasses.dataclass
class Comparison:
    issues: list[BudgetIssue]
    # measured strictly under a ceiling budget: legitimate (warm caches,
    # real improvements) but worth surfacing so ceilings get tightened
    improvements: list[BudgetIssue]


class BudgetManifest:
    """Load/compare/render kernel_budgets.json.

    Schema:
        {"entries": {"<entry point>": {
            "justification": "<one line>",
            "metrics": {"<metric>": <int>, ...}}}}
    Serialization is canonical (engine.canonical_json) so a re-written
    manifest with unchanged content is byte-identical — the round-trip
    property tests/test_budget_manifest.py pins.
    """

    def __init__(
        self, entries: dict[str, dict], path: Optional[str] = None
    ):
        self.entries = entries
        self.path = path

    @classmethod
    def load(cls, path: str) -> "BudgetManifest":
        if not os.path.exists(path):
            return cls({}, path)
        import json

        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        return cls(dict(data.get("entries", {})), path)

    def scoped(self, spmd: bool) -> "BudgetManifest":
        """This tier's slice of the shared manifest: the SPMD tier owns
        the `spmd:`-prefixed entries, the IR tier everything else. Each
        tier compares against its own slice so the other tier's entries
        never read as orphaned (compare() polices `entries - measured`)."""
        return BudgetManifest(
            {
                name: e
                for name, e in self.entries.items()
                if name.startswith(SPMD_PREFIX) == spmd
            },
            self.path,
        )

    def unjustified(self) -> list[str]:
        """Entry names whose justification is empty or a TODO placeholder
        (same policing as graftlint.baseline.json)."""
        out = []
        for name, e in self.entries.items():
            j = str(e.get("justification", "")).strip()
            if not j or j.startswith("TODO"):
                out.append(name)
        return sorted(out)

    def compare(self, measured: dict[str, dict[str, int]]) -> Comparison:
        issues: list[BudgetIssue] = []
        improvements: list[BudgetIssue] = []
        for name in sorted(measured):
            entry = self.entries.get(name)
            if entry is None:
                issues.append(
                    BudgetIssue("missing-entry", name, None, None, None)
                )
                continue
            budget_metrics = dict(entry.get("metrics", {}))
            for metric in sorted(measured[name]):
                got = int(measured[name][metric])
                if metric not in budget_metrics:
                    issues.append(
                        BudgetIssue("missing-metric", name, metric, None, got)
                    )
                    continue
                want = int(budget_metrics.pop(metric))
                policy = METRIC_POLICY.get(metric)
                if policy == "exact":
                    if got != want:
                        issues.append(
                            BudgetIssue(
                                "structure-mismatch", name, metric, want, got
                            )
                        )
                elif policy == "ceiling":
                    if got > want:
                        issues.append(
                            BudgetIssue("regression", name, metric, want, got)
                        )
                    elif got < want:
                        improvements.append(
                            BudgetIssue(
                                "improvement", name, metric, want, got
                            )
                        )
                else:
                    issues.append(
                        BudgetIssue("unknown-metric", name, metric, want, got)
                    )
            for metric in sorted(budget_metrics):
                # budgeted but no longer measured: the tool dropped the
                # metric or the manifest carries a typo — police it
                issues.append(
                    BudgetIssue(
                        "unknown-metric",
                        name,
                        metric,
                        int(budget_metrics[metric]),
                        None,
                    )
                )
        for name in sorted(set(self.entries) - set(measured)):
            issues.append(
                BudgetIssue("orphaned-entry", name, None, None, None)
            )
        return Comparison(issues=issues, improvements=improvements)

    @staticmethod
    def render(
        measured: dict[str, dict[str, int]],
        existing: Optional["BudgetManifest"] = None,
        spmd_scope: Optional[bool] = None,
    ) -> dict:
        """Manifest dict for --write-budgets. Entries that already exist
        keep their hand-written justification (the --write-baseline
        convention); genuinely new ones get the TODO placeholder.

        `spmd_scope` names the tier doing the write (True: SPMD, False:
        IR, None: legacy whole-file write): the OTHER tier's existing
        entries are carried over verbatim, so a `--write-budgets` under
        either tier can never truncate its sibling's half of the shared
        file."""
        entries = {}
        if spmd_scope is not None and existing is not None:
            for name, e in existing.entries.items():
                if name.startswith(SPMD_PREFIX) != spmd_scope:
                    entries[name] = {
                        "justification": str(e.get("justification", "")),
                        "metrics": {
                            m: int(v)
                            for m, v in sorted(
                                dict(e.get("metrics", {})).items()
                            )
                        },
                    }
        for name in sorted(measured):
            old = (existing.entries.get(name) if existing else None) or {}
            entries[name] = {
                "justification": str(
                    old.get("justification", "TODO: justify or fix")
                ),
                "metrics": {
                    m: int(v) for m, v in sorted(measured[name].items())
                },
            }
        return {"entries": entries}

    @staticmethod
    def dumps(data: dict) -> str:
        return canonical_json(data)
