"""Rule guarding the metric exposition surface.

- metric-naming: every `metrics.REGISTRY.counter/gauge/histogram(...)`
  registration site must use a LITERAL name with the `karpenter_` prefix
  and Prometheus-legal characters, a literal non-empty help string, and a
  name no other registration site in the run already claimed.
  Registry._register silently returns the EXISTING metric on a name
  collision — two modules registering the same name with different label
  sets would ship one of them broken, with no error anywhere. The literal
  requirement is load-bearing too: docs/observability.md's catalog drift
  test and this rule both read names from source, so a computed name
  would be invisible to every mechanical check.
"""

from __future__ import annotations

import ast
import re
from typing import Optional

from karpenter_tpu.analysis.engine import FileContext, Finding, Rule

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*\Z")
_REGISTER_METHODS = frozenset({"counter", "gauge", "histogram"})


def _literal_str(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class MetricNamingRule(Rule):
    id = "metric-naming"
    summary = (
        "REGISTRY metric registrations need a literal karpenter_-prefixed "
        "unique name and a non-empty help string"
    )
    targets = ("karpenter_tpu/**/*.py",)

    def __init__(self) -> None:
        # name -> (path, line) of the first registration seen in THIS
        # analyzer run; the engine runs one rule instance over every file
        # (sorted order), so cross-file duplicates surface on the later
        # site. A --changed-only run only sees within-file duplicates —
        # the full-tree pytest gate covers the rest.
        self._seen: dict[str, tuple[str, int]] = {}

    def check(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (
                isinstance(f, ast.Attribute) and f.attr in _REGISTER_METHODS
            ):
                continue
            recv = f.value
            # registration sites go through the module-level REGISTRY
            # (bare or `metrics.REGISTRY`); ad-hoc Registry() instances in
            # tests/fixtures are their own namespace and stay out of scope
            if not (
                (isinstance(recv, ast.Name) and recv.id == "REGISTRY")
                or (isinstance(recv, ast.Attribute) and recv.attr == "REGISTRY")
            ):
                continue
            name_node = node.args[0] if node.args else next(
                (kw.value for kw in node.keywords if kw.arg == "name"), None
            )
            help_node = node.args[1] if len(node.args) > 1 else next(
                (kw.value for kw in node.keywords if kw.arg == "help"), None
            )
            name = _literal_str(name_node)
            if name is None:
                out.append(
                    ctx.finding(
                        self.id,
                        node,
                        f"metric name passed to REGISTRY.{f.attr}() must be "
                        "a string literal (the catalog drift test and this "
                        "rule read names from source)",
                    )
                )
            else:
                if not name.startswith("karpenter_"):
                    out.append(
                        ctx.finding(
                            self.id,
                            node,
                            f"metric {name!r} lacks the karpenter_ namespace "
                            "prefix (reference pkg/metrics/metrics.go:32)",
                        )
                    )
                elif not _NAME_RE.match(name):
                    out.append(
                        ctx.finding(
                            self.id,
                            node,
                            f"metric {name!r} contains characters outside "
                            "[a-zA-Z0-9_:]",
                        )
                    )
                prev = self._seen.get(name)
                here = (ctx.relpath, node.lineno)
                if prev is not None and prev != here:
                    out.append(
                        ctx.finding(
                            self.id,
                            node,
                            f"metric {name!r} already registered at "
                            f"{prev[0]}:{prev[1]} — Registry._register "
                            "silently returns the existing metric on a "
                            "name collision",
                        )
                    )
                else:
                    self._seen[name] = here
            help_text = _literal_str(help_node)
            # missing, computed, or blank all fail: help must be a LITERAL
            # non-empty string, same source-visibility contract as names
            if help_text is None or not help_text.strip():
                out.append(
                    ctx.finding(
                        self.id,
                        node,
                        f"metric registration via REGISTRY.{f.attr}() needs "
                        "a literal non-empty help string (# HELP is the "
                        "operator's only in-band documentation)",
                    )
                )
        return out


RULES = (MetricNamingRule,)
